"""Generate the API reference from live docstrings (stdlib-only).

The image ships no sphinx/mkdocs/pdoc, so the reference is generated with
``inspect``: every public symbol of ``metrics_tpu`` (modules, metric classes,
functionals, parallel plane) is emitted as markdown with its signature and
docstring — the same docstrings the test suite executes as doctests, so the
examples shown here are verified on every CI run.

Usage:  python docs/gen_api.py [output.md]     (default: docs/api.md)
"""
import importlib
import inspect
import os
import sys
from pathlib import Path

# run from anywhere: the repo root on sys.path, not via PYTHONPATH (which
# breaks the axon TPU plugin registration in this image — see benchmarks/)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SECTIONS = [
    ("Core", "metrics_tpu", ["Metric", "MetricCollection", "CompositionalMetric", "PureMetric",
                             "set_default_jit", "enable_sync_count_check"]),
    ("Classification", "metrics_tpu.classification", None),
    ("Regression", "metrics_tpu.regression", None),
    ("Retrieval", "metrics_tpu.retrieval", None),
    ("Text", "metrics_tpu.text", None),
    ("Audio", "metrics_tpu.audio", None),
    ("Wrappers", "metrics_tpu.wrappers", None),
    ("Clustering", "metrics_tpu.clustering", None),
    ("Nominal association", "metrics_tpu.nominal", None),
    ("Detection", "metrics_tpu.detection", None),
    ("Functional", "metrics_tpu.functional", None),
    ("Parallel (mesh sync, placement, sharded epoch)", "metrics_tpu.parallel", None),
    ("Ops (kernels)", "metrics_tpu.ops.binned", ["binned_stat_counts"]),
    ("Utilities", "metrics_tpu.utils.data", None),
]


def _public_names(mod):
    if hasattr(mod, "__all__"):
        return list(mod.__all__)
    return [
        n for n, obj in vars(mod).items()
        if not n.startswith("_") and (inspect.isclass(obj) or inspect.isfunction(obj))
        and getattr(obj, "__module__", "").startswith("metrics_tpu")
    ]


def _signature(obj, drop_self: bool = False):
    try:
        sig = inspect.signature(obj)
    except (TypeError, ValueError):
        return "(...)"
    if drop_self:
        params = list(sig.parameters.values())
        if params and params[0].name == "self":
            sig = sig.replace(parameters=params[1:])
    return str(sig)


def _markdownize(doc: str) -> str:
    """Make a Google-style docstring render as markdown.

    Doctest blocks (contiguous runs containing a ``>>>`` line) become fenced
    python code blocks; ``Args:``-style section headers become bold.
    """
    lines = doc.splitlines()
    out, block, in_code, in_args = [], [], False, False

    def flush():
        nonlocal in_code
        if in_code and block:
            # dedent the whole example by the `>>>` line's indent so the
            # expected-output lines stay aligned with their statements
            indent = len(block[0]) - len(block[0].lstrip())
            out.append("```python")
            out.extend(ln[indent:] if ln[:indent].isspace() or not ln[:indent] else ln
                       for ln in block)
            out.append("```")
        else:
            out.extend(block)
        block.clear()
        in_code = False

    for ln in lines:
        if not ln.strip():
            flush()
            in_args = False
            out.append(ln)
            continue
        if ln.lstrip().startswith(">>>"):
            if not in_code:
                flush()
            in_code = True
        if not in_code and ln.rstrip().endswith(":") and ln.strip() in (
            "Args:", "Returns:", "Raises:", "Example:", "Examples:", "Note:", "Yields:"
        ):
            flush()
            in_args = ln.strip() in ("Args:", "Raises:")
            out.append(f"**{ln.strip()[:-1]}**\n")
            continue
        if in_args and not in_code:
            # "name: description" entries -> list items; deeper-indented
            # continuation lines fold into the same item
            stripped = ln.strip()
            indent = len(ln) - len(ln.lstrip())
            if indent <= 4 and ":" in stripped:
                name, _, rest = stripped.partition(":")
                block.append(f"- `{name.strip()}`:{rest}")
            elif block:
                block[-1] += " " + stripped
            else:
                block.append(stripped)
            continue
        block.append(ln)
    flush()
    return "\n".join(out)


def _doc(obj):
    doc = inspect.getdoc(obj)
    return _markdownize(doc) if doc else "*(undocumented)*"


def _emit_symbol(out, name, obj, level="###"):
    if inspect.isclass(obj):
        out.append(f"{level} `{name}{_signature(obj.__init__, drop_self=True)}`\n")
        out.append(_doc(obj) + "\n")
        for meth_name in ("update", "compute", "forward_batched", "pure", "device_put", "note_count"):
            meth = obj.__dict__.get(meth_name)
            if meth is None or not callable(meth):
                continue
            doc = inspect.getdoc(meth)
            if not doc:
                continue
            out.append(f"**`.{meth_name}{_signature(meth, drop_self=True)}`** — {doc.splitlines()[0]}\n")
    else:
        out.append(f"{level} `{name}{_signature(obj)}`\n")
        out.append(_doc(obj) + "\n")


def generate() -> str:
    out = [
        "# metrics_tpu API reference\n",
        "*Generated from live docstrings by `docs/gen_api.py`; the examples",
        "below run as doctests in CI (`make test`). Regenerate with",
        "`make docs`.*\n",
    ]
    seen = set()
    for title, modname, names in SECTIONS:
        mod = importlib.import_module(modname)
        out.append(f"\n## {title}\n")
        mod_doc = inspect.getdoc(mod)
        if mod_doc and names is None:
            out.append(mod_doc.splitlines()[0] + "\n")
        for name in names or sorted(_public_names(mod)):
            obj = getattr(mod, name, None)
            if obj is None or id(obj) in seen:
                continue
            seen.add(id(obj))
            _emit_symbol(out, name, obj)
    return "\n".join(out)


def main() -> int:
    target = Path(sys.argv[1]) if len(sys.argv) > 1 else Path(__file__).parent / "api.md"
    text = generate()
    n_symbols = text.count("\n### ")
    if n_symbols < 60:
        print(f"ERROR: only {n_symbols} symbols documented — generator or package broken", file=sys.stderr)
        return 1
    target.write_text(text)
    print(f"wrote {target} ({n_symbols} symbols, {len(text)} bytes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
