"""Render the markdown docs tree into a browsable static HTML site.

Usage:  python docs/build_html.py [outdir]      (default: docs/site)

Uses the image's python-markdown (the only doc tool shipped — no
sphinx/mkdocs) with tables + fenced-code + toc extensions. Every
``docs/*.md`` page plus the repo ``README.md`` becomes one HTML page with a
shared navigation sidebar; internal ``.md`` links are rewritten to ``.html``.
Built by ``make docs`` after the API reference is regenerated, and by CI.
"""
import os
import re
import sys
from pathlib import Path

import markdown

DOCS = Path(__file__).resolve().parent
REPO = DOCS.parent

# (source file, page title) in sidebar order
PAGES = [
    (REPO / "README.md", "Home"),
    (DOCS / "quickstart.md", "Quickstart"),
    (DOCS / "overview.md", "Architecture overview"),
    (DOCS / "training_integration.md", "Training integration (flax/optax)"),
    (DOCS / "collection_performance.md", "MetricCollection performance"),
    (DOCS / "implement.md", "Implementing a metric"),
    (DOCS / "api.md", "API reference"),
]

TEMPLATE = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>{title} — metrics_tpu</title>
<style>
:root {{ --fg: #1a1a1a; --muted: #666; --line: #e2e2e2; --accent: #0b57d0;
         --code-bg: #f6f8fa; --sidebar-bg: #fafafa; }}
* {{ box-sizing: border-box; }}
body {{ margin: 0; font: 16px/1.6 system-ui, -apple-system, "Segoe UI", sans-serif;
        color: var(--fg); display: flex; min-height: 100vh; }}
nav {{ width: 248px; flex-shrink: 0; border-right: 1px solid var(--line);
       background: var(--sidebar-bg); padding: 1.5rem 1rem; position: sticky;
       top: 0; height: 100vh; overflow-y: auto; }}
nav .brand {{ font-weight: 700; font-size: 1.1rem; margin-bottom: 1rem; }}
nav a {{ display: block; padding: .3rem .5rem; border-radius: 6px;
         color: var(--fg); text-decoration: none; font-size: .95rem; }}
nav a:hover {{ background: #eee; }}
nav a.active {{ background: var(--accent); color: #fff; }}
main {{ flex: 1; min-width: 0; padding: 2rem 3rem 4rem; max-width: 60rem; }}
h1, h2, h3 {{ line-height: 1.25; }}
h2 {{ border-bottom: 1px solid var(--line); padding-bottom: .3rem; margin-top: 2rem; }}
a {{ color: var(--accent); }}
code {{ background: var(--code-bg); padding: .12em .35em; border-radius: 4px;
        font: .875em/1.5 ui-monospace, "SF Mono", Menlo, Consolas, monospace; }}
pre {{ background: var(--code-bg); padding: .9rem 1.1rem; border-radius: 8px;
       overflow-x: auto; }}
pre code {{ background: none; padding: 0; }}
table {{ border-collapse: collapse; display: block; overflow-x: auto;
         font-size: .92rem; }}
th, td {{ border: 1px solid var(--line); padding: .35rem .7rem; text-align: left; }}
th {{ background: var(--sidebar-bg); }}
blockquote {{ border-left: 3px solid var(--line); margin-left: 0;
              padding-left: 1rem; color: var(--muted); }}
@media (max-width: 760px) {{ body {{ flex-direction: column; }}
  nav {{ width: 100%; height: auto; position: static; }} main {{ padding: 1rem; }} }}
</style>
</head>
<body>
<nav>
<div class="brand">metrics_tpu</div>
{nav}
</nav>
<main>
{body}
</main>
</body>
</html>
"""


def _out_name(src: Path) -> str:
    if src.name == "README.md":
        return "index.html"
    return src.stem + ".html"


def _rewrite_links(html: str) -> str:
    # internal .md links -> the rendered .html page
    def sub(m):
        href = m.group(1)
        if href.startswith(("http://", "https://", "#")):
            return m.group(0)
        path, _, fragment = href.partition("#")
        root, ext = os.path.splitext(os.path.basename(path))
        if ext == ".md":
            target = "index.html" if root == "README" else root + ".html"
            if fragment:
                target += "#" + fragment
            return f'href="{target}"'
        return m.group(0)

    return re.sub(r'href="([^"]+)"', sub, html)


def build(outdir: Path) -> int:
    outdir.mkdir(parents=True, exist_ok=True)
    md = markdown.Markdown(extensions=["tables", "fenced_code", "toc", "sane_lists"])
    built = 0
    for src, title in PAGES:
        if not src.exists():
            print(f"skip (missing): {src}", file=sys.stderr)
            continue
        active = ' class="active"'
        nav = "\n".join(
            f'<a href="{_out_name(s)}"{active if s == src else ""}>{t}</a>'
            for s, t in PAGES if s.exists()
        )
        md.reset()
        body = _rewrite_links(md.convert(src.read_text()))
        (outdir / _out_name(src)).write_text(
            TEMPLATE.format(title=title, nav=nav, body=body)
        )
        built += 1
    return built


if __name__ == "__main__":
    out = Path(sys.argv[1]) if len(sys.argv) > 1 else DOCS / "site"
    n = build(out)
    print(f"built {n} pages -> {out}")
