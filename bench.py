"""Benchmark of record (BASELINE.md #3): per-step metric update+sync overhead
of ``MetricCollection(Accuracy, F1, Precision, Recall)``.

Ours: the **marginal** wall-clock of folding the fused pure-state collection
update into an already-jitted training step (the idiomatic TPU deployment:
the metric update compiles into the step, so the dispatch cost is shared) —
measured as t(train+metrics) - t(train) on the default backend.

Baseline: the actual reference torchmetrics (mounted at /root/reference,
imported in-place, torch CPU — the only reference runtime in this image)
driving the same collection's ``update`` per step; eager torch has no
dispatch to amortize, so its per-step update time is its marginal cost.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where value
is our marginal ms/step and vs_baseline = reference_ms / our_ms (>1 means
faster than the reference).
"""
import json
import sys
import time

import numpy as np

N_STEPS = 200
WARMUP = 10
BATCH = 4096
NUM_CLASSES = 32
FEATURES = 256


def bench_ours() -> float:
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, F1, MetricCollection, Precision, Recall

    collection = MetricCollection([
        Accuracy(),
        F1(num_classes=NUM_CLASSES, average="macro"),
        Precision(num_classes=NUM_CLASSES, average="macro"),
        Recall(num_classes=NUM_CLASSES, average="macro"),
    ])
    pure = collection.pure()

    rng = np.random.RandomState(0)
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, BATCH).astype(np.int32))
    x = jnp.asarray(rng.rand(BATCH, FEATURES).astype(np.float32))
    w = jnp.asarray(rng.rand(FEATURES, NUM_CLASSES).astype(np.float32))

    def loss(w):
        return -jnp.mean(jax.nn.log_softmax(x @ w)[jnp.arange(BATCH), target])

    @jax.jit
    def train_only(w):
        return w - 0.01 * jax.grad(loss)(w)

    @jax.jit
    def train_with_metrics(w, state):
        g = jax.grad(loss)(w)
        probs = jax.nn.softmax(x @ w)
        state = pure.update(state, probs, target)
        return w - 0.01 * g, state

    def timeit(fn, *args):
        out = None
        for _ in range(WARMUP):
            out = fn(*args)
        jax.block_until_ready(out)
        start = time.perf_counter()
        for _ in range(N_STEPS):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - start) / N_STEPS * 1e3

    t_plain = timeit(train_only, w)
    t_with = timeit(train_with_metrics, w, pure.init())
    return max(t_with - t_plain, 1e-6)


def bench_reference() -> float:
    sys.path.insert(0, "/root/reference")
    import torch
    from torchmetrics import Accuracy, F1, MetricCollection, Precision, Recall

    collection = MetricCollection([
        Accuracy(),
        F1(num_classes=NUM_CLASSES, average="macro"),
        Precision(num_classes=NUM_CLASSES, average="macro"),
        Recall(num_classes=NUM_CLASSES, average="macro"),
    ])

    rng = np.random.RandomState(0)
    logits = rng.rand(BATCH, NUM_CLASSES).astype(np.float32)
    preds = torch.from_numpy(logits / logits.sum(-1, keepdims=True))
    target = torch.from_numpy(rng.randint(0, NUM_CLASSES, BATCH).astype(np.int64))

    for _ in range(WARMUP):
        collection.update(preds, target)

    start = time.perf_counter()
    for _ in range(N_STEPS):
        collection.update(preds, target)
    return (time.perf_counter() - start) / N_STEPS * 1e3


def main() -> None:
    ours_ms = bench_ours()
    try:
        ref_ms = bench_reference()
        vs_baseline = ref_ms / ours_ms
    except Exception:
        vs_baseline = float("nan")

    print(
        json.dumps(
            {
                "metric": "marginal per-step update+sync overhead of MetricCollection(Accuracy,F1,Precision,"
                          f"Recall) fused into a jitted train step (batch {BATCH}x{NUM_CLASSES}) "
                          "vs reference torchmetrics eager update (torch CPU)",
                "value": round(ours_ms, 4),
                "unit": "ms/step",
                "vs_baseline": round(vs_baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
