"""Benchmark of record (BASELINE.md #3): per-step sync wall-clock of
``MetricCollection(Accuracy, F1, Precision, Recall)`` over 8 devices, with
``dist_sync_on_step`` semantics — every step updates, cross-device syncs, and
computes the collection.

Ours: one jitted ``shard_map`` step over an 8-device mesh (virtual CPU devices
— multi-chip TPU hardware is not available in this image; the XLA collective
code paths are the same): per-shard fused update, ``psum`` sync of every
state, replicated compute. Measured in a subprocess so the parent process can
keep the default (TPU) backend for the single-chip number.

Baseline: the actual reference torchmetrics (mounted at /root/reference,
imported in-place) on an 8-process Gloo group — its own distributed story
(reference tests/helpers/testers.py:41-47) — driving the same collection's
``forward`` with ``dist_sync_on_step=True`` per step.

Also reported (extra keys): the single-chip marginal cost of folding the fused
collection update into an already-jitted train step on the default backend
(TPU when available), vs the reference's eager per-step ``update`` on torch
CPU — the single-device deployment number.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} where
value is our 8-device sync-in-the-loop ms/step and vs_baseline =
reference_ms / our_ms (>1 means we are faster than the reference). The line
also carries the compute-groups A/B ("grouped_sync8_ms" vs
"ungrouped_sync8_ms", with "states_synced" counts), the gather-plane A/B
("gather_coalesced_ms" vs "gather_per_leaf_ms": bucketed vs per-leaf
``all_gather`` sync of a buffer-state AUROC+AveragePrecision+Spearman
collection), and the hierarchical A/B ("gather_hier_ms" vs
"gather_flat2d_ms": the same collection on the (4,2) ici x dcn test mesh,
two-stage hierarchical plane vs flat world axis, with the per-crossing
"hier_dcn_bytes"/"flat2d_world_bytes" traffic split) so BENCH_r* tracks the
group/coalescing/hierarchy gains. The keyed-slab scenario
("keyed_sync_ms"/"keyed_collective_calls"/"keyed_sync_bytes":
Keyed(AUROC(approx="sketch"), num_slots=10,000) on the same (4,2) mesh)
rides the default line too, with the cross-scenario keyed gate pinning that
K=10,000 segments sync with the identical staged-collective count and kinds
as the unkeyed metric (psum-only, zero gathers). The staged collective-count keys
("collective_calls", "sync_bytes", ...) ride the DEFAULT line — counting
happens at trace time and costs nothing per step — so ``--check-trajectory``
binds on every new round. ``--smoke`` runs a 2-step, no-reference version
with the same headline schema for CI (tests/integrations/test_bench_smoke.py).

``--check-collectives`` is the collective regression gate: it traces each
scenario's step program and compares the staged ``collective_calls`` /
``sync_bytes`` — plus the per-crossing ``ici``/``dcn``/``world`` calls and
ring-traffic bytes for the hierarchical scenarios — against the pinned
``EXPECTED_COLLECTIVES``, exiting non-zero on growth, and enforces the
hierarchy gate of record: the hierarchical gather plane's DCN-crossing
bytes strictly below the flat plane's world-axis bytes (the smoke test
runs it in tier-1, so a silently added or reflattened collective fails CI
even when ms noise hides it).

``--check-fleet`` is the sharded-serving gate: the ``MetricFleet`` merged
output must be bit-exact vs a single-process oracle at shard counts
{1, 2, 8}, 8-shard ingest throughput must reach 4x the 1-shard loop over
the simulated per-batch serving work, and a seeded shard-kill chaos soak
must recover with zero lost windows and no double-published merged window.

``--check-watermark`` is the rank-coherent streaming gate: a windowed
metric under a cross-rank ``WatermarkAgreement`` must stage the identical
in-jit sync program as the unwindowed metric (the min-exchange is
host-plane only), no window may publish before every participating rank's
watermark passes it (one seeded +30s-skewed rank and one late-burst rank on
the virtual mesh, merged values bit-exact vs a union-stream oracle), a
rate=1.0 stalled rank must be excluded after the agreement deadline
(``wm_stragglers`` > 0, publishes stamped degraded, no peer deadlock), and
sliding windows (``slide_s < window_s``) must be bit-exact vs independent
per-slot oracles.

``--check-quantile`` is the quantile-sketch gate: every quantile estimate on
seeded Zipfian/Cauchy/lognormal streams must land within the ``alpha``
relative-error certificate (overflow-bucket hits flagged ``inf``), the
(4,2)-mesh psum merge of per-device sketches must equal the single-process
sketch bit-exactly, ``Keyed(Quantile)`` / ``Windowed(Keyed(Quantile))`` must
stage the identical collective program as the unkeyed scalar metric
(psum-only, zero gathers), and qsketch state bytes must stay constant over
the stream while the capacity-buffer twin grows.

``--trace OUT.json`` (composable with ``--smoke``) enables the observability
subsystem around the A/B: the JSON line grows a ``phase_ms`` span-aggregate
table, and OUT.json gets a Chrome-trace/Perfetto file of the bench phases
(load at ui.perfetto.dev). Schema v3 (``trace_schema: 3``: the collective
counts moved to the default line, the hierarchical A/B and per-crossing
counters joined) additionally carries: ``compile`` — XLA compile telemetry
from ``jax.monitoring`` (event count, per-phase ms, persistent-cache
hit/miss), with every span in OUT.json stamped ``compiled=yes/no`` +
``compile_ms`` so first-dispatch spans stop conflating trace+compile with
run; ``device_ms`` — a per-metric update/sync/compute device-time table
from the fenced stateful scenario (``metrics_tpu.observability.devtime``);
``phase_compile_ms`` — the compile share of each bench phase; and the full
``counters``/``gather_counters``/``hier_counters`` snapshots (per-kind,
per-dtype, per-crossing).

``--check-trajectory`` is the bench-trajectory regression gate: it loads the
prior ``BENCH_r*.json`` rounds and diffs the current numbers (measured via a
smoke A/B, or injected with ``--trajectory-current FILE`` for testing)
against them — phase-latency drift beyond pinned tolerances or ANY staged
collective-count growth exits non-zero (``metrics_tpu.observability.regress``).
"""
import json
import math
import os
import subprocess
import sys
import time

import numpy as np

# resolve `benchmarks.timing` regardless of the caller's cwd; do NOT use
# PYTHONPATH for this (it breaks the axon TPU plugin registration)
_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

# persistent XLA compile cache: the chained-loop train-step programs are the
# slow part of this benchmark; cached, a re-run is seconds
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(_HERE, ".jax_cache_tpu"))

N_STEPS = 100
WARMUP = 10
BATCH_PER_DEVICE = 512
N_DEVICES = 8
NUM_CLASSES = 32
FEATURES = 256


GATHER_CAPACITY = 2048  # per-device rows of each buffer (cat) state
HIER_SLICES = 2  # the (4,2) test mesh: 2 virtual "slices" x 4 ici devices
# sketch A/B grid sizes: the curve sketch (AUROC+AP share ONE compute-group
# histogram) plus the Spearman rank sketch must together stay well under 10%
# of the buffer plane's payload — the acceptance gate --check-collectives pins
SKETCH_CURVE_BINS = 256  # (2, 256) int32 histogram = 2 KB
SKETCH_RANK_BINS = 16  # (16, 16) int32 joint histogram = 1 KB
# keyed-slab scenario: ONE sketch AUROC x 10,000 segments. The slab is a
# (K, 2, KEYED_BINS) histogram plus a (K,) row-count slab, and the pinned
# property is that the STAGED COLLECTIVE COUNT is identical to the unkeyed
# metric's — segments scale the payload, never the program.
KEYED_SLOTS = 10_000
KEYED_BINS = 16
# sparse delta-sync scenario (parallel/sparse.py): the SAME Keyed(AUROC
# sketch) x 10,000-slot slab, but each step touches only SPARSE_TOUCH rows
# and syncs through SparseSyncPlane — a lane-packed touched-row bitmap psum,
# then ONE fixed-capacity all_gather carrying only the union's rows behind a
# slot-id header, scatter-added into the local slab. The pinned properties:
# staged sync bytes proportional to the TOUCHED-ROW count, not K (the sparse
# gate pins them under a tenth of the dense keyed plane's), staged collective
# counts constant in K, merges bit-exact vs the dense coalesced plane on both
# the flat and (4,2) hierarchical meshes, and the capacity-overflow fallback
# to the dense plane counted (sparse_fallbacks — zero-pinned on a clean run).
SPARSE_TOUCH = 64
SPARSE_CAPACITY = 64
SPARSE_SMALL_K = 1_000  # the K-independence twin the sparse gate re-traces
# heavy-hitter scenario (wrappers/heavy_hitters.py): the same sketch AUROC
# behind the two-tier open-world wrapper — 256 exact hot slab rows over a
# (4, 1024)-cell count-min tail — fed keys drawn from a 1,000,000-key space.
# The pinned property extends the keyed gate to UNBOUNDED cardinality: both
# tiers are sum leaves, so the staged program is the identical two-stage
# psum the unkeyed metric stages (psum-only, zero gathers) and total state
# bytes are constant in the live-key count. The eager half of the gate pins
# mass conservation (hot + tail totals bit-exact vs an unkeyed oracle
# through promotion/demotion churn) and the (e/width)*N tail certificate on
# a seeded Zipfian stream.
HH_HOT_SLOTS = 256
HH_TAIL_DEPTH = 4
HH_TAIL_WIDTH = 1024
HH_KEY_SPACE = 1_000_000
HH_KEY_SPACE_SMALL = 10_000
HH_GATE_SLOTS = 64  # the eager gate/ingest streams use a smaller hot tier
# the gate stream's tail is DEEPER than the sync scenario's: the gate
# demands EVERY tail query within the certificate, and the per-query failure
# probability is e^-depth (1.8% at depth 4 — too loose over ~500 queries;
# 0.03% at depth 8 holds with margin on the seeded stream). The (e/width)*N
# bound itself is depth-independent.
HH_GATE_TAIL_DEPTH = 8
HH_GATE_BATCHES = 40
HH_GATE_BATCH = 64
# windowed serving scenario: the same sketch AUROC as a 4-slot tumbling ring
# (wrappers/windowed.py). The pinned property mirrors the keyed gate:
# windows are a leading STATE axis, so the staged collective count is
# identical to the unwindowed metric's (psum-only) — window roll is a slot
# rotation, never a new collective.
SERVICE_WINDOWS = 4
SERVICE_WINDOW_S = 60.0
# sharded serving fleet scenario (serving/fleet.py): N MetricService ingest
# shards behind the stable-hash router, merged by pure state addition as
# windows close. The pinned properties: merged output BIT-EXACT vs a
# single-process oracle at every shard count, and ingest throughput scaling
# near-linearly with shard count. The CI host is a single core, so the
# per-batch serving work the shards overlap is SIMULATED with a seeded
# ingest_stall at the fleet.shard chaos site (same convention as
# --check-async's simulated-DCN gather: sleeps overlap perfectly, so the
# measured ratio isolates the fleet's routing/queueing scalability).
FLEET_SHARDS = 8
FLEET_WINDOW_S = 10.0
FLEET_WINDOWS = 4
FLEET_LATENESS_S = 20.0
FLEET_EXACT_BATCHES = 24  # the bit-exact merge stream (per shard count)
FLEET_EXACT_BATCH = 16
FLEET_SCALE_BATCHES = 48  # the scaling stream (1 vs 8 shards)
FLEET_SCALE_BATCH = 8
FLEET_WORK_S = 0.15  # simulated per-batch serving work (the overlap target)
FLEET_SCALING_MIN_X = 4.0  # the gate: 8-shard >= 4x 1-shard throughput
FLEET_KILL_SHARDS = 4  # chaos soak topology
FLEET_KILL_CALL = 4  # the killed shard's ingest call (past its first publish)
FLEET_SOAK_BUDGET_S = 120.0

# pipeline-health soak parameters. The default-line soak advances event time
# deterministically (publish_lag_ms / selfmeter_p99_ms are monotonic-clock
# stage latencies; lifecycle_windows_stamped is routing arithmetic, exact).
# The --check-health lag tiers instead drive WALL-CLOCK event times, because
# watermark lag compares event time against the host clock — synthetic
# seconds-from-zero times would report a billion-second lag.
HEALTH_WINDOW_S = 10.0  # default-line soak (synthetic event time)
HEALTH_BATCHES = 16
HEALTH_BATCH = 8
HEALTH_STEP_S = 5.0  # event-time advance per batch (2 batches per window)
HEALTH_GATE_WINDOW_S = 0.4  # gate lag soak: ~6 windows in ~2.4 s wall
HEALTH_GATE_BATCHES = 24
HEALTH_GATE_STEP_S = 0.1  # wall sleep between gate-soak submissions
HEALTH_LAG_BOUND_S = 5.0  # clean-stream watermark lag must stay under this
HEALTH_STALL_S = 0.8  # the seeded ingest stall; lag must spike >= half this
HEALTH_FLEET_SHARDS = 4
# watermark-agreement scenario/gate (core/streaming.WatermarkAgreement +
# bench.py --check-watermark): N virtual ranks of the mesh share one agreed
# (global-min) clock; windows close, publish and recycle only when the
# AGREED watermark passes. The ring is sized for the seeded +30s skew: the
# skewed rank's local head runs (skew + window + lateness) / window_s = 5
# windows ahead of the agreed close frontier, so W = 8 keeps every
# agreement-open window resident (no expiry-forced early publish).
WM_RANKS = 4
WM_WINDOW_S = 10.0
WM_WINDOWS = 8
WM_LATENESS_S = 10.0
WM_SKEW_S = 30.0  # the seeded skewed rank's clock shift (+3 windows)
WM_SKEW_RANK = 1
WM_LATE_RANK = 2
WM_LATE_CALL = 3  # the late-burst batch on the late rank (its OWN call index)
WM_LATE_SKEW_S = 8.0  # within lateness: routed late, never dropped
WM_BATCHES = 12  # lockstep rounds (one batch per rank per round)
WM_BATCH = 16
WM_BUDGET_S = 60.0
WM_STALL_DEADLINE_S = 0.75  # the stall tier's agreement deadline
# sliding-window scenario: windows start every SLIDE_S seconds and span
# SLIDE_WINDOW_S, so each event scatters into SLIDE_WINDOW_S/SLIDE_S = 3
# overlapping ring slots; published windows are pinned bit-exact vs
# independent per-slot oracles. Lateness cap: W*slide - window = 6s.
SLIDE_WINDOW_S = 6.0
SLIDE_S = 2.0
SLIDE_WINDOWS = 6
SLIDE_LATENESS_S = 4.0
SLIDE_BATCHES = 10
SLIDE_BATCH = 8
# quantile-sketch scenario/gate (parallel/qsketch.py + bench.py
# --check-quantile): Keyed(Quantile(q=0.99)) x QSK_SLOTS tenants — the
# per-tenant p99 state — synced on the (4,2) mesh. The grid below is the
# bench-sized twin of the defaults: alpha=0.05 over 6 decades gives
# B = 2*139 + 3 = 281 buckets, so the keyed slab pair is
# (QSK_SLOTS * 281 + QSK_SLOTS) int32 cells. Pinned properties: staged
# collective count identical to the unkeyed scalar Quantile (psum-only,
# zero gathers), every estimate within the alpha certificate on the seeded
# Zipfian/Cauchy/lognormal gate streams, (4,2) psum merge bit-exact vs
# single-process, and state bytes FLAT while a capacity-buffer twin grows.
QSK_ALPHA = 0.05
QSK_LO = 1e-3
QSK_HI = 1e3
QSK_SLOTS = 256
QSK_GATE_N = 20_000  # samples per seeded gate stream
# tiered-retention scenario/gate (serving/retention.py + bench.py
# --check-retention): closed windows published by a real MetricService are
# banked in a RetentionStore and rolled up a resolution ladder by pure
# state addition. The gate drives ALL FOUR mergeable state kinds (array
# sums via Accuracy, histogram sketch via AUROC(approx="sketch"), quantile
# sketch via Quantile, count-min via a bench-local CMS vehicle) plus the
# nested Windowed(Keyed(...)) per-tenant plane through the store, tees the
# raw published partials, and pins every query — at the native mixed
# resolution and every legal coarse grid — BIT-exact against a flat
# recompute (value_from_partials over the union of raw partials), plus the
# memory-flat property: resident bytes bounded by the ladder shape, not by
# stream length. The stream below spans RET_BATCHES * RET_STEP_S = 240 s =
# 24 ten-second windows over the (4, 4, 8)-capacity ladder, so both
# roll-up rungs are exercised (the coarsest holds one merged bucket).
RET_WINDOW_S = 10.0
RET_WINDOWS = 4
RET_LADDER = ((RET_WINDOW_S, 4), (4 * RET_WINDOW_S, 4), (16 * RET_WINDOW_S, 8))
RET_BATCHES = 96
RET_BATCH = 8
RET_STEP_S = 2.5
RET_SPAN_S = RET_BATCHES * RET_STEP_S  # 240 s = 24 windows
RET_TENANTS = 8
RET_CMS_DEPTH = 4
RET_CMS_WIDTH = 64
RET_CMS_SEED = 7
RET_CMS_KEYS = 64  # distinct keys folded into the gate's count-min tail
RETENTION_READ_REPEATS = 12  # best-of repeats for the default-line read key
# megafusion mixed-collection scenario (--check-collectives megafusion
# gate): every mergeable state kind behind ONE MetricCollection — array
# sums (classification counts + float error sums), pmin/pmax riders (PSNR
# with a tracked data range), histogram + rank sketches, a count-min tail
# (HeavyHitters), and quantile sketches — synced through the PACKED reduce
# plane: all sum buckets fold into ONE variadic psum per crossing (4-byte
# integer dtypes bitcast into a shared int32 lane, float dtypes as sibling
# operands of the same call), with one pmin + one pmax riding for the
# dtypes that need them. The pinned property: the staged collective count
# is IDENTICAL at 6 and 14 members — membership grows the payload, never
# the program.
MIXED_MEMBERS = 6
MIXED_MEMBERS_WIDE = 14


def _serialize_cpu_dispatch():
    """Keep at most ONE XLA:CPU execution in flight.

    XLA:CPU's async dispatch enqueues consecutive executions of the timed
    step; runs whose collectives depend only on the (constant) state input
    — the gather planes — are not serialized by the carried-accumulator
    chain (see _build_gather_runner), so on a low-core host two concurrent
    runs' 8-participant rendezvous race for the same thread pool and can
    starve each other (observed as a permanent hang on a 1-core CI host:
    7 ranks parked in the AllGather rendezvous, the 8th never dispatched).
    Disabling async dispatch makes every run() loop effectively
    block_until_ready per step without touching the runners; it is a no-op
    for what is measured (the loops already time wall-clock over a final
    block). The flag is read when the CPU client is CREATED, so this must
    run before anything initializes the backend — which is also why there
    is no platform check here (``jax.default_backend()`` would itself
    create the client); the flag only shapes the CPU client and is inert
    for TPU measurement.
    """
    import jax

    jax.config.update("jax_cpu_enable_async_dispatch", False)


_FENCE_PER_STEP = None  # resolved on first _step_fence call (backend query)


def _step_fence(x):
    """Block on a sharded step's result before dispatching the next (CPU).

    ``jax_cpu_enable_async_dispatch=False`` only covers NON-parallel
    computations — the 8-virtual-device sharded programs the timed loops
    dispatch still overlap, and two in-flight executions' collective
    rendezvous can starve each other on a low-core host (see
    _serialize_cpu_dispatch). Fencing each step keeps exactly one sharded
    execution in flight; on a 1-core host cross-step pipelining was never
    real concurrency, so the per-step cost measured is unchanged. On real
    hardware this is identity — the device pipeline stays intact.
    """
    global _FENCE_PER_STEP
    if _FENCE_PER_STEP is None:
        import jax

        _FENCE_PER_STEP = jax.default_backend() == "cpu"
    if _FENCE_PER_STEP:
        import jax

        jax.block_until_ready(x)
    return x


def _collection_ours(compute_groups: bool = True):
    from metrics_tpu import Accuracy, F1, MetricCollection, Precision, Recall

    return MetricCollection([
        Accuracy(),
        F1(num_classes=NUM_CLASSES, average="macro"),
        Precision(num_classes=NUM_CLASSES, average="macro"),
        Recall(num_classes=NUM_CLASSES, average="macro"),
    ], compute_groups=compute_groups)


def _collection_gather():
    """The gather-plane collection: buffer-state (cat) metrics whose sync is
    ``all_gather`` of PaddedBuffer epochs, not ``psum`` of reduce states."""
    from metrics_tpu import AUROC, AveragePrecision, MetricCollection, SpearmanCorrcoef

    return MetricCollection([
        AUROC(capacity=GATHER_CAPACITY),
        AveragePrecision(num_classes=1, capacity=GATHER_CAPACITY),
        SpearmanCorrcoef(capacity=GATHER_CAPACITY),
    ])


def _collection_sketch():
    """The sketch-mode twin of ``_collection_gather``: the same AUROC + AP +
    Spearman members with ``approx="sketch"`` states instead of
    capacity-2048 buffers. AUROC and AveragePrecision share one compute
    group (identical sketch_curve_update plane), so the synced state is ONE
    (2, 256) histogram plus Spearman's (16, 16) joint — ~3 KB of psum-reduced
    payload against the buffer plane's ~48 KB of gathered payload."""
    from metrics_tpu import AUROC, AveragePrecision, MetricCollection, SpearmanCorrcoef

    return MetricCollection([
        AUROC(approx="sketch", num_bins=SKETCH_CURVE_BINS),
        AveragePrecision(approx="sketch", num_bins=SKETCH_CURVE_BINS),
        SpearmanCorrcoef(approx="sketch", num_bins=SKETCH_RANK_BINS),
    ])


def _shard_map(fn, mesh, in_specs, out_specs):
    """jax.shard_map on current jax; the experimental module on older jax."""
    from metrics_tpu.utils.compat import shard_map

    return shard_map(fn, mesh, in_specs, out_specs)


def _build_sync8_runner(compute_groups: bool):
    """(timed_run(steps) -> ms/step, states_synced) for one A/B variant.

    ``states_synced`` counts the state leaves entering the per-step
    collective sync — compute groups shrink it (one state pytree per
    group), coalesced sync then buckets what remains.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    pure = _collection_ours(compute_groups).pure()
    mesh = Mesh(np.array(jax.devices("cpu")[:N_DEVICES]), ("dp",))

    def step(state, preds, target):
        # local shard delta -> one collective sync -> replicated accumulate
        delta = pure.update(pure.init(), preds, target)
        delta = pure.sync(delta, "dp")
        state = pure.merge(state, delta)
        return state, pure.compute(state)

    sharded_step = jax.jit(
        _shard_map(step, mesh, in_specs=(P(), P("dp"), P("dp")), out_specs=(P(), P()))
    )

    rng = np.random.RandomState(0)
    batch = BATCH_PER_DEVICE * N_DEVICES
    logits = rng.rand(batch, NUM_CLASSES).astype(np.float32)
    preds = jnp.asarray(logits / logits.sum(-1, keepdims=True))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, batch).astype(np.int32))

    states_synced = len(jax.tree_util.tree_leaves(pure.init()))

    def run(steps: int) -> float:
        state = pure.init()
        out = None
        start = time.perf_counter()
        for _ in range(steps):
            state, out = sharded_step(state, preds, target)
            _step_fence(out)
        jax.block_until_ready(out)
        return (time.perf_counter() - start) / steps * 1e3

    return run, states_synced


def bench_ours_sync8(compute_groups: bool = True, steps: int = N_STEPS, warmup: int = WARMUP):
    """Per-step update + psum-sync + compute of the collection over an
    8-device mesh (the metric of record). Runs on virtual CPU devices."""
    run, states_synced = _build_sync8_runner(compute_groups)
    run(warmup)
    return run(steps), states_synced


def _build_gather_runner(coalesced: bool):
    """(timed_run(steps) -> ms/step, states_synced) for one gather-plane
    variant: 6 half-filled PaddedBuffer epoch states (AUROC + AP +
    Spearman) synced over the 8-device mesh per step, with the bucketed
    (``coalesced_sync_state``: one data + one counts ``all_gather`` per
    dtype bucket) vs the per-leaf plane (2 ``all_gather`` per buffer).
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu.parallel.sync import coalesced_sync_state, sync_state
    from metrics_tpu.utils.compat import shard_map

    col = _collection_gather()
    rng = np.random.RandomState(0)
    rows = GATHER_CAPACITY // 2  # half-filled: the sync moves capacity either way
    preds = jnp.asarray(rng.rand(rows).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 2, rows).astype(np.int32))
    col.update(preds, target)  # one eager update promotes every cat state to a buffer

    state = {(k, n): v for k, m in col.items() for n, v in m._current_state().items()}
    reductions = {key: col[key[0]]._reductions[key[1]] for key in state}
    mesh = Mesh(np.array(jax.devices("cpu")[:N_DEVICES]), ("dp",))
    sync = coalesced_sync_state if coalesced else sync_state

    def step(s, acc):
        synced = sync(s, reductions, "dp")
        # fold every synced leaf into the carried scalar: the carry chains
        # step i+1's RESULT on step i — but the gathers themselves depend
        # only on `state`, so async dispatch can still launch them
        # concurrently; _step_fence in the run() loop closes that hole on
        # low-core CPU hosts (see _serialize_cpu_dispatch)
        for leaf in jax.tree_util.tree_leaves(synced):
            acc = acc + jnp.sum(leaf.astype(jnp.float32))
        return acc

    # vma checking off: gather+compaction outputs are replicated but the
    # varying-axis checker cannot prove it through the compaction scatter
    sharded_step = jax.jit(
        shard_map(step, mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False)
    )

    def run(steps: int) -> float:
        acc = jnp.zeros((), jnp.float32)
        start = time.perf_counter()
        for _ in range(steps):
            acc = _step_fence(sharded_step(state, acc))
        jax.block_until_ready(acc)
        return (time.perf_counter() - start) / steps * 1e3

    return run, len(state)


def _build_hier_gather_runner(hierarchical: bool):
    """(timed_run(steps) -> ms/step, states_synced) for the hierarchical
    A/B: the same 6-buffer gather collection synced over the (4,2)
    ``ici`` x ``dcn`` test mesh (2 virtual slices x 4 devices), either with
    the two-stage hierarchical plane (one DCN exchange of per-slice
    payloads, then intra-slice replication) or the flat plane spanning the
    whole ``("dcn", "ici")`` world axis. Values are bit-identical; the
    staged DCN-crossing traffic is what shrinks (``bytes_by_crossing``).
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu.parallel.placement import MeshHierarchy
    from metrics_tpu.parallel.sync import coalesced_sync_state
    from metrics_tpu.utils.compat import shard_map

    col = _collection_gather()
    rng = np.random.RandomState(0)
    rows = GATHER_CAPACITY // 2
    preds = jnp.asarray(rng.rand(rows).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 2, rows).astype(np.int32))
    col.update(preds, target)

    state = {(k, n): v for k, m in col.items() for n, v in m._current_state().items()}
    reductions = {key: col[key[0]]._reductions[key[1]] for key in state}
    mesh = Mesh(
        np.array(jax.devices("cpu")[:N_DEVICES]).reshape(HIER_SLICES, N_DEVICES // HIER_SLICES),
        ("dcn", "ici"),
    )
    axis = MeshHierarchy(ici_axis="ici", dcn_axis="dcn") if hierarchical else ("dcn", "ici")
    # hierarchy=False pins the flat arm: auto-derivation would otherwise
    # promote the ("dcn", "ici") tuple axis to the two-stage plane
    hierarchy = None if hierarchical else False

    def step(s, acc):
        synced = coalesced_sync_state(s, reductions, axis, hierarchy=hierarchy)
        # carry chains step i+1 on step i (see _build_gather_runner)
        for leaf in jax.tree_util.tree_leaves(synced):
            acc = acc + jnp.sum(leaf.astype(jnp.float32))
        return acc

    sharded_step = jax.jit(
        shard_map(step, mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False)
    )

    def run(steps: int) -> float:
        acc = jnp.zeros((), jnp.float32)
        start = time.perf_counter()
        for _ in range(steps):
            acc = _step_fence(sharded_step(state, acc))
        jax.block_until_ready(acc)
        return (time.perf_counter() - start) / steps * 1e3

    return run, len(state)


def _build_sketch_sync_runner(hierarchical: bool = True):
    """(timed_run(steps) -> ms/step, states_synced) for the SKETCH sync
    scenario: the ``_collection_sketch`` states (one compute-group histogram
    for AUROC+AP, one rank joint for Spearman) synced per step with
    ``coalesced_sync_state`` on the same (4,2) ici x dcn mesh the
    hierarchical gather A/B uses. The sketch leaves fold into ONE int32 sum
    bucket, so the staged program is psum-only — zero all_gathers — and the
    payload is traffic-independent (~3 KB vs the buffer plane's ~48 KB).
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu.parallel.placement import MeshHierarchy
    from metrics_tpu.parallel.sync import coalesced_sync_state
    from metrics_tpu.utils.compat import shard_map

    col = _collection_sketch()
    rng = np.random.RandomState(0)
    rows = GATHER_CAPACITY // 2  # the same per-step traffic as the gather A/B
    preds = jnp.asarray(rng.rand(rows).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 2, rows).astype(np.int32))
    col.update(preds, target)

    # one state entry per compute group (AUROC+AP share their histogram),
    # exactly what the collection's pure sync plane would move
    gm = col._group_map()
    state = {
        (k, n): v for k, m in col.items() if gm[k] == k for n, v in m._current_state().items()
    }
    reductions = {key: col[key[0]]._reductions[key[1]] for key in state}
    if hierarchical:
        mesh = Mesh(
            np.array(jax.devices("cpu")[:N_DEVICES]).reshape(HIER_SLICES, N_DEVICES // HIER_SLICES),
            ("dcn", "ici"),
        )
        axis = MeshHierarchy(ici_axis="ici", dcn_axis="dcn")
    else:
        mesh = Mesh(np.array(jax.devices("cpu")[:N_DEVICES]), ("dp",))
        axis = "dp"

    def step(s, acc):
        synced = coalesced_sync_state(s, reductions, axis)
        # carry chains step i+1 on step i (see _build_gather_runner)
        for leaf in jax.tree_util.tree_leaves(synced):
            acc = acc + jnp.sum(leaf.astype(jnp.float32))
        return acc

    sharded_step = jax.jit(
        shard_map(step, mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False)
    )

    def run(steps: int) -> float:
        acc = jnp.zeros((), jnp.float32)
        start = time.perf_counter()
        for _ in range(steps):
            acc = _step_fence(sharded_step(state, acc))
        jax.block_until_ready(acc)
        return (time.perf_counter() - start) / steps * 1e3

    return run, len(state)


def _build_keyed_sync_runner(num_slots: "int | None" = KEYED_SLOTS):
    """(timed_run(steps) -> ms/step, states_synced) for the KEYED multi-
    tenant scenario: ``Keyed(AUROC(approx="sketch"), num_slots=K)`` — one
    metric x K segments as a leading state axis — synced per step with
    ``coalesced_sync_state`` on the (4,2) ici x dcn mesh. The slab leaves
    (a (K, 2, B) histogram slab + the (K,) row-count slab) fold into ONE
    int32 sum bucket, so the staged program is the same two-stage psum the
    unkeyed sketch metric stages: collective counts are K-INDEPENDENT
    (``num_slots=None`` builds the unkeyed twin the cross-scenario keyed
    gate compares against).
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu import AUROC, Keyed
    from metrics_tpu.parallel.placement import MeshHierarchy
    from metrics_tpu.parallel.sync import coalesced_sync_state
    from metrics_tpu.utils.compat import shard_map

    inner = AUROC(approx="sketch", num_bins=KEYED_BINS)
    metric = inner if num_slots is None else Keyed(inner, num_slots=num_slots)
    rng = np.random.RandomState(0)
    rows = GATHER_CAPACITY // 2  # same per-step traffic shape as the sketch A/B
    preds = jnp.asarray(rng.rand(rows).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 2, rows).astype(np.int32))
    if num_slots is None:
        metric.update(preds, target)
    else:
        slots = jnp.asarray(rng.randint(0, num_slots, rows).astype(np.int32))
        metric.update(preds, target, slot=slots)

    state = metric._current_state()
    reductions = metric._reductions
    mesh = Mesh(
        np.array(jax.devices("cpu")[:N_DEVICES]).reshape(HIER_SLICES, N_DEVICES // HIER_SLICES),
        ("dcn", "ici"),
    )
    axis = MeshHierarchy(ici_axis="ici", dcn_axis="dcn")

    def step(s, acc):
        synced = coalesced_sync_state(s, reductions, axis)
        # carry chains step i+1 on step i (see _build_gather_runner)
        for leaf in jax.tree_util.tree_leaves(synced):
            acc = acc + jnp.sum(leaf.astype(jnp.float32))
        return acc

    sharded_step = jax.jit(
        shard_map(step, mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False)
    )

    def run(steps: int) -> float:
        acc = jnp.zeros((), jnp.float32)
        start = time.perf_counter()
        for _ in range(steps):
            acc = _step_fence(sharded_step(state, acc))
        jax.block_until_ready(acc)
        return (time.perf_counter() - start) / steps * 1e3

    return run, len(state)


def _build_sparse_sync_runner(num_slots: int = KEYED_SLOTS, hierarchical: bool = True):
    """(timed_run(steps) -> ms/step, states_synced) for the SPARSE delta-sync
    scenario: the same ``Keyed(AUROC sketch, K)`` slab as the keyed A/B, but
    each step's batch touches only ``SPARSE_TOUCH`` distinct rows and syncs
    through ``SparseSyncPlane`` — a lane-packed touched-row bitmap psum, then
    ONE fixed-capacity all_gather of only the union's rows (slot-id header +
    per-leaf contributions), scatter-added into the local slab. The staged
    payload follows the TOUCHED-ROW count, not K: the sparse gate pins it
    under a tenth of the dense ``keyed_sync`` plane's bytes with a staged
    collective count constant in K.

    The plane is built while the metric is RESET (that snapshot is the delta
    baseline); ``run`` replays seeded rebase+sync rounds with the
    ``slab_touched_mask`` hint, so the first call compiles and stages both
    sparse programs (bitmap + union gather) and never overflows capacity.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from metrics_tpu import AUROC, Keyed
    from metrics_tpu.parallel.slab import slab_touched_mask

    metric = Keyed(AUROC(approx="sketch", num_bins=KEYED_BINS), num_slots=num_slots)
    if hierarchical:
        mesh = Mesh(
            np.array(jax.devices("cpu")[:N_DEVICES]).reshape(HIER_SLICES, N_DEVICES // HIER_SLICES),
            ("dcn", "ici"),
        )
        axis = ("dcn", "ici")  # auto-derived two-stage ici-first hierarchy
    else:
        mesh = Mesh(np.array(jax.devices("cpu")[:N_DEVICES]), ("dp",))
        axis = "dp"
    plane = metric.sparse_plane(axis, mesh, capacity=SPARSE_CAPACITY)
    initial = metric._current_state()

    rng = np.random.RandomState(0)
    rows = GATHER_CAPACITY // 2  # same per-step traffic shape as the keyed A/B
    preds = jnp.asarray(rng.rand(rows).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 2, rows).astype(np.int32))
    hot = rng.choice(num_slots, min(SPARSE_TOUCH, num_slots), replace=False)
    slots = jnp.asarray(hot[rng.randint(0, len(hot), rows)].astype(np.int32))
    metric.update(preds, target, slot=slots)
    updated = metric._current_state()
    touched = slab_touched_mask(slots, num_slots)

    def run(steps: int) -> float:
        start = time.perf_counter()
        for _ in range(steps):
            plane.rebase(initial)
            plane.sync(updated, touched=touched)
        return (time.perf_counter() - start) / steps * 1e3

    return run, len(updated)


def _build_qsketch_sync_runner(num_slots: "int | None" = QSK_SLOTS):
    """(timed_run(steps) -> ms/step, states_synced) for the QUANTILE-SKETCH
    scenario: ``Keyed(Quantile(q=0.99), num_slots=K)`` — the per-tenant p99
    state — synced per step with ``coalesced_sync_state`` on the (4,2)
    ici x dcn mesh. The slab leaves (a (K, B) log-bucketed counts slab + the
    (K,) row-count slab) fold into ONE int32 sum bucket, so the staged
    program is the same two-stage psum the unkeyed scalar Quantile stages:
    collective counts are K-INDEPENDENT (``num_slots=None`` builds the
    unkeyed twin the parity pin compares against).
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu import Keyed, Quantile
    from metrics_tpu.parallel.placement import MeshHierarchy
    from metrics_tpu.parallel.sync import coalesced_sync_state
    from metrics_tpu.utils.compat import shard_map

    inner = Quantile(q=0.99, alpha=QSK_ALPHA, min_value=QSK_LO, max_value=QSK_HI)
    metric = inner if num_slots is None else Keyed(inner, num_slots=num_slots)
    rng = np.random.RandomState(0)
    rows = GATHER_CAPACITY // 2  # same per-step traffic shape as the sketch A/B
    values = jnp.asarray(rng.lognormal(0.0, 1.5, rows).astype(np.float32))
    if num_slots is None:
        metric.update(values)
    else:
        slots = jnp.asarray(rng.randint(0, num_slots, rows).astype(np.int32))
        metric.update(values, slot=slots)

    state = metric._current_state()
    reductions = metric._reductions
    mesh = Mesh(
        np.array(jax.devices("cpu")[:N_DEVICES]).reshape(HIER_SLICES, N_DEVICES // HIER_SLICES),
        ("dcn", "ici"),
    )
    axis = MeshHierarchy(ici_axis="ici", dcn_axis="dcn")

    def step(s, acc):
        synced = coalesced_sync_state(s, reductions, axis)
        for leaf in jax.tree_util.tree_leaves(synced):
            acc = acc + jnp.sum(leaf.astype(jnp.float32))
        return acc

    sharded_step = jax.jit(
        shard_map(step, mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False)
    )

    def run(steps: int) -> float:
        acc = jnp.zeros((), jnp.float32)
        start = time.perf_counter()
        for _ in range(steps):
            acc = _step_fence(sharded_step(state, acc))
        jax.block_until_ready(acc)
        return (time.perf_counter() - start) / steps * 1e3

    return run, len(state)


def _qsketch_state_bytes() -> int:
    """The keyed per-tenant p99 metric's state bytes — deterministic and
    traffic-independent by construction ((K*B + K) int32 cells); the
    default line carries it so --check-trajectory pins any growth."""
    import jax.numpy as jnp

    from metrics_tpu import Keyed, Quantile
    from metrics_tpu.observability.counters import state_nbytes

    metric = Keyed(
        Quantile(q=0.99, alpha=QSK_ALPHA, min_value=QSK_LO, max_value=QSK_HI),
        num_slots=QSK_SLOTS,
    )
    rng = np.random.RandomState(1)
    metric.update(
        jnp.asarray(rng.lognormal(0.0, 1.0, 64).astype(np.float32)),
        slot=jnp.asarray(rng.randint(0, QSK_SLOTS, 64).astype(np.int32)),
    )
    return int(state_nbytes(metric._current_state()))


def _build_hh_sync_runner():
    """(timed_run(steps) -> ms/step, states_synced) for the HEAVY-HITTER
    open-world scenario: ``HeavyHitters(AUROC(approx="sketch"), 256 hot
    slots, (4, 1024) tail)`` fed keys from a 1M-key space, synced per step
    with ``coalesced_sync_state`` on the (4,2) ici x dcn mesh. The hot slab
    pair ((K, 2, B) histogram + (K,) rows) and the tail pair ((D, W, 2, B)
    count-min + (D, W) rows) all fold into ONE int32 sum bucket, so the
    staged program is the same two-stage psum the unkeyed sketch metric
    stages (the ``keyed_unkeyed`` twin): collective counts — and state
    bytes — are independent of the simulated key count.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu import AUROC, HeavyHitters
    from metrics_tpu.parallel.placement import MeshHierarchy
    from metrics_tpu.parallel.sync import coalesced_sync_state
    from metrics_tpu.utils.compat import shard_map

    metric = HeavyHitters(
        AUROC(approx="sketch", num_bins=KEYED_BINS),
        num_hot_slots=HH_HOT_SLOTS, tail=(HH_TAIL_DEPTH, HH_TAIL_WIDTH),
    )
    rng = np.random.RandomState(0)
    rows = GATHER_CAPACITY // 2  # same per-step traffic shape as the sketch A/B
    preds = jnp.asarray(rng.rand(rows).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 2, rows).astype(np.int32))
    keys = [int(k) for k in rng.randint(0, HH_KEY_SPACE, rows)]
    metric.update(preds, target, key=keys)

    state = metric._current_state()
    reductions = metric._reductions
    mesh = Mesh(
        np.array(jax.devices("cpu")[:N_DEVICES]).reshape(HIER_SLICES, N_DEVICES // HIER_SLICES),
        ("dcn", "ici"),
    )
    axis = MeshHierarchy(ici_axis="ici", dcn_axis="dcn")

    def step(s, acc):
        synced = coalesced_sync_state(s, reductions, axis)
        # carry chains step i+1 on step i (see _build_gather_runner)
        for leaf in jax.tree_util.tree_leaves(synced):
            acc = acc + jnp.sum(leaf.astype(jnp.float32))
        return acc

    sharded_step = jax.jit(
        shard_map(step, mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False)
    )

    def run(steps: int) -> float:
        acc = jnp.zeros((), jnp.float32)
        start = time.perf_counter()
        for _ in range(steps):
            acc = _step_fence(sharded_step(state, acc))
        jax.block_until_ready(acc)
        return (time.perf_counter() - start) / steps * 1e3

    return run, len(state)


def _hh_stream(key_space: int, batches: int, batch: int, seed: int = 11):
    """The seeded Zipfian key stream the heavy-hitter gate and ingest
    scenarios share: heavy keys concentrate (and promote), the long tail
    exercises the count-min tier."""
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    for _ in range(batches):
        keys = [int(k) for k in rng.zipf(1.3, batch) % key_space]
        preds = jnp.asarray(rng.rand(batch).astype(np.float32))
        target = jnp.asarray(rng.randint(0, 2, batch).astype(np.int32))
        yield keys, preds, target


def _collection_mixed(members: int = MIXED_MEMBERS):
    """The MIXED gate collection: all four mergeable state kinds behind one
    ``MetricCollection``. Binary classification counts (int32 sums), float
    error sums (MSE/PSNR — and MAE at 14 members), the PSNR tracked data
    range (the pmin/pmax riders), curve/rank histogram sketches, a
    HeavyHitters count-min tail, and per-step quantile sketches.
    ``members=14`` widens every family without adding a dtype bucket, which
    is exactly what the megafusion gate pins: the packed reduce plane's
    staged collective count must not move between the two sizes."""
    from metrics_tpu import (
        AUROC, Accuracy, F1, HeavyHitters, MeanAbsoluteError,
        MeanSquaredError, MetricCollection, PSNR, Precision, Quantile,
        Recall, SpearmanCorrcoef, Specificity,
    )

    cols = {
        "acc": Accuracy(),
        "mse": MeanSquaredError(),
        "psnr": PSNR(),
        "auroc": AUROC(approx="sketch", num_bins=KEYED_BINS),
        "p99": Quantile(q=0.99, alpha=QSK_ALPHA, min_value=QSK_LO, max_value=QSK_HI),
        "hh": HeavyHitters(
            AUROC(approx="sketch", num_bins=KEYED_BINS),
            num_hot_slots=HH_GATE_SLOTS, tail=(HH_TAIL_DEPTH, HH_TAIL_WIDTH),
        ),
    }
    if members > MIXED_MEMBERS:
        cols.update({
            "prec": Precision(),
            "rec": Recall(),
            "f1": F1(),
            "spec": Specificity(),
            "mae": MeanAbsoluteError(),
            "spear": SpearmanCorrcoef(approx="sketch", num_bins=SKETCH_RANK_BINS),
            "p50": Quantile(q=0.5, alpha=QSK_ALPHA, min_value=QSK_LO, max_value=QSK_HI),
            "psnr2": PSNR(),
        })
    assert len(cols) == members, (len(cols), members)
    return MetricCollection(cols)


def _mixed_update(col) -> None:
    """Drive one seeded batch through every member of the mixed collection
    EAGERLY (HeavyHitters' space-saving table is host-side, so the batch
    cannot run under jit — same constraint as ``_build_hh_sync_runner``);
    the sync plane is then traced over the members' ``_current_state``."""
    import jax.numpy as jnp

    from metrics_tpu import HeavyHitters, Quantile
    from metrics_tpu.regression import MeanAbsoluteError, MeanSquaredError, PSNR

    rng = np.random.RandomState(0)
    rows = GATHER_CAPACITY // 2  # same per-step traffic shape as the sketch A/B
    probs = jnp.asarray(rng.rand(rows).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 2, rows).astype(np.int32))
    values = jnp.asarray(rng.lognormal(0.0, 1.5, rows).astype(np.float32))
    keys = [int(k) for k in rng.randint(0, HH_KEY_SPACE, rows)]
    for m in col.values():
        if isinstance(m, HeavyHitters):
            m.update(probs, target, key=keys)
        elif isinstance(m, Quantile):
            m.update(values)
        elif isinstance(m, (MeanAbsoluteError, MeanSquaredError, PSNR)):
            m.update(probs, target.astype(jnp.float32))
        else:
            m.update(probs, target)


def _build_mixed_sync_runner(members: int = MIXED_MEMBERS, hierarchical: bool = True):
    """(timed_run(steps) -> ms/step, states_synced) for the MEGAFUSION mixed
    scenario: the whole mixed collection's joint state synced per step with
    ``MetricCollection.sync_state`` on the (4,2) ici x dcn mesh (or the
    flat ``dp`` axis). Every sum leaf — int32 classification counts, f32
    error sums, histogram/rank/quantile sketch counts, the HeavyHitters
    hot slab + count-min tail — folds into ONE packed psum per crossing
    (int dtypes bitcast into the int32 lane, floats as sibling operands of
    the same call), with one pmin + one pmax riding for PSNR's tracked
    data range: 3 staged calls flat, 6 hierarchical, at EITHER size."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu.parallel.placement import MeshHierarchy
    from metrics_tpu.utils.compat import shard_map

    col = _collection_mixed(members)
    _mixed_update(col)
    state = {k: m._current_state() for k, m in col.items()}
    if hierarchical:
        mesh = Mesh(
            np.array(jax.devices("cpu")[:N_DEVICES]).reshape(HIER_SLICES, N_DEVICES // HIER_SLICES),
            ("dcn", "ici"),
        )
        axis = MeshHierarchy(ici_axis="ici", dcn_axis="dcn")
    else:
        mesh = Mesh(np.array(jax.devices("cpu")[:N_DEVICES]), ("dp",))
        axis = "dp"

    def step(s, acc):
        synced = col.sync_state(s, axis)
        # carry chains step i+1 on step i (see _build_gather_runner)
        for leaf in jax.tree_util.tree_leaves(synced):
            acc = acc + jnp.sum(leaf.astype(jnp.float32))
        return acc

    sharded_step = jax.jit(
        shard_map(step, mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False)
    )

    def run(steps: int) -> float:
        acc = jnp.zeros((), jnp.float32)
        start = time.perf_counter()
        for _ in range(steps):
            acc = _step_fence(sharded_step(state, acc))
        jax.block_until_ready(acc)
        return (time.perf_counter() - start) / steps * 1e3

    return run, sum(len(s) for s in state.values())


def _mixed_sync_parity_failures() -> list:
    """The megafusion gate's bit-exactness half: the packed
    one-psum-per-crossing plane must reproduce the per-leaf ``sync_value``
    reference EXACTLY for every state leaf of the 14-member mixed
    collection — all four mergeable state kinds, int and float dtypes,
    min/max riders included — on BOTH the flat axis and the (4,2)
    ici x dcn hierarchy. Returns failure strings (empty on parity)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu.parallel.placement import MeshHierarchy
    from metrics_tpu.parallel.sync import sync_value
    from metrics_tpu.utils.compat import shard_map

    col = _collection_mixed(MIXED_MEMBERS_WIDE)
    _mixed_update(col)
    state = {k: m._current_state() for k, m in col.items()}
    reductions = {k: m._reductions for k, m in col.items()}
    failures = []
    for arm in ("flat", "hier"):
        if arm == "hier":
            mesh = Mesh(
                np.array(jax.devices("cpu")[:N_DEVICES]).reshape(HIER_SLICES, N_DEVICES // HIER_SLICES),
                ("dcn", "ici"),
            )
            axis = MeshHierarchy(ici_axis="ici", dcn_axis="dcn")
        else:
            mesh = Mesh(np.array(jax.devices("cpu")[:N_DEVICES]), ("dp",))
            axis = "dp"

        def packed(s):
            return col.sync_state(s, axis)

        def per_leaf(s):
            return {
                k: {n: sync_value(reductions[k][n], v, axis) for n, v in s[k].items()}
                for k in s
            }

        run_packed = jax.jit(
            shard_map(packed, mesh=mesh, in_specs=(P(),), out_specs=P(), check_vma=False)
        )
        run_ref = jax.jit(
            shard_map(per_leaf, mesh=mesh, in_specs=(P(),), out_specs=P(), check_vma=False)
        )
        got = jax.tree_util.tree_leaves(run_packed(state))
        want = jax.tree_util.tree_leaves(run_ref(state))
        bad = sum(
            not np.array_equal(np.asarray(g), np.asarray(w))
            for g, w in zip(got, want)
        )
        if len(got) != len(want) or bad:
            failures.append(
                f"megafusion gate: packed psum diverged from the per-leaf sync"
                f" reference on the {arm} mesh ({bad}/{len(want)} leaves)"
                " — the packed plane must be bit-exact"
            )
    return failures


def _bench_fused_forward(steps: int = N_STEPS, warmup: int = WARMUP) -> float:
    """ms/step of the MEGAFUSED whole-collection forward: the sync8
    collection driven through the host API, where ONE jitted program per
    (membership, generation) runs every compute-group update together —
    input canonicalization shared across groups, state slabs donated back
    to XLA. The first call builds + caches the collection step; a dead
    ``_col_step`` afterwards means the fused path silently fell back and
    the key would lie, so that raises instead."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    col = _collection_ours(True)
    rng = np.random.RandomState(0)
    rows = BATCH_PER_DEVICE * N_DEVICES
    preds = jnp.asarray(rng.rand(rows, NUM_CLASSES).astype(np.float32))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, rows).astype(np.int32))
    out = col(preds, target)  # compiles + caches the collection-fused step
    if col._col_step is None:
        raise RuntimeError("megafused collection step did not build")
    for _ in range(warmup):
        out = col(preds, target)
    jax.block_until_ready(jax.tree_util.tree_leaves(out))
    start = time.perf_counter()
    for _ in range(steps):
        out = col(preds, target)
    jax.block_until_ready(jax.tree_util.tree_leaves(out))
    return (time.perf_counter() - start) / steps * 1e3


HH_INGEST_BATCHES = 16
HH_INGEST_WARMUP = 4


def _bench_hh_ingest(key_space: int):
    """(batches/sec, metric) through a real ``HeavyHitters`` ingest loop —
    host-side space-saving routing, hot scatters, tail folds, promotion
    churn included. Measured at a 10k AND a 1M key space: the loop's work
    is constant in the key-space size (the table is O(hot), the tail is
    O(depth x width)), so steps/s must stay FLAT as keys grow — the number
    that makes "open-world cardinality" a measured claim, not a design
    note."""
    from metrics_tpu import Accuracy, HeavyHitters

    hh = HeavyHitters(Accuracy(), num_hot_slots=HH_GATE_SLOTS,
                      tail=(HH_GATE_TAIL_DEPTH, HH_TAIL_WIDTH))
    stream = list(_hh_stream(key_space, HH_INGEST_BATCHES + HH_INGEST_WARMUP,
                             HH_GATE_BATCH, seed=13))
    for keys, preds, target in stream[:HH_INGEST_WARMUP]:
        hh.update(preds, target, key=keys)  # compile the scatter/fold paths
    start = time.perf_counter()
    for keys, preds, target in stream[HH_INGEST_WARMUP:]:
        hh.update(preds, target, key=keys)
    elapsed = time.perf_counter() - start
    return HH_INGEST_BATCHES / max(elapsed, 1e-9), hh


def _build_windowed_sync_runner(windowed: bool = True, with_agreement: bool = False):
    """(timed_run(steps) -> ms/step, states_synced) for the WINDOWED serving
    scenario: ``Windowed(AUROC(approx="sketch"), window_s, num_windows=4)``
    — tumbling windows as ring slots on the state's leading axis — synced
    per step with ``coalesced_sync_state`` on the (4,2) ici x dcn mesh. The
    window slabs (a (W, 2, B) histogram slab + the (W,) row-count slab) fold
    into ONE int32 sum bucket, so the staged program is the same two-stage
    psum the unwindowed sketch metric stages: collective counts are
    WINDOW-COUNT-INDEPENDENT (``windowed=False`` builds the unwindowed twin
    the ``--check-service`` parity gate compares against).
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu import AUROC, Windowed
    from metrics_tpu.parallel.placement import MeshHierarchy
    from metrics_tpu.parallel.sync import coalesced_sync_state
    from metrics_tpu.utils.compat import shard_map

    inner = AUROC(approx="sketch", num_bins=KEYED_BINS)
    if windowed:
        metric = Windowed(
            inner, window_s=SERVICE_WINDOW_S, num_windows=SERVICE_WINDOWS,
            allowed_lateness_s=(SERVICE_WINDOWS - 1) * SERVICE_WINDOW_S,
        )
        if with_agreement:
            # the --check-watermark parity tier: a metric UNDER a watermark
            # agreement must stage the identical in-jit sync program — the
            # exchange is host-plane only, never a staged collective
            from metrics_tpu import WatermarkAgreement

            agreement = WatermarkAgreement(deadline_s=3600.0, label="bench/wm_parity")
            metric.attach_agreement(agreement, rank=0)
    else:
        metric = inner
    rng = np.random.RandomState(0)
    rows = GATHER_CAPACITY // 2  # same per-step traffic shape as the sketch A/B
    preds = jnp.asarray(rng.rand(rows).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 2, rows).astype(np.int32))
    if windowed:
        # events spread over the still-open horizon: windows 1..3 of the
        # 4-slot ring, none late enough to drop
        times = rng.uniform(SERVICE_WINDOW_S, SERVICE_WINDOWS * SERVICE_WINDOW_S, rows)
        metric.update(preds, target, event_time=times)
        if with_agreement:
            # one exchange round rides the host plane before the staged
            # capture: the counters prove it stages nothing
            handle = metric.agreement.exchange()
            if handle is not None:
                handle.result(10.0)
    else:
        metric.update(preds, target)

    state = metric._current_state()
    reductions = metric._reductions
    mesh = Mesh(
        np.array(jax.devices("cpu")[:N_DEVICES]).reshape(HIER_SLICES, N_DEVICES // HIER_SLICES),
        ("dcn", "ici"),
    )
    axis = MeshHierarchy(ici_axis="ici", dcn_axis="dcn")

    def step(s, acc):
        synced = coalesced_sync_state(s, reductions, axis)
        # carry chains step i+1 on step i (see _build_gather_runner)
        for leaf in jax.tree_util.tree_leaves(synced):
            acc = acc + jnp.sum(leaf.astype(jnp.float32))
        return acc

    sharded_step = jax.jit(
        shard_map(step, mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False)
    )

    def run(steps: int) -> float:
        acc = jnp.zeros((), jnp.float32)
        start = time.perf_counter()
        for _ in range(steps):
            acc = _step_fence(sharded_step(state, acc))
        jax.block_until_ready(acc)
        return (time.perf_counter() - start) / steps * 1e3

    return run, len(state)


def _build_async_sync8_runner(deferred: bool, depth: int = 1):
    """(timed_run(steps) -> ms/step, states_synced) for the DEFERRED-SYNC A/B
    on the sync8 collection: the per-step program split into one update
    dispatch (per-shard group deltas, stacked over the mesh axis) plus one
    staged-sync dispatch (``coalesced_sync_state`` — the identical bucketed
    psum the in-loop plane stages). Both variants dispatch the SAME two
    programs per step; only the fence moves. The fenced variant
    (``deferred=False``) blocks on each step's sync before the next step —
    the synchronous plane's critical path. The deferred variant dispatches
    through ``parallel.deferred.deferred_sync_state`` and fences the
    PREVIOUS step's :class:`SyncHandle` (the ``sync_lag=1`` read), so the
    collective's device time overlaps the next step's update. After each
    ``run(steps)`` call, ``run.last_wait_ms`` holds the total time the host
    spent blocked on fences — the overlap evidence ``--check-async``
    reports next to the ms A/B.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu.parallel.deferred import DeferredSyncPlane
    from metrics_tpu.parallel.sync import coalesced_sync_state
    from metrics_tpu.utils.compat import shard_map

    col = _collection_ours(True)
    pure = col.pure()
    mesh = Mesh(np.array(jax.devices("cpu")[:N_DEVICES]), ("dp",))
    init = pure.init()
    reductions = {(k, n): col[k]._reductions[n] for k, s in init.items() for n in s}

    def upd(preds, target):
        delta = pure.update(pure.init(), preds, target)
        flat = {(k, n): v for k, s in delta.items() for n, v in s.items()}
        return jax.tree_util.tree_map(lambda x: x[None], flat)

    update_prog = jax.jit(
        shard_map(upd, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P("dp"))
    )

    def syncb(flat):
        per = {k: v[0] for k, v in flat.items()}
        return coalesced_sync_state(per, reductions, "dp")

    # vma checking off: psum outputs are replicated but the checker cannot
    # always prove it through the bucket slicing (same as the gather runners)
    sync_prog = jax.jit(
        shard_map(syncb, mesh=mesh, in_specs=(P("dp"),), out_specs=P(), check_vma=False)
    )

    rng = np.random.RandomState(0)
    batch = BATCH_PER_DEVICE * N_DEVICES
    logits = rng.rand(batch, NUM_CLASSES).astype(np.float32)
    preds = jnp.asarray(logits / logits.sum(-1, keepdims=True))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, batch).astype(np.int32))

    if deferred:
        # the hot-loop form: the plane resolves its compiled program once
        # (tracing here, so the staged-collective capture sees it) and each
        # step pays one unfenced dispatch + one handle. ``depth`` is the
        # lag-k ring: up to ``depth`` dispatched syncs stay in flight before
        # the oldest is fenced (depth=1 is PR 10's single-handle loop).
        from collections import deque

        template = update_prog(preds, target)
        plane = DeferredSyncPlane(reductions, "dp", mesh, template)

        def run(steps: int) -> float:
            ring = deque()
            wait = 0.0
            start = time.perf_counter()
            for _ in range(steps):
                ring.append(plane.dispatch(update_prog(preds, target)))
                if len(ring) > depth:
                    w0 = time.perf_counter()
                    ring.popleft().result()
                    wait += time.perf_counter() - w0
            while ring:
                w0 = time.perf_counter()
                ring.popleft().result()
                wait += time.perf_counter() - w0
            run.last_wait_ms = wait * 1e3
            return (time.perf_counter() - start) / steps * 1e3

    else:

        def run(steps: int) -> float:
            wait = 0.0
            start = time.perf_counter()
            for _ in range(steps):
                synced = sync_prog(update_prog(preds, target))
                w0 = time.perf_counter()
                jax.block_until_ready(synced)
                wait += time.perf_counter() - w0
            run.last_wait_ms = wait * 1e3
            return (time.perf_counter() - start) / steps * 1e3

    run.last_wait_ms = 0.0
    return run, len(reductions)


# serving ingest throughput: the traffic-generator scenario. Event times
# advance ~2.5 s per batch over 10 s windows, so the measured loop includes
# real window closes (and their deferred publishes) — ingest throughput of
# the SERVING loop, not of a bare update.
SERVICE_INGEST_BATCHES = 24
SERVICE_INGEST_BATCH = 64
SERVICE_INGEST_WARMUP = 4


def _bench_service_ingest(batches: int = SERVICE_INGEST_BATCHES) -> float:
    """Sustained batches/sec through a real ``MetricService`` ingest loop
    (bounded queue, background worker, watermark routing, deferred window
    publishes) — the serving-throughput number ``service_sync_ms`` never
    measured: that key times the sync *program*, this one times the loop."""
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MetricService, Windowed
    from metrics_tpu.parallel.sync import gather_all_arrays

    metric = Windowed(
        Accuracy(), window_s=10.0, num_windows=4, allowed_lateness_s=10.0,
        dist_sync_fn=gather_all_arrays,
    )
    rng = np.random.RandomState(3)
    data = []
    for i in range(batches + SERVICE_INGEST_WARMUP):
        preds = jnp.asarray(rng.rand(SERVICE_INGEST_BATCH).astype(np.float32))
        target = jnp.asarray((rng.rand(SERVICE_INGEST_BATCH) > 0.5).astype(np.int32))
        times = i * 2.5 + rng.uniform(0.0, 2.5, SERVICE_INGEST_BATCH)
        data.append((preds, target, times))
    with MetricService(metric, queue_size=batches + SERVICE_INGEST_WARMUP) as svc:
        for preds, target, times in data[:SERVICE_INGEST_WARMUP]:
            svc.submit(preds, target, event_time=times)  # compile the scatter path
        svc.flush()
        start = time.perf_counter()
        for preds, target, times in data[SERVICE_INGEST_WARMUP:]:
            svc.submit(preds, target, event_time=times)
        svc.flush()
        elapsed = time.perf_counter() - start
    return batches / max(elapsed, 1e-9)


# The ingest fast path's A/B: the SAME pre-staged bursty stream through a
# coalescing service (the worker drains the backlog and applies each
# contiguous publish-free run as ONE routed update) vs the one-batch twin
# (coalesce_max_batches=1). The whole stream is submitted back-to-back so
# the queue genuinely backs up — the scenario where per-submission dispatch
# overhead dominates and coalescing pays. The window is far longer than the
# stream so no window closes inside the timed region: the A/B isolates the
# drain/dispatch plane (publish costs are identical constants on both sides
# and window-close behavior is --check-ingest's parity tier, not a timing
# headline).
INGEST_COALESCE_BATCHES = 160
INGEST_COALESCE_BATCH = 32
INGEST_COALESCE_WARMUP = 8
INGEST_COALESCE_MAX = 16
INGEST_COALESCE_WINDOW_S = 600.0


def _bench_ingest_coalesce() -> dict:
    """The ingest fast path's default-line numbers.

    ``ingest_coalesced_steps_per_s``: batches/sec through the coalescing
    drain loop on the bursty stream (rate-gated by --check-trajectory).
    ``ingest_coalesce_factor``: batches applied per worker drain cycle —
    the samples-not-submissions headline (1.0 means coalescing never
    engaged). ``ingest_program_cache_misses``: bucketed routing programs
    compiled over the soak — steady-state misses pin to the distinct
    (bucket, structure) count, so growth means the cache key churns and
    every drain recompiles. Bit-exactness of the coalesced path is
    --check-ingest's pin; this helper only times it."""
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MetricService, Windowed
    from metrics_tpu.observability.counters import COUNTERS

    rng = np.random.RandomState(7)
    total = INGEST_COALESCE_BATCHES + INGEST_COALESCE_WARMUP
    data = []
    for i in range(total):
        preds = jnp.asarray(rng.rand(INGEST_COALESCE_BATCH).astype(np.float32))
        target = jnp.asarray((rng.rand(INGEST_COALESCE_BATCH) > 0.5).astype(np.int32))
        times = i * 0.5 + rng.uniform(0.0, 0.5, INGEST_COALESCE_BATCH)
        data.append((preds, target, times))

    def run(max_batches: int) -> float:
        metric = Windowed(
            Accuracy(), window_s=INGEST_COALESCE_WINDOW_S, num_windows=4,
            allowed_lateness_s=INGEST_COALESCE_WINDOW_S,
        )
        # pre-warm every bucket the drain loop can form (spans are whole
        # batches, so sample counts are batch * 2^k): the compiles land
        # here, in the pre-stream era, and the timed region measures
        # dispatch — the same discipline every other bench scenario keeps
        warm_rng = np.random.RandomState(17)
        n = INGEST_COALESCE_BATCH
        while n <= INGEST_COALESCE_BATCH * max_batches:
            metric.update(
                jnp.asarray(warm_rng.rand(n).astype(np.float32)),
                jnp.asarray((warm_rng.rand(n) > 0.5).astype(np.int32)),
                event_time=warm_rng.uniform(0.0, 0.4, n),
            )
            n *= 2
        with MetricService(
            metric, queue_size=total, coalesce_max_batches=max_batches,
            poll_interval_s=0.002,
        ) as svc:
            for preds, target, times in data[:INGEST_COALESCE_WARMUP]:
                svc.submit(preds, target, event_time=times)  # warm the drain loop
            svc.flush()
            start = time.perf_counter()
            for preds, target, times in data[INGEST_COALESCE_WARMUP:]:
                svc.submit(preds, target, event_time=times)
            svc.flush()
            elapsed = time.perf_counter() - start
            drains, processed = svc.drains, svc.processed
        return INGEST_COALESCE_BATCHES / max(elapsed, 1e-9), drains, processed

    was_enabled = COUNTERS.enabled
    COUNTERS.enabled = True
    hits0 = COUNTERS.ingest_program_cache_hits
    miss0 = COUNTERS.ingest_program_cache_misses
    try:
        coal_sps, coal_drains, coal_processed = run(INGEST_COALESCE_MAX)
        hits = COUNTERS.ingest_program_cache_hits - hits0
        misses = COUNTERS.ingest_program_cache_misses - miss0
        uncoal_sps, _, _ = run(1)
    finally:
        COUNTERS.enabled = was_enabled
    return {
        "coalesced_steps_per_s": coal_sps,
        "uncoalesced_steps_per_s": uncoal_sps,
        "coalesce_factor": coal_processed / max(coal_drains, 1),
        "drains": coal_drains,
        "processed": coal_processed,
        "program_cache_hits": hits,
        "program_cache_misses": misses,
    }


def _bench_retention_read():
    """The tiered-retention read plane's default-line numbers.

    ``retention_query_ms``: one full-range native query (every retained
    bucket finished through ``value_from_partials``) against a store banked
    from a real ``MetricService`` stream — best-of over warmed repeats (the
    read path's cost, which no ingest key measures). The other three ride
    along from the store's gauges and are EXACT pins: the seeded stream
    publishes a deterministic window count, the ladder compacts it with a
    deterministic roll-up count, and resident bytes are bounded by the
    ladder shape (flat by design — growth means retention started leaking).
    """
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MetricService, RetentionStore, Windowed

    metric = Windowed(
        Accuracy(), window_s=RET_WINDOW_S, num_windows=RET_WINDOWS,
        allowed_lateness_s=0.0,
    )
    rng = np.random.RandomState(23)
    with MetricService(metric, name="bench/retention",
                       deferred_publish=False) as svc:
        store = RetentionStore(ladder=RET_LADDER,
                               name="bench/retention-store").attach(svc)
        for i in range(RET_BATCHES):
            svc.submit(
                jnp.asarray(rng.rand(RET_BATCH).astype(np.float32)),
                jnp.asarray(rng.randint(0, 2, RET_BATCH).astype(np.int32)),
                event_time=np.full(RET_BATCH, i * RET_STEP_S),
            )
        svc.finalize()
    span = (0.0, RET_SPAN_S)
    store.query(time_range=span)  # compile the finisher off the clock
    times = []
    for _ in range(RETENTION_READ_REPEATS):
        t0 = time.perf_counter()
        store.query(time_range=span)
        times.append((time.perf_counter() - t0) * 1e3)
    return (min(times), store.windows_banked, store.rollups,
            int(store.resident_bytes()))


def _bench_health_soak():
    """The pipeline health plane's default-line numbers.

    A tiny deterministic service soak with the lifecycle ledger on:
    ``publish_lag_ms`` is the worst end-to-end close -> publish latency any
    published window's stage ledger recorded (monotonic-clock stamps, so no
    wall-clock event times needed), ``selfmeter_p99_ms`` the self-meter
    sketch's certified e2e p99 over the same windows, and
    ``lifecycle_windows_stamped`` the count of published windows carrying a
    COMPLETE core stage ledger — an exact pin equal to the deterministic
    publish count (a drop means a publish path stopped stamping). The deep
    pins (stamp monotonicity, the sketch-vs-exact certificate, wall-clock
    lag recovery under a seeded stall, the fleet fold) live in
    ``--check-health``.
    """
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MetricService, Windowed
    from metrics_tpu.observability import lifecycle as lifecycle_mod
    from metrics_tpu.observability.lifecycle import CORE_STAGES, LEDGER
    from metrics_tpu.observability.selfmeter import SELFMETER

    was_enabled = LEDGER.enabled
    lifecycle_mod.enable()
    rng = np.random.RandomState(29)
    try:
        metric = Windowed(
            Accuracy(), window_s=HEALTH_WINDOW_S, num_windows=4,
            allowed_lateness_s=0.0,
        )
        with MetricService(metric, name="bench/health") as svc:
            for i in range(HEALTH_BATCHES):
                preds = jnp.asarray(rng.rand(HEALTH_BATCH).astype(np.float32))
                target = jnp.asarray((rng.rand(HEALTH_BATCH) > 0.5).astype(np.int32))
                svc.submit(
                    preds, target,
                    event_time=np.full(HEALTH_BATCH, i * HEALTH_STEP_S),
                )
            svc.finalize()
            label = svc.label
            pubs = list(svc.publications)
        ledgers = LEDGER.ledgers(label)
        stamped = sum(
            1 for rec in pubs
            if all(s in ledgers.get(rec["window"], {}) for s in CORE_STAGES)
        )
        lag_ms = max(
            (LEDGER.latencies(label, rec["window"]).get("e2e", 0.0) for rec in pubs),
            default=0.0,
        )
        meter = SELFMETER.meters(label).get("e2e")
        p99_ms = meter.quantile(0.99) if meter is not None else float("nan")
    finally:
        if not was_enabled:
            lifecycle_mod.disable()
    return lag_ms, p99_ms, stamped


def _bench_watermark_scenario():
    """The watermark-agreement numbers of the default line.

    ``wm_agreement_ms``: one agreement round — both virtual ranks report
    (through a real ``Windowed.update``) and one explicit min-exchange rides
    the background host plane to resolution — averaged over the warmed loop.
    ``wm_exchange_calls``: exchanges the loop dispatched (deterministic: one
    explicit round per iteration; the cadence auto-dispatch is disabled so
    the count is pure arithmetic). ``slide_windows_published``: sliding
    windows published over the seeded sliding-service stream (pure routing
    arithmetic — the same stream the ``--check-watermark`` sliding tier pins
    bit-exact). ``wm_stragglers`` rides along from the process counter: both
    ranks stay healthy, so the clean line pins it at zero.
    """
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, WatermarkAgreement, Windowed
    from metrics_tpu.observability import counters as _ctr

    agreement = WatermarkAgreement(
        deadline_s=3600.0, exchange_every_s=3600.0, label="bench/wm"
    )
    ranks = [
        Windowed(
            Accuracy(), window_s=WM_WINDOW_S, num_windows=WM_WINDOWS,
            allowed_lateness_s=WM_LATENESS_S, agreement=agreement, rank=i,
        )
        for i in range(2)
    ]
    preds = jnp.asarray(np.array([0.9, 0.1], np.float32))
    target = jnp.asarray(np.array([1, 0], np.int32))

    def round_(r: int) -> None:
        for i, metric in enumerate(ranks):
            metric.update(preds, target, event_time=[r * 5.0 + i])
        handle = agreement.exchange()
        if handle is not None:
            handle.result(10.0)

    warm, rounds = 3, 20
    for r in range(warm):
        round_(r)
    was_enabled = _ctr.is_enabled()
    _ctr.enable()
    before = _ctr.COUNTERS.wm_exchange_calls
    try:
        start = time.perf_counter()
        for r in range(warm, warm + rounds):
            round_(r)
        wm_ms = (time.perf_counter() - start) / rounds * 1e3
        exchange_calls = _ctr.COUNTERS.wm_exchange_calls - before
    finally:
        if not was_enabled:
            _ctr.disable()

    slide_pubs, _merged, slide_service = _drive_slide(_slide_stream())
    del slide_service
    return wm_ms, exchange_calls, len(slide_pubs), _ctr.COUNTERS.wm_stragglers


def _sync8_ab(steps: int = N_STEPS, warmup: int = WARMUP, repeats: int = 3, trace_path=None) -> dict:
    """Compute-groups on/off A/B over the same 8-device mesh program.

    The two variants are timed in INTERLEAVED rounds and reported as the
    best-of — a monotonic load drift would otherwise bias whichever variant
    ran second (the A/B is a difference of two absolute measurements).

    With ``trace_path`` set, the observability subsystem is enabled around
    the whole A/B: the per-variant collective counters are snapshotted over
    the compiling first call (staged collectives per step program — the
    honest per-step collective cost), the bench phases are spanned, and a
    Perfetto-loadable Chrome trace is written to ``trace_path``. The result
    then carries ``collective_calls`` / ``sync_bytes`` (grouped program) and
    a ``phase_ms`` table from the span aggregates.
    """
    _serialize_cpu_dispatch()
    from metrics_tpu.observability import counters as _ctr

    obs = None
    if trace_path is not None:
        from metrics_tpu import observability as obs_mod

        obs = obs_mod
        # compile_events: spans carry compiled=yes/no + compile_ms, and the
        # JSON line gets the process compile telemetry snapshot
        obs.enable(compile_events=True)
        obs.reset()

    def build(builder, variant, label):
        """Build + compile one A/B variant; ALWAYS snapshot the staged
        collective counters over the compiling first call (cheap: counting
        happens at trace time), so the default JSON line carries the
        trace-schema keys and --check-trajectory binds on every new
        BENCH_r* round. Spans only when tracing."""
        with (obs.span(f"bench.build_{label}") if obs else _null_cm()):
            run, states = builder(variant)
        _ctr.COUNTERS.reset()
        was_enabled = _ctr.is_enabled()
        _ctr.enable()
        try:
            with (obs.span(f"bench.compile_{label}") if obs else _null_cm()):
                run(1)  # first call traces+compiles: counters now hold the program's collectives
            counters = _ctr.snapshot()
        finally:
            if not was_enabled:
                _ctr.disable()
        with (obs.span(f"bench.warmup_{label}") if obs else _null_cm()):
            run(max(warmup - 1, 1))
        return run, states, counters

    run_grouped, states_grouped, grouped_counters = build(_build_sync8_runner, True, "grouped")
    run_ungrouped, states_ungrouped, ungrouped_counters = build(_build_sync8_runner, False, "ungrouped")
    grouped_times, ungrouped_times = [], []
    for _ in range(repeats):
        with (obs.span("bench.timed_grouped") if obs else _null_cm()):
            grouped_times.append(run_grouped(steps))
        with (obs.span("bench.timed_ungrouped") if obs else _null_cm()):
            ungrouped_times.append(run_ungrouped(steps))
    grouped_ms = min(grouped_times)
    ungrouped_ms = min(ungrouped_times)

    # gather-plane A/B: same interleaved best-of protocol over the
    # buffer-state collection (coalesced bucketed all_gather vs per-leaf)
    run_coal, states_gather, coal_counters = build(_build_gather_runner, True, "gather_coalesced")
    run_leaf, _, leaf_counters = build(_build_gather_runner, False, "gather_per_leaf")
    coal_times, leaf_times = [], []
    for _ in range(repeats):
        with (obs.span("bench.timed_gather_coalesced") if obs else _null_cm()):
            coal_times.append(run_coal(steps))
        with (obs.span("bench.timed_gather_per_leaf") if obs else _null_cm()):
            leaf_times.append(run_leaf(steps))

    # hierarchical A/B: the same gather collection on the (4,2) ici x dcn
    # mesh — two-stage hierarchical plane vs the flat world-axis plane
    run_hier, _, hier_counters = build(_build_hier_gather_runner, True, "gather_hier")
    run_flat2d, _, flat2d_counters = build(_build_hier_gather_runner, False, "gather_flat2d")
    hier_times, flat2d_times = [], []
    for _ in range(repeats):
        with (obs.span("bench.timed_gather_hier") if obs else _null_cm()):
            hier_times.append(run_hier(steps))
        with (obs.span("bench.timed_gather_flat2d") if obs else _null_cm()):
            flat2d_times.append(run_flat2d(steps))

    # sketch A/B: the sketch-mode twin of the gather collection on the SAME
    # (4,2) mesh — constant-memory histogram states, psum-only sync; the
    # headline is sketch_sync_ms vs gather_hier_ms and the ~16x payload drop
    run_sketch, states_sketch, sketch_counters = build(
        _build_sketch_sync_runner, True, "sketch_sync"
    )
    sketch_times = []
    for _ in range(repeats):
        with (obs.span("bench.timed_sketch_sync") if obs else _null_cm()):
            sketch_times.append(run_sketch(steps))

    # keyed A/B: Keyed(AUROC sketch) x 10,000 segments vs the unkeyed metric
    # on the same (4,2) mesh — the headline is that the STAGED COLLECTIVE
    # COUNT does not move with K (the unkeyed twin is traced for its
    # counters only; timing one side is enough for the ms trajectory)
    run_keyed, states_keyed, keyed_counters = build(
        _build_keyed_sync_runner, KEYED_SLOTS, "keyed_sync"
    )
    _, _, keyed_unkeyed_counters = build(_build_keyed_sync_runner, None, "keyed_unkeyed")
    keyed_times = []
    for _ in range(repeats):
        with (obs.span("bench.timed_keyed_sync") if obs else _null_cm()):
            keyed_times.append(run_keyed(steps))

    # sparse delta-sync A/B: the same Keyed slab, but each step touches only
    # SPARSE_TOUCH of the K=10,000 rows and syncs through SparseSyncPlane
    # (bitmap psum + fixed-capacity union gather) — the headline is staged
    # sync bytes proportional to the touched rows (< dense keyed/10), with
    # sparse_fallbacks riding the default line pinned at ZERO
    run_sparse, states_sparse, sparse_counters = build(
        lambda _v: _build_sparse_sync_runner(), None, "sparse_sync"
    )
    sparse_times = []
    for _ in range(repeats):
        with (obs.span("bench.timed_sparse_sync") if obs else _null_cm()):
            sparse_times.append(run_sparse(steps))

    # heavy-hitter A/B: HeavyHitters(AUROC sketch) over a 1M-key space vs
    # the same unkeyed twin — the open-world extension of the keyed gate:
    # the staged count must not move with the SIMULATED key count, and the
    # ingest loop must stay flat as the key space grows 10k -> 1M
    run_hh, states_hh, hh_counters = build(lambda _v: _build_hh_sync_runner(), None, "hh_sync")
    hh_times = []
    for _ in range(repeats):
        with (obs.span("bench.timed_hh_sync") if obs else _null_cm()):
            hh_times.append(run_hh(steps))
    with (obs.span("bench.hh_ingest") if obs else _null_cm()):
        hh_sps_small, _ = _bench_hh_ingest(HH_KEY_SPACE_SMALL)
        hh_sps_big, hh_big = _bench_hh_ingest(HH_KEY_SPACE)

    # quantile-sketch A/B: Keyed(Quantile(q=0.99)) x 256 tenants vs the
    # unkeyed scalar Quantile on the same (4,2) mesh — the per-tenant p99
    # plane; the headline is the keyed/unkeyed staged-count parity and the
    # deterministic, traffic-independent state-byte pin
    run_qsk, states_qsk, qsk_counters = build(
        _build_qsketch_sync_runner, QSK_SLOTS, "qsketch_sync"
    )
    _, _, qsk_unkeyed_counters = build(_build_qsketch_sync_runner, None, "qsketch_unkeyed")
    qsk_times = []
    for _ in range(repeats):
        with (obs.span("bench.timed_qsketch_sync") if obs else _null_cm()):
            qsk_times.append(run_qsk(steps))
    with (obs.span("bench.qsketch_state_bytes") if obs else _null_cm()):
        qsk_state_bytes = _qsketch_state_bytes()

    # megafusion A/B: (a) the whole-collection FUSED FORWARD — one jitted
    # program per host-API step, state slabs donated (fused_step_ms); (b)
    # the MIXED collection sync — every mergeable state kind in one
    # collection on the (4,2) mesh, synced through the packed
    # one-psum-per-crossing reduce plane; the headline is the staged
    # collective count pinned EQUAL at 6 and 14 members (the 14-member
    # twin is traced for its counters only)
    with (obs.span("bench.fused_forward") if obs else _null_cm()):
        fused_step_ms = _bench_fused_forward(steps=steps, warmup=warmup)
    run_mixed, states_mixed, mixed_counters = build(
        _build_mixed_sync_runner, MIXED_MEMBERS, "mixed6_sync"
    )
    _, _, mixed_wide_counters = build(
        _build_mixed_sync_runner, MIXED_MEMBERS_WIDE, "mixed14_sync"
    )
    mixed_times = []
    for _ in range(repeats):
        with (obs.span("bench.timed_mixed_sync") if obs else _null_cm()):
            mixed_times.append(run_mixed(steps))

    # windowed serving A/B: Windowed(AUROC sketch) x 4 window slots vs the
    # unwindowed metric on the same (4,2) mesh — like the keyed gate, the
    # headline is that the STAGED COLLECTIVE COUNT does not move with the
    # window count (the unwindowed twin is traced for its counters only)
    run_service, states_service, service_counters = build(
        _build_windowed_sync_runner, True, "service_windowed"
    )
    _, _, service_unwindowed_counters = build(
        _build_windowed_sync_runner, False, "service_unwindowed"
    )
    service_times = []
    for _ in range(repeats):
        with (obs.span("bench.timed_service_windowed") if obs else _null_cm()):
            service_times.append(run_service(steps))

    # deferred-sync A/B: the same sync8 staged program dispatched FENCED each
    # step (the synchronous plane's critical path) vs deferred one step
    # (sync_lag=1 read through parallel.deferred) — identical collectives,
    # only the fence moves; the ms gap is the overlap the deferred plane buys
    run_async, states_async, async_counters = build(
        _build_async_sync8_runner, True, "async_sync8"
    )
    run_fenced, _, async_fenced_counters = build(
        _build_async_sync8_runner, False, "fenced_sync8"
    )
    async_times, fenced_times = [], []
    for _ in range(repeats):
        with (obs.span("bench.timed_async_sync8") if obs else _null_cm()):
            async_times.append(run_async(steps))
        with (obs.span("bench.timed_fenced_sync8") if obs else _null_cm()):
            fenced_times.append(run_fenced(steps))

    # lag-k ring on the device plane: depths 2 and 3 replay the SAME compiled
    # sync program as the depth-1 async plane (staged counts pinned equal)
    # with deeper in-flight handle rings; the ms keys ride the default line
    # so the trajectory gate catches a ring regression at any depth
    run_lag2, _, _ = build(
        lambda v: _build_async_sync8_runner(v, depth=2), True, "async_lag2_sync8"
    )
    # the deferred program cache would replay the depth-1 build's compiled
    # program here and stage NOTHING — clear it so the depth-3 capture
    # re-counts the full program (the pin: identical to the depth-1 plane)
    from metrics_tpu.parallel.deferred import clear_program_cache

    clear_program_cache()
    run_lag3, _, lag3_counters = build(
        lambda v: _build_async_sync8_runner(v, depth=3), True, "async_lag3_sync8"
    )
    lag2_times, lag3_times = [], []
    for _ in range(repeats):
        with (obs.span("bench.timed_async_lag_sync8") if obs else _null_cm()):
            lag2_times.append(run_lag2(steps))
            lag3_times.append(run_lag3(steps))

    # deferred epoch gather parity counts (bit-exactness is --check-async's
    # pin; the default line carries the per-group gather-call counts so the
    # trajectory gate catches a deferred epoch plane that grew collectives)
    with (obs.span("bench.epoch_gather_parity") if obs else _null_cm()):
        _, _, epoch_calls_def, epoch_calls_sync = _bench_epoch_gather_parity()

    # the traffic-generator scenario: sustained batches/sec through a real
    # MetricService ingest loop (deferred window publishes included)
    with (obs.span("bench.service_ingest") if obs else _null_cm()):
        ingest_steps_per_s = _bench_service_ingest()

    # the ingest fast path A/B: the identical bursty stream through the
    # coalescing drain loop vs the one-batch twin — throughput, the
    # batches-per-drain factor, and the bucketed routing-program compile
    # count (bit-exactness is --check-ingest's pin)
    with (obs.span("bench.ingest_coalesce") if obs else _null_cm()):
        ingest_coalesce = _bench_ingest_coalesce()

    # the tiered-retention read plane: a full-range query against the banked
    # ladder (ms) plus the store's deterministic roll-up/residency pins
    with (obs.span("bench.retention_read") if obs else _null_cm()):
        (retention_query_ms, retention_banked, retention_rollups,
         retention_resident) = _bench_retention_read()

    # the sharded fleet: ingest throughput at 1 vs 8 shards under the
    # simulated per-batch serving work (the scaling headline --check-fleet
    # gates at >= 4x), plus the merge tier's deterministic window counts
    # over the exact stream (lost windows pinned at zero)
    with (obs.span("bench.fleet_ingest") if obs else _null_cm()):
        fleet_sps_1 = _bench_fleet_ingest(1)
        fleet_sps_8 = _bench_fleet_ingest(FLEET_SHARDS)
    with (obs.span("bench.fleet_merge") if obs else _null_cm()):
        fleet_batches = _fleet_stream(FLEET_EXACT_BATCHES, FLEET_EXACT_BATCH)
        fleet_run = _drive_fleet(fleet_batches, FLEET_SHARDS)
        fleet_oracle = _fleet_oracle(fleet_batches)
        fleet_merged = len({r["window"] for r in fleet_run["records"]})
        fleet_lost = len(fleet_oracle["published"]) - fleet_merged

    # the pipeline health plane: a tiny seeded service soak with the
    # lifecycle ledger on — worst close -> publish e2e, the self-metered
    # e2e p99, and the complete-ledger window count
    with (obs.span("bench.health_soak") if obs else _null_cm()):
        publish_lag_ms, selfmeter_p99_ms, lifecycle_stamped = _bench_health_soak()

    # the watermark-agreement plane: one report + min-exchange round through
    # the background host plane (wm_agreement_ms / wm_exchange_calls), the
    # seeded sliding-service publish count, and the straggler counter pinned
    # zero on the clean line
    with (obs.span("bench.watermark") if obs else _null_cm()):
        wm_ms, wm_exchange_calls, slide_published, wm_stragglers = (
            _bench_watermark_scenario()
        )

    out = {
        "grouped_sync8_ms": grouped_ms,
        "ungrouped_sync8_ms": ungrouped_ms,
        "states_synced": states_grouped,
        "states_synced_ungrouped": states_ungrouped,
        "gather_coalesced_ms": min(coal_times),
        "gather_per_leaf_ms": min(leaf_times),
        "gather_states_synced": states_gather,
        "gather_hier_ms": min(hier_times),
        "gather_flat2d_ms": min(flat2d_times),
        # staged-collective keys ride the DEFAULT line (trace-schema keys:
        # --check-trajectory binds on every new BENCH_r* round)
        "collective_calls": grouped_counters["collective_calls"],
        "sync_bytes": grouped_counters["sync_bytes"],
        "collective_calls_ungrouped": ungrouped_counters["collective_calls"],
        "sync_bytes_ungrouped": ungrouped_counters["sync_bytes"],
        "gather_collective_calls": coal_counters["collective_calls"],
        "gather_sync_bytes": coal_counters["sync_bytes"],
        "gather_collective_calls_per_leaf": leaf_counters["collective_calls"],
        "gather_sync_bytes_per_leaf": leaf_counters["sync_bytes"],
        # the hierarchical plane's per-crossing structure: DCN traffic is
        # the headline (strictly below the flat plane's world traffic)
        "hier_collective_calls": hier_counters["collective_calls"],
        "hier_sync_bytes": hier_counters["sync_bytes"],
        "hier_dcn_calls": hier_counters["calls_by_crossing"].get("dcn", 0),
        "hier_dcn_bytes": hier_counters["bytes_by_crossing"].get("dcn", 0),
        "hier_ici_bytes": hier_counters["bytes_by_crossing"].get("ici", 0),
        "flat2d_collective_calls": flat2d_counters["collective_calls"],
        "flat2d_world_bytes": flat2d_counters["bytes_by_crossing"].get("world", 0),
        # the sketch plane: psum-only (zero staged gathers), traffic-
        # independent payload — the memory/bandwidth headline of record
        "sketch_sync_ms": min(sketch_times),
        "sketch_states_synced": states_sketch,
        "sketch_collective_calls": sketch_counters["collective_calls"],
        "sketch_sync_bytes": sketch_counters["sync_bytes"],
        "sketch_dcn_bytes": sketch_counters["bytes_by_crossing"].get("dcn", 0),
        "sketch_gather_calls": sum(
            sketch_counters["calls_by_kind"].get(k, 0)
            for k in ("all_gather", "coalesced_gather", "process_allgather")
        ),
        # the keyed slab plane: K=10,000 segments sync with the SAME staged
        # program shape as the unkeyed metric (psum-only, count pinned equal)
        "keyed_sync_ms": min(keyed_times),
        "keyed_states_synced": states_keyed,
        "keyed_collective_calls": keyed_counters["collective_calls"],
        "keyed_sync_bytes": keyed_counters["sync_bytes"],
        "keyed_gather_calls": sum(
            keyed_counters["calls_by_kind"].get(k, 0)
            for k in ("all_gather", "coalesced_gather", "process_allgather")
        ),
        "keyed_unkeyed_collective_calls": keyed_unkeyed_counters["collective_calls"],
        # the sparse delta-sync plane: staged bytes follow the touched-row
        # count, not the table size (the --check-collectives sparse gate
        # pins them under a tenth of the dense keyed plane's, with
        # K-independent staged counts and bit-exact merges); the fallback
        # counter rides the default line pinned at ZERO — a clean run that
        # overflows sparse_capacity into the dense plane is a regression
        "sparse_sync_ms": min(sparse_times),
        "sparse_states_synced": states_sparse,
        "sparse_collective_calls": sparse_counters["collective_calls"],
        "sparse_sync_bytes": sparse_counters["sync_bytes"],
        "sparse_gather_calls": sum(
            sparse_counters["calls_by_kind"].get(k, 0)
            for k in ("all_gather", "coalesced_gather", "process_allgather")
        ),
        "sparse_fallbacks": sparse_counters.get("sparse", {}).get("fallbacks", 0),
        # the heavy-hitter plane: open-world keys over the same staged
        # program shape as the unkeyed metric (psum-only, count pinned
        # equal, state bytes constant in the live-key count), with the
        # ingest pair pinning steps/s FLAT as the key space grows 100x and
        # the tail's (e/width)*N certificate on the default line
        "hh_sync_ms": min(hh_times),
        "hh_states_synced": states_hh,
        "hh_collective_calls": hh_counters["collective_calls"],
        "hh_sync_bytes": hh_counters["sync_bytes"],
        "hh_gather_calls": sum(
            hh_counters["calls_by_kind"].get(k, 0)
            for k in ("all_gather", "coalesced_gather", "process_allgather")
        ),
        "hh_unkeyed_collective_calls": keyed_unkeyed_counters["collective_calls"],
        "hh_ingest_steps_per_s": round(hh_sps_big, 3),
        "hh_ingest_steps_per_s_10k": round(hh_sps_small, 3),
        "hh_tail_overcount_bound": round(hh_big.tail_overcount_bound(), 4),
        # the quantile-sketch plane: per-tenant p99 slots are a state axis —
        # the staged collective count equals the unkeyed scalar Quantile's
        # (psum-only, zero gathers) and state bytes are deterministic and
        # traffic-independent ((K*B + K) int32 cells, pinned exactly)
        "qsketch_sync_ms": min(qsk_times),
        "qsketch_states_synced": states_qsk,
        "qsketch_collective_calls": qsk_counters["collective_calls"],
        "qsketch_sync_bytes": qsk_counters["sync_bytes"],
        "qsketch_gather_calls": sum(
            qsk_counters["calls_by_kind"].get(k, 0)
            for k in ("all_gather", "coalesced_gather", "process_allgather")
        ),
        "qsketch_unkeyed_collective_calls": qsk_unkeyed_counters["collective_calls"],
        "qsketch_state_bytes": qsk_state_bytes,
        # the megafusion plane: ONE staged program per host-API collection
        # step (fused_step_ms — canonicalization shared across groups,
        # state slabs donated) and the mixed-collection packed sync whose
        # staged count must not move with membership (one packed psum per
        # crossing + the pmin/pmax riders; 14 members, same program)
        "fused_step_ms": fused_step_ms,
        "mixed_sync_ms": min(mixed_times),
        "mixed_states_synced": states_mixed,
        "fused_collective_calls": mixed_counters["collective_calls"],
        "fused_sync_bytes": mixed_counters["sync_bytes"],
        "fused_collective_calls_14": mixed_wide_counters["collective_calls"],
        # the windowed serving plane: window slots are a leading state axis,
        # so the staged program matches the unwindowed metric's (psum-only)
        "service_sync_ms": min(service_times),
        "service_states_synced": states_service,
        "service_collective_calls": service_counters["collective_calls"],
        "service_sync_bytes": service_counters["sync_bytes"],
        "service_gather_calls": sum(
            service_counters["calls_by_kind"].get(k, 0)
            for k in ("all_gather", "coalesced_gather", "process_allgather")
        ),
        "service_unwindowed_collective_calls": service_unwindowed_counters["collective_calls"],
        # the deferred sync plane: identical staged program as the fenced
        # synchronous twin (count pinned equal, psum-only), with the ms gap
        # showing the overlap; --check-trajectory binds on all of these
        "async_sync8_ms": min(async_times),
        "fenced_sync8_ms": min(fenced_times),
        "async_states_synced": states_async,
        "async_collective_calls": async_counters["collective_calls"],
        "async_sync_bytes": async_counters["sync_bytes"],
        "async_gather_calls": sum(
            async_counters["calls_by_kind"].get(k, 0)
            for k in ("all_gather", "coalesced_gather", "process_allgather")
        ),
        "async_fenced_collective_calls": async_fenced_counters["collective_calls"],
        # the lag-k ring: deeper rings replay the identical staged program
        # (counts pinned equal to the depth-1 plane) and their step ms rides
        # the line; the epoch keys pin the deferred grouped host sync to the
        # synchronous plane's per-group gather-call count
        "async_lag2_ms": min(lag2_times),
        "async_lag3_ms": min(lag3_times),
        "async_lag_collective_calls": lag3_counters["collective_calls"],
        "async_lag_sync_bytes": lag3_counters["sync_bytes"],
        "async_lag_epoch_gather_calls": epoch_calls_def,
        "async_lag_epoch_sync_gather_calls": epoch_calls_sync,
        # serving ingest throughput (batches/sec through a real service loop)
        "service_ingest_steps_per_s": round(ingest_steps_per_s, 3),
        # the ingest fast path: coalesced drain throughput on the bursty
        # stream (rate-gated), the batches-per-drain factor (1.0 means the
        # drain loop stopped coalescing), and the bucketed routing-program
        # compile count — an exact pin on the seeded soak (growth means the
        # program-cache key churns and steady state recompiles)
        "ingest_coalesced_steps_per_s": round(ingest_coalesce["coalesced_steps_per_s"], 3),
        "ingest_coalesce_factor": round(ingest_coalesce["coalesce_factor"], 3),
        "ingest_program_cache_misses": ingest_coalesce["program_cache_misses"],
        # the tiered-retention read plane: the query path's full-range
        # native read against the banked ladder rides the line in ms, and
        # the store's gauge counts are EXACT pins on the seeded stream —
        # banked windows and roll-ups are routing arithmetic, resident
        # bytes are bounded by the ladder shape (growth means a leak)
        "retention_query_ms": round(retention_query_ms, 4),
        "retention_windows_banked": retention_banked,
        "retention_rollups": retention_rollups,
        "retention_resident_bytes": retention_resident,
        # the sharded fleet's scaling pair + merge-tier counts: throughput
        # keys are rate-gated by --check-trajectory (may not collapse),
        # window counts are exact pins, lost windows bind at ZERO
        "fleet_ingest_steps_per_s": round(fleet_sps_8, 3),
        "fleet_ingest_steps_per_s_1shard": round(fleet_sps_1, 3),
        "fleet_scaling_x": round(fleet_sps_8 / max(fleet_sps_1, 1e-9), 3),
        "fleet_shards_merged_windows": fleet_merged,
        "fleet_shards_published_windows": fleet_run["published"],
        "fleet_lost_windows": fleet_lost,
        # the watermark-agreement plane: one agreement round's wall cost, the
        # deterministic exchange count, the sliding-service publish count,
        # and the straggler counter (zero on a healthy clean line)
        "wm_agreement_ms": round(wm_ms, 4),
        "wm_exchange_calls": wm_exchange_calls,
        "wm_stragglers": wm_stragglers,
        "slide_windows_published": slide_published,
        # the pipeline health plane: the latency keys are ms-gated (worst
        # close -> publish e2e + the self-meter sketch's certified e2e p99
        # over the seeded soak); the stamped-window count is an EXACT pin —
        # every deterministically-published window must carry a complete
        # core stage ledger, a drop means a publish path stopped stamping
        "publish_lag_ms": round(publish_lag_ms, 4),
        "selfmeter_p99_ms": round(selfmeter_p99_ms, 4),
        "lifecycle_windows_stamped": lifecycle_stamped,
        # slab drop evidence rides the default line pinned at ZERO (in-window
        # traffic never drops; the --check-service chaos soak pins nonzero)
        "slab_dropped_samples": service_counters.get("slab_dropped_samples", 0),
    }
    # fault counters ride the default line, pinned at ZERO: a clean bench run
    # that retries, degrades, or quarantines anything is a regression
    # (--check-trajectory binds these on every new BENCH_r* round)
    out.update({k: v for k, v in grouped_counters.get("faults", {}).items()})
    if obs is not None:
        # the device-time scenario: drive the stateful per-metric API with
        # per-phase fencing on, so the trace carries per-metric
        # update/sync/compute device_ms rows (the A/B's instrumented sites
        # only run at trace time inside the jitted step — nothing concrete
        # to fence there)
        from metrics_tpu.observability import devtime as devtime_mod

        with obs.span("bench.devtime"):
            devtime_mod.enable()
            try:
                _devtime_scenario()
            finally:
                devtime_mod.disable()

        # v17: the ingest fast path joined (ingest_coalesced_steps_per_s /
        # ingest_coalesce_factor — the queue-drain coalescing A/B on the
        # bursty producer stream — plus the bucketed routing-program
        # compile pin ingest_program_cache_misses and the ingest_counters
        # block, gated by --check-ingest's parity/throughput/chaos tiers);
        # v16: the pipeline health plane joined (publish_lag_ms /
        # selfmeter_p99_ms — the lifecycle ledger's worst close -> publish
        # e2e and the self-meter sketch's certified p99 over the seeded
        # soak — plus the exact lifecycle_windows_stamped pin, gated by
        # --check-health's ledger/certificate/lag-recovery/fleet-fold tiers);
        # v15: the megafusion plane joined (fused_step_ms — the whole-
        # collection single-program forward with donated state slabs —
        # plus the mixed-collection packed-psum sync keys
        # fused_collective_calls / fused_sync_bytes with the 14-member
        # count pinned equal, gated by --check-collectives' megafusion
        # gate's bit-exact packed-vs-per-leaf parity);
        # v14: the tiered retention plane joined (retention_query_ms — the
        # banked ladder's full-range read — plus the deterministic
        # windows-banked / roll-up / resident-bytes pins on the default
        # line, gated by --check-retention's four-kind bit-exact sweep);
        # v13: the sparse delta-sync plane joined (sparse_* staged keys with
        # sync bytes pinned under a tenth of the dense keyed plane's and
        # collective counts constant in K, sparse_fallbacks zero-pinned on
        # the default line, gated by --check-collectives' sparse gate);
        # v12: the quantile-sketch plane joined (qsketch_* staged-count keys
        # pinned to the unkeyed scalar twin, the deterministic
        # qsketch_state_bytes pin, and qsketch_sync_ms on the default line,
        # gated by --check-quantile);
        # v11: the rank-coherent streaming plane joined (wm_agreement_ms /
        # wm_exchange_calls / wm_stragglers — zero-pinned on the clean
        # trajectory — and the sliding-window publish count on the default
        # line, gated by --check-watermark);
        # v10: the heavy-hitter open-world plane joined (hh_* staged-count
        # keys pinned to the unkeyed twin, the 10k/1M ingest flatness pair,
        # and the tail's (e/width)*N certificate on the default line);
        # v9: the sharded fleet joined (fleet_ingest_steps_per_s at 1/8
        # shards + fleet_scaling_x + the merge tier's window counts with
        # fleet_lost_windows pinned at zero on the default line); v8 added
        # the lag-k pipelined plane (async_lag2/3_ms ring-depth keys,
        # async_lag_* staged-count pins, and the deferred-epoch-gather
        # call-count pair on the default line); v7 added the deferred-sync
        # A/B (async_* staged-count keys + fenced twin +
        # service_ingest_steps_per_s on the default line, full async
        # counters here — incl. the deferred dispatch/fence/completion
        # block); v6 added the windowed serving A/B; v5 the keyed slab A/B;
        # v4 the sketch A/B; v3 moved the collective counts to the default
        # line and added the hierarchical A/B
        out["trace_schema"] = 17
        out["counters"] = grouped_counters
        out["gather_counters"] = coal_counters
        out["hier_counters"] = hier_counters
        out["sketch_counters"] = sketch_counters
        out["keyed_counters"] = keyed_counters
        out["sparse_counters"] = sparse_counters
        out["hh_counters"] = hh_counters
        out["qsketch_counters"] = qsk_counters
        out["mixed_counters"] = mixed_counters
        out["service_counters"] = service_counters
        out["async_counters"] = async_counters
        out["ingest_counters"] = ingest_coalesce
        summary = obs.summarize()
        out["phase_ms"] = {
            name: round(row["total_ms"], 3) for name, row in sorted(summary.items())
        }
        out["phase_compile_ms"] = {
            name: round(row["compile_ms"], 3)
            for name, row in sorted(summary.items())
            if row["compile_ms"] > 0
        }
        out["device_ms"] = {
            metric: {phase: round(ms, 3) for phase, ms in sorted(row.items())}
            for metric, row in sorted(obs.device_time_table().items())
        }
        out["compile"] = obs.compile_snapshot()
        out["trace_file"] = trace_path
        # otherData pins the headline (grouped sum-plane) program's counters,
        # not whichever variant's compile reset the live counters last
        obs.write_chrome_trace(trace_path, counters=grouped_counters)
        obs.disable()
    return out


def _devtime_scenario(steps: int = 3, rows: int = 256) -> None:
    """Per-metric device-time attribution rows for ``--trace``.

    Drives the eager stateful API (independent members, value-based host
    gather) for a few steps with devtime fencing on: every ``metric.update``
    / ``metric.sync_state`` / ``metric.compute`` span gets a ``device_ms``
    attr, which ``observability.device_time_table()`` folds into the
    per-metric update/sync/compute table the JSON line reports.
    """
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, F1, MetricCollection, Precision, Recall
    from metrics_tpu.parallel.sync import gather_all_arrays

    collection = MetricCollection([
        Accuracy(dist_sync_fn=gather_all_arrays),
        F1(num_classes=NUM_CLASSES, average="macro", dist_sync_fn=gather_all_arrays),
        Precision(num_classes=NUM_CLASSES, average="macro", dist_sync_fn=gather_all_arrays),
        Recall(num_classes=NUM_CLASSES, average="macro", dist_sync_fn=gather_all_arrays),
    ], compute_groups=False)

    rng = np.random.RandomState(1)
    logits = rng.rand(rows, NUM_CLASSES).astype(np.float32)
    preds = jnp.asarray(logits / logits.sum(-1, keepdims=True))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, rows).astype(np.int32))
    for _ in range(steps):
        for _name, metric in collection.items():
            metric.update(preds, target)
    collection.compute()


def _null_cm():
    import contextlib

    return contextlib.nullcontext()


def _flag_value(argv, flag: str) -> "str | None":
    """Value following ``flag`` anywhere on the command line, else None."""
    if flag in argv:
        i = argv.index(flag)
        if i + 1 >= len(argv):
            raise SystemExit(f"{flag} requires a value")
        return argv[i + 1]
    return None


def _trace_arg(argv) -> "str | None":
    """Value of ``--trace OUT.json`` anywhere on the command line, else None."""
    return _flag_value(argv, "--trace")


def check_trajectory_cli(argv) -> int:
    """``--check-trajectory``: diff current bench numbers against the prior
    ``BENCH_r*.json`` rounds and exit non-zero on drift beyond the pinned
    tolerances (``metrics_tpu.observability.regress``).

    Current numbers come from a 2-step smoke A/B with tracing (so the
    staged-collective counters ride along), or from ``--trajectory-current
    FILE`` — the injection hook the tier-1 pass/fail pair uses, which also
    keeps the differ testable without re-measuring. ``--rounds-dir DIR``
    overrides where the rounds live (default: the bench's own directory).
    Prints one JSON report line either way.
    """
    import tempfile

    from metrics_tpu.observability import regress

    rounds_dir = _flag_value(argv, "--rounds-dir") or _HERE
    current_file = _flag_value(argv, "--trajectory-current")
    if current_file is not None:
        with open(current_file) as f:
            current = json.load(f)
    else:
        fd, tmp = tempfile.mkstemp(suffix="_trajectory_trace.json")
        os.close(fd)
        try:
            current = _sync8_ab(steps=2, warmup=1, trace_path=tmp)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
    rounds = regress.load_rounds(rounds_dir)
    report = regress.check_trajectory(current, rounds)
    print(json.dumps({"check": "trajectory", **report}))
    return 0 if report["ok"] else 1


def _ref_sync8_worker(rank: int, world_size: int, steps: int, out_q) -> None:
    import torch
    import torch.distributed as dist

    sys.path.insert(0, "/root/reference")
    from torchmetrics import Accuracy, F1, MetricCollection, Precision, Recall

    dist.init_process_group(
        "gloo", init_method="tcp://127.0.0.1:29511", rank=rank, world_size=world_size
    )
    collection = MetricCollection([
        Accuracy(dist_sync_on_step=True),
        F1(num_classes=NUM_CLASSES, average="macro", dist_sync_on_step=True),
        Precision(num_classes=NUM_CLASSES, average="macro", dist_sync_on_step=True),
        Recall(num_classes=NUM_CLASSES, average="macro", dist_sync_on_step=True),
    ])

    rng = np.random.RandomState(rank)
    logits = rng.rand(BATCH_PER_DEVICE, NUM_CLASSES).astype(np.float32)
    preds = torch.from_numpy(logits / logits.sum(-1, keepdims=True))
    target = torch.from_numpy(rng.randint(0, NUM_CLASSES, BATCH_PER_DEVICE).astype(np.int64))

    for _ in range(WARMUP):
        collection(preds, target)
    dist.barrier()
    start = time.perf_counter()
    for _ in range(steps):
        collection(preds, target)
    dist.barrier()
    elapsed_ms = (time.perf_counter() - start) / steps * 1e3
    if rank == 0:
        out_q.put(elapsed_ms)
    dist.destroy_process_group()


def bench_reference_sync8() -> float:
    """Reference collection forward with dist_sync_on_step=True on an
    8-process Gloo group (the reference's own distributed mechanism)."""
    import torch.multiprocessing as mp

    ctx = mp.get_context("spawn")
    out_q = ctx.Queue()
    procs = [
        ctx.Process(target=_ref_sync8_worker, args=(r, N_DEVICES, N_STEPS // 2, out_q))
        for r in range(N_DEVICES)
    ]
    for p in procs:
        p.start()
    try:
        # a dead/hung worker (port clash, init failure) must not hang the bench
        result = out_q.get(timeout=240)
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
    return result


def bench_ours_fused_singlechip() -> float:
    """Marginal cost of folding the fused collection update into a jitted
    train step on the default backend (TPU when available).

    Timing protocol (tunnel-proof): through the axon TPU tunnel,
    ``jax.block_until_ready`` does NOT wait for device execution (it returns
    in ~0.1 ms for work that takes hundreds of ms; only a value readback
    forces and awaits execution — see benchmarks/roofline.py). So each
    variant runs K chained train steps inside ONE jitted ``lax.fori_loop``
    (step i+1 consumes step i's weights/metric state — nothing can be
    hoisted or elided), is timed via a forcing scalar readback at two
    different K, and per-step = (T(K2) - T(K1)) / (K2 - K1): the ~99 ms
    readback floor cancels exactly. Correct on every backend.
    """
    import functools

    import jax
    from jax import lax
    import jax.numpy as jnp

    pure = _collection_ours().pure()
    batch = BATCH_PER_DEVICE * N_DEVICES

    rng = np.random.RandomState(0)
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, batch).astype(np.int32))
    x = jnp.asarray(rng.rand(batch, FEATURES).astype(np.float32))
    w0 = jnp.asarray(rng.rand(FEATURES, NUM_CLASSES).astype(np.float32))

    def loss(w):
        return -jnp.mean(jax.nn.log_softmax(x @ w)[jnp.arange(batch), target])

    @functools.partial(jax.jit, static_argnums=0)
    def run_plain(k, w):
        def body(_, w):
            return w - 0.01 * jax.grad(loss)(w)

        return lax.fori_loop(0, k, body, w)[0, 0]

    @functools.partial(jax.jit, static_argnums=0)
    def run_with_metrics(k, w, state):
        def body(_, carry):
            w, st = carry
            g = jax.grad(loss)(w)
            probs = jax.nn.softmax(x @ w)
            st = pure.update(st, probs, target)
            return w - 0.01 * g, st

        w, st = lax.fori_loop(0, k, body, (w, state))
        # fold every metric-state leaf into the readback so the whole chain
        # (train step AND metric update) is forced
        acc = w[0, 0]
        for leaf in jax.tree_util.tree_leaves(st):
            acc = acc + leaf.astype(jnp.float32).sum()
        return acc

    from benchmarks.timing import best_of, two_k_delta

    k1, k2 = 5, 105

    def per_step_ms(run, *args):
        float(run(k1, *args))  # compile both K variants + warm the path
        float(run(k2, *args))
        return two_k_delta(
            lambda k: best_of(lambda: float(run(k, *args))), k1, k2
        ) * 1e3

    # the marginal is a DIFFERENCE of two measurements; alternate the order
    # pair to pair (cancels monotonic drift) and take the median
    diffs = []
    for i in range(3):
        if i % 2 == 0:
            t_plain = per_step_ms(run_plain, w0)
            t_with = per_step_ms(run_with_metrics, w0, pure.init())
        else:
            t_with = per_step_ms(run_with_metrics, w0, pure.init())
            t_plain = per_step_ms(run_plain, w0)
        diffs.append(t_with - t_plain)
    # floor at ~timing resolution: XLA often fuses the metric update into the
    # step for free, making the true marginal indistinguishable from noise
    return max(sorted(diffs)[len(diffs) // 2], 0.01)


def bench_reference_eager_update() -> float:
    """Reference eager per-step collection update, torch CPU (single-device)."""
    sys.path.insert(0, "/root/reference")
    import torch
    from torchmetrics import Accuracy, F1, MetricCollection, Precision, Recall

    collection = MetricCollection([
        Accuracy(),
        F1(num_classes=NUM_CLASSES, average="macro"),
        Precision(num_classes=NUM_CLASSES, average="macro"),
        Recall(num_classes=NUM_CLASSES, average="macro"),
    ])

    batch = BATCH_PER_DEVICE * N_DEVICES
    rng = np.random.RandomState(0)
    logits = rng.rand(batch, NUM_CLASSES).astype(np.float32)
    preds = torch.from_numpy(logits / logits.sum(-1, keepdims=True))
    target = torch.from_numpy(rng.randint(0, NUM_CLASSES, batch).astype(np.int64))

    for _ in range(WARMUP):
        collection.update(preds, target)
    start = time.perf_counter()
    for _ in range(N_STEPS):
        collection.update(preds, target)
    return (time.perf_counter() - start) / N_STEPS * 1e3


def _metric_description() -> str:
    return (
        "per-step update+psum-sync+compute of MetricCollection(Accuracy,F1,"
        f"Precision,Recall), dist_sync_on_step, 8 devices ({BATCH_PER_DEVICE}"
        f"x{NUM_CLASSES} per device; ours: shard_map on 8 virtual CPU devices,"
        " compute groups + coalesced collectives, reference: torchmetrics"
        " forward on 8-process Gloo)"
    )


# extra keys _sync8_ab emits (collective counts always; span/compile tables
# when tracing); the parent copies them verbatim from the child's JSON (full
# mode) or the in-process dict (smoke mode)
_TRACE_KEYS = (
    "trace_schema",
    "sync_retries",
    "sync_deadline_exceeded",
    "degraded_computes",
    "quarantined_updates",
    "collective_calls",
    "sync_bytes",
    "collective_calls_ungrouped",
    "sync_bytes_ungrouped",
    "gather_collective_calls",
    "gather_sync_bytes",
    "gather_collective_calls_per_leaf",
    "gather_sync_bytes_per_leaf",
    "hier_collective_calls",
    "hier_sync_bytes",
    "hier_dcn_calls",
    "hier_dcn_bytes",
    "hier_ici_bytes",
    "flat2d_collective_calls",
    "flat2d_world_bytes",
    "sketch_sync_ms",
    "sketch_states_synced",
    "sketch_collective_calls",
    "sketch_sync_bytes",
    "sketch_dcn_bytes",
    "sketch_gather_calls",
    "keyed_sync_ms",
    "keyed_states_synced",
    "keyed_collective_calls",
    "keyed_sync_bytes",
    "keyed_gather_calls",
    "keyed_unkeyed_collective_calls",
    "sparse_sync_ms",
    "sparse_states_synced",
    "sparse_collective_calls",
    "sparse_sync_bytes",
    "sparse_gather_calls",
    "sparse_fallbacks",
    "hh_sync_ms",
    "hh_states_synced",
    "hh_collective_calls",
    "hh_sync_bytes",
    "hh_gather_calls",
    "hh_unkeyed_collective_calls",
    "hh_ingest_steps_per_s",
    "hh_ingest_steps_per_s_10k",
    "hh_tail_overcount_bound",
    "qsketch_sync_ms",
    "qsketch_states_synced",
    "qsketch_collective_calls",
    "qsketch_sync_bytes",
    "qsketch_gather_calls",
    "qsketch_unkeyed_collective_calls",
    "qsketch_state_bytes",
    "fused_step_ms",
    "mixed_sync_ms",
    "mixed_states_synced",
    "fused_collective_calls",
    "fused_sync_bytes",
    "fused_collective_calls_14",
    "service_sync_ms",
    "service_states_synced",
    "service_collective_calls",
    "service_sync_bytes",
    "service_gather_calls",
    "service_unwindowed_collective_calls",
    "async_sync8_ms",
    "fenced_sync8_ms",
    "async_states_synced",
    "async_collective_calls",
    "async_sync_bytes",
    "async_gather_calls",
    "async_fenced_collective_calls",
    "async_lag2_ms",
    "async_lag3_ms",
    "async_lag_collective_calls",
    "async_lag_sync_bytes",
    "async_lag_epoch_gather_calls",
    "async_lag_epoch_sync_gather_calls",
    "service_ingest_steps_per_s",
    "ingest_coalesced_steps_per_s",
    "ingest_coalesce_factor",
    "ingest_program_cache_misses",
    "retention_query_ms",
    "retention_windows_banked",
    "retention_rollups",
    "retention_resident_bytes",
    "fleet_ingest_steps_per_s",
    "fleet_ingest_steps_per_s_1shard",
    "fleet_scaling_x",
    "fleet_shards_merged_windows",
    "fleet_shards_published_windows",
    "fleet_lost_windows",
    "wm_agreement_ms",
    "wm_exchange_calls",
    "wm_stragglers",
    "slide_windows_published",
    "publish_lag_ms",
    "selfmeter_p99_ms",
    "lifecycle_windows_stamped",
    "slab_dropped_samples",
    "counters",
    "gather_counters",
    "hier_counters",
    "sketch_counters",
    "keyed_counters",
    "sparse_counters",
    "hh_counters",
    "qsketch_counters",
    "mixed_counters",
    "service_counters",
    "async_counters",
    "ingest_counters",
    "phase_ms",
    "phase_compile_ms",
    "device_ms",
    "compile",
    "trace_file",
)


# ---------------------------------------------------- collective regression gate
# Pinned per-scenario expectations for --check-collectives. The counters are
# per compiled step program (staged collectives — exact, replayed every
# step), so these are deterministic, not noisy ms numbers. GROWTH in either
# number fails the gate; a shrink is an improvement — re-pin it deliberately.
#
# sum plane (Accuracy+F1+Precision+Recall, NUM_CLASSES=32): the grouped
#   program psums one 520-byte int32 bucket (2 Accuracy scalars + 4 (C,)
#   stat vectors); ungrouped still coalesces into one bucket but moves every
#   member's copy (14 leaves, 1544 bytes).
# gather plane (AUROC+AP+Spearman, capacity 2048): coalesced stages ONE
#   all_gather per dtype bucket (counts bitcast into the data payload:
#   f32 + i32 -> 2 calls); per-leaf stages 2 per buffer (12). Bytes match:
#   same payload, fewer round-trips.
# sharded engines (row-sharded states, the ring / all_to_all programs):
#   sharded_auroc (binary, capacity 1024) stages 3 ppermutes (the sorted
#   pack circulating) + 1 coalesced psum; sharded_retrieval (MRR, capacity
#   1024) stages 4 all_to_alls (idx/preds/target/real regroup) + 3 psums
#   (overflow count, float total, int count+flag plane).
# sketch plane (AUROC+AP+Spearman with approx="sketch" on the same (4,2)
#   mesh): the sketch leaves fold into ONE int32 sum bucket — the staged
#   program is PSUM-ONLY ("gather_calls" pinned at ZERO: all_gather +
#   coalesced_gather + process_allgather) with a two-stage hierarchical psum
#   (1 ici + 1 dcn call) over the 3 KB group-deduped payload (AUROC+AP share
#   one compute-group histogram). The cross-scenario SKETCH GATE below
#   additionally requires this payload under 10% of the buffer plane's.
# hierarchical scenarios additionally pin the per-crossing structure on the
# (4,2) ici x dcn test mesh (S=2 slices x L=4 devices). Crossing BYTES are
# ring traffic (payload x (participants - 1), see observability.counters):
# the flat planes burn W-1 = 7 DCN-crossing hops per payload byte, the
# two-stage planes S-1 = 1 — the structural win --check-collectives pins.
# keyed plane (Keyed(AUROC sketch, K=10,000) vs the unkeyed metric on the
#   same (4,2) mesh): the (K, 2, 16) histogram slab + the (K,) row-count
#   slab fold into ONE int32 sum bucket — the staged program is the SAME
#   two-stage psum (1 ici + 1 dcn call) the unkeyed metric stages; only the
#   payload scales with K ((10000*2*16 + 10000) * 4 bytes per stage). The
#   cross-scenario KEYED GATE below pins the K-independence: equal staged
#   collective counts and kinds at K=10,000 and K=1 (psum-only, zero
#   gathers).
EXPECTED_COLLECTIVES = {
    "sketch_sync": {
        "collective_calls": 2, "sync_bytes": 6144, "gather_calls": 0,
        "dcn_calls": 1, "dcn_bytes": 3072, "ici_calls": 1, "ici_bytes": 9216,
    },
    "keyed_sync": {
        "collective_calls": 2, "sync_bytes": 2640000, "gather_calls": 0,
        "dcn_calls": 1, "dcn_bytes": 1320000, "ici_calls": 1, "ici_bytes": 3960000,
    },
    "keyed_unkeyed": {
        "collective_calls": 2, "sync_bytes": 256, "gather_calls": 0,
        "dcn_calls": 1, "dcn_bytes": 128, "ici_calls": 1, "ici_bytes": 384,
    },
    # heavy-hitter plane (HeavyHitters(AUROC sketch, 256 hot slots,
    # (4, 1024) tail) over a 1M-key space on the same (4,2) mesh): the hot
    # slab pair + the count-min tail pair fold into ONE int32 sum bucket —
    # the SAME two-stage psum program as keyed_unkeyed; the payload is
    # (256*32 + 256 + 4*1024*32 + 4*1024) * 4 = 574,464 bytes per stage,
    # constant in the live-key count. The cross-scenario HH GATE below pins
    # the open-world contract (staged parity, mass conservation, the tail
    # certificate, constant state bytes).
    "hh_sync": {
        "collective_calls": 2, "sync_bytes": 1148928, "gather_calls": 0,
        "dcn_calls": 1, "dcn_bytes": 574464, "ici_calls": 1, "ici_bytes": 1723392,
    },
    # sparse delta-sync plane (SparseSyncPlane over the same keyed slab,
    # K=10,000, capacity 64): program A psums the lane-packed touched bitmap
    # (1,250 uint32 words = 5,000 B per stage), program B all_gathers the
    # 64-row union payload (slot-id header + (2,16) histogram row + row
    # count = 8,704 B per stage) — 13,704 staged bytes flat, 1.4% of the
    # dense keyed plane's 2,640,000 at the same K; hierarchically each
    # program stages one ici + one dcn leg. The cross-scenario SPARSE GATE
    # below pins bytes < dense/10, K-independent counts, bit-exact merges
    # vs the dense plane, and the counted capacity-overflow fallback.
    "sparse_sync": {
        "collective_calls": 4, "sync_bytes": 36112, "gather_calls": 2,
        "dcn_calls": 2, "dcn_bytes": 13704, "ici_calls": 2, "ici_bytes": 67224,
    },
    "sparse_sync_flat": {
        "collective_calls": 2, "sync_bytes": 13704, "gather_calls": 1,
        "world_bytes": 95928,
    },
    "sum_grouped": {"collective_calls": 1, "sync_bytes": 520},
    "sum_ungrouped": {"collective_calls": 1, "sync_bytes": 1544},
    # megafusion mixed plane (all four mergeable state kinds in ONE
    # MetricCollection on the (4,2) mesh): every sum bucket — int32
    # classification counts and sketch/count-min/quantile cells bitcast
    # into one int32 lane, f32 error sums as sibling operands of the SAME
    # call — folds into ONE packed psum per crossing (psum_calls: 1 ici +
    # 1 dcn), with one pmin + one pmax riding for PSNR's tracked data
    # range: 6 staged calls hierarchically. The 14-member twin pins the
    # membership-independence: IDENTICAL counts, only the payload moves
    # (+0.4% — the HeavyHitters tail dominates both). The cross-scenario
    # MEGAFUSION GATE below additionally requires packed-vs-per-leaf
    # bit-exactness on both meshes.
    "mixed6_sync": {
        "collective_calls": 6, "sync_bytes": 1100808, "gather_calls": 0,
        "psum_calls": 2,
        "dcn_calls": 3, "dcn_bytes": 550404, "ici_calls": 3, "ici_bytes": 1651212,
    },
    "mixed14_sync": {
        "collective_calls": 6, "sync_bytes": 1105280, "gather_calls": 0,
        "psum_calls": 2,
        "dcn_calls": 3, "dcn_bytes": 552640, "ici_calls": 3, "ici_bytes": 1657920,
    },
    "gather_coalesced": {"collective_calls": 2, "sync_bytes": 49176},
    "gather_per_leaf": {"collective_calls": 12, "sync_bytes": 49176},
    "gather_hier": {
        "collective_calls": 4, "sync_bytes": 147528,
        "dcn_calls": 2, "dcn_bytes": 49176, "ici_calls": 2, "ici_bytes": 295056,
    },
    "gather_flat2d": {
        "collective_calls": 2, "sync_bytes": 49176,
        "dcn_bytes": 0, "world_bytes": 344232,
    },
    "sharded_auroc": {"collective_calls": 4, "sync_bytes": 1548},
    "sharded_auroc_hier": {
        "collective_calls": 8, "sync_bytes": 4632,
        "dcn_calls": 4, "dcn_bytes": 1548, "ici_calls": 4, "ici_bytes": 9252,
    },
    "sharded_retrieval": {"collective_calls": 7, "sync_bytes": 6672},
    "sharded_retrieval_hier": {
        "collective_calls": 14, "sync_bytes": 13344,
        "dcn_calls": 7, "dcn_bytes": 6672, "ici_calls": 7, "ici_bytes": 20016,
    },
}


SHARDED_GATE_CAPACITY = 1024  # rows per sharded-engine gate scenario


def _sharded_gate_mesh(hierarchical: bool):
    """(mesh, axis) for the sharded-engine gate scenarios: the flat 8-device
    ``dp`` axis, or the (4,2) 2-level mesh with its hierarchy."""
    import jax
    from jax.sharding import Mesh

    from metrics_tpu.parallel.placement import MeshHierarchy

    if hierarchical:
        mesh = Mesh(
            np.array(jax.devices("cpu")[:N_DEVICES]).reshape(
                HIER_SLICES, N_DEVICES // HIER_SLICES
            ),
            ("dcn", "ici"),
        )
        return mesh, MeshHierarchy(ici_axis="ici", dcn_axis="dcn")
    return Mesh(np.array(jax.devices("cpu")[:N_DEVICES]), ("dp",)), "dp"


def _build_sharded_auroc_runner(hierarchical: bool = False):
    """(run, states) for the row-sharded binary AUROC ring-engine program.

    ``run(1)`` dispatches ``compute()`` over row-sharded epoch buffers: the
    first call traces the ring engine's ``shard_map`` program, so the
    counters then hold its staged collectives (the sorted-pack ppermutes +
    the coalesced stats psum; hierarchically: one dcn pack exchange + the
    ici-only ring + the two-stage psum).
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from metrics_tpu import AUROC
    from metrics_tpu.parallel import row_sharded

    mesh, axis = _sharded_gate_mesh(hierarchical)
    metric = AUROC(pos_label=1, capacity=SHARDED_GATE_CAPACITY)
    metric.device_put(row_sharded(mesh, axis))
    rows = SHARDED_GATE_CAPACITY // 2
    rng = np.random.RandomState(0)
    preds = jnp.asarray(np.round(rng.rand(rows), 2).astype(np.float32))
    target = jnp.asarray((rng.rand(rows) > 0.5).astype(np.int32))
    metric.update(preds, target)

    def run(steps: int) -> float:
        start = time.perf_counter()
        for _ in range(steps):
            metric._computed = None
            metric.compute()
        return (time.perf_counter() - start) / steps * 1e3

    return run, len(metric._defaults)


def _build_sharded_retrieval_runner(hierarchical: bool = False):
    """(run, states) for the row-sharded RetrievalMRR all_to_all program
    (regroup-by-query exchange + the grouped engine's psums; hierarchically:
    the two-stage slice-then-device routing)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from metrics_tpu.parallel import row_sharded
    from metrics_tpu.retrieval import RetrievalMRR

    mesh, axis = _sharded_gate_mesh(hierarchical)
    metric = RetrievalMRR(capacity=SHARDED_GATE_CAPACITY)
    metric.device_put(row_sharded(mesh, axis))
    rows = SHARDED_GATE_CAPACITY // 2
    rng = np.random.RandomState(0)
    idx = jnp.asarray(rng.randint(0, 64, rows).astype(np.int32))
    preds = jnp.asarray(rng.rand(rows).astype(np.float32))
    target = jnp.asarray((rng.rand(rows) > 0.7).astype(np.int32))
    metric.update(idx, preds, target)

    def run(steps: int) -> float:
        start = time.perf_counter()
        for _ in range(steps):
            metric._computed = None
            metric.compute()
        return (time.perf_counter() - start) / steps * 1e3

    return run, len(metric._defaults)


def check_collectives() -> int:
    """``--check-collectives``: trace each scenario's step program and diff
    its staged ``collective_calls``/``sync_bytes`` — and, for the
    hierarchical scenarios, the per-crossing ``ici``/``dcn``/``world``
    calls and ring-traffic bytes — against the pinned expectations. Returns
    a non-zero exit status on any growth — the CI gate that catches silent
    collective-count regressions the ms numbers hide in noise. The
    cross-scenario HIERARCHY GATE additionally requires the hierarchical
    gather plane's DCN-crossing bytes to stay strictly below the flat
    plane's world-axis bytes (a future change that reflattens a
    DCN-crossing collective fails here even if its own pins still hold),
    and the SKETCH GATE requires the sketch-mode twin of the gather
    collection to stay PSUM-ONLY (zero staged gathers of any kind) with
    sync bytes under 10% of the buffer plane's on the same (4,2) mesh.
    Prints one JSON report line either way.
    """
    _serialize_cpu_dispatch()
    from metrics_tpu import observability as obs

    builders = {
        "sketch_sync": lambda: _build_sketch_sync_runner(True),
        "keyed_sync": lambda: _build_keyed_sync_runner(KEYED_SLOTS),
        "keyed_unkeyed": lambda: _build_keyed_sync_runner(None),
        "sparse_sync": lambda: _build_sparse_sync_runner(KEYED_SLOTS, True),
        "sparse_sync_flat": lambda: _build_sparse_sync_runner(KEYED_SLOTS, False),
        "hh_sync": _build_hh_sync_runner,
        "sum_grouped": lambda: _build_sync8_runner(True),
        "sum_ungrouped": lambda: _build_sync8_runner(False),
        "mixed6_sync": lambda: _build_mixed_sync_runner(MIXED_MEMBERS),
        "mixed14_sync": lambda: _build_mixed_sync_runner(MIXED_MEMBERS_WIDE),
        "gather_coalesced": lambda: _build_gather_runner(True),
        "gather_per_leaf": lambda: _build_gather_runner(False),
        "gather_hier": lambda: _build_hier_gather_runner(True),
        "gather_flat2d": lambda: _build_hier_gather_runner(False),
        "sharded_auroc": lambda: _build_sharded_auroc_runner(False),
        "sharded_auroc_hier": lambda: _build_sharded_auroc_runner(True),
        "sharded_retrieval": lambda: _build_sharded_retrieval_runner(False),
        "sharded_retrieval_hier": lambda: _build_sharded_retrieval_runner(True),
    }
    obs.enable()
    report, failures = {}, []
    for name, build in builders.items():
        run, _ = build()
        obs.COUNTERS.reset()
        run(1)  # first call traces+compiles: counters now hold the staged program
        snap = obs.counters_snapshot()
        got = {
            "collective_calls": snap["collective_calls"],
            "sync_bytes": snap["sync_bytes"],
            "ici_calls": snap["calls_by_crossing"].get("ici", 0),
            "ici_bytes": snap["bytes_by_crossing"].get("ici", 0),
            "dcn_calls": snap["calls_by_crossing"].get("dcn", 0),
            "dcn_bytes": snap["bytes_by_crossing"].get("dcn", 0),
            "world_bytes": snap["bytes_by_crossing"].get("world", 0),
            # staged gathers of ANY kind — the psum-only pin of the sketch plane
            "gather_calls": sum(
                snap["calls_by_kind"].get(k, 0)
                for k in ("all_gather", "coalesced_gather", "process_allgather")
            ),
            # staged sum-plane dispatches — the megafusion pin of ONE
            # packed psum per crossing
            "psum_calls": snap["calls_by_kind"].get("psum", 0),
        }
        expected = EXPECTED_COLLECTIVES[name]
        status = "ok"
        for key, pinned in expected.items():
            if got[key] > pinned:
                status = "regression"
                failures.append(f"{name}.{key}: {got[key]} > pinned {pinned}")
            elif got[key] < pinned and status == "ok":
                status = "improved (re-pin EXPECTED_COLLECTIVES)"
        keep = set(expected) | {"collective_calls", "sync_bytes"}
        report[name] = {**{k: v for k, v in got.items() if k in keep},
                        "expected": expected, "status": status}
    obs.disable()

    # the hierarchy gate of record: staged DCN traffic of the hierarchical
    # gather plane strictly below the flat plane's world-axis traffic
    hier_dcn = report["gather_hier"]["dcn_bytes"]
    flat_world = report["gather_flat2d"]["world_bytes"]
    hier_gate = {"hier_dcn_bytes": hier_dcn, "flat2d_world_bytes": flat_world,
                 "ok": hier_dcn < flat_world}
    if not hier_gate["ok"]:
        failures.append(
            f"hierarchy gate: gather_hier dcn bytes {hier_dcn} not strictly below"
            f" gather_flat2d world bytes {flat_world}"
        )

    # the sketch gate of record: the sketch-mode twin of the gather
    # collection must be psum-only (zero staged gathers) AND move under 10%
    # of the buffer plane's bytes on the same (4,2) mesh — the acceptance
    # criterion that makes the O(samples)->O(bins) conversion a gated number
    sketch_bytes = report["sketch_sync"]["sync_bytes"]
    buffer_bytes = report["gather_hier"]["sync_bytes"]
    sketch_gathers = report["sketch_sync"]["gather_calls"]
    sketch_gate = {
        "sketch_sync_bytes": sketch_bytes,
        "buffer_hier_bytes": buffer_bytes,
        "sketch_gather_calls": sketch_gathers,
        "ok": sketch_gathers == 0 and sketch_bytes * 10 < buffer_bytes,
    }
    if sketch_gathers != 0:
        failures.append(
            f"sketch gate: sketch_sync staged {sketch_gathers} gather collectives"
            " (the sketch plane must be psum-only)"
        )
    if sketch_bytes * 10 >= buffer_bytes:
        failures.append(
            f"sketch gate: sketch sync bytes {sketch_bytes} not under 10% of the"
            f" buffer plane's {buffer_bytes} on the same mesh"
        )

    # the keyed gate of record: K=10,000 segments sync with the IDENTICAL
    # staged-collective count and kinds as the unkeyed metric (psum-only,
    # zero gathers of any kind) — segments are a leading state axis, never
    # extra collectives, which is the whole point of the slab design
    keyed_calls = report["keyed_sync"]["collective_calls"]
    unkeyed_calls = report["keyed_unkeyed"]["collective_calls"]
    keyed_gathers = report["keyed_sync"]["gather_calls"]
    keyed_gate = {
        "keyed_collective_calls": keyed_calls,
        "unkeyed_collective_calls": unkeyed_calls,
        "keyed_gather_calls": keyed_gathers,
        "num_slots": KEYED_SLOTS,
        "ok": keyed_calls == unkeyed_calls and keyed_gathers == 0
        and report["keyed_unkeyed"]["gather_calls"] == 0,
    }
    if keyed_calls != unkeyed_calls:
        failures.append(
            f"keyed gate: K={KEYED_SLOTS} staged {keyed_calls} collectives vs the"
            f" unkeyed metric's {unkeyed_calls} — collective counts must be"
            " K-independent"
        )
    if keyed_gathers != 0:
        failures.append(
            f"keyed gate: keyed_sync staged {keyed_gathers} gather collectives"
            " (the slab plane must be psum-only)"
        )

    # the heavy-hitter gate of record: the OPEN-WORLD extension of the keyed
    # gate. Staged half: a 1M-key-space HeavyHitters stages the IDENTICAL
    # collective count and kinds as the unkeyed metric (psum-only, zero
    # gathers). Eager half (seeded Zipfian streams, deterministic):
    # promotion/demotion round-trips conserve mass bit-exactly vs an unkeyed
    # oracle, every tail query's true value lies within the reported
    # (e/width)*N certificate, and total state bytes are IDENTICAL whether
    # the stream drew from 10k or 1M keys.
    hh_eager = _hh_eager_gate()
    hh_calls = report["hh_sync"]["collective_calls"]
    hh_gathers = report["hh_sync"]["gather_calls"]
    hh_gate = {
        "hh_collective_calls": hh_calls,
        "unkeyed_collective_calls": unkeyed_calls,
        "hh_gather_calls": hh_gathers,
        "simulated_key_space": HH_KEY_SPACE,
        **hh_eager,
        "ok": (
            hh_calls == unkeyed_calls and hh_gathers == 0
            and hh_eager["mass_conserved"] and hh_eager["cert_violations"] == 0
            and hh_eager["state_bytes_10k"] == hh_eager["state_bytes_1m"]
        ),
    }
    if hh_calls != unkeyed_calls:
        failures.append(
            f"hh gate: a {HH_KEY_SPACE}-key-space HeavyHitters staged {hh_calls}"
            f" collectives vs the unkeyed metric's {unkeyed_calls} — collective"
            " counts must be key-count-independent"
        )
    if hh_gathers != 0:
        failures.append(
            f"hh gate: hh_sync staged {hh_gathers} gather collectives (both tiers"
            " must be psum-only)"
        )
    if not hh_eager["mass_conserved"]:
        failures.append(
            "hh gate: hot + tail totals diverged from the unkeyed oracle —"
            " promotion/demotion must conserve mass bit-exactly"
        )
    if hh_eager["cert_violations"]:
        failures.append(
            f"hh gate: {hh_eager['cert_violations']}/{hh_eager['cert_checked']}"
            f" tail queries exceeded the (e/width)*N certificate"
            f" ({hh_eager['tail_overcount_bound']})"
        )
    if hh_eager["state_bytes_10k"] != hh_eager["state_bytes_1m"]:
        failures.append(
            f"hh gate: state bytes moved with the key space"
            f" ({hh_eager['state_bytes_10k']} at 10k vs"
            f" {hh_eager['state_bytes_1m']} at 1M) — must be constant in the"
            " live-key count"
        )

    # the sparse gate of record: the delta-sync headline. Staged half: the
    # seeded sparse-touch stream (K=10,000, <= SPARSE_TOUCH touched rows per
    # step) must stage UNDER 10% of the dense keyed plane's bytes on the
    # same mesh, and the staged collective count must be K-INDEPENDENT
    # (re-traced at K=1,000 — the bitmap payload shrinks, the program does
    # not). Eager half (deterministic host arithmetic): merges bit-exact vs
    # the dense coalesced plane on BOTH the flat and (4,2) hierarchical
    # meshes, the capacity-overflow round falls back to the dense plane
    # bit-exactly AND is counted (sparse_fallbacks), and the empty-touch
    # round skips the row exchange entirely (sparse skips + gather_skips).
    obs.enable()
    run_small, _ = _build_sparse_sync_runner(SPARSE_SMALL_K, True)
    obs.COUNTERS.reset()
    run_small(1)
    sparse_small_calls = obs.counters_snapshot()["collective_calls"]
    obs.disable()
    sparse_eager = _sparse_eager_gate()
    sparse_bytes = report["sparse_sync"]["sync_bytes"]
    dense_bytes = report["keyed_sync"]["sync_bytes"]
    sparse_calls = report["sparse_sync"]["collective_calls"]
    sparse_gate = {
        "sparse_sync_bytes": sparse_bytes,
        "dense_keyed_bytes": dense_bytes,
        "sparse_collective_calls": sparse_calls,
        "small_k_collective_calls": sparse_small_calls,
        "small_k": SPARSE_SMALL_K,
        **sparse_eager,
        "ok": (
            sparse_bytes * 10 < dense_bytes
            and sparse_calls == sparse_small_calls
            and sparse_eager["bit_exact_flat"]
            and sparse_eager["bit_exact_hier"]
            and sparse_eager["fallback_bit_exact"]
            and sparse_eager["fallbacks"] > 0
            and sparse_eager["skips"] > 0
            and sparse_eager["gather_skips"] > 0
        ),
    }
    if sparse_bytes * 10 >= dense_bytes:
        failures.append(
            f"sparse gate: sparse sync bytes {sparse_bytes} not under 10% of the"
            f" dense keyed plane's {dense_bytes} on the same mesh"
        )
    if sparse_calls != sparse_small_calls:
        failures.append(
            f"sparse gate: K={KEYED_SLOTS} staged {sparse_calls} collectives vs"
            f" {sparse_small_calls} at K={SPARSE_SMALL_K} — the staged count must"
            " be K-independent"
        )
    for arm in ("flat", "hier"):
        if not sparse_eager[f"bit_exact_{arm}"]:
            failures.append(
                f"sparse gate: sparse merge diverged from the dense coalesced"
                f" plane on the {arm} mesh — merges must be bit-exact"
            )
    if not sparse_eager["fallback_bit_exact"]:
        failures.append(
            "sparse gate: the capacity-overflow fallback round diverged from"
            " the dense plane — the fallback must be bit-exact"
        )
    if sparse_eager["fallbacks"] == 0:
        failures.append(
            "sparse gate: the capacity-overflow round did not bump"
            " sparse_fallbacks — the fallback must be counted"
        )
    if sparse_eager["skips"] == 0 or sparse_eager["gather_skips"] == 0:
        failures.append(
            "sparse gate: the empty-touch round did not record a sparse skip +"
            " gather skip — an empty union must skip the row exchange"
        )

    # the megafusion gate of record: the packed reduce plane. Staged half:
    # the mixed collection (all four mergeable state kinds behind one
    # MetricCollection) must stage ONE packed psum per crossing (1 ici +
    # 1 dcn on the (4,2) mesh — int dtypes bitcast into the shared int32
    # lane, floats as sibling operands of the SAME call) with the total
    # staged count IDENTICAL at 6 and 14 members — membership grows the
    # payload, never the program. Bit-exact half: the packed plane's
    # synced leaves must equal the per-leaf sync_value reference EXACTLY
    # on both the flat and hierarchical meshes (14-member collection —
    # int sums, float sums, min/max riders, every sketch kind).
    mixed_parity = _mixed_sync_parity_failures()
    m6_calls = report["mixed6_sync"]["collective_calls"]
    m14_calls = report["mixed14_sync"]["collective_calls"]
    m6_psums = report["mixed6_sync"]["psum_calls"]
    m14_psums = report["mixed14_sync"]["psum_calls"]
    megafusion_gate = {
        "mixed6_collective_calls": m6_calls,
        "mixed14_collective_calls": m14_calls,
        "mixed6_psum_calls": m6_psums,
        "mixed14_psum_calls": m14_psums,
        "crossings": 2,
        "parity_ok": not mixed_parity,
        "ok": (
            m6_calls == m14_calls and m6_psums == 2 and m14_psums == 2
            and not mixed_parity
        ),
    }
    if m6_calls != m14_calls:
        failures.append(
            f"megafusion gate: 6 members staged {m6_calls} collectives vs"
            f" {m14_calls} at 14 members — the staged count must be"
            " membership-independent"
        )
    if m6_psums != 2 or m14_psums != 2:
        failures.append(
            f"megafusion gate: the mixed sum plane staged {m6_psums} (6-member)"
            f" / {m14_psums} (14-member) psums over 2 crossings — must be ONE"
            " packed psum per crossing"
        )
    failures.extend(mixed_parity)

    print(json.dumps({
        "check": "collectives",
        "ok": not failures,
        "failures": failures,
        "hier_gate": hier_gate,
        "sketch_gate": sketch_gate,
        "keyed_gate": keyed_gate,
        "hh_gate": hh_gate,
        "sparse_gate": sparse_gate,
        "megafusion_gate": megafusion_gate,
        "scenarios": report,
    }))
    return 1 if failures else 0


def _hh_eager_gate() -> dict:
    """The eager half of the heavy-hitter gate: drive seeded Zipfian streams
    (10k- and 1M-key spaces) through ``HeavyHitters(Accuracy)`` next to an
    unkeyed oracle and measure mass conservation, the tail certificate, and
    state-byte constancy. Deterministic: host arithmetic over integer
    states, no timing."""
    from metrics_tpu import Accuracy, HeavyHitters
    from metrics_tpu.observability.counters import state_nbytes

    def run(key_space):
        hh = HeavyHitters(Accuracy(), num_hot_slots=HH_GATE_SLOTS,
                          tail=(HH_GATE_TAIL_DEPTH, HH_TAIL_WIDTH))
        oracle = Accuracy()
        true_counts = {}
        for keys, preds, target in _hh_stream(key_space, HH_GATE_BATCHES, HH_GATE_BATCH):
            hh.update(preds, target, key=keys)
            oracle.update(preds, target)
            for k in keys:
                true_counts[k] = true_counts.get(k, 0) + 1
        return hh, oracle, true_counts

    hh_small, _, _ = run(HH_KEY_SPACE_SMALL)
    hh_big, oracle, true_counts = run(HH_KEY_SPACE)

    # mass conservation: hot + tail totals bit-exact vs the unkeyed oracle
    # (every tail row carries the full tail mass, so row 0's sum IS it)
    total_samples = HH_GATE_BATCHES * HH_GATE_BATCH
    mass_conserved = (
        int(np.asarray(hh_big.hh_rows).sum()) + hh_big.tail_mass() == total_samples
    )
    for name in ("correct", "total"):
        hot = int(np.asarray(getattr(hh_big, name)).sum())
        tail = int(np.asarray(getattr(hh_big, name + "_tail").counts[0]).sum())
        mass_conserved = mass_conserved and hot + tail == int(np.asarray(getattr(oracle, name)))

    # the certificate: every currently-tail key's true count is covered by
    # its (overcounting) estimate within (e/width) * N. The device tail
    # rows and the table's host mirror must agree bit-exactly (the mirror
    # is how promotion decisions stay readback-free), which also lets the
    # sweep run in host numpy.
    mirror_ok = np.array_equal(
        np.asarray(getattr(hh_big, "hh_tail_rows").counts), hh_big._table._mirror
    )
    bound = hh_big.tail_overcount_bound()
    cert_checked = cert_violations = 0
    for key, true in true_counts.items():
        if key in hh_big._table:
            continue
        estimate = hh_big._table.tail_estimate(key)
        cert_checked += 1
        if not (true <= estimate <= true + bound):
            cert_violations += 1
    return {
        "mass_conserved": bool(mass_conserved and mirror_ok),
        "demotions": hh_big._table.demotions,
        "cert_checked": cert_checked,
        "cert_violations": cert_violations,
        "tail_overcount_bound": round(bound, 4),
        "tail_mass": hh_big.tail_mass(),
        "state_bytes_10k": state_nbytes(hh_small._current_state()),
        "state_bytes_1m": state_nbytes(hh_big._current_state()),
    }


def _sparse_eager_gate() -> dict:
    """The eager half of the sparse gate: on both the flat 8-device mesh and
    the (4,2) hierarchical mesh, a seeded sparse-touch round through
    ``SparseSyncPlane`` must merge bit-exactly vs the dense coalesced plane,
    a batch touching 2x ``sparse_capacity`` rows must fall back to the dense
    plane bit-exactly AND bump ``sparse_fallbacks``, and an unchanged-state
    round must skip the row exchange (sparse skips + gather_skips).
    Deterministic: seeded streams, integer histogram states, no timing."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu import AUROC, Keyed
    from metrics_tpu.observability import counters as _ctr
    from metrics_tpu.parallel.slab import slab_touched_mask
    from metrics_tpu.parallel.sparse import _payload_of
    from metrics_tpu.parallel.sync import coalesced_sync_state
    from metrics_tpu.utils.compat import shard_map

    def bit_exact(a, b):
        return all(
            np.array_equal(np.asarray(_payload_of(a[k])), np.asarray(_payload_of(b[k])))
            for k in a
        )

    rng = np.random.RandomState(0)
    rows = GATHER_CAPACITY // 2
    preds = jnp.asarray(rng.rand(rows).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 2, rows).astype(np.int32))
    narrow = rng.choice(KEYED_SLOTS, SPARSE_TOUCH, replace=False)
    wide = rng.choice(KEYED_SLOTS, SPARSE_CAPACITY * 2, replace=False)

    out = {"fallbacks": 0, "skips": 0, "gather_skips": 0, "fallback_bit_exact": True}
    for label, hierarchical in (("flat", False), ("hier", True)):
        if hierarchical:
            mesh = Mesh(
                np.array(jax.devices("cpu")[:N_DEVICES]).reshape(
                    HIER_SLICES, N_DEVICES // HIER_SLICES
                ),
                ("dcn", "ici"),
            )
            axis = ("dcn", "ici")  # auto-derived hierarchy on both planes
        else:
            mesh = Mesh(np.array(jax.devices("cpu")[:N_DEVICES]), ("dp",))
            axis = "dp"

        metric = Keyed(AUROC(approx="sketch", num_bins=KEYED_BINS), num_slots=KEYED_SLOTS)
        plane = metric.sparse_plane(axis, mesh, capacity=SPARSE_CAPACITY)
        initial = metric._current_state()
        reductions = dict(metric._reductions)
        dense_fn = jax.jit(shard_map(
            lambda s, r=reductions, a=axis: coalesced_sync_state(s, r, a),
            mesh=mesh, in_specs=(P(),), out_specs=P(), check_vma=False,
        ))

        # the sparse round: <= SPARSE_TOUCH touched rows, hinted bitmap
        slots = jnp.asarray(narrow[rng.randint(0, len(narrow), rows)].astype(np.int32))
        metric.update(preds, target, slot=slots)
        updated = metric._current_state()
        merged = plane.sync(updated, touched=slab_touched_mask(slots, KEYED_SLOTS))
        out[f"bit_exact_{label}"] = bit_exact(dense_fn(updated), merged)

        # the overflow round: 2x capacity distinct rows -> counted dense
        # fallback, still bit-exact (correctness never rides the estimate)
        metric.reset()
        wide_slots = jnp.asarray(wide[rng.randint(0, len(wide), rows)].astype(np.int32))
        metric.update(preds, target, slot=wide_slots)
        updated_wide = metric._current_state()
        before_fb = _ctr.COUNTERS.sparse["fallbacks"]
        plane.rebase(initial)
        merged_wide = plane.sync(updated_wide)
        out["fallback_bit_exact"] = out["fallback_bit_exact"] and bit_exact(
            dense_fn(updated_wide), merged_wide
        )
        out["fallbacks"] += _ctr.COUNTERS.sparse["fallbacks"] - before_fb

        # the empty-touch round: unchanged state skips the row exchange
        before_sk = _ctr.COUNTERS.sparse["skips"]
        before_gs = _ctr.COUNTERS.gather_skips
        plane.rebase(initial)
        merged_empty = plane.sync(dict(initial))
        out["skips"] += _ctr.COUNTERS.sparse["skips"] - before_sk
        out["gather_skips"] += _ctr.COUNTERS.gather_skips - before_gs
        out[f"bit_exact_{label}"] = out[f"bit_exact_{label}"] and bit_exact(
            initial, merged_empty
        )
    return out


# ------------------------------------------------------- fault-tolerance gate
# --check-faults drives the sync8 collection's HOST sync plane (per-step
# dist_sync_on_step forwards + the epoch compute) under a seeded chaos
# schedule and pins the fault-tolerance contract:
#   clean     — a guarded run with no injector reports ZERO fault counters
#   recovered — transient drop + stall + corrupted-payload faults, all inside
#               the retry budget: the final epoch values are BIT-EXACT vs the
#               clean run and nothing degraded
#   degraded  — a persistent drop exhausts the budget under policy 'degrade':
#               the run completes within the deadline budget (no hang), the
#               sync span is stamped degraded=yes, degraded_computes > 0
FAULT_STEPS = 4
FAULT_DEADLINE_S = 0.3
FAULT_RETRIES = 2
FAULT_BACKOFF_S = 0.02


def _fault_collection():
    from metrics_tpu import Accuracy, F1, MetricCollection, Precision, Recall
    from metrics_tpu.parallel.sync import gather_all_arrays

    kw = dict(dist_sync_on_step=True, dist_sync_fn=gather_all_arrays)
    return MetricCollection([
        Accuracy(**kw),
        F1(num_classes=NUM_CLASSES, average="macro", **kw),
        Precision(num_classes=NUM_CLASSES, average="macro", **kw),
        Recall(num_classes=NUM_CLASSES, average="macro", **kw),
    ])


def _fault_epoch(schedule, guard, trace: bool = False):
    """Drive FAULT_STEPS dist_sync_on_step forwards + the epoch compute under
    ``schedule``/``guard``; returns (epoch values as numpy, counters
    snapshot, elapsed seconds, degraded-span count)."""
    import contextlib

    import jax.numpy as jnp

    from metrics_tpu import observability as obs
    from metrics_tpu.parallel import faults
    from metrics_tpu.parallel.sync import set_sync_guard

    rng = np.random.RandomState(7)
    logits = rng.rand(256, NUM_CLASSES).astype(np.float32)
    preds = jnp.asarray(logits / logits.sum(-1, keepdims=True))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, 256).astype(np.int32))

    obs.reset()
    if trace:
        obs.enable()
    old_guard = set_sync_guard(guard)
    injector = faults.ChaosInjector(schedule, seed=0) if schedule else contextlib.nullcontext()
    try:
        with injector:
            collection = _fault_collection()
            start = time.perf_counter()
            for _ in range(FAULT_STEPS):
                collection(preds, target)
            values = {k: np.asarray(v) for k, v in collection.compute().items()}
            elapsed = time.perf_counter() - start
    finally:
        set_sync_guard(old_guard)
    counters = obs.counters_snapshot()
    degraded_spans = 0
    if trace:
        degraded_spans = sum(
            1 for rec in obs.records() if (rec.attrs or {}).get("degraded") == "yes"
        )
        obs.disable()
    return values, counters, elapsed, degraded_spans


def check_faults() -> int:
    """``--check-faults``: the fault-tolerance regression gate (see the
    schedule comment above). Prints one JSON report line; non-zero exit on
    any broken contract."""
    from metrics_tpu.parallel.faults import FaultSpec
    from metrics_tpu.parallel.sync import SyncGuard

    failures = []
    guard = SyncGuard(
        deadline_s=FAULT_DEADLINE_S, max_retries=FAULT_RETRIES,
        backoff_s=FAULT_BACKOFF_S, policy="raise", check_finite=True,
    )

    clean_values, clean_counters, _, _ = _fault_epoch(schedule=None, guard=guard)
    if any(clean_counters["faults"].values()):
        failures.append(f"clean run reported nonzero fault counters: {clean_counters['faults']}")

    recovered_schedule = [
        FaultSpec(kind="drop", call=0, times=1),
        FaultSpec(kind="stall", call=2, times=1, duration_s=2 * FAULT_DEADLINE_S),
        FaultSpec(kind="corrupt", call=4, times=1),
    ]
    rec_values, rec_counters, _, _ = _fault_epoch(schedule=recovered_schedule, guard=guard)
    if set(rec_values) != set(clean_values) or any(
        not np.array_equal(rec_values[k], clean_values[k]) for k in clean_values
    ):
        failures.append("retry-recovered run is not bit-exact vs the fault-free run")
    if rec_counters["faults"]["sync_retries"] < 3:
        failures.append(
            f"recovered run retried {rec_counters['faults']['sync_retries']} times; expected >= 3"
        )
    if rec_counters["faults"]["degraded_computes"] != 0:
        failures.append("recovered run degraded; every fault was inside the retry budget")

    degrade_guard = guard._replace(policy="degrade", max_retries=1, check_finite=False)
    # generous no-hang budget: every guarded call could at worst burn the full
    # deadline per attempt plus backoffs; a blocking-collective hang would
    # blow far past it
    budget_s = 30.0
    deg_schedule = [FaultSpec(kind="drop", call=1, times=10_000)]
    deg_values, deg_counters, deg_elapsed, deg_spans = _fault_epoch(
        schedule=deg_schedule, guard=degrade_guard, trace=True
    )
    if deg_elapsed > budget_s:
        failures.append(f"degrade run took {deg_elapsed:.1f}s > {budget_s}s budget (hang?)")
    if deg_counters["faults"]["degraded_computes"] < 1:
        failures.append("degrade run never flagged degraded_computes")
    if deg_spans < 1:
        failures.append("no sync span was stamped degraded=yes")
    del deg_values  # single-process local-only state == the world state

    print(json.dumps({
        "check": "faults",
        "ok": not failures,
        "failures": failures,
        "clean": {"faults": clean_counters["faults"]},
        "recovered": {"faults": rec_counters["faults"]},
        "degraded": {
            "faults": deg_counters["faults"],
            "elapsed_s": round(deg_elapsed, 3),
            "budget_s": budget_s,
            "degraded_spans": deg_spans,
        },
    }))
    return 1 if failures else 0


# -------------------------------------------------------- deferred-sync gate
# --check-async pins the deferred-sync contract on the sync8 scenario:
#   parity  — the deferred plane's staged collective COUNT and KINDS are
#             IDENTICAL to the synchronous plane's (it dispatches the same
#             coalesced_sync_state program; zero new collective kinds)
#   lag     — Metric sync_lag=k forward values are BIT-EXACT the synchronous
#             plane's values from k steps back, for every k in
#             ASYNC_LAG_DEPTHS (steps 0..k-1 read the documented local
#             warm-up view); the epoch compute drains the whole ring in
#             entry order and matches exactly
#   monotone— wall time over the bursty simulated-DCN forward loop is
#             monotone non-increasing in lag depth: each extra ring level
#             buys a straggler burst one more step of runway (see the
#             ASYNC_SWEEP_* block)
#   auto    — sync_lag="auto" (the LagController feedback loop over the
#             measured fence-wait split) picks lag 0 under the free
#             collective (bit-exact synchronous values, zero staleness) and
#             deepens to lag >= 1 under the slow gather
#   epoch   — the collection's DEFERRED _grouped_host_sync form publishes
#             bit-exactly the synchronous form's values with the identical
#             per-group gather-call count
#   overlap — the sync8 collection's dist_sync_on_step forward loop under a
#             SIMULATED-DCN gather: the sync_lag=1 plane's step ms must come
#             in strictly below the synchronous plane's. The gather sleeps
#             ASYNC_DCN_SLEEP_S inside the call — exactly where a multi-host
#             process_allgather would block the caller — because this image
#             is single-host (often single-core): a real DCN rendezvous wait
#             does not exist here, and device-plane concurrency cannot be
#             measured on one core (an executing psum IS the core's work;
#             only a *waiting* gather yields it). The deferred plane's win is
#             hiding exactly that wait behind the next step's update, which
#             the sleep reproduces faithfully. The device plane's fence-wait
#             split (async fences wait less host time than the synchronous
#             block) rides along as supporting evidence.
ASYNC_GATE_STEPS = 60
ASYNC_GATE_REPEATS = 4
ASYNC_LAG_BATCHES = 6
ASYNC_LAG_DEPTHS = (1, 2, 3)  # the lag-sweep tier's ring depths
ASYNC_DCN_SLEEP_S = 0.002  # simulated per-gather-call DCN rendezvous wait
ASYNC_FWD_STEPS = 10
ASYNC_FWD_ROWS = 1024
# the monotonicity sweep's simulated DCN: a BURSTY gather (every
# ASYNC_SWEEP_BURST_EVERY-th step, the first member's gather stalls
# ASYNC_SWEEP_BURST_S; all other calls pay ASYNC_SWEEP_FAST_S) plus a fixed
# per-step train-work sleep. A constant-latency gather would make every
# depth >= 1 equally fast (the single-worker plane's throughput is
# depth-independent in steady state); a BURST is what a deeper ring absorbs
# — each extra level of depth buys the burst one more step of runway, so the
# per-burst blocked wait shrinks by ~one step time per level. That is the
# regime where wall time is monotone non-increasing in lag depth, and it is
# the realistic one: DCN rendezvous waits are bursty (stragglers), not
# constant. The numbers are chosen so the HOST loop, not the single-worker
# plane, is the bottleneck (bursts rare enough that total background work
# stays below total train work) and so the burst exceeds three steps of
# runway — both conditions hold across the plausible range of per-forward
# host cost, keeping the per-level margin at burst-count x step-time
# (tens of ms), far above timer noise.
ASYNC_SWEEP_STEPS = 18
ASYNC_SWEEP_REPEATS = 3
ASYNC_SWEEP_BURST_EVERY = 6  # steps between bursts (3 bursts per run)
ASYNC_SWEEP_BURST_S = 0.070
ASYNC_SWEEP_FAST_S = 0.0002
ASYNC_SWEEP_TRAIN_S = 0.012  # per-step host work the loop interleaves
ASYNC_SWEEP_MEMBERS = 4  # gather calls per step (one per collection member)
# the adaptive-controller gate: forwards under a free gather must keep
# sync_lag="auto" at lag 0; under a slow gather it must deepen to >= 1
ASYNC_AUTO_STEPS = 8
ASYNC_AUTO_SLOW_SLEEP_S = 0.005
# a loaded CI host can legitimately hand the controller a > free_ms
# executor round-trip (that deepening is the feedback loop WORKING, and
# calm_steps hysteresis keeps it deep past the short run) — so the free
# arm gets fresh-metric retries and must converge to lag 0 on one of them
ASYNC_AUTO_ATTEMPTS = 3


def _build_lag_sweep_runner(sync_lag: int):
    """The lag-sweep variant of :func:`_build_async_forward_runner`: same
    four-member forward loop, but with the bursty simulated-DCN gather and
    the fixed per-step train work (see the ASYNC_SWEEP_* block). The burst
    schedule is call-indexed and resets every ``run`` call, so every depth
    replays the identical fault pattern."""
    from metrics_tpu.parallel.sync import packable_gather

    calls = {"n": 0}

    @packable_gather
    def bursty_gather(value):
        idx = calls["n"]
        calls["n"] += 1
        step, member = divmod(idx, ASYNC_SWEEP_MEMBERS)
        if member == 0 and step % ASYNC_SWEEP_BURST_EVERY == 0:
            time.sleep(ASYNC_SWEEP_BURST_S)  # the straggler rendezvous
        else:
            time.sleep(ASYNC_SWEEP_FAST_S)
        return [value]

    inner = _build_async_forward_runner(
        sync_lag, gather_fn=bursty_gather, train_work_s=ASYNC_SWEEP_TRAIN_S
    )

    def run(steps: int) -> float:
        calls["n"] = 0  # replay the identical burst schedule every run
        return inner(steps)

    return run


def _bench_epoch_gather_parity():
    """The deferred-epoch-gather A/B: one collection of two compute groups
    (2x Accuracy + 2x Precision) built twice, its epoch ``compute()`` run
    once through the DEFERRED ``_grouped_host_sync`` form and once through
    the synchronous form, with the shared gather counted at the call site.
    Returns ``(values_deferred, values_sync, calls_deferred, calls_sync)`` —
    the bit-exactness and identical-collective-count pins ``--check-async``
    gates (the default bench line carries the counts)."""
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MetricCollection, Precision
    from metrics_tpu.parallel.sync import packable_gather

    rng = np.random.RandomState(17)
    logits = rng.rand(256, NUM_CLASSES).astype(np.float32)
    preds = jnp.asarray(logits / logits.sum(-1, keepdims=True))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, 256).astype(np.int32))

    calls = {"n": 0}

    @packable_gather
    def counted_gather(value):
        calls["n"] += 1
        return [value]

    def build():
        col = MetricCollection({
            "acc_a": Accuracy(dist_sync_fn=counted_gather),
            "acc_b": Accuracy(dist_sync_fn=counted_gather),
            "prec_a": Precision(num_classes=NUM_CLASSES, average="macro", dist_sync_fn=counted_gather),
            "prec_b": Precision(num_classes=NUM_CLASSES, average="macro", dist_sync_fn=counted_gather),
        })
        col.update(preds, target)
        return col

    col_def = build()
    calls["n"] = 0
    vals_def = {k: np.asarray(v) for k, v in col_def.compute().items()}
    calls_def = calls["n"]

    col_sync = build()
    col_sync.deferred_epoch_sync = False
    calls["n"] = 0
    vals_sync = {k: np.asarray(v) for k, v in col_sync.compute().items()}
    calls_sync = calls["n"]
    return vals_def, vals_sync, calls_def, calls_sync


def _build_async_forward_runner(sync_lag: int, gather_fn=None, train_work_s: float = 0.0):
    """(timed_run(steps) -> ms/step) for the dist_sync_on_step forward A/B:
    the sync8 collection driven through real per-step forwards with a
    simulated-DCN host gather as every member's ``dist_sync_fn``.

    ``compute_groups=False`` keeps the variants structurally identical —
    four per-member gather planes per step either way (grouped ``sync_lag=0``
    members would share step gathers, which lag members by design do not).
    With ``sync_lag=k`` each forward dispatches its plane on the background
    executor and reads the view from k steps back through the handle ring;
    the synchronous variant blocks the step on all four gathers.

    ``gather_fn`` overrides the default constant-sleep DCN simulation (the
    lag-sweep tier passes a BURSTY schedule); ``train_work_s`` adds a fixed
    per-step host sleep — the training work a real loop interleaves between
    metric forwards, which is exactly the runway a deeper ring converts into
    hidden gather time.
    """
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, F1, MetricCollection, Precision, Recall
    from metrics_tpu.parallel.sync import packable_gather

    if gather_fn is None:
        @packable_gather
        def gather_fn(value):
            time.sleep(ASYNC_DCN_SLEEP_S)  # the rendezvous wait a real DCN pays
            return [value]

    kw = dict(dist_sync_on_step=True, dist_sync_fn=gather_fn)
    col = MetricCollection([
        Accuracy(**kw),
        F1(num_classes=NUM_CLASSES, average="macro", **kw),
        Precision(num_classes=NUM_CLASSES, average="macro", **kw),
        Recall(num_classes=NUM_CLASSES, average="macro", **kw),
    ], compute_groups=False)
    for m in col.values():
        m.sync_lag = sync_lag

    rng = np.random.RandomState(0)
    logits = rng.rand(ASYNC_FWD_ROWS, NUM_CLASSES).astype(np.float32)
    preds = jnp.asarray(logits / logits.sum(-1, keepdims=True))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, ASYNC_FWD_ROWS).astype(np.int32))

    def run(steps: int) -> float:
        start = time.perf_counter()
        for _ in range(steps):
            col(preds, target)
            if train_work_s:
                time.sleep(train_work_s)
        # the lag variant's last planes are still in flight: fencing them
        # keeps the measured window honest (it owns all the work it queued)
        for m in col.values():
            m._drain_handle_ring()
        return (time.perf_counter() - start) / steps * 1e3

    return run


def check_async() -> int:
    """``--check-async``: the deferred-sync regression gate (see the block
    comment above). Prints one JSON report line; non-zero exit on any broken
    contract."""
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, observability as obs
    from metrics_tpu.parallel.sync import gather_all_arrays

    failures = []

    # -- parity: identical staged collective count/kinds, zero new kinds ----
    obs.enable()
    run_fenced, _ = _build_async_sync8_runner(False)
    obs.COUNTERS.reset()
    run_fenced(1)  # first call traces+compiles: counters hold the staged program
    snap_sync = obs.counters_snapshot()
    run_async, _ = _build_async_sync8_runner(True)
    obs.COUNTERS.reset()
    run_async(1)
    snap_async = obs.counters_snapshot()
    obs.disable()
    parity = {
        "sync_calls_by_kind": snap_sync["calls_by_kind"],
        "async_calls_by_kind": snap_async["calls_by_kind"],
        "sync_bytes": snap_sync["sync_bytes"],
        "async_bytes": snap_async["sync_bytes"],
        "async_deferred": snap_async["deferred"],
    }
    if snap_async["calls_by_kind"] != snap_sync["calls_by_kind"]:
        failures.append(
            f"parity: deferred plane staged {snap_async['calls_by_kind']} vs the"
            f" synchronous plane's {snap_sync['calls_by_kind']} — the deferred"
            " dispatch must stage the identical collective count and kinds"
        )
    if snap_async["sync_bytes"] != snap_sync["sync_bytes"]:
        failures.append(
            f"parity: deferred plane moved {snap_async['sync_bytes']} bytes vs"
            f" {snap_sync['sync_bytes']} — same program, same payload"
        )
    if snap_async["deferred"]["dispatched"] != snap_async["deferred"]["fenced"]:
        failures.append(
            f"parity: {snap_async['deferred']['dispatched']} dispatches vs"
            f" {snap_async['deferred']['fenced']} fences — the A/B leaked a handle"
        )

    # -- lag-k: ring reads are the synchronous series k steps back ----------
    rng = np.random.RandomState(11)
    batches = []
    for _ in range(ASYNC_LAG_BATCHES):
        preds = jnp.asarray(rng.rand(128).astype(np.float32))
        target = jnp.asarray((rng.rand(128) > 0.5).astype(np.int32))
        batches.append((preds, target))
    sync_m = Accuracy(dist_sync_on_step=True, dist_sync_fn=gather_all_arrays)
    sync_vals = [np.asarray(sync_m(*b)) for b in batches]
    sync_epoch = np.asarray(sync_m.compute())
    lag_series = {}
    for k in ASYNC_LAG_DEPTHS:
        lag_m = Accuracy(dist_sync_on_step=True, dist_sync_fn=gather_all_arrays)
        lag_m.sync_lag = k
        lag_vals = [np.asarray(lag_m(*b)) for b in batches]
        lag_series[k] = lag_vals
        for i in range(ASYNC_LAG_BATCHES):
            # steps >= k read the k-step-lagged synchronous series; warm-up
            # steps read the local delta, which on a single process IS the
            # synced delta
            expect = sync_vals[i - k] if i >= k else sync_vals[i]
            if not np.array_equal(lag_vals[i], expect):
                failures.append(
                    f"lag: sync_lag={k} step {i} value {lag_vals[i]} != expected"
                    f" {expect} (the k-step-lag contract)"
                )
        if len(lag_m._handle_ring) != k:
            failures.append(
                f"lag: sync_lag={k} ring holds {len(lag_m._handle_ring)} handles"
                f" after the loop, expected {k}"
            )
        lag_epoch = np.asarray(lag_m.compute())
        if not np.array_equal(lag_epoch, sync_epoch):
            failures.append(
                f"lag: sync_lag={k} epoch compute {lag_epoch} != synchronous"
                f" {sync_epoch} — the accumulated state must not lag, only the"
                " per-step read"
            )
        if lag_m._handle_ring:
            failures.append(
                f"lag: sync_lag={k} epoch compute left {len(lag_m._handle_ring)}"
                " handles in the ring — it must drain in entry order"
            )

    # -- monotone: wall time non-increasing in lag depth (bursty DCN) -------
    sweep_runs = {k: _build_lag_sweep_runner(k) for k in ASYNC_LAG_DEPTHS}
    for run in sweep_runs.values():
        run(2)  # warm past compile noise
    sweep_times = {k: [] for k in ASYNC_LAG_DEPTHS}
    for r in range(ASYNC_SWEEP_REPEATS):
        # alternate depth order: a monotonic load drift must not bias the
        # deeper depths that would otherwise consistently run later
        order = ASYNC_LAG_DEPTHS if r % 2 == 0 else tuple(reversed(ASYNC_LAG_DEPTHS))
        for k in order:
            sweep_times[k].append(sweep_runs[k](ASYNC_SWEEP_STEPS))
    sweep_ms = {k: min(sweep_times[k]) for k in ASYNC_LAG_DEPTHS}
    for lo, hi in zip(ASYNC_LAG_DEPTHS, ASYNC_LAG_DEPTHS[1:]):
        if not sweep_ms[hi] <= sweep_ms[lo]:
            failures.append(
                f"monotone: lag={hi} step {sweep_ms[hi]:.4g} ms exceeds lag={lo}"
                f" step {sweep_ms[lo]:.4g} ms — a deeper ring must never be"
                " slower under the bursty simulated-DCN gather"
            )

    # -- auto: the adaptive controller picks 0 when free, >= 1 when slow ----
    from metrics_tpu.parallel.sync import packable_gather

    for _ in range(ASYNC_AUTO_ATTEMPTS):
        auto_free = Accuracy(dist_sync_on_step=True, dist_sync_fn=gather_all_arrays)
        auto_free.sync_lag = "auto"
        free_vals = [np.asarray(auto_free(*batches[i % ASYNC_LAG_BATCHES]))
                     for i in range(ASYNC_AUTO_STEPS)]
        free_lag = auto_free._lag_controller.lag
        if free_lag == 0:
            break
    if free_lag != 0:
        failures.append(
            f"auto: controller picked lag {free_lag} under the free collective"
            f" on every one of {ASYNC_AUTO_ATTEMPTS} attempts — a fast gather"
            " must stay synchronous (zero staleness)"
        )
    else:
        for i in range(ASYNC_LAG_BATCHES):
            # at lag 0 the auto plane IS the synchronous plane, bit-exactly
            if not np.array_equal(free_vals[i], sync_vals[i]):
                failures.append(
                    f"auto: lag-0 step {i} value {free_vals[i]} != synchronous"
                    f" {sync_vals[i]}"
                )

    @packable_gather
    def slow_gather(value):
        time.sleep(ASYNC_AUTO_SLOW_SLEEP_S)
        return [value]

    auto_slow = Accuracy(dist_sync_on_step=True, dist_sync_fn=slow_gather)
    auto_slow.sync_lag = "auto"
    for i in range(ASYNC_AUTO_STEPS):
        auto_slow(*batches[i % ASYNC_LAG_BATCHES])
    slow_lag = auto_slow._lag_controller.lag
    if slow_lag < 1:
        failures.append(
            f"auto: controller stayed at lag {slow_lag} under the slow gather"
            " — a blocking DCN wait must deepen the ring"
        )
    auto_slow._drain_handle_ring()

    # -- epoch: deferred _grouped_host_sync == synchronous, same gathers ----
    epoch_def, epoch_sync, epoch_calls_def, epoch_calls_sync = _bench_epoch_gather_parity()
    for name in epoch_sync:
        if not np.array_equal(epoch_def[name], epoch_sync[name]):
            failures.append(
                f"epoch: deferred grouped sync {name} = {epoch_def[name]} !="
                f" synchronous {epoch_sync[name]}"
            )
    if epoch_calls_def != epoch_calls_sync:
        failures.append(
            f"epoch: deferred grouped sync issued {epoch_calls_def} gather calls"
            f" vs the synchronous plane's {epoch_calls_sync} — same groups, same"
            " collectives, only the fence moves"
        )

    # -- overlap: the dist_sync_on_step forward loop under simulated DCN ----
    run_lag = _build_async_forward_runner(1)
    run_sync_fwd = _build_async_forward_runner(0)
    run_lag(2)  # warm both paths past compile noise
    run_sync_fwd(2)
    lag_times, sync_fwd_times = [], []
    for r in range(ASYNC_GATE_REPEATS):
        # alternate the pair order: the A/B is a difference of two absolute
        # measurements, and a monotonic load drift would otherwise bias
        # whichever variant consistently ran second
        order = (True, False) if r % 2 == 0 else (False, True)
        for is_lag in order:
            if is_lag:
                lag_times.append(run_lag(ASYNC_FWD_STEPS))
            else:
                sync_fwd_times.append(run_sync_fwd(ASYNC_FWD_STEPS))
    async_ms, fenced_ms = min(lag_times), min(sync_fwd_times)

    # device-plane fence-wait split: the deferred fence waits strictly less
    # host time than the synchronous block (the hidden wait IS the overlap)
    run_async(ASYNC_GATE_STEPS)  # warm past compile noise
    run_fenced(ASYNC_GATE_STEPS)
    device_async_times, device_fenced_times = [], []
    async_waits, fenced_waits = [], []
    for _ in range(3):
        device_async_times.append(run_async(ASYNC_GATE_STEPS))
        async_waits.append(run_async.last_wait_ms / ASYNC_GATE_STEPS)
        device_fenced_times.append(run_fenced(ASYNC_GATE_STEPS))
        fenced_waits.append(run_fenced.last_wait_ms / ASYNC_GATE_STEPS)
    device_async_ms, device_fenced_ms = min(device_async_times), min(device_fenced_times)
    async_wait, fenced_wait = min(async_waits), min(fenced_waits)

    overlap = {
        "async_step_ms": round(async_ms, 4),
        "sync_step_ms": round(fenced_ms, 4),
        "simulated_dcn_ms": ASYNC_DCN_SLEEP_S * 1e3,
        "steps": ASYNC_FWD_STEPS,
        "device_async_ms": round(device_async_ms, 4),
        "device_fenced_ms": round(device_fenced_ms, 4),
        "async_fence_wait_ms": round(async_wait, 4),
        "fenced_block_ms": round(fenced_wait, 4),
    }
    if not async_ms < fenced_ms:
        failures.append(
            f"overlap: sync_lag=1 step {async_ms:.4g} ms not strictly below the"
            f" synchronous step {fenced_ms:.4g} ms — the deferred plane is not"
            " hiding the gather wait behind the next step's update"
        )
    if not async_wait < fenced_wait:
        failures.append(
            f"overlap: deferred fences waited {async_wait:.4g} ms/step vs the"
            f" synchronous block's {fenced_wait:.4g} — the device dispatch is not"
            " running ahead of its fence"
        )

    print(json.dumps({
        "check": "async",
        "ok": not failures,
        "failures": failures,
        "parity": parity,
        "lag": {
            "sync_vals": [float(v) for v in sync_vals],
            "lag_vals": {str(k): [float(v) for v in lag_series[k]] for k in ASYNC_LAG_DEPTHS},
            "epoch": float(sync_epoch),
        },
        "lag_sweep": {
            "steps": ASYNC_SWEEP_STEPS,
            "burst_ms": ASYNC_SWEEP_BURST_S * 1e3,
            "burst_every": ASYNC_SWEEP_BURST_EVERY,
            "ms_by_lag": {str(k): round(sweep_ms[k], 4) for k in ASYNC_LAG_DEPTHS},
        },
        "auto": {"free_lag": free_lag, "slow_lag": slow_lag},
        "epoch_gather": {
            "deferred_calls": epoch_calls_def,
            "sync_calls": epoch_calls_sync,
        },
        "overlap": overlap,
    }))
    return 1 if failures else 0


# ------------------------------------------------------- serving-runtime gate
# --check-service soaks the windowed serving loop (wrappers/windowed.py +
# serving/service.py) end to end and pins the serving contract:
#   parity — the windowed metric's staged sync program is IDENTICAL to the
#            unwindowed metric's (psum-only; windows are a state axis,
#            never extra collectives)
#   clean  — a seeded event stream (in-order + late-within-lateness events)
#            through a real MetricService is BIT-EXACT vs a single-process
#            oracle: every published window, the merged sliding view, the
#            per-window sample counts (zero misrouted), the drop count, and
#            zero fault counters
#   chaos  — a seeded late-burst + ingest-stall + mid-window-preempt +
#            persistent-sync-drop schedule: the soak completes within the
#            deadline budget (degrade, never stall), every publish is
#            stamped degraded, degraded_computes and slab_dropped_samples
#            match their pins exactly, the preempted service resumes from
#            its snapshot with idempotent replay, and the values are still
#            bit-exact vs the oracle
SERVICE_SOAK_WINDOW_S = 10.0
SERVICE_SOAK_WINDOWS = 4
SERVICE_SOAK_LATENESS_S = 10.0
SERVICE_SOAK_BATCHES = 16
SERVICE_SOAK_BATCH = 32
SERVICE_SOAK_BUDGET_S = 60.0
SERVICE_LATE_SKEW_S = 25.0  # the late-burst shift (beyond allowed lateness)
SERVICE_LATE_CALLS = (2, 3)  # ingest calls the burst hits
SERVICE_PREEMPT_CALL = 8  # mid-window kill point


def _service_stream(seed: int = 0):
    """The seeded soak stream: SERVICE_SOAK_BATCHES batches whose event
    times mostly advance (5 s per batch) with ~15% late-within-lateness
    stragglers. Returns [(times float64 (B,), preds f32, target i32), ...]."""
    rng = np.random.RandomState(seed)
    batches = []
    for i in range(SERVICE_SOAK_BATCHES):
        preds = rng.rand(SERVICE_SOAK_BATCH).astype(np.float32)
        target = (rng.rand(SERVICE_SOAK_BATCH) > 0.5).astype(np.int32)
        times = i * 5.0 + rng.uniform(0.0, 5.0, SERVICE_SOAK_BATCH)
        late = rng.rand(SERVICE_SOAK_BATCH) < 0.15
        times = np.where(late, times - rng.uniform(0.0, 8.0, SERVICE_SOAK_BATCH), times)
        batches.append((times, preds, target))
    return batches


def _service_oracle(batches, shifts=None):
    """Single-process oracle: replay the stream's routing arithmetic in
    plain numpy (running-max watermark; accept iff the event's window is
    still open), then compute every window's value with a FRESH unwindowed
    metric over exactly its accepted events. ``shifts`` maps batch index ->
    event-time shift (the chaos schedule's late bursts, which the gate can
    reconstruct because the schedule is call-pinned)."""
    import jax.numpy as jnp

    from metrics_tpu import Accuracy

    window_s, num_windows = SERVICE_SOAK_WINDOW_S, SERVICE_SOAK_WINDOWS
    lateness = SERVICE_SOAK_LATENESS_S
    wm = None
    events = {}  # window -> [(pred, target), ...]
    dropped = 0
    for i, (times, preds, target) in enumerate(batches):
        t = np.asarray(times, dtype=np.float64) + (shifts or {}).get(i, 0.0)
        wm = float(t.max()) if wm is None else max(wm, float(t.max()))
        head = int(np.floor(wm / window_s))
        w = np.floor_divide(t, window_s).astype(np.int64)
        ok = ((w + 1) * window_s + lateness > wm) & (w > head - num_windows)
        dropped += int((~ok).sum())
        for j in np.nonzero(ok)[0]:
            events.setdefault(int(w[j]), []).append((preds[j], target[j]))
    origin = min(events) if events else head
    published = list(range(origin, head + 1))
    resident = [w for w in published if w > head - num_windows]

    def value(windows):
        pairs = [p for w in windows for p in events.get(w, [])]
        if not pairs:
            return np.asarray(np.nan, dtype=np.float32)
        metric = Accuracy()
        metric.update(
            jnp.asarray(np.array([p for p, _ in pairs], dtype=np.float32)),
            jnp.asarray(np.array([t for _, t in pairs], dtype=np.int32)),
        )
        return np.asarray(metric.compute())

    return {
        "published": published,
        "resident": resident,
        "values": {w: value([w]) for w in published},
        "merged": value(resident),
        "counts": {w: len(events.get(w, [])) for w in resident},
        "dropped": dropped,
        "head": head,
    }


def _drive_service(batches, schedule, guard):
    """Run the stream through a real MetricService (background worker,
    bounded queue) under ``schedule``; on a mid-window preempt, snapshot,
    build a FRESH service, restore, and replay from two steps BEFORE the
    snapshot point (exercising guarded_update idempotence). Returns the
    soak evidence for the gate's pins."""
    import contextlib

    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MetricService, Windowed
    from metrics_tpu.parallel import faults
    from metrics_tpu.parallel.sync import gather_all_arrays
    from metrics_tpu.serving.service import ServiceStoppedError
    from metrics_tpu.utils.exceptions import PreemptionError

    def build():
        metric = Windowed(
            Accuracy(), window_s=SERVICE_SOAK_WINDOW_S, num_windows=SERVICE_SOAK_WINDOWS,
            allowed_lateness_s=SERVICE_SOAK_LATENESS_S, dist_sync_fn=gather_all_arrays,
        )
        return MetricService(metric, queue_size=8, shed_policy="block", guard=guard)

    injector = faults.ChaosInjector(schedule, seed=0) if schedule else contextlib.nullcontext()
    publications = []
    preempted = False
    start = time.perf_counter()
    with injector:
        service = build()
        for i, (times, preds, target) in enumerate(batches):
            try:
                service.submit(jnp.asarray(preds), jnp.asarray(target), event_time=times, seq=i)
            except ServiceStoppedError:
                preempted = True
                break
        if not preempted:
            try:
                service.flush(SERVICE_SOAK_BUDGET_S)
            except PreemptionError:
                preempted = True
        if preempted:
            service._worker.join(timeout=10)
            snapshot = service.snapshot()
            publications += service.publications
            replacement = build()
            replacement.restore(snapshot)
            for i in range(max(0, snapshot["processed"] - 2), len(batches)):
                times, preds, target = batches[i]
                replacement.submit(
                    jnp.asarray(preds), jnp.asarray(target), event_time=times, seq=i
                )
            service = replacement
        merged = np.asarray(service.finalize(SERVICE_SOAK_BUDGET_S))
        publications += service.publications
        service.stop(SERVICE_SOAK_BUDGET_S)
    return {
        "service": service,
        "publications": publications,
        "merged": merged,
        "elapsed_s": time.perf_counter() - start,
        "preempted": preempted,
        "injected": dict(injector.injected) if schedule else {},
    }


def _check_service_soak(result, oracle, failures, label):
    """Shared clean/chaos assertions: publication coverage + bit-exactness,
    merged value, per-window counts (zero misrouted), drop count."""
    pubs = {p["window"]: p for p in result["publications"]}
    if sorted(pubs) != oracle["published"]:
        failures.append(
            f"{label}: published windows {sorted(pubs)} != oracle {oracle['published']}"
        )
    if len(result["publications"]) != len(pubs):
        failures.append(f"{label}: a window was published more than once")
    for w, expected in oracle["values"].items():
        got = pubs.get(w, {}).get("value")
        if got is None or not np.array_equal(got, expected, equal_nan=True):
            failures.append(f"{label}: window {w} value {got} != oracle {expected}")
    if not np.array_equal(result["merged"], oracle["merged"], equal_nan=True):
        failures.append(
            f"{label}: merged value {result['merged']} != oracle {oracle['merged']}"
        )
    metric = result["service"].metric
    rows = np.asarray(metric._current_state()["windowed_rows"])
    for w, count in oracle["counts"].items():
        got = int(rows[w % SERVICE_SOAK_WINDOWS])
        if got != count:
            failures.append(
                f"{label}: window {w} holds {got} samples, oracle routed {count}"
                " (misrouted or lost samples)"
            )
    if metric.dropped_samples != oracle["dropped"]:
        failures.append(
            f"{label}: metric dropped {metric.dropped_samples} samples,"
            f" oracle dropped {oracle['dropped']}"
        )
    if result["elapsed_s"] > SERVICE_SOAK_BUDGET_S:
        failures.append(
            f"{label}: soak took {result['elapsed_s']:.1f}s > {SERVICE_SOAK_BUDGET_S}s budget (hang?)"
        )


def check_service() -> int:
    """``--check-service``: the serving-runtime regression gate (see the
    block comment above). Prints one JSON report line; non-zero exit on any
    broken contract."""
    from metrics_tpu import observability as obs
    from metrics_tpu.parallel.faults import FaultSpec
    from metrics_tpu.parallel.sync import SyncGuard
    from metrics_tpu.serving.service import INGEST_SITE

    failures = []

    # -- parity: the windowed sync program == the unwindowed program --------
    obs.enable()
    parity = {}
    for name, windowed in (("windowed", True), ("unwindowed", False)):
        run, _ = _build_windowed_sync_runner(windowed)
        obs.COUNTERS.reset()
        run(1)  # first call traces+compiles: counters hold the staged program
        snap = obs.counters_snapshot()
        parity[name] = {
            "collective_calls": snap["collective_calls"],
            "sync_bytes": snap["sync_bytes"],
            "gather_calls": sum(
                snap["calls_by_kind"].get(k, 0)
                for k in ("all_gather", "coalesced_gather", "process_allgather")
            ),
            "calls_by_kind": snap["calls_by_kind"],
        }
    obs.disable()
    if parity["windowed"]["collective_calls"] != parity["unwindowed"]["collective_calls"]:
        failures.append(
            f"parity: windowed metric staged {parity['windowed']['collective_calls']}"
            f" collectives vs the unwindowed metric's"
            f" {parity['unwindowed']['collective_calls']} — window slots must be a"
            " state axis, never extra collectives"
        )
    if parity["windowed"]["gather_calls"] != 0:
        failures.append(
            f"parity: windowed sync staged {parity['windowed']['gather_calls']} gather"
            " collectives (the window plane must be psum-only)"
        )

    batches = _service_stream()
    guard = SyncGuard(deadline_s=2.0, max_retries=1, backoff_s=0.02, policy="degrade")

    # -- clean soak: bit-exact vs the oracle, zero faults -------------------
    obs.reset()
    clean = _drive_service(batches, schedule=None, guard=guard)
    clean_counters = obs.counters_snapshot()
    _check_service_soak(clean, _service_oracle(batches), failures, "clean")
    if any(clean_counters["faults"].values()):
        failures.append(f"clean soak reported nonzero fault counters: {clean_counters['faults']}")
    if clean.get("preempted"):
        failures.append("clean soak preempted without a schedule")
    if clean["service"].shed_events:
        failures.append(f"clean soak shed {clean['service'].shed_events} batches under backpressure")

    # -- chaos soak: late burst + ingest stall + mid-window preempt +
    #    persistent sync drop (every publish degrades, nothing stalls) ------
    schedule = [
        FaultSpec(kind="late_burst", call=SERVICE_LATE_CALLS[0],
                  times=len(SERVICE_LATE_CALLS), skew_s=SERVICE_LATE_SKEW_S, site=INGEST_SITE),
        FaultSpec(kind="ingest_stall", call=5, times=1, duration_s=0.2, site=INGEST_SITE),
        FaultSpec(kind="preempt", call=SERVICE_PREEMPT_CALL, times=1, site=INGEST_SITE),
        # rate=1.0 fires on EVERY gather call (deterministically): the
        # persistent-drop peer no sync can reach — every publish must
        # degrade to local-only state instead of stalling the stream
        FaultSpec(kind="drop", rate=1.0, times=100_000, site="host_gather"),
    ]
    shifts = {c: -SERVICE_LATE_SKEW_S for c in SERVICE_LATE_CALLS}
    obs.reset()
    chaos = _drive_service(batches, schedule=schedule, guard=guard)
    chaos_counters = obs.counters_snapshot()
    chaos_oracle = _service_oracle(batches, shifts=shifts)
    _check_service_soak(chaos, chaos_oracle, failures, "chaos")
    if not chaos["preempted"]:
        failures.append("chaos soak never hit the mid-window preempt")
    n_pubs = len(chaos["publications"])
    if not all(p["degraded"] for p in chaos["publications"]):
        failures.append("chaos soak published un-degraded values under a persistent sync drop")
    # every publish syncs exactly once and finalize syncs exactly once: the
    # degraded_computes pin is structural, not a lower bound
    expected_degraded = n_pubs + 1
    if chaos_counters["faults"]["degraded_computes"] != expected_degraded:
        failures.append(
            f"chaos soak degraded_computes ="
            f" {chaos_counters['faults']['degraded_computes']}, pinned"
            f" {expected_degraded} (one per publish + the finalize read)"
        )
    if chaos_counters["slab_dropped_samples"] != chaos_oracle["dropped"]:
        failures.append(
            f"chaos soak slab_dropped_samples ="
            f" {chaos_counters['slab_dropped_samples']}, pinned"
            f" {chaos_oracle['dropped']} (the late burst's too-late events)"
        )
    if chaos_oracle["dropped"] == 0:
        failures.append("chaos late burst dropped nothing; the schedule lost its teeth")

    print(json.dumps({
        "check": "service",
        "ok": not failures,
        "failures": failures,
        "parity": parity,
        "clean": {
            "published": sorted(p["window"] for p in clean["publications"]),
            "dropped": clean["service"].metric.dropped_samples,
            "elapsed_s": round(clean["elapsed_s"], 3),
            "faults": clean_counters["faults"],
        },
        "chaos": {
            "published": sorted(p["window"] for p in chaos["publications"]),
            "dropped": chaos["service"].metric.dropped_samples,
            "elapsed_s": round(chaos["elapsed_s"], 3),
            "budget_s": SERVICE_SOAK_BUDGET_S,
            "faults": chaos_counters["faults"],
            "slab_dropped_samples": chaos_counters["slab_dropped_samples"],
            "injected": chaos["injected"],
            "preempted": chaos["preempted"],
        },
    }))
    return 1 if failures else 0


# --check-ingest pins the ingest fast path (queue-drain coalescing + the
# bucketed compiled routing plane) behind three tiers:
#   parity     — a seeded 200-batch stream with ~15% late-within-lateness
#                stragglers, driven through a coalescing service and the
#                one-batch twin: EVERY published record (window, start,
#                value, merged view, degraded/final flags, drop count) is
#                bit-exact, drop and replay counts match, the final merged
#                view matches, and the bucketed program cache stops missing
#                after the steady-state segment (zero recompiles: a second
#                identically-shaped stream segment may not grow the miss
#                count)
#   throughput — the bursty-producer A/B (_bench_ingest_coalesce): the
#                coalescing drain loop must clear >= 2x the one-batch
#                twin's batches/sec, and the batches-per-drain factor must
#                show coalescing actually engaged
#   chaos      — a call-pinned mid-span preempt: worker dies between spans,
#                post-mortem snapshot, fresh service, restore, replay from
#                two steps BEFORE the snapshot point with the original seq
#                ids — zero lost windows, zero double publishes, values
#                still bit-exact vs the uncoalesced twin under the same
#                schedule, and guarded_update's span watermark skips the
#                already-folded replays on both sides
#
# Exactness caveat: the tiers accumulate integer-valued counts (Accuracy's
# correct/total), where float addition is exact — so coalesced
# segment-sums match sequential scatters BIT-exactly. Metrics whose
# accumulators are arbitrary floats may reassociate within a span (same
# caveat as any batched reduction); docs/streaming.md spells this out.

INGEST_PARITY_BATCHES = 200
INGEST_PARITY_BATCH = 32
INGEST_PARITY_WINDOW_S = 5.0
INGEST_PARITY_LATENESS_S = 10.0
INGEST_PARITY_WINDOWS = 4
INGEST_CHAOS_BATCHES = 120
INGEST_CHAOS_BATCH = 16
INGEST_CHAOS_PREEMPT_CALL = 37
INGEST_GATE_BUDGET_S = 120.0


def _ingest_stream(batches: int, batch: int, seed: int = 11):
    """Seeded gate stream: event times advance ~1 s per batch with ~15%
    late-within-lateness stragglers (so spans carry genuinely out-of-order
    events and window closes split them) and ~3% BEYOND-lateness events (so
    the per-event prefix judge must produce the exact same drop verdicts as
    the sequential plane). Returns [(times, preds, target)]."""
    rng = np.random.RandomState(seed)
    out = []
    for i in range(batches):
        preds = rng.rand(batch).astype(np.float32)
        target = (rng.rand(batch) > 0.5).astype(np.int32)
        times = i * 1.0 + rng.uniform(0.0, 1.0, batch)
        late = rng.rand(batch) < 0.15
        times = np.where(late, times - rng.uniform(0.0, 8.0, batch), times)
        too_late = rng.rand(batch) < 0.03
        times = np.where(too_late, times - rng.uniform(20.0, 30.0, batch), times)
        out.append((times, preds, target))
    return out


def _drive_ingest(batches, coalesce, schedule=None, extra=None):
    """Drive the stream through a MetricService with coalescing on
    (max_batches=8) or off (=1); synchronous publishes so the record order
    is deterministic. Under a preempt ``schedule``, runs the post-mortem
    failover protocol (worker join -> snapshot -> fresh service -> restore
    -> replay from processed-2 with the ORIGINAL seq ids). ``extra`` is a
    second identically-shaped stream segment submitted after the cache-miss
    checkpoint — the steady-state recompile probe."""
    import contextlib

    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MetricService, Windowed
    from metrics_tpu.parallel import faults
    from metrics_tpu.serving.service import ServiceStoppedError
    from metrics_tpu.utils.exceptions import PreemptionError

    def build():
        metric = Windowed(
            Accuracy(), window_s=INGEST_PARITY_WINDOW_S,
            num_windows=INGEST_PARITY_WINDOWS,
            allowed_lateness_s=INGEST_PARITY_LATENESS_S,
        )
        return MetricService(
            metric, queue_size=len(batches) + len(extra or ()),
            coalesce_max_batches=(8 if coalesce else 1),
            deferred_publish=False,
        )

    injector = faults.ChaosInjector(schedule, seed=0) if schedule else contextlib.nullcontext()
    publications = []
    preempted = False
    with injector:
        service = build()
        for i, (times, preds, target) in enumerate(batches):
            try:
                service.submit(jnp.asarray(preds), jnp.asarray(target),
                               event_time=times, seq=i)
            except ServiceStoppedError:
                preempted = True
                break
        if not preempted:
            try:
                service.flush(INGEST_GATE_BUDGET_S)
            except PreemptionError:
                preempted = True
        if preempted:
            service._worker.join(timeout=10)
            snapshot = service.snapshot()  # post-mortem: past the preempt call
            publications += service.publications
            replacement = build()
            replacement.restore(snapshot)
            for i in range(max(0, snapshot["processed"] - 2), len(batches)):
                times, preds, target = batches[i]
                replacement.submit(jnp.asarray(preds), jnp.asarray(target),
                                   event_time=times, seq=i)
            service = replacement
        service.flush(INGEST_GATE_BUDGET_S)
        misses_mark = len(service.metric._ingest_programs)
        for i, (times, preds, target) in enumerate(extra or ()):
            service.submit(jnp.asarray(preds), jnp.asarray(target),
                           event_time=times, seq=len(batches) + i)
        merged = np.asarray(service.finalize(INGEST_GATE_BUDGET_S))
        publications += service.publications
        misses_end = len(service.metric._ingest_programs)
        out = {
            "publications": publications,
            "merged": merged,
            "dropped": service.metric.dropped_samples,
            "processed": service.processed,
            "replayed": service.replayed_steps,
            "drains": service.drains,
            "coalesced_batches": service.coalesced_batches,
            "misses_mark": misses_mark,
            "misses_end": misses_end,
            "preempted": preempted,
        }
        service.stop(INGEST_GATE_BUDGET_S)
    return out


def _check_ingest_parity(on, off, failures, label):
    """Record-by-record bit-exactness of the coalescing service vs the
    one-batch twin, plus the drop/replay/merged pins."""
    if len(on["publications"]) != len(off["publications"]):
        failures.append(
            f"{label}: coalescing published {len(on['publications'])} records,"
            f" the one-batch twin {len(off['publications'])}"
        )
    for a, b in zip(on["publications"], off["publications"]):
        for key in ("window", "window_start_s", "degraded", "final", "dropped_samples"):
            if a.get(key) != b.get(key):
                failures.append(
                    f"{label}: record for window {a.get('window')} differs on"
                    f" {key!r}: {a.get(key)!r} != {b.get(key)!r}"
                )
        for key in ("value", "merged"):
            if not np.array_equal(np.asarray(a[key]), np.asarray(b[key]), equal_nan=True):
                failures.append(
                    f"{label}: window {a['window']} {key} {a[key]} !="
                    f" twin's {b[key]} (coalescing changed published bits)"
                )
    windows = [p["window"] for p in on["publications"] if p["final"]]
    if len(windows) != len(set(windows)):
        failures.append(f"{label}: coalescing double-published a window: {sorted(windows)}")
    if not np.array_equal(on["merged"], off["merged"], equal_nan=True):
        failures.append(
            f"{label}: final merged view {on['merged']} != twin's {off['merged']}"
        )
    if on["dropped"] != off["dropped"]:
        failures.append(
            f"{label}: coalescing dropped {on['dropped']} samples, twin {off['dropped']}"
        )
    if on["replayed"] != off["replayed"]:
        failures.append(
            f"{label}: coalescing replayed {on['replayed']} steps, twin {off['replayed']}"
        )


def check_ingest() -> int:
    """``--check-ingest``: the ingest fast-path regression gate (see the
    block comment above). Prints one JSON report line; non-zero exit on any
    broken contract."""
    from metrics_tpu.parallel.faults import FaultSpec
    from metrics_tpu.serving.service import INGEST_SITE

    failures = []

    # -- parity: coalescing on vs off, bit-exact records + recompile pin ----
    stream = _ingest_stream(INGEST_PARITY_BATCHES, INGEST_PARITY_BATCH)
    tail = _ingest_stream(40, INGEST_PARITY_BATCH, seed=13)
    base = INGEST_PARITY_BATCHES * 1.0
    tail = [(t + base, p, y) for (t, p, y) in tail]  # keep event time advancing
    on = _drive_ingest(stream, coalesce=True, extra=tail)
    off = _drive_ingest(stream, coalesce=False, extra=tail)
    _check_ingest_parity(on, off, failures, "parity")
    if on["coalesced_batches"] == 0:
        failures.append("parity: coalescing never engaged (0 coalesced batches)")
    if on["dropped"] == 0:
        failures.append(
            "parity: the beyond-lateness stragglers dropped nothing; the"
            " stream lost its teeth"
        )
    if on["misses_end"] != on["misses_mark"]:
        failures.append(
            f"parity: steady-state recompiles — the bucketed program cache grew"
            f" from {on['misses_mark']} to {on['misses_end']} entries over an"
            " identically-shaped stream segment"
        )

    # -- throughput: the bursty A/B must clear 2x -------------------------
    bench = _bench_ingest_coalesce()
    if bench["coalesced_steps_per_s"] < 2.0 * bench["uncoalesced_steps_per_s"]:
        failures.append(
            f"throughput: coalesced {bench['coalesced_steps_per_s']:.1f} steps/s"
            f" < 2x the one-batch twin's {bench['uncoalesced_steps_per_s']:.1f}"
        )
    if bench["coalesce_factor"] < 2.0:
        failures.append(
            f"throughput: coalesce factor {bench['coalesce_factor']:.2f} < 2"
            " (the drain loop stopped batching the backlog)"
        )

    # -- chaos: mid-span preempt + post-mortem failover -------------------
    chaos_stream = _ingest_stream(INGEST_CHAOS_BATCHES, INGEST_CHAOS_BATCH, seed=23)
    schedule = [FaultSpec(kind="preempt", call=INGEST_CHAOS_PREEMPT_CALL, times=1,
                          site=INGEST_SITE)]
    chaos_on = _drive_ingest(chaos_stream, coalesce=True, schedule=schedule)
    chaos_off = _drive_ingest(chaos_stream, coalesce=False, schedule=schedule)
    _check_ingest_parity(chaos_on, chaos_off, failures, "chaos")
    if not chaos_on["preempted"] or not chaos_off["preempted"]:
        failures.append("chaos: the call-pinned preempt never fired")
    if chaos_on["replayed"] == 0:
        failures.append(
            "chaos: replay-from-before-the-snapshot folded zero already-applied"
            " steps (the idempotence path went untested)"
        )

    print(json.dumps({
        "check": "ingest",
        "ok": not failures,
        "failures": failures,
        "parity": {
            "records": len(on["publications"]),
            "drains": on["drains"],
            "coalesced_batches": on["coalesced_batches"],
            "dropped": on["dropped"],
            "program_cache_entries": on["misses_end"],
        },
        "throughput": {k: (round(v, 3) if isinstance(v, float) else v)
                       for k, v in bench.items()},
        "chaos": {
            "records": len(chaos_on["publications"]),
            "replayed_on": chaos_on["replayed"],
            "replayed_off": chaos_off["replayed"],
            "coalesced_batches": chaos_on["coalesced_batches"],
            "preempted": chaos_on["preempted"],
        },
    }))
    return 1 if failures else 0


# --check-fleet soaks the sharded serving fleet (serving/fleet.py) end to
# end and pins the scale-out contract:
#   exact   — the merged fleet output (per-window records, sample counts,
#             the global sliding view) is BIT-EXACT vs a single-process
#             oracle at shard counts {1, 2, 8}: hash partitioning + merge by
#             pure state addition loses nothing and double-counts nothing
#   scaling — ingest throughput over the simulated per-batch serving work
#             is near-linear in shard count (8-shard >= 4x 1-shard on the
#             CI host; sleeps overlap perfectly, so the ratio isolates the
#             fleet's routing/queueing path)
#   chaos   — a seeded FaultSpec(site="fleet.shard", shard=i) schedule
#             stalls one shard and KILLS another mid-stream; recover_shard
#             (snapshot/restore + replay-log overlap replay through
#             guarded_update) brings it back with ZERO lost windows, no
#             double-published merged window, and values still bit-exact


def _fleet_factory():
    from metrics_tpu import Accuracy, Windowed
    from metrics_tpu.parallel.sync import gather_all_arrays

    return Windowed(
        Accuracy(), window_s=FLEET_WINDOW_S, num_windows=FLEET_WINDOWS,
        allowed_lateness_s=FLEET_LATENESS_S, dist_sync_fn=gather_all_arrays,
    )


def _fleet_tenants(per_shard: int, shards: int = FLEET_SHARDS):
    """A deterministic tenant population balanced across the stable hash's
    ``shards`` buckets (exactly ``per_shard`` keys per bucket) — the many-
    tenant limit where hash partitioning balances load, without multinomial
    wobble at bench-sized populations."""
    from metrics_tpu.serving import shard_for_key

    keys, buckets = [], {s: 0 for s in range(shards)}
    candidate = 0
    while any(count < per_shard for count in buckets.values()):
        key = f"tenant-{candidate}"
        candidate += 1
        bucket = shard_for_key(key, shards)
        if buckets[bucket] < per_shard:
            buckets[bucket] += 1
            keys.append(key)
    return keys


def _fleet_stream(n_batches: int, batch: int, seed: int = 0, step_s: float = 2.5,
                  straggle_s: float = 8.0):
    """The seeded fleet soak stream: tenant-keyed batches whose event times
    advance ``step_s`` per batch with ~15% late-within-lateness stragglers
    (never beyond ``FLEET_LATENESS_S``, so no routing verdict depends on
    which shard's watermark judged it — the bit-exactness precondition).
    Returns ``[(key, times, preds, target), ...]``."""
    rng = np.random.RandomState(seed)
    keys = _fleet_tenants(max(n_batches // FLEET_SHARDS, 1))
    out = []
    for i in range(n_batches):
        times = i * step_s + rng.uniform(0.0, step_s, batch)
        if straggle_s > 0:
            late = rng.rand(batch) < 0.15
            times = np.where(late, times - rng.uniform(0.0, straggle_s, batch), times)
        out.append((
            keys[i % len(keys)], times,
            rng.rand(batch).astype(np.float32),
            (rng.rand(batch) > 0.5).astype(np.int32),
        ))
    return out


def _fleet_oracle(batches):
    """Single-process oracle over the fleet stream: global-watermark routing
    in plain numpy, every window's value from a FRESH unwindowed metric over
    exactly its events (keys ignored — partitioning must not change any
    value)."""
    import jax.numpy as jnp

    from metrics_tpu import Accuracy

    window_s, num_windows, lateness = FLEET_WINDOW_S, FLEET_WINDOWS, FLEET_LATENESS_S
    wm, events, dropped = None, {}, 0
    for _key, times, preds, target in batches:
        t = np.asarray(times, dtype=np.float64)
        wm = float(t.max()) if wm is None else max(wm, float(t.max()))
        head = int(np.floor(wm / window_s))
        w = np.floor_divide(t, window_s).astype(np.int64)
        ok = ((w + 1) * window_s + lateness > wm) & (w > head - num_windows)
        dropped += int((~ok).sum())
        for j in np.nonzero(ok)[0]:
            events.setdefault(int(w[j]), []).append((preds[j], target[j]))
    origin = min(events)
    published = list(range(origin, head + 1))
    resident = [w for w in published if w > head - num_windows]

    def value(windows):
        pairs = [p for w in windows for p in events.get(w, [])]
        if not pairs:
            return np.asarray(np.nan, dtype=np.float32)
        metric = Accuracy()
        metric.update(
            jnp.asarray(np.array([p for p, _ in pairs], dtype=np.float32)),
            jnp.asarray(np.array([t for _, t in pairs], dtype=np.int32)),
        )
        return np.asarray(metric.compute())

    return {
        "published": published,
        "values": {w: value([w]) for w in published},
        "merged": value(resident),
        "counts": {w: len(events.get(w, [])) for w in published},
        "dropped": dropped,
    }


def _drive_fleet(batches, num_shards: int, schedule=None, recover: bool = False):
    """Run the stream through a real ``MetricFleet`` (N background workers,
    the stable-hash router, the merge tier) under ``schedule``; with
    ``recover``, bring killed shards back via ``recover_shard`` (fresh
    snapshot + replay-log overlap replay) and keep going. Returns the soak
    evidence for the gate's pins."""
    import contextlib

    import jax.numpy as jnp

    from metrics_tpu import MetricFleet
    from metrics_tpu.parallel import faults
    from metrics_tpu.serving import ShardStoppedError

    injector = faults.ChaosInjector(schedule, seed=0) if schedule else contextlib.nullcontext()
    recoveries = 0
    start = time.perf_counter()
    with injector:
        fleet = MetricFleet(_fleet_factory, num_shards=num_shards, queue_size=64)
        with fleet:
            for key, times, preds, target in batches:
                try:
                    fleet.submit(key, jnp.asarray(preds), jnp.asarray(target), event_time=times)
                except ShardStoppedError as err:
                    if not recover:
                        raise
                    # the failed submission is already in the replay log:
                    # recovery delivers it — no re-submit
                    fleet.recover_shard(err.shard)
                    recoveries += 1
            try:
                fleet.flush(FLEET_SOAK_BUDGET_S)
            except Exception:
                if not recover:
                    raise
                # a kill after the last submission to that shard surfaces at
                # the flush barrier — recover and drain again
                for index, service in enumerate(fleet.shards):
                    if service.state != "running":
                        fleet.recover_shard(index)
                        recoveries += 1
                fleet.flush(FLEET_SOAK_BUDGET_S)
            merged = np.asarray(fleet.finalize(FLEET_SOAK_BUDGET_S))
            records = list(fleet.merged_records)
            replayed = sum(s.replayed_steps for s in fleet.shards)
            published = sum(len(s.publications) for s in fleet.shards)
            dropped = sum(s.metric.dropped_samples for s in fleet.shards)
    return {
        "records": records,
        "merged": merged,
        "elapsed_s": time.perf_counter() - start,
        "replayed": replayed,
        "recoveries": recoveries,
        "published": published,
        "dropped": dropped,
        "injected": dict(injector.injected) if schedule else {},
    }


def _bench_fleet_ingest(num_shards: int, batches=None) -> float:
    """Sustained batches/sec through a real ``MetricFleet`` ingest loop at
    ``num_shards`` shards, under the simulated per-batch serving work
    (``FLEET_WORK_S`` ingest_stall at the fleet.shard site, every call).
    Sleeps overlap across shard workers, so this measures how well the
    fleet's routing/queueing path actually parallelizes — the
    ``fleet_ingest_steps_per_s`` scaling headline."""
    import jax.numpy as jnp

    from metrics_tpu import MetricFleet
    from metrics_tpu.parallel import faults
    from metrics_tpu.serving.fleet import FLEET_SITE

    if batches is None:
        batches = _fleet_stream(FLEET_SCALE_BATCHES, FLEET_SCALE_BATCH, seed=1,
                                step_s=1.5, straggle_s=0.0)
    batches = [
        (key, times, jnp.asarray(preds), jnp.asarray(target))
        for key, times, preds, target in batches
    ]
    # warm the eager scatter path's compile cache outside the timed loop
    warm = _fleet_factory()
    warm.update(batches[0][2], batches[0][3], event_time=batches[0][1])
    schedule = [faults.FaultSpec(kind="ingest_stall", rate=1.0,
                                 duration_s=FLEET_WORK_S, site=FLEET_SITE)]
    with faults.ChaosInjector(schedule, seed=0):
        with MetricFleet(_fleet_factory, num_shards=num_shards,
                         queue_size=len(batches)) as fleet:
            start = time.perf_counter()
            for key, times, preds, target in batches:
                fleet.submit(key, preds, target, event_time=times)
            fleet.flush(FLEET_SOAK_BUDGET_S)
            elapsed = time.perf_counter() - start
    return len(batches) / max(elapsed, 1e-9)


def _check_fleet_exact(result, oracle, failures, label):
    """Shared merged-output assertions: window coverage, exactly-once in
    order, bit-exact values, per-window sample counts (zero lost, zero
    misrouted, zero double-counted), the global merged view."""
    windows = [r["window"] for r in result["records"]]
    if windows != sorted(set(windows)):
        failures.append(f"{label}: merged windows {windows} out of order or duplicated")
    if sorted(set(windows)) != oracle["published"]:
        failures.append(
            f"{label}: merged windows {sorted(set(windows))} != oracle {oracle['published']}"
            " (lost windows)"
        )
    for record in result["records"]:
        expected = oracle["values"].get(record["window"])
        if expected is None or not np.array_equal(record["value"], expected, equal_nan=True):
            failures.append(
                f"{label}: window {record['window']} merged value {record['value']}"
                f" != oracle {expected}"
            )
        count = oracle["counts"].get(record["window"])
        if count is not None and record["rows"] != count:
            failures.append(
                f"{label}: window {record['window']} merged {record['rows']} samples,"
                f" oracle routed {count} (misrouted, lost or double-counted)"
            )
    if not np.array_equal(result["merged"], oracle["merged"], equal_nan=True):
        failures.append(
            f"{label}: merged sliding view {result['merged']} != oracle {oracle['merged']}"
        )
    if result["dropped"] != oracle["dropped"]:
        failures.append(
            f"{label}: shards dropped {result['dropped']} events, oracle {oracle['dropped']}"
        )
    if result["elapsed_s"] > FLEET_SOAK_BUDGET_S:
        failures.append(
            f"{label}: soak took {result['elapsed_s']:.1f}s > {FLEET_SOAK_BUDGET_S}s budget (hang?)"
        )


def check_fleet() -> int:
    """``--check-fleet``: the sharded-serving regression gate (see the block
    comment above). Prints one JSON report line; non-zero exit on any broken
    contract."""
    from metrics_tpu.parallel.faults import FaultSpec
    from metrics_tpu.serving import shard_for_key
    from metrics_tpu.serving.fleet import FLEET_SITE

    failures = []

    # -- exact: merged output bit-exact vs the oracle at {1, 2, 8} shards --
    batches = _fleet_stream(FLEET_EXACT_BATCHES, FLEET_EXACT_BATCH)
    oracle = _fleet_oracle(batches)
    exact = {}
    for num_shards in (1, 2, FLEET_SHARDS):
        result = _drive_fleet(batches, num_shards)
        _check_fleet_exact(result, oracle, failures, f"exact[{num_shards}]")
        exact[str(num_shards)] = {
            "merged_windows": len(result["records"]),
            "shard_publishes": result["published"],
            "elapsed_s": round(result["elapsed_s"], 3),
        }

    # -- scaling: 8-shard ingest throughput >= 4x 1-shard ------------------
    # wall-clock throughput under box load is noisy: a background spike
    # during either measurement sinks the ratio. Best-of-N with FRESH
    # measurement pairs (the --check-async auto gate's retry idiom) — a real
    # serialization regression fails all attempts, a load blip passes one.
    for _ in range(ASYNC_AUTO_ATTEMPTS):
        sps_1 = _bench_fleet_ingest(1)
        sps_8 = _bench_fleet_ingest(FLEET_SHARDS)
        scaling_x = sps_8 / max(sps_1, 1e-9)
        if scaling_x >= FLEET_SCALING_MIN_X:
            break
    if scaling_x < FLEET_SCALING_MIN_X:
        failures.append(
            f"scaling: 8-shard ingest {sps_8:.1f}/s is only {scaling_x:.2f}x the"
            f" 1-shard {sps_1:.1f}/s on every one of {ASYNC_AUTO_ATTEMPTS}"
            f" attempts (gate: >= {FLEET_SCALING_MIN_X}x) — something"
            " global serializes the shard workers"
        )

    # -- chaos: stall one shard, KILL another mid-stream, recover ----------
    kill_shard = shard_for_key(batches[2][0], FLEET_KILL_SHARDS)
    stall_shard = (kill_shard + 1) % FLEET_KILL_SHARDS
    schedule = [
        FaultSpec(kind="preempt", call=FLEET_KILL_CALL, times=1,
                  site=FLEET_SITE, shard=kill_shard),
        FaultSpec(kind="ingest_stall", call=1, times=2, duration_s=0.1,
                  site=FLEET_SITE, shard=stall_shard),
    ]
    chaos = _drive_fleet(batches, FLEET_KILL_SHARDS, schedule=schedule, recover=True)
    _check_fleet_exact(chaos, oracle, failures, "chaos")
    if chaos["injected"].get("preempt") != 1:
        failures.append(f"chaos: expected exactly one shard kill, injected {chaos['injected']}")
    if chaos["injected"].get("ingest_stall", 0) < 1:
        failures.append("chaos: the shard stall never fired")
    if chaos["recoveries"] < 1:
        failures.append("chaos: the killed shard was never recovered")
    if chaos["replayed"] < 1:
        failures.append(
            "chaos: the overlap replay never hit the epoch watermark — replay"
            " idempotence went unexercised"
        )

    print(json.dumps({
        "check": "fleet",
        "ok": not failures,
        "failures": failures,
        "exact": exact,
        "scaling": {
            "steps_per_s_1shard": round(sps_1, 3),
            "steps_per_s_8shard": round(sps_8, 3),
            "x": round(scaling_x, 3),
            "min_x": FLEET_SCALING_MIN_X,
            "work_s": FLEET_WORK_S,
        },
        "chaos": {
            "merged_windows": len(chaos["records"]),
            "recoveries": chaos["recoveries"],
            "replayed": chaos["replayed"],
            "injected": chaos["injected"],
            "elapsed_s": round(chaos["elapsed_s"], 3),
            "budget_s": FLEET_SOAK_BUDGET_S,
        },
    }))
    return 1 if failures else 0


# --check-watermark pins the rank-coherent streaming contract (cross-rank
# watermark agreement + skew-tolerant closing + sliding windows):
#   parity   — a windowed metric UNDER a WatermarkAgreement stages the
#              IDENTICAL in-jit sync program as the unwindowed metric (the
#              exchange is host-plane only: zero staged collectives, zero
#              gathers, pinned by counters)
#   coherent — WM_RANKS rank services share one agreement; a seeded
#              +WM_SKEW_S clock_skew on one rank and a late burst on
#              another: NO window publishes before every participating
#              rank's watermark passes it (checked each lockstep round
#              against the reported local watermarks), the skewed rank's
#              local clock provably ran ahead of the agreed frontier, and
#              all published windows + merged views are BIT-EXACT vs a
#              single-process oracle over the union stream (zero lost, zero
#              double-published, zero drops — late-within-lateness events
#              route, "late" means the same thing on every rank)
#   stall    — one rank stalls at rate=1.0: closing proceeds once the
#              agreement deadline excludes it (wm_stragglers > 0), the
#              publishes stamp degraded=True, and no peer deadlocks
#              (finalize completes inside the budget)
#   sliding  — slide_s < window_s: every published sliding window is
#              bit-exact vs an independent per-slot oracle over exactly the
#              events its [w*slide, w*slide + window) span covers


def _wm_rank_stream(seed: int = 0):
    """The coherence soak's lockstep stream: WM_BATCHES rounds, one batch
    per rank per round, event times advancing ~half a window per round with
    jitter. Returns ``rounds[r][rank] = (times, preds, target)``."""
    rng = np.random.RandomState(seed)
    rounds = []
    for r in range(WM_BATCHES):
        per_rank = []
        for _rank in range(WM_RANKS):
            times = r * 5.0 + rng.uniform(0.0, 5.0, WM_BATCH)
            preds = rng.rand(WM_BATCH).astype(np.float32)
            target = (rng.rand(WM_BATCH) > 0.5).astype(np.int32)
            per_rank.append((times, preds, target))
        rounds.append(per_rank)
    return rounds


def _wm_shifts():
    """Per-(round, rank) event-time shifts the chaos schedule applies — the
    oracle reconstructs them because the schedule is call/rate pinned."""
    shifts = {}
    for r in range(WM_BATCHES):
        shifts[(r, WM_SKEW_RANK)] = WM_SKEW_S  # rate=1.0: every batch
    shifts[(WM_LATE_CALL, WM_LATE_RANK)] = -WM_LATE_SKEW_S
    return shifts


def _wm_oracle(rounds, shifts):
    """Single-process oracle over the UNION of all ranks' (shifted) streams.

    Under the AGREED clock the seeded stream is constructed to never drop:
    the agreed (min-rank) watermark trails every rank's newest events, and
    the late burst stays within the lateness of the minimum clock at its
    round — so the oracle is pure membership, window ``w`` holding every
    (shifted) event with ``floor(t / window_s) == w``. (This is exactly the
    coherence claim: judged by the agreed clock, a skewed peer cannot make
    an honest rank's in-time events "late". The LOCAL-clock replay of the
    same stream drops hundreds of them — the contrast the gate exists for.)
    """
    import jax.numpy as jnp

    from metrics_tpu import Accuracy

    events, head = {}, None
    for r, per_rank in enumerate(rounds):
        for rank, (times, preds, target) in enumerate(per_rank):
            t = np.asarray(times, dtype=np.float64) + shifts.get((r, rank), 0.0)
            w = np.floor_divide(t, WM_WINDOW_S).astype(np.int64)
            for j in range(t.size):
                events.setdefault(int(w[j]), []).append((preds[j], target[j]))
            hi = int(np.floor(float(t.max()) / WM_WINDOW_S))
            head = hi if head is None else max(head, hi)
    published = list(range(min(events), head + 1))

    def value(windows):
        pairs = [p for w in windows for p in events.get(w, [])]
        if not pairs:
            return np.asarray(np.nan, dtype=np.float32)
        metric = Accuracy()
        metric.update(
            jnp.asarray(np.array([p for p, _ in pairs], dtype=np.float32)),
            jnp.asarray(np.array([t for _, t in pairs], dtype=np.int32)),
        )
        return np.asarray(metric.compute())

    return {
        "published": published,
        "values": {w: value([w]) for w in published},
        "counts": {w: len(events.get(w, [])) for w in published},
        "head": head,
    }


def _wm_build_ranks(n_ranks: int, deadline_s: float, guard):
    """N rank MetricServices over one shared WatermarkAgreement (rank i is
    fault-addressable via FaultSpec(rank=i)). Returns (agreement, services,
    partials) where partials[window][rank] collects each rank's published
    window partial for the merge check."""
    import threading

    from metrics_tpu import Accuracy, MetricService, WatermarkAgreement, Windowed
    from metrics_tpu.parallel.sync import gather_all_arrays

    agreement = WatermarkAgreement(deadline_s=deadline_s, label="gate/wm")
    partials: dict = {}
    lock = threading.Lock()
    services = []
    for rank in range(n_ranks):
        metric = Windowed(
            Accuracy(), window_s=WM_WINDOW_S, num_windows=WM_WINDOWS,
            allowed_lateness_s=WM_LATENESS_S, dist_sync_fn=gather_all_arrays,
            agreement=agreement, rank=rank,
        )

        def tap(record, partial, _rank=rank):
            with lock:
                partials.setdefault(int(record["window"]), {})[_rank] = partial

        services.append(MetricService(
            metric, queue_size=16, guard=guard, fault_rank=rank,
            partial_publish_fn=tap,
        ))
    return agreement, services, partials


def _wm_drive_coherent(failures):
    """The coherence soak: lockstep rounds through the rank services under
    the seeded skew + late-burst schedule, with the publish-ordering pin
    checked against the reported local watermarks after every round."""
    import jax.numpy as jnp

    from metrics_tpu.parallel import faults
    from metrics_tpu.parallel.sync import SyncGuard

    rounds = _wm_rank_stream()
    shifts = _wm_shifts()
    guard = SyncGuard(deadline_s=2.0, max_retries=1, backoff_s=0.02, policy="degrade")
    schedule = [
        faults.FaultSpec(kind="clock_skew", rank=WM_SKEW_RANK, rate=1.0,
                         times=10**6, skew_s=WM_SKEW_S, site="service.ingest"),
        faults.FaultSpec(kind="late_burst", rank=WM_LATE_RANK, call=WM_LATE_CALL,
                         times=1, skew_s=WM_LATE_SKEW_S, site="service.ingest"),
    ]
    start = time.perf_counter()
    skew_ran_ahead = False
    with faults.ChaosInjector(schedule, seed=0) as injector:
        agreement, services, partials = _wm_build_ranks(WM_RANKS, 3600.0, guard)
        for r in range(WM_BATCHES):
            for rank, (times, preds, target) in enumerate(rounds[r]):
                services[rank].submit(
                    jnp.asarray(preds), jnp.asarray(target), event_time=times, seq=r
                )
            for service in services:
                service.flush(WM_BUDGET_S)
            # the ordering pin: every window ANY rank has published so far
            # must already be closed by EVERY rank's reported watermark —
            # min local wm is monotone, so a premature publish (a window
            # ahead of the agreed frontier, e.g. closed by the skewed
            # rank's local clock alone) stays visible at this check
            local_wms = [
                wm for wm in agreement.local_watermarks().values() if wm is not None
            ]
            min_wm = min(local_wms) if len(local_wms) == WM_RANKS else None
            for service in services:
                for pub in service.publications:
                    w = pub["window"]
                    if min_wm is None or (
                        (w + 1) * WM_WINDOW_S + WM_LATENESS_S > min_wm
                    ):
                        failures.append(
                            f"coherent: round {r} rank {service.label} published"
                            f" window {w} before every rank's watermark passed it"
                            f" (min local wm {min_wm})"
                        )
            # structural evidence the agreement actually withheld something:
            # the skewed rank's LOCAL clock closes windows its peers still
            # feed; under agreement its publish frontier must trail it
            skew_wm = agreement.local_watermarks().get(WM_SKEW_RANK)
            if min_wm is not None and skew_wm is not None and skew_wm > min_wm:
                local_closed = int(math.floor((skew_wm - WM_LATENESS_S) / WM_WINDOW_S)) - 1
                agreed_closed = int(math.floor((min_wm - WM_LATENESS_S) / WM_WINDOW_S)) - 1
                if local_closed > agreed_closed:
                    published = [p["window"] for p in services[WM_SKEW_RANK].publications]
                    if all(w <= agreed_closed for w in published):
                        skew_ran_ahead = True
        merged_views = {}
        for rank, service in enumerate(services):
            merged_views[rank] = np.asarray(service.finalize(WM_BUDGET_S))
        for service in services:
            service.stop(WM_BUDGET_S)
        injected = dict(injector.injected)
    if not skew_ran_ahead:
        failures.append(
            "coherent: the skewed rank's local clock never ran ahead of the agreed"
            " frontier — the skew schedule lost its teeth"
        )
    return {
        "services": services,
        "partials": partials,
        "merged_views": merged_views,
        "injected": injected,
        "elapsed_s": time.perf_counter() - start,
        "shifts": shifts,
        "rounds": rounds,
    }


def _wm_check_coherent(result, failures):
    """Bit-exactness of the coherent soak vs the union-stream oracle: every
    oracle window merged from the rank partials exactly once, per-window
    sample counts conserved, zero drops, zero double publishes."""
    from metrics_tpu import Accuracy, Windowed
    from metrics_tpu.parallel.sync import gather_all_arrays

    oracle = _wm_oracle(result["rounds"], result["shifts"])
    template = Windowed(
        Accuracy(), window_s=WM_WINDOW_S, num_windows=WM_WINDOWS,
        allowed_lateness_s=WM_LATENESS_S, dist_sync_fn=gather_all_arrays,
    )
    partials = result["partials"]
    merged_windows = sorted(partials)
    if merged_windows != oracle["published"]:
        failures.append(
            f"coherent: published windows {merged_windows} != oracle"
            f" {oracle['published']} (lost or phantom windows)"
        )
    for service in result["services"]:
        windows = [p["window"] for p in service.publications]
        if len(windows) != len(set(windows)):
            failures.append(f"coherent: {service.label} double-published a window")
        if service.metric.dropped_samples:
            failures.append(
                f"coherent: {service.label} dropped"
                f" {service.metric.dropped_samples} events — under the agreed"
                " clock the seeded stream never exceeds the lateness"
            )
    for w in oracle["published"]:
        by_rank = partials.get(w, {})
        got = np.asarray(template.value_from_partials(list(by_rank.values())))
        expected = oracle["values"][w]
        if not np.array_equal(got, expected, equal_nan=True):
            failures.append(
                f"coherent: window {w} merged value {got} != oracle {expected}"
            )
        rows = sum(float(np.asarray(p["rows"])) for p in by_rank.values())
        if int(rows) != oracle["counts"][w]:
            failures.append(
                f"coherent: window {w} holds {int(rows)} samples across ranks,"
                f" oracle routed {oracle['counts'][w]} (lost or double-counted)"
            )
    if result["elapsed_s"] > WM_BUDGET_S:
        failures.append(
            f"coherent: soak took {result['elapsed_s']:.1f}s > {WM_BUDGET_S}s budget"
        )
    if result["injected"].get("clock_skew", 0) < WM_BATCHES:
        failures.append(
            f"coherent: clock_skew fired {result['injected'].get('clock_skew', 0)}"
            f" times, expected every one of rank {WM_SKEW_RANK}'s {WM_BATCHES} batches"
        )
    if result["injected"].get("late_burst", 0) != 1:
        failures.append("coherent: the late burst never fired")
    return oracle


def _wm_drive_stall(failures):
    """The stall tier: one rank stalls at rate=1.0, the agreement deadline
    excludes it, closing proceeds degraded on the survivors — nothing
    deadlocks."""
    import jax.numpy as jnp

    from metrics_tpu.observability import counters as _ctr
    from metrics_tpu.parallel import faults
    from metrics_tpu.parallel.sync import SyncGuard

    guard = SyncGuard(deadline_s=1.5, max_retries=1, backoff_s=0.02, policy="degrade")
    stall_rank = 2
    schedule = [
        faults.FaultSpec(kind="ingest_stall", rank=stall_rank, rate=1.0,
                         times=10**6, duration_s=2.5, site="service.ingest"),
    ]
    stragglers_before = _ctr.COUNTERS.wm_stragglers
    start = time.perf_counter()
    rng = np.random.RandomState(7)
    with faults.ChaosInjector(schedule, seed=0):
        agreement, services, _partials = _wm_build_ranks(3, WM_STALL_DEADLINE_S, guard)
        # the stalled rank gets ONE batch (its worker then sleeps through the
        # deadline holding its watermark still); the healthy ranks keep
        # streaming past it
        services[stall_rank].submit(
            jnp.asarray(rng.rand(4).astype(np.float32)),
            jnp.asarray((rng.rand(4) > 0.5).astype(np.int32)),
            event_time=rng.uniform(0.0, 5.0, 4), seq=0,
        )
        for r in range(6):
            for rank in (0, 1):
                services[rank].submit(
                    jnp.asarray(rng.rand(8).astype(np.float32)),
                    jnp.asarray((rng.rand(8) > 0.5).astype(np.int32)),
                    event_time=r * 10.0 + rng.uniform(0.0, 10.0, 8), seq=r,
                )
            for rank in (0, 1):
                services[rank].flush(WM_BUDGET_S)
            time.sleep(0.25)
        for rank in (0, 1):
            services[rank].finalize(WM_BUDGET_S)
        published = {
            rank: [(p["window"], p["degraded"]) for p in services[rank].publications]
            for rank in (0, 1)
        }
        # the stalled worker drains its sleep before stop so teardown is clean
        services[stall_rank].stop(WM_BUDGET_S)
        for rank in (0, 1):
            services[rank].stop(WM_BUDGET_S)
    elapsed = time.perf_counter() - start
    stragglers = _ctr.COUNTERS.wm_stragglers - stragglers_before
    healthy_published = [w for rank in (0, 1) for (w, _d) in published[rank]]
    degraded_published = [d for rank in (0, 1) for (_w, d) in published[rank]]
    if stragglers < 1:
        failures.append("stall: the stalled rank was never excluded (wm_stragglers == 0)")
    if not healthy_published:
        failures.append("stall: no window ever closed — the stalled rank wedged its peers")
    if not any(degraded_published):
        failures.append(
            "stall: publishes made while a straggler was excluded never stamped"
            " degraded=True"
        )
    if elapsed > WM_BUDGET_S:
        failures.append(f"stall: tier took {elapsed:.1f}s > {WM_BUDGET_S}s budget (deadlock?)")
    return {
        "published": published,
        "stragglers": stragglers,
        "excluded": [repr(r) for r in agreement.excluded()],
        "elapsed_s": elapsed,
    }


def _slide_stream(seed: int = 3):
    """The sliding tier's seeded stream: event times advance one stride per
    batch with jitter and ~15% within-lateness stragglers."""
    rng = np.random.RandomState(seed)
    batches = []
    for i in range(SLIDE_BATCHES):
        times = i * SLIDE_S + rng.uniform(0.0, SLIDE_S, SLIDE_BATCH)
        late = rng.rand(SLIDE_BATCH) < 0.15
        times = np.where(late, np.maximum(times - 3.0, 0.0), times)
        preds = rng.rand(SLIDE_BATCH).astype(np.float32)
        target = (rng.rand(SLIDE_BATCH) > 0.5).astype(np.int32)
        batches.append((times, preds, target))
    return batches


def _drive_slide(batches, guard=None):
    """Run the sliding stream through a real MetricService over
    ``Windowed(slide_s=...)``; returns (publications, merged, service)."""
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MetricService, Windowed
    from metrics_tpu.parallel.sync import SyncGuard, gather_all_arrays

    metric = Windowed(
        Accuracy(), window_s=SLIDE_WINDOW_S, num_windows=SLIDE_WINDOWS,
        allowed_lateness_s=SLIDE_LATENESS_S, slide_s=SLIDE_S,
        dist_sync_fn=gather_all_arrays,
    )
    guard = guard or SyncGuard(deadline_s=2.0, max_retries=1, policy="degrade")
    service = MetricService(metric, queue_size=16, guard=guard)
    for i, (times, preds, target) in enumerate(batches):
        service.submit(jnp.asarray(preds), jnp.asarray(target), event_time=times, seq=i)
    merged = np.asarray(service.finalize(WM_BUDGET_S))
    publications = list(service.publications)
    service.stop(WM_BUDGET_S)
    return publications, merged, service


def _check_slide(publications, failures):
    """Every published sliding window bit-exact vs an independent per-slot
    oracle: a fresh unwindowed metric over exactly the events whose time
    falls in the window's [w*slide, w*slide + window) span. Sound because
    the seeded stream's stragglers stay within the lateness of every
    covering window (no routing verdict depends on arrival order)."""
    import jax.numpy as jnp

    from metrics_tpu import Accuracy

    batches = _slide_stream()
    events = [
        (t, p, y)
        for times, preds, target in batches
        for t, p, y in zip(np.asarray(times, np.float64), preds, target)
    ]
    by_window = {}
    for w in {p["window"] for p in publications}:
        lo = w * SLIDE_S
        pairs = [(p, y) for (t, p, y) in events if lo <= t < lo + SLIDE_WINDOW_S]
        by_window[w] = pairs
    if len(publications) != len({p["window"] for p in publications}):
        failures.append("sliding: a window was published more than once")
    for pub in publications:
        w = pub["window"]
        pairs = by_window.get(w, [])
        if not pairs:
            failures.append(f"sliding: published window {w} covers no oracle events")
            continue
        metric = Accuracy()
        metric.update(
            jnp.asarray(np.array([p for p, _ in pairs], dtype=np.float32)),
            jnp.asarray(np.array([y for _, y in pairs], dtype=np.int32)),
        )
        expected = np.asarray(metric.compute())
        if not np.array_equal(pub["value"], expected, equal_nan=True):
            failures.append(
                f"sliding: window {w} value {pub['value']} != per-slot oracle"
                f" {expected}"
            )


def check_watermark() -> int:
    """``--check-watermark``: the rank-coherent streaming gate (see the
    block comment above). Prints one JSON report line; non-zero exit on any
    broken contract."""
    from metrics_tpu import observability as obs

    failures = []

    # -- parity: agreement adds ZERO staged collectives --------------------
    obs.enable()
    parity = {}
    for name, kwargs in (
        ("agreed", dict(windowed=True, with_agreement=True)),
        ("windowed", dict(windowed=True)),
        ("unwindowed", dict(windowed=False)),
    ):
        run, _ = _build_windowed_sync_runner(**kwargs)
        # the agreed build's exchange round lands during build (before the
        # staged capture): read its count before resetting for the capture
        exchanged = obs.counters_snapshot()["wm_exchange_calls"]
        obs.COUNTERS.reset()
        run(1)  # first call traces+compiles: counters hold the staged program
        snap = obs.counters_snapshot()
        parity[name] = {
            "collective_calls": snap["collective_calls"],
            "sync_bytes": snap["sync_bytes"],
            "gather_calls": sum(
                snap["calls_by_kind"].get(k, 0)
                for k in ("all_gather", "coalesced_gather", "process_allgather")
            ),
            "wm_exchange_calls": exchanged + snap["wm_exchange_calls"],
        }
    obs.disable()
    if parity["agreed"]["collective_calls"] != parity["unwindowed"]["collective_calls"]:
        failures.append(
            f"parity: the agreed metric staged {parity['agreed']['collective_calls']}"
            f" collectives vs the unwindowed {parity['unwindowed']['collective_calls']}"
            " — the watermark exchange must never enter the sync program"
        )
    if parity["agreed"]["gather_calls"] != 0:
        failures.append(
            f"parity: the agreed metric staged {parity['agreed']['gather_calls']}"
            " gather collectives (the exchange must be host-plane only)"
        )
    if parity["agreed"]["wm_exchange_calls"] < 1:
        failures.append("parity: the watermark exchange never actually ran")

    # -- coherent: skew + late burst, publish ordering + bit-exactness ------
    obs.reset()
    coherent = _wm_drive_coherent(failures)
    oracle = _wm_check_coherent(coherent, failures)

    # -- stall: deadline exclusion unblocks closing, degraded, no deadlock --
    stall = _wm_drive_stall(failures)

    # -- sliding: bit-exact vs independent per-slot oracles -----------------
    slide_pubs, _slide_merged, slide_service = _drive_slide(_slide_stream())
    _check_slide(slide_pubs, failures)
    if slide_service.metric.dropped_samples:
        failures.append(
            f"sliding: {slide_service.metric.dropped_samples} events dropped —"
            " the seeded stragglers must stay within the lateness"
        )

    print(json.dumps({
        "check": "watermark",
        "ok": not failures,
        "failures": failures,
        "parity": parity,
        "coherent": {
            "published": oracle["published"],
            "ranks": WM_RANKS,
            "skew_s": WM_SKEW_S,
            "injected": coherent["injected"],
            "elapsed_s": round(coherent["elapsed_s"], 3),
        },
        "stall": {
            "stragglers": stall["stragglers"],
            "excluded": stall["excluded"],
            "published": {str(k): v for k, v in stall["published"].items()},
            "elapsed_s": round(stall["elapsed_s"], 3),
            "budget_s": WM_BUDGET_S,
        },
        "sliding": {
            "published": sorted(p["window"] for p in slide_pubs),
            "windows_published": len(slide_pubs),
            "overlap": int(round(SLIDE_WINDOW_S / SLIDE_S)),
        },
    }))
    return 1 if failures else 0


# --check-quantile pins the quantile-sketch contract (parallel/qsketch.py +
# the Quantile/Percentile/MedianAbsoluteError family):
#   certificate — every quantile estimate on seeded heavy-tailed/adversarial
#                 streams (Zipfian, Cauchy, lognormal) lands within the
#                 alpha relative-error certificate (|est - true| <=
#                 alpha*|true| + min_value against the selected order
#                 statistic), with quantile_error_bound reporting alpha
#   merge       — a real (4,2)-mesh two-stage psum of 8 per-device sketches
#                 equals the single-process sketch BIT-EXACTLY
#   parity      — Keyed(Quantile) x QSK_SLOTS and Windowed(Keyed(Quantile))
#                 stage the IDENTICAL collective count and kinds (psum-only,
#                 zero gathers) as the unkeyed scalar Quantile
#   memory      — qsketch state bytes are CONSTANT over the stream while the
#                 capacity-buffer twin's state grows with every batch


def _qsk_gate_streams():
    """The seeded gate streams: heavy-tailed positive (Zipfian discrete,
    lognormal) and signed heavy-tailed (Cauchy)."""
    rng = np.random.RandomState(42)
    return {
        "zipfian": rng.zipf(1.5, QSK_GATE_N).astype(np.float64),
        "cauchy": rng.standard_cauchy(QSK_GATE_N),
        "lognormal": rng.lognormal(1.0, 2.0, QSK_GATE_N),
    }


def _qsk_check_certificate(failures: list) -> dict:
    import jax.numpy as jnp

    from metrics_tpu import Quantile

    report = {}
    qs = (0.5, 0.9, 0.99, 0.999)
    for name, stream in _qsk_gate_streams().items():
        m = Quantile(q=list(qs), alpha=QSK_ALPHA, min_value=QSK_LO, max_value=QSK_HI)
        m.update(jnp.asarray(stream.astype(np.float32)))
        est = np.asarray(m.compute(), dtype=np.float64)
        bound = np.asarray(m.error_bound(), dtype=np.float64)
        s = np.sort(stream)
        rows = {}
        for q, e, b in zip(qs, est, bound):
            r = q * (len(s) - 1)
            bracket = (s[int(np.floor(r))], s[int(np.ceil(r))])
            ok = any(
                abs(e - t) <= QSK_ALPHA * abs(t) + QSK_LO + 3 * QSK_ALPHA**2 * abs(t)
                for t in bracket
            )
            if np.isfinite(b) and abs(b - QSK_ALPHA) > 1e-6:
                failures.append(
                    f"certificate: {name} q={q} reported bound {b} != alpha {QSK_ALPHA}"
                )
            if np.isfinite(b) and not ok:
                failures.append(
                    f"certificate: {name} q={q} estimate {e} outside the alpha"
                    f" certificate of order stats {bracket}"
                )
            rows[str(q)] = {"estimate": float(e), "bound": round(float(b), 6),
                            "order_stat": float(bracket[0])}
        report[name] = rows
    return report


def _qsk_check_merge(failures: list) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu.parallel.placement import MeshHierarchy
    from metrics_tpu.parallel.qsketch import (
        QuantileSketch, qsketch_init, qsketch_update, quantile_sketch_spec,
    )
    from metrics_tpu.parallel.sync import sync_value
    from metrics_tpu.utils.compat import shard_map

    rng = np.random.RandomState(7)
    values = rng.lognormal(0.0, 2.0, (N_DEVICES, 512)).astype(np.float32)
    spec = quantile_sketch_spec(QSK_ALPHA, QSK_LO, QSK_HI)
    mesh = Mesh(
        np.array(jax.devices("cpu")[:N_DEVICES]).reshape(HIER_SLICES, N_DEVICES // HIER_SLICES),
        ("dcn", "ici"),
    )
    axis = MeshHierarchy(ici_axis="ici", dcn_axis="dcn")

    def fn(v):
        local = qsketch_update(qsketch_init(spec).counts, v[0], QSK_ALPHA, QSK_LO, QSK_HI)
        return sync_value("sum", QuantileSketch(local), axis).counts

    synced = jax.jit(shard_map(
        fn, mesh=mesh, in_specs=(P(("dcn", "ici")),), out_specs=P(), check_vma=False
    ))(jnp.asarray(values))
    single = qsketch_update(
        qsketch_init(spec).counts, jnp.asarray(values.reshape(-1)), QSK_ALPHA, QSK_LO, QSK_HI
    )
    bit_exact = bool(jnp.array_equal(synced, single))
    if not bit_exact:
        failures.append("merge: (4,2)-mesh psum of per-device sketches != single-process sketch")
    return {"bit_exact": bit_exact, "total": int(np.asarray(single).sum())}


def _qsk_staged_counts(build_metric) -> dict:
    """Staged collective counters of one metric's coalesced sync program on
    the (4,2) mesh (trace-time counting over the compiling call)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu import observability as obs
    from metrics_tpu.parallel.placement import MeshHierarchy
    from metrics_tpu.parallel.sync import coalesced_sync_state
    from metrics_tpu.utils.compat import shard_map

    metric = build_metric()
    state = metric._current_state()
    reductions = {k: metric._reductions[k] for k in state}
    mesh = Mesh(
        np.array(jax.devices("cpu")[:N_DEVICES]).reshape(HIER_SLICES, N_DEVICES // HIER_SLICES),
        ("dcn", "ici"),
    )
    axis = MeshHierarchy(ici_axis="ici", dcn_axis="dcn")

    def fn(v):
        del v
        synced = coalesced_sync_state(state, reductions, axis)
        return jax.tree_util.tree_leaves(synced)[0]

    probe = jnp.zeros((N_DEVICES,), jnp.float32)
    obs.enable()
    obs.reset()
    jax.jit(shard_map(
        fn, mesh=mesh, in_specs=(P(("dcn", "ici")),), out_specs=P(), check_vma=False
    )).lower(probe).compile()
    snap = obs.counters_snapshot()
    obs.disable()
    return {
        "collective_calls": snap["collective_calls"],
        "psum_calls": snap["calls_by_kind"].get("psum", 0),
        "gather_calls": sum(
            snap["calls_by_kind"].get(k, 0)
            for k in ("all_gather", "coalesced_gather", "process_allgather", "ppermute")
        ),
    }


def _qsk_check_parity(failures: list) -> dict:
    import jax.numpy as jnp

    from metrics_tpu import Keyed, Quantile, Windowed

    rng = np.random.RandomState(9)
    values = jnp.asarray(rng.lognormal(0.0, 1.0, 128).astype(np.float32))
    slots = jnp.asarray(rng.randint(0, 32, 128).astype(np.int32))
    times = np.sort(rng.uniform(0.0, 30.0, 128))

    def unkeyed():
        m = Quantile(q=0.99, alpha=QSK_ALPHA, min_value=QSK_LO, max_value=QSK_HI)
        m.update(values)
        return m

    def keyed():
        m = Keyed(Quantile(q=0.99, alpha=QSK_ALPHA, min_value=QSK_LO, max_value=QSK_HI),
                  num_slots=32)
        m.update(values, slot=slots)
        return m

    def windowed_keyed():
        m = Windowed(
            Keyed(Quantile(q=0.99, alpha=QSK_ALPHA, min_value=QSK_LO, max_value=QSK_HI),
                  num_slots=32),
            window_s=10.0, num_windows=4,
        )
        m.update(values, slot=slots, event_time=times)
        return m

    report = {
        "unkeyed": _qsk_staged_counts(unkeyed),
        "keyed": _qsk_staged_counts(keyed),
        "windowed_keyed": _qsk_staged_counts(windowed_keyed),
    }
    base = report["unkeyed"]
    for name in ("keyed", "windowed_keyed"):
        if report[name]["collective_calls"] != base["collective_calls"]:
            failures.append(
                f"parity: {name} staged {report[name]['collective_calls']} collectives"
                f" vs the unkeyed scalar metric's {base['collective_calls']}"
            )
        if report[name]["gather_calls"] != 0:
            failures.append(f"parity: {name} staged gather collectives (must be psum-only)")
    if base["psum_calls"] == 0:
        failures.append("parity: the unkeyed program staged no psum at all")
    return report


def _qsk_check_memory(failures: list) -> dict:
    import jax.numpy as jnp

    from metrics_tpu import Quantile, SpearmanCorrcoef
    from metrics_tpu.observability.counters import state_nbytes

    rng = np.random.RandomState(11)
    q = Quantile(q=0.99, alpha=QSK_ALPHA, min_value=QSK_LO, max_value=QSK_HI)
    twin = SpearmanCorrcoef()  # the O(samples) capacity-buffer twin
    q_sizes, twin_sizes = [], []
    for _ in range(6):
        batch = rng.lognormal(0.0, 1.0, 1024).astype(np.float32)
        q.update(jnp.asarray(batch))
        twin.update(jnp.asarray(batch), jnp.asarray(batch * 2.0))
        q_sizes.append(int(state_nbytes(q._current_state())))
        twin_sizes.append(int(state_nbytes(twin._current_state())))
    if len(set(q_sizes)) != 1:
        failures.append(f"memory: qsketch state bytes moved with traffic: {q_sizes}")
    if not twin_sizes[-1] > twin_sizes[0]:
        failures.append("memory: the buffer twin did not grow (scenario broken)")
    return {"qsketch_bytes": q_sizes[0], "buffer_twin_bytes": twin_sizes}


def check_quantile() -> int:
    """``--check-quantile``: the quantile-sketch regression gate (see the
    block comment above). Prints one JSON line; exit 0 iff every tier holds.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")

    failures: list = []
    certificate = _qsk_check_certificate(failures)
    merge = _qsk_check_merge(failures)
    parity = _qsk_check_parity(failures)
    memory = _qsk_check_memory(failures)

    print(json.dumps({
        "check": "quantile",
        "ok": not failures,
        "failures": failures,
        "alpha": QSK_ALPHA,
        "certificate": certificate,
        "merge": merge,
        "parity": parity,
        "memory": memory,
    }))
    return 1 if failures else 0


# ------------------------------------------------------ tiered-retention gate
def _ret_cms_metric_cls():
    """The gate's count-min vehicle: no library metric carries a bare
    counter CMS, so the fourth state kind gets a bench-local one. Row
    buckets are resolved HOST-side (``cms_buckets`` over the stable key
    hashes) and fed as a data argument, so the per-sample update stays pure
    under ``Windowed``'s vmapped delta path — the documented contract of
    the windowed count-min slab."""
    from metrics_tpu.core.metric import Metric
    from metrics_tpu.parallel.cms import CMSSpec, CountMinSketch, cms_scatter, cms_total

    class BenchCMSTotal(Metric):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state(
                "tail",
                default=CMSSpec(RET_CMS_DEPTH, RET_CMS_WIDTH, (), np.int32,
                                seed=RET_CMS_SEED),
                dist_reduce_fx="sum", persistent=True,
            )

        def update(self, buckets, deltas):
            self.tail = CountMinSketch(cms_scatter(self.tail.counts, buckets, deltas))

        def compute(self):
            return cms_total(self.tail.counts)

    return BenchCMSTotal


def _ret_vehicles():
    """(name, template factory, submit fn) per mergeable state kind — the
    four kinds of the paper's state algebra plus the nested per-tenant
    plane. Every submit drives the SAME seeded event-time grid, one batch
    per RET_STEP_S tick."""
    import jax.numpy as jnp

    from metrics_tpu import AUROC, Accuracy, Keyed, Quantile, Windowed
    from metrics_tpu.parallel.cms import cms_buckets, stable_key_hashes

    def windowed(inner):
        return Windowed(inner, window_s=RET_WINDOW_S, num_windows=RET_WINDOWS,
                        allowed_lateness_s=0.0)

    def times(i):
        return np.full(RET_BATCH, i * RET_STEP_S)

    def classifier_submit(svc, rng, i):
        svc.submit(jnp.asarray(rng.rand(RET_BATCH).astype(np.float32)),
                   jnp.asarray(rng.randint(0, 2, RET_BATCH).astype(np.int32)),
                   event_time=times(i))

    def keyed_submit(svc, rng, i):
        svc.submit(jnp.asarray(rng.rand(RET_BATCH).astype(np.float32)),
                   jnp.asarray(rng.randint(0, 2, RET_BATCH).astype(np.int32)),
                   slot=jnp.asarray(rng.randint(0, RET_TENANTS, RET_BATCH).astype(np.int32)),
                   event_time=times(i))

    def quantile_submit(svc, rng, i):
        svc.submit(jnp.asarray(rng.lognormal(0.0, 1.0, RET_BATCH).astype(np.float32)),
                   event_time=times(i))

    def cms_submit(svc, rng, i):
        keys = [f"user-{k}" for k in rng.randint(0, RET_CMS_KEYS, RET_BATCH)]
        buckets = jnp.asarray(cms_buckets(
            stable_key_hashes(keys), RET_CMS_DEPTH, RET_CMS_WIDTH, RET_CMS_SEED))
        svc.submit(buckets, jnp.ones((RET_BATCH,), jnp.int32), event_time=times(i))

    cms_cls = _ret_cms_metric_cls()
    return (
        ("array", lambda: windowed(Accuracy()), classifier_submit),
        ("hist_sketch",
         lambda: windowed(AUROC(approx="sketch", num_bins=64)), classifier_submit),
        ("qsketch",
         lambda: windowed(Quantile(q=0.99, alpha=QSK_ALPHA,
                                   min_value=QSK_LO, max_value=QSK_HI)),
         quantile_submit),
        ("cms", lambda: windowed(cms_cls()), cms_submit),
        ("keyed",
         lambda: windowed(Keyed(Accuracy(), num_slots=RET_TENANTS)), keyed_submit),
    )


def _ret_drive(factory, submit, label, batches=RET_BATCHES, ladder=RET_LADDER):
    """One seeded service stream into an attached store, with the raw
    published partials teed for the flat-recompute oracle."""
    from metrics_tpu import MetricService, RetentionStore

    raw = []
    with MetricService(factory(), name=label, deferred_publish=False) as svc:
        svc.partial_publish_fn = lambda record, partial: raw.append(partial)
        store = RetentionStore(ladder=ladder, name=f"{label}-store").attach(svc)
        rng = np.random.RandomState(17)
        for i in range(batches):
            submit(svc, rng, i)
        svc.finalize()
    return store, raw


def _ret_flat(factory, raw, start_s, seconds):
    """The oracle: finish the union of raw published partials covering one
    output bucket through a FRESH template — no store, no roll-up."""
    group = [p for p in raw
             if start_s <= p["window_start_s"] < start_s + seconds]
    return np.asarray(factory().value_from_partials(group)), len(group)


def _ret_check_exactness(failures: list) -> dict:
    """Every query — native mixed resolution and every legal coarse grid —
    must be BIT-exact vs the flat recompute, for all four state kinds and
    the nested keyed plane; a grid finer than a rolled-up bucket must raise
    rather than approximate."""
    report = {}
    total_windows = int(math.ceil(RET_SPAN_S / RET_WINDOW_S))
    # the full-range grids every retained bucket nests inside (the ladder's
    # overflow cell rolled up into one [0, 40) coarse bucket, so 4x the
    # window stride is the finest legal full-range grid)
    resolutions = [4 * RET_WINDOW_S, 8 * RET_WINDOW_S,
                   16 * RET_WINDOW_S, 24 * RET_WINDOW_S]
    for name, factory, submit in _ret_vehicles():
        store, raw = _ret_drive(factory, submit, f"gate/retention-{name}")
        vehicle = {"published": len(raw), "points": {}}
        sweeps = [("native", None, (0.0, RET_SPAN_S)),
                  ("raw_tail", RET_WINDOW_S,
                   (RET_SPAN_S - 4 * RET_WINDOW_S, RET_SPAN_S))]
        sweeps += [(f"{int(r)}s", r, (0.0, RET_SPAN_S)) for r in resolutions]
        for sweep, res, span in sweeps:
            points = store.query(metric=store.labels[0], time_range=span,
                                 resolution_s=res)
            if not points:
                failures.append(f"{name}/{sweep}: query returned no points")
                continue
            windows = 0
            for point in points:
                flat, n_raw = _ret_flat(factory, raw,
                                        point["start_s"], point["seconds"])
                windows += point["windows"]
                if point["windows"] != n_raw:
                    failures.append(
                        f"{name}/{sweep}: point at {point['start_s']}s merged"
                        f" {point['windows']} windows but {n_raw} raw partials"
                        " cover its span"
                    )
                if not np.array_equal(point["value"], flat, equal_nan=True):
                    failures.append(
                        f"{name}/{sweep}: point at {point['start_s']}s is not"
                        " bit-exact vs the flat recompute of its raw partials"
                    )
                if name == "keyed":
                    for tenant in (0, RET_TENANTS - 1):
                        sliced = store.query(metric=store.labels[0],
                                             tenant=tenant, time_range=span,
                                             resolution_s=res)
                        got = next(p["value"] for p in sliced
                                   if p["start_s"] == point["start_s"])
                        if not np.array_equal(got, point["value"][tenant],
                                              equal_nan=True):
                            failures.append(
                                f"{name}/{sweep}: tenant {tenant} slice"
                                " diverged from the full slab's row"
                            )
            expect = (total_windows if span == (0.0, RET_SPAN_S)
                      else int((span[1] - span[0]) / RET_WINDOW_S))
            if windows != expect:
                failures.append(
                    f"{name}/{sweep}: points cover {windows} windows,"
                    f" expected {expect}"
                )
            vehicle["points"][sweep] = len(points)
        # the negative space: a grid finer than a rolled-up bucket must
        # refuse loudly (merged state never splits), not interpolate
        for res in (RET_WINDOW_S, 2 * RET_WINDOW_S):
            try:
                store.query(metric=store.labels[0],
                            time_range=(0.0, RET_SPAN_S), resolution_s=res)
                failures.append(
                    f"{name}: resolution {res}s should have raised (it"
                    " splits a rolled-up bucket) but returned points"
                )
            except ValueError:
                pass
        report[name] = vehicle
    return report


def _ret_check_memory(failures: list) -> dict:
    """Resident bytes must be bounded by the ladder shape, NOT by stream
    length: a 3x-longer stream through a saturated (evicting) ladder banks
    3x the windows in the SAME footprint."""
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, Windowed

    def factory():
        return Windowed(Accuracy(), window_s=RET_WINDOW_S,
                        num_windows=RET_WINDOWS, allowed_lateness_s=0.0)

    def submit(svc, rng, i):
        svc.submit(jnp.asarray(rng.rand(RET_BATCH).astype(np.float32)),
                   jnp.asarray(rng.randint(0, 2, RET_BATCH).astype(np.int32)),
                   event_time=np.full(RET_BATCH, i * RET_WINDOW_S))

    ladder = ((RET_WINDOW_S, 4), (4 * RET_WINDOW_S, 4), (16 * RET_WINDOW_S, 4))
    short, _ = _ret_drive(factory, submit, "gate/retention-mem-1x",
                          batches=96, ladder=ladder)
    long, _ = _ret_drive(factory, submit, "gate/retention-mem-3x",
                         batches=288, ladder=ladder)
    report = {
        "resident_bytes_1x": int(short.resident_bytes()),
        "resident_bytes_3x": int(long.resident_bytes()),
        "banked_1x": short.windows_banked, "banked_3x": long.windows_banked,
        "evicted_1x": short.evicted_buckets, "evicted_3x": long.evicted_buckets,
    }
    if long.resident_bytes() != short.resident_bytes():
        failures.append(
            f"memory: resident bytes moved with stream length"
            f" ({report['resident_bytes_1x']} -> {report['resident_bytes_3x']})"
        )
    if long.windows_banked != 3 * short.windows_banked:
        failures.append("memory: the 3x stream did not bank 3x the windows")
    if not (short.evicted_buckets > 0 and
            long.evicted_buckets > short.evicted_buckets):
        failures.append("memory: the ladder never saturated (scenario broken)")
    return report


def _ret_check_exposition(failures: list) -> dict:
    """The scrape surface must stay well-formed: one terminal ``# EOF`` and
    the retained stream's latest value present (the strict line-level format
    contract is tier-1's ``test_openmetrics.py``; this is the smoke seam)."""
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, Windowed
    from metrics_tpu.serving import render

    def factory():
        return Windowed(Accuracy(), window_s=RET_WINDOW_S,
                        num_windows=RET_WINDOWS, allowed_lateness_s=0.0)

    def submit(svc, rng, i):
        svc.submit(jnp.asarray(rng.rand(RET_BATCH).astype(np.float32)),
                   jnp.asarray(rng.randint(0, 2, RET_BATCH).astype(np.int32)),
                   event_time=np.full(RET_BATCH, i * RET_STEP_S))

    store, _ = _ret_drive(factory, submit, "gate/retention-scrape", batches=16)
    text = render([store])
    if not text.endswith("# EOF\n"):
        failures.append("exposition: rendering does not terminate with '# EOF\\n'")
    if text.count("# EOF") != 1:
        failures.append("exposition: '# EOF' must appear exactly once")
    if "metrics_tpu_retained_latest{" not in text:
        failures.append("exposition: the retained stream's latest value is missing")
    return {"bytes": len(text), "lines": text.count("\n")}


def check_retention() -> int:
    """``--check-retention``: the tiered-retention regression gate (see the
    RET_* block comment). Prints one JSON line; exit 0 iff every tier holds.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")

    failures: list = []
    exact = _ret_check_exactness(failures)
    memory = _ret_check_memory(failures)
    exposition = _ret_check_exposition(failures)

    print(json.dumps({
        "check": "retention",
        "ok": not failures,
        "failures": failures,
        "windows": int(math.ceil(RET_SPAN_S / RET_WINDOW_S)),
        "exact": exact,
        "memory": memory,
        "exposition": exposition,
    }))
    return 1 if failures else 0


# --check-health pins the pipeline health plane (the lifecycle ledger +
# self-meter sketches of metrics_tpu.observability threaded through the
# serving stack):
#   clean  — a wall-clock service soak: every published window carries a
#            COMPLETE core stage ledger (first_event -> last_event -> closed
#            -> sync_started -> sync_done -> published) with MONOTONE stamps
#            and a distinct flow id on the record; the self-meter's e2e
#            p50/p95/p99 sit inside the DDSketch certificate
#            (alpha * |true| + min_value) of the exact rank-selected
#            latencies the very same ledgers recorded; watermark lag stays
#            under HEALTH_LAG_BOUND_S
#   stall  — a seeded mid-stream ingest_stall: the lag gauge must SPIKE to
#            at least half the stall and be back under the stall magnitude
#            by the final publish — the plane both detects the backlog and
#            confirms the recovery
#   fleet  — a HEALTH_FLEET_SHARDS-shard fleet with an attached
#            RetentionStore: health_report()'s latency table EQUALS the
#            manual merge_meters fold of the per-shard sketches (the merge
#            is pure state addition — no approximation in the fold), every
#            merged window stamps 'merged' on each contributing shard's
#            ledger and 'banked' on the fleet's, and the exposition renders
#            the new health families under one terminal '# EOF'


def _health_soak(label: str, schedule=None):
    """One wall-clock service soak under the health plane: real
    ``time.time()`` event times with a sleep between submissions so windows
    close while the stream is still flowing. Returns the publications and
    the per-publish watermark lag samples (publish wall time minus the
    record's watermark), in publish order."""
    import contextlib

    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MetricService, Windowed
    from metrics_tpu.parallel import faults

    rng = np.random.RandomState(13)
    lags: list = []

    def on_publish(record):
        wm = record.get("watermark")
        if wm is not None:
            lags.append(time.time() - float(wm))

    injector = (faults.ChaosInjector(schedule, seed=0)
                if schedule else contextlib.nullcontext())
    with injector:
        metric = Windowed(Accuracy(), window_s=HEALTH_GATE_WINDOW_S,
                          num_windows=4, allowed_lateness_s=0.0)
        with MetricService(metric, name=label, publish_fn=on_publish) as svc:
            for _ in range(HEALTH_GATE_BATCHES):
                preds = jnp.asarray(rng.rand(HEALTH_BATCH).astype(np.float32))
                target = jnp.asarray((rng.rand(HEALTH_BATCH) > 0.5).astype(np.int32))
                svc.submit(preds, target,
                           event_time=np.full(HEALTH_BATCH, time.time()))
                time.sleep(HEALTH_GATE_STEP_S)
            svc.finalize()
            pubs = list(svc.publications)
    return pubs, lags


def _health_check_clean(failures: list) -> dict:
    """The clean tier: complete monotone ledgers + distinct flow ids, the
    sketch-vs-exact quantile certificate, and bounded watermark lag."""
    from metrics_tpu.observability.lifecycle import CORE_STAGES, LEDGER
    from metrics_tpu.observability.selfmeter import SELFMETER, SELFMETER_QUANTILES

    label = "gate/health"
    pubs, lags = _health_soak(label)
    if len(pubs) < 3:
        failures.append(f"clean: only {len(pubs)} windows published (scenario broken)")
    flows = set()
    exact_e2e = []
    for rec in pubs:
        window = rec["window"]
        entry = LEDGER.entry(label, window) or {}
        missing = [s for s in CORE_STAGES if s not in entry]
        if missing:
            failures.append(f"clean: window {window} ledger is missing stages {missing}")
            continue
        stamps = [entry[s] for s in CORE_STAGES]
        if any(b < a for a, b in zip(stamps, stamps[1:])):
            failures.append(f"clean: window {window} stage stamps are not monotone")
        exact_e2e.append((entry["published"] - entry["closed"]) / 1e6)
        fid = rec.get("flow")
        if fid is None:
            failures.append(f"clean: window {window} published without a flow id")
        elif fid in flows:
            failures.append(f"clean: flow id {fid} reused across windows")
        else:
            flows.add(fid)
    meter = SELFMETER.meters(label).get("e2e")
    windows = len({rec["window"] for rec in pubs})
    quantiles = {}
    if meter is None or meter.count != windows:
        got = 0 if meter is None else meter.count
        failures.append(f"clean: the e2e self-meter holds {got} samples, expected {windows}")
    elif exact_e2e:
        vals = np.sort(np.asarray(exact_e2e))
        cum = np.arange(1, len(vals) + 1)
        for q in SELFMETER_QUANTILES:
            est = meter.quantile(q)
            # the sketch's own rank rule applied to the exact samples — the
            # certificate is relative error vs the rank-SELECTED latency
            idx = int(np.clip(np.searchsorted(cum, q * (len(vals) - 1), side="right"),
                              0, len(vals) - 1))
            true = float(vals[idx])
            bound = meter.alpha * abs(true) + meter.min_value
            quantiles[str(q)] = {"est_ms": round(est, 4), "true_ms": round(true, 4)}
            if not (abs(est - true) <= bound + 1e-9):
                failures.append(
                    f"clean: self-meter p{int(q * 100)} {est:.4f}ms is outside the"
                    f" certificate of the exact {true:.4f}ms (bound {bound:.4f}ms)"
                )
    max_lag = max(lags, default=float("nan"))
    if not lags:
        failures.append("clean: no watermark lag samples recorded")
    elif max_lag >= HEALTH_LAG_BOUND_S:
        failures.append(
            f"clean: watermark lag peaked at {max_lag:.3f}s"
            f" (bound {HEALTH_LAG_BOUND_S}s)"
        )
    return {"published": len(pubs),
            "max_lag_s": round(max_lag, 4) if lags else None,
            "quantiles": quantiles}


def _health_check_stall(failures: list) -> dict:
    """The stall tier: a seeded mid-stream ingest stall in the worker — the
    lag gauge must see the backlog (spike) and the drain (recovery)."""
    from metrics_tpu.parallel.faults import FaultSpec
    from metrics_tpu.serving.service import INGEST_SITE

    schedule = [FaultSpec(kind="ingest_stall", call=HEALTH_GATE_BATCHES // 2,
                          times=1, duration_s=HEALTH_STALL_S, site=INGEST_SITE)]
    pubs, lags = _health_soak("gate/health-stall", schedule=schedule)
    if not lags:
        failures.append("stall: no watermark lag samples recorded")
        return {"published": len(pubs)}
    max_lag = max(lags)
    if max_lag < HEALTH_STALL_S * 0.5:
        failures.append(
            f"stall: lag peaked at {max_lag:.3f}s under a {HEALTH_STALL_S}s ingest"
            " stall — the gauge never saw the backlog"
        )
    if lags[-1] >= HEALTH_STALL_S:
        failures.append(
            f"stall: the final publish still lags {lags[-1]:.3f}s — the stream"
            " never recovered after the stall"
        )
    return {"published": len(pubs), "max_lag_s": round(max_lag, 4),
            "final_lag_s": round(lags[-1], 4)}


def _health_check_fleet(failures: list) -> dict:
    """The fleet tier: the health_report fold vs the manual per-shard merge,
    merge/bank stamps on the right ledgers, and the exposition families."""
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MetricFleet, RetentionStore, Windowed
    from metrics_tpu.observability.lifecycle import LEDGER
    from metrics_tpu.observability.selfmeter import SELFMETER, merge_meters
    from metrics_tpu.serving import render

    def factory():
        return Windowed(Accuracy(), window_s=HEALTH_WINDOW_S, num_windows=4,
                        allowed_lateness_s=0.0)

    rng = np.random.RandomState(11)
    fleet = MetricFleet(factory, num_shards=HEALTH_FLEET_SHARDS,
                        name="gate/health-fleet")
    store = RetentionStore(name="gate/health-bank").attach(fleet)
    with fleet:
        for i in range(HEALTH_BATCHES):
            preds = jnp.asarray(rng.rand(HEALTH_BATCH).astype(np.float32))
            target = jnp.asarray((rng.rand(HEALTH_BATCH) > 0.5).astype(np.int32))
            fleet.submit(f"tenant-{i % 8}", preds, target,
                         event_time=np.full(HEALTH_BATCH, i * HEALTH_STEP_S))
        fleet.finalize(FLEET_SOAK_BUDGET_S)
        report = fleet.health_report()
        records = list(fleet.merged_records)
        shard_meters = [SELFMETER.meters(s.label) for s in fleet.shards]
    if not records:
        failures.append("fleet: no merged windows (scenario broken)")
    for stage, summary in sorted(report["latency"].items()):
        fold = merge_meters(m[stage] for m in shard_meters if stage in m)
        if fold is None or fold.summary() != summary:
            failures.append(
                f"fleet: health_report latency[{stage}] diverged from the"
                " manual per-shard merge_meters fold"
            )
    for need in ("e2e", "merge"):
        if need not in report["latency"]:
            failures.append(f"fleet: stage {need!r} never reached the fleet fold")
    for rec in records:
        for shard in rec["shards"]:
            entry = LEDGER.entry(f"{fleet.label}/shard{shard}", rec["window"]) or {}
            if "merged" not in entry:
                failures.append(
                    f"fleet: window {rec['window']} never stamped 'merged' on"
                    f" shard {shard}"
                )
        if "banked" not in (LEDGER.entry(fleet.label, rec["window"]) or {}):
            failures.append(f"fleet: window {rec['window']} never stamped 'banked'")
    staleness = report["staleness_s"]
    if not (isinstance(staleness, float) and np.isfinite(staleness) and staleness >= 0.0):
        failures.append(f"fleet: staleness_s {staleness!r} is not a finite age")
    text = render([store])
    for family in ("metrics_tpu_watermark_lag_seconds",
                   "metrics_tpu_publish_staleness_seconds",
                   "metrics_tpu_lifecycle_windows_stamped",
                   "metrics_tpu_lifecycle_open_windows",
                   "metrics_tpu_stage_latency_ms"):
        if family not in text:
            failures.append(f"fleet: exposition is missing the {family} family")
    if text.count("# EOF") != 1 or not text.endswith("# EOF\n"):
        failures.append("fleet: exposition must terminate with exactly one '# EOF'")
    return {"merged_windows": len(records),
            "latency_stages": sorted(report["latency"]),
            "degraded_shards": report["degraded_shards"],
            "staleness_s": round(staleness, 4) if isinstance(staleness, float) else None}


def check_health() -> int:
    """``--check-health``: the pipeline-health regression gate (see the
    HEALTH_* block comment). Prints one JSON line; exit 0 iff every tier
    holds."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from metrics_tpu import observability as obs

    obs.enable()
    obs.reset()
    failures: list = []
    clean = _health_check_clean(failures)
    stall = _health_check_stall(failures)
    fleet = _health_check_fleet(failures)

    print(json.dumps({
        "check": "health",
        "ok": not failures,
        "failures": failures,
        "clean": clean,
        "stall": stall,
        "fleet": fleet,
    }))
    return 1 if failures else 0


def main() -> None:
    trace_path = _trace_arg(sys.argv)
    if len(sys.argv) > 1 and sys.argv[1] == "--check-trajectory":
        # trajectory gate: measuring needs the virtual devices (set before
        # jax import, same as --smoke); an injected current file does not
        # touch jax at all
        if _flag_value(sys.argv, "--trajectory-current") is None:
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={N_DEVICES}"
            ).strip()
        raise SystemExit(check_trajectory_cli(sys.argv))

    if len(sys.argv) > 1 and sys.argv[1] == "--check-faults":
        # fault-tolerance gate: host-plane only (no virtual devices needed);
        # jax not yet imported, so the platform pin lands in-process
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        raise SystemExit(check_faults())

    if len(sys.argv) > 1 and sys.argv[1] == "--check-async":
        # deferred-sync gate: the A/B traces the 8-virtual-device sync8
        # programs (jax not yet imported, so the flag lands in-process)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={N_DEVICES}"
        ).strip()
        raise SystemExit(check_async())

    if len(sys.argv) > 1 and sys.argv[1] == "--check-fleet":
        # sharded-fleet gate: pure host-plane (threads + queues + numpy);
        # jax not yet imported, so the platform pin lands in-process
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        raise SystemExit(check_fleet())

    if len(sys.argv) > 1 and sys.argv[1] == "--check-watermark":
        # rank-coherent streaming gate: the soaks are host-plane, but the
        # parity tier traces the (4,2) mesh — virtual devices needed (jax
        # not yet imported, so the flag lands in-process)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={N_DEVICES}"
        ).strip()
        raise SystemExit(check_watermark())

    if len(sys.argv) > 1 and sys.argv[1] == "--check-service":
        # serving-runtime gate: the soaks are host-plane, but the parity
        # scenarios trace the (4,2) mesh — virtual devices needed (jax not
        # yet imported, so the flag lands in-process)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={N_DEVICES}"
        ).strip()
        raise SystemExit(check_service())

    if len(sys.argv) > 1 and sys.argv[1] == "--check-quantile":
        # quantile-sketch gate: the certificate/memory tiers are host-plane,
        # but the merge/parity tiers trace the (4,2) mesh — virtual devices
        # needed (jax not yet imported, so the flag lands in-process)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={N_DEVICES}"
        ).strip()
        raise SystemExit(check_quantile())

    if len(sys.argv) > 1 and sys.argv[1] == "--check-retention":
        # tiered-retention gate: host-plane banking/roll-up/query over
        # eagerly-driven services (jax not yet imported, so the platform
        # pin lands in-process; no virtual devices needed)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        raise SystemExit(check_retention())

    if len(sys.argv) > 1 and sys.argv[1] == "--check-health":
        # pipeline-health gate: host-plane serving soaks (threads + wall
        # clock + numpy); jax not yet imported, so the platform pin lands
        # in-process (no virtual devices needed)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        raise SystemExit(check_health())

    if len(sys.argv) > 1 and sys.argv[1] == "--check-ingest":
        # ingest fast-path gate: host-plane serving soaks (threads + queues
        # + numpy routing) against the eagerly-compiled bucketed scatter;
        # jax not yet imported, so the platform pin lands in-process
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        raise SystemExit(check_ingest())

    if len(sys.argv) > 1 and sys.argv[1] == "--check-collectives":
        # collective regression gate: jax is not yet imported, so the
        # virtual-device flag can be set in-process (same as --smoke)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={N_DEVICES}"
        ).strip()
        raise SystemExit(check_collectives())

    if len(sys.argv) > 1 and sys.argv[1] == "--sync8":
        # child process: CPU platform must be forced before backend init
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={N_DEVICES}"
        ).strip()
        print(json.dumps(_sync8_ab(trace_path=trace_path)))
        return

    if len(sys.argv) > 1 and sys.argv[1] == "--smoke":
        # CI smoke: 2 timed steps, no subprocess reference, same JSON schema
        # for the headline keys (tests/integrations/test_bench_smoke.py
        # validates it) — jax is not yet imported here, so the virtual-device
        # flag can be set in-process
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={N_DEVICES}"
        ).strip()
        ab = _sync8_ab(steps=2, warmup=1, trace_path=trace_path)
        out = {
            "metric": _metric_description(),
            "value": round(ab["grouped_sync8_ms"], 4),
            "unit": "ms/step",
            "grouped_sync8_ms": round(ab["grouped_sync8_ms"], 4),
            "ungrouped_sync8_ms": round(ab["ungrouped_sync8_ms"], 4),
            "states_synced": ab["states_synced"],
            "states_synced_ungrouped": ab["states_synced_ungrouped"],
            "gather_coalesced_ms": round(ab["gather_coalesced_ms"], 4),
            "gather_per_leaf_ms": round(ab["gather_per_leaf_ms"], 4),
            "gather_states_synced": ab["gather_states_synced"],
            "gather_hier_ms": round(ab["gather_hier_ms"], 4),
            "gather_flat2d_ms": round(ab["gather_flat2d_ms"], 4),
            "smoke": True,
        }
        out.update({k: ab[k] for k in _TRACE_KEYS if k in ab})
        print(json.dumps(out))
        return

    here = os.path.dirname(os.path.abspath(__file__))

    child_argv = [sys.executable, os.path.abspath(__file__), "--sync8"]
    if trace_path is not None:
        child_argv += ["--trace", trace_path]
    child = subprocess.run(
        child_argv,
        # the full A/B now carries the mixed-collection megafusion scenarios
        # on top of the gather planes; give it headroom beyond 600s
        capture_output=True, text=True, timeout=1200,
        env={**os.environ, "PYTHONPATH": here},
    )
    if child.returncode != 0 or not child.stdout.strip():
        raise RuntimeError(
            f"--sync8 child failed (rc={child.returncode}):\n{child.stderr[-2000:]}"
        )
    ab = json.loads(child.stdout.strip().splitlines()[-1])
    ours_sync8_ms = ab["grouped_sync8_ms"]

    try:
        ref_sync8_ms = bench_reference_sync8()
        vs_baseline = ref_sync8_ms / ours_sync8_ms
    except Exception:
        ref_sync8_ms = float("nan")
        vs_baseline = float("nan")

    try:
        ours_fused_ms = bench_ours_fused_singlechip()
        ref_eager_ms = bench_reference_eager_update()
        fused_vs_ref = ref_eager_ms / ours_fused_ms
        # 0.01 ms is the floor bench_ours_fused_singlechip clamps to when
        # XLA fuses the metric update into the train step below timing
        # resolution; the ratio is then a lower bound, not a point value
        marginal_at_floor = ours_fused_ms <= 0.01
    except Exception:
        ours_fused_ms = ref_eager_ms = fused_vs_ref = float("nan")
        marginal_at_floor = False

    out = {
        "metric": _metric_description(),
        "value": round(ours_sync8_ms, 4),
        "unit": "ms/step",
        "vs_baseline": round(vs_baseline, 3),
        "reference_sync8_ms": round(ref_sync8_ms, 4),
        "grouped_sync8_ms": round(ab["grouped_sync8_ms"], 4),
        "ungrouped_sync8_ms": round(ab["ungrouped_sync8_ms"], 4),
        "states_synced": ab["states_synced"],
        "states_synced_ungrouped": ab["states_synced_ungrouped"],
        "gather_coalesced_ms": round(ab["gather_coalesced_ms"], 4),
        "gather_per_leaf_ms": round(ab["gather_per_leaf_ms"], 4),
        "gather_states_synced": ab["gather_states_synced"],
        "gather_hier_ms": round(ab["gather_hier_ms"], 4),
        "gather_flat2d_ms": round(ab["gather_flat2d_ms"], 4),
        "singlechip_fused_update_ms": round(ours_fused_ms, 4),
        "singlechip_reference_eager_update_ms": round(ref_eager_ms, 4),
        "singlechip_vs_reference": round(fused_vs_ref, 3),
        "singlechip_marginal_at_floor": marginal_at_floor,
        "smoke": False,
    }
    out.update({k: ab[k] for k in _TRACE_KEYS if k in ab})
    print(json.dumps(out))


if __name__ == "__main__":
    main()
