"""Benchmark of record (BASELINE.md #3): per-step sync wall-clock of
``MetricCollection(Accuracy, F1, Precision, Recall)`` over 8 devices, with
``dist_sync_on_step`` semantics — every step updates, cross-device syncs, and
computes the collection.

Ours: one jitted ``shard_map`` step over an 8-device mesh (virtual CPU devices
— multi-chip TPU hardware is not available in this image; the XLA collective
code paths are the same): per-shard fused update, ``psum`` sync of every
state, replicated compute. Measured in a subprocess so the parent process can
keep the default (TPU) backend for the single-chip number.

Baseline: the actual reference torchmetrics (mounted at /root/reference,
imported in-place) on an 8-process Gloo group — its own distributed story
(reference tests/helpers/testers.py:41-47) — driving the same collection's
``forward`` with ``dist_sync_on_step=True`` per step.

Also reported (extra keys): the single-chip marginal cost of folding the fused
collection update into an already-jitted train step on the default backend
(TPU when available), vs the reference's eager per-step ``update`` on torch
CPU — the single-device deployment number.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} where
value is our 8-device sync-in-the-loop ms/step and vs_baseline =
reference_ms / our_ms (>1 means we are faster than the reference). The line
also carries the compute-groups A/B ("grouped_sync8_ms" vs
"ungrouped_sync8_ms", with "states_synced" counts) so BENCH_r* tracks the
group/coalescing gain. ``--smoke`` runs a 2-step, no-reference version with
the same headline schema for CI (tests/integrations/test_bench_smoke.py).

``--trace OUT.json`` (composable with ``--smoke``) enables the observability
subsystem around the A/B: the JSON line grows ``collective_calls`` /
``sync_bytes`` (collectives staged per step program, from
``metrics_tpu.observability.counters``, replacing ad-hoc timers for the
per-phase story), a ``phase_ms`` span-aggregate table, and OUT.json gets a
Chrome-trace/Perfetto file of the bench phases (load at ui.perfetto.dev).
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

# resolve `benchmarks.timing` regardless of the caller's cwd; do NOT use
# PYTHONPATH for this (it breaks the axon TPU plugin registration)
_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

# persistent XLA compile cache: the chained-loop train-step programs are the
# slow part of this benchmark; cached, a re-run is seconds
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(_HERE, ".jax_cache_tpu"))

N_STEPS = 100
WARMUP = 10
BATCH_PER_DEVICE = 512
N_DEVICES = 8
NUM_CLASSES = 32
FEATURES = 256


def _collection_ours(compute_groups: bool = True):
    from metrics_tpu import Accuracy, F1, MetricCollection, Precision, Recall

    return MetricCollection([
        Accuracy(),
        F1(num_classes=NUM_CLASSES, average="macro"),
        Precision(num_classes=NUM_CLASSES, average="macro"),
        Recall(num_classes=NUM_CLASSES, average="macro"),
    ], compute_groups=compute_groups)


def _shard_map(fn, mesh, in_specs, out_specs):
    """jax.shard_map on current jax; the experimental module on older jax."""
    from metrics_tpu.utils.compat import shard_map

    return shard_map(fn, mesh, in_specs, out_specs)


def _build_sync8_runner(compute_groups: bool):
    """(timed_run(steps) -> ms/step, states_synced) for one A/B variant.

    ``states_synced`` counts the state leaves entering the per-step
    collective sync — compute groups shrink it (one state pytree per
    group), coalesced sync then buckets what remains.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    pure = _collection_ours(compute_groups).pure()
    mesh = Mesh(np.array(jax.devices("cpu")[:N_DEVICES]), ("dp",))

    def step(state, preds, target):
        # local shard delta -> one collective sync -> replicated accumulate
        delta = pure.update(pure.init(), preds, target)
        delta = pure.sync(delta, "dp")
        state = pure.merge(state, delta)
        return state, pure.compute(state)

    sharded_step = jax.jit(
        _shard_map(step, mesh, in_specs=(P(), P("dp"), P("dp")), out_specs=(P(), P()))
    )

    rng = np.random.RandomState(0)
    batch = BATCH_PER_DEVICE * N_DEVICES
    logits = rng.rand(batch, NUM_CLASSES).astype(np.float32)
    preds = jnp.asarray(logits / logits.sum(-1, keepdims=True))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, batch).astype(np.int32))

    states_synced = len(jax.tree_util.tree_leaves(pure.init()))

    def run(steps: int) -> float:
        state = pure.init()
        out = None
        start = time.perf_counter()
        for _ in range(steps):
            state, out = sharded_step(state, preds, target)
        jax.block_until_ready(out)
        return (time.perf_counter() - start) / steps * 1e3

    return run, states_synced


def bench_ours_sync8(compute_groups: bool = True, steps: int = N_STEPS, warmup: int = WARMUP):
    """Per-step update + psum-sync + compute of the collection over an
    8-device mesh (the metric of record). Runs on virtual CPU devices."""
    run, states_synced = _build_sync8_runner(compute_groups)
    run(warmup)
    return run(steps), states_synced


def _sync8_ab(steps: int = N_STEPS, warmup: int = WARMUP, repeats: int = 3, trace_path=None) -> dict:
    """Compute-groups on/off A/B over the same 8-device mesh program.

    The two variants are timed in INTERLEAVED rounds and reported as the
    best-of — a monotonic load drift would otherwise bias whichever variant
    ran second (the A/B is a difference of two absolute measurements).

    With ``trace_path`` set, the observability subsystem is enabled around
    the whole A/B: the per-variant collective counters are snapshotted over
    the compiling first call (staged collectives per step program — the
    honest per-step collective cost), the bench phases are spanned, and a
    Perfetto-loadable Chrome trace is written to ``trace_path``. The result
    then carries ``collective_calls`` / ``sync_bytes`` (grouped program) and
    a ``phase_ms`` table from the span aggregates.
    """
    obs = None
    if trace_path is not None:
        from metrics_tpu import observability as obs_mod

        obs = obs_mod
        obs.enable()
        obs.reset()

    def build(compute_groups: bool, label: str):
        if obs is None:
            run, states = _build_sync8_runner(compute_groups)
            run(warmup)
            return run, states, None
        with obs.span(f"bench.build_{label}"):
            run, states = _build_sync8_runner(compute_groups)
        obs.COUNTERS.reset()
        with obs.span(f"bench.compile_{label}"):
            run(1)  # first call traces+compiles: counters now hold the program's collectives
        counters = obs.counters_snapshot()
        with obs.span(f"bench.warmup_{label}"):
            run(max(warmup - 1, 1))
        return run, states, counters

    run_grouped, states_grouped, grouped_counters = build(True, "grouped")
    run_ungrouped, states_ungrouped, ungrouped_counters = build(False, "ungrouped")
    grouped_times, ungrouped_times = [], []
    for _ in range(repeats):
        with (obs.span("bench.timed_grouped") if obs else _null_cm()):
            grouped_times.append(run_grouped(steps))
        with (obs.span("bench.timed_ungrouped") if obs else _null_cm()):
            ungrouped_times.append(run_ungrouped(steps))
    grouped_ms = min(grouped_times)
    ungrouped_ms = min(ungrouped_times)
    out = {
        "grouped_sync8_ms": grouped_ms,
        "ungrouped_sync8_ms": ungrouped_ms,
        "states_synced": states_grouped,
        "states_synced_ungrouped": states_ungrouped,
    }
    if obs is not None:
        out["collective_calls"] = grouped_counters["collective_calls"]
        out["sync_bytes"] = grouped_counters["sync_bytes"]
        out["collective_calls_ungrouped"] = ungrouped_counters["collective_calls"]
        out["sync_bytes_ungrouped"] = ungrouped_counters["sync_bytes"]
        out["counters"] = grouped_counters
        out["phase_ms"] = {
            name: round(row["total_ms"], 3) for name, row in sorted(obs.summarize().items())
        }
        out["trace_file"] = trace_path
        obs.write_chrome_trace(trace_path)
        obs.disable()
    return out


def _null_cm():
    import contextlib

    return contextlib.nullcontext()


def _trace_arg(argv) -> "str | None":
    """Value of ``--trace OUT.json`` anywhere on the command line, else None."""
    if "--trace" in argv:
        i = argv.index("--trace")
        if i + 1 >= len(argv):
            raise SystemExit("--trace requires an output path")
        return argv[i + 1]
    return None


def _ref_sync8_worker(rank: int, world_size: int, steps: int, out_q) -> None:
    import torch
    import torch.distributed as dist

    sys.path.insert(0, "/root/reference")
    from torchmetrics import Accuracy, F1, MetricCollection, Precision, Recall

    dist.init_process_group(
        "gloo", init_method="tcp://127.0.0.1:29511", rank=rank, world_size=world_size
    )
    collection = MetricCollection([
        Accuracy(dist_sync_on_step=True),
        F1(num_classes=NUM_CLASSES, average="macro", dist_sync_on_step=True),
        Precision(num_classes=NUM_CLASSES, average="macro", dist_sync_on_step=True),
        Recall(num_classes=NUM_CLASSES, average="macro", dist_sync_on_step=True),
    ])

    rng = np.random.RandomState(rank)
    logits = rng.rand(BATCH_PER_DEVICE, NUM_CLASSES).astype(np.float32)
    preds = torch.from_numpy(logits / logits.sum(-1, keepdims=True))
    target = torch.from_numpy(rng.randint(0, NUM_CLASSES, BATCH_PER_DEVICE).astype(np.int64))

    for _ in range(WARMUP):
        collection(preds, target)
    dist.barrier()
    start = time.perf_counter()
    for _ in range(steps):
        collection(preds, target)
    dist.barrier()
    elapsed_ms = (time.perf_counter() - start) / steps * 1e3
    if rank == 0:
        out_q.put(elapsed_ms)
    dist.destroy_process_group()


def bench_reference_sync8() -> float:
    """Reference collection forward with dist_sync_on_step=True on an
    8-process Gloo group (the reference's own distributed mechanism)."""
    import torch.multiprocessing as mp

    ctx = mp.get_context("spawn")
    out_q = ctx.Queue()
    procs = [
        ctx.Process(target=_ref_sync8_worker, args=(r, N_DEVICES, N_STEPS // 2, out_q))
        for r in range(N_DEVICES)
    ]
    for p in procs:
        p.start()
    try:
        # a dead/hung worker (port clash, init failure) must not hang the bench
        result = out_q.get(timeout=240)
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
    return result


def bench_ours_fused_singlechip() -> float:
    """Marginal cost of folding the fused collection update into a jitted
    train step on the default backend (TPU when available).

    Timing protocol (tunnel-proof): through the axon TPU tunnel,
    ``jax.block_until_ready`` does NOT wait for device execution (it returns
    in ~0.1 ms for work that takes hundreds of ms; only a value readback
    forces and awaits execution — see benchmarks/roofline.py). So each
    variant runs K chained train steps inside ONE jitted ``lax.fori_loop``
    (step i+1 consumes step i's weights/metric state — nothing can be
    hoisted or elided), is timed via a forcing scalar readback at two
    different K, and per-step = (T(K2) - T(K1)) / (K2 - K1): the ~99 ms
    readback floor cancels exactly. Correct on every backend.
    """
    import functools

    import jax
    from jax import lax
    import jax.numpy as jnp

    pure = _collection_ours().pure()
    batch = BATCH_PER_DEVICE * N_DEVICES

    rng = np.random.RandomState(0)
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, batch).astype(np.int32))
    x = jnp.asarray(rng.rand(batch, FEATURES).astype(np.float32))
    w0 = jnp.asarray(rng.rand(FEATURES, NUM_CLASSES).astype(np.float32))

    def loss(w):
        return -jnp.mean(jax.nn.log_softmax(x @ w)[jnp.arange(batch), target])

    @functools.partial(jax.jit, static_argnums=0)
    def run_plain(k, w):
        def body(_, w):
            return w - 0.01 * jax.grad(loss)(w)

        return lax.fori_loop(0, k, body, w)[0, 0]

    @functools.partial(jax.jit, static_argnums=0)
    def run_with_metrics(k, w, state):
        def body(_, carry):
            w, st = carry
            g = jax.grad(loss)(w)
            probs = jax.nn.softmax(x @ w)
            st = pure.update(st, probs, target)
            return w - 0.01 * g, st

        w, st = lax.fori_loop(0, k, body, (w, state))
        # fold every metric-state leaf into the readback so the whole chain
        # (train step AND metric update) is forced
        acc = w[0, 0]
        for leaf in jax.tree_util.tree_leaves(st):
            acc = acc + leaf.astype(jnp.float32).sum()
        return acc

    from benchmarks.timing import best_of, two_k_delta

    k1, k2 = 5, 105

    def per_step_ms(run, *args):
        float(run(k1, *args))  # compile both K variants + warm the path
        float(run(k2, *args))
        return two_k_delta(
            lambda k: best_of(lambda: float(run(k, *args))), k1, k2
        ) * 1e3

    # the marginal is a DIFFERENCE of two measurements; alternate the order
    # pair to pair (cancels monotonic drift) and take the median
    diffs = []
    for i in range(3):
        if i % 2 == 0:
            t_plain = per_step_ms(run_plain, w0)
            t_with = per_step_ms(run_with_metrics, w0, pure.init())
        else:
            t_with = per_step_ms(run_with_metrics, w0, pure.init())
            t_plain = per_step_ms(run_plain, w0)
        diffs.append(t_with - t_plain)
    # floor at ~timing resolution: XLA often fuses the metric update into the
    # step for free, making the true marginal indistinguishable from noise
    return max(sorted(diffs)[len(diffs) // 2], 0.01)


def bench_reference_eager_update() -> float:
    """Reference eager per-step collection update, torch CPU (single-device)."""
    sys.path.insert(0, "/root/reference")
    import torch
    from torchmetrics import Accuracy, F1, MetricCollection, Precision, Recall

    collection = MetricCollection([
        Accuracy(),
        F1(num_classes=NUM_CLASSES, average="macro"),
        Precision(num_classes=NUM_CLASSES, average="macro"),
        Recall(num_classes=NUM_CLASSES, average="macro"),
    ])

    batch = BATCH_PER_DEVICE * N_DEVICES
    rng = np.random.RandomState(0)
    logits = rng.rand(batch, NUM_CLASSES).astype(np.float32)
    preds = torch.from_numpy(logits / logits.sum(-1, keepdims=True))
    target = torch.from_numpy(rng.randint(0, NUM_CLASSES, batch).astype(np.int64))

    for _ in range(WARMUP):
        collection.update(preds, target)
    start = time.perf_counter()
    for _ in range(N_STEPS):
        collection.update(preds, target)
    return (time.perf_counter() - start) / N_STEPS * 1e3


def _metric_description() -> str:
    return (
        "per-step update+psum-sync+compute of MetricCollection(Accuracy,F1,"
        f"Precision,Recall), dist_sync_on_step, 8 devices ({BATCH_PER_DEVICE}"
        f"x{NUM_CLASSES} per device; ours: shard_map on 8 virtual CPU devices,"
        " compute groups + coalesced collectives, reference: torchmetrics"
        " forward on 8-process Gloo)"
    )


# extra keys _sync8_ab emits when tracing; the parent copies them verbatim
# from the child's JSON (full mode) or the in-process dict (smoke mode)
_TRACE_KEYS = (
    "collective_calls",
    "sync_bytes",
    "collective_calls_ungrouped",
    "sync_bytes_ungrouped",
    "counters",
    "phase_ms",
    "trace_file",
)


def main() -> None:
    trace_path = _trace_arg(sys.argv)
    if len(sys.argv) > 1 and sys.argv[1] == "--sync8":
        # child process: CPU platform must be forced before backend init
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={N_DEVICES}"
        ).strip()
        print(json.dumps(_sync8_ab(trace_path=trace_path)))
        return

    if len(sys.argv) > 1 and sys.argv[1] == "--smoke":
        # CI smoke: 2 timed steps, no subprocess reference, same JSON schema
        # for the headline keys (tests/integrations/test_bench_smoke.py
        # validates it) — jax is not yet imported here, so the virtual-device
        # flag can be set in-process
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={N_DEVICES}"
        ).strip()
        ab = _sync8_ab(steps=2, warmup=1, trace_path=trace_path)
        out = {
            "metric": _metric_description(),
            "value": round(ab["grouped_sync8_ms"], 4),
            "unit": "ms/step",
            "grouped_sync8_ms": round(ab["grouped_sync8_ms"], 4),
            "ungrouped_sync8_ms": round(ab["ungrouped_sync8_ms"], 4),
            "states_synced": ab["states_synced"],
            "states_synced_ungrouped": ab["states_synced_ungrouped"],
            "smoke": True,
        }
        out.update({k: ab[k] for k in _TRACE_KEYS if k in ab})
        print(json.dumps(out))
        return

    here = os.path.dirname(os.path.abspath(__file__))

    child_argv = [sys.executable, os.path.abspath(__file__), "--sync8"]
    if trace_path is not None:
        child_argv += ["--trace", trace_path]
    child = subprocess.run(
        child_argv,
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "PYTHONPATH": here},
    )
    if child.returncode != 0 or not child.stdout.strip():
        raise RuntimeError(
            f"--sync8 child failed (rc={child.returncode}):\n{child.stderr[-2000:]}"
        )
    ab = json.loads(child.stdout.strip().splitlines()[-1])
    ours_sync8_ms = ab["grouped_sync8_ms"]

    try:
        ref_sync8_ms = bench_reference_sync8()
        vs_baseline = ref_sync8_ms / ours_sync8_ms
    except Exception:
        ref_sync8_ms = float("nan")
        vs_baseline = float("nan")

    try:
        ours_fused_ms = bench_ours_fused_singlechip()
        ref_eager_ms = bench_reference_eager_update()
        fused_vs_ref = ref_eager_ms / ours_fused_ms
        # 0.01 ms is the floor bench_ours_fused_singlechip clamps to when
        # XLA fuses the metric update into the train step below timing
        # resolution; the ratio is then a lower bound, not a point value
        marginal_at_floor = ours_fused_ms <= 0.01
    except Exception:
        ours_fused_ms = ref_eager_ms = fused_vs_ref = float("nan")
        marginal_at_floor = False

    out = {
        "metric": _metric_description(),
        "value": round(ours_sync8_ms, 4),
        "unit": "ms/step",
        "vs_baseline": round(vs_baseline, 3),
        "reference_sync8_ms": round(ref_sync8_ms, 4),
        "grouped_sync8_ms": round(ab["grouped_sync8_ms"], 4),
        "ungrouped_sync8_ms": round(ab["ungrouped_sync8_ms"], 4),
        "states_synced": ab["states_synced"],
        "states_synced_ungrouped": ab["states_synced_ungrouped"],
        "singlechip_fused_update_ms": round(ours_fused_ms, 4),
        "singlechip_reference_eager_update_ms": round(ref_eager_ms, 4),
        "singlechip_vs_reference": round(fused_vs_ref, 3),
        "singlechip_marginal_at_floor": marginal_at_floor,
        "smoke": False,
    }
    out.update({k: ab[k] for k in _TRACE_KEYS if k in ab})
    print(json.dumps(out))


if __name__ == "__main__":
    main()
