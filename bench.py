"""Benchmark of record (BASELINE.md #3): per-step update+sync wall-clock of
``MetricCollection(Accuracy, F1, Precision, Recall)``.

Ours: one fused jitted step (single update pass, donated states) on the
default JAX backend (TPU chip under the driver). Baseline: the actual
reference torchmetrics (mounted at /root/reference, imported in-place, torch
CPU — the only reference runtime available in this image) driving the same
collection with the same data.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where value
is our ms/step and vs_baseline = reference_ms / our_ms (>1 means faster than
the reference).
"""
import json
import sys
import time

import numpy as np

N_STEPS = 50
WARMUP = 5
BATCH = 4096
NUM_CLASSES = 32


def bench_ours() -> float:
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, F1, MetricCollection, Precision, Recall

    collection = MetricCollection([
        Accuracy(),
        F1(num_classes=NUM_CLASSES, average="macro"),
        Precision(num_classes=NUM_CLASSES, average="macro"),
        Recall(num_classes=NUM_CLASSES, average="macro"),
    ])
    pure = collection.pure()

    rng = np.random.RandomState(0)
    logits = rng.rand(BATCH, NUM_CLASSES).astype(np.float32)
    preds = jnp.asarray(logits / logits.sum(-1, keepdims=True))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, BATCH).astype(np.int32))

    donate = (0,) if jax.default_backend() == "tpu" else ()
    step = jax.jit(lambda state, p, t: pure.update(state, p, t), donate_argnums=donate)

    state = pure.init()
    for _ in range(WARMUP):
        state = step(state, preds, target)
    jax.block_until_ready(state)

    start = time.perf_counter()
    for _ in range(N_STEPS):
        state = step(state, preds, target)
    jax.block_until_ready(state)
    return (time.perf_counter() - start) / N_STEPS * 1e3  # ms/step


def bench_reference() -> float:
    sys.path.insert(0, "/root/reference")
    import torch
    from torchmetrics import Accuracy, F1, MetricCollection, Precision, Recall

    collection = MetricCollection([
        Accuracy(),
        F1(num_classes=NUM_CLASSES, average="macro"),
        Precision(num_classes=NUM_CLASSES, average="macro"),
        Recall(num_classes=NUM_CLASSES, average="macro"),
    ])

    rng = np.random.RandomState(0)
    logits = rng.rand(BATCH, NUM_CLASSES).astype(np.float32)
    preds = torch.from_numpy(logits / logits.sum(-1, keepdims=True))
    target = torch.from_numpy(rng.randint(0, NUM_CLASSES, BATCH).astype(np.int64))

    for _ in range(WARMUP):
        collection.update(preds, target)

    start = time.perf_counter()
    for _ in range(N_STEPS):
        collection.update(preds, target)
    return (time.perf_counter() - start) / N_STEPS * 1e3


def main() -> None:
    ours_ms = bench_ours()
    try:
        ref_ms = bench_reference()
        vs_baseline = ref_ms / ours_ms
    except Exception:
        vs_baseline = float("nan")

    print(
        json.dumps(
            {
                "metric": "MetricCollection(Accuracy,F1,Precision,Recall) fused update wall-clock/step "
                          f"(batch {BATCH}x{NUM_CLASSES}) vs reference torchmetrics (torch CPU)",
                "value": round(ours_ms, 4),
                "unit": "ms/step",
                "vs_baseline": round(vs_baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
