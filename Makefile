.PHONY: test clean bench

# run the full suite on 8 fake CPU devices (the conftest forces the platform)
test:
	XLA_FLAGS="--xla_force_host_platform_device_count=8" python -m pytest tests/ -q

bench:
	python bench.py

clean:
	rm -rf .pytest_cache build dist *.egg-info
	find . -name __pycache__ -type d -exec rm -rf {} +
