.PHONY: test test-tpu doctest clean bench docs

# generate the API reference from live docstrings, then render the whole
# docs tree (README + guides + API) into a browsable static HTML site
docs:
	python docs/gen_api.py docs/api.md
	python docs/build_html.py docs/site

# full suite + package doctests on 8 fake CPU devices (root conftest forces
# the platform; see conftest.py)
test:
	python -m pytest --doctest-modules metrics_tpu/ tests/ -q

# validation run on the real default backend (TPU when available)
test-tpu:
	METRICS_TPU_TEST_PLATFORM=tpu python -m pytest tests/ -q

doctest:
	python -m pytest --doctest-modules metrics_tpu/ -q

bench:
	python bench.py

clean:
	rm -rf .pytest_cache build dist *.egg-info
	find . -name __pycache__ -type d -exec rm -rf {} +
