"""Test harness configuration: 8 fake CPU devices.

The reference tests multi-node without a cluster via a 2-process Gloo group
(reference tests/helpers/testers.py:41-47). The TPU build's analogue is an
8-device virtual CPU mesh: collectives run through the same XLA code paths as
on a real TPU slice, just on host devices.

NOTE: the axon TPU plugin ignores the JAX_PLATFORMS env var, so we force the
CPU platform through jax.config before any backend is initialized.
"""
import os

# must be set before the CPU client is created
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    devices = jax.devices()
    assert len(devices) == 8, f"expected 8 fake CPU devices, got {len(devices)}"
    return devices
