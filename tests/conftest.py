"""Test harness fixtures.

Platform forcing (8 fake CPU devices, or real hardware via
METRICS_TPU_TEST_PLATFORM=tpu) lives in the root ``conftest.py`` so it also
covers ``--doctest-modules metrics_tpu``.
"""
import os

import jax
import pytest

import metrics_tpu

if os.environ.get("METRICS_TPU_TEST_PLATFORM", "cpu") == "cpu":
    # The oracle grid builds thousands of short-lived metric instances; auto-jit
    # would pay an XLA compile per instance on the suite's single CPU core. The
    # fused jit path keeps dedicated coverage via explicit `jit=True` tests.
    metrics_tpu.set_default_jit(False)
# On real hardware the tradeoff inverts: eager dispatch pays a tunnel RTT per
# op, so the auto-jit fused step (one dispatch per batch) stays enabled.


@pytest.fixture(scope="session")
def eight_devices():
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip(f"needs 8 devices, have {len(devices)}")
    return devices[:8]
