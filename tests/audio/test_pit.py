"""PIT vs brute-force permutation search with an independent metric."""
import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import PIT
from metrics_tpu.functional import permutation_invariant_training, pit_permutate
from metrics_tpu.functional.audio.si_sdr import _si_sdr_per_example

_rng = np.random.RandomState(43)
B, S, T = 4, 3, 64


def _np_si_sdr(p, t):
    p, t = p.astype(np.float64), t.astype(np.float64)
    alpha = (p * t).sum(-1, keepdims=True) / np.maximum((t**2).sum(-1, keepdims=True), 1e-8)
    s = alpha * t
    return 10 * np.log10(np.maximum((s**2).sum(-1), 1e-8) / np.maximum(((p - s) ** 2).sum(-1), 1e-8))


def _np_best(preds, target):
    best_vals, best_perms = [], []
    for b in range(preds.shape[0]):
        best, best_p = -np.inf, None
        for perm in itertools.permutations(range(S)):
            val = np.mean([_np_si_sdr(preds[b, perm[s]], target[b, s]) for s in range(S)])
            if val > best:
                best, best_p = val, perm
        best_vals.append(best)
        best_perms.append(best_p)
    return np.asarray(best_vals), np.asarray(best_perms)


def test_pit_matches_bruteforce():
    target = _rng.randn(B, S, T).astype(np.float32)
    # shuffled + noisy sources per example
    preds = np.stack([target[b, _rng.permutation(S)] for b in range(B)])
    preds = (preds + 0.1 * _rng.randn(B, S, T)).astype(np.float32)

    best, perm = permutation_invariant_training(
        jnp.asarray(preds), jnp.asarray(target), lambda p, t: _si_sdr_per_example(p, t, False)
    )
    want_vals, want_perms = _np_best(preds, target)
    np.testing.assert_allclose(np.asarray(best), want_vals, rtol=1e-4)
    np.testing.assert_array_equal(np.asarray(perm), want_perms)

    # pit_permutate aligns the sources: direct metric equals the PIT value
    aligned = pit_permutate(jnp.asarray(preds), perm)
    direct = _si_sdr_per_example(aligned, jnp.asarray(target), False).mean(-1)
    np.testing.assert_allclose(np.asarray(direct), np.asarray(best), rtol=1e-5)


def test_pit_jit_and_module():
    import metrics_tpu

    target = _rng.randn(B, S, T).astype(np.float32)
    preds = (target[:, ::-1, :] + 0.05 * _rng.randn(B, S, T)).astype(np.float32)

    fn = jax.jit(
        lambda p, t: permutation_invariant_training(p, t, lambda a, b: _si_sdr_per_example(a, b, False))
    )
    best, perm = fn(jnp.asarray(preds), jnp.asarray(target))
    assert np.all(np.asarray(perm) == np.asarray([[2, 1, 0]] * B))

    old = metrics_tpu.set_default_jit(True)
    try:
        m = PIT(lambda p, t: _si_sdr_per_example(p, t, False))
        m.update(jnp.asarray(preds), jnp.asarray(target))
        np.testing.assert_allclose(float(m.compute()), float(best.mean()), rtol=1e-5)
    finally:
        metrics_tpu.set_default_jit(old)


def test_pit_min_mode_and_validation():
    target = _rng.randn(2, 2, 32).astype(np.float32)
    preds = target[:, ::-1, :]
    mse = lambda p, t: jnp.mean((p - t) ** 2, axis=-1)
    best, perm = permutation_invariant_training(jnp.asarray(preds), jnp.asarray(target), mse, eval_func="min")
    np.testing.assert_allclose(np.asarray(best), 0.0, atol=1e-7)
    assert np.all(np.asarray(perm) == [[1, 0], [1, 0]])
    with pytest.raises(ValueError, match="eval_func"):
        permutation_invariant_training(jnp.zeros((1, 2, 8)), jnp.zeros((1, 2, 8)), mse, eval_func="best")
    with pytest.raises(ValueError, match="sources"):
        permutation_invariant_training(jnp.zeros((2, 8)), jnp.zeros((2, 8)), mse)
