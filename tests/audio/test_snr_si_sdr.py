"""SNR / SI-SDR / SI-SNR vs an independent numpy oracle."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import SI_SDR, SI_SNR, SNR
from metrics_tpu.functional import (
    scale_invariant_signal_distortion_ratio,
    scale_invariant_signal_noise_ratio,
    signal_noise_ratio,
)
from tests.helpers.testers import NUM_BATCHES, MetricTester

_rng = np.random.RandomState(7)
BATCH_SIZE, TIME = 8, 128

_target = _rng.randn(NUM_BATCHES, BATCH_SIZE, TIME).astype(np.float32)
_preds = (_target + 0.3 * _rng.randn(NUM_BATCHES, BATCH_SIZE, TIME)).astype(np.float32)


def _np_snr(preds, target, zero_mean=False):
    preds = preds.reshape(-1, TIME).astype(np.float64)
    target = target.reshape(-1, TIME).astype(np.float64)
    if zero_mean:
        preds = preds - preds.mean(-1, keepdims=True)
        target = target - target.mean(-1, keepdims=True)
    vals = 10 * np.log10((target**2).sum(-1) / ((preds - target) ** 2).sum(-1))
    return vals.mean()


def _np_si_sdr(preds, target, zero_mean=False):
    preds = preds.reshape(-1, TIME).astype(np.float64)
    target = target.reshape(-1, TIME).astype(np.float64)
    if zero_mean:
        preds = preds - preds.mean(-1, keepdims=True)
        target = target - target.mean(-1, keepdims=True)
    alpha = (preds * target).sum(-1, keepdims=True) / (target**2).sum(-1, keepdims=True)
    scaled = alpha * target
    vals = 10 * np.log10((scaled**2).sum(-1) / ((preds - scaled) ** 2).sum(-1))
    return vals.mean()


class TestSNR(MetricTester):
    atol = 1e-4
    rtol = 1e-4  # TPU log10 differs ~1e-5 relative; dB magnitudes need rtol

    @pytest.mark.parametrize("ddp", [False, True])
    @pytest.mark.parametrize("dist_sync_on_step", [False, True])
    @pytest.mark.parametrize("zero_mean", [False, True])
    def test_snr_class(self, ddp, dist_sync_on_step, zero_mean):
        self.run_class_metric_test(
            ddp=ddp,
            preds=_preds,
            target=_target,
            metric_class=SNR,
            sk_metric=lambda p, t: _np_snr(p, t, zero_mean),
            dist_sync_on_step=dist_sync_on_step,
            metric_args={"zero_mean": zero_mean},
        )

    def test_snr_functional(self):
        self.run_functional_metric_test(
            _preds, _target, metric_functional=signal_noise_ratio,
            sk_metric=lambda p, t: _np_snr(p, t),
        )


class TestSISDR(MetricTester):
    atol = 1e-4
    rtol = 1e-4

    @pytest.mark.parametrize("ddp", [False, True])
    @pytest.mark.parametrize("dist_sync_on_step", [False, True])
    @pytest.mark.parametrize("zero_mean", [False, True])
    def test_si_sdr_class(self, ddp, dist_sync_on_step, zero_mean):
        self.run_class_metric_test(
            ddp=ddp,
            preds=_preds,
            target=_target,
            metric_class=SI_SDR,
            sk_metric=lambda p, t: _np_si_sdr(p, t, zero_mean),
            dist_sync_on_step=dist_sync_on_step,
            metric_args={"zero_mean": zero_mean},
        )

    def test_si_sdr_functional(self):
        self.run_functional_metric_test(
            _preds, _target, metric_functional=scale_invariant_signal_distortion_ratio,
            sk_metric=lambda p, t: _np_si_sdr(p, t),
        )


class TestSISNR(MetricTester):
    atol = 1e-4
    rtol = 1e-4

    @pytest.mark.parametrize("ddp", [False, True])
    @pytest.mark.parametrize("dist_sync_on_step", [False, True])
    def test_si_snr_class(self, ddp, dist_sync_on_step):
        self.run_class_metric_test(
            ddp=ddp,
            preds=_preds,
            target=_target,
            metric_class=SI_SNR,
            sk_metric=lambda p, t: _np_si_sdr(p, t, zero_mean=True),
            dist_sync_on_step=dist_sync_on_step,
        )

    def test_si_snr_functional(self):
        self.run_functional_metric_test(
            _preds, _target, metric_functional=scale_invariant_signal_noise_ratio,
            sk_metric=lambda p, t: _np_si_sdr(p, t, zero_mean=True),
        )


def test_audio_metrics_jit_and_accumulation():
    """Fused forward under jit; accumulation equals the global mean."""
    import metrics_tpu

    old = metrics_tpu.set_default_jit(True)
    try:
        m = SI_SDR()
        for i in range(NUM_BATCHES):
            m(jnp.asarray(_preds[i]), jnp.asarray(_target[i]))
        np.testing.assert_allclose(float(m.compute()), _np_si_sdr(_preds, _target), atol=1e-4)
    finally:
        metrics_tpu.set_default_jit(old)


def test_snr_shape_mismatch_raises():
    with pytest.raises(RuntimeError, match="same shape"):
        signal_noise_ratio(jnp.zeros((2, 8)), jnp.zeros((2, 9)))
