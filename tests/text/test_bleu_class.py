"""BLEUScore / SacreBLEUScore classes + sacrebleu 13a tokenization."""
import numpy as np
import pytest

from metrics_tpu import BLEUScore, SacreBLEUScore
from metrics_tpu.functional import bleu_score, sacre_bleu_score
from metrics_tpu.functional.text_sacrebleu import tokenize_sacrebleu

PREDS = ["the cat is on the mat", "a dog sleeps"]
TARGET = [
    ["there is a cat on the mat", "a cat is on the mat"],
    ["the dog is sleeping", "a dog sleeps soundly"],
]


def test_class_matches_functional():
    m = BLEUScore()
    m.update(PREDS, TARGET)
    want = float(bleu_score([p.split() for p in PREDS],
                            [[r.split() for r in rs] for rs in TARGET]))
    np.testing.assert_allclose(float(m.compute()), want, atol=1e-6)


def test_streaming_is_corpus_aggregation():
    """Summed statistics across updates == one-shot corpus score (NOT a mean
    of per-batch scores)."""
    m = BLEUScore()
    m.update(PREDS[:1], TARGET[:1])
    m.update(PREDS[1:], TARGET[1:])
    one_shot = BLEUScore()
    one_shot.update(PREDS, TARGET)
    np.testing.assert_allclose(float(m.compute()), float(one_shot.compute()), atol=1e-6)
    m.reset()
    assert float(m.compute()) == 0.0


def test_smooth_and_ngram_options():
    m = BLEUScore(n_gram=2, smooth=True)
    m.update(["the cat"], [["the cat sat"]])
    assert 0.0 < float(m.compute()) <= 1.0
    with pytest.raises(ValueError, match="n_gram"):
        BLEUScore(n_gram=0)
    with pytest.raises(ValueError, match="sentences"):
        BLEUScore().update(["a"], [])


def test_13a_tokenization_rules():
    # punctuation splits off; periods split unless between digits
    assert tokenize_sacrebleu("Hello, world!") == ["Hello", ",", "world", "!"]
    assert tokenize_sacrebleu("It costs 3.50 dollars.") == \
        ["It", "costs", "3.50", "dollars", "."]
    assert tokenize_sacrebleu("A&amp;B") == ["A", "&", "B"]
    assert tokenize_sacrebleu("pre 1990-2000 post") == ["pre", "1990", "-", "2000", "post"]
    assert tokenize_sacrebleu("Hello World", lowercase=True) == ["hello", "world"]
    # char drops whitespace entirely (sacrebleu parity)
    assert tokenize_sacrebleu("ab c", tokenize="char") == ["a", "b", "c"]
    assert tokenize_sacrebleu("Hello, world!", tokenize="none") == ["Hello,", "world!"]
    with pytest.raises(ValueError, match="tokenize"):
        tokenize_sacrebleu("x", tokenize="13b")


def test_13a_matches_installed_sacrebleu_tokenizer():
    from sacrebleu.tokenizers.tokenizer_13a import Tokenizer13a

    tok = Tokenizer13a()
    probes = [
        "Hello, world!", "It costs 3.50 dollars.", "A&amp;B", "pre 1990-2000 post",
        "quo“ted” text", "semi;colon:and/slash", "(parens) [brackets] {braces}",
        "ends with period.", "12,345.67 numbers", "dash-between-words",
    ]
    for s in probes:
        assert tokenize_sacrebleu(s) == tok(s).split(), s


@pytest.mark.parametrize("tokenize", ["13a", "none", "char"])
def test_corpus_vs_installed_sacrebleu(tokenize):
    import sacrebleu

    preds = ["The cat is on the mat.", "A dog sleeps soundly!"]
    target = [["There is a cat on the mat.", "A cat is on the mat."],
              ["The dog is sleeping.", "A dog sleeps."]]
    got = float(sacre_bleu_score(preds, target, tokenize=tokenize))
    # sacrebleu wants references transposed: one list per reference position
    refs_t = [[target[i][j] for i in range(len(preds))] for j in range(2)]
    want = sacrebleu.corpus_bleu(
        preds, refs_t, smooth_method="none", tokenize=tokenize, force=True
    ).score / 100.0
    # TPU f32 exp/log in the geometric mean differ ~2e-5 from sacrebleu's
    # f64 (statistics are exact); CPU keeps the tight differential guard
    import os

    atol = 1e-4 if os.environ.get("METRICS_TPU_TEST_PLATFORM") == "tpu" else 1e-6
    np.testing.assert_allclose(got, want, atol=atol, err_msg=tokenize)


def test_sacre_bleu_vs_manual_tokenization():
    """SacreBLEU == plain BLEU over 13a-pre-tokenized text."""
    preds = ["The cat, it sat."]
    target = [["The cat sat.", "A cat, it sat down."]]
    got = float(sacre_bleu_score(preds, target))
    want = float(bleu_score([tokenize_sacrebleu(preds[0])],
                            [[tokenize_sacrebleu(r) for r in target[0]]]))
    np.testing.assert_allclose(got, want, atol=1e-6)
    m = SacreBLEUScore()
    m.update(preds, target)
    np.testing.assert_allclose(float(m.compute()), want, atol=1e-6)


def test_sacre_bleu_punctuation_matters():
    """13a separates punctuation, so 'mat.' matches 'mat .' n-grams."""
    with_13a = float(sacre_bleu_score(["the mat."], [["the mat ."]], n_gram=2))
    plain = float(BLEUScore(n_gram=2)(["the mat."], [["the mat ."]]))
    assert with_13a == pytest.approx(1.0)
    assert plain < 1.0  # whitespace split keeps 'mat.' != 'mat', '.'


def test_sacre_bleu_validation():
    with pytest.raises(ValueError, match="tokenize"):
        SacreBLEUScore(tokenize="13b")
