"""SQuAD EM/F1 vs hand-computed official-semantics values."""
import numpy as np
import pytest

from metrics_tpu import SQuAD
from metrics_tpu.functional import squad


def test_known_values():
    out = squad(["the cat"], [["The cat!", "a dog"]])
    assert out == {"exact_match": 100.0, "f1": 100.0}
    # articles and punctuation strip: "the" is removed before comparison
    out = squad(["cat"], ["the cat"])
    assert out == {"exact_match": 100.0, "f1": 100.0}
    # partial overlap: pred {brown, dog} vs ref {brown, cat}: P=R=1/2 -> F1 0.5
    out = squad(["brown dog"], ["brown cat"])
    assert out["exact_match"] == 0.0
    np.testing.assert_allclose(out["f1"], 50.0)
    # best over multiple references
    out = squad(["brown dog"], [["white fox", "brown dog here"]])
    np.testing.assert_allclose(out["f1"], 100.0 * 2 * (1.0 * (2 / 3)) / (1.0 + 2 / 3))


def test_empty_answers_v11_semantics():
    # official v1.1 script: both normalize to empty -> EM 100 but F1 0
    assert squad([""], [""]) == {"exact_match": 100.0, "f1": 0.0}
    assert squad(["the"], ["the"]) == {"exact_match": 100.0, "f1": 0.0}
    assert squad(["something"], [""]) == {"exact_match": 0.0, "f1": 0.0}


def test_single_question_flat_references():
    # a str pred with a flat list target = one question, many references
    out = squad("the cat", ["the cat", "a dog"])
    assert out == {"exact_match": 100.0, "f1": 100.0}


def test_module_accumulates():
    m = SQuAD()
    m.update(["the cat"], ["cat"])
    m.update(["wrong"], ["right answer"])
    out = m.compute()
    np.testing.assert_allclose(float(out["exact_match"]), 50.0)
    np.testing.assert_allclose(float(out["f1"]), 50.0)
    with pytest.raises(ValueError, match="same number"):
        m.update(["a"], ["a", "b"])


def test_single_question_nested_references():
    # str pred + already-nested 1-question batch form also works
    assert squad("the cat", [["the cat", "a dog"]]) == {"exact_match": 100.0, "f1": 100.0}
