"""CER / MER / WIP / WIL vs brute-force alignment oracles and hand values."""
from functools import lru_cache

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import CharErrorRate, MatchErrorRate, WordInfoLost, WordInfoPreserved
from metrics_tpu.functional import cer, match_error_rate, word_information_lost, word_information_preserved
from metrics_tpu.functional.text import _np_edit_distance_hits


def _brute_dist_hits(a, b):
    """Exhaustive recursion over alignments: min distance, then max hits."""
    a, b = tuple(a), tuple(b)

    @lru_cache(maxsize=None)
    def go(i, j):
        if i == len(a):
            return (len(b) - j, 0)
        if j == len(b):
            return (len(a) - i, 0)
        cands = []
        d, h = go(i + 1, j + 1)
        cands.append((d, h + 1) if a[i] == b[j] else (d + 1, h))
        d, h = go(i + 1, j)
        cands.append((d + 1, h))
        d, h = go(i, j + 1)
        cands.append((d + 1, h))
        return min(cands, key=lambda x: (x[0], -x[1]))

    return go(0, 0)


@pytest.mark.parametrize("seed", range(8))
def test_edit_distance_hits_vs_bruteforce(seed):
    rng = np.random.RandomState(seed)
    vocab = list("abcd")
    a = [vocab[i] for i in rng.randint(0, 4, rng.randint(0, 9))]
    b = [vocab[i] for i in rng.randint(0, 4, rng.randint(0, 9))]
    assert _np_edit_distance_hits(a, b) == _brute_dist_hits(a, b)


def test_known_values():
    # hand-checked: 3 matched words, 3 deletions
    assert match_error_rate("the cat sat", "the cat sat on the mat") == 0.5
    assert word_information_preserved("the cat sat", "the cat sat on the mat") == 0.5
    assert word_information_lost("the cat sat", "the cat sat on the mat") == 0.5
    # perfect match
    assert match_error_rate("a b", "a b") == 0.0
    assert word_information_preserved("a b", "a b") == 1.0
    # complete mismatch
    assert word_information_preserved("x y", "a b") == 0.0
    assert match_error_rate("x y", "a b") == 1.0
    # CER counts characters incl. spaces
    assert cer("ab cd", "ab cd") == 0.0
    assert cer("abcd", "abce") == 0.25


def test_modules_accumulate_as_corpus():
    """Streaming sums equal the one-shot corpus value."""
    pairs = [
        ("the cat sat", "the cat sat on the mat"),
        ("hello world", "hello there world"),
        ("exact match", "exact match"),
        ("", "non empty"),
    ]
    for cls, fn in [
        (CharErrorRate, cer),
        (MatchErrorRate, match_error_rate),
        (WordInfoPreserved, word_information_preserved),
        (WordInfoLost, word_information_lost),
    ]:
        m = cls()
        for p, t in pairs:
            m.update([p], [t])
        corpus = fn([p for p, _ in pairs], [t for _, t in pairs])
        np.testing.assert_allclose(float(m.compute()), corpus, atol=1e-6)


def test_edge_cases_and_sync():
    # empty reference: cer 0 on empty-empty, inf with errors
    assert cer("", "") == 0.0
    assert cer("abc", "") == float("inf")
    m = CharErrorRate()
    m.update([""], [""])
    assert float(m.compute()) == 0.0

    # host-plane sync across fake 2-rank world sums the stats
    m2 = MatchErrorRate(dist_sync_fn=lambda arr: [arr, arr])
    m2.update(["the cat"], ["the cat sat"])
    doubled = float(m2.compute())
    np.testing.assert_allclose(doubled, match_error_rate(["the cat"], ["the cat sat"]), atol=1e-6)  # scale-free

    with pytest.raises(ValueError, match="same number"):
        match_error_rate(["a"], ["a", "b"])
