"""WER vs a reference-free dynamic-programming oracle (full 2D DP matrix,
independent of the package's row-recurrence implementation)."""
import random

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import WER
from metrics_tpu.functional import edit_distance_padded, wer


def _oracle_edit_distance(a, b):
    dp = [[0] * (len(b) + 1) for _ in range(len(a) + 1)]
    for i in range(len(a) + 1):
        dp[i][0] = i
    for j in range(len(b) + 1):
        dp[0][j] = j
    for i in range(1, len(a) + 1):
        for j in range(1, len(b) + 1):
            dp[i][j] = min(
                dp[i - 1][j] + 1,
                dp[i][j - 1] + 1,
                dp[i - 1][j - 1] + (a[i - 1] != b[j - 1]),
            )
    return dp[len(a)][len(b)]


WORDS = ["the", "cat", "sat", "on", "mat", "dog", "ran", "fast", "slow", "big"]


def _random_sentence(rng, lo=0, hi=12):
    return " ".join(rng.choice(WORDS) for _ in range(rng.randint(lo, hi)))


def test_wer_known_values():
    assert wer("the cat sat", "the cat sat") == 0.0
    assert wer("the cat sat", "the cat sat on the mat") == 0.5
    assert wer("", "a b") == 1.0
    assert wer("a b", "a b c d") == 0.5


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_wer_vs_oracle_random(seed):
    rng = random.Random(seed)
    preds = [_random_sentence(rng) for _ in range(20)]
    target = [_random_sentence(rng, lo=1) for _ in range(20)]
    errors = sum(_oracle_edit_distance(p.split(), t.split()) for p, t in zip(preds, target))
    total = sum(len(t.split()) for t in target)
    np.testing.assert_allclose(wer(preds, target), errors / total, atol=1e-9)


def test_wer_module_accumulates():
    rng = random.Random(5)
    m = WER()
    errors = total = 0
    for _ in range(4):
        p = [_random_sentence(rng) for _ in range(5)]
        t = [_random_sentence(rng, lo=1) for _ in range(5)]
        m.update(p, t)
        errors += sum(_oracle_edit_distance(a.split(), b.split()) for a, b in zip(p, t))
        total += sum(len(b.split()) for b in t)
    np.testing.assert_allclose(float(m.compute()), errors / total, atol=1e-7)


def test_wer_mismatched_lengths():
    with pytest.raises(ValueError, match="same number"):
        wer(["a"], ["a", "b"])


def test_wer_empty_reference_and_counts_cache():
    # empty reference: perfect empty match is 0.0, errors are inf — same for
    # the functional and the module
    assert wer("", "") == 0.0
    assert wer("a b", "") == float("inf")
    m = WER()
    m.update("a b", "")
    assert float(m.compute()) == float("inf")

    # pre-tokenized input nests one level (a flat list is a batch)
    np.testing.assert_allclose(wer([["the", "cat"]], [["the", "cat", "sat"]]), 1 / 3)

    # update_counts invalidates the compute cache
    m2 = WER()
    m2.update_counts(jnp.array([2]), jnp.array([4]))
    assert float(m2.compute()) == 0.5
    m2.update_counts(jnp.array([10]), jnp.array([4]))
    np.testing.assert_allclose(float(m2.compute()), 12 / 8)


@pytest.mark.parametrize("seed", [3, 4])
def test_device_edit_distance_vs_oracle(seed):
    rng = np.random.RandomState(seed)
    B, N, M, V = 8, 10, 12, 6
    pred_len = rng.randint(0, N + 1, B)
    target_len = rng.randint(1, M + 1, B)
    pred = rng.randint(1, V, (B, N))
    target = rng.randint(1, V, (B, M))

    got = np.asarray(
        edit_distance_padded(
            jnp.asarray(pred), jnp.asarray(target), jnp.asarray(pred_len), jnp.asarray(target_len)
        )
    )
    for b in range(B):
        want = _oracle_edit_distance(list(pred[b, :pred_len[b]]), list(target[b, :target_len[b]]))
        assert got[b] == want, (b, got[b], want)


def test_device_edit_distance_jit_and_counts_path():
    import jax

    p = jnp.array([[1, 2, 3, 0], [4, 4, 4, 4]])
    t = jnp.array([[1, 9, 3, 4], [4, 4, 0, 0]])
    pl, tl = jnp.array([3, 4]), jnp.array([4, 2])
    dists = jax.jit(edit_distance_padded)(p, t, pl, tl)
    assert list(np.asarray(dists)) == [2, 2]

    m = WER()
    m.update_counts(dists, tl)
    np.testing.assert_allclose(float(m.compute()), 4 / 6, atol=1e-7)


def test_edit_distance_length_validation():
    import jax

    p = jnp.array([[1, 2, 3, 0]])
    t = jnp.array([[1, 9, 3, 4]])
    with pytest.raises(ValueError, match="target_len"):
        edit_distance_padded(p, t, jnp.array([3]), jnp.array([5]))
    with pytest.raises(ValueError, match="pred_len"):
        edit_distance_padded(p, t, jnp.array([-1]), jnp.array([4]))
    # under tracing values are unknown: out-of-range lengths clamp to the
    # boundary instead of erroring (documented contract)
    out = jax.jit(edit_distance_padded)(p, t, jnp.array([3]), jnp.array([9]))
    want = edit_distance_padded(p, t, jnp.array([3]), jnp.array([4]))
    assert int(out[0]) == int(want[0])
