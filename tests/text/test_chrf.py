"""chrF vs the REAL sacrebleu library (installed in the image) plus
hand-derived cases for the scoring conventions."""
import numpy as np
import pytest
import sacrebleu

from metrics_tpu import CHRFScore
from metrics_tpu.functional import chrf_score


def _oracle(preds, target, order=6, beta=2.0, eps_smoothing=False):
    """sacrebleu itself — the genuinely independent implementation."""
    chrf = sacrebleu.CHRF(char_order=order, word_order=0, beta=int(beta),
                          eps_smoothing=eps_smoothing)
    return chrf.corpus_score(list(preds), [list(target)]).score / 100.0


def test_identical_sentences():
    assert chrf_score(["the cat sat"], ["the cat sat"]) == pytest.approx(1.0)


def test_disjoint_sentences():
    assert chrf_score(["aaaa"], ["bbbb"]) == pytest.approx(0.0)


def test_hand_case_single_order():
    """order=1, beta=1: hyp 'ab' vs ref 'abc' (whitespace-free): matches=2,
    hyp=2, ref=3 -> P=1, R=2/3, F1=0.8 — computed on paper."""
    assert chrf_score(["ab"], ["abc"], n_char_order=1, beta=1.0) == pytest.approx(0.8)


def test_hand_case_beta_weighting():
    """beta=2 weights recall: same stats give F = 5*P*R/(4P+R) = 5*(2/3)/(4+2/3)."""
    want = 5 * (2 / 3) / (4 + 2 / 3)
    assert chrf_score(["ab"], ["abc"], n_char_order=1, beta=2.0) == pytest.approx(want)


def test_short_hypothesis_vs_sacrebleu():
    """'ab' vs 'abcdef' exercises the effective-order averaging exactly as
    sacrebleu does (avg P/R over both-sides orders, one F of the averages)."""
    got = chrf_score(["ab"], ["abcdef"])
    np.testing.assert_allclose(got, _oracle(["ab"], ["abcdef"]), atol=1e-9)


@pytest.mark.parametrize("eps_smoothing", [False, True])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_random_corpora_vs_sacrebleu(seed, eps_smoothing):
    rng = np.random.RandomState(seed)
    vocab = list("abcdefg ")
    preds = ["".join(rng.choice(vocab, rng.randint(3, 30))).strip() or "a" for _ in range(12)]
    target = ["".join(rng.choice(vocab, rng.randint(3, 30))).strip() or "b" for _ in range(12)]
    got = chrf_score(preds, target, eps_smoothing=eps_smoothing)
    np.testing.assert_allclose(
        got, _oracle(preds, target, eps_smoothing=eps_smoothing), atol=1e-7
    )


def test_mixed_length_corpus_vs_sacrebleu():
    preds = ["the cat is on the mat", "ab", "x"]
    target = ["the cat sat on the mat", "abcdefgh", "xyz"]
    np.testing.assert_allclose(chrf_score(preds, target), _oracle(preds, target), atol=1e-9)


def test_streaming_equals_corpus():
    """Batch-streamed statistics equal sacrebleu's one-shot corpus score
    (the sum-then-score aggregation, not a mean of batch scores)."""
    rng = np.random.RandomState(7)
    vocab = list("abcde ")
    preds = ["".join(rng.choice(vocab, rng.randint(4, 20))).strip() or "a" for _ in range(9)]
    target = ["".join(rng.choice(vocab, rng.randint(4, 20))).strip() or "b" for _ in range(9)]
    m = CHRFScore()
    for i in range(3):
        m.update(preds[i * 3:(i + 1) * 3], target[i * 3:(i + 1) * 3])
    np.testing.assert_allclose(float(m.compute()), _oracle(preds, target), atol=1e-6)
    m.reset()
    assert float(m.compute()) == 0.0


def test_whitespace_and_lowercase_options():
    # with whitespace kept, 'a b' vs 'ab' shares only the chars, not the bigram
    strict = chrf_score(["a b"], ["ab"], n_char_order=2, whitespace=True)
    loose = chrf_score(["a b"], ["ab"], n_char_order=2, whitespace=False)
    assert loose == pytest.approx(1.0) and strict < loose
    assert chrf_score(["AB"], ["ab"], lowercase=True) == pytest.approx(1.0)
    assert chrf_score(["AB"], ["ab"], lowercase=False) == pytest.approx(0.0)


def test_validation():
    with pytest.raises(ValueError, match="sentences"):
        chrf_score(["a", "b"], ["a"])
    with pytest.raises(ValueError, match="positive"):
        CHRFScore(n_char_order=0)
    with pytest.raises(ValueError, match="beta"):
        CHRFScore(beta=-1.0)
