"""chrF vs an independent per-order reimplementation + hand-derived cases.

sacrebleu is not in the image; the oracle below recomputes per-order
precision/recall/F from scratch (dict loops, no shared helpers) following
the published chrF2 definition, and the hand cases pin values computed on
paper.
"""
import numpy as np
import pytest

from metrics_tpu import CHRFScore
from metrics_tpu.functional import chrf_score


def _oracle(preds, target, order=6, beta=2.0):
    total = {"m": [0] * order, "h": [0] * order, "r": [0] * order}
    for hyp, ref in zip(preds, target):
        hyp = hyp.replace(" ", "").replace("\t", "").replace("\n", "")
        ref = ref.replace(" ", "").replace("\t", "").replace("\n", "")
        for n in range(1, order + 1):
            hg, rg = {}, {}
            for i in range(len(hyp) - n + 1):
                g = hyp[i:i + n]
                hg[g] = hg.get(g, 0) + 1
            for i in range(len(ref) - n + 1):
                g = ref[i:i + n]
                rg[g] = rg.get(g, 0) + 1
            total["m"][n - 1] += sum(min(c, rg.get(g, 0)) for g, c in hg.items())
            total["h"][n - 1] += sum(hg.values())
            total["r"][n - 1] += sum(rg.values())
    score, eff = 0.0, 0
    for m, h, r in zip(total["m"], total["h"], total["r"]):
        if h > 0 or r > 0:  # either-side effective order; missing side ~0
            eff += 1
            p = m / h if h > 0 else 1e-16
            rc = m / r if r > 0 else 1e-16
            d = beta * beta * p + rc
            if d > 0:
                score += (1 + beta * beta) * p * rc / d
    return score / eff if eff else 0.0


def test_identical_sentences():
    assert chrf_score(["the cat sat"], ["the cat sat"]) == pytest.approx(1.0)


def test_disjoint_sentences():
    assert chrf_score(["aaaa"], ["bbbb"]) == pytest.approx(0.0)


def test_hand_case_single_order():
    """order=1, beta=1: hyp 'ab' vs ref 'abc' (whitespace-free): matches=2,
    hyp=2, ref=3 -> P=1, R=2/3, F1=0.8 — computed on paper."""
    assert chrf_score(["ab"], ["abc"], n_char_order=1, beta=1.0) == pytest.approx(0.8)


def test_hand_case_beta_weighting():
    """beta=2 weights recall: same stats give F = 5*P*R/(4P+R) = 5*(2/3)/(4+2/3)."""
    want = 5 * (2 / 3) / (4 + 2 / 3)
    assert chrf_score(["ab"], ["abc"], n_char_order=1, beta=2.0) == pytest.approx(want)


def test_short_hypothesis_penalized_for_uncoverable_orders():
    """'ab' vs 'abcdef': the hypothesis has n-grams only for orders 1-2, but
    orders 3-6 still count (either-side rule) with ~0 contribution — a short
    hypothesis must not be excused from the orders it cannot cover."""
    got = chrf_score(["ab"], ["abcdef"])
    # order 1: P=1, R=2/6; order 2: P=1, R=1/5; orders 3-6: ~0 — averaged /6
    f1 = 5 * 1 * (2 / 6) / (4 * 1 + 2 / 6)
    f2 = 5 * 1 * (1 / 5) / (4 * 1 + 1 / 5)
    np.testing.assert_allclose(got, (f1 + f2) / 6, atol=1e-9)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_random_corpora_vs_oracle(seed):
    rng = np.random.RandomState(seed)
    vocab = list("abcdefg ")
    preds = ["".join(rng.choice(vocab, rng.randint(3, 30))) for _ in range(12)]
    target = ["".join(rng.choice(vocab, rng.randint(3, 30))) for _ in range(12)]
    got = chrf_score(preds, target)
    np.testing.assert_allclose(got, _oracle(preds, target), atol=1e-9)


def test_streaming_equals_corpus():
    """Batch-streamed statistics equal the one-shot corpus score (the
    sacrebleu sum-then-score aggregation, not a mean of batch scores)."""
    rng = np.random.RandomState(7)
    vocab = list("abcde ")
    preds = ["".join(rng.choice(vocab, rng.randint(4, 20))) for _ in range(9)]
    target = ["".join(rng.choice(vocab, rng.randint(4, 20))) for _ in range(9)]
    m = CHRFScore()
    for i in range(3):
        m.update(preds[i * 3:(i + 1) * 3], target[i * 3:(i + 1) * 3])
    np.testing.assert_allclose(float(m.compute()), _oracle(preds, target), atol=1e-6)
    m.reset()
    assert float(m.compute()) == 0.0


def test_whitespace_and_lowercase_options():
    # with whitespace kept, 'a b' vs 'ab' shares only the chars, not the bigram
    strict = chrf_score(["a b"], ["ab"], n_char_order=2, whitespace=True)
    loose = chrf_score(["a b"], ["ab"], n_char_order=2, whitespace=False)
    assert loose == pytest.approx(1.0) and strict < loose
    assert chrf_score(["AB"], ["ab"], lowercase=True) == pytest.approx(1.0)
    assert chrf_score(["AB"], ["ab"], lowercase=False) == pytest.approx(0.0)


def test_validation():
    with pytest.raises(ValueError, match="sentences"):
        chrf_score(["a", "b"], ["a"])
    with pytest.raises(ValueError, match="positive"):
        CHRFScore(n_char_order=0)
    with pytest.raises(ValueError, match="beta"):
        CHRFScore(beta=-1.0)
