"""ROUGE vs hand-computed values and an independent per-pair oracle."""
import numpy as np
import pytest

from metrics_tpu import ROUGEScore
from metrics_tpu.functional import rouge_score


def test_known_values():
    out = rouge_score("the cat sat on the mat", "the cat was on the mat")
    # unigram overlap 5 of 6/6; bigrams: (the,cat),(on,the),(the,mat) = 3 of 5/5; LCS 5
    assert round(out["rouge1_fmeasure"], 4) == 0.8333
    assert round(out["rouge2_fmeasure"], 4) == 0.6
    assert round(out["rougeL_fmeasure"], 4) == 0.8333
    # perfect and disjoint
    perfect = rouge_score("a b c", "a b c")
    assert perfect["rouge1_fmeasure"] == 1.0 and perfect["rougeL_fmeasure"] == 1.0
    none = rouge_score("x y", "a b")
    assert none["rouge1_fmeasure"] == 0.0 and none["rougeL_fmeasure"] == 0.0


def test_clipped_counts_and_tokenization():
    # repeated pred tokens clip to the target multiset; punctuation/case strip
    out = rouge_score("The the the!", "the cat")
    # pred unigrams: the x3; target: the, cat -> overlap clipped to 1
    assert round(out["rouge1_precision"], 4) == round(1 / 3, 4)
    assert round(out["rouge1_recall"], 4) == 0.5


def test_lcs_vs_bruteforce():
    from functools import lru_cache

    from metrics_tpu.functional.text_rouge import _lcs_len

    rng = np.random.RandomState(3)
    for _ in range(10):
        a = [str(x) for x in rng.randint(0, 4, rng.randint(0, 8))]
        b = [str(x) for x in rng.randint(0, 4, rng.randint(0, 8))]

        @lru_cache(maxsize=None)
        def lcs(i, j):
            if i == len(a) or j == len(b):
                return 0
            if a[i] == b[j]:
                return 1 + lcs(i + 1, j + 1)
            return max(lcs(i + 1, j), lcs(i, j + 1))

        assert _lcs_len(a, b) == lcs(0, 0)
        lcs.cache_clear()


def test_device_lcs_kernel_vs_host_oracle():
    """The batched device LCS (lcs_length_padded) matches the host DP on
    random padded id batches, including empty and full-pad rows."""
    import jax.numpy as jnp

    from metrics_tpu.functional.text import lcs_length_padded
    from metrics_tpu.functional.text_rouge import _lcs_len

    rng = np.random.RandomState(11)
    B, N, M = 16, 12, 9
    pred_ids = rng.randint(1, 5, (B, N)).astype(np.int32)
    target_ids = rng.randint(1, 5, (B, M)).astype(np.int32)
    pred_len = rng.randint(0, N + 1, B).astype(np.int32)
    target_len = rng.randint(0, M + 1, B).astype(np.int32)
    got = np.asarray(
        lcs_length_padded(
            jnp.asarray(pred_ids), jnp.asarray(target_ids),
            jnp.asarray(pred_len), jnp.asarray(target_len),
        )
    )
    for k in range(B):
        a = [str(x) for x in pred_ids[k, : pred_len[k]]]
        b = [str(x) for x in target_ids[k, : target_len[k]]]
        assert got[k] == _lcs_len(a, b), (k, a, b)

    with pytest.raises(ValueError, match="pred_len"):
        lcs_length_padded(
            jnp.asarray(pred_ids), jnp.asarray(target_ids),
            jnp.asarray(pred_len + N), jnp.asarray(target_len),
        )


def test_rouge_l_device_path_matches_host():
    """Corpus-scale ROUGE-L (device LCS batch) == the host path exactly."""
    from metrics_tpu.functional import text_rouge

    rng = np.random.RandomState(13)
    vocab = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta"]
    preds = [" ".join(rng.choice(vocab, rng.randint(5, 40))) for _ in range(24)]
    targets = [" ".join(rng.choice(vocab, rng.randint(5, 40))) for _ in range(24)]

    host = rouge_score(preds, targets, rouge_keys=("rougeL",))
    old = text_rouge._DEVICE_LCS_MIN_CELLS
    text_rouge._DEVICE_LCS_MIN_CELLS = 0  # force the device kernel
    try:
        dev = rouge_score(preds, targets, rouge_keys=("rougeL",))
    finally:
        text_rouge._DEVICE_LCS_MIN_CELLS = old
    for key, val in host.items():
        assert abs(dev[key] - val) < 1e-12, key


def test_module_accumulates_as_mean_of_sentences():
    pairs = [
        ("the cat sat on the mat", "the cat was on the mat"),
        ("hello world", "hello there world"),
        ("exact match here", "exact match here"),
    ]
    m = ROUGEScore()
    for p, t in pairs:
        m.update([p], [t])
    want = rouge_score([p for p, _ in pairs], [t for _, t in pairs])
    got = m.compute()
    for k, v in want.items():
        np.testing.assert_allclose(float(got[k]), v, atol=1e-6)


def test_validation():
    with pytest.raises(ValueError, match="rouge key"):
        rouge_score("a", "a", rouge_keys=("rougeX",))
    with pytest.raises(ValueError, match="same number"):
        rouge_score(["a"], ["a", "b"])
    with pytest.raises(ValueError, match="rouge key"):
        ROUGEScore(rouge_keys=("bogus",))
