"""TER vs the installed sacrebleu (exact Tercom-semantics parity)."""
import numpy as np
import pytest
import sacrebleu

from metrics_tpu import TranslationEditRate
from metrics_tpu.functional import translation_edit_rate

_TER = sacrebleu.metrics.ter.TER()


def test_hand_cases():
    # one deletion against a 6-word reference
    assert translation_edit_rate(
        ["the cat sat on mat"], [["the cat sat on the mat"]]
    ) == pytest.approx(1 / 6)
    # one block shift = one edit
    assert translation_edit_rate(["b a c d"], [["a b c d"]]) == pytest.approx(0.25)
    assert translation_edit_rate(["a b c d"], [["a b c d"]]) == 0.0


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_random_pairs_vs_sacrebleu(seed):
    rng = np.random.RandomState(seed)
    vocab = ["the", "cat", "dog", "sat", "on", "mat", "a", "ran", "big", "red"]
    for _ in range(60):
        hyp = " ".join(rng.choice(vocab, rng.randint(1, 14)))
        ref = " ".join(rng.choice(vocab, rng.randint(1, 14)))
        got = translation_edit_rate([hyp], [[ref]])
        want = _TER.corpus_score([hyp], [[ref]]).score / 100
        np.testing.assert_allclose(got, want, atol=1e-9, err_msg=f"{hyp!r} vs {ref!r}")


def test_corpus_and_multiref_vs_sacrebleu():
    preds = ["the cat is on the mat", "a big red dog ran", "mat the on cat"]
    target = [
        ["the cat sat on the mat", "a cat is on the mat"],
        ["the big red dog ran fast", "a big dog ran"],
        ["the cat on the mat"],
    ]
    got = translation_edit_rate(preds, target)
    refs_t = [
        [target[i][j] if j < len(target[i]) else target[i][-1] for i in range(len(preds))]
        for j in range(2)
    ]
    want = _TER.corpus_score(preds, refs_t).score / 100
    np.testing.assert_allclose(got, want, atol=1e-9)


def test_length_mismatched_pair_vs_sacrebleu():
    """One fixed long, severely length-mismatched pair keeps the beam-pruned
    edit-distance regime (sacrebleu's pseudo-diagonal beam, width 25) covered
    in tier-1; the randomized sweep below is the slow-marked deep version."""
    rng = np.random.RandomState(7)
    vocab = ["the", "cat", "dog", "sat", "on", "mat", "a", "ran"]
    hyp = " ".join(rng.choice(vocab, 97))
    ref = " ".join(rng.choice(vocab, 5))
    got = translation_edit_rate([hyp], [[ref]])
    want = _TER.corpus_score([hyp], [[ref]]).score / 100
    np.testing.assert_allclose(got, want, atol=1e-9)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [42, 43])
def test_long_length_mismatched_pairs_vs_sacrebleu(seed):
    """Long and severely length-mismatched pairs exercise the beam-pruned
    edit-distance regime (sacrebleu's pseudo-diagonal beam, width 25).
    30 random shapes are compile-bound on CPU (~50s), so this sweep is
    slow-marked; the fixed-shape case above stays in tier-1."""
    rng = np.random.RandomState(seed)
    vocab = ["the", "cat", "dog", "sat", "on", "mat", "a", "ran"]
    for trial in range(15):
        n_h = rng.randint(1, 100)
        n_r = rng.randint(1, 100)
        hyp = " ".join(rng.choice(vocab, n_h))
        ref = " ".join(rng.choice(vocab, n_r))
        got = translation_edit_rate([hyp], [[ref]])
        want = _TER.corpus_score([hyp], [[ref]]).score / 100
        np.testing.assert_allclose(got, want, atol=1e-9, err_msg=f"{n_h} vs {n_r} words")


def test_flat_string_target_raises():
    with pytest.raises(ValueError, match="wrap it"):
        translation_edit_rate(["the cat"], ["the cat"])


def test_case_sensitivity():
    insensitive = translation_edit_rate(["The Cat"], [["the cat"]])
    sensitive = translation_edit_rate(["The Cat"], [["the cat"]], case_sensitive=True)
    assert insensitive == 0.0 and sensitive > 0.0
    want = sacrebleu.metrics.ter.TER(case_sensitive=True).corpus_score(
        ["The Cat"], [["the cat"]]).score / 100
    np.testing.assert_allclose(sensitive, want, atol=1e-9)


def test_streaming_equals_corpus():
    preds = ["the cat is on the mat", "a big red dog ran"]
    target = [["the cat sat on the mat"], ["a big dog ran fast"]]
    m = TranslationEditRate()
    m.update(preds[:1], target[:1])
    m.update(preds[1:], target[1:])
    np.testing.assert_allclose(
        float(m.compute()), translation_edit_rate(preds, target), atol=1e-6
    )
    m.reset()
    assert float(m.compute()) == 0.0


def test_empty_reference_conventions():
    # empty ref, non-empty hyp: every hyp word is an edit, rate 1.0
    assert translation_edit_rate(["a b"], [[""]]) == 1.0
    # both empty: 0.0
    assert translation_edit_rate([""], [[""]]) == 0.0


def test_validation():
    with pytest.raises(ValueError, match="sentences"):
        translation_edit_rate(["a", "b"], [["a"]])
    with pytest.raises(ValueError, match="reference"):
        translation_edit_rate(["a"], [[]])
