"""Metric test harness.

Port of the reference harness semantics (reference tests/helpers/testers.py:76-291)
to the TPU build:

* "Distributed" testing runs a **simulated N-rank world in one process**: each
  rank is a metric instance (fed rank-strided batches) driven by its own
  thread, and the host-plane gather (``dist_sync_fn``) is a barrier +
  read-all-ranks — semantically the reference's barrier + all_gather
  (reference torchmetrics/utilities/distributed.py:115-116), which its tests
  exercised with a 2-process Gloo group (testers.py:41-47). Real-collective
  coverage of the in-jit plane lives in ``tests/parallel/`` via ``shard_map``
  over 8 fake CPU devices.
* sklearn remains the numerical oracle; default ``atol=1e-8``
  (reference testers.py:185).
* Metrics are pickled and restored before use (reference testers.py:117-118).
"""
import functools
import hashlib
import pickle
import threading
from typing import Any, Callable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import Metric
from metrics_tpu.parallel.buffer import PaddedBuffer

NUM_PROCESSES = 2
NUM_BATCHES = 10
BATCH_SIZE = 32
NUM_CLASSES = 5
EXTRA_DIM = 3
THRESHOLD = 0.5

_BARRIER_TIMEOUT = 60.0


# ---------------------------------------------------------------- oracle memo
# The class test and its functional sibling run the sklearn oracle on the
# exact same fixture batches, and the per-sample sklearn loops (mdmc
# 'samplewise') dominate suite wall-clock on the 1-core harness. Results are
# memoized process-wide, keyed on the oracle's identity + the raw input
# bytes, so a repeat evaluation is a dict hit. Callables are keyed by id()
# (pinned in the cache so ids are never reused): two closures over different
# state get distinct keys, but a single callable must be deterministic in its
# inputs — don't pass an oracle that reads state it mutates between calls.
_ORACLE_CACHE: dict = {}


def _fn_fingerprint(fn: Callable) -> Optional[tuple]:
    """A process-stable identity for an oracle callable, or None if unsafe.

    Callables are keyed by ``id`` (plus module/qualname for readability):
    distinct closures get distinct keys even when they share code, and the
    cache pins a strong reference to the whole callable so ids are never
    reused while an entry lives. Arguments with lossy ``repr`` (arrays)
    make the callable uncacheable.
    """
    if isinstance(fn, functools.partial):
        inner = _fn_fingerprint(fn.func)
        if inner is None:
            return None
        parts = [_value_fingerprint(v) for v in fn.args]
        kw = [(k, _value_fingerprint(v)) for k, v in sorted(fn.keywords.items())]
        if any(p is None for p in parts) or any(v is None for _, v in kw):
            return None
        return ("partial", inner, tuple(parts), tuple(kw))
    return (getattr(fn, "__module__", ""), getattr(fn, "__qualname__", ""), id(fn))


def _value_fingerprint(v: Any) -> Optional[Any]:
    """Exact key for a partial argument, or None when repr would be lossy."""
    if isinstance(v, (np.ndarray, jnp.ndarray)):
        return None  # repr of arrays is lossy -> unsafe key
    if isinstance(v, (list, tuple, set, frozenset)):
        parts = [_value_fingerprint(x) for x in v]
        return None if any(p is None for p in parts) else (type(v).__name__, tuple(parts))
    if isinstance(v, dict):
        kv = [(repr(k), _value_fingerprint(x)) for k, x in sorted(v.items(), key=lambda i: repr(i[0]))]
        return None if any(x is None for _, x in kv) else ("dict", tuple(kv))
    if callable(v):
        return _fn_fingerprint(v)
    return repr(v)


def _oracle(sk_metric: Callable, preds: np.ndarray, target: np.ndarray, **kwargs: Any) -> Any:
    fp = _fn_fingerprint(sk_metric)
    if fp is None or kwargs:
        return sk_metric(preds, target, **kwargs)
    preds = np.asarray(preds)
    target = np.asarray(target)
    digest = hashlib.sha1()
    for arr in (preds, target):
        digest.update(str((arr.shape, arr.dtype)).encode())
        digest.update(np.ascontiguousarray(arr).tobytes())
    key = (fp, digest.hexdigest())
    if key not in _ORACLE_CACHE:
        # pin sk_metric so every id() in the key stays allocated for the
        # cache's lifetime (no id reuse -> no false hits)
        _ORACLE_CACHE[key] = (sk_metric, sk_metric(preds, target))
    return _ORACLE_CACHE[key][1]


def _assert_allclose(jax_result: Any, sk_result: Any, atol: float = 1e-8, rtol: float = 1e-7) -> None:
    if isinstance(jax_result, (list, tuple)):
        assert len(jax_result) == len(sk_result)
        for j, s in zip(jax_result, sk_result):
            _assert_allclose(j, s, atol=atol, rtol=rtol)
        return
    if isinstance(jax_result, dict):
        for key in jax_result:
            _assert_allclose(jax_result[key], sk_result[key], atol=atol, rtol=rtol)
        return
    np.testing.assert_allclose(np.asarray(jax_result), np.asarray(sk_result), atol=atol, rtol=rtol)


class BarrierGather:
    """Host-plane gather for a simulated world: barrier, read every rank's
    matching state (identity-matched on the calling rank), barrier."""

    def __init__(self, world: Sequence[Metric]):
        self.world = world
        self.barrier = threading.Barrier(len(world))

    def for_rank(self, rank: int) -> Callable:
        def gather(arr: Any, **kwargs: Any) -> List[Any]:
            self.barrier.wait(timeout=_BARRIER_TIMEOUT)
            locate = self._locate(self.world[rank], arr)
            vals = [self._read(other, *locate) for other in self.world]
            self.barrier.wait(timeout=_BARRIER_TIMEOUT)
            return vals

        return gather

    @staticmethod
    def _locate(me: Metric, arr: Any):
        for name in me._defaults:
            val = getattr(me, name)
            if val is arr:
                return (name, None, "array")
            if isinstance(val, PaddedBuffer):
                if val.data is arr:
                    return (name, None, "buffer_data")
                if val.count is arr:
                    return (name, None, "buffer_count")
            if isinstance(val, list):
                for j, v in enumerate(val):
                    if v is arr:
                        return (name, j, "list")
        raise RuntimeError("gathered array does not correspond to any metric state")

    @staticmethod
    def _read(metric: Metric, name: str, j: Optional[int], kind: str) -> Any:
        val = getattr(metric, name)
        if kind == "array":
            return val
        if kind == "buffer_data":
            return val.data
        if kind == "buffer_count":
            return val.count
        return val[j]


def _run_in_threads(fns: Sequence[Callable]) -> List[Any]:
    """Run one callable per rank concurrently; re-raise the first exception."""
    results: List[Any] = [None] * len(fns)
    errors: List[BaseException] = []

    def runner(i: int) -> None:
        try:
            results[i] = fns[i]()
        except BaseException as e:  # noqa: BLE001 - propagate test assertion errors
            errors.append(e)

    threads = [threading.Thread(target=runner, args=(i,)) for i in range(len(fns))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=2 * _BARRIER_TIMEOUT)
    if errors:
        raise errors[0]
    return results


class MetricTester:
    """Test a metric class/functional against an sklearn oracle over batched fixtures."""

    atol: float = 1e-8
    rtol: float = 1e-7

    def run_functional_metric_test(
        self,
        preds: np.ndarray,
        target: np.ndarray,
        metric_functional: Callable,
        sk_metric: Callable,
        metric_args: Optional[dict] = None,
        **kwargs_update: Any,
    ) -> None:
        """Per-batch functional-vs-oracle comparison (reference testers.py:145-172)."""
        metric_args = metric_args or {}
        for i in range(NUM_BATCHES):
            jax_result = metric_functional(
                jnp.asarray(preds[i]), jnp.asarray(target[i]), **metric_args, **kwargs_update
            )
            sk_result = _oracle(sk_metric, preds[i], target[i], **kwargs_update)
            _assert_allclose(jax_result, sk_result, atol=self.atol, rtol=self.rtol)

    def run_class_metric_test(
        self,
        ddp: bool,
        preds: np.ndarray,
        target: np.ndarray,
        metric_class: type,
        sk_metric: Callable,
        dist_sync_on_step: bool,
        metric_args: Optional[dict] = None,
        check_dist_sync_on_step: bool = True,
        check_batch: bool = True,
    ) -> None:
        """Stateful accumulate/sync/compute test (reference testers.py:76-142, 228-291)."""
        metric_args = metric_args or {}
        world_size = NUM_PROCESSES if ddp else 1

        world: List[Metric] = []
        for _ in range(world_size):
            metric = metric_class(dist_sync_on_step=dist_sync_on_step, **metric_args)
            metric = pickle.loads(pickle.dumps(metric))
            world.append(metric)
        if world_size > 1:
            sync = BarrierGather(world)
            for rank, metric in enumerate(world):
                metric.dist_sync_fn = sync.for_rank(rank)

        for step in range(NUM_BATCHES // world_size):
            idxs = [r + step * world_size for r in range(world_size)]
            fns = [
                (lambda r=r, i=i: world[r](jnp.asarray(preds[i]), jnp.asarray(target[i])))
                for r, i in enumerate(idxs)
            ]
            batch_results = _run_in_threads(fns) if (world_size > 1 and dist_sync_on_step) else [f() for f in fns]

            for rank in range(world_size):
                i = idxs[rank]
                if dist_sync_on_step and check_dist_sync_on_step and rank == 0:
                    # batch value was synced: compare against the union of this step's batches
                    union_preds = np.concatenate([preds[j] for j in idxs])
                    union_target = np.concatenate([target[j] for j in idxs])
                    _assert_allclose(
                        batch_results[rank],
                        _oracle(sk_metric, union_preds, union_target),
                        atol=self.atol,
                        rtol=self.rtol,
                    )
                elif check_batch and not dist_sync_on_step:
                    _assert_allclose(
                        batch_results[rank],
                        _oracle(sk_metric, preds[i], target[i]),
                        atol=self.atol,
                        rtol=self.rtol,
                    )

        # final compute must equal the oracle on ALL batches on every rank
        total_preds = np.concatenate([preds[i] for i in range(NUM_BATCHES)])
        total_target = np.concatenate([target[i] for i in range(NUM_BATCHES)])
        sk_result = _oracle(sk_metric, total_preds, total_target)
        computes = [(lambda m=m: m.compute()) for m in world]
        final = _run_in_threads(computes) if world_size > 1 else [computes[0]()]
        for result in final:
            _assert_allclose(result, sk_result, atol=self.atol, rtol=self.rtol)


class DummyMetric(Metric):
    name = "Dummy"

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self.add_state("x", jnp.asarray(0.0), dist_reduce_fx=None)

    def update(self):
        pass

    def compute(self):
        pass


class DummyListMetric(Metric):
    name = "DummyList"

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self.add_state("x", [], dist_reduce_fx=None)

    def update(self):
        pass

    def compute(self):
        pass


class DummyMetricSum(DummyMetric):

    def update(self, x):
        self.x = self.x + x

    def compute(self):
        return self.x


class DummyMetricDiff(DummyMetric):

    def update(self, y):
        self.x = self.x - y

    def compute(self):
        return self.x
