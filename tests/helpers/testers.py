"""Metric test harness.

Port of the reference harness semantics (reference tests/helpers/testers.py:76-291)
to the TPU build:

* "Distributed" testing runs a **simulated N-rank world in one process**: each
  rank is a metric instance (fed rank-strided batches) driven by its own
  thread, and the host-plane gather (``dist_sync_fn``) is a barrier +
  read-all-ranks — semantically the reference's barrier + all_gather
  (reference torchmetrics/utilities/distributed.py:115-116), which its tests
  exercised with a 2-process Gloo group (testers.py:41-47). Real-collective
  coverage of the in-jit plane lives in ``tests/parallel/`` via ``shard_map``
  over 8 fake CPU devices.
* sklearn remains the numerical oracle; default ``atol=1e-8``
  (reference testers.py:185).
* Metrics are pickled and restored before use (reference testers.py:117-118).
"""
import pickle
import threading
from typing import Any, Callable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from metrics_tpu.core.metric import Metric
from metrics_tpu.parallel.buffer import PaddedBuffer

NUM_PROCESSES = 2
NUM_BATCHES = 10
BATCH_SIZE = 32
NUM_CLASSES = 5
EXTRA_DIM = 3
THRESHOLD = 0.5

_BARRIER_TIMEOUT = 60.0


def _assert_allclose(jax_result: Any, sk_result: Any, atol: float = 1e-8) -> None:
    if isinstance(jax_result, (list, tuple)):
        assert len(jax_result) == len(sk_result)
        for j, s in zip(jax_result, sk_result):
            _assert_allclose(j, s, atol=atol)
        return
    if isinstance(jax_result, dict):
        for key in jax_result:
            _assert_allclose(jax_result[key], sk_result[key], atol=atol)
        return
    np.testing.assert_allclose(np.asarray(jax_result), np.asarray(sk_result), atol=atol)


class BarrierGather:
    """Host-plane gather for a simulated world: barrier, read every rank's
    matching state (identity-matched on the calling rank), barrier."""

    def __init__(self, world: Sequence[Metric]):
        self.world = world
        self.barrier = threading.Barrier(len(world))

    def for_rank(self, rank: int) -> Callable:
        def gather(arr: Any, **kwargs: Any) -> List[Any]:
            self.barrier.wait(timeout=_BARRIER_TIMEOUT)
            locate = self._locate(self.world[rank], arr)
            vals = [self._read(other, *locate) for other in self.world]
            self.barrier.wait(timeout=_BARRIER_TIMEOUT)
            return vals

        return gather

    @staticmethod
    def _locate(me: Metric, arr: Any):
        for name in me._defaults:
            val = getattr(me, name)
            if val is arr:
                return (name, None, "array")
            if isinstance(val, PaddedBuffer):
                if val.data is arr:
                    return (name, None, "buffer_data")
                if val.count is arr:
                    return (name, None, "buffer_count")
            if isinstance(val, list):
                for j, v in enumerate(val):
                    if v is arr:
                        return (name, j, "list")
        raise RuntimeError("gathered array does not correspond to any metric state")

    @staticmethod
    def _read(metric: Metric, name: str, j: Optional[int], kind: str) -> Any:
        val = getattr(metric, name)
        if kind == "array":
            return val
        if kind == "buffer_data":
            return val.data
        if kind == "buffer_count":
            return val.count
        return val[j]


def _run_in_threads(fns: Sequence[Callable]) -> List[Any]:
    """Run one callable per rank concurrently; re-raise the first exception."""
    results: List[Any] = [None] * len(fns)
    errors: List[BaseException] = []

    def runner(i: int) -> None:
        try:
            results[i] = fns[i]()
        except BaseException as e:  # noqa: BLE001 - propagate test assertion errors
            errors.append(e)

    threads = [threading.Thread(target=runner, args=(i,)) for i in range(len(fns))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=2 * _BARRIER_TIMEOUT)
    if errors:
        raise errors[0]
    return results


class MetricTester:
    """Test a metric class/functional against an sklearn oracle over batched fixtures."""

    atol: float = 1e-8

    def run_functional_metric_test(
        self,
        preds: np.ndarray,
        target: np.ndarray,
        metric_functional: Callable,
        sk_metric: Callable,
        metric_args: Optional[dict] = None,
        **kwargs_update: Any,
    ) -> None:
        """Per-batch functional-vs-oracle comparison (reference testers.py:145-172)."""
        metric_args = metric_args or {}
        for i in range(NUM_BATCHES):
            jax_result = metric_functional(
                jnp.asarray(preds[i]), jnp.asarray(target[i]), **metric_args, **kwargs_update
            )
            sk_result = sk_metric(preds[i], target[i], **kwargs_update)
            _assert_allclose(jax_result, sk_result, atol=self.atol)

    def run_class_metric_test(
        self,
        ddp: bool,
        preds: np.ndarray,
        target: np.ndarray,
        metric_class: type,
        sk_metric: Callable,
        dist_sync_on_step: bool,
        metric_args: Optional[dict] = None,
        check_dist_sync_on_step: bool = True,
        check_batch: bool = True,
    ) -> None:
        """Stateful accumulate/sync/compute test (reference testers.py:76-142, 228-291)."""
        metric_args = metric_args or {}
        world_size = NUM_PROCESSES if ddp else 1

        world: List[Metric] = []
        for _ in range(world_size):
            metric = metric_class(dist_sync_on_step=dist_sync_on_step, **metric_args)
            metric = pickle.loads(pickle.dumps(metric))
            world.append(metric)
        if world_size > 1:
            sync = BarrierGather(world)
            for rank, metric in enumerate(world):
                metric.dist_sync_fn = sync.for_rank(rank)

        for step in range(NUM_BATCHES // world_size):
            idxs = [r + step * world_size for r in range(world_size)]
            fns = [
                (lambda r=r, i=i: world[r](jnp.asarray(preds[i]), jnp.asarray(target[i])))
                for r, i in enumerate(idxs)
            ]
            batch_results = _run_in_threads(fns) if (world_size > 1 and dist_sync_on_step) else [f() for f in fns]

            for rank in range(world_size):
                i = idxs[rank]
                if dist_sync_on_step and check_dist_sync_on_step and rank == 0:
                    # batch value was synced: compare against the union of this step's batches
                    union_preds = np.concatenate([preds[j] for j in idxs])
                    union_target = np.concatenate([target[j] for j in idxs])
                    _assert_allclose(batch_results[rank], sk_metric(union_preds, union_target), atol=self.atol)
                elif check_batch and not dist_sync_on_step:
                    _assert_allclose(batch_results[rank], sk_metric(preds[i], target[i]), atol=self.atol)

        # final compute must equal the oracle on ALL batches on every rank
        total_preds = np.concatenate([preds[i] for i in range(NUM_BATCHES)])
        total_target = np.concatenate([target[i] for i in range(NUM_BATCHES)])
        sk_result = sk_metric(total_preds, total_target)
        computes = [(lambda m=m: m.compute()) for m in world]
        final = _run_in_threads(computes) if world_size > 1 else [computes[0]()]
        for result in final:
            _assert_allclose(result, sk_result, atol=self.atol)


class DummyMetric(Metric):
    name = "Dummy"

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self.add_state("x", jnp.asarray(0.0), dist_reduce_fx=None)

    def update(self):
        pass

    def compute(self):
        pass


class DummyListMetric(Metric):
    name = "DummyList"

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self.add_state("x", [], dist_reduce_fx=None)

    def update(self):
        pass

    def compute(self):
        pass


class DummyMetricSum(DummyMetric):

    def update(self, x):
        self.x = self.x + x

    def compute(self):
        return self.x


class DummyMetricDiff(DummyMetric):

    def update(self, y):
        self.x = self.x - y

    def compute(self):
        return self.x
