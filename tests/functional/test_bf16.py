"""bf16-input parity (SURVEY §7 hard part 6).

bf16 is the TPU-native activation dtype: metrics must accept bf16 inputs,
upcast before accumulation (classification formatting upcasts like the
reference does for fp16, checks.py:402-403; regression kernels upcast via
``upcast_accum``), and agree with the fp32 sklearn oracle at relaxed
tolerance (bf16 has ~3 significant decimal digits).
"""
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import (
    accuracy_score as sk_accuracy,
    f1_score as sk_f1,
    mean_absolute_error as sk_mae,
    mean_squared_error as sk_mse,
    precision_score as sk_precision,
    r2_score as sk_r2,
    roc_auc_score as sk_auroc,
)

from metrics_tpu import Accuracy, F1, MeanAbsoluteError, MeanSquaredError, Precision, R2Score
from metrics_tpu.functional import auroc

NUM_CLASSES = 5
BATCHES = 4
N = 64

# bf16 rounding of inputs can flip argmax/threshold decisions near ties and
# shifts every value at the 3rd decimal; tolerance reflects the input error,
# not the accumulation (which runs in fp32)
ATOL = 2e-2


def _bf16_probs(rng, n, c):
    logits = rng.rand(n, c).astype(np.float32)
    probs = logits / logits.sum(-1, keepdims=True)
    return jnp.asarray(probs, dtype=jnp.bfloat16), np.asarray(
        jnp.asarray(probs, dtype=jnp.bfloat16), dtype=np.float32
    )


@pytest.mark.parametrize(
    "metric_cls, metric_args, sk_fn",
    [
        (Accuracy, {}, lambda p, t: sk_accuracy(t, p.argmax(-1))),
        (
            Precision,
            {"num_classes": NUM_CLASSES, "average": "macro"},
            lambda p, t: sk_precision(t, p.argmax(-1), average="macro", zero_division=0),
        ),
        (
            F1,
            {"num_classes": NUM_CLASSES, "average": "macro"},
            lambda p, t: sk_f1(t, p.argmax(-1), average="macro", zero_division=0),
        ),
    ],
)
def test_classification_bf16_inputs(metric_cls, metric_args, sk_fn):
    rng = np.random.RandomState(42)
    metric = metric_cls(**metric_args)
    all_p, all_t = [], []
    for _ in range(BATCHES):
        preds_bf16, preds_as_f32 = _bf16_probs(rng, N, NUM_CLASSES)
        target = rng.randint(0, NUM_CLASSES, N)
        metric.update(preds_bf16, jnp.asarray(target))
        all_p.append(preds_as_f32)
        all_t.append(target)
    expected = sk_fn(np.concatenate(all_p), np.concatenate(all_t))
    np.testing.assert_allclose(float(metric.compute()), expected, atol=ATOL)


@pytest.mark.parametrize(
    "metric_cls, sk_fn",
    [
        (MeanSquaredError, sk_mse),
        (MeanAbsoluteError, sk_mae),
        (R2Score, sk_r2),
    ],
)
def test_regression_bf16_inputs(metric_cls, sk_fn):
    rng = np.random.RandomState(7)
    metric = metric_cls()
    all_p, all_t = [], []
    for _ in range(BATCHES):
        p = jnp.asarray(rng.rand(N).astype(np.float32), dtype=jnp.bfloat16)
        t = jnp.asarray(rng.rand(N).astype(np.float32), dtype=jnp.bfloat16)
        metric.update(p, t)
        all_p.append(np.asarray(p, dtype=np.float32))
        all_t.append(np.asarray(t, dtype=np.float32))
    # accumulator states must be fp32 regardless of the bf16 inputs
    for name in metric._defaults:
        state = getattr(metric, name)
        if jnp.issubdtype(state.dtype, jnp.floating):
            assert state.dtype == jnp.float32, name
    expected = sk_fn(np.concatenate(all_t), np.concatenate(all_p))
    np.testing.assert_allclose(float(metric.compute()), expected, atol=ATOL)


def test_auroc_bf16_inputs():
    rng = np.random.RandomState(3)
    scores = jnp.asarray(rng.rand(256).astype(np.float32), dtype=jnp.bfloat16)
    target = (rng.rand(256) > 0.5).astype(np.int64)
    ours = auroc(scores, jnp.asarray(target), pos_label=1)
    expected = sk_auroc(target, np.asarray(scores, dtype=np.float32))
    np.testing.assert_allclose(float(ours), expected, atol=ATOL)
