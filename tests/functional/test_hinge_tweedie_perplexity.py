"""HingeLoss vs sklearn, TweedieDevianceScore vs sklearn, Perplexity vs numpy."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import hinge_loss as sk_hinge
from sklearn.metrics import mean_tweedie_deviance as sk_tweedie

from metrics_tpu import HingeLoss, Perplexity, TweedieDevianceScore
from metrics_tpu.functional import hinge_loss, perplexity, tweedie_deviance_score
from tests.helpers.testers import NUM_BATCHES, MetricTester

_rng = np.random.RandomState(23)
BATCH_SIZE = 48


# ------------------------------------------------------------------- hinge
_bin_preds = _rng.randn(NUM_BATCHES, BATCH_SIZE).astype(np.float32)
_bin_target = (_rng.rand(NUM_BATCHES, BATCH_SIZE) > 0.5).astype(np.int64)
_mc_preds = _rng.randn(NUM_BATCHES, BATCH_SIZE, 4).astype(np.float32)
_mc_target = _rng.randint(0, 4, (NUM_BATCHES, BATCH_SIZE))


def _sk_hinge_binary(preds, target):
    return sk_hinge(np.asarray(target).reshape(-1), np.asarray(preds).reshape(-1))


def _sk_hinge_mc(preds, target):
    p = np.asarray(preds).reshape(-1, 4)
    return sk_hinge(np.asarray(target).reshape(-1), p, labels=list(range(4)))


class TestHinge(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("ddp", [False, True])
    def test_binary_class(self, ddp):
        self.run_class_metric_test(
            ddp=ddp, preds=_bin_preds, target=_bin_target, metric_class=HingeLoss,
            sk_metric=_sk_hinge_binary, dist_sync_on_step=False,
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_multiclass_class(self, ddp):
        self.run_class_metric_test(
            ddp=ddp, preds=_mc_preds, target=_mc_target, metric_class=HingeLoss,
            sk_metric=_sk_hinge_mc, dist_sync_on_step=False,
        )

    def test_functional(self):
        self.run_functional_metric_test(
            _bin_preds, _bin_target, metric_functional=hinge_loss, sk_metric=_sk_hinge_binary
        )


def test_hinge_squared_and_errors():
    got = float(hinge_loss(jnp.asarray(_bin_preds[0]), jnp.asarray(_bin_target[0]), squared=True))
    y = 2.0 * _bin_target[0] - 1.0
    want = (np.maximum(0, 1 - y * _bin_preds[0]) ** 2).mean()
    np.testing.assert_allclose(got, want, atol=1e-5)
    with pytest.raises(ValueError, match="ndim"):
        hinge_loss(jnp.zeros((2, 2, 2)), jnp.zeros(2))


# ----------------------------------------------------------------- tweedie
_tw_target = (_rng.rand(NUM_BATCHES, BATCH_SIZE) * 3).astype(np.float32)
_tw_preds = (_tw_target + 0.5 + _rng.rand(NUM_BATCHES, BATCH_SIZE)).astype(np.float32)


@pytest.mark.parametrize("power", [0, 1, 2, 1.5])
def test_tweedie_vs_sklearn(power):
    got = float(tweedie_deviance_score(jnp.asarray(_tw_preds[0]), jnp.asarray(_tw_target[0] + 0.1), power=power))
    want = sk_tweedie(_tw_target[0] + 0.1, _tw_preds[0], power=power)
    # TPU log differs ~4e-5 relative from CPU/f64 (Poisson/Gamma terms)
    np.testing.assert_allclose(got, want, rtol=1e-4)


class TestTweedie(MetricTester):
    atol = 1e-5
    rtol = 1e-4  # TPU log differs ~4e-5 relative (Poisson/Gamma deviance terms)

    @pytest.mark.parametrize("ddp", [False, True])
    @pytest.mark.parametrize("power", [0, 1])
    def test_class(self, ddp, power):
        self.run_class_metric_test(
            ddp=ddp, preds=_tw_preds, target=_tw_target + 0.1, metric_class=TweedieDevianceScore,
            sk_metric=lambda p, t: sk_tweedie(np.asarray(t).reshape(-1), np.asarray(p).reshape(-1), power=power),
            dist_sync_on_step=False, metric_args={"power": power},
        )


def test_tweedie_power_validation():
    with pytest.raises(ValueError, match="power"):
        TweedieDevianceScore(power=3)
    with pytest.raises(ValueError, match="power"):
        tweedie_deviance_score(jnp.ones(2), jnp.ones(2), power=0.5)


# -------------------------------------------------------------- perplexity
def _np_perplexity(logits, ids, ignore=None):
    logits = np.asarray(logits, np.float64).reshape(-1, logits.shape[-1])
    ids = np.asarray(ids).reshape(-1)
    logp = logits - np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1, keepdims=True)) - logits.max(-1, keepdims=True)
    mask = np.ones_like(ids, dtype=bool) if ignore is None else ids != ignore
    nll = -logp[np.arange(ids.size), np.where(mask, ids, 0)]
    return float(np.exp(nll[mask].mean()))


def test_perplexity_vs_numpy():
    logits = _rng.randn(4, 12, 7).astype(np.float32)
    ids = _rng.randint(0, 7, (4, 12))
    got = float(perplexity(jnp.asarray(logits), jnp.asarray(ids)))
    np.testing.assert_allclose(got, _np_perplexity(logits, ids), rtol=1e-5)

    # ignore_index masks padding tokens
    ids_pad = ids.copy()
    ids_pad[:, -3:] = -100
    got = float(perplexity(jnp.asarray(logits), jnp.asarray(ids_pad), ignore_index=-100))
    np.testing.assert_allclose(got, _np_perplexity(logits, ids_pad, ignore=-100), rtol=1e-5)


def test_perplexity_module_accumulates_and_jits():
    import metrics_tpu

    logits = _rng.randn(6, 4, 10, 5).astype(np.float32)
    ids = _rng.randint(0, 5, (6, 4, 10))
    old = metrics_tpu.set_default_jit(True)
    try:
        m = Perplexity()
        for i in range(6):
            m(jnp.asarray(logits[i]), jnp.asarray(ids[i]))
        np.testing.assert_allclose(float(m.compute()), _np_perplexity(logits, ids), rtol=1e-5)
    finally:
        metrics_tpu.set_default_jit(old)


def test_perplexity_shape_errors():
    with pytest.raises(ValueError, match="vocab"):
        perplexity(jnp.zeros(3), jnp.zeros(3))
    with pytest.raises(ValueError, match="target"):
        perplexity(jnp.zeros((2, 3, 4)), jnp.zeros((2, 4)))


def test_hinge_accepts_plus_minus_one_labels():
    """sklearn's native {-1,+1} convention gives sklearn's answer too."""
    p = np.array([0.5, -0.2, 0.3, 2.0], dtype=np.float32)
    t = np.array([-1, -1, 1, 1])
    got = float(hinge_loss(jnp.asarray(p), jnp.asarray(t)))
    np.testing.assert_allclose(got, sk_hinge(t, p), atol=1e-6)
