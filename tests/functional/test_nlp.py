"""BLEU vs nltk oracle (mirrors reference tests/functional/test_nlp.py)."""
from functools import partial

import numpy as np
import pytest
from nltk.translate.bleu_score import SmoothingFunction, corpus_bleu

from metrics_tpu.functional import bleu_score

# example taken from https://www.nltk.org/api/nltk.translate.html?highlight=bleu%20score#nltk.translate.bleu_score.sentence_bleu
HYPOTHESIS1 = tuple(
    "It is a guide to action which ensures that the military always obeys the commands of the party".split()
)
REFERENCE1 = tuple("It is a guide to action that ensures that the military will forever heed Party commands".split())
REFERENCE2 = tuple(
    "It is a guiding principle which makes the military forces always being under the command of the Party".split()
)
REFERENCE3 = tuple("It is the practical guide for the army always to heed the directions of the party".split())

# example taken from https://www.nltk.org/api/nltk.translate.html?highlight=bleu%20score#nltk.translate.bleu_score.corpus_bleu
HYP1A = ["It", "is", "a", "guide", "to", "action", "which", "ensures", "that", "the", "military", "always", "obeys",
         "the", "commands", "of", "the", "party"]
HYP2A = ["he", "read", "the", "book", "because", "he", "was", "interested", "in", "world", "history"]

REF1A = ["It", "is", "a", "guide", "to", "action", "that", "ensures", "that", "the", "military", "will", "forever",
         "heed", "Party", "commands"]
REF1B = ["It", "is", "a", "guiding", "principle", "which", "makes", "the", "military", "forces", "always", "being",
         "under", "the", "command", "of", "the", "Party"]
REF1C = ["It", "is", "the", "practical", "guide", "for", "the", "army", "always", "to", "heed", "the", "directions",
         "of", "the", "party"]
REF2A = ["he", "was", "interested", "in", "world", "history", "because", "he", "read", "the", "book"]

TUPLE_OF_REFERENCES = ((REF1A, REF1B, REF1C), (REF2A, ))
TUPLE_OF_HYPOTHESES = (HYP1A, HYP2A)

smooth_func = SmoothingFunction().method2


@pytest.mark.parametrize(
    ["weights", "n_gram", "smooth_func", "smooth"],
    [
        ([1], 1, None, False),
        ([0.5, 0.5], 2, smooth_func, True),
        ([0.333333, 0.333333, 0.333333], 3, None, False),
        ([0.25, 0.25, 0.25, 0.25], 4, smooth_func, True),
    ],
)
def test_bleu_score(weights, n_gram, smooth_func, smooth):
    nltk_output = corpus_bleu(
        TUPLE_OF_REFERENCES, TUPLE_OF_HYPOTHESES, weights=weights, smoothing_function=smooth_func
    )
    our_output = bleu_score(TUPLE_OF_HYPOTHESES, TUPLE_OF_REFERENCES, n_gram=n_gram, smooth=smooth)
    # smooth path: nltk >= 3.6 fixed method2 to not smooth unigrams; the
    # reference (and this port) add-1 smooths every order like 2021-era nltk,
    # so allow the small systematic difference there
    atol = 1e-3 if smooth else 1e-4
    np.testing.assert_allclose(float(our_output), nltk_output, atol=atol)


def test_bleu_empty():
    hyp = [[]]
    ref = [[[]]]
    assert float(bleu_score(hyp, ref)) == 0.0


def test_no_4_gram():
    hyps = [["My", "full", "pytorch-lightning"]]
    refs = [[["My", "full", "pytorch-lightning", "test"], ["Completely", "Different"]]]
    assert float(bleu_score(hyps, refs)) == 0.0


def test_bleu_counts_device_accumulation():
    """The sufficient statistics jit, and summing them across batches equals
    one-shot BLEU over the concatenated corpus (sum-reducible states)."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu.functional.nlp import (
        _intern_corpus,
        _pad_corpus,
        bleu_counts,
        bleu_from_counts,
    )

    hyp_ids, ref_ids = _intern_corpus(TUPLE_OF_HYPOTHESES, TUPLE_OF_REFERENCES)
    padded = _pad_corpus(hyp_ids, ref_ids)

    jitted = jax.jit(bleu_counts, static_argnames="n_gram")
    num, den, c, r = jitted(*padded, n_gram=4)
    one_shot = bleu_from_counts(num, den, c, r)
    np.testing.assert_allclose(
        float(one_shot), float(bleu_score(TUPLE_OF_HYPOTHESES, TUPLE_OF_REFERENCES)), rtol=1e-6
    )

    # accumulate per-sentence counts, then merge by summation
    totals = None
    for b in range(len(TUPLE_OF_HYPOTHESES)):
        h, rs = _intern_corpus([TUPLE_OF_HYPOTHESES[b]], [TUPLE_OF_REFERENCES[b]])
        counts = bleu_counts(*_pad_corpus(h, rs), n_gram=4)
        totals = counts if totals is None else tuple(t + x for t, x in zip(totals, counts))
    np.testing.assert_allclose(float(bleu_from_counts(*totals)), float(one_shot), rtol=1e-6)
