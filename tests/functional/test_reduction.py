"""reduce/class_reduce tests (mirrors reference tests/functional/test_reduction.py:20-31)."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.utils.reductions import class_reduce, reduce


def test_reduce():
    start_tensor = jnp.arange(50, dtype=jnp.float32).reshape(5, 10)

    np.testing.assert_allclose(np.asarray(reduce(start_tensor, "elementwise_mean")), np.asarray(jnp.mean(start_tensor)))
    np.testing.assert_allclose(np.asarray(reduce(start_tensor, "sum")), np.asarray(jnp.sum(start_tensor)))
    np.testing.assert_allclose(np.asarray(reduce(start_tensor, "none")), np.asarray(start_tensor))

    with pytest.raises(ValueError):
        reduce(start_tensor, "error_reduction")


def test_class_reduce():
    num = jnp.asarray(np.random.randint(1, 10, 100).astype(np.float32))
    denom = jnp.asarray(np.random.rand(100).astype(np.float32) + num)
    weights = jnp.asarray(np.random.randint(1, 100, 100).astype(np.float32))

    for class_reduction in ["micro", "macro", "weighted", "none"]:
        result = class_reduce(num, denom, weights, class_reduction=class_reduction)
        if class_reduction == "micro":
            expected = float(jnp.sum(num) / jnp.sum(denom))
            np.testing.assert_allclose(float(result), expected, rtol=1e-6)
        elif class_reduction == "macro":
            expected = float(jnp.mean(num / denom))
            np.testing.assert_allclose(float(result), expected, rtol=1e-6)
        elif class_reduction == "weighted":
            expected = float(jnp.sum(num / denom * (weights / jnp.sum(weights))))
            np.testing.assert_allclose(float(result), expected, rtol=1e-6)
        else:
            expected = np.asarray(num / denom)
            np.testing.assert_allclose(np.asarray(result), expected, rtol=1e-6)


def test_class_reduce_nan_guard():
    """0/0 entries become 0 in every mode (incl. micro, reference distributed.py:74)."""
    num = jnp.zeros(3)
    denom = jnp.zeros(3)
    weights = jnp.ones(3)
    for mode in ["micro", "macro", "weighted", "none"]:
        result = class_reduce(num, denom, weights, class_reduction=mode)
        assert not bool(jnp.any(jnp.isnan(jnp.atleast_1d(result)))), mode
