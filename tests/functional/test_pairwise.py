"""Pairwise similarity/distance functionals vs sklearn.metrics.pairwise."""
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import pairwise as skp

from metrics_tpu.functional import (
    pairwise_cosine_similarity,
    pairwise_euclidean_distance,
    pairwise_linear_similarity,
    pairwise_manhattan_distance,
)

_rng = np.random.RandomState(59)
X = _rng.randn(24, 6).astype(np.float32)
Y = _rng.randn(17, 6).astype(np.float32)

_CASES = [
    (pairwise_cosine_similarity, skp.cosine_similarity),
    (pairwise_euclidean_distance, skp.euclidean_distances),
    (pairwise_manhattan_distance, skp.manhattan_distances),
    (pairwise_linear_similarity, skp.linear_kernel),
]


@pytest.mark.parametrize("ours, theirs", _CASES)
def test_pairwise_two_inputs(ours, theirs):
    got = np.asarray(ours(jnp.asarray(X), jnp.asarray(Y)))
    np.testing.assert_allclose(got, theirs(X, Y), atol=1e-4)


@pytest.mark.parametrize("ours, theirs", _CASES)
def test_pairwise_self_zero_diagonal(ours, theirs):
    got = np.asarray(ours(jnp.asarray(X)))
    want = theirs(X, X)
    np.fill_diagonal(want, 0.0)
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_pairwise_reductions_and_validation():
    full = np.asarray(pairwise_euclidean_distance(jnp.asarray(X), jnp.asarray(Y)))
    np.testing.assert_allclose(
        np.asarray(pairwise_euclidean_distance(jnp.asarray(X), jnp.asarray(Y), reduction="mean")),
        full.mean(-1), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(pairwise_euclidean_distance(jnp.asarray(X), jnp.asarray(Y), reduction="sum")),
        full.sum(-1), atol=1e-4)
    with pytest.raises(ValueError, match="reduction"):
        pairwise_euclidean_distance(jnp.asarray(X), reduction="max")
    with pytest.raises(ValueError, match="2-D"):
        pairwise_cosine_similarity(jnp.zeros(3))
    with pytest.raises(ValueError, match="Expected y of shape"):
        pairwise_cosine_similarity(jnp.zeros((3, 2)), jnp.zeros((3, 4)))


def test_pairwise_jit():
    import jax

    got = jax.jit(pairwise_cosine_similarity)(jnp.asarray(X))
    want = skp.cosine_similarity(X, X)
    np.fill_diagonal(want, 0.0)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4)
