"""embedding_similarity vs sklearn pairwise kernels
(mirrors reference tests/functional/test_self_supervised.py)."""
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics.pairwise import cosine_similarity, linear_kernel

from metrics_tpu.functional import embedding_similarity


@pytest.mark.parametrize("similarity", ["cosine", "dot"])
@pytest.mark.parametrize("reduction", ["none", "mean", "sum"])
def test_against_sklearn(similarity, reduction):
    rng = np.random.RandomState(0)
    batch = rng.rand(10, 5).astype(np.float32)

    result = embedding_similarity(jnp.asarray(batch), similarity=similarity, reduction=reduction, zero_diagonal=False)

    sk = cosine_similarity(batch) if similarity == "cosine" else linear_kernel(batch)
    if reduction == "mean":
        sk = sk.mean(axis=-1)
    elif reduction == "sum":
        sk = sk.sum(axis=-1)
    np.testing.assert_allclose(np.asarray(result), sk, rtol=1e-4, atol=1e-5)


def test_zero_diagonal():
    rng = np.random.RandomState(1)
    batch = rng.rand(6, 4).astype(np.float32)
    result = embedding_similarity(jnp.asarray(batch), zero_diagonal=True)
    np.testing.assert_allclose(np.diag(np.asarray(result)), np.zeros(6), atol=1e-7)
