"""image_gradients (mirrors reference tests/functional/test_image_gradients.py)."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.functional import image_gradients


def test_invalid_input_ndims():
    image = jnp.arange(0, 25, dtype=jnp.float32).reshape(5, 5)
    with pytest.raises(RuntimeError):
        image_gradients(image)


def test_image_gradients_shapes():
    image = jnp.zeros((2, 3, 5, 8))
    dy, dx = image_gradients(image)
    assert dy.shape == image.shape
    assert dx.shape == image.shape


def test_image_gradients_values():
    """1-step finite differences, TF-style layout (reference test asserts the same grid)."""
    image = jnp.arange(0, 25, dtype=jnp.float32).reshape(1, 1, 5, 5)
    dy, dx = image_gradients(image)

    true_dy = np.array(
        [
            [5.0, 5.0, 5.0, 5.0, 5.0],
            [5.0, 5.0, 5.0, 5.0, 5.0],
            [5.0, 5.0, 5.0, 5.0, 5.0],
            [5.0, 5.0, 5.0, 5.0, 5.0],
            [0.0, 0.0, 0.0, 0.0, 0.0],
        ]
    )
    true_dx = np.array(
        [
            [1.0, 1.0, 1.0, 1.0, 0.0],
            [1.0, 1.0, 1.0, 1.0, 0.0],
            [1.0, 1.0, 1.0, 1.0, 0.0],
            [1.0, 1.0, 1.0, 1.0, 0.0],
            [1.0, 1.0, 1.0, 1.0, 0.0],
        ]
    )
    np.testing.assert_allclose(np.asarray(dy[0, 0]), true_dy)
    np.testing.assert_allclose(np.asarray(dx[0, 0]), true_dx)
