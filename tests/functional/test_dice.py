"""dice_score edge cases (mirrors reference tests/functional/test_classification.py dice tests)."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.functional import dice_score


@pytest.mark.parametrize(
    ["pred", "target", "expected"],
    [
        ([[0, 0], [1, 1]], [[0, 0], [1, 1]], 1.0),
        ([[1, 1], [0, 0]], [[0, 0], [1, 1]], 0.0),
        ([[1, 1], [1, 1]], [[1, 1], [0, 0]], 2 / 3),
        ([[1, 1], [0, 0]], [[1, 1], [0, 0]], 1.0),
    ],
)
def test_dice_score(pred, target, expected):
    score = dice_score(jnp.asarray(pred), jnp.asarray(target))
    np.testing.assert_allclose(float(score), expected, atol=1e-6)
