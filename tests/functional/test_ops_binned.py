"""Pallas binned-count kernel vs the XLA contraction (its numerical oracle).

Runs the kernel in interpret mode on the CPU harness (same kernel logic the
TPU executes compiled); real-hardware execution and timing are covered by
``benchmarks/binned_kernel.py`` on the TPU validation run. Binary inputs
(C == 1) exercise the MXU kernel; per-class inputs verify that the dispatch
routes to the XLA path unchanged.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from metrics_tpu.functional.classification.binned_curves import binned_stat_curve_update
from metrics_tpu.ops.binned import binned_stat_counts


@pytest.mark.parametrize(
    "n,t",
    [
        (37, 5),  # everything unaligned, single partial tile
        (256, 100),  # T not lane-aligned
        (2048, 128),  # exactly one aligned tile
        (2049, 64),  # tile boundary + 1
        (5000, 129),  # multiple tiles, T crosses a lane boundary
    ],
)
def test_binary_kernel_matches_xla(n, t):
    rng = np.random.RandomState(42)
    preds = jnp.asarray(rng.rand(n, 1).astype(np.float32))
    pos = jnp.asarray((rng.rand(n, 1) > 0.5).astype(np.float32))
    neg = 1.0 - pos
    thr = jnp.asarray(np.sort(rng.rand(t)).astype(np.float32))

    tp_x, fp_x = binned_stat_counts(preds, pos, neg, thr, impl="xla")
    tp_p, fp_p = binned_stat_counts(preds, pos, neg, thr, impl="pallas_interpret")
    np.testing.assert_allclose(np.asarray(tp_p), np.asarray(tp_x), atol=0)
    np.testing.assert_allclose(np.asarray(fp_p), np.asarray(fp_x), atol=0)


@pytest.mark.parametrize("n,c,t", [(100, 3, 7), (513, 32, 100), (0, 3, 5)])
def test_multiclass_and_empty_dispatch_to_xla(n, c, t):
    """C>1 and N=0 take the XLA path under every impl (same results)."""
    rng = np.random.RandomState(1)
    preds = jnp.asarray(rng.rand(n, c).astype(np.float32))
    pos = jnp.asarray((rng.rand(n, c) > 0.5).astype(np.float32))
    neg = 1.0 - pos
    thr = jnp.asarray(np.sort(rng.rand(t)).astype(np.float32))
    ref = binned_stat_counts(preds, pos, neg, thr, impl="xla")
    out = binned_stat_counts(preds, pos, neg, thr, impl="pallas_interpret")
    for a, b in zip(out, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0)
        assert a.shape == (c, t)


def test_threshold_boundary_equality():
    """Samples exactly on a threshold count as >= (inclusive), both impls."""
    preds = jnp.asarray([[0.5], [0.25], [0.75]], dtype=jnp.float32)
    pos = jnp.asarray([[1.0], [1.0], [0.0]], dtype=jnp.float32)
    neg = 1.0 - pos
    thr = jnp.asarray([0.25, 0.5, 0.75], dtype=jnp.float32)
    for impl in ("xla", "pallas_interpret"):
        tp, fp = binned_stat_counts(preds, pos, neg, thr, impl=impl)
        np.testing.assert_allclose(np.asarray(tp[0]), [2.0, 1.0, 0.0])
        np.testing.assert_allclose(np.asarray(fp[0]), [1.0, 1.0, 1.0])


@pytest.mark.parametrize("shape", [(64,), (64, 4)])
def test_curve_update_impl_parity(shape):
    """binned_stat_curve_update produces identical 4-tuples under both impls."""
    rng = np.random.RandomState(7)
    preds = jnp.asarray(rng.rand(*shape).astype(np.float32))
    target = jnp.asarray((rng.rand(*shape) > 0.5).astype(np.int32))
    thr = jnp.asarray(np.linspace(0.0, 1.0, 50, dtype=np.float32))
    ref = binned_stat_curve_update(preds, target, thr, impl="xla")
    out = binned_stat_curve_update(preds, target, thr, impl="pallas_interpret")
    for a, b in zip(out, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0)
