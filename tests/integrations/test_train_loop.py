"""Integration: metrics inside a real jitted JAX training loop.

The reference's integration tests train a Lightning BoringModel with a metric
in training_step (reference tests/integrations/test_metric_lightning.py:48).
The TPU-native analogue: a linear-classifier train loop where the metric state
threads through a fully jitted (and optionally sharded) train step.
"""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import Accuracy, MetricCollection, Precision
from metrics_tpu.utils import compat


def _make_data(n=256, d=16, c=4, seed=0):
    rng = np.random.RandomState(seed)
    w_true = rng.randn(d, c).astype(np.float32)
    x = rng.randn(n, d).astype(np.float32)
    y = np.argmax(x @ w_true + 0.1 * rng.randn(n, c), axis=1).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


def test_metric_in_jitted_train_loop():
    """Metric state is part of the jitted train-step carry; accuracy improves."""
    x, y = _make_data()
    c = 4

    metric = Accuracy()
    pure = metric.pure()

    def loss_fn(w, xb, yb):
        logits = xb @ w
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(logp[jnp.arange(yb.shape[0]), yb]), logits

    @jax.jit
    def train_step(w, metric_state, xb, yb):
        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(w, xb, yb)
        w = w - 0.1 * grads
        probs = jax.nn.softmax(logits)
        metric_state = pure.update(metric_state, probs, yb)
        return w, metric_state, loss

    w = jnp.zeros((16, c))
    state = pure.init()
    first_epoch_acc = None
    for epoch in range(8):
        state = pure.init()
        for i in range(0, 256, 64):
            w, state, loss = train_step(w, state, x[i:i + 64], y[i:i + 64])
        epoch_acc = float(pure.compute(state))
        if first_epoch_acc is None:
            first_epoch_acc = epoch_acc
    assert epoch_acc > first_epoch_acc
    assert epoch_acc > 0.8


def test_metric_collection_in_sharded_eval(eight_devices):
    """Eval step sharded over the mesh: per-shard update + collective sync
    equals single-device evaluation."""
    x, y = _make_data(n=512)
    w = jnp.asarray(np.random.RandomState(1).randn(16, 4).astype(np.float32))

    collection = MetricCollection([Accuracy(), Precision(num_classes=4, average="macro")])
    pure = collection.pure()
    mesh = Mesh(np.array(eight_devices), ("dp",))

    def eval_step(xb, yb):
        probs = jax.nn.softmax(xb @ w)
        state = pure.update(pure.init(), probs, yb)
        state = pure.sync(state, "dp")
        return pure.compute(state)

    sharded = jax.jit(compat.shard_map(eval_step, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P()))
    out_sharded = sharded(x, y)

    probs = jax.nn.softmax(x @ w)
    state = pure.update(pure.init(), probs, y)
    out_single = pure.compute(state)

    for key in out_single:
        np.testing.assert_allclose(float(out_sharded[key]), float(out_single[key]), atol=1e-6)


def test_stateful_api_in_host_loop_matches_jit_loop():
    """The host-driven stateful API and the in-jit pure API agree exactly."""
    x, y = _make_data(n=128)
    w = jnp.asarray(np.random.RandomState(2).randn(16, 4).astype(np.float32))
    probs = jax.nn.softmax(x @ w)

    m_host = Accuracy()
    for i in range(0, 128, 32):
        m_host(probs[i:i + 32], y[i:i + 32])

    m_pure = Accuracy()
    pure = m_pure.pure()
    step = jax.jit(lambda s, p, t: pure.update(s, p, t))
    state = pure.init()
    for i in range(0, 128, 32):
        state = step(state, probs[i:i + 32], y[i:i + 32])

    np.testing.assert_allclose(float(m_host.compute()), float(pure.compute(state)), atol=0)
