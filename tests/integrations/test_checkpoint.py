"""Checkpoint/resume integration (SURVEY §5): metric states are pytrees, so
they checkpoint with orbax and with plain numpy state_dicts; shard-merging via
``merge_states`` reconstructs a full run from partial checkpoints."""
import os
import pickle
import tempfile

import jax.numpy as jnp
import numpy as np

from metrics_tpu import Accuracy, ConfusionMatrix, MetricCollection


def test_orbax_checkpoint_roundtrip():
    import orbax.checkpoint as ocp

    metric = Accuracy()
    metric(jnp.asarray([0.9, 0.2, 0.8]), jnp.asarray([1, 0, 1]))

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "ckpt")
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(path, metric.state_pytree())

        restored = ckptr.restore(path)
        fresh = Accuracy()
        fresh._set_state({k: jnp.asarray(v) for k, v in restored.items()})
        assert float(fresh.compute()) == float(metric.compute())

        # resume accumulating after restore
        fresh(jnp.asarray([0.1]), jnp.asarray([1]))
        assert int(fresh.total) == 4


def test_state_dict_pickle_roundtrip_collection():
    coll = MetricCollection([Accuracy(), ConfusionMatrix(num_classes=3)])
    coll.persistent(True)
    coll(jnp.asarray([[0.8, 0.1, 0.1], [0.2, 0.7, 0.1]]), jnp.asarray([0, 2]))

    blobs = {name: m.state_dict() for name, m in coll.items()}
    blob = pickle.dumps(blobs)

    coll2 = MetricCollection([Accuracy(), ConfusionMatrix(num_classes=3)])
    for name, m in coll2.items():
        m.load_state_dict(pickle.loads(blob)[name])
    for key, value in coll.compute().items():
        np.testing.assert_allclose(np.asarray(coll2.compute()[key]), np.asarray(value))


def test_merge_states_reconstructs_full_run():
    """Checkpoint-shard merging: two half-run states merge into the full run."""
    full = Accuracy()
    a, b = Accuracy(), Accuracy()

    p1, t1 = jnp.asarray([0.9, 0.3]), jnp.asarray([1, 1])
    p2, t2 = jnp.asarray([0.7, 0.1]), jnp.asarray([1, 0])
    full(p1, t1)
    full(p2, t2)
    a(p1, t1)
    b(p2, t2)

    merged = a.merge_states(a.state_pytree(), b.state_pytree())
    assert float(a.compute_from_state(merged)) == float(full.compute())
