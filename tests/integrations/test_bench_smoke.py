"""The benchmark of record must keep emitting its JSON line.

``python bench.py --smoke`` runs the 8-virtual-device sync benchmark for 2
steps with no subprocess reference — cheap enough for tier-1 — and this test
pins the schema of the printed line so the bench path cannot silently rot
between BENCH_r* rounds (a broken bench would otherwise only surface at the
next manual round).
"""
import json
import os
import subprocess
import sys

_BENCH = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "bench.py")


def test_bench_smoke_json_schema():
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, _BENCH, "--smoke"],
        capture_output=True, text=True, timeout=280, env=env,
        cwd=os.path.dirname(_BENCH),
    )
    assert proc.returncode == 0, f"--smoke failed:\n{proc.stderr[-3000:]}"
    line = proc.stdout.strip().splitlines()[-1]
    out = json.loads(line)

    # schema of record: BENCH_r* and the acceptance gate read these keys
    assert isinstance(out["metric"], str) and "MetricCollection" in out["metric"]
    assert out["unit"] == "ms/step"
    assert out["smoke"] is True
    for key in ("value", "grouped_sync8_ms", "ungrouped_sync8_ms"):
        assert isinstance(out[key], (int, float)) and out[key] > 0, key
    assert out["value"] == out["grouped_sync8_ms"]

    # compute groups must actually deduplicate the synced state plane:
    # Accuracy + the F1/Precision/Recall stat group -> 2+4 leaves vs 14
    assert isinstance(out["states_synced"], int)
    assert isinstance(out["states_synced_ungrouped"], int)
    assert out["states_synced"] < out["states_synced_ungrouped"]
    assert out["states_synced"] == 6
    assert out["states_synced_ungrouped"] == 14
