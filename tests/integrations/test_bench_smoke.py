"""The benchmark of record must keep emitting its JSON line.

``python bench.py --smoke`` runs the 8-virtual-device sync benchmark for 2
steps with no subprocess reference — cheap enough for tier-1 — and this test
pins the schema of the printed line so the bench path cannot silently rot
between BENCH_r* rounds (a broken bench would otherwise only surface at the
next manual round). The ``--trace`` variant additionally pins the
observability fields (``collective_calls`` / ``sync_bytes`` from the
collective counters) and that the emitted Chrome-trace file is valid JSON in
the ``trace_events`` shape Perfetto loads.
"""
import json
import os
import subprocess
import sys

_BENCH = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "bench.py")


def _run_smoke(extra_args=()):
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, _BENCH, "--smoke", *extra_args],
        capture_output=True, text=True, timeout=280, env=env,
        cwd=os.path.dirname(_BENCH),
    )
    assert proc.returncode == 0, f"--smoke failed:\n{proc.stderr[-3000:]}"
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _assert_headline_schema(out):
    # schema of record: BENCH_r* and the acceptance gate read these keys
    assert isinstance(out["metric"], str) and "MetricCollection" in out["metric"]
    assert out["unit"] == "ms/step"
    assert out["smoke"] is True
    for key in ("value", "grouped_sync8_ms", "ungrouped_sync8_ms"):
        assert isinstance(out[key], (int, float)) and out[key] > 0, key
    assert out["value"] == out["grouped_sync8_ms"]

    # compute groups must actually deduplicate the synced state plane:
    # Accuracy + the F1/Precision/Recall stat group -> 2+4 leaves vs 14
    assert isinstance(out["states_synced"], int)
    assert isinstance(out["states_synced_ungrouped"], int)
    assert out["states_synced"] < out["states_synced_ungrouped"]
    assert out["states_synced"] == 6
    assert out["states_synced_ungrouped"] == 14

    # the gather-plane A/B (buffer-state collection) rides the same line
    for key in ("gather_coalesced_ms", "gather_per_leaf_ms"):
        assert isinstance(out[key], (int, float)) and out[key] > 0, key
    assert out["gather_states_synced"] == 6  # 6 PaddedBuffer states


def test_bench_smoke_json_schema():
    out = _run_smoke()
    _assert_headline_schema(out)
    # without --trace the observability fields stay absent (off by default)
    assert "collective_calls" not in out and "sync_bytes" not in out


def test_bench_smoke_trace_json_schema(tmp_path):
    trace_file = tmp_path / "bench_trace.json"
    out = _run_smoke(("--trace", str(trace_file)))
    _assert_headline_schema(out)

    # collective accounting of the grouped step program: the 6 deduped sum
    # leaves coalesce into ONE bucketed psum; bytes shrink vs ungrouped
    assert isinstance(out["collective_calls"], int) and out["collective_calls"] >= 1
    assert out["collective_calls"] <= out["states_synced"]
    assert isinstance(out["sync_bytes"], int) and out["sync_bytes"] > 0
    assert out["sync_bytes"] < out["sync_bytes_ungrouped"]
    # counter totals must agree with the states_synced the bench reports
    assert out["counters"]["states_synced"] == out["states_synced"]
    assert out["counters"]["collective_calls"] == out["collective_calls"]

    # the coalesced gather plane: 2 all_gathers per dtype bucket (f32 data
    # + counts, i32 data + counts) instead of 2 per buffer — same payload
    # bytes, a third of the staged collectives
    assert out["gather_collective_calls"] == 4
    assert out["gather_collective_calls_per_leaf"] == 12
    assert out["gather_sync_bytes"] == out["gather_sync_bytes_per_leaf"]
    assert out["gather_counters"]["calls_by_kind"]["coalesced_gather"] == 4

    # per-phase ms come from the span aggregates, not ad-hoc timers
    assert any(name.startswith("bench.compile") for name in out["phase_ms"])
    assert all(ms >= 0 for ms in out["phase_ms"].values())

    # the trace file is valid Chrome-trace JSON (Perfetto-loadable)
    doc = json.loads(trace_file.read_text())
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    complete = [e for e in events if e.get("ph") == "X"]
    assert complete and all(
        isinstance(e["name"], str) and e["dur"] >= 0 and "ts" in e for e in complete
    )
    assert {e["name"] for e in complete} >= {
        "bench.compile_grouped", "bench.timed_grouped",
        "bench.compile_gather_coalesced", "bench.timed_gather_per_leaf",
    }
    assert doc["otherData"]["collective_calls"] == out["collective_calls"]


def test_bench_check_collectives_gate():
    """``bench.py --check-collectives`` is the tier-1 regression gate: the
    staged ``collective_calls``/``sync_bytes`` of every scenario must be
    within the pinned expectations (growth exits non-zero). This catches a
    silent collective-count regression even when the ms numbers hide it in
    noise."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, _BENCH, "--check-collectives"],
        capture_output=True, text=True, timeout=280, env=env,
        cwd=os.path.dirname(_BENCH),
    )
    assert proc.returncode == 0, f"--check-collectives failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["ok"] is True and out["failures"] == []
    scenarios = out["scenarios"]
    assert set(scenarios) == {
        "sum_grouped", "sum_ungrouped", "gather_coalesced", "gather_per_leaf"
    }
    # the headline reductions of record: one bucketed psum for the grouped
    # sum plane; 4 staged all_gathers (2 per dtype bucket) vs 12 per-leaf
    # for the gather plane, at identical payload bytes
    assert scenarios["sum_grouped"]["collective_calls"] == 1
    assert scenarios["gather_coalesced"]["collective_calls"] == 4
    assert scenarios["gather_per_leaf"]["collective_calls"] == 12
    assert (
        scenarios["gather_coalesced"]["sync_bytes"]
        == scenarios["gather_per_leaf"]["sync_bytes"]
    )
    for row in scenarios.values():
        assert row["status"] != "regression"
