"""The benchmark of record must keep emitting its JSON line.

``python bench.py --smoke`` runs the 8-virtual-device sync benchmark for 2
steps with no subprocess reference — cheap enough for tier-1 — and this test
pins the schema of the printed line so the bench path cannot silently rot
between BENCH_r* rounds (a broken bench would otherwise only surface at the
next manual round). The ``--trace`` variant additionally pins the
observability fields (``collective_calls`` / ``sync_bytes`` from the
collective counters) and that the emitted Chrome-trace file is valid JSON in
the ``trace_events`` shape Perfetto loads.
"""
import json
import os
import subprocess
import sys

_BENCH = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "bench.py")


def _run_smoke(extra_args=()):
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, _BENCH, "--smoke", *extra_args],
        capture_output=True, text=True, timeout=280, env=env,
        cwd=os.path.dirname(_BENCH),
    )
    assert proc.returncode == 0, f"--smoke failed:\n{proc.stderr[-3000:]}"
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _assert_headline_schema(out):
    # schema of record: BENCH_r* and the acceptance gate read these keys
    assert isinstance(out["metric"], str) and "MetricCollection" in out["metric"]
    assert out["unit"] == "ms/step"
    assert out["smoke"] is True
    for key in ("value", "grouped_sync8_ms", "ungrouped_sync8_ms"):
        assert isinstance(out[key], (int, float)) and out[key] > 0, key
    assert out["value"] == out["grouped_sync8_ms"]

    # compute groups must actually deduplicate the synced state plane:
    # Accuracy + the F1/Precision/Recall stat group -> 2+4 leaves vs 14
    assert isinstance(out["states_synced"], int)
    assert isinstance(out["states_synced_ungrouped"], int)
    assert out["states_synced"] < out["states_synced_ungrouped"]
    assert out["states_synced"] == 6
    assert out["states_synced_ungrouped"] == 14


def test_bench_smoke_json_schema():
    out = _run_smoke()
    _assert_headline_schema(out)
    # without --trace the observability fields stay absent (off by default)
    assert "collective_calls" not in out and "sync_bytes" not in out


def test_bench_smoke_trace_json_schema(tmp_path):
    trace_file = tmp_path / "bench_trace.json"
    out = _run_smoke(("--trace", str(trace_file)))
    _assert_headline_schema(out)

    # collective accounting of the grouped step program: the 6 deduped sum
    # leaves coalesce into ONE bucketed psum; bytes shrink vs ungrouped
    assert isinstance(out["collective_calls"], int) and out["collective_calls"] >= 1
    assert out["collective_calls"] <= out["states_synced"]
    assert isinstance(out["sync_bytes"], int) and out["sync_bytes"] > 0
    assert out["sync_bytes"] < out["sync_bytes_ungrouped"]
    # counter totals must agree with the states_synced the bench reports
    assert out["counters"]["states_synced"] == out["states_synced"]
    assert out["counters"]["collective_calls"] == out["collective_calls"]

    # per-phase ms come from the span aggregates, not ad-hoc timers
    assert any(name.startswith("bench.compile") for name in out["phase_ms"])
    assert all(ms >= 0 for ms in out["phase_ms"].values())

    # the trace file is valid Chrome-trace JSON (Perfetto-loadable)
    doc = json.loads(trace_file.read_text())
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    complete = [e for e in events if e.get("ph") == "X"]
    assert complete and all(
        isinstance(e["name"], str) and e["dur"] >= 0 and "ts" in e for e in complete
    )
    assert {e["name"] for e in complete} >= {"bench.compile_grouped", "bench.timed_grouped"}
    assert doc["otherData"]["collective_calls"] == out["collective_calls"]
