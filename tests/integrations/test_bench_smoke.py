"""The benchmark of record must keep emitting its JSON line.

``python bench.py --smoke`` runs the 8-virtual-device sync benchmark for 2
steps with no subprocess reference — cheap enough for tier-1 — and this test
pins the schema of the printed line so the bench path cannot silently rot
between BENCH_r* rounds (a broken bench would otherwise only surface at the
next manual round). The ``--trace`` variant additionally pins the
observability fields: schema v2 (``trace_schema``), the collective counters,
the ``compile`` telemetry block, the per-metric ``device_ms``
update/sync/compute table, per-span ``compiled=yes/no`` + ``compile_ms``
attrs in the emitted Chrome-trace file, and that the file is valid JSON in
the ``trace_events`` shape Perfetto loads. ``--check-collectives`` and
``--check-trajectory`` are the two CI gates — both run here in tier-1, the
trajectory gate as an injected pass/fail pair so it stays deterministic.
"""
import json
import os
import subprocess
import sys

_BENCH = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "bench.py")


def _run_smoke(extra_args=()):
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, _BENCH, "--smoke", *extra_args],
        capture_output=True, text=True, timeout=280, env=env,
        cwd=os.path.dirname(_BENCH),
    )
    assert proc.returncode == 0, f"--smoke failed:\n{proc.stderr[-3000:]}"
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _assert_headline_schema(out):
    # schema of record: BENCH_r* and the acceptance gate read these keys
    assert isinstance(out["metric"], str) and "MetricCollection" in out["metric"]
    assert out["unit"] == "ms/step"
    assert out["smoke"] is True
    for key in ("value", "grouped_sync8_ms", "ungrouped_sync8_ms"):
        assert isinstance(out[key], (int, float)) and out[key] > 0, key
    assert out["value"] == out["grouped_sync8_ms"]

    # compute groups must actually deduplicate the synced state plane:
    # Accuracy + the F1/Precision/Recall stat group -> 2+4 leaves vs 14
    assert isinstance(out["states_synced"], int)
    assert isinstance(out["states_synced_ungrouped"], int)
    assert out["states_synced"] < out["states_synced_ungrouped"]
    assert out["states_synced"] == 6
    assert out["states_synced_ungrouped"] == 14

    # the gather-plane A/B (buffer-state collection) rides the same line
    for key in ("gather_coalesced_ms", "gather_per_leaf_ms"):
        assert isinstance(out[key], (int, float)) and out[key] > 0, key
    assert out["gather_states_synced"] == 6  # 6 PaddedBuffer states

    # the hierarchical A/B on the (4,2) ici x dcn mesh rides the same line
    for key in ("gather_hier_ms", "gather_flat2d_ms"):
        assert isinstance(out[key], (int, float)) and out[key] > 0, key

    # the staged collective-count keys ride the DEFAULT line (trace-schema
    # keys: --check-trajectory binds on every new BENCH_r* round)
    assert out["collective_calls"] == 1 and out["sync_bytes"] == 520
    assert out["sync_bytes"] < out["sync_bytes_ungrouped"]
    assert out["gather_collective_calls"] == 2
    assert out["gather_collective_calls_per_leaf"] == 12
    assert out["gather_sync_bytes"] == out["gather_sync_bytes_per_leaf"]
    # the hierarchy headline: two-stage plane (2 calls per bucket), DCN
    # ring traffic strictly below the flat plane's world traffic
    assert out["hier_collective_calls"] == 2 * out["flat2d_collective_calls"]
    assert out["hier_dcn_calls"] == out["flat2d_collective_calls"]
    assert out["hier_dcn_bytes"] < out["flat2d_world_bytes"]
    assert out["hier_dcn_bytes"] == out["gather_sync_bytes"]  # S-1 = 1 hop

    # the sketch A/B rides the same line: the sketch-mode twin of the gather
    # collection syncs PSUM-ONLY (zero staged gathers) over a traffic-
    # independent payload an order of magnitude under the buffer plane's
    assert isinstance(out["sketch_sync_ms"], (int, float)) and out["sketch_sync_ms"] > 0
    assert out["sketch_states_synced"] == 2  # AUROC+AP share one group histogram
    assert out["sketch_collective_calls"] == 2  # two-stage (ici + dcn) psum
    assert out["sketch_gather_calls"] == 0  # psum-only: the sketch contract
    assert out["sketch_sync_bytes"] * 10 < out["hier_sync_bytes"]

    # the keyed slab A/B rides the same line: Keyed(AUROC sketch) x 10,000
    # segments stages the SAME collective count and kinds as the unkeyed
    # metric — psum-only, K-independent program, only the payload scales
    assert isinstance(out["keyed_sync_ms"], (int, float)) and out["keyed_sync_ms"] > 0
    assert out["keyed_states_synced"] == 2  # the histogram slab + the row-count slab
    assert out["keyed_collective_calls"] == 2  # two-stage (ici + dcn) psum
    assert out["keyed_collective_calls"] == out["keyed_unkeyed_collective_calls"]
    assert out["keyed_gather_calls"] == 0  # psum-only: the slab contract
    assert out["keyed_sync_bytes"] == 2640000  # (10000*2*16 + 10000) * 4 * 2 stages

    # the sparse delta-sync A/B rides the same line: the same Keyed slab,
    # but each step touches only 64 of the 10,000 rows and syncs through
    # SparseSyncPlane (bitmap psum + fixed-capacity union gather) — staged
    # bytes follow the TOUCHED-ROW count, not the table size
    assert isinstance(out["sparse_sync_ms"], (int, float)) and out["sparse_sync_ms"] > 0
    assert out["sparse_states_synced"] == 2  # the histogram slab + the row-count slab
    assert out["sparse_collective_calls"] == 4  # two-stage bitmap psum + union gather
    assert out["sparse_gather_calls"] == 2  # ONE union gather, staged ici + dcn
    assert out["sparse_sync_bytes"] == 36112  # bitmap words + 64-row payload, 2 stages
    assert out["sparse_sync_bytes"] * 10 < out["keyed_sync_bytes"]  # the sparse headline

    # the heavy-hitter A/B rides the same line: HeavyHitters(AUROC sketch)
    # over a 1,000,000-key space stages the SAME collective count and kinds
    # as the unkeyed metric — both tiers (exact hot slab + count-min tail)
    # are sum leaves in one psum bucket, and state bytes are constant in
    # the live-key count ((256*2*16 + 256 + 4*1024*2*16 + 4*1024) * 4 * 2)
    assert isinstance(out["hh_sync_ms"], (int, float)) and out["hh_sync_ms"] > 0
    assert out["hh_states_synced"] == 4  # hot slab+rows, tail cms+rows
    assert out["hh_collective_calls"] == 2  # two-stage (ici + dcn) psum
    assert out["hh_collective_calls"] == out["hh_unkeyed_collective_calls"]
    assert out["hh_gather_calls"] == 0  # psum-only: both tiers
    assert out["hh_sync_bytes"] == 1148928
    # the open-world ingest pair: throughput through the space-saving loop
    # must not collapse as the key space grows 10k -> 1M (the flatness
    # headline; smoke timings are noisy, so only a collapse gate here)
    for key in ("hh_ingest_steps_per_s", "hh_ingest_steps_per_s_10k"):
        assert isinstance(out[key], (int, float)) and out[key] > 0, key
    assert out["hh_ingest_steps_per_s"] > 0.3 * out["hh_ingest_steps_per_s_10k"]
    # the tail's (e/width)*N certificate is on the line, deterministic for
    # the seeded ingest stream
    assert out["hh_tail_overcount_bound"] > 0

    # the quantile-sketch A/B rides the same line: Keyed(Quantile(q=0.99))
    # x 256 tenants — the per-tenant p99 plane — stages the SAME collective
    # count and kinds as the unkeyed scalar Quantile (psum-only), and state
    # bytes are DETERMINISTIC and traffic-independent:
    # (256 slots * 281 log buckets + 256 rows) * 4 bytes
    assert isinstance(out["qsketch_sync_ms"], (int, float)) and out["qsketch_sync_ms"] > 0
    assert out["qsketch_states_synced"] == 2  # the counts slab + the row-count slab
    assert out["qsketch_collective_calls"] == 2  # two-stage (ici + dcn) psum
    assert out["qsketch_collective_calls"] == out["qsketch_unkeyed_collective_calls"]
    assert out["qsketch_gather_calls"] == 0  # psum-only: the sketch contract
    assert out["qsketch_sync_bytes"] == 577536  # (256*281 + 256) * 4 * 2 stages
    assert out["qsketch_state_bytes"] == 288768  # (256*281 + 256) * 4 bytes

    # the megafusion plane rides the same line: (a) the whole-collection
    # fused forward — ONE jitted program per host-API step with donated
    # state slabs; (b) the mixed collection (all four mergeable state
    # kinds) synced through the packed one-psum-per-crossing plane, with
    # the staged count pinned IDENTICAL at 6 and 14 members (3 buckets x
    # 2 crossings: the packed psum + the pmin/pmax riders)
    for key in ("fused_step_ms", "mixed_sync_ms"):
        assert isinstance(out[key], (int, float)) and out[key] > 0, key
    assert out["mixed_states_synced"] == 14  # the 6-member joint state plane
    assert out["fused_collective_calls"] == 6  # (1 psum + pmin + pmax) x 2 stages
    assert out["fused_collective_calls"] == out["fused_collective_calls_14"]
    assert out["fused_sync_bytes"] == 1100808  # int32 lane + f32 siblings + riders

    # the windowed serving A/B rides the same line: Windowed(AUROC sketch)
    # x 4 window slots stages the SAME collective count and kinds as the
    # unwindowed metric — windows are a state axis, window roll is a slot
    # rotation, and the program is psum-only
    assert isinstance(out["service_sync_ms"], (int, float)) and out["service_sync_ms"] > 0
    assert out["service_states_synced"] == 2  # the histogram slab + the row-count slab
    assert out["service_collective_calls"] == 2  # two-stage (ici + dcn) psum
    assert out["service_collective_calls"] == out["service_unwindowed_collective_calls"]
    assert out["service_gather_calls"] == 0  # psum-only: the window-slab contract
    assert out["service_sync_bytes"] == 1056  # (4*2*16 + 4) * 4 bytes * 2 stages

    # the deferred-sync A/B rides the same line: the async plane dispatches
    # the IDENTICAL staged program as its fenced synchronous twin (psum-only,
    # count pinned equal) — only the fence moves; the ordering of the two ms
    # numbers is --check-async's pin, not the smoke schema's (2 timed steps
    # are noise)
    for key in ("async_sync8_ms", "fenced_sync8_ms"):
        assert isinstance(out[key], (int, float)) and out[key] > 0, key
    assert out["async_states_synced"] == 6  # the grouped sync8 state plane
    assert out["async_collective_calls"] == 1  # one bucketed psum
    assert out["async_collective_calls"] == out["async_fenced_collective_calls"]
    assert out["async_sync_bytes"] == 520  # the grouped sum bucket
    assert out["async_gather_calls"] == 0  # psum-only: same program, deferred fence

    # the lag-k ring rides the line too: deeper rings replay the IDENTICAL
    # staged program (depth is in-flight handles, never extra collectives),
    # and the deferred epoch gather issues exactly the synchronous grouped
    # plane's per-group call count (2 groups -> 2 packed gather calls)
    for key in ("async_lag2_ms", "async_lag3_ms"):
        assert isinstance(out[key], (int, float)) and out[key] > 0, key
    assert out["async_lag_collective_calls"] == out["async_collective_calls"]
    assert out["async_lag_sync_bytes"] == out["async_sync_bytes"]
    assert out["async_lag_epoch_gather_calls"] == 2
    assert out["async_lag_epoch_gather_calls"] == out["async_lag_epoch_sync_gather_calls"]

    # the traffic-generator scenario: sustained batches/sec through a real
    # MetricService ingest loop (deferred window publishes included)
    assert isinstance(out["service_ingest_steps_per_s"], (int, float))
    assert out["service_ingest_steps_per_s"] > 0

    # the ingest fast path A/B: coalesced drain throughput on the bursty
    # producer, the batches-per-drain factor (>= 1 by construction; the
    # >= 2x pins live in --check-ingest, not here — smoke timing is noise),
    # and the bucketed routing-program compile pin: the prewarmed bucket
    # ladder 32..512 is EXACTLY five programs, and the timed stream must
    # ride them without a single steady-state recompile
    assert isinstance(out["ingest_coalesced_steps_per_s"], (int, float))
    assert out["ingest_coalesced_steps_per_s"] > 0
    assert out["ingest_coalesce_factor"] >= 1.0
    assert out["ingest_program_cache_misses"] == 5

    # the tiered-retention read plane: the full-range query rides the line
    # in ms, and the store's gauge counts are EXACT pins on the seeded
    # 240 s stream — 24 published windows down the (4, 4, 8) ladder is
    # deterministic routing arithmetic, and resident bytes are bounded by
    # the ladder shape (the memory-flat headline --check-retention gates)
    assert isinstance(out["retention_query_ms"], (int, float))
    assert out["retention_query_ms"] > 0
    assert out["retention_windows_banked"] == 24
    assert out["retention_rollups"] == 21
    assert out["retention_resident_bytes"] == 108

    # the sharded fleet scenario: the 1-vs-8-shard ingest throughput pair
    # over the simulated per-batch serving work (--check-fleet gates the
    # ratio at >= 4x; here only sanity + the merge tier's exact counts —
    # the 8-shard number must at least beat the 1-shard loop even under
    # smoke noise)
    for key in ("fleet_ingest_steps_per_s", "fleet_ingest_steps_per_s_1shard"):
        assert isinstance(out[key], (int, float)) and out[key] > 0, key
    assert out["fleet_ingest_steps_per_s"] > out["fleet_ingest_steps_per_s_1shard"]
    assert out["fleet_scaling_x"] > 1.0
    # deterministic merge-tier counts over the seeded exact stream: 7 merged
    # windows from 8 shards' 41 per-shard publishes, zero lost
    assert out["fleet_shards_merged_windows"] == 7
    assert out["fleet_shards_published_windows"] == 41
    assert out["fleet_lost_windows"] == 0

    # the watermark-agreement scenario: one report + min-exchange round per
    # timed iteration through the background host plane (the exchange count
    # is deterministic — one explicit round each), and the sliding-window
    # publish count over the seeded stream is pure routing arithmetic
    assert isinstance(out["wm_agreement_ms"], (int, float)) and out["wm_agreement_ms"] > 0
    assert out["wm_exchange_calls"] == 20
    assert out["slide_windows_published"] == 12

    # the pipeline-health plane: the deterministic lifecycle soak (16
    # synthetic-event-time batches, 2 per 10 s window) publishes 8 windows
    # and every one must carry a COMPLETE core stage ledger — an exact pin;
    # a drop means a publish path stopped stamping. The ledger-derived
    # latency headlines ride along in ms (monotonic-clock stage spans)
    for key in ("publish_lag_ms", "selfmeter_p99_ms"):
        assert isinstance(out[key], (int, float)) and out[key] > 0, key
    assert out["lifecycle_windows_stamped"] == 8

    # fault counters ride the default line and are ZERO on a clean bench run
    # (--check-trajectory pins them at zero on every new BENCH_r* round);
    # slab_dropped_samples joins them — in-window bench traffic never drops —
    # and wm_stragglers: healthy bench ranks are never excluded from the
    # agreed watermark; sparse_fallbacks joins them — the bench sparse
    # stream never exceeds sparse_capacity, so a dense fallback on the
    # clean line means the sparsity plumbing silently broke
    for key in ("sync_retries", "sync_deadline_exceeded", "degraded_computes", "quarantined_updates",
                "slab_dropped_samples", "wm_stragglers", "sparse_fallbacks"):
        assert out[key] == 0, key


def test_bench_smoke_json_schema():
    out = _run_smoke()
    _assert_headline_schema(out)
    # the span/compile observability fields stay absent without --trace
    # (collective COUNTS are on the default line — trace-time counting is
    # free — but spans, compile telemetry, and the trace file are not)
    for key in ("trace_schema", "phase_ms", "compile", "device_ms", "trace_file", "counters"):
        assert key not in out, key


def test_bench_smoke_trace_json_schema(tmp_path):
    trace_file = tmp_path / "bench_trace.json"
    out = _run_smoke(("--trace", str(trace_file)))
    _assert_headline_schema(out)

    # schema version of the --trace payload: v17 added the ingest fast
    # path (ingest_coalesced_steps_per_s / ingest_coalesce_factor — the
    # queue-drain coalescing A/B on the bursty producer — plus the bucketed
    # routing-program compile pin ingest_program_cache_misses on the default
    # line and the ingest_counters block here, gated by --check-ingest's
    # parity/throughput/chaos tiers); v16 added the pipeline-health
    # plane (publish_lag_ms / selfmeter_p99_ms — the lifecycle ledger's
    # worst close -> publish span and the self-meter sketch's certified e2e
    # p99 — plus the exact lifecycle_windows_stamped pin on the default
    # line, gated by --check-health's ledger/certificate/stall/fleet
    # tiers); v15 added the megafusion
    # plane (fused_step_ms — the whole-collection single-program forward
    # with donated state slabs — plus the mixed packed-psum sync keys
    # fused_collective_calls / fused_sync_bytes with the 14-member count
    # pinned equal, gated by --check-collectives' megafusion gate);
    # v14 added the tiered retention
    # plane (retention_query_ms — the banked ladder's full-range read —
    # plus the deterministic windows-banked/roll-up/resident-bytes pins on
    # the default line, gated by --check-retention's four-kind bit-exact
    # sweep); v13 added the sparse delta-sync
    # plane (sparse_* staged keys with sync bytes pinned under a tenth of
    # the dense keyed plane's and collective counts constant in K,
    # sparse_fallbacks zero-pinned on the default line, gated by
    # --check-collectives' sparse gate); v12 added the quantile-sketch
    # plane (qsketch_* staged-count keys pinned to the unkeyed scalar twin +
    # the deterministic qsketch_state_bytes pin, gated by --check-quantile);
    # v11 added the rank-coherent
    # streaming plane (wm_agreement_ms / wm_exchange_calls / wm_stragglers
    # zero-pinned + slide_windows_published on the default line, gated by
    # --check-watermark); v10 added the heavy-hitter
    # open-world plane (hh_* staged-count keys pinned to the unkeyed twin,
    # the 10k/1M ingest flatness pair, and the tail certificate on the
    # default line); v9 added the sharded fleet
    # (fleet_ingest_steps_per_s at 1/8 shards + fleet_scaling_x + the merge
    # tier's window counts with fleet_lost_windows pinned at zero); v8 added
    # the lag-k pipelined plane (async_lag2/3_ms ring-depth keys,
    # async_lag_* staged-count pins, and the deferred-epoch-gather
    # call-count pair on the default line); v7 added the deferred-sync A/B
    # (async_* staged-count keys + the fenced twin +
    # service_ingest_steps_per_s on the default line, full async counters
    # here incl. the deferred dispatch/fence/completion block); v6 added the
    # windowed serving A/B; v5 the keyed slab A/B; v4 the sketch A/B; v3
    # moved the collective counts to the default line and added the
    # hierarchical A/B + per-crossing counters; bump this pin with the schema
    assert out["trace_schema"] == 17
    # the sketch program's full snapshot: psum-only, no gather kinds staged
    sketch_kinds = out["sketch_counters"]["calls_by_kind"]
    assert sketch_kinds.get("psum", 0) == 2
    for kind in ("all_gather", "coalesced_gather", "process_allgather"):
        assert sketch_kinds.get(kind, 0) == 0, kind
    # the keyed slab program: the same psum-only shape at K=10,000
    keyed_kinds = out["keyed_counters"]["calls_by_kind"]
    assert keyed_kinds.get("psum", 0) == 2
    for kind in ("all_gather", "coalesced_gather", "process_allgather"):
        assert keyed_kinds.get(kind, 0) == 0, kind
    assert out["keyed_counters"]["bytes_by_crossing"]["dcn"] == out["keyed_sync_bytes"] // 2
    # the sparse program pair: one two-stage bitmap psum + one two-stage
    # fixed-capacity union gather, and the round ledger recorded exactly the
    # compiling round — one sync of 64 union rows, zero fallbacks or skips
    sparse_kinds = out["sparse_counters"]["calls_by_kind"]
    assert sparse_kinds.get("psum", 0) == 2
    assert sum(
        sparse_kinds.get(k, 0)
        for k in ("all_gather", "coalesced_gather", "process_allgather")
    ) == 2
    assert out["sparse_counters"]["sparse"] == {
        "syncs": 1, "rows": 64, "fallbacks": 0, "skips": 0,
    }
    # the heavy-hitter program: the same psum-only shape over a 1M key space
    hh_kinds = out["hh_counters"]["calls_by_kind"]
    assert hh_kinds.get("psum", 0) == 2
    for kind in ("all_gather", "coalesced_gather", "process_allgather"):
        assert hh_kinds.get(kind, 0) == 0, kind
    assert out["hh_counters"]["bytes_by_crossing"]["dcn"] == out["hh_sync_bytes"] // 2
    # the quantile-sketch program: the same psum-only shape at K=256 tenants
    qsk_kinds = out["qsketch_counters"]["calls_by_kind"]
    assert qsk_kinds.get("psum", 0) == 2
    for kind in ("all_gather", "coalesced_gather", "process_allgather"):
        assert qsk_kinds.get(kind, 0) == 0, kind
    assert out["qsketch_counters"]["bytes_by_crossing"]["dcn"] == out["qsketch_sync_bytes"] // 2
    # the mixed megafusion program: ONE packed psum per crossing (the
    # multi-dtype payload records under the "packed" label) plus the
    # pmin/pmax riders — zero gathers of any kind
    mixed_kinds = out["mixed_counters"]["calls_by_kind"]
    assert mixed_kinds.get("psum", 0) == 2
    assert mixed_kinds.get("pmin", 0) == 2
    assert mixed_kinds.get("pmax", 0) == 2
    for kind in ("all_gather", "coalesced_gather", "process_allgather"):
        assert mixed_kinds.get(kind, 0) == 0, kind
    assert "psum:packed" in out["mixed_counters"]["bytes_by_kind_dtype"]
    # the windowed serving program: the same psum-only shape at W=4 slots
    service_kinds = out["service_counters"]["calls_by_kind"]
    assert service_kinds.get("psum", 0) == 2
    for kind in ("all_gather", "coalesced_gather", "process_allgather"):
        assert service_kinds.get(kind, 0) == 0, kind
    assert out["service_counters"]["bytes_by_crossing"]["dcn"] == out["service_sync_bytes"] // 2
    # the deferred program: the identical psum-only shape as the fenced twin,
    # and exactly one dispatch/fence/completion from the compiling first step
    async_kinds = out["async_counters"]["calls_by_kind"]
    assert async_kinds.get("psum", 0) == 1
    for kind in ("all_gather", "coalesced_gather", "process_allgather"):
        assert async_kinds.get(kind, 0) == 0, kind
    assert out["async_counters"]["deferred"] == {
        "dispatched": 1, "fenced": 1, "completed": 1,
    }
    # the ingest coalescing block: the A/B's raw numbers ride the trace
    # payload — the timed stream is 168 batches, the bucket ladder is five
    # compiled programs, and every timed drain hits the cache
    ingest = out["ingest_counters"]
    assert ingest["processed"] == 168
    assert ingest["drains"] >= 1
    assert ingest["coalesce_factor"] >= 1.0
    assert ingest["program_cache_misses"] == 5
    assert ingest["program_cache_hits"] >= 1

    # counter totals must agree with the states_synced the bench reports
    assert out["counters"]["states_synced"] == out["states_synced"]
    assert out["counters"]["collective_calls"] == out["collective_calls"]
    assert out["gather_counters"]["calls_by_kind"]["coalesced_gather"] == 2
    # the hierarchical program's full snapshot: per-crossing split included
    assert out["hier_counters"]["calls_by_crossing"] == {"dcn": 2, "ici": 2}
    assert out["hier_counters"]["bytes_by_crossing"]["dcn"] == out["hier_dcn_bytes"]

    # per-phase ms come from the span aggregates, not ad-hoc timers
    assert any(name.startswith("bench.compile") for name in out["phase_ms"])
    assert all(ms >= 0 for ms in out["phase_ms"].values())

    # compile telemetry (jax.monitoring): the A/B builds compiled at least
    # one program, and the compile phases carry nonzero backend time
    compile_info = out["compile"]
    assert compile_info["compile_events"] >= 1
    assert compile_info["backend_compile_ms"] > 0
    assert set(compile_info["compile_cache"]) == {"hits", "misses"}
    # the span aggregates attribute compile time to the bench.compile_*
    # phases (first-dispatch spans no longer conflate compile with run)
    assert any(
        name.startswith(("bench.build", "bench.compile"))
        for name in out["phase_compile_ms"]
    )

    # the per-metric device-time table: every bench-collection member gets
    # update/sync/compute rows from the fenced stateful scenario
    device_ms = out["device_ms"]
    for member in ("Accuracy", "F1", "Precision", "Recall"):
        assert {"update", "sync", "compute"} <= set(device_ms[member]), member
        assert all(ms >= 0 for ms in device_ms[member].values())

    # the trace file is valid Chrome-trace JSON (Perfetto-loadable)
    doc = json.loads(trace_file.read_text())
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    complete = [e for e in events if e.get("ph") == "X"]
    assert complete and all(
        isinstance(e["name"], str) and e["dur"] >= 0 and "ts" in e for e in complete
    )
    assert {e["name"] for e in complete} >= {
        "bench.compile_grouped", "bench.timed_grouped",
        "bench.compile_gather_coalesced", "bench.timed_gather_per_leaf",
        "bench.devtime",
    }
    assert doc["otherData"]["collective_calls"] == out["collective_calls"]

    # per-span compile disambiguation: every complete event is stamped
    # compiled=yes/no; the compile phases say yes with compile_ms, the
    # steady-state timed phases say no
    by_name = {e["name"]: e for e in complete}
    for e in complete:
        assert e.get("args", {}).get("compiled") in ("yes", "no"), e["name"]
    compile_grouped = by_name["bench.compile_grouped"]["args"]
    assert compile_grouped["compiled"] == "yes"
    assert compile_grouped["compile_ms"] > 0
    assert by_name["bench.timed_grouped"]["args"]["compiled"] == "no"

    # the fenced scenario's spans carry device_ms on the metric phases
    fenced = [
        e for e in complete
        if e["name"] in ("metric.update", "metric.sync_state", "metric.compute")
        and "device_ms" in e.get("args", {})
    ]
    assert fenced and all(e["args"]["device_ms"] >= 0 for e in fenced)


def test_bench_check_collectives_gate():
    """``bench.py --check-collectives`` is the tier-1 regression gate: the
    staged ``collective_calls``/``sync_bytes`` of every scenario must be
    within the pinned expectations (growth exits non-zero). This catches a
    silent collective-count regression even when the ms numbers hide it in
    noise."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, _BENCH, "--check-collectives"],
        capture_output=True, text=True, timeout=280, env=env,
        cwd=os.path.dirname(_BENCH),
    )
    assert proc.returncode == 0, f"--check-collectives failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["ok"] is True and out["failures"] == []
    scenarios = out["scenarios"]
    assert set(scenarios) == {
        "sketch_sync", "keyed_sync", "keyed_unkeyed",
        "sparse_sync", "sparse_sync_flat", "hh_sync",
        "sum_grouped", "sum_ungrouped", "gather_coalesced", "gather_per_leaf",
        "gather_hier", "gather_flat2d",
        "mixed6_sync", "mixed14_sync",
        "sharded_auroc", "sharded_auroc_hier",
        "sharded_retrieval", "sharded_retrieval_hier",
    }
    # the headline reductions of record: one bucketed psum for the grouped
    # sum plane; 2 staged all_gathers (1 per dtype bucket, counts riding
    # the data payload) vs 12 per-leaf for the gather plane, at identical
    # payload bytes
    assert scenarios["sum_grouped"]["collective_calls"] == 1
    assert scenarios["gather_coalesced"]["collective_calls"] == 2
    assert scenarios["gather_per_leaf"]["collective_calls"] == 12
    assert (
        scenarios["gather_coalesced"]["sync_bytes"]
        == scenarios["gather_per_leaf"]["sync_bytes"]
    )
    # the sharded engines are pinned like the sync planes: the AUROC ring
    # stages 3 ppermutes + 1 coalesced psum; the retrieval regroup stages
    # 4 all_to_alls + 3 psums
    assert scenarios["sharded_auroc"]["collective_calls"] == 4
    assert scenarios["sharded_retrieval"]["collective_calls"] == 7
    # the hierarchical scenarios pin the per-crossing structure: every
    # staged collective splits into an ici stage and a dcn stage, and the
    # DCN-crossing ring traffic is S-1 = 1 hop per payload byte where the
    # flat plane pays W-1 = 7
    assert scenarios["gather_hier"]["dcn_calls"] == 2
    assert scenarios["gather_hier"]["dcn_bytes"] == scenarios["gather_coalesced"]["sync_bytes"]
    assert scenarios["gather_flat2d"]["world_bytes"] == 7 * scenarios["gather_flat2d"]["sync_bytes"]
    assert scenarios["sharded_auroc_hier"]["dcn_bytes"] == scenarios["sharded_auroc"]["sync_bytes"]
    assert scenarios["sharded_retrieval_hier"]["dcn_calls"] == 7
    # the hierarchy gate of record: reflattening a DCN-crossing collective
    # (dcn bytes >= flat world bytes) fails the gate
    assert out["hier_gate"]["ok"] is True
    assert out["hier_gate"]["hier_dcn_bytes"] < out["hier_gate"]["flat2d_world_bytes"]
    # the sketch gate of record: the sketch plane is psum-only (zero staged
    # gathers of any kind) and moves under 10% of the buffer plane's bytes
    # on the same (4,2) mesh — the acceptance criterion of the constant-
    # memory conversion
    assert out["sketch_gate"]["ok"] is True
    assert scenarios["sketch_sync"]["gather_calls"] == 0
    assert scenarios["sketch_sync"]["sync_bytes"] * 10 < scenarios["gather_hier"]["sync_bytes"]
    # the keyed gate of record: K=10,000 segments stage the identical
    # collective count as the unkeyed metric, psum-only — collective counts
    # are K-independent (the slab contract)
    assert out["keyed_gate"]["ok"] is True
    assert (
        scenarios["keyed_sync"]["collective_calls"]
        == scenarios["keyed_unkeyed"]["collective_calls"]
    )
    assert scenarios["keyed_sync"]["gather_calls"] == 0
    # the heavy-hitter gate of record: the OPEN-WORLD contract — a 1M-key-
    # space HeavyHitters stages the identical psum-only program as the
    # unkeyed metric, promotion/demotion conserves mass bit-exactly vs the
    # oracle, every tail query on the seeded Zipfian stream lies within the
    # reported (e/width)*N certificate, and state bytes are IDENTICAL at
    # 10k and 1M live keys
    hh_gate = out["hh_gate"]
    assert hh_gate["ok"] is True
    assert hh_gate["hh_collective_calls"] == hh_gate["unkeyed_collective_calls"]
    assert hh_gate["hh_gather_calls"] == 0
    assert hh_gate["simulated_key_space"] == 1_000_000
    assert hh_gate["mass_conserved"] is True
    assert hh_gate["demotions"] > 0  # the stream actually churned the tiers
    assert hh_gate["cert_violations"] == 0 and hh_gate["cert_checked"] > 100
    assert hh_gate["state_bytes_10k"] == hh_gate["state_bytes_1m"]
    # the sparse gate of record: staged bytes proportional to the touched
    # rows — under 10% of the dense keyed plane's on the same mesh at the
    # same K — with a K-independent staged collective count, merges
    # bit-exact vs the dense coalesced plane on both the flat and (4,2)
    # hierarchical meshes, the capacity-overflow round falling back to the
    # dense plane bit-exactly AND counted, and the empty-touch round
    # skipping the row exchange entirely
    sparse_gate = out["sparse_gate"]
    assert sparse_gate["ok"] is True
    assert sparse_gate["sparse_sync_bytes"] * 10 < sparse_gate["dense_keyed_bytes"]
    assert sparse_gate["sparse_collective_calls"] == sparse_gate["small_k_collective_calls"]
    assert sparse_gate["bit_exact_flat"] is True
    assert sparse_gate["bit_exact_hier"] is True
    assert sparse_gate["fallback_bit_exact"] is True and sparse_gate["fallbacks"] > 0
    assert sparse_gate["skips"] > 0 and sparse_gate["gather_skips"] > 0
    assert scenarios["sparse_sync"]["sync_bytes"] * 10 < scenarios["keyed_sync"]["sync_bytes"]
    # the megafusion gate of record: the mixed collection — every mergeable
    # state kind behind one MetricCollection — stages ONE packed psum per
    # crossing (2 on the (4,2) mesh) and the SAME staged collective count
    # at 6 and 14 members (membership grows the payload, never the
    # program), with the packed plane bit-exact vs the per-leaf reference
    mega = out["megafusion_gate"]
    assert mega["ok"] is True
    assert mega["mixed6_psum_calls"] == mega["crossings"] == 2
    assert mega["mixed14_psum_calls"] == 2
    assert mega["mixed6_collective_calls"] == mega["mixed14_collective_calls"]
    assert mega["parity_ok"] is True
    assert scenarios["mixed6_sync"]["gather_calls"] == 0
    assert scenarios["mixed14_sync"]["gather_calls"] == 0
    for row in scenarios.values():
        assert row["status"] != "regression"


def test_bench_check_faults_gate():
    """``bench.py --check-faults`` is the fault-tolerance gate: under a
    seeded stall+drop+corruption schedule on the sync8 collection's host
    plane, the retry-recovered run must be bit-exact vs the fault-free run,
    the degrade-policy run must complete within its budget (no hang) with a
    ``degraded=yes``-stamped sync span and nonzero ``degraded_computes``,
    and a clean guarded run must report zero fault counters."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, _BENCH, "--check-faults"],
        capture_output=True, text=True, timeout=280, env=env,
        cwd=os.path.dirname(_BENCH),
    )
    assert proc.returncode == 0, f"--check-faults failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["ok"] is True and out["failures"] == []
    assert all(v == 0 for v in out["clean"]["faults"].values())
    assert out["recovered"]["faults"]["sync_retries"] >= 3
    assert out["recovered"]["faults"]["degraded_computes"] == 0
    assert out["degraded"]["faults"]["degraded_computes"] >= 1
    assert out["degraded"]["degraded_spans"] >= 1
    assert out["degraded"]["elapsed_s"] < out["degraded"]["budget_s"]


def test_bench_check_async_gate():
    """``bench.py --check-async`` is the deferred-sync gate: the deferred
    plane must stage the IDENTICAL collective count and kinds as the
    synchronous plane (zero new kinds — it dispatches the same
    ``coalesced_sync_state`` program), ``sync_lag=k`` forward values must be
    bit-exact the synchronous plane's k-steps-back values for k in {1,2,3}
    with an exact epoch compute, wall time must be monotone non-increasing
    in lag depth under the bursty simulated-DCN gather, ``sync_lag="auto"``
    must pick lag 0 on the free collective and lag >= 1 under the slow one,
    the deferred epoch gather must match the synchronous grouped plane
    bit-exactly at the identical gather-call count, and the async step ms
    must come in strictly below the fenced synchronous step ms on the sync8
    scenario (the overlap the deferred dispatch exists to buy)."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, _BENCH, "--check-async"],
        capture_output=True, text=True, timeout=280, env=env,
        cwd=os.path.dirname(_BENCH),
    )
    assert proc.returncode == 0, f"--check-async failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["ok"] is True and out["failures"] == []
    # parity: same collective kinds and counts, same payload bytes
    assert out["parity"]["async_calls_by_kind"] == out["parity"]["sync_calls_by_kind"]
    assert out["parity"]["async_bytes"] == out["parity"]["sync_bytes"]
    # the compiling first step dispatched and fenced exactly one handle
    assert out["parity"]["async_deferred"]["dispatched"] == out["parity"]["async_deferred"]["fenced"]
    # lag: every reported per-step series IS the synchronous series shifted
    # by its ring depth (warm-up steps read the local == synced delta)
    for k_str, series in out["lag"]["lag_vals"].items():
        k = int(k_str)
        assert series[k:] == out["lag"]["sync_vals"][:-k], k_str
    # monotone: deeper rings never slower under the bursty DCN simulation
    sweep = out["lag_sweep"]["ms_by_lag"]
    assert sweep["3"] <= sweep["2"] <= sweep["1"]
    # auto: free collective -> lag 0; slow gather -> lag >= 1
    assert out["auto"]["free_lag"] == 0
    assert out["auto"]["slow_lag"] >= 1
    # epoch: the deferred grouped gather costs exactly the synchronous count
    assert out["epoch_gather"]["deferred_calls"] == out["epoch_gather"]["sync_calls"]
    # overlap: the sync_lag=1 forward loop beats the synchronous plane under
    # the simulated-DCN gather, and on the device plane the deferred fence
    # waits less host time than the synchronous block (the hidden wait)
    assert out["overlap"]["async_step_ms"] < out["overlap"]["sync_step_ms"]
    assert out["overlap"]["async_fence_wait_ms"] < out["overlap"]["fenced_block_ms"]


def test_bench_check_service_gate():
    """``bench.py --check-service`` is the serving-runtime gate: the
    windowed metric's staged sync program must be identical to the
    unwindowed metric's (psum-only parity), the clean MetricService soak
    must be bit-exact vs the single-process oracle (published windows,
    merged view, per-window sample counts — zero misrouted — and the drop
    count), and the seeded chaos soak (late burst + ingest stall +
    mid-window preempt + persistent sync drop) must complete within its
    budget with every publish degraded, ``degraded_computes`` and
    ``slab_dropped_samples`` matching their pins, and a snapshot-restored
    service replaying idempotently."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, _BENCH, "--check-service"],
        capture_output=True, text=True, timeout=280, env=env,
        cwd=os.path.dirname(_BENCH),
    )
    assert proc.returncode == 0, f"--check-service failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["ok"] is True and out["failures"] == []
    # parity: windows are a state axis — identical staged count, psum-only
    assert (
        out["parity"]["windowed"]["collective_calls"]
        == out["parity"]["unwindowed"]["collective_calls"]
    )
    assert out["parity"]["windowed"]["gather_calls"] == 0
    # clean soak: no faults, no drops, windows published in order
    assert all(v == 0 for v in out["clean"]["faults"].values())
    assert out["clean"]["dropped"] == 0
    assert out["clean"]["published"] == sorted(out["clean"]["published"])
    # chaos soak: survived the schedule inside the budget, with the pins
    assert out["chaos"]["preempted"] is True
    assert out["chaos"]["elapsed_s"] < out["chaos"]["budget_s"]
    assert out["chaos"]["faults"]["degraded_computes"] >= 1
    assert out["chaos"]["slab_dropped_samples"] > 0
    assert out["chaos"]["injected"]["late_burst"] >= 1
    assert out["chaos"]["injected"]["ingest_stall"] >= 1
    assert out["chaos"]["injected"]["preempt"] == 1


def test_bench_check_ingest_gate():
    """``bench.py --check-ingest`` is the ingest fast-path gate: the
    coalescing drain loop must publish a bit-identical record stream to the
    one-batch-per-drain twin over a bursty late/straggler mix (same windows,
    values, merged view, drop count — beyond-lateness drops included), the
    steady state must run on the prewarmed bucketed routing programs with
    ZERO further compiles, the throughput tier must show the coalesced loop
    >= 2x the uncoalesced twin with a batches-per-drain factor >= 2, and the
    chaos tier (mid-stream preempt + snapshot/restore + seq-guarded replay)
    must converge to the identical publication stream on both planes."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, _BENCH, "--check-ingest"],
        capture_output=True, text=True, timeout=280, env=env,
        cwd=os.path.dirname(_BENCH),
    )
    assert proc.returncode == 0, f"--check-ingest failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["ok"] is True and out["failures"] == []
    # parity: the coalescing plane really coalesced, really dropped the
    # beyond-lateness stragglers, and never recompiled in steady state
    assert out["parity"]["coalesced_batches"] > 0
    assert out["parity"]["dropped"] > 0
    assert out["parity"]["records"] > 0
    # throughput: the A/B's gate pins (>= 2x, factor >= 2) held
    assert out["throughput"]["coalesced_steps_per_s"] >= 2 * out["throughput"]["uncoalesced_steps_per_s"]
    assert out["throughput"]["coalesce_factor"] >= 2.0
    assert out["throughput"]["program_cache_misses"] == 5
    # chaos: both planes preempted and the coalescing side replayed
    assert out["chaos"]["preempted"] is True
    assert out["chaos"]["replayed_on"] >= 1
    assert out["chaos"]["records"] > 0


def test_bench_check_fleet_gate():
    """``bench.py --check-fleet`` is the sharded-serving gate: the merged
    fleet output must be bit-exact vs the single-process oracle at shard
    counts {1, 2, 8} (windows exactly once, in order, sample counts
    conserved), 8-shard ingest throughput must reach 4x the 1-shard loop
    over the simulated per-batch serving work, and the seeded chaos soak
    (stall one shard, kill another mid-stream) must recover via
    snapshot/restore + replay-log overlap replay with zero lost windows and
    no double-published merged window."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, _BENCH, "--check-fleet"],
        capture_output=True, text=True, timeout=280, env=env,
        cwd=os.path.dirname(_BENCH),
    )
    assert proc.returncode == 0, f"--check-fleet failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["ok"] is True and out["failures"] == []
    # exact: every shard count merged the same 7 oracle windows
    assert set(out["exact"]) == {"1", "2", "8"}
    assert len({row["merged_windows"] for row in out["exact"].values()}) == 1
    # more shards -> more per-shard publishes, same merged stream
    assert out["exact"]["8"]["shard_publishes"] > out["exact"]["1"]["shard_publishes"]
    # scaling: the near-linear headline, gated at >= 4x
    assert out["scaling"]["x"] >= out["scaling"]["min_x"] == 4.0
    # chaos: exactly one kill, recovered, idempotent replay exercised
    assert out["chaos"]["injected"]["preempt"] == 1
    assert out["chaos"]["recoveries"] >= 1
    assert out["chaos"]["replayed"] >= 1
    assert out["chaos"]["elapsed_s"] < out["chaos"]["budget_s"]


def test_bench_check_health_gate():
    """``bench.py --check-health`` is the pipeline-health gate: every window
    a clean wall-clock service soak publishes must carry a complete monotone
    core stage ledger with a distinct flow id, the self-meter's e2e
    p50/p95/p99 must sit inside the DDSketch certificate of the exact
    rank-selected latencies the same ledgers recorded, watermark lag must
    stay bounded on the clean stream and spike-then-recover under a seeded
    mid-stream ingest stall, and a 4-shard fleet's ``health_report()``
    latency table must equal the manual ``merge_meters`` fold of the
    per-shard sketches, with merge/bank stamps on the right ledgers and the
    new health families in the exposition."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, _BENCH, "--check-health"],
        capture_output=True, text=True, timeout=280, env=env,
        cwd=os.path.dirname(_BENCH),
    )
    assert proc.returncode == 0, f"--check-health failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["ok"] is True and out["failures"] == []
    # clean: windows published, lag bounded, the certificate quantiles rode
    assert out["clean"]["published"] >= 3
    assert 0 <= out["clean"]["max_lag_s"] < 5.0
    assert set(out["clean"]["quantiles"]) == {"0.5", "0.95", "0.99"}
    # stall: the gauge saw the backlog, then the stream drained
    assert out["stall"]["max_lag_s"] >= 0.4
    assert out["stall"]["final_lag_s"] < 0.8
    # fleet: the merge tier metered its own latency into the fold
    assert out["fleet"]["merged_windows"] == 8
    assert "merge" in out["fleet"]["latency_stages"]
    assert "e2e" in out["fleet"]["latency_stages"]
    assert out["fleet"]["degraded_shards"] == []


def test_bench_check_watermark_gate():
    """``bench.py --check-watermark`` is the rank-coherent streaming gate:
    a windowed metric under a WatermarkAgreement must stage the identical
    in-jit sync program as the unwindowed metric (the exchange is host-plane
    only — zero staged collectives, zero gathers), the coherence soak (one
    +30s clock-skewed rank + one late-burst rank on the virtual mesh) must
    publish NO window before every participating rank's watermark passes it
    with all merged values bit-exact vs the union-stream oracle (zero lost,
    zero double-published, zero drops), the stall tier (rate=1.0 stalled
    rank) must proceed past the agreement deadline with ``wm_stragglers > 0``
    and degraded publishes while no peer deadlocks, and sliding windows must
    be bit-exact vs independent per-slot oracles."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, _BENCH, "--check-watermark"],
        capture_output=True, text=True, timeout=280, env=env,
        cwd=os.path.dirname(_BENCH),
    )
    assert proc.returncode == 0, f"--check-watermark failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["ok"] is True and out["failures"] == []
    # parity: the agreement adds ZERO staged collectives — the exchange is
    # host-plane only, and it actually ran
    assert (
        out["parity"]["agreed"]["collective_calls"]
        == out["parity"]["unwindowed"]["collective_calls"]
    )
    assert out["parity"]["agreed"]["gather_calls"] == 0
    assert out["parity"]["agreed"]["wm_exchange_calls"] >= 1
    # coherent: the skew actually fired on every one of the skewed rank's
    # batches, and the late burst on its pinned call
    assert out["coherent"]["injected"]["clock_skew"] >= 12
    assert out["coherent"]["injected"]["late_burst"] == 1
    assert out["coherent"]["published"] == sorted(out["coherent"]["published"])
    # stall: exclusion proceeded (wm_stragglers), publishes degraded, fast
    assert out["stall"]["stragglers"] >= 1
    assert any(d for pubs in out["stall"]["published"].values() for _w, d in pubs)
    assert out["stall"]["elapsed_s"] < out["stall"]["budget_s"]
    # sliding: every event covers window_s/slide_s = 3 overlapping windows
    assert out["sliding"]["overlap"] == 3
    assert out["sliding"]["windows_published"] == 12


def test_bench_check_quantile_gate():
    """``bench.py --check-quantile`` is the quantile-sketch gate: every
    quantile estimate on the seeded Zipfian/Cauchy/lognormal streams must
    land within the alpha relative-error certificate (overflow-bucket hits
    flagged ``inf``, never silently certified), the (4,2)-mesh psum merge
    must be bit-exact vs the single-process sketch, Keyed(Quantile) and
    Windowed(Keyed(Quantile)) must stage the identical collective count as
    the unkeyed scalar metric (psum-only, zero gathers), and qsketch state
    bytes must stay constant over the stream while the capacity-buffer twin
    grows."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, _BENCH, "--check-quantile"],
        capture_output=True, text=True, timeout=280, env=env,
        cwd=os.path.dirname(_BENCH),
    )
    assert proc.returncode == 0, f"--check-quantile failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["ok"] is True and out["failures"] == []
    # certificate: all three seeded streams reported, every finite bound
    # equals alpha (the zipf tail quantiles exceed max_value and flag inf)
    assert set(out["certificate"]) == {"zipfian", "cauchy", "lognormal"}
    for rows in out["certificate"].values():
        for row in rows.values():
            assert row["bound"] == out["alpha"] or row["bound"] == float("inf")
    # the zipf p999 order stat is far beyond max_value: the certificate must
    # FLAG it rather than certify it
    assert out["certificate"]["zipfian"]["0.999"]["bound"] == float("inf")
    # merge: bit-exact with nothing dropped (the gate stream is NaN-free)
    assert out["merge"]["bit_exact"] is True
    assert out["merge"]["total"] == 8 * 512
    # parity: K slots and W x K windows never change the staged program
    assert (
        out["parity"]["unkeyed"]["collective_calls"]
        == out["parity"]["keyed"]["collective_calls"]
        == out["parity"]["windowed_keyed"]["collective_calls"]
    )
    assert all(tier["gather_calls"] == 0 for tier in out["parity"].values())
    # memory: flat sketch, growing buffer twin
    assert out["memory"]["qsketch_bytes"] > 0
    assert out["memory"]["buffer_twin_bytes"][-1] > out["memory"]["buffer_twin_bytes"][0]


def test_bench_check_retention_gate():
    """``bench.py --check-retention`` is the tiered-retention gate: every
    query against the banked roll-up ladder — at the native mixed
    resolution and every legal coarse grid — must be bit-exact vs a flat
    recompute over the raw published partials, for ALL FOUR mergeable state
    kinds (array, histogram sketch, quantile sketch, count-min) plus the
    nested Windowed(Keyed(...)) per-tenant plane; a grid finer than a
    rolled-up bucket must raise; resident bytes must stay flat as the
    stream grows 3x through a saturated ladder; and the OpenMetrics
    rendering must stay well-formed."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, _BENCH, "--check-retention"],
        capture_output=True, text=True, timeout=280, env=env,
        cwd=os.path.dirname(_BENCH),
    )
    assert proc.returncode == 0, f"--check-retention failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["ok"] is True and out["failures"] == []
    # all five vehicles ran the full sweep over the 24-window stream
    assert set(out["exact"]) == {"array", "hist_sketch", "qsketch", "cms", "keyed"}
    for vehicle in out["exact"].values():
        assert vehicle["published"] == out["windows"] == 24
        # 4 raw windows + 4 forty-second cells + 1 coarse bucket natively;
        # one point once the grid spans the whole retained range
        assert vehicle["points"]["native"] == 9
        assert vehicle["points"]["raw_tail"] == 4
        assert vehicle["points"]["240s"] == 1
    # the memory-flat headline: 3x the stream, the same resident bytes
    assert out["memory"]["resident_bytes_3x"] == out["memory"]["resident_bytes_1x"]
    assert out["memory"]["banked_3x"] == 3 * out["memory"]["banked_1x"]
    assert out["memory"]["evicted_3x"] > out["memory"]["evicted_1x"] > 0
    assert out["exposition"]["bytes"] > 0


def _run_trajectory(tmp_path, current, rounds):
    rounds_dir = tmp_path / "rounds"
    rounds_dir.mkdir(exist_ok=True)
    for n, parsed in rounds.items():
        (rounds_dir / f"BENCH_r{n:02d}.json").write_text(
            json.dumps({"n": n, "parsed": parsed})
        )
    current_file = tmp_path / "current.json"
    current_file.write_text(json.dumps(current))
    proc = subprocess.run(
        [
            sys.executable, _BENCH, "--check-trajectory",
            "--rounds-dir", str(rounds_dir),
            "--trajectory-current", str(current_file),
        ],
        capture_output=True, text=True, timeout=120,
    )
    return proc.returncode, json.loads(proc.stdout.strip().splitlines()[-1])


_TRAJECTORY_BASE = {
    "grouped_sync8_ms": 5.0,
    "ungrouped_sync8_ms": 6.0,
    "gather_coalesced_ms": 7.0,
    "gather_per_leaf_ms": 9.0,
    "collective_calls": 1,
    "sync_bytes": 520,
    "gather_collective_calls": 2,
    "gather_sync_bytes": 49176,
    "states_synced": 6,
}


def test_bench_check_trajectory_gate_passes_within_tolerance(tmp_path):
    """``bench.py --check-trajectory`` diffs the current numbers against the
    prior BENCH rounds: matching numbers (and a mild latency wobble under
    the pinned ratio) pass, and rounds missing a key don't constrain it."""
    current = dict(_TRAJECTORY_BASE, grouped_sync8_ms=5.6)  # within 2.5x
    rc, out = _run_trajectory(tmp_path, current, {6: _TRAJECTORY_BASE})
    assert rc == 0, out
    assert out["ok"] is True and out["failures"] == []
    assert out["checks"]["grouped_sync8_ms"]["status"] == "ok"
    assert out["checks"]["collective_calls"]["status"] == "ok"
    assert out["rounds_compared"] == [6]


def test_bench_check_trajectory_gate_fails_on_injected_regression(tmp_path):
    """The fail half of the pair: an injected phase-latency blowup AND a
    collective-count growth must each land in failures, exit non-zero."""
    bad = dict(_TRAJECTORY_BASE, grouped_sync8_ms=50.0, collective_calls=3)
    rc, out = _run_trajectory(tmp_path, bad, {5: _TRAJECTORY_BASE, 6: _TRAJECTORY_BASE})
    assert rc == 1
    assert out["ok"] is False
    assert any("grouped_sync8_ms" in f for f in out["failures"])
    assert any("collective_calls" in f for f in out["failures"])
    assert out["checks"]["grouped_sync8_ms"]["status"] == "regression"
    assert out["checks"]["collective_calls"]["status"] == "regression"
    # an improvement is never a failure — it reports as such for re-pinning
    improved = dict(_TRAJECTORY_BASE, collective_calls=0)
    rc, out = _run_trajectory(tmp_path, improved, {6: _TRAJECTORY_BASE})
    assert rc == 0
    assert out["checks"]["collective_calls"]["status"] == "improved"


def test_bench_check_trajectory_gates_rate_keys_as_collapse_detectors(tmp_path):
    """Throughput keys (``*_steps_per_s``) gate as collapse detectors: a
    value below best-prior / 3 fails, ordinary wobble (and improvement)
    passes, and fleet_lost_windows binds at zero like the fault keys."""
    base = dict(_TRAJECTORY_BASE, fleet_ingest_steps_per_s=36.0,
                fleet_lost_windows=0)
    wobbly = dict(base, fleet_ingest_steps_per_s=20.0)  # above 36/3
    rc, out = _run_trajectory(tmp_path, wobbly, {11: base})
    assert rc == 0, out
    assert out["checks"]["fleet_ingest_steps_per_s"]["status"] == "ok"

    collapsed = dict(base, fleet_ingest_steps_per_s=5.0)  # below 36/3
    rc, out = _run_trajectory(tmp_path, collapsed, {11: base})
    assert rc == 1
    assert any("fleet_ingest_steps_per_s" in f for f in out["failures"])
    assert out["checks"]["fleet_ingest_steps_per_s"]["status"] == "regression"

    lossy = dict(base, fleet_lost_windows=1)
    rc, out = _run_trajectory(tmp_path, lossy, {11: base})
    assert rc == 1
    assert any("fleet_lost_windows" in f for f in out["failures"])


def test_bench_check_trajectory_pins_fault_counters_at_zero(tmp_path):
    """Fault counters bind at EXACTLY zero whenever the current line carries
    them — no prior round needed (zero is the contract, not a baseline) —
    and a nonzero value fails even if a prior round also recorded one."""
    clean = dict(_TRAJECTORY_BASE, sync_retries=0, sync_deadline_exceeded=0,
                 degraded_computes=0, quarantined_updates=0)
    rc, out = _run_trajectory(tmp_path, clean, {6: _TRAJECTORY_BASE})
    assert rc == 0, out
    assert out["checks"]["sync_retries"] == {"current": 0, "baseline": 0, "kind": "fault", "status": "ok"}

    dirty = dict(clean, degraded_computes=2)
    rc, out = _run_trajectory(tmp_path, dirty, {6: clean})
    assert rc == 1
    assert any("degraded_computes" in f for f in out["failures"])
    assert out["checks"]["degraded_computes"]["status"] == "regression"

    # wm_stragglers binds the same way: a clean bench line that excluded a
    # rank from the agreed watermark is a clock regression
    wm_dirty = dict(clean, wm_stragglers=1)
    rc, out = _run_trajectory(tmp_path, wm_dirty, {6: clean})
    assert rc == 1
    assert any("wm_stragglers" in f for f in out["failures"])

    # rounds predating the keys: current lines without them aren't constrained
    rc, out = _run_trajectory(tmp_path, _TRAJECTORY_BASE, {6: _TRAJECTORY_BASE})
    assert rc == 0
    assert out["checks"]["sync_retries"]["status"] == "missing"
