"""COCO mAP engine vs an independent numpy implementation + hand cases.

The numpy oracle below follows the pycocotools algorithm structure
(per-image/per-class greedy matching loops with crowd/area-ignore handling,
maxDets slicing, 101-point interpolation) and is deliberately written
loop-wise — a second, independent derivation of the same semantics, since
pycocotools itself is not in the image. Because oracle and kernel share an
author, the hand-fixture tests below pin expected values derived on paper
(crowd, area-range, and maxDets cases each have a hand-computed constant).
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.functional.detection.iou import box_iou
from metrics_tpu.functional.detection.map import (
    COCO_AREA_RANGES,
    COCO_IOU_THRESHOLDS,
    COCO_MAX_DETS,
    coco_map_padded,
)


def _np_area(boxes):
    return np.clip(boxes[:, 2] - boxes[:, 0], 0, None) * np.clip(boxes[:, 3] - boxes[:, 1], 0, None)


def _np_iou(a, b, crowd=None):
    """(N, M) IoU; columns flagged in ``crowd`` use intersection/det-area."""
    inter_lt = np.maximum(a[:, None, :2], b[None, :, :2])
    inter_rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.clip(inter_rb - inter_lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_a = _np_area(a)
    area_b = _np_area(b)
    union = area_a[:, None] + area_b[None, :] - inter
    iou = np.where(union > 0, inter / np.where(union > 0, union, 1), 0.0)
    if crowd is not None and crowd.any():
        da = np.where(area_a > 0, area_a, 1.0)[:, None]
        iou_cr = np.where(area_a[:, None] > 0, inter / da, 0.0)
        iou = np.where(crowd[None, :], iou_cr, iou)
    return iou


def _with_crowd(images):
    """Normalize 5-tuples (no crowd) to 6-tuples."""
    out = []
    for im in images:
        if len(im) == 5:
            im = (*im, np.zeros(len(im[4]), dtype=bool))
        out.append(im)
    return out


def _np_coco_map(images, num_classes, thresholds=COCO_IOU_THRESHOLDS,
                 max_dets=COCO_MAX_DETS, area_ranges=COCO_AREA_RANGES):
    """Full pycocotools-semantics oracle over
    ``(det_boxes, det_scores, det_labels, gt_boxes, gt_labels[, gt_crowd])``."""
    images = _with_crowd(images)
    n_area = len(area_ranges)
    n_thr = len(thresholds)
    k_max = max(max_dets)
    aps = np.full((n_area, n_thr, num_classes), np.nan)
    recs = {k: np.full((n_area, n_thr, num_classes), np.nan) for k in max_dets}

    for ai, (_, lo, hi) in enumerate(area_ranges):
        for ci in range(num_classes):
            n_gt = 0
            per_img = []  # (scores, tp(T, nd), ig(T, nd)) in per-image rank order
            for det_boxes, det_scores, det_labels, gt_boxes, gt_labels, gt_crowd in images:
                d_idx = np.where(det_labels == ci)[0]
                d_idx = d_idx[np.argsort(-det_scores[d_idx], kind="stable")][:k_max]
                g_idx = np.where(gt_labels == ci)[0]
                g_crowd = gt_crowd[g_idx].astype(bool)
                g_area = _np_area(gt_boxes[g_idx])
                g_ig = g_crowd | (g_area < lo) | (g_area > hi)
                # pycocotools sorts gts unignored-first before matching
                g_order = np.argsort(g_ig, kind="stable")
                g_idx, g_ig, g_crowd = g_idx[g_order], g_ig[g_order], g_crowd[g_order]
                n_gt += int((~g_ig).sum())

                ious = (_np_iou(det_boxes[d_idx], gt_boxes[g_idx], g_crowd)
                        if len(d_idx) and len(g_idx) else np.zeros((len(d_idx), len(g_idx))))
                d_area = _np_area(det_boxes[d_idx])
                d_out = (d_area < lo) | (d_area > hi)
                tp = np.zeros((n_thr, len(d_idx)), bool)
                ig = np.zeros((n_thr, len(d_idx)), bool)
                for ti, thr in enumerate(thresholds):
                    used = np.zeros(len(g_idx), bool)
                    for r in range(len(d_idx)):
                        best, best_iou = -1, float(thr)
                        for c in range(len(g_idx)):
                            if used[c] and not g_crowd[c]:
                                continue
                            # unignored match found and rest are ignored: stop
                            if best >= 0 and not g_ig[best] and g_ig[c]:
                                break
                            if ious[r, c] < best_iou:
                                continue
                            best, best_iou = c, ious[r, c]
                        if best >= 0:
                            used[best] = True
                            (ig if g_ig[best] else tp)[ti, r] = True
                        elif d_out[r]:
                            ig[ti, r] = True
                per_img.append((det_scores[d_idx], tp, ig))

            if n_gt == 0:
                continue
            for k in max_dets:
                for ti in range(n_thr):
                    total_tp = sum(tp[ti, :k].sum() for _, tp, _ in per_img)
                    recs[k][ai, ti, ci] = total_tp / n_gt
            # global ranking for AP (ignored dets contribute neither way)
            scores = np.concatenate([s for s, _, _ in per_img]) if per_img else np.zeros(0)
            order = np.argsort(-scores, kind="stable")
            for ti in range(n_thr):
                tp_flat = np.concatenate([tp[ti] for _, tp, _ in per_img])[order]
                ig_flat = np.concatenate([ig[ti] for _, _, ig in per_img])[order]
                keep = ~ig_flat
                tps = np.cumsum(tp_flat[keep])
                fps = np.cumsum(~tp_flat[keep])
                recall = tps / n_gt if len(tps) else np.zeros(0)
                precision = tps / np.maximum(tps + fps, 1e-30) if len(tps) else np.zeros(0)
                for i in range(len(precision) - 1, 0, -1):
                    precision[i - 1] = max(precision[i - 1], precision[i])
                q = np.zeros(101)
                inds = np.searchsorted(recall, np.linspace(0, 1, 101), side="left")
                for kk, pi in enumerate(inds):
                    if pi < len(precision):
                        q[kk] = precision[pi]
                aps[ai, ti, ci] = q.mean()

    k_largest = max(max_dets)
    out = {
        "map": np.nanmean(aps[0]),
        "map_50": np.nanmean(aps[0, thresholds.index(0.5)]),
        "map_75": np.nanmean(aps[0, thresholds.index(0.75)]),
        "map_per_class": np.nanmean(aps[0], axis=0),
        f"mar_{k_largest}_per_class": np.nanmean(recs[k_largest][0], axis=0),
    }
    for k in max_dets:
        out[f"mar_{k}"] = np.nanmean(recs[k][0])
    for ai, (name, _, _) in enumerate(area_ranges):
        if name == "all":
            continue
        out[f"map_{name}"] = np.nanmean(aps[ai])
        out[f"mar_{name}"] = np.nanmean(recs[k_largest][ai])
    return out


_FULL_KEYS = (
    "map", "map_50", "map_75", "map_small", "map_medium", "map_large",
    "mar_1", "mar_10", "mar_100", "mar_small", "mar_medium", "mar_large",
)


def _pad_images(images, num_classes, d_cap, g_cap):
    images = _with_crowd(images)
    I = len(images)
    db = np.zeros((I, d_cap, 4), np.float32); ds = np.zeros((I, d_cap), np.float32)
    dl = np.zeros((I, d_cap), np.int32); dv = np.zeros((I, d_cap), bool)
    gb = np.zeros((I, g_cap, 4), np.float32); gl = np.zeros((I, g_cap), np.int32)
    gv = np.zeros((I, g_cap), bool); gc = np.zeros((I, g_cap), bool)
    for i, (dbx, dsc, dlb, gbx, glb, gcr) in enumerate(images):
        nd, ng = len(dsc), len(glb)
        db[i, :nd] = dbx; ds[i, :nd] = dsc; dl[i, :nd] = dlb; dv[i, :nd] = True
        gb[i, :ng] = gbx; gl[i, :ng] = glb; gv[i, :ng] = True; gc[i, :ng] = gcr
    return (jnp.asarray(db), jnp.asarray(ds), jnp.asarray(dl), jnp.asarray(dv),
            jnp.asarray(gb), jnp.asarray(gl), jnp.asarray(gv), jnp.asarray(gc))


def _run(images, num_classes, d_cap=12, g_cap=10):
    args = _pad_images(images, num_classes, d_cap, g_cap)
    out = coco_map_padded(*args[:7], num_classes=num_classes, gt_crowd=args[7])
    return {k: np.asarray(v) for k, v in out.items()}


def test_perfect_predictions():
    box = np.array([[0, 0, 10, 10], [20, 20, 30, 30]], np.float32)
    images = [(box, np.array([0.9, 0.8], np.float32), np.array([0, 1]), box, np.array([0, 1]))]
    out = _run(images, num_classes=2)
    assert out["map"] == pytest.approx(1.0)
    assert out["map_50"] == pytest.approx(1.0)
    assert out["mar_100"] == pytest.approx(1.0)
    assert out["mar_1"] == pytest.approx(1.0)  # one det per image per class
    # both boxes are "small" (area 100): the small slice carries everything
    assert out["map_small"] == pytest.approx(1.0)
    assert np.isnan(out["map_medium"]) and np.isnan(out["map_large"])


def test_iou_threshold_cutoff():
    """A detection overlapping its GT at IoU=0.62 counts only for thresholds
    <= 0.6: AP 1.0 at {0.5, 0.55, 0.6}, 0 above -> map = 0.3. (0.62 keeps a
    safe f32 margin from the 0.60/0.65 threshold boundaries — exact-boundary
    IoUs are float-sensitive on every backend, as in pycocotools.)"""
    gt = np.array([[0, 0, 10, 10]], np.float32)
    det = np.array([[0, 0, 10, 6.2]], np.float32)  # IoU = 0.62
    images = [(det, np.array([0.9], np.float32), np.array([0]), gt, np.array([0]))]
    out = _run(images, num_classes=1)
    assert out["map"] == pytest.approx(0.3, abs=1e-6)
    assert out["map_50"] == pytest.approx(1.0)
    assert out["map_75"] == pytest.approx(0.0)


def test_high_scoring_false_positive_halves_ap():
    """FP ranked above the TP: interpolated precision is 0.5 at every recall
    level -> AP 0.5."""
    gt = np.array([[0, 0, 10, 10]], np.float32)
    det = np.array([[50, 50, 60, 60], [0, 0, 10, 10]], np.float32)
    images = [(det, np.array([0.9, 0.8], np.float32), np.array([0, 0]), gt, np.array([0]))]
    out = _run(images, num_classes=1)
    assert out["map"] == pytest.approx(0.5, abs=1e-6)


def test_missed_gt_caps_recall():
    gt = np.array([[0, 0, 10, 10], [20, 20, 30, 30]], np.float32)
    det = np.array([[0, 0, 10, 10]], np.float32)
    images = [(det, np.array([0.9], np.float32), np.array([0]), gt, np.array([0, 0]))]
    out = _run(images, num_classes=1)
    assert out["mar_100"] == pytest.approx(0.5)
    # precision 1 up to recall 0.5, then nothing: 51 of 101 points at 1.0
    assert out["map"] == pytest.approx(51 / 101, abs=1e-6)


def test_double_detection_is_fp():
    """Second detection of an already-matched GT is a false positive."""
    gt = np.array([[0, 0, 10, 10]], np.float32)
    det = np.array([[0, 0, 10, 10], [0, 0, 10, 10]], np.float32)
    images = [(det, np.array([0.9, 0.8], np.float32), np.array([0, 0]), gt, np.array([0]))]
    out = _run(images, num_classes=1)
    assert out["map"] == pytest.approx(1.0)  # TP first; trailing FP doesn't dent the envelope


def test_crowd_gt_absorbs_would_be_fp():
    """Hand case: a high-scoring detection inside a crowd region is IGNORED
    (neither TP nor FP), so the real TP keeps AP at 1.0; without crowd
    semantics the leading FP would halve it to 0.5. The crowd gt does not
    count toward n_gt (mar over the one real gt = 1.0)."""
    gt = np.array([[0, 0, 10, 10], [20, 20, 60, 60]], np.float32)
    crowd = np.array([False, True])
    det = np.array([[25, 25, 35, 35],   # fully inside the crowd box
                    [0, 0, 10, 10]], np.float32)
    images = [(det, np.array([0.95, 0.9], np.float32), np.array([0, 0]),
               gt, np.array([0, 0]), crowd)]
    out = _run(images, num_classes=1)
    assert out["map"] == pytest.approx(1.0)
    assert out["mar_100"] == pytest.approx(1.0)
    # the same detections WITHOUT the crowd flag: the region box becomes a
    # real gt (n_gt=2), the 0.95 det is a leading FP (IoU 100/1600 = 0.0625),
    # recall caps at 0.5 with precision 1/2 -> AP = 51 * 0.5 / 101
    images_nc = [(det, np.array([0.95, 0.9], np.float32), np.array([0, 0]),
                  gt, np.array([0, 0]))]
    out_nc = _run(images_nc, num_classes=1)
    assert out_nc["map"] == pytest.approx(51 * 0.5 / 101, abs=1e-6)


def test_crowd_matches_many_detections():
    """Hand case: two detections inside one crowd gt are BOTH ignored (a
    crowd is never consumed); the class has no real gt -> all-nan map."""
    gt = np.array([[0, 0, 100, 100]], np.float32)
    det = np.array([[10, 10, 20, 20], [30, 30, 40, 40]], np.float32)
    images = [(det, np.array([0.9, 0.8], np.float32), np.array([0, 0]),
               gt, np.array([0]), np.array([True]))]
    out = _run(images, num_classes=1)
    assert np.isnan(out["map"])  # no un-ignored ground truth anywhere


def test_area_ranges_split():
    """Hand case: one small (10x10=100) and one large (200x200=40000) gt,
    each matched exactly. Every per-size slice that has gts scores 1.0; the
    out-of-range pair is ignore-flagged away, never an FP."""
    gt = np.array([[0, 0, 10, 10], [300, 300, 500, 500]], np.float32)
    det = gt.copy()
    images = [(det, np.array([0.9, 0.8], np.float32), np.array([0, 0]),
               gt, np.array([0, 0]))]
    out = _run(images, num_classes=1)
    assert out["map"] == pytest.approx(1.0)
    assert out["map_small"] == pytest.approx(1.0)
    assert out["map_large"] == pytest.approx(1.0)
    assert np.isnan(out["map_medium"])  # no gt with area in [32^2, 96^2]
    assert out["mar_small"] == pytest.approx(1.0)
    assert out["mar_large"] == pytest.approx(1.0)
    assert np.isnan(out["mar_medium"])


def test_max_dets_recall_caps():
    """Hand case: top-1 detection is an FP, the TP ranks second -> mar_1 is
    0 (only the FP survives the cap) while mar_10/mar_100 recover the gt."""
    gt = np.array([[0, 0, 10, 10]], np.float32)
    det = np.array([[50, 50, 60, 60], [0, 0, 10, 10]], np.float32)
    images = [(det, np.array([0.9, 0.8], np.float32), np.array([0, 0]),
               gt, np.array([0]))]
    out = _run(images, num_classes=1)
    assert out["mar_1"] == pytest.approx(0.0)
    assert out["mar_10"] == pytest.approx(1.0)
    assert out["mar_100"] == pytest.approx(1.0)


def test_result_keys_full_coco_surface():
    gt = np.array([[0, 0, 10, 10]], np.float32)
    images = [(gt, np.array([0.9], np.float32), np.array([0]), gt, np.array([0]))]
    out = _run(images, num_classes=1)
    for key in _FULL_KEYS:
        assert key in out, key
    assert out["map_per_class"].shape == (1,)
    assert out["mar_100_per_class"].shape == (1,)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_random_vs_numpy_oracle(seed):
    rng = np.random.RandomState(seed)
    num_classes, n_images = 3, 6
    images = []
    for _ in range(n_images):
        ng = rng.randint(1, 6)
        gt = np.sort(rng.rand(ng, 2, 2) * 50, axis=1).reshape(ng, 4).astype(np.float32)
        gt[:, 2:] += 2.0  # non-degenerate
        glab = rng.randint(0, num_classes, ng)
        crowd = rng.rand(ng) < 0.2
        nd = rng.randint(0, 9)
        # half jittered copies of gts, half random
        det, dlab = [], []
        for j in range(nd):
            if j < ng and rng.rand() < 0.6:
                det.append(gt[j] + rng.randn(4) * rng.choice([0.5, 3.0]))
                dlab.append(glab[j] if rng.rand() < 0.8 else rng.randint(0, num_classes))
            else:
                b = np.sort(rng.rand(2, 2) * 50, axis=0).reshape(4); b[2:] += 2
                det.append(b); dlab.append(rng.randint(0, num_classes))
        det = np.asarray(det, np.float32).reshape(nd, 4)
        scores = rng.rand(nd).astype(np.float32)  # distinct w.p. 1
        images.append((det, scores, np.asarray(dlab, np.int64), gt, glab, crowd))
    got = _run(images, num_classes)
    want = _np_coco_map(images, num_classes)
    for key in _FULL_KEYS:
        np.testing.assert_allclose(got[key], want[key], atol=1e-5, err_msg=key, equal_nan=True)
    for key in ("map_per_class", "mar_100_per_class"):
        np.testing.assert_allclose(got[key], want[key], atol=1e-5, equal_nan=True, err_msg=key)


def test_iou_kernels():
    a = np.array([[0, 0, 2, 2], [1, 1, 4, 4]], np.float32)
    b = np.array([[1, 1, 3, 3], [5, 5, 6, 6]], np.float32)
    np.testing.assert_allclose(np.asarray(box_iou(jnp.asarray(a), jnp.asarray(b))),
                               _np_iou(a, b), atol=1e-6)
    with pytest.raises(ValueError, match="xyxy"):
        box_iou(jnp.zeros((3, 3)), jnp.zeros((2, 4)))


def test_map_jit():
    import jax

    gt = np.array([[0, 0, 10, 10]], np.float32)
    det = np.array([[0, 0, 10, 10]], np.float32)
    images = [(det, np.array([0.9], np.float32), np.array([0]), gt, np.array([0]))]
    args = _pad_images(images, 1, 4, 4)
    out = jax.jit(lambda *a: coco_map_padded(*a[:7], num_classes=1, gt_crowd=a[7]))(*args)
    assert float(out["map"]) == pytest.approx(1.0)
