"""COCO mAP engine vs an independent numpy implementation + hand cases.

The numpy oracle below follows the pycocotools algorithm structure
(per-image/per-class greedy matching loops, 101-point interpolation) and is
deliberately written loop-wise — a second, independent derivation of the
same semantics, since pycocotools itself is not in the image.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.functional.detection.iou import box_iou
from metrics_tpu.functional.detection.map import COCO_IOU_THRESHOLDS, coco_map_padded


def _np_iou(a, b):
    inter_lt = np.maximum(a[:, None, :2], b[None, :, :2])
    inter_rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.clip(inter_rb - inter_lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_a = np.clip(a[:, 2] - a[:, 0], 0, None) * np.clip(a[:, 3] - a[:, 1], 0, None)
    area_b = np.clip(b[:, 2] - b[:, 0], 0, None) * np.clip(b[:, 3] - b[:, 1], 0, None)
    union = area_a[:, None] + area_b[None, :] - inter
    return np.where(union > 0, inter / np.where(union > 0, union, 1), 0.0)


def _np_coco_map(images, num_classes, thresholds=COCO_IOU_THRESHOLDS):
    """images: list of (det_boxes, det_scores, det_labels, gt_boxes, gt_labels)."""
    aps = np.full((len(thresholds), num_classes), np.nan)
    recalls = np.full((len(thresholds), num_classes), np.nan)
    for ci in range(num_classes):
        n_gt = sum(int((g_lab == ci).sum()) for *_, g_lab in
                   [(im[3], im[4]) for im in images])
        n_gt = sum(int((im[4] == ci).sum()) for im in images)
        for ti, thr in enumerate(thresholds):
            records = []  # (score, is_tp)
            for det_boxes, det_scores, det_labels, gt_boxes, gt_labels in images:
                d_idx = np.where(det_labels == ci)[0]
                g_idx = np.where(gt_labels == ci)[0]
                d_idx = d_idx[np.argsort(-det_scores[d_idx], kind="stable")]
                ious = _np_iou(det_boxes[d_idx], gt_boxes[g_idx]) if len(d_idx) and len(g_idx) \
                    else np.zeros((len(d_idx), len(g_idx)))
                used = np.zeros(len(g_idx), dtype=bool)
                for row, d in enumerate(d_idx):
                    best, best_iou = -1, float(thr)
                    for col in range(len(g_idx)):
                        if used[col] or ious[row, col] < best_iou:
                            continue
                        best, best_iou = col, ious[row, col]
                    if best >= 0:
                        used[best] = True
                        records.append((det_scores[d], True))
                    else:
                        records.append((det_scores[d], False))
            if n_gt == 0:
                continue
            records.sort(key=lambda r: -r[0])
            tp = np.cumsum([r[1] for r in records]) if records else np.zeros(0)
            fp = np.cumsum([not r[1] for r in records]) if records else np.zeros(0)
            recall = tp / n_gt if len(tp) else np.zeros(0)
            precision = tp / np.maximum(tp + fp, 1e-30) if len(tp) else np.zeros(0)
            # envelope + 101-point sampling (pycocotools accumulate())
            for i in range(len(precision) - 1, 0, -1):
                precision[i - 1] = max(precision[i - 1], precision[i])
            q = np.zeros(101)
            inds = np.searchsorted(recall, np.linspace(0, 1, 101), side="left")
            for k, pi in enumerate(inds):
                if pi < len(precision):
                    q[k] = precision[pi]
            aps[ti, ci] = q.mean()
            recalls[ti, ci] = recall[-1] if len(recall) else 0.0
    return {
        "map": np.nanmean(aps),
        "map_50": np.nanmean(aps[thresholds.index(0.5)]),
        "map_75": np.nanmean(aps[thresholds.index(0.75)]),
        "mar": np.nanmean(recalls),
        "map_per_class": np.nanmean(aps, axis=0),
    }


def _pad_images(images, num_classes, d_cap, g_cap):
    I = len(images)
    db = np.zeros((I, d_cap, 4), np.float32); ds = np.zeros((I, d_cap), np.float32)
    dl = np.zeros((I, d_cap), np.int32); dv = np.zeros((I, d_cap), bool)
    gb = np.zeros((I, g_cap, 4), np.float32); gl = np.zeros((I, g_cap), np.int32)
    gv = np.zeros((I, g_cap), bool)
    for i, (dbx, dsc, dlb, gbx, glb) in enumerate(images):
        nd, ng = len(dsc), len(glb)
        db[i, :nd] = dbx; ds[i, :nd] = dsc; dl[i, :nd] = dlb; dv[i, :nd] = True
        gb[i, :ng] = gbx; gl[i, :ng] = glb; gv[i, :ng] = True
    return (jnp.asarray(db), jnp.asarray(ds), jnp.asarray(dl), jnp.asarray(dv),
            jnp.asarray(gb), jnp.asarray(gl), jnp.asarray(gv))


def _run(images, num_classes, d_cap=12, g_cap=10):
    args = _pad_images(images, num_classes, d_cap, g_cap)
    return {k: np.asarray(v) for k, v in
            coco_map_padded(*args, num_classes=num_classes).items()}


def test_perfect_predictions():
    box = np.array([[0, 0, 10, 10], [20, 20, 30, 30]], np.float32)
    images = [(box, np.array([0.9, 0.8], np.float32), np.array([0, 1]), box, np.array([0, 1]))]
    out = _run(images, num_classes=2)
    assert out["map"] == pytest.approx(1.0)
    assert out["map_50"] == pytest.approx(1.0)
    assert out["mar"] == pytest.approx(1.0)


def test_iou_threshold_cutoff():
    """A detection overlapping its GT at IoU=0.62 counts only for thresholds
    <= 0.6: AP 1.0 at {0.5, 0.55, 0.6}, 0 above -> map = 0.3. (0.62 keeps a
    safe f32 margin from the 0.60/0.65 threshold boundaries — exact-boundary
    IoUs are float-sensitive on every backend, as in pycocotools.)"""
    gt = np.array([[0, 0, 10, 10]], np.float32)
    det = np.array([[0, 0, 10, 6.2]], np.float32)  # IoU = 0.62
    images = [(det, np.array([0.9], np.float32), np.array([0]), gt, np.array([0]))]
    out = _run(images, num_classes=1)
    assert out["map"] == pytest.approx(0.3, abs=1e-6)
    assert out["map_50"] == pytest.approx(1.0)
    assert out["map_75"] == pytest.approx(0.0)


def test_high_scoring_false_positive_halves_ap():
    """FP ranked above the TP: interpolated precision is 0.5 at every recall
    level -> AP 0.5."""
    gt = np.array([[0, 0, 10, 10]], np.float32)
    det = np.array([[50, 50, 60, 60], [0, 0, 10, 10]], np.float32)
    images = [(det, np.array([0.9, 0.8], np.float32), np.array([0, 0]), gt, np.array([0]))]
    out = _run(images, num_classes=1)
    assert out["map"] == pytest.approx(0.5, abs=1e-6)


def test_missed_gt_caps_recall():
    gt = np.array([[0, 0, 10, 10], [20, 20, 30, 30]], np.float32)
    det = np.array([[0, 0, 10, 10]], np.float32)
    images = [(det, np.array([0.9], np.float32), np.array([0]), gt, np.array([0, 0]))]
    out = _run(images, num_classes=1)
    assert out["mar"] == pytest.approx(0.5)
    # precision 1 up to recall 0.5, then nothing: 51 of 101 points at 1.0
    assert out["map"] == pytest.approx(51 / 101, abs=1e-6)


def test_double_detection_is_fp():
    """Second detection of an already-matched GT is a false positive."""
    gt = np.array([[0, 0, 10, 10]], np.float32)
    det = np.array([[0, 0, 10, 10], [0, 0, 10, 10]], np.float32)
    images = [(det, np.array([0.9, 0.8], np.float32), np.array([0, 0]), gt, np.array([0]))]
    out = _run(images, num_classes=1)
    assert out["map"] == pytest.approx(1.0)  # TP first; trailing FP doesn't dent the envelope


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_random_vs_numpy_oracle(seed):
    rng = np.random.RandomState(seed)
    num_classes, n_images = 3, 6
    images = []
    for _ in range(n_images):
        ng = rng.randint(1, 6)
        gt = np.sort(rng.rand(ng, 2, 2) * 50, axis=1).reshape(ng, 4).astype(np.float32)
        gt[:, 2:] += 2.0  # non-degenerate
        glab = rng.randint(0, num_classes, ng)
        nd = rng.randint(0, 9)
        # half jittered copies of gts, half random
        det, dlab = [], []
        for j in range(nd):
            if j < ng and rng.rand() < 0.6:
                det.append(gt[j] + rng.randn(4) * rng.choice([0.5, 3.0]))
                dlab.append(glab[j] if rng.rand() < 0.8 else rng.randint(0, num_classes))
            else:
                b = np.sort(rng.rand(2, 2) * 50, axis=0).reshape(4); b[2:] += 2
                det.append(b); dlab.append(rng.randint(0, num_classes))
        det = np.asarray(det, np.float32).reshape(nd, 4)
        scores = rng.rand(nd).astype(np.float32)  # distinct w.p. 1
        images.append((det, scores, np.asarray(dlab, np.int64), gt, glab))
    got = _run(images, num_classes)
    want = _np_coco_map(images, num_classes)
    for key in ("map", "map_50", "map_75", "mar"):
        np.testing.assert_allclose(got[key], want[key], atol=1e-5, err_msg=key)
    np.testing.assert_allclose(got["map_per_class"], want["map_per_class"],
                               atol=1e-5, equal_nan=True)


def test_iou_kernels():
    a = np.array([[0, 0, 2, 2], [1, 1, 4, 4]], np.float32)
    b = np.array([[1, 1, 3, 3], [5, 5, 6, 6]], np.float32)
    np.testing.assert_allclose(np.asarray(box_iou(jnp.asarray(a), jnp.asarray(b))),
                               _np_iou(a, b), atol=1e-6)
    with pytest.raises(ValueError, match="xyxy"):
        box_iou(jnp.zeros((3, 3)), jnp.zeros((2, 4)))


def test_map_jit():
    import jax

    gt = np.array([[0, 0, 10, 10]], np.float32)
    det = np.array([[0, 0, 10, 10]], np.float32)
    images = [(det, np.array([0.9], np.float32), np.array([0]), gt, np.array([0]))]
    args = _pad_images(images, 1, 4, 4)
    out = jax.jit(lambda *a: coco_map_padded(*a, num_classes=1))(*args)
    assert float(out["map"]) == pytest.approx(1.0)
