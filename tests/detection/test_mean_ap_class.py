"""Stateful MeanAveragePrecision: streaming, caps, pickling, edge cases."""
import pickle

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import MeanAveragePrecision
from tests.detection.test_mean_ap import _np_coco_map


def _random_images(rng, n_images, num_classes, max_gt=5, max_det=8):
    images = []
    for _ in range(n_images):
        ng = rng.randint(1, max_gt + 1)
        gt = np.sort(rng.rand(ng, 2, 2) * 50, axis=1).reshape(ng, 4).astype(np.float32)
        gt[:, 2:] += 2.0
        glab = rng.randint(0, num_classes, ng)
        nd = rng.randint(0, max_det + 1)
        det, dlab = [], []
        for j in range(nd):
            if j < ng and rng.rand() < 0.6:
                det.append(gt[j] + rng.randn(4) * rng.choice([0.5, 3.0]))
                dlab.append(glab[j])
            else:
                b = np.sort(rng.rand(2, 2) * 50, axis=0).reshape(4)
                b[2:] += 2
                det.append(b)
                dlab.append(rng.randint(0, num_classes))
        det = np.asarray(det, np.float32).reshape(nd, 4)
        images.append((det, rng.rand(nd).astype(np.float32), np.asarray(dlab), gt, glab))
    return images


def _feed(metric, images):
    preds = [{"boxes": jnp.asarray(d), "scores": jnp.asarray(s), "labels": jnp.asarray(l)}
             for d, s, l, _, _ in images]
    target = [{"boxes": jnp.asarray(g), "labels": jnp.asarray(gl)}
              for _, _, _, g, gl in images]
    metric.update(preds, target)


def test_streaming_matches_oracle():
    rng = np.random.RandomState(7)
    images = _random_images(rng, 8, num_classes=3)
    m = MeanAveragePrecision(num_classes=3, max_detections=10, max_gt=6, class_metrics=True)
    _feed(m, images[:3])  # multiple update calls stream per-image stacks
    _feed(m, images[3:])
    got = {k: np.asarray(v) for k, v in m.compute().items()}
    want = _np_coco_map(images, 3)
    for key in ("map", "map_50", "map_75", "mar_1", "mar_10", "mar_100",
                "map_small", "map_medium", "map_large", "mar_small", "mar_medium", "mar_large"):
        np.testing.assert_allclose(got[key], want[key], atol=1e-5, err_msg=key, equal_nan=True)
    np.testing.assert_allclose(got["map_per_class"], want["map_per_class"],
                               atol=1e-5, equal_nan=True)


def test_crowd_through_update_dicts():
    """`iscrowd` in a target dict flows into the engine: a detection inside
    the crowd region is ignored instead of counting as a leading FP."""
    gt = np.array([[0, 0, 10, 10], [20, 20, 60, 60]], np.float32)
    det = np.array([[25, 25, 35, 35], [0, 0, 10, 10]], np.float32)
    m = MeanAveragePrecision(num_classes=1, max_detections=4, max_gt=4)
    m.update(
        [{"boxes": jnp.asarray(det), "scores": jnp.asarray([0.95, 0.9]),
          "labels": jnp.asarray([0, 0])}],
        [{"boxes": jnp.asarray(gt), "labels": jnp.asarray([0, 0]),
          "iscrowd": jnp.asarray([False, True])}],
    )
    out = m.compute()
    assert float(out["map"]) == pytest.approx(1.0)
    assert float(out["mar_100"]) == pytest.approx(1.0)


def test_max_detection_thresholds_knob():
    """Custom maxDets thresholds produce matching mar_<k> keys."""
    gt = np.array([[0, 0, 10, 10]], np.float32)
    det = np.array([[50, 50, 60, 60], [0, 0, 10, 10]], np.float32)
    m = MeanAveragePrecision(num_classes=1, max_detections=4, max_gt=4,
                             max_detection_thresholds=(1, 2))
    m.update(
        [{"boxes": jnp.asarray(det), "scores": jnp.asarray([0.9, 0.8]),
          "labels": jnp.asarray([0, 0])}],
        [{"boxes": jnp.asarray(gt), "labels": jnp.asarray([0])}],
    )
    out = m.compute()
    assert float(out["mar_1"]) == pytest.approx(0.0)  # top-1 is the FP
    assert float(out["mar_2"]) == pytest.approx(1.0)
    with pytest.raises(ValueError, match="max_detection_thresholds"):
        MeanAveragePrecision(num_classes=1, max_detection_thresholds=())


def test_max_detections_capacity_truncates_by_score_and_warns():
    """Over-capacity detections keep the top scores, with a loud notice
    (the static capacity is NOT the per-class COCO maxDets)."""
    gt = np.array([[0, 0, 10, 10]], np.float32)
    det = np.array([[50, 50, 60, 60], [0, 0, 10, 10]], np.float32)  # FP scored higher
    m = MeanAveragePrecision(num_classes=1, max_detections=1, max_gt=4)
    with pytest.warns(UserWarning, match="truncated to"):
        m.update(
            [{"boxes": jnp.asarray(det), "scores": jnp.asarray([0.9, 0.8]), "labels": jnp.asarray([0, 0])}],
            [{"boxes": jnp.asarray(gt), "labels": jnp.asarray([0])}],
        )
    out = m.compute()
    # only the (higher-scoring) FP survives the capacity -> no TP at all
    assert float(out["map"]) == pytest.approx(0.0)


def test_pickle_and_reset():
    rng = np.random.RandomState(9)
    images = _random_images(rng, 4, num_classes=2)
    m = MeanAveragePrecision(num_classes=2, max_detections=10, max_gt=6)
    _feed(m, images[:2])
    m2 = pickle.loads(pickle.dumps(m))
    _feed(m2, images[2:])
    want = _np_coco_map(images, 2)
    np.testing.assert_allclose(float(m2.compute()["map"]), want["map"], atol=1e-5)
    m2.reset()
    assert np.isnan(float(m2.compute()["map"]))


def test_empty_and_validation():
    m = MeanAveragePrecision(num_classes=2)
    assert np.isnan(float(m.compute()["map"]))
    with pytest.raises(ValueError, match="positive int"):
        MeanAveragePrecision(num_classes=0)
    with pytest.raises(ValueError, match="images"):
        m.update([], [{"boxes": jnp.zeros((0, 4)), "labels": jnp.zeros(0, jnp.int32)}])
    with pytest.raises(ValueError, match="max_gt"):
        mm = MeanAveragePrecision(num_classes=1, max_gt=1)
        mm.update(
            [{"boxes": jnp.zeros((0, 4)), "scores": jnp.zeros(0), "labels": jnp.zeros(0, jnp.int32)}],
            [{"boxes": jnp.zeros((2, 4)), "labels": jnp.zeros(2, jnp.int32)}],
        )


def test_image_without_detections_or_gts():
    """Images with zero dets (missed recall) and zero gts (pure FPs) both count."""
    gt = np.array([[0, 0, 10, 10]], np.float32)
    m = MeanAveragePrecision(num_classes=1, max_detections=4, max_gt=4)
    m.update(
        [
            {"boxes": jnp.zeros((0, 4)), "scores": jnp.zeros(0), "labels": jnp.zeros(0, jnp.int32)},
            {"boxes": jnp.asarray(gt), "scores": jnp.asarray([0.9]), "labels": jnp.asarray([0])},
        ],
        [
            {"boxes": jnp.asarray(gt), "labels": jnp.asarray([0])},
            {"boxes": jnp.zeros((0, 4)), "labels": jnp.zeros(0, jnp.int32)},
        ],
    )
    out = m.compute()
    # one GT total; its image had no dets; the other image's det is a FP
    assert float(out["mar_100"]) == pytest.approx(0.0)
    assert float(out["map"]) == pytest.approx(0.0)
