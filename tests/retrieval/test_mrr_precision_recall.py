"""RetrievalMRR / RetrievalPrecision / RetrievalRecall vs numpy oracles
(same harness shape as tests/retrieval/test_map.py; oracles are direct
per-query numpy rankings)."""
import random

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.functional.retrieval import (
    retrieval_precision,
    retrieval_recall,
    retrieval_reciprocal_rank,
)
from metrics_tpu.retrieval import RetrievalMRR, RetrievalPrecision, RetrievalRecall


def _np_rank_order(preds):
    # descending score, stable on ties — matches the device kernels
    return np.argsort(-preds, kind="stable")


def _np_mrr(target, preds):
    t = target[_np_rank_order(preds)]
    hits = np.flatnonzero(t)
    return 0.0 if hits.size == 0 else 1.0 / (hits[0] + 1)


def _np_precision(target, preds, k=None):
    n = len(target)
    k_eff = n if k is None else k
    t = target[_np_rank_order(preds)]
    return t[: min(k_eff, n)].sum() / k_eff


def _np_recall(target, preds, k=None):
    n = len(target)
    k_eff = n if k is None else k
    t = target[_np_rank_order(preds)]
    total = target.sum()
    return 0.0 if total == 0 else t[: min(k_eff, n)].sum() / total


def _mean_over_queries(oracle, target, preds, behaviour, **kw):
    out = []
    for t, p in zip(target, preds):
        if t.sum() == 0:
            if behaviour == "skip":
                continue
            out.append(1.0 if behaviour == "pos" else 0.0)
        else:
            out.append(oracle(t, p, **kw))
    return np.mean(out) if out else np.array(0.0)


@pytest.mark.parametrize("size", [1, 4, 10])
@pytest.mark.parametrize("n_queries", [1, 5])
@pytest.mark.parametrize("behaviour", ["skip", "pos", "neg"])
@pytest.mark.parametrize(
    "metric_cls,oracle,kw",
    [
        (RetrievalMRR, _np_mrr, {}),
        (RetrievalPrecision, _np_precision, {}),
        (RetrievalPrecision, _np_precision, {"k": 2}),
        (RetrievalRecall, _np_recall, {}),
        (RetrievalRecall, _np_recall, {"k": 2}),
    ],
)
def test_results_vs_numpy_oracle(size, n_queries, behaviour, metric_cls, oracle, kw):
    seed = size + n_queries * 10
    np.random.seed(seed)
    random.seed(seed)

    target = [np.random.randint(0, 2, size=(size,)) for _ in range(n_queries)]
    preds = [np.random.randn(size) for _ in range(n_queries)]
    expected = _mean_over_queries(oracle, target, preds, behaviour, **kw)

    metric = metric_cls(query_without_relevant_docs=behaviour, **kw)
    for i, (p, t) in enumerate(zip(preds, target)):
        metric.update(
            jnp.asarray(np.full(size, i)), jnp.asarray(p.astype(np.float32)), jnp.asarray(t)
        )
    np.testing.assert_allclose(float(metric.compute()), expected, atol=1e-6)


@pytest.mark.parametrize(
    "fn,oracle",
    [
        (retrieval_reciprocal_rank, _np_mrr),
        (retrieval_precision, _np_precision),
        (retrieval_recall, _np_recall),
    ],
)
def test_functional_vs_numpy_oracle(fn, oracle):
    np.random.seed(7)
    for _ in range(5):
        t = np.random.randint(0, 2, size=(12,))
        p = np.random.randn(12)
        if t.sum() == 0:
            t[3] = 1
        np.testing.assert_allclose(
            float(fn(jnp.asarray(p.astype(np.float32)), jnp.asarray(t))),
            oracle(t, p),
            atol=1e-6,
        )


@pytest.mark.parametrize("k", [1, 3, 12, 20])
def test_functional_topk_vs_numpy_oracle(k):
    np.random.seed(11)
    t = np.random.randint(0, 2, size=(12,))
    p = np.random.randn(12)
    np.testing.assert_allclose(
        float(retrieval_precision(jnp.asarray(p.astype(np.float32)), jnp.asarray(t), k=k)),
        _np_precision(t, p, k=k),
        atol=1e-6,
    )
    np.testing.assert_allclose(
        float(retrieval_recall(jnp.asarray(p.astype(np.float32)), jnp.asarray(t), k=k)),
        _np_recall(t, p, k=k),
        atol=1e-6,
    )


def test_exclude_sentinel_rows_do_not_count():
    # precision with k=None divides by the count of REAL rows only
    metric = RetrievalPrecision()
    idx = jnp.array([0, 0, 0, 0])
    preds = jnp.array([0.9, 0.8, 0.7, 0.6])
    target = jnp.array([1, 0, -100, -100])
    np.testing.assert_allclose(float(metric(idx, preds, target)), 0.5, atol=1e-6)


def _np_hit_rate(target, preds, k=None):
    n = len(target)
    k_eff = n if k is None else k
    t = target[_np_rank_order(preds)]
    return 1.0 if t[: min(k_eff, n)].sum() > 0 else 0.0


def _np_fall_out(target, preds, k=None):
    n = len(target)
    k_eff = n if k is None else k
    neg = 1 - target
    order = _np_rank_order(preds)
    total_neg = neg.sum()
    return 0.0 if total_neg == 0 else neg[order][: min(k_eff, n)].sum() / total_neg


@pytest.mark.parametrize("size", [1, 4, 10])
@pytest.mark.parametrize("n_queries", [1, 5])
@pytest.mark.parametrize("k", [None, 2])
def test_hit_rate_vs_numpy_oracle(size, n_queries, k):
    from metrics_tpu.retrieval import RetrievalHitRate

    np.random.seed(size + n_queries)
    target = [np.random.randint(0, 2, size=(size,)) for _ in range(n_queries)]
    preds = [np.random.randn(size) for _ in range(n_queries)]
    expected = _mean_over_queries(_np_hit_rate, target, preds, "skip", k=k)

    metric = RetrievalHitRate(k=k)
    for i, (p, t) in enumerate(zip(preds, target)):
        metric.update(jnp.asarray(np.full(size, i)), jnp.asarray(p.astype(np.float32)), jnp.asarray(t))
    np.testing.assert_allclose(float(metric.compute()), expected, atol=1e-6)


@pytest.mark.parametrize("size", [1, 4, 10])
@pytest.mark.parametrize("n_queries", [1, 5])
@pytest.mark.parametrize("k", [None, 2])
def test_fall_out_vs_numpy_oracle(size, n_queries, k):
    from metrics_tpu.retrieval import RetrievalFallOut

    np.random.seed(size * 3 + n_queries)
    target = [np.random.randint(0, 2, size=(size,)) for _ in range(n_queries)]
    preds = [np.random.randn(size) for _ in range(n_queries)]

    # fall-out's policy applies to queries with no NON-relevant docs
    out = []
    for t, p in zip(target, preds):
        if (1 - t).sum() == 0:
            continue  # 'skip'
        out.append(_np_fall_out(t, p, k=k))
    expected = np.mean(out) if out else 0.0

    metric = RetrievalFallOut(k=k)
    for i, (p, t) in enumerate(zip(preds, target)):
        metric.update(jnp.asarray(np.full(size, i)), jnp.asarray(p.astype(np.float32)), jnp.asarray(t))
    np.testing.assert_allclose(float(metric.compute()), expected, atol=1e-6)


@pytest.mark.parametrize("k", [None, 1, 2, 20])
def test_functional_hit_rate_fall_out_vs_numpy(k):
    from metrics_tpu.functional.retrieval import retrieval_fall_out, retrieval_hit_rate

    np.random.seed(23)
    for _ in range(4):
        t = np.random.randint(0, 2, size=(9,))
        p = np.random.randn(9)
        if t.sum() == 0:
            t[0] = 1
        if (1 - t).sum() == 0:
            t[1] = 0
        np.testing.assert_allclose(
            float(retrieval_hit_rate(jnp.asarray(p.astype(np.float32)), jnp.asarray(t), k=k)),
            _np_hit_rate(t, p, k=k),
            atol=1e-6,
        )
        np.testing.assert_allclose(
            float(retrieval_fall_out(jnp.asarray(p.astype(np.float32)), jnp.asarray(t), k=k)),
            _np_fall_out(t, p, k=k),
            atol=1e-6,
        )


def _np_r_precision(target, preds, k=None):
    r = int(target.sum())
    if r == 0:
        return 0.0
    t = target[_np_rank_order(preds)]
    return t[:r].sum() / r


@pytest.mark.parametrize("size", [1, 4, 10])
@pytest.mark.parametrize("n_queries", [1, 5])
@pytest.mark.parametrize("behaviour", ["skip", "pos", "neg"])
def test_r_precision_vs_numpy_oracle(size, n_queries, behaviour):
    from metrics_tpu.retrieval import RetrievalRPrecision

    np.random.seed(size * 7 + n_queries)
    target = [np.random.randint(0, 2, size=(size,)) for _ in range(n_queries)]
    preds = [np.random.randn(size) for _ in range(n_queries)]
    expected = _mean_over_queries(_np_r_precision, target, preds, behaviour)

    metric = RetrievalRPrecision(query_without_relevant_docs=behaviour)
    for i, (p, t) in enumerate(zip(preds, target)):
        metric.update(jnp.asarray(np.full(size, i)), jnp.asarray(p.astype(np.float32)), jnp.asarray(t))
    np.testing.assert_allclose(float(metric.compute()), expected, atol=1e-6)


def test_functional_r_precision_vs_numpy():
    from metrics_tpu.functional.retrieval import retrieval_r_precision

    np.random.seed(41)
    for _ in range(5):
        t = np.random.randint(0, 2, size=(10,))
        p = np.random.randn(10)
        if t.sum() == 0:
            t[2] = 1
        np.testing.assert_allclose(
            float(retrieval_r_precision(jnp.asarray(p.astype(np.float32)), jnp.asarray(t))),
            _np_r_precision(t, p),
            atol=1e-6,
        )


def test_fall_out_error_policy_message():
    from metrics_tpu.retrieval import RetrievalFallOut

    metric = RetrievalFallOut(query_without_relevant_docs="error")
    metric.update(jnp.array([0, 0]), jnp.array([0.1, 0.2]), jnp.array([1, 1]))  # all relevant
    with pytest.raises(ValueError, match="without non-relevant targets"):
        metric.compute()


def test_fall_out_exclude_sentinels_ignored():
    from metrics_tpu.retrieval import RetrievalFallOut

    metric = RetrievalFallOut(k=1)
    idx = jnp.array([0, 0, 0, 0])
    preds = jnp.array([0.9, 0.8, 0.7, 0.6])
    target = jnp.array([0, 1, -100, -100])  # one real negative, ranked first
    np.testing.assert_allclose(float(metric(idx, preds, target)), 1.0, atol=1e-6)


def test_bad_k_raises():
    for cls in (RetrievalPrecision, RetrievalRecall):
        with pytest.raises(ValueError, match="positive integer"):
            cls(k=0)
    with pytest.raises(ValueError, match="positive integer"):
        retrieval_precision(jnp.array([0.1]), jnp.array([1]), k=-1)


def test_functional_r_precision_trace_safe():
    """R is computed on device: the functional must compose under jit/vmap."""
    import jax
    from metrics_tpu.functional.retrieval import retrieval_r_precision

    np.random.seed(42)
    t = np.random.randint(0, 2, size=(4, 12))
    t[t.sum(1) == 0, 0] = 1
    p = np.random.randn(4, 12).astype(np.float32)
    batched = jax.jit(jax.vmap(retrieval_r_precision))(jnp.asarray(p), jnp.asarray(t))
    for i in range(4):
        np.testing.assert_allclose(float(batched[i]), _np_r_precision(t[i], p[i]), atol=1e-6)
    # no-relevant query under vmap (the r==0 branch must be trace-safe too)
    z = jax.jit(retrieval_r_precision)(jnp.asarray(p[0]), jnp.zeros(12, dtype=jnp.int32))
    assert float(z) == 0.0
