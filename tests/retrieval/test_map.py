"""RetrievalMAP vs a sklearn-based oracle
(mirrors reference tests/retrieval/test_map.py, which groups with numpy and
scores each group with sklearn's average_precision_score)."""
import math
import random

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import average_precision_score as sk_average_precision

from metrics_tpu.functional.retrieval import retrieval_average_precision
from metrics_tpu.retrieval import RetrievalMAP


def _compute_sklearn_metric(metric, target, preds, behaviour):
    """Reference oracle (reference tests/retrieval/test_map.py:12-41)."""
    sk_results = []
    kwargs = {}

    for b, a in zip(target, preds):
        if b.sum() == 0:
            if behaviour == "skip":
                pass
            elif behaviour == "pos":
                sk_results.append(1.0)
            else:
                sk_results.append(0.0)
        else:
            res = metric(b, a, **kwargs)
            sk_results.append(res)

    if len(sk_results) > 0:
        return np.mean(sk_results)
    return np.array(0.0)


@pytest.mark.parametrize("size", [1, 4, 10])
@pytest.mark.parametrize("n_documents", [1, 5])
@pytest.mark.parametrize("query_without_relevant_docs_options", ["skip", "pos", "neg"])
def test_results(size, n_documents, query_without_relevant_docs_options):
    """Test metrics are computed correctly wrt the sklearn baseline
    (reference tests/retrieval/test_map.py:44-75)."""
    _seed = size + n_documents * 10
    np.random.seed(_seed)
    random.seed(_seed)

    target = [np.random.randint(0, 2, size=(size,)) for _ in range(n_documents)]
    preds = [np.random.randn(size) for _ in range(n_documents)]

    sk_results = _compute_sklearn_metric(
        sk_average_precision, target, preds, query_without_relevant_docs_options
    )

    indexes = [np.full(size, fill_value=i) for i in range(n_documents)]
    metric = RetrievalMAP(query_without_relevant_docs=query_without_relevant_docs_options)

    for i, p, t in zip(indexes, preds, target):
        metric.update(jnp.asarray(i), jnp.asarray(p.astype(np.float32)), jnp.asarray(t))

    result = metric.compute()
    np.testing.assert_allclose(float(result), sk_results, atol=1e-6)


def test_dtypes_and_shapes():
    metric = RetrievalMAP()
    with pytest.raises(ValueError, match="must be of the same shape"):
        metric.update(jnp.array([0, 0]), jnp.array([0.1, 0.2, 0.3]), jnp.array([1, 0]))


def test_error_on_empty_queries():
    metric = RetrievalMAP(query_without_relevant_docs="error")
    metric.update(jnp.array([0, 0]), jnp.array([0.1, 0.2]), jnp.array([0, 0]))
    with pytest.raises(ValueError, match="without positive targets"):
        metric.compute()


def test_wrong_policy():
    with pytest.raises(ValueError, match="received a wrong value"):
        RetrievalMAP(query_without_relevant_docs="fancy")


def test_functional_average_precision():
    """reference tests check AP of single queries against sklearn."""
    rng = np.random.RandomState(42)
    for _ in range(10):
        preds = rng.rand(20).astype(np.float32)
        target = rng.randint(0, 2, 20)
        if target.sum() == 0:
            continue
        mine = float(retrieval_average_precision(jnp.asarray(preds), jnp.asarray(target)))
        np.testing.assert_allclose(mine, sk_average_precision(target, preds), atol=1e-6)


def test_exclude_sentinel_rows():
    """Rows with target == exclude are dropped before ranking; the
    empty-query check uses raw sums (reference retrieval_metric.py:121 quirk)."""
    metric = RetrievalMAP()
    idx = jnp.array([0, 0, 0, 1, 1])
    preds = jnp.array([0.9, 0.5, 0.1, 0.8, 0.2])
    target = jnp.array([-100, 1, 0, 1, 0])
    # query 0: exclude top row -> remaining [0.5->1, 0.1->0] -> AP = 1.0
    # query 1: [0.8->1, 0.2->0] -> AP = 1.0
    result = metric(idx, preds, target)
    np.testing.assert_allclose(float(result), 1.0, atol=1e-6)


def test_interleaved_query_rows():
    """Rows of the same query arriving in different updates are regrouped."""
    metric = RetrievalMAP()
    metric.update(jnp.array([0, 1]), jnp.array([0.5, 0.3]), jnp.array([1, 0]))
    metric.update(jnp.array([1, 0]), jnp.array([0.6, 0.1]), jnp.array([1, 0]))
    # query 0: preds [.5(1), .1(0)] -> AP 1.0 ; query 1: preds [.3(0), .6(1)] -> AP 1.0
    np.testing.assert_allclose(float(metric.compute()), 1.0, atol=1e-6)
