"""RetrievalNormalizedDCG vs sklearn ndcg_score."""
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import ndcg_score

from metrics_tpu.functional.retrieval import retrieval_normalized_dcg
from metrics_tpu.retrieval import RetrievalNormalizedDCG


def test_functional_vs_sklearn():
    rng = np.random.RandomState(7)
    for _ in range(10):
        preds = rng.rand(16).astype(np.float32)
        target = rng.randint(0, 4, 16)  # graded relevance
        if target.sum() == 0:
            continue
        mine = float(retrieval_normalized_dcg(jnp.asarray(preds), jnp.asarray(target)))
        sk = ndcg_score(target[None], preds[None])
        np.testing.assert_allclose(mine, sk, atol=1e-5)


def test_module_multi_query_vs_sklearn():
    rng = np.random.RandomState(11)
    n_queries, size = 6, 12
    metric = RetrievalNormalizedDCG()
    per_query = []
    for q in range(n_queries):
        preds = rng.rand(size).astype(np.float32)
        target = rng.randint(0, 3, size)
        if target.sum() == 0:
            target[0] = 1
        per_query.append(ndcg_score(target[None], preds[None]))
        metric.update(jnp.full(size, q), jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_allclose(float(metric.compute()), np.mean(per_query), atol=1e-5)


def test_vectorized_matches_single_query():
    rng = np.random.RandomState(3)
    preds = rng.rand(24).astype(np.float32)
    target = rng.randint(0, 2, 24)
    target[:2] = 1
    idx = np.repeat(np.arange(3), 8)
    metric = RetrievalNormalizedDCG()
    metric.update(jnp.asarray(idx), jnp.asarray(preds), jnp.asarray(target))
    grouped = float(metric.compute())

    singles = []
    for q in range(3):
        m = target[idx == q]
        if m.sum() == 0:
            continue
        singles.append(float(retrieval_normalized_dcg(jnp.asarray(preds[idx == q]), jnp.asarray(m))))
    np.testing.assert_allclose(grouped, np.mean(singles), atol=1e-6)


@pytest.mark.parametrize("k", [1, 3, 8, 20])
def test_topk_module_vs_sklearn(k):
    rng = np.random.RandomState(5)
    n_queries, size = 4, 12
    metric = RetrievalNormalizedDCG(k=k)
    per_query = []
    for q in range(n_queries):
        preds = rng.rand(size).astype(np.float32)
        target = rng.randint(0, 3, size)
        if target.sum() == 0:
            target[0] = 1
        per_query.append(ndcg_score(target[None], preds[None], k=k))
        metric.update(jnp.full(size, q), jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_allclose(float(metric.compute()), np.mean(per_query), atol=1e-5)


def test_invalid_k():
    with pytest.raises(ValueError, match="positive integer"):
        RetrievalNormalizedDCG(k=-1)
