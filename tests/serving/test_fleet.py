"""MetricFleet: the sharded serving runtime's contract.

What must hold (serving/fleet.py):

- routing: ``stable_key_hash`` is a process-restart-stable FNV-1a (pinned
  against precomputed values, NOT against another in-process call — that
  would pass even with a salted hash), ``shard_for_key`` partitions with it,
  and non-canonical key types are rejected loudly;
- merge tier: merged records cover every oracle window exactly once, in
  window order, bit-exact vs a single-process oracle at several shard
  counts, with per-window sample counts conserved (zero lost, zero
  misrouted, zero double-counted);
- failover: a chaos ``preempt`` addressed at ``site="fleet.shard",
  shard=i`` kills exactly that shard; ``recover_shard`` (snapshot/restore +
  replay-log overlap replay through ``guarded_update``) brings it back with
  no double-published merged window and values still bit-exact — at the
  FLEET level, extending the single-service replay tests in
  ``tests/serving/test_service.py``;
- isolation: a hot shard's shedding/backpressure does not stall the other
  shards;
- gauges: ``fleet_shards`` rides the counters snapshot with per-shard
  health/queue/occupancy/published/replayed entries.
"""
import time

import numpy as np
import pytest

import jax.numpy as jnp

import metrics_tpu.observability as obs
from metrics_tpu import Accuracy, MetricFleet, Windowed
from metrics_tpu.parallel import faults
from metrics_tpu.parallel.sync import gather_all_arrays
from metrics_tpu.serving import (
    ShardStoppedError, shard_for_key, shards_for_keys, stable_key_hash,
)
from metrics_tpu.serving.fleet import FLEET_SITE

W, NW, LATE = 10.0, 4, 20.0


def _factory():
    return Windowed(Accuracy(), window_s=W, num_windows=NW, allowed_lateness_s=LATE,
                    dist_sync_fn=gather_all_arrays)


def _balanced_keys(per_shard, shards):
    keys, buckets = [], {s: 0 for s in range(shards)}
    j = 0
    while any(v < per_shard for v in buckets.values()):
        k = f"tenant-{j}"
        j += 1
        s = shard_for_key(k, shards)
        if buckets[s] < per_shard:
            buckets[s] += 1
            keys.append(k)
    return keys


def _stream(n=20, size=12, seed=0, shards=4):
    rng = np.random.RandomState(seed)
    keys = _balanced_keys(max(n // shards, 1), shards)
    out = []
    for i in range(n):
        t = i * 2.5 + rng.uniform(0, 2.5, size)
        late = rng.rand(size) < 0.2
        t = np.where(late, t - rng.uniform(0, 8.0, size), t)
        out.append((keys[i % len(keys)], t,
                    rng.rand(size).astype(np.float32),
                    rng.randint(0, 2, size).astype(np.int32)))
    return out


def _oracle(batches):
    """Global-watermark routing + fresh-metric window values (keys ignored:
    partitioning must never change a value)."""
    wm, events = None, {}
    for _key, t, p, y in batches:
        wm = float(t.max()) if wm is None else max(wm, float(t.max()))
        head = int(np.floor(wm / W))
        w = np.floor_divide(t, W).astype(np.int64)
        ok = ((w + 1) * W + LATE > wm) & (w > head - NW)
        assert ok.all(), "test streams must not drop (shard watermarks lag the global)"
        for j in range(len(t)):
            events.setdefault(int(w[j]), []).append((p[j], y[j]))
    origin = min(events)
    published = list(range(origin, head + 1))
    resident = [w for w in published if w > head - NW]

    def value(ws):
        pairs = [x for w in ws for x in events.get(w, [])]
        if not pairs:
            return np.asarray(np.nan, np.float32)
        m = Accuracy()
        m.update(jnp.asarray(np.array([a for a, _ in pairs], np.float32)),
                 jnp.asarray(np.array([b for _, b in pairs], np.int32)))
        return np.asarray(m.compute())

    return {"published": published, "values": {w: value([w]) for w in published},
            "merged": value(resident), "counts": {w: len(events.get(w, [])) for w in published}}


def _feed(fleet, batches):
    for key, t, p, y in batches:
        fleet.submit(key, jnp.asarray(p), jnp.asarray(y), event_time=t)


def _assert_matches_oracle(records, merged, oracle):
    windows = [r["window"] for r in records]
    assert windows == sorted(set(windows)), "merged records out of order or duplicated"
    assert sorted(set(windows)) == oracle["published"], "lost (or invented) windows"
    for r in records:
        np.testing.assert_array_equal(r["value"], oracle["values"][r["window"]],
                                      err_msg=f"window {r['window']}")
        assert r["rows"] == oracle["counts"][r["window"]], (
            f"window {r['window']}: merged {r['rows']} samples,"
            f" oracle routed {oracle['counts'][r['window']]}"
        )
    np.testing.assert_array_equal(merged, oracle["merged"])


# ------------------------------------------------------------------ routing
def test_stable_key_hash_is_pinned_across_processes():
    # pinned FNV-1a values: a restarted process (or another language's
    # implementation of the documented hash) MUST reproduce these exactly —
    # comparing two in-process calls would not catch a salted hash
    assert stable_key_hash("tenant-0") == 0x1CE48A04A2FF1955
    assert stable_key_hash("tenant-1") == 0x1CE48904A2FF17A2
    assert stable_key_hash(b"tenant-0") == 0x3D82925F040C1B10
    assert stable_key_hash(0) == 0x2B0A3B192B55573E
    assert stable_key_hash(12345) == 0xDBD8F4A96E701FD1


def test_shard_for_key_is_the_mod_partition_and_type_tagged():
    for key in ("t", b"t", 7, np.int64(7)):
        assert shard_for_key(key, 8) == stable_key_hash(key) % 8
    assert stable_key_hash(1) != stable_key_hash("1")  # type-tagged canonical bytes
    assert stable_key_hash(7) == stable_key_hash(np.int64(7))
    with pytest.raises(TypeError, match="str, bytes or int"):
        stable_key_hash(1.5)
    with pytest.raises(TypeError, match="str, bytes or int"):
        stable_key_hash(("a", 1))
    with pytest.raises(ValueError, match="num_shards"):
        shard_for_key("t", 0)


def test_shards_for_keys_matches_the_scalar_router_exactly():
    """The vectorized router is the SAME partition contract: one FNV-1a
    array pass + one ``% num_shards`` must assign every key the identical
    shard as ``shard_for_key`` — across str/bytes/int key batches, mixed
    object arrays, and every shard count a fleet would use. A single
    disagreement would misroute a tenant on the next restart."""
    str_keys = np.array([f"tenant-{i}" for i in range(257)] + ["", "雪", "a\x00b"])
    byte_keys = np.array([b"tenant-0", b"", b"a\x00b", b"\xff\xfe\x01"], dtype="S")
    int_keys = np.array([0, 1, -1, 12345, -(2**62), 2**62], dtype=np.int64)
    mixed = np.array(["a", b"a", 1, "1"], dtype=object)
    for keys in (str_keys, byte_keys, int_keys, mixed):
        for n in (1, 2, 7, 8, 64):
            got = shards_for_keys(keys, n)
            assert got.dtype == np.int64
            expect = [shard_for_key(k, n) for k in keys]
            np.testing.assert_array_equal(got, np.array(expect, dtype=np.int64))
    # plain python lists route identically to their array form
    np.testing.assert_array_equal(
        shards_for_keys(["u-1", "u-2"], 8),
        [shard_for_key("u-1", 8), shard_for_key("u-2", 8)],
    )
    assert shards_for_keys(np.array([], dtype=np.int64), 4).shape == (0,)
    with pytest.raises(ValueError, match="num_shards"):
        shards_for_keys(["t"], 0)


def test_router_deterministic_across_fleet_restarts():
    """The same keys route to the same shards in a freshly built fleet (the
    restart story: no per-process salt anywhere in the path)."""
    keys = [f"user-{i}" for i in range(64)]
    with MetricFleet(_factory, num_shards=4) as a:
        route_a = {k: a.shard_of(k) for k in keys}
    with MetricFleet(_factory, num_shards=4) as b:
        route_b = {k: b.shard_of(k) for k in keys}
    assert route_a == route_b
    assert set(route_a.values()) == {0, 1, 2, 3}  # 64 keys spread over all shards


def test_constructor_validation():
    with pytest.raises(ValueError, match="num_shards"):
        MetricFleet(_factory, num_shards=0)
    with pytest.raises(ValueError, match="callable"):
        MetricFleet("nope", num_shards=2)
    with pytest.raises(ValueError, match="Windowed"):
        MetricFleet(lambda: Accuracy(), num_shards=2)
    with pytest.raises(ValueError, match="Windowed"):
        MetricFleet(lambda: Windowed(Accuracy(), decay_half_life_s=5.0), num_shards=2)
    with pytest.raises(ValueError, match="replay_log"):
        MetricFleet(_factory, num_shards=2, replay_log=0)


# --------------------------------------------------------------- merge tier
@pytest.mark.parametrize("num_shards", [1, 2, 4])
def test_merged_output_bit_exact_vs_single_process_oracle(num_shards):
    batches = _stream()
    oracle = _oracle(batches)
    with MetricFleet(_factory, num_shards=num_shards) as fleet:
        _feed(fleet, batches)
        merged = np.asarray(fleet.finalize())
        records = list(fleet.merged_records)
    _assert_matches_oracle(records, merged, oracle)


def test_merge_overlaps_ingest_and_emits_in_window_order():
    """Merged records arrive through merged_publish_fn in window order, and
    early windows are already merged before the stream ends (the merge tier
    runs on the shards' publish stages, not at finalize)."""
    batches = _stream(n=24)
    seen = []
    with MetricFleet(_factory, num_shards=2, merged_publish_fn=lambda r: seen.append(r)) as fleet:
        _feed(fleet, batches)
        fleet.flush()
        mid_stream = len(seen)
        fleet.finalize()
    assert mid_stream >= 1, "nothing merged before finalize"
    windows = [r["window"] for r in seen]
    assert windows == sorted(windows) and len(set(windows)) == len(windows)
    assert seen[0]["forced"] is False  # closed by every shard, not forced


def test_empty_shards_merge_as_identity():
    """Fewer tenants than shards: traffic-less shards contribute nothing and
    block nothing at finalize."""
    batches = [(f"solo-{i % 2}", np.asarray([5.0 * i + 1.0]),
                np.float32([0.9]), np.int32([1])) for i in range(8)]
    oracle = _oracle(batches)
    with MetricFleet(_factory, num_shards=8) as fleet:
        _feed(fleet, batches)
        merged = np.asarray(fleet.finalize())
        records = list(fleet.merged_records)
    _assert_matches_oracle(records, merged, oracle)


def test_windowed_keyed_composition_partials_merge():
    """Windowed(Keyed(...)) shards merge per-window per-segment slabs — the
    'per-tenant-cohort AUROC over the last N windows' fleet story."""
    from metrics_tpu import Keyed

    def factory():
        return Windowed(Keyed(Accuracy(), num_slots=3), window_s=W, num_windows=NW,
                        allowed_lateness_s=LATE, dist_sync_fn=gather_all_arrays)

    rng = np.random.RandomState(7)
    oracle = factory()
    shards = [factory(), factory()]
    for i in range(6):
        t = np.full(6, i * 5.0 + 1.0)
        p = rng.rand(6).astype(np.float32)
        y = rng.randint(0, 2, 6).astype(np.int32)
        slots = rng.randint(0, 3, 6).astype(np.int32)
        shards[i % 2].update(jnp.asarray(p), jnp.asarray(y), event_time=t, slot=jnp.asarray(slots))
        oracle.update(jnp.asarray(p), jnp.asarray(y), event_time=t, slot=jnp.asarray(slots))
    template = factory()
    for w in oracle.resident_windows():
        parts = [m.window_partial(w) for m in shards if w in m.resident_windows()]
        np.testing.assert_array_equal(
            np.asarray(template.value_from_partials(parts)),
            np.asarray(oracle.compute_window(w)), err_msg=f"window {w}",
        )


def test_windowed_keyed_quantile_partials_merge():
    """The per-tenant sliding-p99 fleet story: Windowed(Keyed(Quantile))
    shards merge their per-window per-tenant quantile sketches by pure
    counts addition — bit-exact vs the union-stream oracle per window."""
    from metrics_tpu import Keyed, Quantile

    def factory():
        return Windowed(
            Keyed(Quantile(q=0.99, alpha=0.05, min_value=1e-3, max_value=1e3),
                  num_slots=3),
            window_s=W, num_windows=NW, allowed_lateness_s=LATE,
            dist_sync_fn=gather_all_arrays,
        )

    rng = np.random.RandomState(8)
    oracle = factory()
    shards = [factory(), factory()]
    for i in range(6):
        t = np.full(8, i * 5.0 + 1.0)
        v = rng.lognormal(0.0, 1.0, 8).astype(np.float32)
        slots = rng.randint(0, 3, 8).astype(np.int32)
        shards[i % 2].update(jnp.asarray(v), event_time=t, slot=jnp.asarray(slots))
        oracle.update(jnp.asarray(v), event_time=t, slot=jnp.asarray(slots))
    template = factory()
    for w in oracle.resident_windows():
        parts = [m.window_partial(w) for m in shards if w in m.resident_windows()]
        np.testing.assert_array_equal(
            np.asarray(template.value_from_partials(parts)),
            np.asarray(oracle.compute_window(w)), err_msg=f"window {w}",
        )


# ----------------------------------------------------------------- failover
def test_shard_kill_recover_replay_is_idempotent_at_fleet_level():
    """Kill one shard mid-stream (seeded, shard-addressed), recover it, and
    the merged stream is exactly the uninterrupted oracle's: no lost window,
    no double-published merged window, watermark replay no-ops the overlap."""
    batches = _stream(n=24)
    oracle = _oracle(batches)
    kill = shard_for_key(batches[2][0], 4)
    schedule = [faults.FaultSpec(kind="preempt", call=4, times=1,
                                 site=FLEET_SITE, shard=kill)]
    with faults.ChaosInjector(schedule, seed=0) as inj:
        with MetricFleet(_factory, num_shards=4) as fleet:
            recovered = 0
            for key, t, p, y in batches:
                try:
                    fleet.submit(key, jnp.asarray(p), jnp.asarray(y), event_time=t)
                except ShardStoppedError as err:
                    assert err.shard == kill
                    fleet.recover_shard(err.shard)
                    recovered += 1
            try:
                fleet.flush()
            except Exception:
                for i, svc in enumerate(fleet.shards):
                    if svc.state != "running":
                        fleet.recover_shard(i)
                        recovered += 1
                fleet.flush()
            merged = np.asarray(fleet.finalize())
            records = list(fleet.merged_records)
            replayed = sum(s.replayed_steps for s in fleet.shards)
    assert inj.injected["preempt"] == 1
    assert recovered == 1
    assert replayed >= 1, "the overlap replay never exercised guarded_update idempotence"
    _assert_matches_oracle(records, merged, oracle)


def test_recover_shard_routing_survives_restore():
    """A recovered shard still owns exactly its key partition — restores are
    shard-count-preserving, so the stable hash keeps routing identical."""
    batches = _stream(n=16)
    with MetricFleet(_factory, num_shards=4) as fleet:
        before = {key: fleet.shard_of(key) for key, *_ in batches}
        _feed(fleet, batches[:8])
        fleet.flush()
        victim = before[batches[0][0]]
        fleet.recover_shard(victim)
        after = {key: fleet.shard_of(key) for key, *_ in batches}
        assert before == after
        _feed(fleet, batches[8:])
        merged = np.asarray(fleet.finalize())
        records = list(fleet.merged_records)
    _assert_matches_oracle(records, merged, _oracle(batches))


def test_recover_shard_validation():
    with MetricFleet(_factory, num_shards=2) as fleet:
        with pytest.raises(ValueError, match="shard must be"):
            fleet.recover_shard(5)


# ---------------------------------------------------------------- isolation
def test_hot_shard_sheds_without_stalling_the_others():
    """drop_oldest on a stalled hot shard sheds ITS batches only; the other
    shards' streams flow and the merge tier still emits (forced at finalize
    where the hot shard's data went missing)."""
    keys = _balanced_keys(1, 2)  # one tenant per shard
    hot, cold = keys[0], keys[1]
    hot_shard = shard_for_key(hot, 2)
    schedule = [faults.FaultSpec(kind="ingest_stall", rate=1.0, duration_s=0.2,
                                 site=FLEET_SITE, shard=hot_shard)]
    rng = np.random.RandomState(3)
    with faults.ChaosInjector(schedule, seed=0):
        with MetricFleet(_factory, num_shards=2, queue_size=2,
                         shed_policy="drop_oldest") as fleet:
            for i in range(8):
                t = np.full(4, i * 2.0 + 0.5)
                p = rng.rand(4).astype(np.float32)
                y = rng.randint(0, 2, 4).astype(np.int32)
                for key in (hot, cold):
                    fleet.submit(key, jnp.asarray(p), jnp.asarray(y), event_time=t)
                # pace the producer so the COLD worker keeps up; the hot
                # worker (0.2 s/batch stall) still falls behind and sheds
                time.sleep(0.05)
            fleet.flush(60)
            shed = [s.shed_events for s in fleet.shards]
            processed = [s.processed for s in fleet.shards]
    assert shed[hot_shard] >= 1, "the hot shard never shed under the stall"
    other = 1 - hot_shard
    assert shed[other] == 0
    assert processed[other] == 8, "the cold shard was stalled by the hot one"


# ------------------------------------------------------------------- gauges
def test_fleet_shards_gauge_in_snapshot():
    batches = _stream(n=12)
    obs.enable()
    obs.reset()
    try:
        with MetricFleet(_factory, num_shards=3, name="fleet-gauge-test") as fleet:
            _feed(fleet, batches)
            fleet.finalize()
        snap = obs.counters_snapshot()
    finally:
        obs.disable()
    gauges = snap["fleet_shards"]["fleet-gauge-test"]
    assert set(gauges) == {"0", "1", "2"}
    for row in gauges.values():
        assert set(row) == {"health", "queue_depth", "occupied", "published", "replayed"}
        assert row["health"] in ("healthy", "degraded", "shedding")
    assert sum(row["published"] for row in gauges.values()) >= 3
    # shard services report under fleet-scoped labels in service_health
    shard_labels = [k for k in snap["service_health"] if k.startswith("fleet-gauge-test/shard")]
    assert len(shard_labels) == 3


def test_fleet_requires_tenant_key_types():
    with MetricFleet(_factory, num_shards=2) as fleet:
        with pytest.raises(TypeError, match="str, bytes or int"):
            fleet.submit(3.14, jnp.asarray(np.float32([0.5])),
                         jnp.asarray(np.int32([1])), event_time=np.array([1.0]))


# ------------------------------------------------------- heavy-hitter fleet
def test_heavy_hitter_fleet_routes_and_merges_global_topk():
    """``HeavyHitterFleet`` serves an UNBOUNDED key space with no pre-sized
    slot table: keys partition by the stable router (disjoint per-shard hot
    sets), per-shard state is constant in the live-key count, and the global
    top-K is the pure merge of per-shard records — counts exact for hot keys
    with no tail residue."""
    from metrics_tpu import HeavyHitterFleet, HeavyHitters

    fleet = HeavyHitterFleet(
        lambda: HeavyHitters(Accuracy(), num_hot_slots=8, tail=(4, 512)),
        num_shards=4,
    )
    rng = np.random.RandomState(5)
    true_counts: dict = {}
    for _ in range(20):
        keys = [int(k) for k in rng.zipf(1.4, 32) % 10_000]
        preds = jnp.asarray(rng.rand(32).astype(np.float32))
        target = jnp.asarray(rng.randint(0, 2, 32).astype(np.int32))
        fleet.submit(keys, preds, target)
        for k in keys:
            true_counts[k] = true_counts.get(k, 0) + 1
    records = fleet.compute_heavy_hitters(k=5)
    assert len(records) == 5
    counts = [r["count"] for r in records]
    assert counts == sorted(counts, reverse=True)
    for record in records:
        assert record["shard"] == fleet.shard_of(record["key"])
        if record["exact"]:
            assert record["count"] == true_counts[record["key"]]
    # every key reads from its home shard, certified when tail-resident
    tail_key = next(k for k in true_counts if all(k not in s._table for s in fleet.shards))
    home = fleet.shards[fleet.shard_of(tail_key)]
    est = home.tail_estimate(tail_key)
    assert true_counts[tail_key] <= est["count"] <= true_counts[tail_key] + est["bound"]
    assert fleet.tail_overcount_bound() >= est["bound"] - 1e-9
    assert fleet.tail_mass() == sum(s.tail_mass() for s in fleet.shards)
    value = fleet.compute(tail_key)
    np.testing.assert_array_equal(np.asarray(value), np.asarray(est["value"]))


def test_heavy_hitter_fleet_validation():
    from metrics_tpu import HeavyHitterFleet, HeavyHitters

    with pytest.raises(ValueError, match="zero-arg callable"):
        HeavyHitterFleet("nope", 2)
    with pytest.raises(ValueError, match="num_shards"):
        HeavyHitterFleet(lambda: HeavyHitters(Accuracy(), 2), 0)
    with pytest.raises(ValueError, match="HeavyHitters"):
        HeavyHitterFleet(lambda: Accuracy(), 2)


def test_fleet_agreement_excludes_stalled_shard_and_merges_degraded():
    """The fleet clock: with ``agreement=True`` every shard joins one
    WatermarkAgreement as rank i. A shard that stops reporting is excluded
    after the deadline (``wm_stragglers`` bumps) and the merge frontier
    proceeds on the survivors — stamped ``degraded=True`` — instead of
    waiting on it forever."""
    import metrics_tpu.observability as obs
    from metrics_tpu.parallel.sync import SyncGuard
    from metrics_tpu.serving import shard_for_key

    guard = SyncGuard(deadline_s=0.6, max_retries=1, backoff_s=0.02, policy="degrade")
    before = obs.COUNTERS.wm_stragglers
    fleet = MetricFleet(_factory, num_shards=2, guard=guard, agreement=True)
    try:
        assert fleet.agreement is not None
        assert all(s.metric.agreement is fleet.agreement for s in fleet.shards)
        keys = {shard_for_key(f"t{i}", 2): f"t{i}" for i in range(16)}
        live, dead = keys[0], keys[1]
        preds = jnp.asarray(np.float32([0.9, 0.8]))
        target = jnp.asarray(np.int32([1, 1]))
        # the dead shard speaks once, then goes silent; the live shard
        # keeps streaming past the agreement deadline
        fleet.submit(dead, preds, target, event_time=np.array([1.0, 2.0]))
        for r in range(8):
            fleet.submit(live, preds, target,
                         event_time=np.array([r * 10.0 + 3.0, r * 10.0 + 7.0]))
            fleet.flush(10)
            time.sleep(0.12)
        assert fleet.merged_records, "the stalled shard wedged the merge tier"
        assert all(r["degraded"] for r in fleet.merged_records)
        assert obs.COUNTERS.wm_stragglers - before >= 1
    finally:
        fleet.stop(10)


def test_fleet_exclusion_stamps_only_windows_the_straggler_never_closed():
    """The degraded stamp is per-window, from the verdict actually used: a
    window EVERY shard fully closed before one stalled is coherent and
    merges undegraded even while the exclusion episode is live; only the
    windows the straggler never closed merge degraded on the survivors'
    clocks."""
    from metrics_tpu.parallel.sync import SyncGuard
    from metrics_tpu.serving import shard_for_key

    guard = SyncGuard(deadline_s=0.6, max_retries=1, backoff_s=0.02, policy="degrade")
    fleet = MetricFleet(_factory, num_shards=2, guard=guard, agreement=True)
    try:
        keys = {shard_for_key(f"t{i}", 2): f"t{i}" for i in range(16)}
        live, dying = keys[0], keys[1]
        preds = jnp.asarray(np.float32([0.9, 0.8]))
        target = jnp.asarray(np.int32([1, 1]))
        # phase 1 — both shards healthy: window-0 events land while every
        # clock is still inside window 0, then both clocks advance past its
        # close point (0 + W + LATE = 30) while staying < 40 so window 0 is
        # still RESIDENT in the 4-slot ring; the flush between rounds
        # barriers the reports, and the third round lets whichever shard
        # evaluated first re-evaluate the close and publish too
        fleet.submit(dying, preds, target, event_time=np.array([2.0, 5.0]))
        fleet.submit(live, preds, target, event_time=np.array([1.0, 6.0]))
        fleet.flush(10)
        fleet.submit(dying, preds, target, event_time=np.array([31.0, 33.0]))
        fleet.submit(live, preds, target, event_time=np.array([32.0, 35.0]))
        fleet.flush(10)
        fleet.submit(dying, preds, target, event_time=np.array([34.0, 36.0]))
        fleet.submit(live, preds, target, event_time=np.array([36.0, 38.0]))
        fleet.flush(10)
        by_window = {r["window"]: r for r in fleet.merged_records}
        assert 0 in by_window and by_window[0]["degraded"] is False
        # phase 2 — the dying shard goes silent; the live shard streams
        # past the deadline, the agreement excludes the straggler, and the
        # frontier proceeds degraded on the survivor's clock
        for r in range(8):
            fleet.submit(live, preds, target,
                         event_time=np.array([50.0 + r * 10.0, 55.0 + r * 10.0]))
            fleet.flush(10)
            time.sleep(0.12)
        later = [r for r in fleet.merged_records if r["window"] >= 1]
        assert later, "the stalled shard wedged the merge frontier"
        assert all(r["degraded"] for r in later)
        # the already-coherent window 0 record was emitted before the stall
        # and stays undegraded
        assert {r["window"]: r for r in fleet.merged_records}[0]["degraded"] is False
    finally:
        fleet.stop(10)


def test_fleet_agreement_gates_merge_on_slowest_shard():
    """Before the deadline, the agreed clock holds the merge frontier at the
    slowest healthy shard — a fast shard's publishes bank partials but no
    merged record jumps ahead of the agreed watermark."""
    from metrics_tpu.serving import shard_for_key

    fleet = MetricFleet(_factory, num_shards=2, agreement=True)
    try:
        keys = {shard_for_key(f"t{i}", 2): f"t{i}" for i in range(16)}
        fast, slow = keys[0], keys[1]
        preds = jnp.asarray(np.float32([0.9, 0.8]))
        target = jnp.asarray(np.int32([1, 1]))
        fleet.submit(slow, preds, target, event_time=np.array([1.0, 4.0]))
        fleet.submit(fast, preds, target, event_time=np.array([2.0, 15.0]))
        fleet.submit(fast, preds, target, event_time=np.array([92.0, 95.0]))
        fleet.flush(10)
        # the fast shard's local clock passed window 0's close long ago (its
        # ring pressure even banked window 0's partial), but the agreed
        # clock (min with the slow shard's 4.0) holds every MERGED record
        assert fleet.merged_records == []
        fleet.submit(slow, preds, target, event_time=np.array([90.0, 96.0]))
        fleet.flush(10)
        merged = [r["window"] for r in fleet.merged_records]
        assert merged and merged == sorted(merged)
        assert 0 in merged  # both shards' window-0 partials folded
        by_window = {r["window"]: r for r in fleet.merged_records}
        assert float(by_window[0]["rows"]) == 3.0  # t=1, t=4, t=2 across shards
        assert all(not r["degraded"] for r in fleet.merged_records)
    finally:
        fleet.stop(10)
