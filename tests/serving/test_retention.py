"""RetentionStore: the tiered retention + query plane contract.

What must hold (serving/retention.py):

- lossless roll-up: a query point at ANY legal resolution is BIT-EXACT the
  value a flat recompute over the union of the raw published partials in
  its span produces — roll-up is pure state addition and merge is
  associative/commutative (the four-state-kind sweep lives in
  ``bench.py --check-retention``; these tests pin the store mechanics);
- bounded memory: resident bytes saturate at the ladder shape — a 3x longer
  stream retains EXACTLY the same bytes, with the overflow counted in
  ``evicted_buckets``, never silent;
- final= provenance: windows force-published by ``finalize()`` before the
  close clock passed them carry ``final=False`` through partials, buckets
  and query points — the read side can always tell complete from
  flush-truncated;
- the query plane's edges: empty ranges, ranges straddling a roll-up
  boundary, never-updated tenants, output grids coarser than the coarsest
  rung, and a query racing an in-flight roll-up (readers never observe a
  half-merged bucket);
- ingest hygiene: wire-format version validated loudly, unknown streams
  rejected, a re-published window REPLACES its bucket (publishes are
  idempotent per window, never additive);
- attach: composes with an already-installed partial tap; a fleet banks ONE
  merged partial per window (not one per shard).
"""
import threading

import numpy as np
import pytest

import metrics_tpu.observability as obs
from metrics_tpu import (
    Accuracy,
    Keyed,
    MeanSquaredError,
    MetricFleet,
    MetricService,
    RetentionStore,
    Windowed,
)
from metrics_tpu.parallel.slab import PARTIAL_SCHEMA_VERSION

W = 10.0


def _metric(inner=None, **kw):
    args = dict(window_s=W, num_windows=4, allowed_lateness_s=0.0)
    args.update(kw)
    return Windowed(inner if inner is not None else Accuracy(), **args)


def _drive(svc, n_batches=48, size=8, seed=0, step=2.5, tee=None):
    """Feed a random binary stream; optionally tee raw partials for flat
    recomputes (the tee wraps the tap AFTER attach so it sees every partial
    the store ingests, without double-ingesting)."""
    if tee is not None:
        inner_tap = svc.partial_publish_fn

        def teed(record, partial):
            tee.append(partial)
            if inner_tap is not None:
                inner_tap(record, partial)

        svc.partial_publish_fn = teed
    rng = np.random.RandomState(seed)
    t = 0.0
    for _ in range(n_batches):
        svc.submit(rng.rand(size).astype(np.float32),
                   rng.randint(0, 2, size).astype(np.int32),
                   event_time=np.full(size, t))
        t += step
    return t


def _flat(template_factory, raw, start_s, seconds):
    group = [p for p in raw if start_s <= p["window_start_s"] < start_s + seconds]
    return np.asarray(template_factory().value_from_partials(group))


# ------------------------------------------------------------ lossless read
def test_query_bitexact_vs_flat_recompute_across_resolutions():
    raw = []
    svc = MetricService(_metric(), name="svc-exact", deferred_publish=False)
    store = RetentionStore(ladder=((W, 4), (4 * W, 4), (16 * W, 4)), name="exact").attach(svc)
    end = _drive(svc, n_batches=120, tee=raw)
    svc.finalize()
    svc.stop()
    assert raw and store.windows_banked == len(raw)
    assert store.rollups > 0  # the ladder actually rolled

    # the coarsest retained grid, a coarser-than-coarsest grid, and the
    # native mixed-resolution view: every point equals the flat recompute
    for resolution in (16 * W, 32 * W, None):
        points = store.query(time_range=(0.0, end), resolution_s=resolution)
        assert points == sorted(points, key=lambda p: p["start_s"])
        assert sum(p["windows"] for p in points) == len(raw)
        for p in points:
            expect = _flat(_metric, raw, p["start_s"], p["seconds"])
            assert np.array_equal(expect, p["value"], equal_nan=True)

    # a rolled-up span cannot be read finer than it was merged
    with pytest.raises(ValueError, match="cannot split"):
        store.query(time_range=(0.0, end), resolution_s=W)
    # ...but the still-raw tail can
    tail = store.query(time_range=(end - 2 * W, end), resolution_s=W)
    assert tail and all(p["seconds"] == W for p in tail)
    for p in tail:
        assert np.array_equal(_flat(_metric, raw, p["start_s"], W), p["value"],
                              equal_nan=True)


def test_ladder_validation():
    with pytest.raises(ValueError, match="at least one rung"):
        RetentionStore(ladder=())
    with pytest.raises(ValueError, match="capacity"):
        RetentionStore(ladder=((W, 0),))
    with pytest.raises(ValueError, match="integer multiple"):
        RetentionStore(ladder=((W, 4), (2.5 * W, 4)))
    with pytest.raises(ValueError, match="integer multiple"):
        RetentionStore(ladder=((W, 4), (W, 4)))  # 1x is not a coarsening
    svc = MetricService(_metric(), name="svc-ladder", deferred_publish=False)
    try:
        with pytest.raises(ValueError, match="window stride"):
            RetentionStore(ladder=((W / 2, 4),)).attach(svc)
    finally:
        svc.stop()


def test_memory_flat_as_stream_grows():
    def run(tag, n_batches):
        svc = MetricService(_metric(), name=f"svc-mem-{tag}", deferred_publish=False)
        store = RetentionStore(ladder=((W, 3), (4 * W, 3), (16 * W, 2)),
                               name=f"mem-{tag}").attach(svc)
        _drive(svc, n_batches=n_batches, seed=1)
        svc.finalize()
        svc.stop()
        return store

    short, long = run("1x", 160), run("3x", 480)
    assert long.windows_banked == 3 * short.windows_banked
    assert long.resident_bytes() == short.resident_bytes()  # ladder-bounded
    assert long.evicted_buckets > short.evicted_buckets  # overflow is counted


def test_finalize_truncated_windows_are_not_final():
    svc = MetricService(_metric(), name="svc-final", deferred_publish=False)
    store = RetentionStore(name="final").attach(svc)
    records = []
    svc.publish_fn = records.append
    end = _drive(svc, n_batches=9, step=5.0)  # watermark 40: windows 0-3 closed
    svc.finalize()  # window 4 is still open -> force-published, truncated
    svc.stop()
    points = store.query(time_range=(0.0, end + W), resolution_s=W)
    assert [p["final"] for p in points] == [True] * (len(points) - 1) + [False]
    by_window = {r["window"]: r["final"] for r in records}
    assert by_window[len(points) - 1] is False
    assert all(by_window[w] for w in range(len(points) - 1))
    # the truncation survives a coarse read: any span touching the open
    # window reports final=False
    coarse = store.query(time_range=(0.0, end + W), resolution_s=16 * W)
    assert coarse[-1]["final"] is False


# -------------------------------------------------------------- tenant axis
def test_keyed_per_tenant_query():
    K = 4
    svc = MetricService(_metric(inner=Keyed(Accuracy(), num_slots=K)),
                        name="svc-keyed", deferred_publish=False)
    store = RetentionStore(name="keyed").attach(svc)
    rng = np.random.RandomState(2)
    t = 0.0
    for _ in range(24):
        svc.submit(rng.rand(8).astype(np.float32),
                   rng.randint(0, 2, 8).astype(np.int32),
                   event_time=np.full(8, t),
                   slot=rng.randint(0, K - 1, 8).astype(np.int32))  # slot K-1 never fed
        t += 5.0
    svc.finalize()
    svc.stop()

    whole = store.query(time_range=(0.0, t), resolution_s=16 * W)
    for slot in range(K - 1):
        sliced = store.query(time_range=(0.0, t), tenant=slot, resolution_s=16 * W)
        assert len(sliced) == len(whole)
        for p_whole, p_slot in zip(whole, sliced):
            assert np.array_equal(p_whole["value"][slot], p_slot["value"],
                                  equal_nan=True)
    # a tenant that exists but never updated resolves to the empty policy
    ghost = store.query(time_range=(0.0, t), tenant=K - 1, resolution_s=16 * W)
    assert all(np.isnan(p["value"]) for p in ghost)
    with pytest.raises(KeyError, match="out of range"):
        store.query(time_range=(0.0, t), tenant=K, resolution_s=16 * W)

    flat_svc = MetricService(_metric(), name="svc-flat", deferred_publish=False)
    flat_store = RetentionStore(name="flat").attach(flat_svc)
    _drive(flat_svc, n_batches=4)
    flat_svc.finalize()
    flat_svc.stop()
    with pytest.raises(ValueError, match="no tenant axis"):
        flat_store.query(time_range=(0.0, 100.0), tenant=0)


# ------------------------------------------------------------- query edges
def test_query_edges_empty_range_and_straddling_rollup_boundary():
    raw = []
    svc = MetricService(_metric(), name="svc-edges", deferred_publish=False)
    store = RetentionStore(ladder=((W, 4), (4 * W, 8)), name="edges").attach(svc)
    end = _drive(svc, n_batches=80, tee=raw)
    svc.finalize()
    svc.stop()

    assert store.query(time_range=(end + 1e6, end + 2e6)) == []  # never banked
    assert store.query(time_range=(5.0, 5.0)) == []  # zero-width
    with pytest.raises(ValueError, match="precedes"):
        store.query(time_range=(10.0, 0.0))
    with pytest.raises(ValueError, match="time_range"):
        store.query()

    # a range straddling the rolled-up/raw boundary: old spans come back at
    # the rolled 4W width, the recent tail at raw W width — and every point
    # still equals the flat recompute
    native = store.query(time_range=(0.0, end))
    widths = {p["seconds"] for p in native}
    assert widths == {W, 4 * W}
    for p in native:
        assert np.array_equal(_flat(_metric, raw, p["start_s"], p["seconds"]),
                              p["value"], equal_nan=True)

    # coarser than the coarsest rung just merges further
    one = store.query(time_range=(0.0, end), resolution_s=1024 * W)
    assert len(one) == 1 and one[0]["windows"] == len(raw)
    assert np.array_equal(_flat(_metric, raw, 0.0, 1024 * W), one[0]["value"],
                          equal_nan=True)


def test_query_racing_inflight_rollup_never_observes_half_merged_buckets():
    """One sample per window with value w (target 0, MSE) -> a bucket whose
    first window is ``lo`` and which merged ``n`` consecutive windows MUST
    read ``mean(lo^2 .. (lo+n-1)^2)``. A torn roll-up (bucket visible
    missing a constituent, or a constituent double-counted) breaks that
    identity by whole units. The queue drains on the service worker thread
    while this thread hammers the query plane."""
    svc = MetricService(_metric(window_s=1.0, inner=MeanSquaredError(),
                                allowed_lateness_s=0.0, num_windows=2),
                        name="svc-race", deferred_publish=False, queue_size=512)
    store = RetentionStore(ladder=((1.0, 4), (4.0, 4), (16.0, 16)), name="race").attach(svc)
    n_windows = 160
    errors = []

    def reader():
        try:
            for _ in range(400):
                for p in store.query(time_range=(0.0, float(n_windows))):
                    lo = int(round(p["start_s"]))
                    n = int(p["windows"])
                    got = float(p["value"])
                    want = float(np.mean(np.arange(lo, lo + n, dtype=np.float64) ** 2))
                    if not np.isclose(got, want, rtol=1e-5):
                        errors.append((p["start_s"], p["seconds"], n, got, want))
        except Exception as exc:  # noqa: BLE001 - surfaced on the main thread
            errors.append(exc)

    threads = [threading.Thread(target=reader) for _ in range(2)]
    for th in threads:
        th.start()
    for w in range(n_windows):
        svc.submit(np.float32([w]), np.float32([0.0]),
                   event_time=np.float64([w + 0.5]))
    svc.finalize()
    for th in threads:
        th.join()
    svc.stop()
    assert not errors, errors[:5]
    final = store.query(time_range=(0.0, float(n_windows)))
    assert sum(p["windows"] for p in final) == n_windows


# ------------------------------------------------------------------ ingest
def test_ingest_validates_version_and_stream_and_replaces_republished():
    svc = MetricService(_metric(), name="svc-ingest", deferred_publish=False)
    raw = []
    store = RetentionStore(name="ingest").attach(svc)
    _drive(svc, n_batches=8, tee=raw)
    svc.finalize()
    svc.stop()
    assert raw and all(p["version"] == PARTIAL_SCHEMA_VERSION for p in raw)

    with pytest.raises(ValueError, match="version mismatch"):
        store.ingest("svc-ingest", dict(raw[0], version=99))
    with pytest.raises(ValueError, match="version mismatch"):
        store.ingest("svc-ingest", {k: v for k, v in raw[0].items() if k != "version"})
    with pytest.raises(KeyError, match="no retained stream"):
        store.ingest("never-attached", raw[0])

    # a replayed publish of the same window replaces, never double-counts
    before = store.query(time_range=(0.0, 1e4))
    store.ingest("svc-ingest", raw[0])
    after = store.query(time_range=(0.0, 1e4))
    assert sum(p["windows"] for p in before) == sum(p["windows"] for p in after)
    for a, b in zip(before, after):
        assert np.array_equal(a["value"], b["value"], equal_nan=True)


def test_attach_composes_and_rejects_bad_sources():
    seen = []
    svc = MetricService(_metric(), name="svc-compose", deferred_publish=False,
                        partial_publish_fn=lambda r, p: seen.append(p["window"]))
    store = RetentionStore(name="compose").attach(svc)
    _drive(svc, n_batches=12)
    svc.finalize()
    svc.stop()
    assert seen and store.windows_banked == len(seen)  # both taps saw every window

    with pytest.raises(ValueError, match="MetricService or a MetricFleet"):
        RetentionStore().attach(_metric())
    svc2 = MetricService(_metric(), name="svc-compose", deferred_publish=False)
    try:
        with pytest.raises(ValueError, match="already retained"):
            store.attach(svc2)  # same label, same store
    finally:
        svc2.stop()
    with pytest.raises(ValueError, match="metric= is required"):
        RetentionStore().query(time_range=(0.0, 1.0))


# ------------------------------------------------------------------- fleet
def test_fleet_attach_banks_one_merged_partial_per_window():
    def factory():
        return _metric(allowed_lateness_s=20.0)

    with MetricFleet(factory, num_shards=3, name="fleet-ret") as fleet:
        store = RetentionStore(name="fleet-store").attach(fleet)
        rng = np.random.RandomState(3)
        raw = []
        for i in range(30):
            raw.append((f"tenant-{i % 7}", i * 2.5 + rng.uniform(0, 2.5, 8),
                        rng.rand(8).astype(np.float32),
                        rng.randint(0, 2, 8).astype(np.int32)))
        for key, t, p, y in raw:
            fleet.submit(key, p, y, event_time=t)
        fleet.finalize()
        records = list(fleet.merged_records)

    # one bucket per merged window, values matching the merged records
    points = store.query(time_range=(0.0, 1e4), resolution_s=W)
    assert [p["start_s"] for p in points] == [r["window"] * W for r in records]
    for p, r in zip(points, records):
        assert np.array_equal(p["value"], np.asarray(r["value"]), equal_nan=True)
        assert p["final"] == r["final"]


def test_retention_gauges_ride_the_counters_snapshot():
    obs.reset()
    obs.enable()
    try:
        svc = MetricService(_metric(), name="svc-gauge", deferred_publish=False)
        store = RetentionStore(ladder=((W, 2), (4 * W, 2)), name="gauge-store").attach(svc)
        _drive(svc, n_batches=40, seed=4)
        svc.finalize()
        svc.stop()
        store.query(time_range=(0.0, 1e4))
        snap = obs.counters_snapshot()
        entry = snap["retention"]["gauge-store"]
        assert entry == {
            "windows_banked": store.windows_banked,
            "rollups": store.rollups,
            "resident_bytes": store.resident_bytes(),
            "queries": store.queries,
        }
        assert entry["windows_banked"] > 0 and entry["rollups"] > 0
        assert entry["queries"] >= 1 and entry["resident_bytes"] > 0
    finally:
        obs.reset()
