"""OpenMetrics exposition: the scrape surface's format contract.

What must hold (serving/openmetrics.py):

- STRICT exposition format: every rendering parses under an unforgiving
  line-level validator — ``# TYPE``/``# HELP`` metadata once per family and
  before its samples, sample names matching their family (counter samples
  suffixed ``_total``; summary samples the bare name with a ``quantile``
  label, or ``_count``/``_sum``), legal metric/label names, escaped label
  values, float syntax, one ``# EOF`` terminator at the very end;
- content: the existing observability gauges (``service_health``,
  ``fleet_shards``, ``slab_slots``, fault counters, retention gauges), the
  pipeline-health families (watermark lag / publish staleness / lifecycle
  gauges + the ``stage_latency_ms`` summary), and each retained stream's
  latest resolved value are all present;
- keyed streams fan out one ``tenant``-labeled sample per slot;
- the stdlib HTTP endpoint serves the same text with the OpenMetrics
  content type on an ephemeral port, and survives concurrent scrapes
  racing the write path.
"""
import re
import threading
import urllib.request

import numpy as np
import pytest

import metrics_tpu.observability as obs
from metrics_tpu import (
    Accuracy,
    Keyed,
    MetricFleet,
    MetricService,
    RetentionStore,
    Windowed,
)
from metrics_tpu.serving import CONTENT_TYPE, ExpositionServer, render

W = 10.0

_NAME = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*$")
_VALUE = re.compile(r"(?:[+-]?(?:\d+(?:\.\d+)?(?:e[+-]?\d+)?)|NaN|[+-]Inf)$")
# one sample line: name{labels} value   (no timestamps/exemplars emitted)
_SAMPLE = re.compile(r"([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})? (\S+)$")
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_ESCAPE = re.compile(r"\\(.)")


def _unescape(value):
    def one(m):
        c = m.group(1)
        assert c in ('"', "\\", "n"), f"illegal escape \\{c}"
        return "\n" if c == "n" else c

    return _ESCAPE.sub(one, value)


def _parse_strict(text):
    """A deliberately unforgiving OpenMetrics parser: returns
    {family: {"type", "help", "samples": [(name, labels-dict, value)]}} or
    fails the test at the first violation."""
    assert text.endswith("# EOF\n"), "exposition must terminate with '# EOF\\n'"
    lines = text.split("\n")
    assert lines[-1] == "" and lines[-2] == "# EOF"
    assert "# EOF" not in lines[:-2], "EOF must appear exactly once, at the end"
    families = {}
    current = None
    for line in lines[:-2]:
        assert line == line.strip() and line, f"no padding or blank lines: {line!r}"
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, kind = rest.rsplit(" ", 1)
            assert _NAME.match(name), name
            assert kind in ("gauge", "counter", "histogram", "summary",
                            "info", "stateset", "unknown")
            assert name not in families, f"duplicate TYPE for {name}"
            families[name] = {"type": kind, "help": None, "samples": []}
            current = name
        elif line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, help_text = rest.split(" ", 1)
            assert name == current, "HELP must follow its family's TYPE"
            assert families[name]["help"] is None, f"duplicate HELP for {name}"
            families[name]["help"] = help_text
        else:
            assert not line.startswith("#"), f"unknown comment line: {line!r}"
            m = _SAMPLE.match(line)
            assert m, f"unparseable sample line: {line!r}"
            name, _, label_body, value = m.groups()
            assert current is not None, "sample before any family metadata"
            if families[current]["type"] == "counter":
                assert name == current + "_total", (
                    f"counter sample {name!r} must be {current}_total"
                )
            elif families[current]["type"] == "summary":
                assert name in (current, current + "_count", current + "_sum"), (
                    f"summary sample {name!r} must be {current}"
                    f"{{quantile=...}}, {current}_count or {current}_sum"
                )
            else:
                assert name == current, (
                    f"sample {name!r} outside its family {current!r}"
                )
            labels = {}
            if label_body is not None:
                stripped = _LABEL.sub("", label_body)
                assert set(stripped) <= {","}, (
                    f"malformed label body: {label_body!r}"
                )
                for lname, lvalue in _LABEL.findall(label_body):
                    assert _LABEL_NAME.match(lname), lname
                    assert lname not in labels, f"duplicate label {lname}"
                    labels[lname] = _unescape(lvalue)
            if families[current]["type"] == "summary" and name == current:
                assert "quantile" in labels, (
                    f"bare summary sample {name!r} needs a quantile label"
                )
            assert _VALUE.match(value), f"bad sample value: {value!r}"
            families[current]["samples"].append((name, labels, value))
    return families


def _sample_map(family):
    return {tuple(sorted(labels.items())): value
            for _, labels, value in family["samples"]}


@pytest.fixture()
def counters():
    obs.reset()
    obs.enable()
    yield
    obs.reset()


def _run_service(name, n_batches=16, inner=None, **kw):
    args = dict(window_s=W, num_windows=4, allowed_lateness_s=0.0)
    args.update(kw)
    svc = MetricService(Windowed(inner if inner is not None else Accuracy(), **args),
                        name=name, deferred_publish=False)
    store = RetentionStore(name=f"{name}-store").attach(svc)
    rng = np.random.RandomState(0)
    for i in range(n_batches):
        kwargs = {}
        if inner is not None:
            kwargs["slot"] = rng.randint(0, inner.num_slots, 8).astype(np.int32)
        svc.submit(rng.rand(8).astype(np.float32),
                   rng.randint(0, 2, 8).astype(np.int32),
                   event_time=np.full(8, i * 5.0), **kwargs)
    svc.finalize()
    svc.stop()
    return store


def test_rendering_is_strict_openmetrics_with_all_gauge_families(counters):
    store = _run_service('svc "quoted"\nnewlined\\slashed')
    families = _parse_strict(render([store]))

    # the observability gauges are all families, present even when empty
    for name in ("metrics_tpu_service_health", "metrics_tpu_service_published",
                 "metrics_tpu_service_shed_events", "metrics_tpu_service_queue_depth",
                 "metrics_tpu_fleet_shard_health", "metrics_tpu_fleet_shard_queue_depth",
                 "metrics_tpu_slab_slots", "metrics_tpu_fault",
                 "metrics_tpu_retention_windows_banked", "metrics_tpu_retention_rollups",
                 "metrics_tpu_retention_resident_bytes", "metrics_tpu_retention_queries",
                 "metrics_tpu_retained_latest", "metrics_tpu_retained_latest_final"):
        assert name in families, name
        assert families[name]["help"], f"{name} needs HELP text"

    # label escaping round-trips the hostile service name
    health = families["metrics_tpu_service_health"]
    (_, labels, value), = health["samples"]
    assert labels["service"] == 'svc "quoted"\nnewlined\\slashed'
    assert labels["state"] == "healthy" and value == "1"

    # faults render as counters with _total samples
    fault_kinds = {labels["kind"] for _, labels, _ in
                   families["metrics_tpu_fault"]["samples"]}
    assert {"sync_retries", "sync_deadline_exceeded",
            "degraded_computes", "quarantined_updates"} <= fault_kinds

    # retention gauges agree with the store
    banked = _sample_map(families["metrics_tpu_retention_windows_banked"])
    assert banked[(("store", store.label),)] == str(store.windows_banked)

    # the latest resolved value rides along with its provenance twins
    latest = families["metrics_tpu_retained_latest"]["samples"]
    assert len(latest) == 1
    point = store.latest()
    assert latest[0][2] == ("NaN" if np.isnan(point["value"])
                            else repr(float(point["value"])))
    finals = _sample_map(families["metrics_tpu_retained_latest_final"])
    assert set(finals.values()) <= {"0", "1"}


def test_keyed_stream_fans_out_tenant_samples(counters):
    K = 3
    store = _run_service("svc-keyed-om", inner=Keyed(Accuracy(), num_slots=K))
    families = _parse_strict(render([store]))
    samples = families["metrics_tpu_retained_latest"]["samples"]
    assert len(samples) == K
    tenants = {labels["tenant"] for _, labels, _ in samples}
    assert tenants == {str(i) for i in range(K)}
    point = store.latest()
    for _, labels, value in samples:
        expect = float(point["value"][int(labels["tenant"])])
        assert value == ("NaN" if np.isnan(expect) else repr(expect))


def test_fleet_gauges_render_per_shard(counters):
    def factory():
        return Windowed(Accuracy(), window_s=W, num_windows=4,
                        allowed_lateness_s=20.0)

    with MetricFleet(factory, num_shards=2, name="fleet-om") as fleet:
        rng = np.random.RandomState(1)
        for i in range(12):
            fleet.submit(f"tenant-{i % 5}", rng.rand(8).astype(np.float32),
                         rng.randint(0, 2, 8).astype(np.int32),
                         event_time=i * 2.5 + rng.uniform(0, 2.5, 8))
        fleet.finalize()
        families = _parse_strict(render())
    shard_health = families["metrics_tpu_fleet_shard_health"]["samples"]
    where = {(labels["fleet"], labels["shard"]) for _, labels, _ in shard_health}
    assert where == {("fleet-om", "0"), ("fleet-om", "1")}
    depth = families["metrics_tpu_fleet_shard_queue_depth"]["samples"]
    assert len(depth) == 2


def test_health_families_render_under_the_strict_validator(counters):
    label = "svc-health-om"
    store = _run_service(label)
    families = _parse_strict(render([store]))

    for name in ("metrics_tpu_watermark_lag_seconds",
                 "metrics_tpu_watermark_lag_degraded",
                 "metrics_tpu_publish_staleness_seconds",
                 "metrics_tpu_lifecycle_windows_stamped",
                 "metrics_tpu_lifecycle_open_windows",
                 "metrics_tpu_stage_latency_ms"):
        assert name in families, name
        assert families[name]["help"], f"{name} needs HELP text"

    # the deterministic stream publishes 8 windows, every one fully stamped
    stamped = _sample_map(families["metrics_tpu_lifecycle_windows_stamped"])
    assert stamped[(("service", label),)] == "8"
    lag = _sample_map(families["metrics_tpu_watermark_lag_seconds"])
    assert (("service", label),) in lag
    degraded = _sample_map(families["metrics_tpu_watermark_lag_degraded"])
    assert degraded[(("service", label),)] == "0"
    staleness = _sample_map(families["metrics_tpu_publish_staleness_seconds"])
    assert float(staleness[(("service", label),)]) >= 0.0

    # the summary family: quantile-labeled samples plus _count/_sum per
    # (service, stage) — the validator already enforced the sample grammar
    summary = families["metrics_tpu_stage_latency_ms"]
    assert summary["type"] == "summary"
    quantiles = [(l, v) for n, l, v in summary["samples"]
                 if n == "metrics_tpu_stage_latency_ms"]
    assert quantiles and {l["quantile"] for l, _ in quantiles} <= {"0.5", "0.95", "0.99"}
    counts = {(l["service"], l["stage"]): v for n, l, v in summary["samples"]
              if n.endswith("_count")}
    sums = {(l["service"], l["stage"]): v for n, l, v in summary["samples"]
            if n.endswith("_sum")}
    assert set(counts) == set(sums)
    stages = {stage for service, stage in counts if service == label}
    assert {"ingest", "close", "dispatch", "sync", "publish", "e2e"} <= stages
    assert counts[(label, "e2e")] == "8"  # one sample per published window


def test_http_endpoint_serves_the_exposition(counters):
    store = _run_service("svc-http")
    with ExpositionServer([store]) as server:
        assert server.port > 0
        with urllib.request.urlopen(server.url, timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"] == CONTENT_TYPE
            body = resp.read().decode("utf-8")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/nope", timeout=10)
    families = _parse_strict(body)
    assert "metrics_tpu_retained_latest" in families
    # scrape-visible and render-visible views agree
    assert _parse_strict(render([store])).keys() == families.keys()


def test_exposition_server_survives_concurrent_scrapes(counters):
    """Many scrapers hammering the endpoint while a service is actively
    publishing: every body must still parse under the strict validator, and
    the family schema must be identical across all of them (samples may
    differ — the write path races the reads — but families never flicker)."""
    store = _run_service("svc-scrape-many")
    bodies: list = []
    errors: list = []

    def scrape(server_url):
        try:
            for _ in range(5):
                with urllib.request.urlopen(server_url, timeout=10) as resp:
                    assert resp.status == 200
                    bodies.append(resp.read().decode("utf-8"))
        except Exception as exc:  # surfaced after join; threads can't fail tests
            errors.append(exc)

    with ExpositionServer([store]) as server:
        threads = [threading.Thread(target=scrape, args=(server.url,))
                   for _ in range(6)]
        for t in threads:
            t.start()
        # race the write path: a second service stamps ledgers / meters /
        # gauges in its worker thread while the scrapers read snapshots
        _run_service("svc-scrape-writer", n_batches=8)
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads), "scraper thread hung"

    assert errors == []
    assert len(bodies) == 30
    keysets = {frozenset(_parse_strict(body).keys()) for body in bodies}
    assert len(keysets) == 1, "family schema flickered across scrapes"


def test_render_accepts_an_explicit_snapshot(counters):
    snap = obs.counters_snapshot()
    families = _parse_strict(render(snapshot=snap))
    assert families["metrics_tpu_fault"]["type"] == "counter"
    assert families["metrics_tpu_retained_latest"]["samples"] == []
