"""Queue-drain coalescing: the ingest fast path's bit-exactness contract.

What must hold (serving/service.py + core/streaming.py + core/metric.py +
wrappers/windowed.py):

- equivalence: a MetricService with coalescing ON publishes a record stream
  BIT-IDENTICAL to the one-batch-per-drain twin over a randomized bursty
  stream (shuffled-within-lateness event times, beyond-lateness drops,
  variable batch sizes) — tumbling, sliding, and Windowed(Keyed(...))
  shapes alike. Coalescing is a dispatch optimization, never a semantic;
- judge_prefix: routing a concatenation of k batches under the per-event
  prefix running-max watermark yields the verdicts the sequential plane
  produced — including events a FINAL-max judge would have dropped — and
  the malformed-prefix forms are rejected loudly;
- guarded spans: ``guarded_update(a, ..., span_end=b)`` folds the seq range
  ``[a, b]`` all-or-nothing — whole-span replays no-op, straddling spans
  raise (the caller must split at the watermark), inverted spans raise;
- span formation: a drain coalesces exactly the contiguous same-structure
  publish-free runs (seq gaps split spans; replays no-op and count), and
  the bucketed routing-program cache compiles once per occupied sample
  bucket — steady state never retraces.
"""
import copy

import numpy as np
import pytest

import jax.numpy as jnp

import metrics_tpu.observability as obs
from metrics_tpu import Accuracy, Keyed, MetricService, Windowed
from metrics_tpu.core.streaming import WindowSpec, route_events
from metrics_tpu.observability.counters import COUNTERS


# ------------------------------------------------------------ stream makers
def _bursty_batches(n=90, seed=3, keyed=False):
    """A randomized stream: variable batch sizes, event times shuffled
    within the lateness horizon, and a sprinkle of beyond-lateness
    stragglers that MUST be dropped identically by both planes."""
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        size = int(rng.randint(1, 49))
        base = i * 2.0
        times = np.maximum(base + rng.uniform(-9.0, 1.0, size), 0.0)
        if i % 23 == 11:  # a too-late straggler: beyond every open window
            times[0] = max(base - 40.0, 0.0)
        kwargs = {}
        if keyed:
            kwargs["slot"] = rng.randint(0, 8, size)
        out.append((
            times.astype(np.float64),
            rng.rand(size).astype(np.float32),
            rng.randint(0, 2, size).astype(np.int32),
            kwargs,
        ))
    return out


def _drive(metric, batches, coalesce):
    """Feed the whole stream through a MetricService with the worker stalled
    during submission (so the backlog exists and the coalescing drain has
    something to coalesce), then flush + finalize."""
    svc = MetricService(
        metric,
        queue_size=len(batches) + 4,
        coalesce_max_batches=(8 if coalesce else 1),
    )
    try:
        with svc._proc_lock:
            for i, (t, p, y, kw) in enumerate(batches):
                svc.submit(jnp.asarray(p), jnp.asarray(y), event_time=t, seq=i, **kw)
        svc.flush()
        merged = svc.finalize()
        return {
            "publications": list(svc.publications),
            "merged": np.asarray(merged),
            "coalesced_batches": svc.coalesced_batches,
            "processed": svc.processed,
            "drains": svc.drains,
            "watermark": svc.metric.watermark,
            "head": svc.metric.head_window,
        }
    finally:
        svc.stop()


def _assert_same_publications(on, off):
    assert len(on["publications"]) == len(off["publications"])
    for rec_on, rec_off in zip(on["publications"], off["publications"]):
        assert set(rec_on) == set(rec_off)
        for field in rec_on:
            if field == "service":
                continue  # the label carries the instance counter, not data
            a, b = rec_on[field], rec_off[field]
            if isinstance(a, (np.ndarray, jnp.ndarray)) or isinstance(b, (np.ndarray, jnp.ndarray)):
                assert np.array_equal(np.asarray(a), np.asarray(b), equal_nan=True), field
            else:
                assert a == b, field
    assert np.array_equal(on["merged"], off["merged"], equal_nan=True)
    assert on["watermark"] == off["watermark"]
    assert on["head"] == off["head"]
    assert on["processed"] == off["processed"]


# ------------------------------------------------------------- equivalence
@pytest.mark.parametrize(
    "shape", ["tumbling", "sliding", "keyed"],
)
def test_coalesced_service_is_bit_exact_vs_one_batch_oracle(shape):
    """The tentpole property: coalescing changes drain counts, never a
    single published bit. Runs the identical randomized bursty stream
    through a coalescing service and its one-batch-per-drain twin and
    demands field-for-field equal publications, merged view, drop counts,
    and stream position — for a tumbling ring, a sliding ring (overlap
    routing included), and a Windowed(Keyed(...)) slab (kwarg
    concatenation included)."""
    def build():
        if shape == "sliding":
            return Windowed(Accuracy(), window_s=10.0, num_windows=8,
                            allowed_lateness_s=10.0, slide_s=5.0)
        if shape == "keyed":
            return Windowed(Keyed(Accuracy(), num_slots=8), window_s=10.0,
                            num_windows=4, allowed_lateness_s=10.0)
        return Windowed(Accuracy(), window_s=10.0, num_windows=4,
                        allowed_lateness_s=10.0)

    batches = _bursty_batches(keyed=(shape == "keyed"))
    on = _drive(build(), batches, coalesce=True)
    off = _drive(build(), batches, coalesce=False)
    # the oracle really was sequential; the fast path really coalesced
    assert off["coalesced_batches"] == 0
    assert on["coalesced_batches"] > 0
    assert on["drains"] < off["drains"]
    # the stream really closed windows mid-flight (publish-split coverage)
    assert len(on["publications"]) > 2
    _assert_same_publications(on, off)


# ------------------------------------------------------------ judge_prefix
def test_judge_prefix_routes_the_concatenation_like_the_sequential_plane():
    """The routing algebra under the per-event prefix clock: concatenating
    k batches and judging each event by its own batch's running max yields
    the EXACT sequential verdicts — including an old event the
    concatenation's FINAL max would have dropped (the case the prefix form
    exists for)."""
    spec = WindowSpec(10.0, 8, 10.0, None)
    # batch 2 carries t=1.0: judged at its own wm 19.5 it is accepted-late
    # (window [0,10) stays open until 30); judged at the span's final wm
    # 29.0 it would be dropped. The prefix must preserve the acceptance.
    batches = [
        np.array([12.0, 15.5, 3.0]),
        np.array([19.5, 1.0]),
        np.array([29.0, 22.0, 11.0]),
    ]
    wm, head = None, None
    seq_slots, seq_late, seq_dropped = [], 0, 0
    prefix = []
    for t in batches:
        route = route_events(t, wm, head, spec)
        seq_slots.append(route.slot_ids)
        seq_late += route.n_late
        seq_dropped += route.n_dropped
        wm, head = route.watermark, route.head
        prefix.append(np.full(t.shape, wm))
    cat = np.concatenate(batches)
    judge = np.concatenate(prefix)
    routed = route_events(cat, None, None, spec, judge_prefix=judge)
    np.testing.assert_array_equal(routed.slot_ids, np.concatenate(seq_slots))
    assert routed.n_late == seq_late
    assert routed.n_dropped == seq_dropped
    assert routed.watermark == wm and routed.head == head
    # the prefix is load-bearing: the scalar final-max judge disagrees
    scalar = route_events(cat, None, None, spec)
    assert scalar.n_dropped > seq_dropped
    # sliding overlap rows route identically under the prefix too
    slide = WindowSpec(10.0, 16, 10.0, 2.5)
    wm2 = head2 = None
    rows, prefix2 = [], []
    for t in batches:
        route = route_events(t, wm2, head2, slide)
        rows.append(np.stack([route.slot_ids, *route.overlap_slots]))
        wm2, head2 = route.watermark, route.head
        prefix2.append(np.full(t.shape, wm2))
    routed2 = route_events(cat, None, None, slide, judge_prefix=np.concatenate(prefix2))
    np.testing.assert_array_equal(
        np.stack([routed2.slot_ids, *routed2.overlap_slots]),
        np.concatenate(rows, axis=1),
    )


def test_judge_prefix_malformed_forms_are_rejected():
    spec = WindowSpec(10.0, 4, 10.0, None)
    t = np.array([5.0, 7.0])
    with pytest.raises(ValueError, match="must match event_times"):
        route_events(t, None, None, spec, judge_prefix=np.array([7.0]))
    with pytest.raises(ValueError, match="non-decreasing"):
        route_events(t, None, None, spec, judge_prefix=np.array([7.0, 5.0]))
    with pytest.raises(ValueError, match="end at the batch watermark"):
        route_events(t, None, None, spec, judge_prefix=np.array([5.0, 6.0]))
    with pytest.raises(ValueError, match="mutually exclusive"):
        route_events(t, None, None, spec, agreed=3.0,
                     judge_prefix=np.array([5.0, 7.0]))
    decay = Windowed(Accuracy(), decay_half_life_s=5.0)
    with pytest.raises(ValueError, match="decay"):
        decay.update(jnp.asarray(np.float32([0.9])), jnp.asarray(np.int32([1])),
                     event_time=np.array([1.0]), judge_prefix=np.array([1.0]))


# ---------------------------------------------------------- guarded spans
def test_guarded_update_span_is_all_or_nothing():
    p = jnp.asarray(np.float32([0.9, 0.1, 0.8, 0.3]))
    y = jnp.asarray(np.int32([1, 0, 1, 1]))
    m = Accuracy()
    with pytest.raises(ValueError, match="span_end"):
        m.guarded_update(5, p, y, span_end=4)
    # fold steps [0, 3] as one update: the watermark lands past the span
    assert m.guarded_update(0, p, y, span_end=3) is True
    assert m.epoch_watermark == 4
    before = np.asarray(m.compute())
    # a whole-span replay no-ops (any sub-span of the folded range too)
    assert m.guarded_update(0, p, y, span_end=3) is False
    assert m.guarded_update(1, p, y, span_end=2) is False
    np.testing.assert_array_equal(np.asarray(m.compute()), before)
    # a straddling span must be split by the caller, not half-applied
    with pytest.raises(ValueError, match="straddles"):
        m.guarded_update(2, p, y, span_end=5)
    np.testing.assert_array_equal(np.asarray(m.compute()), before)
    # the stream resumes at the watermark; a width-1 span is legal
    assert m.guarded_update(4, p, y, span_end=4) is True
    assert m.epoch_watermark == 5


# ---------------------------------------------------------- span formation
def _items(seqs, size=16, t0=0.0, seed=5):
    rng = np.random.RandomState(seed)
    out = []
    for j, seq in enumerate(seqs):
        times = t0 + j * 0.5 + rng.uniform(0.0, 0.4, size)
        out.append((
            seq,
            (jnp.asarray(rng.rand(size).astype(np.float32)),
             jnp.asarray(rng.randint(0, 2, size).astype(np.int32))),
            times.astype(np.float64),
            {},
        ))
    return out


def _wide_metric():
    # window longer than the stream: every drain is publish-free, so span
    # formation is decided by seq contiguity/structure alone
    return Windowed(Accuracy(), window_s=600.0, num_windows=4,
                    allowed_lateness_s=600.0)


def _slab_arrays(m):
    out = {name: np.asarray(getattr(m, name)) for name in m.metric._defaults}
    out["windowed_rows"] = np.asarray(getattr(m, "windowed_rows"))
    return out


def test_drain_coalesces_contiguous_runs_and_splits_on_seq_gaps():
    """Deterministic span formation, driven through ``_process_drain``
    directly (the worker loop's apply path): a contiguous backlog coalesces
    up to ``coalesce_max_batches``, a seq gap splits the span, and the
    folded state is bit-identical to the sequential twin's."""
    svc = MetricService(_wide_metric(), coalesce_max_batches=8)
    try:
        items = _items(range(9))
        with svc._proc_lock:
            svc._process_drain(items)
        # one drain: an 8-batch span + the 9th batch alone
        assert svc.drains == 1
        assert svc.processed == 9
        assert svc.coalesced_batches == 8
        twin = _wide_metric()
        for _, (p, y), t, _kw in items:
            twin.update(p, y, event_time=t)
        got, want = _slab_arrays(svc.metric), _slab_arrays(twin)
        for name in want:
            np.testing.assert_array_equal(got[name], want[name], err_msg=name)
        assert svc.metric.epoch_watermark == twin.epoch_watermark == 9
    finally:
        svc.stop()

    svc2 = MetricService(_wide_metric(), coalesce_max_batches=8)
    try:
        gapped = _items([0, 1, 5, 6])
        with svc2._proc_lock:
            svc2._process_drain(gapped)
        # the gap splits the backlog into two 2-batch spans, one drain
        assert svc2.drains == 1
        assert svc2.processed == 4
        assert svc2.coalesced_batches == 4
    finally:
        svc2.stop()


def test_replayed_drain_no_ops_per_batch():
    """Replaying an already-folded backlog (the restore path's overlap) must
    no-op batch by batch: counted as replays, zero state movement."""
    svc = MetricService(_wide_metric(), coalesce_max_batches=8)
    try:
        items = _items(range(4))
        with svc._proc_lock:
            svc._process_drain(items)
        before = _slab_arrays(svc.metric)
        assert svc.replayed_steps == 0
        with svc._proc_lock:
            svc._process_drain(items)
        assert svc.replayed_steps == 4
        assert svc.processed == 8
        after = _slab_arrays(svc.metric)
        for name in before:
            np.testing.assert_array_equal(after[name], before[name], err_msg=name)
    finally:
        svc.stop()


# ------------------------------------------------- bucketed program cache
def test_ingest_program_cache_compiles_once_per_bucket():
    """The retrace guard: every update pads to a power-of-two sample bucket
    and reuses ONE compiled routed-scatter program per (bucket, structure) —
    distinct batch sizes within a bucket are cache hits, a new bucket is
    exactly one miss, and copies start with an empty cache (programs are
    derived state, never checkpointed)."""
    obs.enable()
    try:
        metric = _wide_metric()
        rng = np.random.RandomState(9)

        def feed(size, t0):
            metric.update(
                jnp.asarray(rng.rand(size).astype(np.float32)),
                jnp.asarray(rng.randint(0, 2, size).astype(np.int32)),
                event_time=t0 + rng.uniform(0.0, 0.4, size),
            )

        h0, m0 = COUNTERS.ingest_program_cache_hits, COUNTERS.ingest_program_cache_misses
        feed(17, 0.0)
        feed(25, 1.0)
        feed(32, 2.0)  # all three pad into the 32-sample bucket
        assert len(metric._ingest_programs) == 1
        assert COUNTERS.ingest_program_cache_misses - m0 == 1
        assert COUNTERS.ingest_program_cache_hits - h0 == 2
        feed(40, 3.0)  # a second bucket: exactly one more program
        assert len(metric._ingest_programs) == 2
        assert COUNTERS.ingest_program_cache_misses - m0 == 2
        # padded rows never pollute the slabs: the fold equals the twin's
        twin = _wide_metric()
        rng2 = np.random.RandomState(9)
        for size, t0 in ((17, 0.0), (25, 1.0), (32, 2.0), (40, 3.0)):
            twin.update(
                jnp.asarray(rng2.rand(size).astype(np.float32)),
                jnp.asarray(rng2.randint(0, 2, size).astype(np.int32)),
                event_time=t0 + rng2.uniform(0.0, 0.4, size),
            )
        got, want = _slab_arrays(metric), _slab_arrays(twin)
        for name in want:
            np.testing.assert_array_equal(got[name], want[name], err_msg=name)
        # deep copies (snapshot/restore, fleet shard clones) drop the cache
        clone = copy.deepcopy(metric)
        assert len(clone._ingest_programs) == 0
        for name, arr in _slab_arrays(clone).items():
            np.testing.assert_array_equal(arr, got[name], err_msg=name)
    finally:
        obs.disable()
        obs.reset()
