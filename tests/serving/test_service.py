"""MetricService: the supervised serving loop's contract.

What must hold (serving/service.py):

- lifecycle: a background worker drains the bounded ingress queue in FIFO
  order; ``flush`` is a barrier, ``finalize`` force-publishes open windows,
  ``stop`` is idempotent;
- publishes: every closed window is published exactly once, in order, with
  host-numpy values; a sync that exhausts its guard under chaos publishes
  ``degraded=True`` instead of stalling;
- backpressure/shedding: ``drop_oldest`` sheds the oldest queued batch with
  a counter and flips health to ``shedding``; ``block`` never sheds;
- crash-safety: a chaos ``preempt`` at the ingest site kills the worker
  mid-window; a FRESH service restored from the snapshot replays the stream
  (from before the checkpoint — idempotent) and finishes bit-exact vs an
  uninterrupted run;
- health/gauges: ``service_health`` rides every counters snapshot, recorded
  even with observability off.

The ``soak`` marker tags the longer randomized scenario; its smoke-sized
variant stays in tier-1.
"""
import time

import numpy as np
import pytest

import jax.numpy as jnp

import metrics_tpu.observability as obs
from metrics_tpu import Accuracy, MetricService, Windowed
from metrics_tpu.parallel import faults
from metrics_tpu.parallel.sync import SyncGuard, gather_all_arrays
from metrics_tpu.serving.service import INGEST_SITE, ServiceStoppedError
from metrics_tpu.utils.exceptions import PreemptionError


def _metric(**kw):
    args = dict(window_s=10.0, num_windows=4, allowed_lateness_s=10.0)
    args.update(kw)
    return Windowed(Accuracy(), **args)


def _batches(n=10, size=8, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        out.append((
            i * 5.0 + rng.uniform(0.0, 5.0, size),
            rng.rand(size).astype(np.float32),
            rng.randint(0, 2, size).astype(np.int32),
        ))
    return out


def _feed(service, batches, start=0):
    for i, (t, p, y) in enumerate(batches[start:], start=start):
        service.submit(jnp.asarray(p), jnp.asarray(y), event_time=t, seq=i)


# --------------------------------------------------------------- lifecycle
def test_lifecycle_publishes_closed_windows_in_order():
    published = []
    with MetricService(_metric(), publish_fn=lambda r: published.append(r["window"])) as svc:
        batches = _batches()
        _feed(svc, batches)
        svc.flush()
        windows = [p["window"] for p in svc.publications]
        assert windows == sorted(windows) and len(set(windows)) == len(windows)
        merged = svc.finalize()
        # every resident window published by the end, none twice
        final_windows = [p["window"] for p in svc.publications]
        assert final_windows == sorted(set(final_windows))
        assert svc.metric.head_window == final_windows[-1]
        # publication payloads are host numpy with the stamp schema
        rec = svc.publications[0]
        assert isinstance(rec["value"], np.ndarray)
        assert rec["degraded"] is False and rec["watermark"] is not None
        assert not np.isnan(float(np.asarray(merged)))
    assert svc.state == "stopped"
    svc.stop()  # idempotent


def test_flush_is_a_barrier_and_stop_rejects_new_events():
    svc = MetricService(_metric())
    _feed(svc, _batches(4))
    svc.flush()
    assert svc.processed == 4
    svc.stop()
    with pytest.raises(ServiceStoppedError):
        svc.submit(jnp.asarray(np.float32([0.5])), jnp.asarray(np.int32([1])),
                   event_time=np.array([1.0]))


def test_constructor_validation():
    with pytest.raises(ValueError, match="Windowed"):
        MetricService(Accuracy())
    with pytest.raises(ValueError, match="window roll"):
        MetricService(Windowed(Accuracy(), decay_half_life_s=5.0))
    with pytest.raises(ValueError, match="shed_policy"):
        MetricService(_metric(), shed_policy="tail_drop")
    with pytest.raises(ValueError, match="queue_size"):
        MetricService(_metric(), queue_size=0)
    svc = MetricService(_metric())
    with pytest.raises(ValueError, match="event_time"):
        svc.submit(jnp.asarray(np.float32([0.5])))
    svc.stop()


# ----------------------------------------------------- backpressure / shed
def test_drop_oldest_sheds_with_counter_and_health():
    svc = MetricService(_metric(), queue_size=2, shed_policy="drop_oldest")
    batches = _batches(5)
    try:
        # pin the worker: submit one batch and wait until it is IN
        # processing (queue drained), then hold the processing lock so the
        # next submissions pile into the bounded queue deterministically
        _feed(svc, batches[:1])
        deadline = time.monotonic() + 5.0
        while svc._queue.qsize() > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        with svc._proc_lock:
            # hand the worker ONE batch to hold in hand (it blocks on the
            # lock we own), so the 2-deep queue is deterministically ours —
            # the worker cannot steal an item mid-feed
            _feed(svc, batches[1:2])
            deadline = time.monotonic() + 5.0
            while svc._queue.qsize() > 0 and time.monotonic() < deadline:
                time.sleep(0.005)
            # fill the 2-deep queue + 1 overflow: a deterministic shed
            _feed(svc, batches[2:])
            assert svc.shed_events >= 1
            assert svc.health == "shedding"
        svc.flush()
        assert svc.processed + svc.shed_events == len(batches)
        snap = obs.counters_snapshot()
        label = svc.label
        assert snap["service_health"][label]["shed_events"] == svc.shed_events
    finally:
        svc.stop()


def test_block_policy_never_sheds():
    svc = MetricService(_metric(), queue_size=2, shed_policy="block")
    _feed(svc, _batches(6))
    svc.flush()
    assert svc.shed_events == 0 and svc.processed == 6
    svc.stop()


# ------------------------------------------------------- degrade over stall
def test_degraded_publish_under_persistent_sync_drop():
    guard = SyncGuard(deadline_s=1.0, max_retries=1, backoff_s=0.01, policy="degrade")
    metric = _metric(dist_sync_fn=gather_all_arrays)
    schedule = [faults.FaultSpec(kind="drop", rate=1.0, times=10_000, site="host_gather")]
    with faults.ChaosInjector(schedule, seed=0):
        svc = MetricService(metric, guard=guard)
        _feed(svc, _batches(6))
        svc.flush()
        svc.finalize()
        svc.stop()
    assert svc.publications, "windows closed but nothing published"
    assert all(p["degraded"] for p in svc.publications)
    assert svc.health == "degraded"
    snap = obs.counters_snapshot()
    assert snap["service_health"][svc.label]["state"] == "degraded"


# ------------------------------------------------------------ chaos: ingest
def test_ingest_stall_and_clock_skew_faults_apply():
    schedule = [
        faults.FaultSpec(kind="ingest_stall", call=0, times=1, duration_s=0.15,
                         site=INGEST_SITE),
        faults.FaultSpec(kind="clock_skew", call=1, times=1, skew_s=100.0,
                         site=INGEST_SITE),
    ]
    with faults.ChaosInjector(schedule, seed=0) as inj:
        svc = MetricService(_metric())
        start = time.perf_counter()
        _feed(svc, _batches(2))
        svc.flush()
        elapsed = time.perf_counter() - start
        svc.stop()
    assert inj.injected["ingest_stall"] == 1
    assert inj.injected["clock_skew"] == 1
    assert elapsed >= 0.15  # the stall really slept the worker
    # batch 1's times (~5..10s) skewed +100s -> watermark jumped past 100
    assert svc.metric.watermark > 100.0


def test_late_burst_routes_to_drop_path():
    schedule = [
        faults.FaultSpec(kind="late_burst", call=3, times=1, skew_s=50.0, site=INGEST_SITE),
    ]
    before = obs.COUNTERS.slab_dropped_samples
    with faults.ChaosInjector(schedule, seed=0):
        svc = MetricService(_metric())
        batches = _batches(5)
        _feed(svc, batches)
        svc.flush()
        svc.stop()
    # batch 3 (times ~15..20) shifted -50s: far beyond the 10s lateness
    assert svc.metric.dropped_samples == len(batches[3][0])
    assert obs.COUNTERS.slab_dropped_samples - before == svc.metric.dropped_samples


# ------------------------------------------------- preempt + restore + replay
def test_mid_window_preempt_snapshot_restore_replays_idempotently():
    batches = _batches(12, seed=3)

    # the uninterrupted truth
    plain = MetricService(_metric())
    _feed(plain, batches)
    truth = np.asarray(plain.finalize())
    truth_windows = {p["window"]: p["value"] for p in plain.publications}
    plain.stop()

    schedule = [faults.FaultSpec(kind="preempt", call=6, times=1, site=INGEST_SITE)]
    with faults.ChaosInjector(schedule, seed=0):
        svc = MetricService(_metric())
        preempted = False
        try:
            _feed(svc, batches)
            svc.flush()
        except (ServiceStoppedError, PreemptionError):
            preempted = True
        assert preempted
        assert svc.state == "preempted"
        assert isinstance(svc.error, PreemptionError)
        with pytest.raises(ServiceStoppedError):
            svc.submit(jnp.asarray(batches[0][1]), jnp.asarray(batches[0][2]),
                       event_time=batches[0][0])
        snapshot = svc.snapshot()
        assert snapshot["processed"] == 6  # the in-flight batch was NOT applied
        early_pubs = {p["window"]: p["value"] for p in svc.publications}

        restored = MetricService(_metric())
        restored.restore(snapshot)
        # replay from BEFORE the snapshot: already-folded steps must no-op
        _feed(restored, batches, start=4)
        resumed = np.asarray(restored.finalize())
        restored.stop()

    np.testing.assert_array_equal(resumed, truth)
    late_pubs = {p["window"]: p["value"] for p in restored.publications}
    assert set(early_pubs) | set(late_pubs) == set(truth_windows)
    assert not set(early_pubs) & set(late_pubs)  # no window published twice
    for w, value in {**early_pubs, **late_pubs}.items():
        np.testing.assert_array_equal(value, truth_windows[w], err_msg=str(w))
    assert restored.metric.dropped_samples == plain.metric.dropped_samples


def test_last_snapshot_refreshes_on_publish():
    svc = MetricService(_metric())
    assert svc.last_snapshot is None
    _feed(svc, _batches(8))
    svc.flush()
    svc.stop()
    assert svc.last_snapshot is not None
    assert svc.last_snapshot["processed"] >= 1
    assert "metric" in svc.last_snapshot


# ------------------------------------------------------------------- soak
def _soak(n_batches):
    rng = np.random.RandomState(42)
    faults_before = dict(obs.COUNTERS.faults)
    svc = MetricService(_metric(), queue_size=16)
    wm = None
    expected_events = {}
    dropped = 0
    for i in range(n_batches):
        times = i * 4.0 + rng.uniform(-12.0, 4.0, 16)
        preds = rng.rand(16).astype(np.float32)
        target = rng.randint(0, 2, 16).astype(np.int32)
        svc.submit(jnp.asarray(preds), jnp.asarray(target), event_time=times, seq=i)
        wm = times.max() if wm is None else max(wm, times.max())
        head = int(np.floor(wm / 10.0))
        w = np.floor_divide(times, 10.0).astype(int)
        ok = ((w + 1) * 10.0 + 10.0 > wm) & (w > head - 4)
        dropped += int((~ok).sum())
        for j in np.nonzero(ok)[0]:
            expected_events.setdefault(int(w[j]), []).append((preds[j], target[j]))
    svc.finalize()
    svc.stop()
    # bit-exact per published window vs fresh metrics over the oracle routing
    for p in svc.publications:
        pairs = expected_events.get(p["window"], [])
        if not pairs:
            assert np.isnan(float(p["value"]))
            continue
        fresh = Accuracy()
        fresh.update(
            jnp.asarray(np.array([x for x, _ in pairs], np.float32)),
            jnp.asarray(np.array([y for _, y in pairs], np.int32)),
        )
        np.testing.assert_array_equal(p["value"], np.asarray(fresh.compute()),
                                      err_msg=str(p["window"]))
    assert svc.metric.dropped_samples == dropped
    # no fault evidence accrued during the clean soak (counters are
    # process-wide and record unconditionally, so compare deltas)
    assert obs.COUNTERS.faults == faults_before


def test_soak_smoke():
    """The tier-1 soak smoke: a short randomized stream through the real
    background loop, bit-exact per published window vs the oracle router."""
    _soak(12)


@pytest.mark.soak
@pytest.mark.slow
def test_soak_long():
    """The full soak (excluded from tier-1 by the slow marker; select with
    ``-m soak``)."""
    _soak(120)


# ---------------------------------------------------- gauge-label identity
def test_unnamed_services_get_distinct_auto_indexed_labels():
    """Two services over the same inner metric must not overwrite each
    other's gauges: unnamed instances auto-index their label, and both
    service_health and the publish pipeline's deferred_depth key on it."""
    a = MetricService(_metric())
    b = MetricService(_metric())
    try:
        assert a.label != b.label
        assert a.label.startswith("MetricService(Accuracy)#")
        snap = obs.counters_snapshot()
        assert a.label in snap["service_health"]
        assert b.label in snap["service_health"]
    finally:
        a.stop()
        b.stop()


def test_named_services_thread_label_through_both_gauges():
    obs.enable()
    obs.reset()
    try:
        with MetricService(_metric(dist_sync_fn=gather_all_arrays), name="svc-A") as a, \
                MetricService(_metric(dist_sync_fn=gather_all_arrays), name="svc-B") as b:
            for svc in (a, b):
                _feed(svc, _batches(8))
                svc.flush()
        snap = obs.counters_snapshot()
    finally:
        obs.disable()
    # per-service health entries, no collision
    assert snap["service_health"]["svc-A"]["published"] >= 1
    assert snap["service_health"]["svc-B"]["published"] >= 1
    # per-service publish-pipeline depth gauges, no collision
    assert "svc-A" in snap["deferred_depth"]
    assert "svc-B" in snap["deferred_depth"]


def test_replayed_steps_counts_watermark_noops():
    batches = _batches(6)
    svc = MetricService(_metric())
    _feed(svc, batches)
    svc.flush()
    snapshot = svc.snapshot()
    restored = MetricService(_metric())
    restored.restore(snapshot)
    _feed(restored, batches)  # full replay: every step below the watermark
    restored.flush()
    assert restored.replayed_steps == len(batches)
    restored.stop()
    svc.stop()


def test_watermark_jump_publishes_expiring_windows_before_the_roll():
    """A sparse stream can jump the watermark several windows in one batch
    (a fleet shard sees 1/N of the traffic): windows the jump expires from
    the ring must be published BEFORE their slots recycle — never silently
    lost."""
    svc = MetricService(_metric())
    rng = np.random.RandomState(11)
    # windows 0 and 1 get events, then the stream jumps to window ~8: both
    # early windows leave the 4-slot ring in one roll
    for base in (2.0, 12.0):
        svc.submit(jnp.asarray(rng.rand(4).astype(np.float32)),
                   jnp.asarray(rng.randint(0, 2, 4).astype(np.int32)),
                   event_time=np.full(4, base))
    svc.flush()
    assert [p["window"] for p in svc.publications] == []  # nothing closed yet
    svc.submit(jnp.asarray(rng.rand(4).astype(np.float32)),
               jnp.asarray(rng.randint(0, 2, 4).astype(np.int32)),
               event_time=np.full(4, 85.0))
    svc.flush()
    published = [p["window"] for p in svc.publications]
    assert published[:2] == [0, 1], f"expiring windows lost to the jump: {published}"
    for p in svc.publications[:2]:
        assert not np.isnan(float(np.asarray(p["value"])))
    svc.stop()


# ------------------------------------------------- deferred publish stage
def test_deferred_publish_matches_synchronous_stage():
    """The deferred stage snapshots the close-point state, so every published
    record — values, merged view, watermark, drop counts — is bit-identical
    to the synchronous stage's over the same stream."""
    batches = _batches(12, seed=4)
    runs = {}
    for deferred in (False, True):
        svc = MetricService(
            _metric(dist_sync_fn=gather_all_arrays), deferred_publish=deferred
        )
        with svc:
            _feed(svc, batches)
            svc.flush()
            merged = np.asarray(svc.finalize())
        runs[deferred] = (svc.publications, merged)
    sync_pubs, sync_merged = runs[False]
    defer_pubs, defer_merged = runs[True]
    assert [p["window"] for p in defer_pubs] == [p["window"] for p in sync_pubs]
    for a, b in zip(defer_pubs, sync_pubs):
        for key in ("value", "merged", "degraded", "watermark", "dropped_samples"):
            assert np.array_equal(np.asarray(a[key]), np.asarray(b[key]), equal_nan=False), key
    assert np.array_equal(defer_merged, sync_merged)


def test_flush_is_a_barrier_over_the_publish_pipeline():
    """After ``flush`` every window the ingested events closed has a landed
    publication — the deferred stage must not leave records in flight."""
    batches = _batches(10, seed=5)
    with MetricService(_metric(dist_sync_fn=gather_all_arrays)) as svc:
        _feed(svc, batches)
        svc.flush()
        windows = [p["window"] for p in svc.publications]
        assert windows == sorted(windows)
        assert len(windows) >= 2  # the stream closed several windows
        assert svc.last_snapshot is not None
        assert svc.last_snapshot["published_through"] == windows[-1]
        svc.finalize()


def test_publish_pipeline_depth_gauge():
    """The service's deferred publish pipeline reports its depth through the
    per-label ``deferred_depth`` gauge: a slow publish-time sync backs the
    pipeline up (max >= 1) and a flushed service reads depth 0."""
    from metrics_tpu.parallel.sync import packable_gather

    @packable_gather
    def slow_gather(value):
        time.sleep(0.05)
        return [value]

    batches = _batches(10, seed=8)
    obs.enable()
    obs.reset()
    try:
        with MetricService(_metric(dist_sync_fn=slow_gather), label="svc-depth") as svc:
            _feed(svc, batches)
            svc.flush()
        snap = obs.counters_snapshot()
    finally:
        obs.disable()
    depth = snap["deferred_depth"]["svc-depth"]
    assert depth["max"] >= 1  # the pipeline actually ran deep
    assert depth["current"] == 0  # and the flush barrier drained it


def test_stop_with_deep_publish_pipeline_leaves_no_pending_handles():
    """The deterministic-shutdown satellite: stopping a service whose
    publish pipeline is several windows deep drains every in-flight publish
    — no pending handles, every closed window published, background plane
    empty."""
    from metrics_tpu.parallel.deferred import drain_host_plane
    from metrics_tpu.parallel.sync import packable_gather

    @packable_gather
    def slow_gather(value):
        time.sleep(0.05)
        return [value]

    batches = _batches(12, seed=9)
    svc = MetricService(_metric(dist_sync_fn=slow_gather))
    _feed(svc, batches)
    svc.stop()
    assert svc._pending_publishes == []  # no pending handles after stop
    windows = [p["window"] for p in svc.publications]
    assert windows == sorted(windows) and len(windows) >= 2
    assert svc.last_snapshot is not None
    assert svc.last_snapshot["published_through"] == windows[-1]
    start = time.perf_counter()
    drain_host_plane()  # the plane itself is idle too
    assert time.perf_counter() - start < 1.0


def test_publish_emits_per_window_spans():
    """Every publish emits one ``service.publish`` span stamped window=,
    degraded=, queue_depth, and deferred= (the per-window Perfetto view)."""
    batches = _batches(10, seed=6)
    obs.enable()
    try:
        import metrics_tpu.observability.trace as obs_trace

        obs_trace.clear()
        with MetricService(_metric(dist_sync_fn=gather_all_arrays)) as svc:
            _feed(svc, batches)
            svc.flush()
            svc.finalize()
            published = [p["window"] for p in svc.publications]
        spans = [r for r in obs.records() if r.name == "service.publish"]
    finally:
        obs.disable()
    assert len(spans) == len(published)
    assert [s.attrs["window"] for s in spans] == published
    for s in spans:
        assert s.attrs["service"] == svc.label  # label threads into the span
        assert s.attrs["degraded"] in ("yes", "no")
        assert s.attrs["deferred"] == "yes"
        assert isinstance(s.attrs["queue_depth"], int)


def test_deferred_publish_degrades_and_stamps_span_under_drop():
    batches = _batches(8, seed=7)
    guard = SyncGuard(deadline_s=0.5, max_retries=1, backoff_s=0.01, policy="degrade")
    obs.enable()
    try:
        import metrics_tpu.observability.trace as obs_trace

        obs_trace.clear()
        with faults.ChaosInjector(
            [faults.FaultSpec(kind="drop", rate=1.0, times=100_000)], seed=0
        ):
            with MetricService(_metric(dist_sync_fn=gather_all_arrays), guard=guard) as svc:
                _feed(svc, batches)
                svc.flush()
                svc.finalize()
                assert svc.publications and all(p["degraded"] for p in svc.publications)
                assert svc.health == "degraded"
        spans = [r for r in obs.records() if r.name == "service.publish"]
    finally:
        obs.disable()
    assert spans and all(s.attrs["degraded"] == "yes" for s in spans)


# --------------------------------------------------- cross-rank agreed clock
def _agreed_pair(deadline_s=30.0, guard_deadline_s=1.0, lateness=10.0):
    from metrics_tpu import WatermarkAgreement
    from metrics_tpu.parallel.sync import SyncGuard, gather_all_arrays

    agreement = WatermarkAgreement(deadline_s=deadline_s)
    guard = SyncGuard(deadline_s=guard_deadline_s, max_retries=1,
                      backoff_s=0.02, policy="degrade")

    def build(rank):
        metric = Windowed(
            Accuracy(), window_s=10.0, num_windows=8, allowed_lateness_s=lateness,
            dist_sync_fn=gather_all_arrays, agreement=agreement, rank=rank,
        )
        return MetricService(metric, queue_size=8, guard=guard, fault_rank=rank)

    return agreement, build


def test_publish_gates_on_agreed_watermark():
    """No window publishes before every participating rank's watermark
    passes it: a rank far ahead publishes nothing while its peer lags, then
    everything the agreed clock closed once the peer catches up."""
    agreement, build = _agreed_pair()
    fast, slow = build(0), build(1)
    try:
        preds = jnp.asarray(np.float32([0.9, 0.8]))
        target = jnp.asarray(np.int32([1, 1]))
        fast.submit(preds, target, event_time=np.array([5.0, 55.0]), seq=0)
        fast.flush(10)
        # the fast rank's LOCAL clock closed windows 0..2, but the peer has
        # not spoken: the agreement holds every window open (and the t=5.0
        # event ROUTES into window 0 — nothing is late before agreement)
        assert fast.publications == []
        assert np.asarray(fast.metric._current_state()["windowed_rows"])[0] == 1.0
        slow.submit(preds, target, event_time=np.array([3.0, 52.0]), seq=0)
        slow.flush(10)
        fast.submit(preds, target, event_time=np.array([56.0, 57.0]), seq=1)
        fast.flush(10)
        # agreed = min(57, 52) = 52: windows with end + lateness <= 52 are
        # closed -> windows 0..3; the fast rank's resident ring starts at
        # its origin 0, so it publishes 0..3 (1 and 2 as empty windows)
        assert agreement.agreed() == 52.0
        assert [p["window"] for p in fast.publications] == [0, 1, 2, 3]
        assert fast.publications[0]["degraded"] is False
        assert fast.publications[0]["agreed_watermark"] == 52.0
        assert float(fast.publications[0]["value"]) == 1.0
    finally:
        fast.stop(10)
        slow.stop(10)


def test_finalize_under_guard_deadline_with_stalled_peer():
    """The shutdown satellite: a stalled peer (or a dead watermark exchange)
    must not hang finalize/stop — the force-publish waits at most the guard
    deadline, then degrades to local-only publish with degraded=True (and
    the agreement's own deadline marks the straggler)."""
    import metrics_tpu.observability as obs

    agreement, build = _agreed_pair(deadline_s=0.6, guard_deadline_s=0.8)
    agreement.register("stalled-peer")  # a rank that never reports
    service = build(0)
    before = obs.COUNTERS.wm_stragglers
    try:
        service.submit(jnp.asarray(np.float32([0.9, 0.8])), jnp.asarray(np.int32([1, 1])),
                       event_time=np.array([5.0, 25.0]), seq=0)
        start = time.monotonic()
        merged = service.finalize(10.0)
        elapsed = time.monotonic() - start
        # bounded: the wait is the guard deadline, not the 10s budget (and
        # certainly not forever — the pre-fix failure mode)
        assert elapsed < 5.0
        assert [p["window"] for p in service.publications] == [0, 1, 2]
        assert all(p["degraded"] for p in service.publications)
        assert float(merged) == 1.0
        assert obs.COUNTERS.wm_stragglers - before >= 1
        assert "stalled-peer" in [str(r) for r in agreement.excluded()]
    finally:
        service.stop(10)


def test_finalize_healthy_agreement_publishes_undegraded():
    """A HEALTHY shutdown under an agreement: once every rank's final
    report is in, finalize's bounded wait succeeds (the agreed clock
    catches each rank's local watermark — it can never close the HEAD
    window, which is exactly what finalize force-publishes) — records
    publish agreement-ordered with NO degraded stamp and no guard-deadline
    burn."""
    agreement, build = _agreed_pair(guard_deadline_s=5.0)
    a, b = build(0), build(1)
    preds = jnp.asarray(np.float32([0.9, 0.8]))
    target = jnp.asarray(np.int32([1, 1]))
    try:
        a.submit(preds, target, event_time=np.array([5.0, 55.0]), seq=0)
        b.submit(preds, target, event_time=np.array([3.0, 55.0]), seq=0)
        a.flush(10)
        b.flush(10)
        start = time.monotonic()
        a.finalize(10.0)
        b.finalize(10.0)
        elapsed = time.monotonic() - start
        # the pre-fix failure: waiting for the agreed clock to CLOSE the
        # head window can never succeed, so every healthy finalize burned
        # the whole guard deadline and stamped all force-publishes degraded
        assert elapsed < 4.0
        assert a.publications and b.publications
        assert not any(p["degraded"] for p in a.publications + b.publications)
        assert [p["window"] for p in a.publications] == sorted(
            {p["window"] for p in a.publications}
        )
    finally:
        a.stop(10)
        b.stop(10)


def test_clock_skew_addressable_per_rank():
    """FaultSpec(rank=) addresses one rank of a multi-rank stream: only the
    skewed rank's event times shift."""
    from metrics_tpu.parallel import faults

    agreement, build = _agreed_pair()
    schedule = [
        faults.FaultSpec(kind="clock_skew", rank=1, rate=1.0, times=10**6,
                         skew_s=30.0, site="service.ingest"),
    ]
    with faults.ChaosInjector(schedule, seed=0) as injector:
        honest, skewed = build(0), build(1)
        try:
            preds = jnp.asarray(np.float32([0.9]))
            target = jnp.asarray(np.int32([1]))
            honest.submit(preds, target, event_time=np.array([5.0]), seq=0)
            skewed.submit(preds, target, event_time=np.array([5.0]), seq=0)
            honest.flush(10)
            skewed.flush(10)
            assert honest.metric.watermark == 5.0
            assert skewed.metric.watermark == 35.0
            assert injector.injected["clock_skew"] == 1
        finally:
            honest.stop(10)
            skewed.stop(10)
