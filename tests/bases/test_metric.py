"""Core Metric runtime semantics (mirrors reference tests/bases/test_metric.py:29-239)."""
import pickle

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import Metric
from tests.helpers.testers import DummyListMetric, DummyMetric, DummyMetricSum


def test_inherit():
    DummyMetric()


def test_add_state():
    a = DummyMetric()

    a.add_state("a", jnp.asarray(0.0), "sum")
    assert np.asarray(a._defaults["a"]) == 0

    a.add_state("b", jnp.asarray(0.0), "mean")
    a.add_state("c", jnp.asarray(0.0), "cat")
    a.add_state("d", [], None)

    with pytest.raises(ValueError):
        a.add_state("e", jnp.asarray(0.0), "xyz")

    with pytest.raises(ValueError):
        a.add_state("e", jnp.asarray(0.0), 42)

    with pytest.raises(ValueError):
        a.add_state("e", "abc", "sum")

    with pytest.raises(ValueError):
        a.add_state("e", [jnp.asarray(0.0)], "sum")

    # custom reduce functions are accepted
    a.add_state("e", jnp.asarray(0.0), lambda x: jnp.sum(x, axis=0))


def test_add_state_persistent():
    a = DummyMetric()
    a.add_state("a", jnp.asarray(0.0), "sum", persistent=True)
    assert "a" in a.state_dict()

    a.add_state("b", jnp.asarray(0.0), "sum", persistent=False)
    assert "b" not in a.state_dict()


def test_reset():
    class A(DummyMetric):
        pass

    class B(DummyListMetric):
        pass

    a = A()
    assert float(a.x) == 0
    a.x = jnp.asarray(5.0)
    a.reset()
    assert float(a.x) == 0

    b = B()
    assert isinstance(b.x, list) and len(b.x) == 0
    b.x = jnp.asarray(5.0)
    b.reset()
    assert isinstance(b.x, list) and len(b.x) == 0


def test_update():
    class A(DummyMetric):

        def update(self, x):
            self.x = self.x + x

    a = A()
    assert float(a.x) == 0
    assert a._computed is None
    a.update(1)
    assert a._computed is None
    assert float(a.x) == 1
    a.update(2)
    assert float(a.x) == 3
    assert a._computed is None


def test_compute():
    class A(DummyMetric):

        def update(self, x):
            self.x = self.x + x

        def compute(self):
            return self.x

    a = A()
    assert float(a.compute()) == 0
    assert float(a.x) == 0
    a.update(1)
    assert a._computed is None
    assert float(a.compute()) == 1
    assert float(a._computed) == 1
    a.update(2)
    assert a._computed is None
    assert float(a.compute()) == 3
    assert float(a._computed) == 3

    # called without update, the cached result is returned
    _ = a.compute()
    assert float(a._computed) == 3


def test_hash():
    metric_1 = DummyMetric()
    metric_2 = DummyMetric()
    assert hash(metric_1) != hash(metric_2)

    metric_1 = DummyListMetric()
    metric_2 = DummyListMetric()
    assert hash(metric_1) != hash(metric_2)
    assert isinstance(metric_1.x, list) and len(metric_1.x) == 0
    metric_1.x.append(jnp.asarray(5.0))
    hash_1 = hash(metric_1)
    metric_1.x.append(jnp.asarray(10.0))
    hash_2 = hash(metric_1)
    assert hash_1 != hash_2


def test_forward():
    class A(DummyMetric):

        def update(self, x):
            self.x = self.x + x

        def compute(self):
            return self.x

    a = A()
    assert float(a(5)) == 5
    assert float(a._forward_cache) == 5

    assert float(a(8)) == 8
    assert float(a._forward_cache) == 8

    assert float(a.compute()) == 13


def test_forward_compute_on_step_false():
    class A(DummyMetric):

        def update(self, x):
            self.x = self.x + x

        def compute(self):
            return self.x

    a = A(compute_on_step=False)
    assert a(5) is None
    assert a(8) is None
    assert float(a.compute()) == 13


def test_pickle():
    a = DummyMetricSum()
    a.update(1)

    metric_pickled = pickle.dumps(a)
    metric_loaded = pickle.loads(metric_pickled)
    assert float(metric_loaded.compute()) == 1

    metric_loaded.update(5)
    assert float(metric_loaded.compute()) == 6


def test_state_dict():
    """Persistent states round-trip through state_dict/load_state_dict."""

    class A(DummyMetric):

        def __init__(self):
            super().__init__()
            self.add_state("persistent_state", jnp.asarray(0.0), "sum", persistent=True)

        def update(self, x):
            self.persistent_state = self.persistent_state + x

        def compute(self):
            return self.persistent_state

    a = A()
    a.update(10.0)
    sd = a.state_dict()
    assert float(sd["persistent_state"]) == 10

    b = A()
    b.load_state_dict(sd)
    assert float(b.compute()) == 10


def test_clone_is_independent():
    a = DummyMetricSum()
    a.update(5)
    b = a.clone()
    b.update(3)
    assert float(a.compute()) == 5
    assert float(b.compute()) == 8


def test_device_and_dtype():
    """States can be placed on devices/shardings and cast; reset preserves both."""
    import jax

    a = DummyMetricSum()
    a.update(3.0)
    a.device_put(jax.devices()[0])
    assert a.x.devices() == {jax.devices()[0]}

    a.astype(jnp.bfloat16)
    assert a.x.dtype == jnp.bfloat16
    a.reset()
    assert a.x.dtype == jnp.bfloat16
    assert a.x.devices() == {jax.devices()[0]}


def test_pure_api_roundtrip():
    """init/update/compute/merge pure functions agree with the stateful API."""

    class SumMetric(Metric):

        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state("x", jnp.asarray(0.0), dist_reduce_fx="sum")

        def update(self, x):
            self.x = self.x + x

        def compute(self):
            return self.x

    a = SumMetric()
    pure = a.pure()
    state = pure.init()
    state = pure.update(state, 2.0)
    state = pure.update(state, 3.0)
    assert float(pure.compute(state)) == 5.0

    s1 = pure.update(pure.init(), 2.0)
    s2 = pure.update(pure.init(), 3.0)
    merged = pure.merge(s1, s2)
    assert float(pure.compute(merged)) == 5.0

    # the stateful instance was untouched by the pure calls
    assert float(a.x) == 0.0


def test_int32_accumulator_overflow_warns():
    """Counts near 2^31 must warn at compute time instead of silently wrapping."""
    import warnings

    class CountMetric(Metric):

        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state("total", jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")

        def update(self, n):
            self.total = self.total + n

        def compute(self):
            return self.total

    # the check is a host-side bound on elements processed (never a device
    # readback — those dominate wall-clock through remote-device tunnels);
    # custom metrics that add more than 1 per element use note_count
    m = CountMetric()
    m.update(jnp.asarray(1, dtype=jnp.int32))
    m.note_count(2**30)
    with pytest.warns(UserWarning, match="silently wrap"):
        m.compute()

    # library-style per-row counting warns via argument sizes alone
    class SmallThreshold(CountMetric):
        _OVERFLOW_WARN_THRESHOLD = 64

        def update(self, n):
            self.total = self.total + jnp.sum(n)

    m3 = SmallThreshold()
    m3.update(jnp.ones((65,), jnp.int32))
    with pytest.warns(UserWarning, match="silently wrap"):
        m3.compute()
    # reset clears the bound: no further warning
    m3.reset()
    m3.update(jnp.ones((3,), jnp.int32))
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")
        m3.compute()

    # below the threshold: no warning on any compute
    m2 = CountMetric()
    m2.update(jnp.asarray(7, dtype=jnp.int32))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert int(m2.compute()) == 7
        m2.update(jnp.asarray(1, dtype=jnp.int32))
        assert int(m2.compute()) == 8


def test_forward_does_not_swallow_genuine_update_bugs():
    """A real TypeError inside update must propagate, not demote to eager."""

    class BuggyMetric(Metric):

        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state("x", jnp.asarray(0.0), dist_reduce_fx="sum")

        def update(self, x):
            self.x = self.x + x
            len(3)  # TypeError: object of type 'int' has no len()

        def compute(self):
            return self.x

    m = BuggyMetric()
    with pytest.raises(TypeError):
        m(jnp.asarray(1.0))
    assert not m._jit_failed


def test_forward_tracing_fallback_warns():
    """A value-dependent update falls back to eager with a loud warning."""

    class EagerOnlyMetric(Metric):

        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state("x", jnp.asarray(0.0), dist_reduce_fx="sum")

        def update(self, x):
            self.x = self.x + float(x)  # forces concretization under tracing

        def compute(self):
            return self.x

    m = EagerOnlyMetric(jit=True)
    with pytest.warns(UserWarning, match="cannot be jit-compiled"):
        out = m(jnp.asarray(2.0))
    assert m._jit_failed
    assert float(out) == 2.0
    assert float(m.compute()) == 2.0


def test_fused_jit_step_compiles_and_accumulates():
    """Explicit jit=True coverage: the fused step compiles once and matches eager."""

    class SumMetric(Metric):

        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state("x", jnp.asarray(0.0), dist_reduce_fx="sum")

        def update(self, x):
            self.x = self.x + x

        def compute(self):
            return self.x

    m = SumMetric(jit=True)
    assert float(m(jnp.asarray(2.0))) == 2.0
    # the fully-fused step (update+merge+batch value in one dispatch) serves
    # the default forward; the plain step exists only if compute can't trace
    assert (m._jitted_step_fc is not None or m._jitted_step is not None) and not m._jit_failed
    assert float(m(jnp.asarray(3.0))) == 3.0
    assert float(m.compute()) == 5.0


def test_set_default_jit():
    """The process-wide default applies to jit=None metrics; explicit wins."""
    from metrics_tpu import set_default_jit

    class SumMetric(Metric):

        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state("x", jnp.asarray(0.0), dist_reduce_fx="sum")

        def update(self, x):
            self.x = self.x + x

        def compute(self):
            return self.x

    old = set_default_jit(False)
    try:
        assert not SumMetric()._jittable
        assert SumMetric(jit=True)._jittable  # explicit overrides the default
        set_default_jit(None)
        assert SumMetric()._jittable  # auto: fixed-shape states -> jittable
    finally:
        set_default_jit(old)


def test_profile_metric_helper():
    from metrics_tpu import Accuracy, profile_metric

    times = profile_metric(Accuracy(), jnp.array([1, 0, 1]), jnp.array([1, 1, 0]), iters=3, )
    assert set(times) == {"update_ms", "compute_ms"}
    assert all(v > 0 for v in times.values())


def test_jitted_step_sharing_rules():
    """Config-identical instances share one compiled step; different config or
    side-writing update/compute methods get private steps."""
    import metrics_tpu
    from metrics_tpu.core.metric import _traced_attr_writes

    old = metrics_tpu.set_default_jit(True)
    try:

        class CleanMetric(Metric):

            def __init__(self, scale=1.0, **kw):
                super().__init__(**kw)
                self.scale = scale
                self.add_state("x", jnp.asarray(0.0), dist_reduce_fx="sum")

            def update(self, v):
                self.x = self.x + v * self.scale

            def compute(self):
                return self.x

        a, b = CleanMetric(), CleanMetric()
        a(jnp.asarray(1.0)); b(jnp.asarray(2.0))
        assert a._jitted_step_fc is b._jitted_step_fc and a._jitted_step_fc is not None
        assert float(a.compute()) == 1.0 and float(b.compute()) == 2.0  # no state bleed

        c = CleanMetric(scale=3.0)
        c(jnp.asarray(1.0))
        assert c._jitted_step_fc is not a._jitted_step_fc  # different config
        assert float(c.compute()) == 3.0

        class SideWriting(CleanMetric):

            def update(self, v):
                self.seen = True  # non-state write -> must not share
                self.x = self.x + v

        assert _traced_attr_writes(SideWriting) is None or not (
            _traced_attr_writes(SideWriting) <= {"x"}
        )
        d, e = SideWriting(), SideWriting()
        d(jnp.asarray(1.0)); e(jnp.asarray(1.0))
        assert d._jitted_step_fc is not e._jitted_step_fc
        assert d.seen and e.seen  # the side write lands on each instance
    finally:
        metrics_tpu.set_default_jit(old)


# ------------------------------------------------------------ forward_batched


def test_forward_batched_matches_per_step_loop():
    """One-dispatch scan over stacked batches == the per-step forward loop,
    including per-batch values, the accumulated state, and the epoch value."""
    import metrics_tpu
    from metrics_tpu import Accuracy

    rng = np.random.RandomState(5)
    logits = rng.rand(10, 10, 5).astype(np.float32)
    probs = logits / logits.sum(-1, keepdims=True)
    target = rng.randint(0, 5, (10, 10)).astype(np.int32)

    old = metrics_tpu.set_default_jit(True)
    try:
        loop = Accuracy()
        loop_vals = [float(loop(jnp.asarray(probs[i]), jnp.asarray(target[i]))) for i in range(10)]

        batched = Accuracy()
        vals = batched.forward_batched(jnp.asarray(probs), jnp.asarray(target))
    finally:
        metrics_tpu.set_default_jit(old)
    assert vals.shape == (10,)
    np.testing.assert_allclose(np.asarray(vals), loop_vals, atol=1e-6)
    np.testing.assert_allclose(float(batched.compute()), float(loop.compute()), atol=1e-6)

    # epoch value was pre-seeded by the scan: compute() returned the cache
    assert batched._computed is not None

    # further updates invalidate the cache and keep accumulating correctly
    batched.update(jnp.asarray(probs[0]), jnp.asarray(target[0]))
    loop.update(jnp.asarray(probs[0]), jnp.asarray(target[0]))
    np.testing.assert_allclose(float(batched.compute()), float(loop.compute()), atol=1e-6)

    # a pre-seeded compute cache must NOT suppress the overflow warning
    batched.forward_batched(jnp.asarray(probs), jnp.asarray(target))
    batched.note_count(2**30)
    with pytest.warns(UserWarning, match="silently wrap"):
        batched.compute()

    # toggling compute_on_step between calls rebuilds the scan for the mode
    toggled = Accuracy()
    toggled.compute_on_step = False
    assert toggled.forward_batched(jnp.asarray(probs), jnp.asarray(target)) is None
    toggled.compute_on_step = True
    vals2 = toggled.forward_batched(jnp.asarray(probs), jnp.asarray(target))
    assert vals2.shape == (10,)
    np.testing.assert_allclose(np.asarray(vals2), loop_vals, atol=1e-6)


def test_forward_batched_compute_on_step_false_and_fallback():
    from metrics_tpu import Accuracy

    rng = np.random.RandomState(6)
    probs = rng.rand(4, 8, 3).astype(np.float32)
    probs /= probs.sum(-1, keepdims=True)
    target = rng.randint(0, 3, (4, 8)).astype(np.int32)

    import metrics_tpu

    old = metrics_tpu.set_default_jit(True)
    try:
        m = Accuracy(compute_on_step=False)
        assert m.forward_batched(jnp.asarray(probs), jnp.asarray(target)) is None
        expected = (probs.reshape(-1, 3).argmax(-1) == target.reshape(-1)).mean()
        np.testing.assert_allclose(float(m.compute()), expected, atol=1e-6)
    finally:
        metrics_tpu.set_default_jit(old)

    # eager fallback (jit disabled) produces the same stacked values
    m2 = Accuracy()
    vals = m2.forward_batched(jnp.asarray(probs), jnp.asarray(target))
    assert np.asarray(vals).shape == (4,)
    np.testing.assert_allclose(float(m2.compute()), expected, atol=1e-6)
