"""Device-time attribution layer: compile telemetry, devtime fencing, tables.

The contract under test, in order of importance:

1. Compile telemetry (``observability.compilemon``) captures ``jax.monitoring``
   compile events — a fresh jit compiles (events grow), a cache-hit replay
   does not — and the persistent compilation cache's hit/miss verdicts are
   counted when the cache is configured.
2. Span stamping: with compile monitoring on, every finished span carries
   ``compiled=yes/no``; a span enclosing a first (compiling) dispatch says
   yes with ``compile_ms`` > 0, a steady-state span says no. With it off,
   span attrs are untouched (the pre-existing contract).
3. Devtime fencing (``observability.devtime``) stamps fenced phase spans
   with ``device_ms``, folds them into the per-metric update/sync/compute
   table, and its phase schema stays in parity with the instrumented span
   vocabulary.
4. ``summarize()`` carries the new ``compile_ms`` / ``device_ms`` columns,
   and the disabled path stays a structural no-op (the singleton span).
5. The profiler-session parser recovers per-phase device totals from a
   Chrome/Perfetto JSON trace dir, and degrades to ``{}`` gracefully.
"""
import gzip
import json
import tempfile

import jax
import jax.numpy as jnp
import pytest

from metrics_tpu import Accuracy
from metrics_tpu import observability as obs
from metrics_tpu.observability import compilemon, devtime
from metrics_tpu.observability import trace as obs_trace
from metrics_tpu.parallel.sync import gather_all_arrays


@pytest.fixture(autouse=True)
def _clean_observability():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


# ------------------------------------------------------------ compile events
def test_compile_events_captured_under_jit_cache_hit_and_miss():
    """A fresh jit trace+compile grows the compile counters; replaying the
    compiled program (the in-memory executable cache hit) does not."""
    compilemon.enable()
    compilemon.reset()
    try:
        fn = jax.jit(lambda x: jnp.sin(x) * 3 + 1)
        fn(jnp.ones(17)).block_until_ready()  # miss: trace + lower + compile
        first = compilemon.snapshot()
        assert first["compile_events"] >= 1
        assert first["backend_compile_ms"] > 0
        assert first["trace_ms"] > 0

        fn(jnp.ones(17)).block_until_ready()  # hit: straight to the executable
        second = compilemon.snapshot()
        assert second["compile_events"] == first["compile_events"]
        assert second["backend_compile_ms"] == first["backend_compile_ms"]
    finally:
        compilemon.disable()


def test_persistent_cache_hit_miss_counted():
    """With the persistent compilation cache configured, the first compile
    records a cache miss and a post-``clear_caches`` recompile records a hit
    (the executable comes back from disk)."""
    saved = (
        jax.config.jax_compilation_cache_dir,
        jax.config.jax_persistent_cache_min_compile_time_secs,
        jax.config.jax_persistent_cache_min_entry_size_bytes,
    )
    cache_dir = tempfile.mkdtemp(prefix="mtpu_compile_cache_")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    compilemon.enable()
    compilemon.reset()
    try:
        jax.jit(lambda x: jnp.tan(x) * 19)(jnp.ones(29)).block_until_ready()
        miss_snap = compilemon.snapshot()
        assert miss_snap["compile_cache"]["misses"] >= 1

        jax.clear_caches()  # drop the in-memory executables, keep the disk cache
        jax.jit(lambda x: jnp.tan(x) * 19)(jnp.ones(29)).block_until_ready()
        hit_snap = compilemon.snapshot()
        assert hit_snap["compile_cache"]["hits"] >= 1
    finally:
        compilemon.disable()
        jax.config.update("jax_compilation_cache_dir", saved[0])
        jax.config.update("jax_persistent_cache_min_compile_time_secs", saved[1])
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", saved[2])


def test_spans_stamped_compiled_yes_no():
    """A span around a first (compiling) dispatch carries compiled=yes +
    compile_ms; a span around the cached replay carries compiled=no."""
    obs.enable(compile_events=True)
    fn = jax.jit(lambda x: jnp.exp(x) - 5)
    with obs.span("first.dispatch"):
        fn(jnp.ones(23)).block_until_ready()
    with obs.span("steady.dispatch"):
        fn(jnp.ones(23)).block_until_ready()
    by_name = {r.name: r for r in obs.records()}
    first = by_name["first.dispatch"].attrs
    assert first["compiled"] == "yes"
    assert first["compile_ms"] > 0
    assert by_name["steady.dispatch"].attrs["compiled"] == "no"


def test_spans_unstamped_without_compile_monitoring():
    """Plain tracing leaves attrs exactly as passed (the PR 2 contract)."""
    obs.enable()
    fn = jax.jit(lambda x: jnp.log1p(x) * 7)
    with obs.span("plain", {"k": "v"}):
        fn(jnp.ones(19)).block_until_ready()
    (rec,) = obs.records()
    assert rec.attrs == {"k": "v"}


# ------------------------------------------------------------ devtime fencing
def _fenced_metric_scenario():
    """One update + one synced compute with fencing on; returns the records."""
    obs.enable(device_time=True)
    metric = Accuracy(dist_sync_fn=gather_all_arrays)
    metric.update(jnp.array([1, 0, 1, 1]), jnp.array([1, 1, 0, 1]))
    metric.compute()
    return obs.records()


def test_fence_stamps_device_ms_on_phase_spans():
    records = _fenced_metric_scenario()
    stamped = {r.name for r in records if r.attrs and "device_ms" in r.attrs}
    assert {"metric.update", "metric.sync_state", "metric.compute"} <= stamped
    for rec in records:
        if rec.attrs and "device_ms" in rec.attrs:
            assert rec.attrs["device_ms"] >= 0
            # the fenced wait is part of the span: device_ms cannot exceed
            # the span's own wall time
            assert rec.attrs["device_ms"] <= rec.duration_ms + 1e-6


def test_device_time_table_per_metric_phases():
    records = _fenced_metric_scenario()
    table = devtime.device_time_table(records)
    assert {"update", "sync", "compute"} <= set(table["Accuracy"])
    assert all(ms >= 0 for ms in table["Accuracy"].values())
    # every table column is a known phase of the span vocabulary
    known_phases = set(devtime.PHASE_OF_SPAN.values())
    for row in table.values():
        assert set(row) <= known_phases


def test_devtime_schema_parity_with_span_names():
    """Every instrumented phase span that can fence has a table column —
    a new span name must be added to PHASE_OF_SPAN or it silently falls
    out of the attribution."""
    instrumented = {
        "metric.update",
        "metric.sync_state",
        "metric.compute",
        "metric.forward",
        "collection.group_update",
        "collection.fused_step",
        "collection.forward_batched",
        "collection.host_sync",
        "collection.step_sync",
        "collection.compute",
        "sharded.launch",
    }
    assert instrumented <= set(devtime.PHASE_OF_SPAN)
    assert set(devtime.PHASE_OF_SPAN.values()) == {
        "update", "sync", "compute", "forward", "engine"
    }


def test_fence_disabled_is_noop_and_span_singleton_preserved():
    # fencing off: spans record but carry no device_ms
    obs.enable()
    metric = Accuracy()
    metric.update(jnp.array([1, 0]), jnp.array([1, 1]))
    assert all(not (r.attrs and "device_ms" in r.attrs) for r in obs.records())
    obs.disable()
    obs.reset()
    # the zero-allocation disabled contract is untouched by the new layers
    assert obs.span("a") is obs.span("b")
    assert obs.span("a") is obs_trace._NULL_SPAN
    devtime.fence(jnp.ones(3))  # disabled: no span, no error, nothing recorded
    assert obs.records() == []


# ------------------------------------------------------------------ summarize
def test_summarize_gains_compile_and_device_columns():
    records = _fenced_metric_scenario()
    table = obs.summarize(records)
    for row in table.values():
        assert "compile_ms" in row and "device_ms" in row
    assert table["metric.update"]["device_ms"] >= 0
    # names without stamps keep zero-valued columns (stable schema)
    obs.reset()
    with obs.span("bare"):
        pass
    bare = obs.summarize()["bare"]
    assert bare["compile_ms"] == 0.0 and bare["device_ms"] == 0.0


# ------------------------------------------------- profiler-session parsing
def test_from_profiler_trace_parses_chrome_json(tmp_path):
    run_dir = tmp_path / "plugins" / "profile" / "run1"
    run_dir.mkdir(parents=True)
    events = {
        "traceEvents": [
            {"ph": "X", "name": "jit(step)/metric.sync/psum", "ts": 0, "dur": 1500.0},
            {"ph": "X", "name": "metric.sync", "ts": 10, "dur": 500.0},
            {"ph": "X", "name": "sharded.engine.ring/ppermute", "ts": 20, "dur": 2000.0},
            {"ph": "X", "name": "unrelated.kernel", "ts": 30, "dur": 9000.0},
            {"ph": "M", "name": "thread_name"},
        ]
    }
    with gzip.open(run_dir / "perfetto_trace.json.gz", "wt") as f:
        json.dump(events, f)
    totals = devtime.from_profiler_trace(str(tmp_path))
    assert totals["metric.sync"] == pytest.approx(2.0)  # 1500 + 500 us
    assert totals["sharded.engine"] == pytest.approx(2.0)
    assert "unrelated.kernel" not in totals


def test_from_profiler_trace_missing_dir_is_empty(tmp_path):
    assert devtime.from_profiler_trace(str(tmp_path / "nope")) == {}
    assert devtime.from_profiler_trace(str(tmp_path)) == {}
