"""MetricCollection semantics (mirrors reference tests/bases/test_collections.py:25-156)."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import MetricCollection
from tests.helpers.testers import DummyMetricDiff, DummyMetricSum


def test_metric_collection():
    m1 = DummyMetricSum()
    m2 = DummyMetricDiff()

    metric_collection = MetricCollection([m1, m2])

    # by default, the keys are the class names
    assert "DummyMetricSum" in metric_collection
    assert "DummyMetricDiff" in metric_collection

    # test correct initialization
    for name, metric in metric_collection.items():
        assert float(metric.x) == 0

    # argument filtering: each metric sees only its own kwargs
    metric_collection.update(x=jnp.asarray(10.0), y=jnp.asarray(20.0))
    assert float(metric_collection["DummyMetricSum"].x) == 10
    assert float(metric_collection["DummyMetricDiff"].x) == -20

    results = metric_collection.compute()
    assert float(results["DummyMetricSum"]) == 10
    assert float(results["DummyMetricDiff"]) == -20

    metric_collection.reset()
    for name, metric in metric_collection.items():
        assert float(metric.x) == 0


def test_device_put():
    import jax

    metric_collection = MetricCollection([DummyMetricSum(), DummyMetricDiff()])
    metric_collection.device_put(jax.devices()[0])
    for _, metric in metric_collection.items():
        assert metric.x.devices() == {jax.devices()[0]}


def test_metric_collection_wrong_input():
    m1 = DummyMetricSum()

    # not a Metric
    with pytest.raises(ValueError, match="is not an instance of"):
        MetricCollection({"metric": 5})

    with pytest.raises(ValueError, match="is not a instance of"):
        MetricCollection([5])

    # same name twice
    with pytest.raises(ValueError, match="Encountered two metrics both named"):
        MetricCollection([m1, m1.clone()])

    with pytest.raises(ValueError, match="Unknown input to MetricCollection."):
        MetricCollection(m1)


def test_metric_collection_args_kwargs():
    m1 = DummyMetricSum()
    m2 = DummyMetricDiff()

    metric_collection = MetricCollection([m1, m2])

    # kwargs are filtered per update signature
    metric_collection.update(x=jnp.asarray(10.0), y=jnp.asarray(20.0))
    assert float(metric_collection["DummyMetricSum"].x) == 10
    assert float(metric_collection["DummyMetricDiff"].x) == -20

    metric_collection.reset()
    results = metric_collection(x=jnp.asarray(10.0), y=jnp.asarray(20.0))
    assert float(results["DummyMetricSum"]) == 10
    assert float(results["DummyMetricDiff"]) == -20


def test_metric_collection_prefix():
    prefix = "prefix_"
    metric_collection = MetricCollection([DummyMetricSum(), DummyMetricDiff()], prefix=prefix)

    results = metric_collection(x=jnp.asarray(10.0), y=jnp.asarray(20.0))
    for name in results:
        assert name.startswith(prefix)

    results = metric_collection.compute()
    for name in results:
        assert name.startswith(prefix)

    # clone with new prefix
    new_clone = metric_collection.clone(prefix="new_prefix_")
    results = new_clone.compute()
    for name in results:
        assert name.startswith("new_prefix_")

    with pytest.raises(ValueError, match="Expected input `prefix` to be a string"):
        MetricCollection([DummyMetricSum()], prefix=5)


def test_metric_collection_clone_independent():
    collection = MetricCollection([DummyMetricSum()])
    clone = collection.clone()
    collection.update(x=jnp.asarray(5.0))
    assert float(collection["DummyMetricSum"].x) == 5
    assert float(clone["DummyMetricSum"].x) == 0


def test_metric_collection_persistent():
    collection = MetricCollection([DummyMetricSum()])
    collection.persistent(True)
    assert collection["DummyMetricSum"]._persistent["x"]


def test_collection_pure_joint_update():
    """The whole collection updates as one pure jitted step."""
    import jax

    collection = MetricCollection([DummyMetricSum(), DummyMetricDiff()])
    pure = collection.pure()

    @jax.jit
    def step(state, x, y):
        return pure.update(state, x=x, y=y)

    state = pure.init()
    state = step(state, jnp.asarray(4.0), jnp.asarray(1.0))
    state = step(state, jnp.asarray(6.0), jnp.asarray(2.0))
    out = pure.compute(state)
    assert float(out["DummyMetricSum"]) == 10
    assert float(out["DummyMetricDiff"]) == -3
