"""MetricCollection semantics (mirrors reference tests/bases/test_collections.py:25-156)."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import MetricCollection
from tests.helpers.testers import DummyMetricDiff, DummyMetricSum


def test_metric_collection():
    m1 = DummyMetricSum()
    m2 = DummyMetricDiff()

    metric_collection = MetricCollection([m1, m2])

    # by default, the keys are the class names
    assert "DummyMetricSum" in metric_collection
    assert "DummyMetricDiff" in metric_collection

    # test correct initialization
    for name, metric in metric_collection.items():
        assert float(metric.x) == 0

    # argument filtering: each metric sees only its own kwargs
    metric_collection.update(x=jnp.asarray(10.0), y=jnp.asarray(20.0))
    assert float(metric_collection["DummyMetricSum"].x) == 10
    assert float(metric_collection["DummyMetricDiff"].x) == -20

    results = metric_collection.compute()
    assert float(results["DummyMetricSum"]) == 10
    assert float(results["DummyMetricDiff"]) == -20

    metric_collection.reset()
    for name, metric in metric_collection.items():
        assert float(metric.x) == 0


def test_device_put():
    import jax

    metric_collection = MetricCollection([DummyMetricSum(), DummyMetricDiff()])
    metric_collection.device_put(jax.devices()[0])
    for _, metric in metric_collection.items():
        assert metric.x.devices() == {jax.devices()[0]}


def test_metric_collection_wrong_input():
    m1 = DummyMetricSum()

    # not a Metric
    with pytest.raises(ValueError, match="is not an instance of"):
        MetricCollection({"metric": 5})

    with pytest.raises(ValueError, match="is not a instance of"):
        MetricCollection([5])

    # same name twice
    with pytest.raises(ValueError, match="Encountered two metrics both named"):
        MetricCollection([m1, m1.clone()])

    with pytest.raises(ValueError, match="Unknown input to MetricCollection."):
        MetricCollection(m1)


def test_metric_collection_args_kwargs():
    m1 = DummyMetricSum()
    m2 = DummyMetricDiff()

    metric_collection = MetricCollection([m1, m2])

    # kwargs are filtered per update signature
    metric_collection.update(x=jnp.asarray(10.0), y=jnp.asarray(20.0))
    assert float(metric_collection["DummyMetricSum"].x) == 10
    assert float(metric_collection["DummyMetricDiff"].x) == -20

    metric_collection.reset()
    results = metric_collection(x=jnp.asarray(10.0), y=jnp.asarray(20.0))
    assert float(results["DummyMetricSum"]) == 10
    assert float(results["DummyMetricDiff"]) == -20


def test_metric_collection_prefix():
    prefix = "prefix_"
    metric_collection = MetricCollection([DummyMetricSum(), DummyMetricDiff()], prefix=prefix)

    results = metric_collection(x=jnp.asarray(10.0), y=jnp.asarray(20.0))
    for name in results:
        assert name.startswith(prefix)

    results = metric_collection.compute()
    for name in results:
        assert name.startswith(prefix)

    # clone with new prefix
    new_clone = metric_collection.clone(prefix="new_prefix_")
    results = new_clone.compute()
    for name in results:
        assert name.startswith("new_prefix_")

    with pytest.raises(ValueError, match="Expected input `prefix` to be a string"):
        MetricCollection([DummyMetricSum()], prefix=5)


def test_metric_collection_clone_independent():
    collection = MetricCollection([DummyMetricSum()])
    clone = collection.clone()
    collection.update(x=jnp.asarray(5.0))
    assert float(collection["DummyMetricSum"].x) == 5
    assert float(clone["DummyMetricSum"].x) == 0


def test_metric_collection_persistent():
    collection = MetricCollection([DummyMetricSum()])
    collection.persistent(True)
    assert collection["DummyMetricSum"]._persistent["x"]


def test_collection_pure_joint_update():
    """The whole collection updates as one pure jitted step."""
    import jax

    collection = MetricCollection([DummyMetricSum(), DummyMetricDiff()])
    pure = collection.pure()

    @jax.jit
    def step(state, x, y):
        return pure.update(state, x=x, y=y)

    state = pure.init()
    state = step(state, jnp.asarray(4.0), jnp.asarray(1.0))
    state = step(state, jnp.asarray(6.0), jnp.asarray(2.0))
    out = pure.compute(state)
    assert float(out["DummyMetricSum"]) == 10
    assert float(out["DummyMetricDiff"]) == -3


def test_collection_fused_single_dispatch():
    """With jit on, the whole collection's forward runs as one jitted step
    (update + merge + batch values), matching the per-metric path exactly."""
    import numpy as np
    import metrics_tpu
    from metrics_tpu import Accuracy, F1, Precision, Recall

    old = metrics_tpu.set_default_jit(True)
    try:
        rng = np.random.RandomState(0)
        logits = rng.rand(10, 32, 5).astype(np.float32)
        probs = logits / logits.sum(-1, keepdims=True)
        target = rng.randint(0, 5, (10, 32))

        def build():
            return MetricCollection([
                Accuracy(),
                F1(num_classes=5, average="macro"),
                Precision(num_classes=5, average="macro"),
                Recall(num_classes=5, average="macro"),
            ])

        fused = build()
        assert fused._collection_fusable()
        step_values = [fused(jnp.asarray(probs[i]), jnp.asarray(target[i])) for i in range(10)]
        assert fused.__dict__.get("_col_step") is not None  # the fused path ran

        metrics_tpu.set_default_jit(False)
        eager = build()
        for i in range(10):
            want = eager(jnp.asarray(probs[i]), jnp.asarray(target[i]))
            for k in want:
                np.testing.assert_allclose(
                    np.asarray(step_values[i][k]), np.asarray(want[k]), atol=1e-6
                )
        for k, v in fused.compute().items():
            np.testing.assert_allclose(np.asarray(v), np.asarray(eager.compute()[k]), atol=1e-6)
    finally:
        metrics_tpu.set_default_jit(old)


def test_collection_fused_membership_change_and_clone():
    """The fused step is rebuilt when membership changes, and cloned
    metrics/collections forward correctly."""
    import numpy as np
    import metrics_tpu
    from metrics_tpu import Accuracy, Precision

    old = metrics_tpu.set_default_jit(True)
    try:
        rng = np.random.RandomState(0)
        probs = jnp.asarray(rng.rand(32, 5).astype(np.float32))
        target = jnp.asarray(rng.randint(0, 5, 32))

        mc = MetricCollection([Accuracy()])
        mc(probs, target)
        mc["Precision"] = Precision(num_classes=5, average="macro")
        out = mc(probs, target)  # must rebuild, not crash on the stale step
        assert set(out) == {"Accuracy", "Precision"}

        # cloned metric forwards (regression: deepcopy must reset the fused step)
        m = Accuracy()
        m(probs, target)
        c = m.clone()
        c(probs, target)
        assert abs(float(c.compute()) - float(m.compute())) < 1e-6

        mc2 = mc.clone()
        out2 = mc2(probs, target)
        assert set(out2) == {"Accuracy", "Precision"}
    finally:
        metrics_tpu.set_default_jit(old)


def test_clone_states_do_not_alias():
    """Clone and original must own distinct state buffers: the TPU fused step
    DONATES the state argument, so a shared buffer would be invalidated for
    whichever object steps second (reproduced on real TPU as INVALID_ARGUMENT
    reads after clone-then-forward)."""
    import metrics_tpu
    from metrics_tpu import Accuracy

    old = metrics_tpu.set_default_jit(True)
    try:
        probs = jnp.asarray(np.random.RandomState(0).rand(8, 5).astype(np.float32))
        target = jnp.asarray(np.random.RandomState(1).randint(0, 5, 8))
        m = Accuracy()
        m(probs, target)
        c = m.clone()
        for name in m._defaults:
            a, b = getattr(m, name), getattr(c, name)
            assert a is not b, name
        # both sides keep working independently after each other's steps
        c(probs, target)
        m(probs, target)
        assert abs(float(m.compute()) - float(Accuracy()(probs, target))) < 1e-6
        assert abs(float(c.compute()) - float(m.compute())) < 1e-6
    finally:
        metrics_tpu.set_default_jit(old)


def test_collection_fused_same_key_replacement():
    """Replacing a child under the SAME key must drop the cached fused step —
    the new config's values must be returned, not the old carrier's."""
    import numpy as np
    import metrics_tpu
    from metrics_tpu import Precision

    old = metrics_tpu.set_default_jit(True)
    try:
        rng = np.random.RandomState(0)
        logits = rng.rand(32, 5).astype(np.float32)
        probs = jnp.asarray(logits / logits.sum(-1, keepdims=True))
        target = jnp.asarray(rng.randint(0, 5, 32))

        mc = MetricCollection({"p": Precision(num_classes=5, average="macro")})
        macro = float(mc(probs, target)["p"])

        mc["p"] = Precision(num_classes=5, average="micro")
        micro = float(mc(probs, target)["p"])

        want_micro = float(Precision(num_classes=5, average="micro")(probs, target))
        want_macro = float(Precision(num_classes=5, average="macro")(probs, target))
        np.testing.assert_allclose(micro, want_micro, atol=1e-6)
        assert abs(want_micro - want_macro) > 1e-4  # the configs genuinely differ
        np.testing.assert_allclose(macro, want_macro, atol=1e-6)
        # and the replacement's own accumulator holds exactly one batch
        np.testing.assert_allclose(float(mc["p"].compute()), want_micro, atol=1e-6)
    finally:
        metrics_tpu.set_default_jit(old)


def test_collection_unfusable_verdict_cached_and_cleared():
    """A non-fusable collection caches the negative verdict (no per-forward
    gate re-runs), and replacing the offending child re-enables fusion."""
    import metrics_tpu
    from metrics_tpu import Accuracy

    old = metrics_tpu.set_default_jit(True)
    try:
        probs = jnp.asarray(np.random.RandomState(0).rand(8, 5).astype(np.float32))
        target = jnp.asarray(np.random.RandomState(1).randint(0, 5, 8))

        mc = MetricCollection({"a": Accuracy(), "b": Accuracy(dist_sync_on_step=True)})
        mc(probs, target)
        assert mc.__dict__.get("_col_unfusable") is True
        assert mc.__dict__.get("_col_step") is None
        # gate must not re-run per forward: poison it to prove it is skipped
        mc._collection_fusable = lambda: (_ for _ in ()).throw(AssertionError("gate re-ran"))
        mc(probs, target)
        del mc._collection_fusable

        # replacing the offending child clears the verdict and fuses
        mc["b"] = Accuracy()
        mc(probs, target)
        assert mc.__dict__.get("_col_step") is not None
    finally:
        metrics_tpu.set_default_jit(old)


def test_collection_forward_batched_matches_per_step():
    """One-dispatch batched collection == the per-step fused loop."""
    import metrics_tpu
    from metrics_tpu import Accuracy, F1, Precision

    rng = np.random.RandomState(31)
    logits = rng.rand(8, 16, 4).astype(np.float32)
    probs = logits / logits.sum(-1, keepdims=True)
    target = rng.randint(0, 4, (8, 16)).astype(np.int32)

    old = metrics_tpu.set_default_jit(True)
    try:
        loop = MetricCollection([Accuracy(), Precision(num_classes=4, average="macro"),
                                 F1(num_classes=4, average="macro")])
        loop_vals = [loop(jnp.asarray(probs[i]), jnp.asarray(target[i])) for i in range(8)]

        batched = MetricCollection([Accuracy(), Precision(num_classes=4, average="macro"),
                                    F1(num_classes=4, average="macro")])
        vals = batched.forward_batched(jnp.asarray(probs), jnp.asarray(target))

        for key in loop_vals[0]:
            assert vals[key].shape == (8,)
            np.testing.assert_allclose(
                np.asarray(vals[key]), [float(v[key]) for v in loop_vals], atol=1e-6
            )
        loop_epoch = loop.compute()
        batched_epoch = batched.compute()  # pre-seeded, no dispatch
        for key in loop_epoch:
            np.testing.assert_allclose(float(batched_epoch[key]), float(loop_epoch[key]), atol=1e-6)

        # further updates invalidate the seed and keep accumulating
        batched.update(jnp.asarray(probs[0]), jnp.asarray(target[0]))
        loop.update(jnp.asarray(probs[0]), jnp.asarray(target[0]))
        for key in loop_epoch:
            np.testing.assert_allclose(
                float(batched.compute()[key]), float(loop.compute()[key]), atol=1e-6
            )
    finally:
        metrics_tpu.set_default_jit(old)


def test_collection_forward_batched_fallback_unjittable():
    """A collection with an unfusable child falls back per child, same values."""
    from metrics_tpu import Accuracy

    rng = np.random.RandomState(33)
    probs = rng.rand(4, 8, 3).astype(np.float32)
    probs /= probs.sum(-1, keepdims=True)
    target = rng.randint(0, 3, (4, 8)).astype(np.int32)

    coll = MetricCollection([Accuracy()])  # jit disabled by conftest -> fallback path
    vals = coll.forward_batched(jnp.asarray(probs), jnp.asarray(target))
    expected = (probs.reshape(-1, 3).argmax(-1) == target.reshape(-1)).mean()
    assert np.asarray(vals["Accuracy"]).shape == (4,)
    np.testing.assert_allclose(float(coll.compute()["Accuracy"]), expected, atol=1e-6)


def test_collection_batched_failure_does_not_disable_fused_forward():
    """A vmap-path failure poisons only the batched plane; the per-step fused
    forward keeps working (and vice versa the flags stay separate)."""
    import metrics_tpu
    from metrics_tpu import Accuracy

    old = metrics_tpu.set_default_jit(True)
    try:
        coll = MetricCollection([Accuracy()])
        coll.__dict__["_col_membership"] = None  # force cache refresh
        probs = jnp.asarray(np.eye(3, dtype=np.float32)[None].repeat(2, 0))
        target = jnp.asarray(np.arange(3, dtype=np.int32)[None].repeat(2, 0))
        coll.forward_batched(probs, target)
        # simulate a batched-plane failure verdict
        coll.__dict__["_col_batched_failed"] = True
        out = coll(probs[0], target[0])  # fused per-step path unaffected
        assert float(out["Accuracy"]) == 1.0
    finally:
        metrics_tpu.set_default_jit(old)
