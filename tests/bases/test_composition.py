"""CompositionalMetric operator semantics (mirrors reference tests/bases/test_composition.py:51-500,
one test per overloaded operator)."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.core.metric import CompositionalMetric, Metric


class DummyMetric(Metric):

    def __init__(self, val_to_return):
        super().__init__()
        self.add_state("_num_updates", jnp.asarray(0), dist_reduce_fx="sum")
        self._val_to_return = val_to_return

    def update(self, *args, **kwargs) -> None:
        self._num_updates = self._num_updates + 1

    def compute(self):
        return jnp.asarray(self._val_to_return)


@pytest.mark.parametrize(["second_operand", "expected_result"], [(2, 4), (2.0, 4.0), (jnp.asarray(2), 4)])
def test_metrics_add(second_operand, expected_result):
    first_metric = DummyMetric(2)
    final_add = first_metric + second_operand
    final_radd = second_operand + first_metric
    assert isinstance(final_add, CompositionalMetric)
    assert isinstance(final_radd, CompositionalMetric)
    final_add.update()
    final_radd.update()
    assert float(final_add.compute()) == expected_result
    assert float(final_radd.compute()) == expected_result


@pytest.mark.parametrize(["second_operand", "expected_result"], [(3, 2), (3.0, 2.0), (jnp.asarray(3), 2)])
def test_metrics_div(second_operand, expected_result):
    first_metric = DummyMetric(6)
    final_div = first_metric / second_operand
    assert isinstance(final_div, CompositionalMetric)
    final_div.update()
    assert float(final_div.compute()) == expected_result


@pytest.mark.parametrize(["second_operand", "expected_result"], [(2, 4), (2.0, 4.0)])
def test_metrics_mul(second_operand, expected_result):
    first_metric = DummyMetric(2)
    final_mul = first_metric * second_operand
    final_rmul = second_operand * first_metric
    final_mul.update()
    final_rmul.update()
    assert float(final_mul.compute()) == expected_result
    assert float(final_rmul.compute()) == expected_result


@pytest.mark.parametrize(["second_operand", "expected_result"], [(2, 1), (2.0, 1.0)])
def test_metrics_sub(second_operand, expected_result):
    first_metric = DummyMetric(3)
    final_sub = first_metric - second_operand
    final_rsub = second_operand - first_metric
    final_sub.update()
    final_rsub.update()
    assert float(final_sub.compute()) == expected_result
    assert float(final_rsub.compute()) == -expected_result


@pytest.mark.parametrize(["second_operand", "expected_result"], [(2, 9), (2.0, 9.0)])
def test_metrics_pow(second_operand, expected_result):
    first_metric = DummyMetric(3)
    final_pow = first_metric**second_operand
    final_pow.update()
    # approx: TPU evaluates float pow via exp(y*log(x)) (3.0**2.0 -> 9.000011)
    assert float(final_pow.compute()) == pytest.approx(expected_result, rel=1e-5)


@pytest.mark.parametrize(["second_operand", "expected_result"], [(5, 1), (5.0, 1.0)])
def test_metrics_mod(second_operand, expected_result):
    first_metric = DummyMetric(11)
    final_mod = first_metric % second_operand
    final_mod.update()
    assert float(final_mod.compute()) == expected_result


@pytest.mark.parametrize(["second_operand", "expected_result"], [(2, 2), (2.0, 2.0)])
def test_metrics_floordiv(second_operand, expected_result):
    first_metric = DummyMetric(5)
    final_floordiv = first_metric // second_operand
    final_floordiv.update()
    assert float(final_floordiv.compute()) == expected_result


def test_metrics_matmul():
    first_metric = DummyMetric([2, 2, 2])
    second_operand = jnp.asarray([2, 2, 2])
    final_matmul = first_metric @ second_operand
    final_matmul.update()
    assert float(final_matmul.compute()) == 12


@pytest.mark.parametrize("op,expected", [("and", 2), ("or", 6), ("xor", 4)])
def test_metrics_bitwise(op, expected):
    first_metric = DummyMetric(2)
    second_operand = jnp.asarray(6)
    if op == "and":
        composed = first_metric & second_operand
    elif op == "or":
        composed = first_metric | second_operand
    else:
        composed = first_metric ^ second_operand
    composed.update()
    assert int(composed.compute()) == expected


@pytest.mark.parametrize(
    "op,expected",
    [("lt", False), ("le", False), ("gt", True), ("ge", True), ("eq", False), ("ne", True)],
)
def test_metrics_comparisons(op, expected):
    first_metric = DummyMetric(3)
    second_operand = 2
    composed = {
        "lt": first_metric < second_operand,
        "le": first_metric <= second_operand,
        "gt": first_metric > second_operand,
        "ge": first_metric >= second_operand,
        "eq": first_metric == second_operand,
        "ne": first_metric != second_operand,
    }[op]
    composed.update()
    assert bool(composed.compute()) is expected


def test_metrics_abs_neg_pos_invert():
    m = DummyMetric(-2)
    assert float(abs(m).compute()) == 2
    # reference quirk: __neg__ is -abs(x) (reference metric.py:453-454)
    assert float((-m).compute()) == -2
    assert float((-DummyMetric(2)).compute()) == -2
    assert float((+m).compute()) == 2
    assert int((~DummyMetric(1)).compute()) == -2


def test_compositional_update_broadcast():
    """update() on the composition updates both children with filtered kwargs."""
    m1 = DummyMetric(2)
    m2 = DummyMetric(3)
    composed = m1 + m2
    composed.update()
    assert int(m1._num_updates) == 1
    assert int(m2._num_updates) == 1
    composed.reset()
    assert int(m1._num_updates) == 0


def test_metrics_chained_operations():
    first = DummyMetric(2)
    second = DummyMetric(3)
    composed = (first + second) * 2 - 4
    composed.update()
    assert float(composed.compute()) == 6


def test_compositional_forward_fused_single_update():
    """Composed forward runs ONE update per child per step, returns the op of
    the children's batch values, and leaves accumulation intact."""

    class Mean(Metric):

        def __init__(self):
            super().__init__()
            self.add_state("s", jnp.asarray(0.0), dist_reduce_fx="sum")
            self.add_state("n", jnp.asarray(0.0), dist_reduce_fx="sum")

        def update(self, x):
            self.s = self.s + jnp.sum(x)
            self.n = self.n + x.shape[0]

        def compute(self):
            return self.s / jnp.maximum(self.n, 1.0)

    a, b = Mean(), Mean()
    composed = a + b
    v1 = composed(jnp.asarray([1.0, 3.0]))  # batch means 2 + 2
    assert float(v1) == 4.0
    v2 = composed(jnp.asarray([5.0, 7.0]))  # batch means 6 + 6
    assert float(v2) == 12.0
    # each child accumulated each batch exactly once
    assert float(a.n) == 4.0 and float(a.s) == 16.0
    # epoch compute composes the children's accumulated computes
    assert float(composed.compute()) == 8.0

    # a constant operand composes against the child's batch value
    shifted = a + 10.0
    assert float(shifted(jnp.asarray([4.0, 4.0]))) == 14.0

    # compute_on_step=False child -> no batch value to compose
    c = Mean()
    c.compute_on_step = False
    silent = c + b
    assert silent(jnp.asarray([1.0])) is None
    assert float(c.n) == 1.0  # still accumulated


def test_compositional_cache_invalidation():
    """forward and reset must invalidate the composed compute cache."""
    a, b = DummyMetric(2), DummyMetric(3)
    c = a + b
    c.update()
    assert float(c.compute()) == 5
    c.reset()
    a._val_to_return, b._val_to_return = 10, 20
    assert float(c.compute()) == 30  # not the cached 5
    # forward on a compute_on_step=False composition also drops the cache
    c2 = DummyMetric(1) + DummyMetric(1)
    c2.update()
    assert float(c2.compute()) == 2
    c2.compute_on_step = False
    assert c2() is None
    c2.metric_a._val_to_return = 7
    assert float(c2.compute()) == 8
