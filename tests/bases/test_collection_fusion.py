"""The megafused collection step's contracts beyond value parity.

Value parity (fused forward == per-metric path), membership invalidation,
and same-key replacement live in ``test_collections.py``. This file pins
the contracts the megafusion PR added around the fused step:

- off CPU the step DONATES its state argument (slab updates in place); on
  CPU donation is gated OFF — XLA:CPU executables deserialized from the
  persistent compilation cache mishandle input-output aliasing (state reads
  flakily see freed memory) — so a direct step call leaves its state
  argument alive;
- a trace-time failure happens BEFORE execution, so the eager fallback
  always finds the members' (would-be donated) state buffers alive;
- ``_dedupe_donated_buffers`` keeps donation legal when members alias one
  buffer (XLA rejects a buffer donated twice);
- members excluded from fusion are named ONCE via ``rank_zero_warn_once``,
  message naming the member key and the offending attribute;
- ``clear_program_cache()`` drops the shared fused-step cache, and lookups
  account under the ``fused_step_cache`` hit/miss block in snapshots;
- the ``shared_input_format`` window memoizes input canonicalization by
  argument identity, folding the implied-num_classes key.
"""
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu
from metrics_tpu import Accuracy, MetricCollection, Precision
from metrics_tpu.core.collections import _COL_STEP_CACHE, _dedupe_donated_buffers
from metrics_tpu.core.metric import Metric
from metrics_tpu.observability import counters as obs_counters
from metrics_tpu.parallel.deferred import clear_program_cache
from metrics_tpu.utils import prints
from metrics_tpu.utils.checks import _input_format_classification, shared_input_format


@pytest.fixture
def jit_on():
    old = metrics_tpu.set_default_jit(True)
    try:
        yield
    finally:
        metrics_tpu.set_default_jit(old)


def _probs_target(rows=32, classes=5, seed=0):
    rng = np.random.RandomState(seed)
    logits = rng.rand(rows, classes).astype(np.float32)
    probs = jnp.asarray(logits / logits.sum(-1, keepdims=True))
    target = jnp.asarray(rng.randint(0, classes, rows))
    return probs, target


def _fused_collection():
    return MetricCollection(
        {"acc": Accuracy(), "prec": Precision(num_classes=5, average="macro")}
    )


# ------------------------------------------------------------------ donation
def test_fused_step_donates_state_slabs(jit_on):
    """Off CPU the compiled step aliases its state inputs to outputs and a
    direct call consumes the donated buffers — the forward path must
    therefore rebind every member to the returned slabs (which it does:
    members stay usable across steps). On CPU donation is gated OFF (the
    persistent compilation cache deserializes XLA:CPU aliasing unsoundly),
    so the same direct call leaves its state argument alive."""
    probs, target = _probs_target()
    col = _fused_collection()
    col(probs, target)
    step = col.__dict__.get("_col_step")
    assert step is not None

    on_cpu = jax.default_backend() == "cpu"
    states = _dedupe_donated_buffers({k: m._current_state() for k, m in col.items()})
    compiled = step.lower(states, probs, target).compile()
    assert ("input_output_alias" in compiled.as_text()) == (not on_cpu)

    # off CPU a direct call consumes its (copied — the snapshot above
    # aliases the members' live buffers) state argument; on CPU the gated
    # step must leave it alive
    copies = jax.tree_util.tree_map(lambda x: x.copy(), states)
    step(copies, probs, target)
    donated = jax.tree_util.tree_leaves(copies)
    assert donated
    if on_cpu:
        assert all(not leaf.is_deleted() for leaf in donated)
    else:
        assert all(leaf.is_deleted() for leaf in donated)
    # the members' own buffers were untouched: the collection keeps working
    for leaf in jax.tree_util.tree_leaves(states):
        assert not leaf.is_deleted()
    col(probs, target)
    assert float(col.compute()["acc"]) >= 0.0


class _ConcreteUpdate(Metric):
    """Fusable by every static gate, but update() needs concrete values —
    the fused trace fails at trace time, AFTER the build but BEFORE any
    buffer is consumed."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds, target):
        self.total = self.total + float(jnp.sum(target))

    def compute(self):
        return self.total


def test_eager_fallback_after_trace_failure_keeps_states_alive(jit_on):
    """A trace-time failure must leave every member's (would-be donated)
    state buffer alive for the eager fallback, and the fallback result must
    be correct."""
    probs, target = _probs_target()
    col = MetricCollection({"acc": Accuracy(), "concrete": _ConcreteUpdate()})
    before = {k: m._current_state() for k, m in col.items()}
    out = col(probs, target)
    assert col.__dict__.get("_col_fuse_failed") is True
    assert col.__dict__.get("_col_step") is None
    for leaf in jax.tree_util.tree_leaves(before):
        assert not leaf.is_deleted()
    assert float(out["concrete"]) == float(jnp.sum(target))
    # accumulators advanced through the fallback, not left at init
    assert float(col.compute()["concrete"]) == float(jnp.sum(target))
    want = float(Accuracy()(probs, target))
    np.testing.assert_allclose(float(out["acc"]), want, atol=1e-6)


def test_dedupe_donated_buffers_copies_aliases():
    a = jnp.arange(4.0)
    b = jnp.ones(3)
    states = {"m1": {"x": a, "y": b}, "m2": {"x": a}}  # m2.x aliases m1.x
    deduped = _dedupe_donated_buffers(states)
    assert deduped["m1"]["x"] is a
    assert deduped["m1"]["y"] is b
    assert deduped["m2"]["x"] is not a
    np.testing.assert_array_equal(np.asarray(deduped["m2"]["x"]), np.asarray(a))
    leaves = jax.tree_util.tree_leaves(deduped)
    assert len({id(l) for l in leaves}) == len(leaves)


def test_aliased_member_states_survive_fused_forward(jit_on):
    """Manual state wiring that aliases one buffer across members must not
    poison donation (XLA rejects a twice-donated buffer)."""
    probs, target = _probs_target()
    col = MetricCollection({"a": Accuracy(), "b": Accuracy()})
    col(probs, target)  # build + first fused step
    # alias b's states onto a's buffers, as load_state_dict-style wiring can
    col["b"]._set_state(dict(col["a"]._current_state()))
    out = col(probs, target)
    np.testing.assert_allclose(float(out["a"]), float(out["b"]), atol=1e-6)
    np.testing.assert_allclose(
        float(col.compute()["a"]), float(col.compute()["b"]), atol=1e-6
    )


# ----------------------------------------------------------------- warn once
def test_unfused_member_warns_once_naming_member_and_attribute(jit_on):
    probs, target = _probs_target()
    col = MetricCollection({"good": Accuracy(), "bad": Accuracy(dist_sync_on_step=True)})
    prints._WARN_ONCE_SEEN.clear()
    with pytest.warns(UserWarning, match=r"'bad'.*dist_sync_on_step=True") as rec:
        col(probs, target)
    excluded = [w for w in rec if "excluded from the fused collection step" in str(w.message)]
    assert len(excluded) == 1  # only the offending member is named
    assert col.__dict__.get("_col_unfusable") is True

    # once per process: the second forward (and a fresh identical collection)
    # stays quiet
    with warnings.catch_warnings(record=True) as again:
        warnings.simplefilter("always")
        col(probs, target)
        MetricCollection({"good": Accuracy(), "bad": Accuracy(dist_sync_on_step=True)})(
            probs, target
        )
    assert not [w for w in again if "excluded from the fused" in str(w.message)]


# -------------------------------------------------------------- cache plane
def test_clear_program_cache_drops_fused_step_cache(jit_on):
    probs, target = _probs_target()
    clear_program_cache()
    _fused_collection()(probs, target)
    assert len(_COL_STEP_CACHE) == 1
    clear_program_cache()
    assert len(_COL_STEP_CACHE) == 0


def test_fused_step_cache_hit_miss_counters(jit_on):
    """Config-identical collections share ONE compiled step; the lookup
    accounts under the snapshot's ``fused_step_cache`` block."""
    probs, target = _probs_target()
    clear_program_cache()
    obs_counters.reset()
    obs_counters.enable()
    try:
        _fused_collection()(probs, target)  # miss: builds and caches
        _fused_collection()(probs, target)  # hit: replays the shared step
        snap = obs_counters.snapshot()
    finally:
        obs_counters.disable()
    assert snap["fused_step_cache"] == {"hits": 1, "misses": 1}


# ------------------------------------------------------- canonicalization memo
def test_shared_input_format_memoizes_by_identity():
    probs, target = _probs_target()
    with shared_input_format():
        first = _input_format_classification(probs, target)
        second = _input_format_classification(probs, target)
        assert first[0] is second[0] and first[1] is second[1]
        # implied num_classes folds into the same key as the explicit value
        explicit = _input_format_classification(probs, target, num_classes=5)
        assert explicit[0] is first[0]
        # different arguments do NOT collide
        other = _input_format_classification(probs, target, top_k=2)
        assert other[0] is not first[0]
    # outside any window nothing is memoized
    a = _input_format_classification(probs, target)
    b = _input_format_classification(probs, target)
    assert a[0] is not b[0]
    np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))


def test_shared_input_format_windows_do_not_leak():
    probs, target = _probs_target()
    with shared_input_format():
        first = _input_format_classification(probs, target)
    with shared_input_format():
        second = _input_format_classification(probs, target)
    assert first[0] is not second[0]
