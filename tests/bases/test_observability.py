"""Observability subsystem: span tracer, collective counters, exports.

The contract under test, in order of importance:

1. Disabled (the default) is a structural no-op: ``span()`` hands back one
   process-wide singleton (nothing allocated per call) and nothing is
   recorded — the hot paths stay cold.
2. Enabled, spans nest correctly across ``forward -> update -> sync`` with
   parent/depth attribution per thread.
3. The Chrome-trace export emits schema-valid ``trace_events`` (what
   chrome://tracing and ui.perfetto.dev load), and the JSONL export
   round-trips through ``json.loads`` line by line.
4. The collective counters agree with ground truth: ``states_synced`` equals
   the synced leaf count that bench --smoke reports (6 for the grouped bench
   collection), and ``sync_bytes`` equals the byte size of those leaves.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import Accuracy, F1, Metric, MetricCollection, Precision, Recall
from metrics_tpu import observability as obs
from metrics_tpu.observability import counters as obs_counters
from metrics_tpu.observability import trace as obs_trace
from metrics_tpu.utils import compat


@pytest.fixture(autouse=True)
def _clean_observability():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


# the bench --smoke collection shape: Accuracy + one StatScores group
def _bench_like_collection():
    return MetricCollection([
        Accuracy(),
        F1(num_classes=4, average="macro"),
        Precision(num_classes=4, average="macro"),
        Recall(num_classes=4, average="macro"),
    ])


# ------------------------------------------------------------ disabled path
def test_disabled_span_is_a_shared_singleton():
    # the zero-allocation contract: no per-call object while disabled
    assert obs.span("a") is obs.span("b")
    assert obs.span("a") is obs_trace._NULL_SPAN


def test_disabled_records_nothing():
    with obs.span("not-recorded"):
        pass

    @obs.traced("also-not-recorded")
    def fn():
        return 1

    assert fn() == 1
    assert obs.records() == []

    m = Accuracy()
    m(jnp.array([1, 0]), jnp.array([1, 1]))
    m.compute()
    assert obs.records() == []
    assert obs.counters_snapshot()["collective_calls"] == 0


def test_disabled_counters_record_nothing():
    obs_counters.record_collective("psum", jnp.zeros((4,)))
    obs_counters.record_states_synced(3)
    obs_counters.record_cache("step", True)
    snap = obs.counters_snapshot()
    assert snap["collective_calls"] == 0
    assert snap["states_synced"] == 0
    assert snap["step_cache"] == {"hits": 0, "misses": 0}


# ------------------------------------------------------------- enabled path
def test_spans_nest_with_parent_and_depth():
    obs.enable()
    with obs.span("outer"):
        with obs.span("inner", {"k": "v"}):
            pass
    recs = obs.records()
    assert [r.name for r in recs] == ["outer", "inner"]  # start order
    outer, inner = recs
    assert inner.parent == "outer" and inner.depth == 1 and inner.attrs == {"k": "v"}
    assert outer.parent is None and outer.depth == 0
    assert inner.start_ns >= outer.start_ns and inner.end_ns <= outer.end_ns


class _UnfusableMetric(Metric):
    """Non-associative callable reduction -> the reference double-update
    forward path, whose wrapped ``update`` runs INSIDE ``forward``."""

    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx=lambda s: s[-1])

    def update(self, x):
        self.total = self.total + jnp.sum(x)

    def compute(self):
        return self.total


def test_forward_update_sync_span_nesting():
    calls = []

    def fake_gather(x):
        calls.append(x)
        return [x, x]

    obs.enable()
    m = _UnfusableMetric()
    m.dist_sync_fn = fake_gather
    m.dist_sync_on_step = True
    m(jnp.arange(3.0))

    by_name = {r.name: r for r in obs.records()}
    assert by_name["metric.forward"].depth == 0
    assert by_name["metric.update"].parent == "metric.forward"
    assert by_name["metric.compute"].parent == "metric.forward"
    # the host-plane sync ran inside the in-forward compute
    assert by_name["metric.sync_state"].parent == "metric.compute"
    assert calls, "fake gather never invoked"
    assert by_name["metric.forward"].attrs == {"metric": "_UnfusableMetric"}


def test_traced_decorator_records_under_qualname():
    obs.enable()

    @obs.traced()
    def my_phase():
        return 7

    assert my_phase() == 7
    (rec,) = obs.records()
    assert "my_phase" in rec.name


# ----------------------------------------------------------------- exports
def test_chrome_trace_events_schema():
    obs.enable()
    with obs.span("phase.a"):
        with obs.span("phase.b"):
            pass
    doc = obs.chrome_trace()
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    for event in doc["traceEvents"]:
        assert isinstance(event["name"], str)
        assert event["ph"] in ("X", "M", "s", "t", "f")
        assert isinstance(event["pid"], int) and isinstance(event["tid"], int)
        if event["ph"] == "X":  # complete events: microsecond ts + dur
            assert isinstance(event["ts"], (int, float))
            assert isinstance(event["dur"], (int, float)) and event["dur"] >= 0
        elif event["ph"] == "M":  # metadata events carry args only
            assert "args" in event
        else:  # flow events: an id joins the arrow chain, ts places it
            assert isinstance(event["id"], int)
            assert isinstance(event["ts"], (int, float))
    # counters ride along for the Perfetto metadata pane
    assert "collective_calls" in doc["otherData"]
    json.dumps(doc)  # must be JSON-serializable as-is


def test_chrome_trace_flow_events_join_publish_spans():
    obs.enable()
    with obs.span("service.publish_dispatch", {"flow": 7}):
        pass
    with obs.span("service.publish", {"flow": 7}):
        pass
    with obs.span("fleet.merge", {"flow": [7, 9]}):  # merge joins a LIST
        pass
    with obs.span("shard.publish", {"flow": 9}):
        pass
    with obs.span("singleton", {"flow": 11}):  # an arrow needs two ends
        pass
    events = [e for e in obs.chrome_trace()["traceEvents"]
              if e.get("cat") == "metrics_tpu.flow"]
    assert events and all(e["name"] == "publish_flow" for e in events)
    by_id = {}
    for e in events:
        by_id.setdefault(e["id"], []).append(e["ph"])
    # flow 7 threads three spans: start -> step -> finish, in start order
    assert by_id[7] == ["s", "t", "f"]
    # flow 9 appears on two spans (the merge's list + the shard publish)
    assert by_id[9] == ["s", "f"]
    assert 11 not in by_id
    # finish events bind to the enclosing slice so Perfetto anchors the head
    assert all(e["bp"] == "e" for e in events if e["ph"] == "f")
    json.dumps(events)


def test_summarize_e2e_and_flow_columns_are_schema_stable():
    obs.enable()
    with obs.span("plain"):
        pass
    with obs.span("service.publish", {"flow": 3, "e2e_ms": 12.5}):
        pass
    with obs.span("service.publish", {"flow": 2, "e2e_ms": 4.0}):
        pass
    with obs.span("fleet.merge", {"flow": [3, 4]}):
        pass
    table = obs.summarize()
    # the columns are schema-stable: present on every row, zero when the
    # lifecycle ledger never stamped the span
    for row in table.values():
        assert "e2e_ms" in row and "flow_id" in row
    assert table["plain"]["e2e_ms"] == 0.0 and table["plain"]["flow_id"] == 0
    # gauges aggregate by max: the worst e2e, the newest flow
    assert table["service.publish"]["e2e_ms"] == 12.5
    assert table["service.publish"]["flow_id"] == 3
    assert table["fleet.merge"]["flow_id"] == 4  # list flows max out too


def test_write_chrome_trace_and_jsonl(tmp_path):
    obs.enable()
    with obs.span("phase.a"):
        pass
    trace_file = tmp_path / "trace.json"
    jsonl_file = tmp_path / "spans.jsonl"
    obs.write_chrome_trace(str(trace_file))
    obs.write_jsonl(str(jsonl_file))

    doc = json.loads(trace_file.read_text())
    assert any(e.get("name") == "phase.a" for e in doc["traceEvents"])

    lines = [json.loads(line) for line in jsonl_file.read_text().splitlines()]
    kinds = {line["type"] for line in lines}
    assert kinds == {"span", "summary", "counters"}
    summary = [l for l in lines if l["type"] == "summary" and l["name"] == "phase.a"]
    assert summary and summary[0]["count"] == 1


def test_summarize_aggregates_by_name():
    obs.enable()
    for _ in range(3):
        with obs.span("repeated"):
            pass
    table = obs.summarize()
    row = table["repeated"]
    assert row["count"] == 3
    assert row["min_ms"] <= row["mean_ms"] <= row["max_ms"]
    assert row["total_ms"] == pytest.approx(row["mean_ms"] * 3)


# ---------------------------------------------------------------- counters
def test_counters_match_bench_smoke_states_synced():
    """The traced grouped sync program must account exactly the 6 state
    leaves bench --smoke reports as ``states_synced``, with ``sync_bytes``
    equal to their byte size (all leaves ride the coalesced sum plane)."""
    from jax.sharding import Mesh, PartitionSpec as P

    obs.enable()
    pure = _bench_like_collection().pure()
    obs.reset()  # drop group-cache traffic from construction

    mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))

    def step(p, t):
        delta = pure.update(pure.init(), p, t)
        return pure.compute(pure.sync(delta, "dp"))

    fn = jax.jit(compat.shard_map(step, mesh, in_specs=(P("dp"), P("dp")), out_specs=P()))
    rng = np.random.RandomState(3)
    logits = rng.rand(16, 4).astype(np.float32)
    fn(jnp.asarray(logits / logits.sum(-1, keepdims=True)),
       jnp.asarray(rng.randint(0, 4, 16).astype(np.int32)))

    snap = obs.counters_snapshot()
    leaves = jax.tree_util.tree_leaves(pure.init())
    assert snap["states_synced"] == len(leaves) == 6
    assert snap["sync_bytes"] == sum(l.size * l.dtype.itemsize for l in leaves)
    assert snap["collective_calls"] >= 1
    assert sum(snap["calls_by_kind"].values()) == snap["collective_calls"]
    # coalescing: far fewer collectives than synced leaves
    assert snap["collective_calls"] < len(leaves)


def test_counters_bucket_by_dtype():
    obs.enable()
    obs.COUNTERS.record_collective("psum", jnp.zeros((8,), jnp.float32))
    obs.COUNTERS.record_collective("psum", jnp.zeros((2,), jnp.int32))
    snap = obs.counters_snapshot()
    assert snap["bytes_by_kind_dtype"] == {"psum:float32": 32, "psum:int32": 8}
    assert snap["collective_calls"] == 2 and snap["sync_bytes"] == 40


def test_counters_snapshot_reset():
    obs.enable()
    obs.COUNTERS.record_collective("psum", jnp.zeros((2,)))
    assert obs.counters_snapshot(reset_after=True)["collective_calls"] == 1
    assert obs.counters_snapshot()["collective_calls"] == 0


# ---------------------------------------------- thread-safety under the
# background host plane: counters recorded from executor threads (the
# deferred sync plane, the service's deferred publish stage) must neither
# race nor drop increments, and span buffers must stay per-thread coherent.
_STRESS_THREADS = 8
_STRESS_ITERS = 200


def test_counters_and_spans_are_exact_under_8_thread_stress():
    obs.enable()
    obs.reset()
    obs_trace.clear()
    barrier = __import__("threading").Barrier(_STRESS_THREADS)
    errors = []

    def worker(tid):
        try:
            barrier.wait(timeout=10)
            for i in range(_STRESS_ITERS):
                obs_counters.record_collective("psum", np.zeros((4,), np.float32))
                obs_counters.record_fault("sync_retries")
                obs_counters.record_deferred("dispatched")
                obs_counters.record_deferred("completed")
                obs_counters.record_state_bytes(f"Stress{tid}", i)
                obs_counters.record_states_synced(1)
                with obs_trace.span("stress.phase", {"tid": tid}):
                    pass
        except BaseException as err:  # noqa: BLE001 - surfaced on the main thread
            errors.append(err)

    threads = [
        __import__("threading").Thread(target=worker, args=(t,), daemon=True)
        for t in range(_STRESS_THREADS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "stress worker wedged"
    assert not errors, errors
    total = _STRESS_THREADS * _STRESS_ITERS
    snap = obs.counters_snapshot()
    # EXACT totals: a single dropped or double-counted increment fails
    assert snap["calls_by_kind"]["psum"] == total
    assert snap["sync_bytes"] == total * 16
    assert snap["faults"]["sync_retries"] == total
    assert snap["deferred"]["dispatched"] == total
    assert snap["deferred"]["completed"] == total
    assert snap["states_synced"] == total
    # gauges: one entry per thread, last write wins with the final value
    assert all(snap["state_bytes"][f"Stress{t}"] == _STRESS_ITERS - 1 for t in range(_STRESS_THREADS))
    # spans: every thread's buffer merged, none torn
    recs = [r for r in obs.records() if r.name == "stress.phase"]
    assert len(recs) == total
    assert {r.attrs["tid"] for r in recs} == set(range(_STRESS_THREADS))
    obs.disable()


def test_snapshot_is_consistent_while_writers_run():
    """Concurrent ``snapshot()`` during mutation must never throw (dict-size-
    changed races) and every observed fault total must be monotonic."""
    obs.enable()
    obs.reset()
    stop = __import__("threading").Event()
    errors = []

    def writer():
        try:
            while not stop.is_set():
                obs_counters.record_collective("all_gather", np.zeros((2,), np.int32))
                obs_counters.record_fault("sync_retries")
                obs_counters.record_deferred("fenced")
        except BaseException as err:  # noqa: BLE001
            errors.append(err)

    threads = [__import__("threading").Thread(target=writer, daemon=True) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        last = -1
        for _ in range(200):
            snap = obs.counters_snapshot()
            assert snap["faults"]["sync_retries"] >= last
            last = snap["faults"]["sync_retries"]
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert not errors, errors
    obs.disable()


# ------------------------------------------------------------ retention gauges
def test_retention_gauges_schema_in_every_snapshot():
    """The retention block is part of the stable snapshot schema: present
    (empty) with counting off or idle, enabled-gated like ``fleet_shards``,
    and each entry carries exactly the four documented keys."""
    # schema key exists even before anything records
    assert obs.counters_snapshot()["retention"] == {}

    # disabled: the module helper is a no-op (telemetry gate)
    obs_counters.record_retention("idle-store", 1, 2, 3, 4)
    assert obs.counters_snapshot()["retention"] == {}

    obs.enable()
    obs_counters.record_retention("store-a", 10, 3, 4096, 7)
    obs_counters.record_retention("store-b", 1, 0, 128, 0)
    obs_counters.record_retention("store-a", 11, 4, 4032, 8)  # latest wins
    snap = obs.counters_snapshot()
    assert sorted(snap["retention"]) == ["store-a", "store-b"]
    for entry in snap["retention"].values():
        assert sorted(entry) == [
            "queries", "resident_bytes", "rollups", "windows_banked",
        ]
        assert all(isinstance(v, int) for v in entry.values())
    assert snap["retention"]["store-a"] == {
        "windows_banked": 11, "rollups": 4, "resident_bytes": 4032, "queries": 8,
    }
    # snapshots are copies: mutating one must not leak into the counters
    snap["retention"]["store-a"]["queries"] = 999
    assert obs.counters_snapshot()["retention"]["store-a"]["queries"] == 8
    # the block is JSON-ready like the rest of the snapshot
    json.dumps(snap["retention"])


# ------------------------------------------------------- pipeline health
def test_snapshot_schema_lint_across_consumers():
    """Every gauge/counter block in the snapshot schema must be visible to
    its consumers: present (empty) in a DISABLED snapshot so they can bind
    unconditionally, rendered as an OpenMetrics family where the scrape
    surface exposes it, and gated in regress.py's key lists where the bench
    trajectory pins it."""
    from metrics_tpu.observability import regress
    from metrics_tpu.serving import render

    snap = obs.counters_snapshot()  # counting is off (autouse fixture)
    # the per-label gauge blocks: schema keys exist before anything records
    for block in ("service_health", "fleet_shards", "slab_slots", "retention",
                  "lifecycle", "watermark_lag", "publish_staleness",
                  "selfmeter", "deferred_depth", "watermark_agreement",
                  "heavy_hitters", "state_bytes"):
        assert block in snap and snap[block] == {}, block
    # every block the exposition surfaces renders its family even when empty
    text = render(snapshot=snap)
    for block, family in (
        ("service_health", "metrics_tpu_service_health"),
        ("fleet_shards", "metrics_tpu_fleet_shard_health"),
        ("slab_slots", "metrics_tpu_slab_slots"),
        ("faults", "metrics_tpu_fault"),
        ("retention", "metrics_tpu_retention_windows_banked"),
        ("lifecycle", "metrics_tpu_lifecycle_windows_stamped"),
        ("lifecycle", "metrics_tpu_lifecycle_open_windows"),
        ("watermark_lag", "metrics_tpu_watermark_lag_seconds"),
        ("watermark_lag", "metrics_tpu_watermark_lag_degraded"),
        ("publish_staleness", "metrics_tpu_publish_staleness_seconds"),
        ("selfmeter", "metrics_tpu_stage_latency_ms"),
    ):
        assert block in snap, block
        assert f"# TYPE {family} " in text, family
    # the health plane's bench-line keys are trajectory-gated in regress.py
    assert "publish_lag_ms" in regress.MS_KEYS
    assert "selfmeter_p99_ms" in regress.MS_KEYS
    assert "lifecycle_windows_stamped" in regress.COUNT_KEYS
    # the ingest fast path: the bucketed routing-program compile cache is
    # present (zeroed) in a disabled snapshot so bench/gate consumers can
    # diff it unconditionally, and its bench-line keys are trajectory-gated
    assert snap["ingest_program_cache"] == {"hits": 0, "misses": 0}
    assert "ingest_coalesced_steps_per_s" in regress.RATE_KEYS
    assert "ingest_coalesce_factor" in regress.RATE_KEYS
    assert "ingest_program_cache_misses" in regress.COUNT_KEYS


def test_lifecycle_ledger_stamps_and_derives_gauges():
    from metrics_tpu.observability import lifecycle

    obs.enable()
    ms = 1_000_000  # ns per ms, for readable synthetic stamps
    # window 1 opens first (still unpublished when window 0's gauges derive);
    # last_event is last-wins by definition
    lifecycle.stamp("svc-ledger", 1, "last_event", ns=7 * ms)
    lifecycle.stamp("svc-ledger", 1, "last_event", ns=8 * ms)
    assert lifecycle.LEDGER.entry("svc-ledger", 1)["last_event"] == 8 * ms
    for stage, ns in (("first_event", 1 * ms), ("last_event", 2 * ms),
                      ("closed", 3 * ms), ("sync_started", 4 * ms),
                      ("sync_done", 5 * ms), ("published", 9 * ms)):
        lifecycle.stamp("svc-ledger", 0, stage, ns=ns)
    lat = lifecycle.LEDGER.latencies("svc-ledger", 0)
    assert lat["e2e"] == pytest.approx(6.0)  # closed -> published, in ms
    assert lat["sync"] == pytest.approx(1.0)
    assert lat["ingest"] == pytest.approx(1.0)
    # every other stage is first-wins (an idempotent replay or a duplicate
    # close cannot rewrite history)
    lifecycle.stamp("svc-ledger", 0, "closed", ns=50 * ms)
    assert lifecycle.LEDGER.entry("svc-ledger", 0)["closed"] == 3 * ms
    # the published stamp derived the gauge blocks and the self-meters
    snap = obs.counters_snapshot()
    assert snap["lifecycle"]["svc-ledger"] == {
        "windows_stamped": 1, "open_windows": 1,
        "e2e_ms": pytest.approx(6.0),
    }
    assert snap["selfmeter"]["svc-ledger"]["e2e"]["count"] == 1
    assert snap["selfmeter"]["svc-ledger"]["e2e"]["sum_ms"] == pytest.approx(6.0)
    assert "svc-ledger" in snap["publish_staleness"]
    assert snap["publish_staleness"]["svc-ledger"]["staleness_s"] >= 0.0


def test_lifecycle_ledger_is_bounded_fifo():
    from metrics_tpu.observability import lifecycle

    obs.enable()
    for w in range(lifecycle.LEDGER_CAP + 64):
        lifecycle.LEDGER.stamp("svc-cap", w, "closed", ns=w + 1)
    entries = lifecycle.LEDGER.ledgers("svc-cap")
    assert len(entries) == lifecycle.LEDGER_CAP  # constant footprint
    assert 0 not in entries and lifecycle.LEDGER_CAP + 63 in entries  # FIFO


def test_latency_meter_certificate_and_merge():
    from metrics_tpu.observability.selfmeter import LatencyMeter, merge_meters

    rng = np.random.RandomState(3)
    vals = rng.lognormal(1.0, 1.5, 4000)
    a, b = LatencyMeter(), LatencyMeter()
    for v in vals[:2000]:
        a.observe(float(v))
    for v in vals[2000:]:
        b.observe(float(v))
    fold = merge_meters([a, b])
    assert fold.count == 4000
    # the certificate vs the exact stream, at the sketch's own rank rule
    sv = np.sort(vals)
    cum = np.arange(1, len(sv) + 1)
    for q in (0.5, 0.95, 0.99):
        est = fold.quantile(q)
        idx = int(np.clip(np.searchsorted(cum, q * (len(sv) - 1), side="right"),
                          0, len(sv) - 1))
        true = float(sv[idx])
        assert abs(est - true) <= fold.alpha * abs(true) + fold.min_value + 1e-9
        assert fold.error_bound(q) == fold.alpha
    # merging shards == observing the union stream (pure state addition)
    union = LatencyMeter()
    for v in vals:
        union.observe(float(v))
    assert np.array_equal(fold.counts, union.counts)
    assert fold.total_ms == pytest.approx(union.total_ms)
    # the edges: empty -> nan, sub-min -> zero bucket, overflow -> inf bound
    empty = LatencyMeter()
    assert np.isnan(empty.quantile(0.5)) and np.isnan(empty.error_bound(0.5))
    tiny = LatencyMeter()
    tiny.observe(1e-6)
    assert abs(tiny.quantile(0.5)) <= tiny.min_value
    huge = LatencyMeter()
    huge.observe(1e9)
    assert huge.error_bound(0.5) == float("inf")
    # cross-grid merges fail loudly rather than corrupt both certificates
    with pytest.raises(ValueError):
        LatencyMeter().merge_(LatencyMeter(alpha=0.05))
