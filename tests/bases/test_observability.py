"""Observability subsystem: span tracer, collective counters, exports.

The contract under test, in order of importance:

1. Disabled (the default) is a structural no-op: ``span()`` hands back one
   process-wide singleton (nothing allocated per call) and nothing is
   recorded — the hot paths stay cold.
2. Enabled, spans nest correctly across ``forward -> update -> sync`` with
   parent/depth attribution per thread.
3. The Chrome-trace export emits schema-valid ``trace_events`` (what
   chrome://tracing and ui.perfetto.dev load), and the JSONL export
   round-trips through ``json.loads`` line by line.
4. The collective counters agree with ground truth: ``states_synced`` equals
   the synced leaf count that bench --smoke reports (6 for the grouped bench
   collection), and ``sync_bytes`` equals the byte size of those leaves.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import Accuracy, F1, Metric, MetricCollection, Precision, Recall
from metrics_tpu import observability as obs
from metrics_tpu.observability import counters as obs_counters
from metrics_tpu.observability import trace as obs_trace
from metrics_tpu.utils import compat


@pytest.fixture(autouse=True)
def _clean_observability():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


# the bench --smoke collection shape: Accuracy + one StatScores group
def _bench_like_collection():
    return MetricCollection([
        Accuracy(),
        F1(num_classes=4, average="macro"),
        Precision(num_classes=4, average="macro"),
        Recall(num_classes=4, average="macro"),
    ])


# ------------------------------------------------------------ disabled path
def test_disabled_span_is_a_shared_singleton():
    # the zero-allocation contract: no per-call object while disabled
    assert obs.span("a") is obs.span("b")
    assert obs.span("a") is obs_trace._NULL_SPAN


def test_disabled_records_nothing():
    with obs.span("not-recorded"):
        pass

    @obs.traced("also-not-recorded")
    def fn():
        return 1

    assert fn() == 1
    assert obs.records() == []

    m = Accuracy()
    m(jnp.array([1, 0]), jnp.array([1, 1]))
    m.compute()
    assert obs.records() == []
    assert obs.counters_snapshot()["collective_calls"] == 0


def test_disabled_counters_record_nothing():
    obs_counters.record_collective("psum", jnp.zeros((4,)))
    obs_counters.record_states_synced(3)
    obs_counters.record_cache("step", True)
    snap = obs.counters_snapshot()
    assert snap["collective_calls"] == 0
    assert snap["states_synced"] == 0
    assert snap["step_cache"] == {"hits": 0, "misses": 0}


# ------------------------------------------------------------- enabled path
def test_spans_nest_with_parent_and_depth():
    obs.enable()
    with obs.span("outer"):
        with obs.span("inner", {"k": "v"}):
            pass
    recs = obs.records()
    assert [r.name for r in recs] == ["outer", "inner"]  # start order
    outer, inner = recs
    assert inner.parent == "outer" and inner.depth == 1 and inner.attrs == {"k": "v"}
    assert outer.parent is None and outer.depth == 0
    assert inner.start_ns >= outer.start_ns and inner.end_ns <= outer.end_ns


class _UnfusableMetric(Metric):
    """Non-associative callable reduction -> the reference double-update
    forward path, whose wrapped ``update`` runs INSIDE ``forward``."""

    def __init__(self):
        super().__init__()
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx=lambda s: s[-1])

    def update(self, x):
        self.total = self.total + jnp.sum(x)

    def compute(self):
        return self.total


def test_forward_update_sync_span_nesting():
    calls = []

    def fake_gather(x):
        calls.append(x)
        return [x, x]

    obs.enable()
    m = _UnfusableMetric()
    m.dist_sync_fn = fake_gather
    m.dist_sync_on_step = True
    m(jnp.arange(3.0))

    by_name = {r.name: r for r in obs.records()}
    assert by_name["metric.forward"].depth == 0
    assert by_name["metric.update"].parent == "metric.forward"
    assert by_name["metric.compute"].parent == "metric.forward"
    # the host-plane sync ran inside the in-forward compute
    assert by_name["metric.sync_state"].parent == "metric.compute"
    assert calls, "fake gather never invoked"
    assert by_name["metric.forward"].attrs == {"metric": "_UnfusableMetric"}


def test_traced_decorator_records_under_qualname():
    obs.enable()

    @obs.traced()
    def my_phase():
        return 7

    assert my_phase() == 7
    (rec,) = obs.records()
    assert "my_phase" in rec.name


# ----------------------------------------------------------------- exports
def test_chrome_trace_events_schema():
    obs.enable()
    with obs.span("phase.a"):
        with obs.span("phase.b"):
            pass
    doc = obs.chrome_trace()
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    for event in doc["traceEvents"]:
        assert isinstance(event["name"], str)
        assert event["ph"] in ("X", "M")
        assert isinstance(event["pid"], int) and isinstance(event["tid"], int)
        if event["ph"] == "X":  # complete events: microsecond ts + dur
            assert isinstance(event["ts"], (int, float))
            assert isinstance(event["dur"], (int, float)) and event["dur"] >= 0
        else:  # metadata events carry args only
            assert "args" in event
    # counters ride along for the Perfetto metadata pane
    assert "collective_calls" in doc["otherData"]
    json.dumps(doc)  # must be JSON-serializable as-is


def test_write_chrome_trace_and_jsonl(tmp_path):
    obs.enable()
    with obs.span("phase.a"):
        pass
    trace_file = tmp_path / "trace.json"
    jsonl_file = tmp_path / "spans.jsonl"
    obs.write_chrome_trace(str(trace_file))
    obs.write_jsonl(str(jsonl_file))

    doc = json.loads(trace_file.read_text())
    assert any(e.get("name") == "phase.a" for e in doc["traceEvents"])

    lines = [json.loads(line) for line in jsonl_file.read_text().splitlines()]
    kinds = {line["type"] for line in lines}
    assert kinds == {"span", "summary", "counters"}
    summary = [l for l in lines if l["type"] == "summary" and l["name"] == "phase.a"]
    assert summary and summary[0]["count"] == 1


def test_summarize_aggregates_by_name():
    obs.enable()
    for _ in range(3):
        with obs.span("repeated"):
            pass
    table = obs.summarize()
    row = table["repeated"]
    assert row["count"] == 3
    assert row["min_ms"] <= row["mean_ms"] <= row["max_ms"]
    assert row["total_ms"] == pytest.approx(row["mean_ms"] * 3)


# ---------------------------------------------------------------- counters
def test_counters_match_bench_smoke_states_synced():
    """The traced grouped sync program must account exactly the 6 state
    leaves bench --smoke reports as ``states_synced``, with ``sync_bytes``
    equal to their byte size (all leaves ride the coalesced sum plane)."""
    from jax.sharding import Mesh, PartitionSpec as P

    obs.enable()
    pure = _bench_like_collection().pure()
    obs.reset()  # drop group-cache traffic from construction

    mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))

    def step(p, t):
        delta = pure.update(pure.init(), p, t)
        return pure.compute(pure.sync(delta, "dp"))

    fn = jax.jit(compat.shard_map(step, mesh, in_specs=(P("dp"), P("dp")), out_specs=P()))
    rng = np.random.RandomState(3)
    logits = rng.rand(16, 4).astype(np.float32)
    fn(jnp.asarray(logits / logits.sum(-1, keepdims=True)),
       jnp.asarray(rng.randint(0, 4, 16).astype(np.int32)))

    snap = obs.counters_snapshot()
    leaves = jax.tree_util.tree_leaves(pure.init())
    assert snap["states_synced"] == len(leaves) == 6
    assert snap["sync_bytes"] == sum(l.size * l.dtype.itemsize for l in leaves)
    assert snap["collective_calls"] >= 1
    assert sum(snap["calls_by_kind"].values()) == snap["collective_calls"]
    # coalescing: far fewer collectives than synced leaves
    assert snap["collective_calls"] < len(leaves)


def test_counters_bucket_by_dtype():
    obs.enable()
    obs.COUNTERS.record_collective("psum", jnp.zeros((8,), jnp.float32))
    obs.COUNTERS.record_collective("psum", jnp.zeros((2,), jnp.int32))
    snap = obs.counters_snapshot()
    assert snap["bytes_by_kind_dtype"] == {"psum:float32": 32, "psum:int32": 8}
    assert snap["collective_calls"] == 2 and snap["sync_bytes"] == 40


def test_counters_snapshot_reset():
    obs.enable()
    obs.COUNTERS.record_collective("psum", jnp.zeros((2,)))
    assert obs.counters_snapshot(reset_after=True)["collective_calls"] == 1
    assert obs.counters_snapshot()["collective_calls"] == 0


# ---------------------------------------------- thread-safety under the
# background host plane: counters recorded from executor threads (the
# deferred sync plane, the service's deferred publish stage) must neither
# race nor drop increments, and span buffers must stay per-thread coherent.
_STRESS_THREADS = 8
_STRESS_ITERS = 200


def test_counters_and_spans_are_exact_under_8_thread_stress():
    obs.enable()
    obs.reset()
    obs_trace.clear()
    barrier = __import__("threading").Barrier(_STRESS_THREADS)
    errors = []

    def worker(tid):
        try:
            barrier.wait(timeout=10)
            for i in range(_STRESS_ITERS):
                obs_counters.record_collective("psum", np.zeros((4,), np.float32))
                obs_counters.record_fault("sync_retries")
                obs_counters.record_deferred("dispatched")
                obs_counters.record_deferred("completed")
                obs_counters.record_state_bytes(f"Stress{tid}", i)
                obs_counters.record_states_synced(1)
                with obs_trace.span("stress.phase", {"tid": tid}):
                    pass
        except BaseException as err:  # noqa: BLE001 - surfaced on the main thread
            errors.append(err)

    threads = [
        __import__("threading").Thread(target=worker, args=(t,), daemon=True)
        for t in range(_STRESS_THREADS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "stress worker wedged"
    assert not errors, errors
    total = _STRESS_THREADS * _STRESS_ITERS
    snap = obs.counters_snapshot()
    # EXACT totals: a single dropped or double-counted increment fails
    assert snap["calls_by_kind"]["psum"] == total
    assert snap["sync_bytes"] == total * 16
    assert snap["faults"]["sync_retries"] == total
    assert snap["deferred"]["dispatched"] == total
    assert snap["deferred"]["completed"] == total
    assert snap["states_synced"] == total
    # gauges: one entry per thread, last write wins with the final value
    assert all(snap["state_bytes"][f"Stress{t}"] == _STRESS_ITERS - 1 for t in range(_STRESS_THREADS))
    # spans: every thread's buffer merged, none torn
    recs = [r for r in obs.records() if r.name == "stress.phase"]
    assert len(recs) == total
    assert {r.attrs["tid"] for r in recs} == set(range(_STRESS_THREADS))
    obs.disable()


def test_snapshot_is_consistent_while_writers_run():
    """Concurrent ``snapshot()`` during mutation must never throw (dict-size-
    changed races) and every observed fault total must be monotonic."""
    obs.enable()
    obs.reset()
    stop = __import__("threading").Event()
    errors = []

    def writer():
        try:
            while not stop.is_set():
                obs_counters.record_collective("all_gather", np.zeros((2,), np.int32))
                obs_counters.record_fault("sync_retries")
                obs_counters.record_deferred("fenced")
        except BaseException as err:  # noqa: BLE001
            errors.append(err)

    threads = [__import__("threading").Thread(target=writer, daemon=True) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        last = -1
        for _ in range(200):
            snap = obs.counters_snapshot()
            assert snap["faults"]["sync_retries"] >= last
            last = snap["faults"]["sync_retries"]
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert not errors, errors
    obs.disable()


# ------------------------------------------------------------ retention gauges
def test_retention_gauges_schema_in_every_snapshot():
    """The retention block is part of the stable snapshot schema: present
    (empty) with counting off or idle, enabled-gated like ``fleet_shards``,
    and each entry carries exactly the four documented keys."""
    # schema key exists even before anything records
    assert obs.counters_snapshot()["retention"] == {}

    # disabled: the module helper is a no-op (telemetry gate)
    obs_counters.record_retention("idle-store", 1, 2, 3, 4)
    assert obs.counters_snapshot()["retention"] == {}

    obs.enable()
    obs_counters.record_retention("store-a", 10, 3, 4096, 7)
    obs_counters.record_retention("store-b", 1, 0, 128, 0)
    obs_counters.record_retention("store-a", 11, 4, 4032, 8)  # latest wins
    snap = obs.counters_snapshot()
    assert sorted(snap["retention"]) == ["store-a", "store-b"]
    for entry in snap["retention"].values():
        assert sorted(entry) == [
            "queries", "resident_bytes", "rollups", "windows_banked",
        ]
        assert all(isinstance(v, int) for v in entry.values())
    assert snap["retention"]["store-a"] == {
        "windows_banked": 11, "rollups": 4, "resident_bytes": 4032, "queries": 8,
    }
    # snapshots are copies: mutating one must not leak into the counters
    snap["retention"]["store-a"]["queries"] = 999
    assert obs.counters_snapshot()["retention"]["store-a"]["queries"] == 8
    # the block is JSON-ready like the rest of the snapshot
    json.dumps(snap["retention"])
