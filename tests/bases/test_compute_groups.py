"""Compute-group semantics of MetricCollection.

Grouped and ungrouped collections must be BIT-identical on every plane —
``forward``, ``forward_batched``, ``compute``, and the pure/sync plane —
because a compute group changes only how many times the shared update runs,
never what it computes. ``compute_groups=False`` is the escape hatch that
restores fully independent per-child execution.
"""
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu
from metrics_tpu import (
    Accuracy,
    F1,
    MetricCollection,
    Precision,
    Recall,
    Specificity,
)


def _shard_map(fn, mesh, in_specs, out_specs):
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


@pytest.fixture()
def jit_on():
    old = metrics_tpu.set_default_jit(True)
    yield
    metrics_tpu.set_default_jit(old)


# (name, metric builders, preds/target generator) — binary / multiclass
# macro / multiclass micro / multilabel, per the classification input modes
def _multiclass_data(rng, n=32, c=5):
    logits = rng.rand(n, c).astype(np.float32)
    probs = logits / logits.sum(-1, keepdims=True)
    return jnp.asarray(probs), jnp.asarray(rng.randint(0, c, n).astype(np.int32))


def _binary_data(rng, n=32):
    return (
        jnp.asarray(rng.rand(n).astype(np.float32)),
        jnp.asarray(rng.randint(0, 2, n).astype(np.int32)),
    )


def _multilabel_data(rng, n=32, c=4):
    return (
        jnp.asarray(rng.rand(n, c).astype(np.float32)),
        jnp.asarray(rng.randint(0, 2, (n, c)).astype(np.int32)),
    )


CONFIGS = {
    "binary-micro": (
        lambda: [Accuracy(), F1(), Precision(), Recall()],
        _binary_data,
    ),
    "multiclass-macro": (
        lambda: [
            Accuracy(),
            F1(num_classes=5, average="macro"),
            Precision(num_classes=5, average="macro"),
            Recall(num_classes=5, average="macro"),
            Specificity(num_classes=5, average="macro"),
        ],
        _multiclass_data,
    ),
    "multiclass-micro": (
        lambda: [F1(num_classes=5), Precision(num_classes=5), Recall(num_classes=5)],
        _multiclass_data,
    ),
    "multilabel-micro": (
        lambda: [F1(is_multiclass=False), Precision(is_multiclass=False)],
        _multilabel_data,
    ),
}


def _pair(name):
    build, gen = CONFIGS[name]
    return (
        MetricCollection(build()),
        MetricCollection(build(), compute_groups=False),
        gen,
    )


def _assert_same(a: dict, b: dict) -> None:
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]), err_msg=k)


def test_stat_family_reduces_to_one_group():
    mc = MetricCollection([
        Accuracy(),
        F1(num_classes=3, average="macro"),
        Precision(num_classes=3, average="macro"),
        Recall(num_classes=3, average="macro"),
        Specificity(num_classes=3, average="macro"),
    ])
    groups = mc.compute_groups
    assert groups["Accuracy"] == ("Accuracy",)
    assert groups["F1"] == ("F1", "Precision", "Recall", "Specificity")
    # the pure plane syncs one state pytree per group, not per member
    assert len(mc.init_state()) == 2


def test_differing_configs_never_group():
    # num_classes mismatch
    mc = MetricCollection([F1(num_classes=5, average="macro"), Precision(num_classes=3, average="macro")])
    assert all(len(m) == 1 for m in mc.compute_groups.values())
    # threshold mismatch
    mc = MetricCollection([F1(threshold=0.5), Precision(threshold=0.3)])
    assert all(len(m) == 1 for m in mc.compute_groups.values())
    # top_k mismatch
    mc = MetricCollection([
        Precision(num_classes=5, average="macro"),
        Recall(num_classes=5, average="macro", top_k=2),
    ])
    assert all(len(m) == 1 for m in mc.compute_groups.values())


def test_compute_groups_false_escape_hatch():
    mc = MetricCollection(
        [F1(num_classes=3, average="macro"), Precision(num_classes=3, average="macro")],
        compute_groups=False,
    )
    assert all(len(m) == 1 for m in mc.compute_groups.values())
    assert len(mc.init_state()) == 2


@pytest.mark.parametrize("config", list(CONFIGS))
def test_grouped_matches_ungrouped_forward_and_compute(config, jit_on):
    grouped, ungrouped, gen = _pair(config)
    rng = np.random.RandomState(7)
    batches = [gen(rng) for _ in range(4)]
    for preds, target in batches:
        _assert_same(grouped(preds, target), ungrouped(preds, target))
    _assert_same(grouped.compute(), ungrouped.compute())

    # ... and again after reset(): group state must restart from defaults
    grouped.reset()
    ungrouped.reset()
    for preds, target in batches[:2]:
        _assert_same(grouped(preds, target), ungrouped(preds, target))
    _assert_same(grouped.compute(), ungrouped.compute())


@pytest.mark.parametrize("config", ["multiclass-macro", "binary-micro"])
def test_grouped_matches_ungrouped_forward_batched(config, jit_on):
    grouped, ungrouped, gen = _pair(config)
    rng = np.random.RandomState(11)
    stack = [gen(rng) for _ in range(6)]
    preds = jnp.stack([p for p, _ in stack])
    target = jnp.stack([t for _, t in stack])
    _assert_same(grouped.forward_batched(preds, target), ungrouped.forward_batched(preds, target))
    _assert_same(grouped.compute(), ungrouped.compute())


def test_grouped_parity_survives_clone_and_pickle(jit_on):
    grouped, ungrouped, gen = _pair("multiclass-macro")
    rng = np.random.RandomState(3)
    preds, target = gen(rng)
    grouped(preds, target)
    ungrouped(preds, target)

    g2 = grouped.clone(prefix="c_")
    u2 = ungrouped.clone(prefix="c_")
    assert g2.compute_groups["F1"] == ("F1", "Precision", "Recall", "Specificity")
    # the escape hatch survives cloning
    assert all(len(m) == 1 for m in u2.compute_groups.values())
    preds2, target2 = gen(rng)
    _assert_same(g2(preds2, target2), u2(preds2, target2))
    _assert_same(g2.compute(), u2.compute())

    g3 = pickle.loads(pickle.dumps(grouped))
    u3 = pickle.loads(pickle.dumps(ungrouped))
    assert g3.compute_groups["F1"] == ("F1", "Precision", "Recall", "Specificity")
    assert all(len(m) == 1 for m in u3.compute_groups.values())
    _assert_same(g3(preds2, target2), u3(preds2, target2))
    _assert_same(g3.compute(), u3.compute())


def test_group_rebuilt_on_setitem_and_delitem(jit_on):
    mc = MetricCollection([
        F1(num_classes=4, average="macro"),
        Precision(num_classes=4, average="macro"),
    ])
    rng = np.random.RandomState(5)
    preds, target = _multiclass_data(rng, c=4)
    mc(preds, target)
    assert mc.compute_groups["F1"] == ("F1", "Precision")

    # adding a compatible member joins the existing group (fused step and
    # group map both rebuild under the generation guard)
    mc["Recall"] = Recall(num_classes=4, average="macro")
    assert mc.compute_groups["F1"] == ("F1", "Precision", "Recall")
    out = mc(preds, target)
    want = float(Recall(num_classes=4, average="macro")(preds, target))
    np.testing.assert_array_equal(np.asarray(out["Recall"]), want)

    # removing the representative reassigns the group to the next member
    del mc["F1"]
    assert mc.compute_groups["Precision"] == ("Precision", "Recall")
    mc(preds, target)

    # replacing a member with an incompatible config splits it out
    mc["Recall"] = Recall(num_classes=4, average="macro", top_k=2)
    assert mc.compute_groups["Precision"] == ("Precision",)


def test_individually_updated_member_keeps_own_state(jit_on):
    """The shared delta merges into each member's OWN accumulator, so a
    member also updated outside the collection stays individually correct."""
    mc = MetricCollection([
        Precision(num_classes=4, average="macro"),
        Recall(num_classes=4, average="macro"),
    ])
    rng = np.random.RandomState(9)
    preds, target = _multiclass_data(rng, c=4)
    mc(preds, target)
    preds2, target2 = _multiclass_data(rng, c=4)
    mc["Recall"].update(preds2, target2)  # out-of-collection update
    preds3, target3 = _multiclass_data(rng, c=4)
    mc(preds3, target3)

    want_p = Precision(num_classes=4, average="macro")
    want_r = Recall(num_classes=4, average="macro")
    for p, t in ((preds, target), (preds3, target3)):
        want_p.update(p, t)
    for p, t in ((preds, target), (preds2, target2), (preds3, target3)):
        want_r.update(p, t)
    np.testing.assert_array_equal(np.asarray(mc.compute()["Precision"]), np.asarray(want_p.compute()))
    np.testing.assert_array_equal(np.asarray(mc.compute()["Recall"]), np.asarray(want_r.compute()))


class _CountingSum(metrics_tpu.Metric):
    """Groupable metric whose update bumps a class-level call counter.

    ``scale`` is compute-only, so two instances with different scales still
    share one update — the delta-sharing observable the eager-path tests pin.
    """

    calls = 0
    _GROUP_UPDATE_ATTRS = ()

    def __init__(self, scale=1.0, **kw):
        super().__init__(**kw)
        self.scale = scale
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, x):
        type(self).calls += 1
        self.total = self.total + jnp.sum(x)

    def compute(self):
        return self.total * self.scale


def test_eager_update_shares_one_update_per_group():
    """The non-jit ``update()`` path runs ONE update per compute group: the
    representative's delta merges into every member's own accumulator."""
    _CountingSum.calls = 0
    mc = MetricCollection({"a": _CountingSum(1.0), "b": _CountingSum(2.0)})
    x = jnp.arange(4.0)
    mc.update(x)
    assert _CountingSum.calls == 1
    assert float(mc["a"].total) == 6.0 and float(mc["b"].total) == 6.0
    out = mc.compute()
    assert float(out["a"]) == 6.0 and float(out["b"]) == 12.0

    # escape hatch restores per-member updates
    _CountingSum.calls = 0
    mc2 = MetricCollection({"a": _CountingSum(1.0), "b": _CountingSum(2.0)}, compute_groups=False)
    mc2.update(x)
    assert _CountingSum.calls == 2


def test_eager_forward_dist_sync_on_step_shares_delta():
    """``dist_sync_on_step`` keeps the fused collection step off, but the
    eager fallback forward now shares the group delta too — each member
    still syncs its batch value through its own compute (semantics
    unchanged), and accumulators keep the LOCAL delta."""

    def gather(arr, **kw):
        return [arr, arr]  # fake 2-rank world

    _CountingSum.calls = 0
    mc = MetricCollection({
        "a": _CountingSum(1.0, dist_sync_on_step=True, dist_sync_fn=gather),
        "b": _CountingSum(2.0, dist_sync_on_step=True, dist_sync_fn=gather),
    })
    out = mc(jnp.arange(3.0))
    assert _CountingSum.calls == 1
    assert float(out["a"]) == 6.0 and float(out["b"]) == 12.0  # synced delta x scale
    assert float(mc["a"].total) == 3.0  # local accumulator survives the sync

    # second step accumulates on top of the first
    out = mc(jnp.arange(3.0))
    assert _CountingSum.calls == 2
    assert float(mc["b"].total) == 6.0 and float(out["b"]) == 12.0


def test_retrieval_family_forms_one_group():
    """RetrievalPrecision/Recall/MRR share the base flatten-append update, so
    matching-capacity instances fuse to ONE group (k and the empty-query
    policy are compute-only); results match the ungrouped collection."""
    from metrics_tpu import RetrievalMRR, RetrievalPrecision, RetrievalRecall

    def build(**kw):
        return [RetrievalPrecision(k=2), RetrievalRecall(k=1), RetrievalMRR()]

    mc = MetricCollection(build())
    groups = mc.compute_groups
    assert groups["RetrievalPrecision"] == ("RetrievalPrecision", "RetrievalRecall", "RetrievalMRR")
    assert len(mc.init_state()) == 1  # one idx/preds/target pytree per group

    ungrouped = MetricCollection(build(), compute_groups=False)
    idx = jnp.array([0, 0, 0, 1, 1, 1, 1])
    preds = jnp.array([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
    target = jnp.array([False, False, True, False, True, False, True])
    _assert_same(mc(idx, preds, target), ungrouped(idx, preds, target))
    _assert_same(mc.compute(), ungrouped.compute())

    # a capacity mismatch changes the state schema: never grouped
    split = MetricCollection([RetrievalPrecision(capacity=8), RetrievalRecall()])
    assert all(len(m) == 1 for m in split.compute_groups.values())


def test_sync_state_roundtrip_2device_mesh():
    """Grouped vs ungrouped pure sync over a real 2-device mesh collective
    program: bit-identical synced computes, with the grouped program moving
    one state pytree per group through the coalesced buckets."""
    from jax.sharding import Mesh, PartitionSpec as P

    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip(f"needs 2 devices, have {len(devices)}")
    mesh = Mesh(np.array(devices[:2]), ("dp",))

    rng = np.random.RandomState(17)
    preds, target = _multiclass_data(rng, n=32, c=5)

    results = {}
    for label, compute_groups in (("grouped", True), ("ungrouped", False)):
        pure = MetricCollection([
            Accuracy(),
            F1(num_classes=5, average="macro"),
            Precision(num_classes=5, average="macro"),
            Recall(num_classes=5, average="macro"),
        ], compute_groups=compute_groups).pure()

        def step(p, t, _pure=pure):
            delta = _pure.update(_pure.init(), p, t)
            synced = _pure.sync(delta, "dp")
            return _pure.compute(synced)

        fn = jax.jit(_shard_map(step, mesh, in_specs=(P("dp"), P("dp")), out_specs=P()))
        results[label] = {k: np.asarray(v) for k, v in fn(preds, target).items()}

    _assert_same(results["grouped"], results["ungrouped"])

    # the mesh sync must equal the single-device epoch over the full batch
    single = MetricCollection([
        Accuracy(),
        F1(num_classes=5, average="macro"),
        Precision(num_classes=5, average="macro"),
        Recall(num_classes=5, average="macro"),
    ])
    single.update(preds, target)
    _assert_same(results["grouped"], {k: np.asarray(v) for k, v in single.compute().items()})


# ------------------------------------------------- group-merged checkpoints
def _ckpt_collection():
    return MetricCollection([
        Accuracy(),
        F1(num_classes=4, average="macro"),
        Precision(num_classes=4, average="macro"),
        Recall(num_classes=4, average="macro"),
    ])


def _ckpt_batch(seed=0, rows=32):
    rng = np.random.RandomState(seed)
    preds = rng.rand(rows, 4).astype(np.float32)
    preds = preds / preds.sum(-1, keepdims=True)
    return jnp.asarray(preds), jnp.asarray(rng.randint(0, 4, rows).astype(np.int32))


def test_state_dict_merges_group_shards_and_roundtrips():
    """Group-aware checkpoint merging: ONE state copy per compute group plus
    a membership manifest; a fresh collection loads it and computes
    bit-identically. Per-member host metadata (_count_bound) persists."""
    col = _ckpt_collection()
    preds, target = _ckpt_batch()
    col.update(preds, target)
    col.persistent(True)
    sd = col.state_dict()

    # one full copy for the group representative, manifest for the rest
    assert sd["_compute_group_manifest"] == {"Precision": "F1", "Recall": "F1"}
    assert "F1.tp" in sd and "Accuracy.correct" in sd
    _META_SUFFIXES = ("_count_bound", "_epoch_watermark")  # per-member host metadata
    assert not any(
        k.startswith(("Precision.", "Recall.")) and not k.endswith(_META_SUFFIXES) for k in sd
    )
    # per-member host metadata still rides along
    assert int(sd["Recall._count_bound"]) == 32
    assert int(sd["Recall._epoch_watermark"]) == 1

    # orbax/pickle-friendly round trip into a FRESH collection
    restored = pickle.loads(pickle.dumps(sd))
    fresh = _ckpt_collection()
    fresh.persistent(True)
    fresh.load_state_dict(restored)
    a, b = col.compute(), fresh.compute()
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]), err_msg=k)
    assert fresh["Recall"]._count_bound == 32


def test_state_dict_keeps_diverged_member_entry():
    """A member written OUTSIDE the collection diverges by value: it keeps
    its own full checkpoint entry (sharing is value-checked at save time,
    never assumed from the group structure), and restores exactly."""
    col = _ckpt_collection()
    preds, target = _ckpt_batch()
    col.update(preds, target)
    col["Precision"].update(preds, target)  # out-of-collection write
    col.persistent(True)
    sd = col.state_dict()
    assert sd["_compute_group_manifest"] == {"Recall": "F1"}
    assert "Precision.tp" in sd

    fresh = _ckpt_collection()
    fresh.persistent(True)
    fresh.load_state_dict(sd)
    a, b = col.compute(), fresh.compute()
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]), err_msg=k)


def test_state_dict_plain_per_member_checkpoint_loads():
    """Back-compat: a checkpoint without a manifest (old per-member format)
    loads member by member unchanged."""
    col = _ckpt_collection()
    preds, target = _ckpt_batch(seed=5)
    col.update(preds, target)
    col.persistent(True)
    sd = {}
    for name, m in col.items():  # the pre-merge format
        m.state_dict(sd, prefix=f"{name}.")
    fresh = _ckpt_collection()
    fresh.persistent(True)
    fresh.load_state_dict(sd)
    a, b = col.compute(), fresh.compute()
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]), err_msg=k)


# ------------------------------------------- checkpoint after preemption
def _epoch_batches(n=4, rows=32):
    return [_ckpt_batch(seed=100 + i, rows=rows) for i in range(n)]


def test_checkpoint_after_preemption_replay_is_idempotent():
    """The kill/restore round-trip of a group-merged checkpoint mid-epoch:
    a collection checkpointed after step 1 is killed during step 2, a fresh
    collection restores it (watermark honored, group manifest fanned out),
    and a naive full replay of the epoch applies ONLY the lost steps —
    the final computes are bit-identical to an uninterrupted epoch."""
    batches = _epoch_batches()

    reference = _ckpt_collection()
    reference.persistent(True)
    for i, (p, t) in enumerate(batches):
        assert reference.guarded_update(i, p, t)
    ref = reference.compute()

    victim = _ckpt_collection()
    victim.persistent(True)
    victim.guarded_update(0, *batches[0])
    victim.guarded_update(1, *batches[1])
    checkpoint = pickle.loads(pickle.dumps(victim.state_dict()))
    # step 2 lands in memory only — the "kill" below loses it, which is
    # exactly the state a preempted loop restores from
    victim.guarded_update(2, *batches[2])
    del victim

    fresh = _ckpt_collection()
    fresh.persistent(True)
    fresh.load_state_dict(checkpoint)
    # the checkpoint is still group-merged: one full copy per compute group
    assert checkpoint["_compute_group_manifest"] == {"Precision": "F1", "Recall": "F1"}
    # watermark honored across the restore: 2 steps are already in
    assert fresh.epoch_watermark == 2
    applied = [fresh.guarded_update(i, p, t) for i, (p, t) in enumerate(batches)]
    assert applied == [False, False, True, True]
    _assert_same(
        {k: np.asarray(v) for k, v in ref.items()},
        {k: np.asarray(v) for k, v in fresh.compute().items()},
    )


def test_replaying_the_last_checkpointed_step_is_a_noop():
    """The acceptance shape of preemption-safe resume: after restore, the
    step that was in flight at the kill is replayed — and must change
    NOTHING (state arrays bit-identical, guarded_update returns False)."""
    batches = _epoch_batches(2)
    col = _ckpt_collection()
    col.persistent(True)
    col.guarded_update(0, *batches[0])
    col.guarded_update(1, *batches[1])
    checkpoint = col.state_dict()

    fresh = _ckpt_collection()
    fresh.persistent(True)
    fresh.load_state_dict(checkpoint)
    before = {k: m._current_state() for k, m in fresh.items()}
    assert fresh.guarded_update(1, *batches[1]) is False  # the in-flight step
    after = {k: m._current_state() for k, m in fresh.items()}
    for name in before:
        for state_key in before[name]:
            np.testing.assert_array_equal(
                np.asarray(before[name][state_key]),
                np.asarray(after[name][state_key]),
                err_msg=f"{name}.{state_key}",
            )
    assert fresh.epoch_watermark == 2


def test_watermark_survives_member_level_roundtrip():
    """Metric-level checkpoints carry the watermark too (the collection path
    fans it out per member; the plain path reads it directly)."""
    m = Accuracy()
    m.persistent(True)
    p, t = _ckpt_batch(seed=9)
    m.guarded_update(0, jnp.argmax(p, axis=-1) == t, (jnp.argmax(p, axis=-1) == t).astype(jnp.int32))
    sd = m.state_dict()
    assert int(sd["_epoch_watermark"]) == 1
    fresh = Accuracy()
    fresh.persistent(True)
    fresh.load_state_dict(sd)
    assert fresh.epoch_watermark == 1
    assert fresh.guarded_update(0, p, t) is False
