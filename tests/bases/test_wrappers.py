"""Wrapper family: MinMaxMetric, ClasswiseWrapper, BootStrapper, MetricTracker."""
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import precision_score as sk_precision_score

from metrics_tpu import (
    Accuracy,
    BootStrapper,
    ClasswiseWrapper,
    MeanAbsoluteError,
    MetricTracker,
    MinMaxMetric,
    Precision,
)

_rng = np.random.RandomState(19)


# ------------------------------------------------------------- MinMaxMetric
def test_minmax_tracks_extrema_across_epochs():
    m = MinMaxMetric(Accuracy())
    m.update(jnp.array([1, 1, 0, 0]), jnp.array([1, 0, 0, 0]))  # acc 0.75
    out = m.compute()
    assert float(out["raw"]) == float(out["min"]) == float(out["max"]) == 0.75

    m.base_metric.reset()
    m.update(jnp.array([1, 1, 0, 0]), jnp.array([1, 1, 0, 0]))  # acc 1.0
    out = m.compute()
    assert float(out["raw"]) == 1.0 and float(out["min"]) == 0.75 and float(out["max"]) == 1.0

    m.reset()
    out_after = m.compute()  # nan raw (no data), +-inf extrema untouched yet
    assert np.isinf(float(out_after["min"]))


def test_minmax_rejects_non_metric():
    with pytest.raises(ValueError, match="Metric"):
        MinMaxMetric(lambda: None)


# --------------------------------------------------------- ClasswiseWrapper
def test_classwise_wrapper_labels_and_values():
    p = _rng.randint(0, 3, 64).astype(np.int32)
    t = _rng.randint(0, 3, 64).astype(np.int32)
    m = ClasswiseWrapper(Precision(num_classes=3, average=None), labels=["a", "b", "c"])
    m.update(jnp.asarray(p), jnp.asarray(t))
    out = m.compute()
    want = sk_precision_score(t, p, average=None, zero_division=0)
    for i, lab in enumerate(["a", "b", "c"]):
        np.testing.assert_allclose(float(out[f"precision_{lab}"]), want[i], atol=1e-6)

    # default labels + prefix
    m2 = ClasswiseWrapper(Precision(num_classes=3, average=None), prefix="p_")
    m2.update(jnp.asarray(p), jnp.asarray(t))
    assert sorted(m2.compute()) == ["p_0", "p_1", "p_2"]


def test_classwise_wrapper_validation():
    with pytest.raises(ValueError, match="labels"):
        ClasswiseWrapper(Precision(num_classes=3, average=None), labels=[1, 2, 3])
    m = ClasswiseWrapper(Precision(num_classes=3, average=None), labels=["a", "b"])
    m.update(jnp.array([0, 1, 2]), jnp.array([0, 1, 2]))
    with pytest.raises(ValueError, match="labels for"):
        m.compute()
    scalar = ClasswiseWrapper(Accuracy())
    scalar.update(jnp.array([0, 1]), jnp.array([0, 1]))
    with pytest.raises(ValueError, match="1-D"):
        scalar.compute()


# ------------------------------------------------------------- BootStrapper
def test_bootstrapper_mean_std_and_determinism():
    p = _rng.rand(256).astype(np.float32) * 10
    t = p + _rng.randn(256).astype(np.float32)

    m1 = BootStrapper(MeanAbsoluteError(), num_bootstraps=20, seed=3, raw=True)
    m1.update(jnp.asarray(p), jnp.asarray(t))
    out1 = m1.compute()
    assert out1["raw"].shape == (20,)
    # bootstrap mean is near the full-sample value, std is small but nonzero
    full = float(np.abs(p - t).mean())
    assert abs(float(out1["mean"]) - full) < 0.2
    assert 0 < float(out1["std"]) < 0.5

    # same seed -> identical resamples; different seed -> different
    m2 = BootStrapper(MeanAbsoluteError(), num_bootstraps=20, seed=3, raw=True)
    m2.update(jnp.asarray(p), jnp.asarray(t))
    np.testing.assert_array_equal(np.asarray(out1["raw"]), np.asarray(m2.compute()["raw"]))
    m3 = BootStrapper(MeanAbsoluteError(), num_bootstraps=20, seed=4, raw=True)
    m3.update(jnp.asarray(p), jnp.asarray(t))
    assert not np.array_equal(np.asarray(out1["raw"]), np.asarray(m3.compute()["raw"]))


def test_bootstrapper_validation_and_reset():
    with pytest.raises(ValueError, match="num_bootstraps"):
        BootStrapper(Accuracy(), num_bootstraps=0)
    m = BootStrapper(MeanAbsoluteError(), num_bootstraps=3)
    m.update(jnp.arange(8.0), jnp.arange(8.0) + 1)
    m.reset()
    m.update(jnp.arange(4.0), jnp.arange(4.0))
    assert float(m.compute()["mean"]) == 0.0


def test_bootstrapper_vmapped_single_program():
    """The TPU-first path: stacked states + one vmapped program per step —
    no per-copy child metrics are ever built, and the loop fallback (forced)
    reproduces bit-identical values from the same seed."""
    p = _rng.rand(128).astype(np.float32) * 5
    t = p + _rng.randn(128).astype(np.float32)

    fast = BootStrapper(MeanAbsoluteError(), num_bootstraps=8, seed=11, raw=True)
    for s in range(3):
        fast.update(jnp.asarray(p + s), jnp.asarray(t))
    assert fast._mode == "vmapped" and fast.metrics is None  # no K-dispatch loop
    assert {n for n in fast._stacked} == set(fast._template._defaults)
    assert all(v.shape[0] == 8 for v in fast._stacked.values())

    slow = BootStrapper(MeanAbsoluteError(), num_bootstraps=8, seed=11, raw=True)
    slow._mode = "loop"  # force the per-copy fallback
    for s in range(3):
        slow.update(jnp.asarray(p + s), jnp.asarray(t))
    np.testing.assert_allclose(
        np.asarray(fast.compute()["raw"]), np.asarray(slow.compute()["raw"]), rtol=1e-6
    )


def test_bootstrapper_forward_one_dispatch_value():
    """forward on the vmapped path returns batch-local mean/std and still
    accumulates (epoch compute sees all batches)."""
    m = BootStrapper(MeanAbsoluteError(), num_bootstraps=4, seed=9)
    p = jnp.arange(64.0)
    out1 = m(p, p + 2.0)
    out2 = m(p, p + 4.0)
    assert abs(float(out1["mean"]) - 2.0) < 1e-6
    assert abs(float(out2["mean"]) - 4.0) < 1e-6
    assert m.metrics is None
    assert abs(float(m.compute()["mean"]) - 3.0) < 1e-6  # both batches accumulated


def test_bootstrapper_untraceable_base_falls_back():
    """A base whose update needs concrete values (mode inference) silently
    takes the per-copy loop with the same drawn resamples."""
    m = BootStrapper(Accuracy(), num_bootstraps=3, seed=1, raw=True)
    p = jnp.asarray((_rng.rand(64) > 0.5).astype(np.int32))
    t = jnp.asarray((_rng.rand(64) > 0.5).astype(np.int32))
    m.update(p, t)
    assert m._mode == "loop" and m.metrics is not None
    out = m.compute()
    assert out["raw"].shape == (3,)
    # same seed, forced loop from the start: identical draws either way
    m2 = BootStrapper(Accuracy(), num_bootstraps=3, seed=1, raw=True)
    m2._mode = "loop"
    m2.update(p, t)
    np.testing.assert_array_equal(np.asarray(out["raw"]), np.asarray(m2.compute()["raw"]))


def test_bootstrapper_concrete_compute_keeps_vmapped_update():
    """A base whose update traces but whose compute needs concrete values
    keeps the one-dispatch vmapped update; only the value goes eager
    per-copy (the base Metric's _fc_failed tier) — and epoch compute()
    works instead of crashing."""
    import jax.numpy as jnp2
    from metrics_tpu import Metric

    class ConcreteCompute(Metric):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state("s", jnp2.asarray(0.0), dist_reduce_fx="sum")
            self.add_state("n", jnp2.asarray(0.0), dist_reduce_fx="sum")

        def update(self, p, t):
            self.s = self.s + jnp2.sum(jnp2.abs(p - t))
            self.n = self.n + p.shape[0]

        def compute(self):
            if float(self.n) == 0:  # concrete branch: cannot trace
                return jnp2.asarray(0.0)
            return self.s / self.n

    m = BootStrapper(ConcreteCompute(), num_bootstraps=4, seed=21)
    p = jnp.arange(32.0)
    out = m(p, p + 3.0)  # forward: stats tier fails, deltas tier succeeds
    assert m._mode == "vmapped" and m._fc_failed and m.metrics is None
    np.testing.assert_allclose(float(out["mean"]), 3.0, atol=1e-6)
    m.update(p, p + 3.0)  # update stays on the vmapped path
    assert m._mode == "vmapped"
    np.testing.assert_allclose(float(m.compute()["mean"]), 3.0, atol=1e-6)

    # update()+compute() alone (never forward) also survives
    m2 = BootStrapper(ConcreteCompute(), num_bootstraps=3, seed=22)
    m2.update(p, p + 5.0)
    assert m2._mode == "vmapped"
    np.testing.assert_allclose(float(m2.compute()["mean"]), 5.0, atol=1e-6)
    assert m2._mode == "vmapped"  # epoch compute fell back eagerly, updates stay fused


def test_bootstrapper_mid_epoch_fallback_keeps_state():
    """A vmapped->loop fallback after batches were already accumulated must
    transfer the stacked state to the children — no batch silently lost."""
    from metrics_tpu.utils.exceptions import TracingUnsupportedError

    m = BootStrapper(MeanAbsoluteError(), num_bootstraps=4, seed=13)
    p = jnp.arange(32.0)
    m.update(p, p + 2.0)
    m.update(p, p + 2.0)
    assert m._mode == "vmapped"

    def boom(*_a, **_k):
        raise TracingUnsupportedError("injected")

    m._build_vstep = boom  # next new-signature step build fails mid-epoch
    m.update(p, target=p + 8.0)  # kwargs: a new step signature
    assert m._mode == "loop" and m.metrics is not None
    # epoch mean over 3 batches with MAE 2, 2, 8 (resampling preserves
    # constant offsets exactly): (2 + 2 + 8) / 3 per copy
    np.testing.assert_allclose(float(m.compute()["mean"]), 4.0, atol=1e-6)


def test_bootstrapper_pickle_and_clone_mid_accumulation():
    import pickle

    m = BootStrapper(MeanAbsoluteError(), num_bootstraps=4, seed=3)
    m.update(jnp.arange(16.0), jnp.arange(16.0) + 1.5)
    c = m.clone()
    r = pickle.loads(pickle.dumps(m))
    for other in (c, r):
        other.update(jnp.arange(16.0), jnp.arange(16.0) + 1.5)
        assert abs(float(other.compute()["mean"]) - 1.5) < 1e-6
    assert abs(float(m.compute()["mean"]) - 1.5) < 1e-6  # original untouched


# ------------------------------------------------------------- MetricTracker
def test_tracker_epochs_best_and_history():
    tracker = MetricTracker(Accuracy(), maximize=True)
    accs = []
    for epoch in range(3):
        tracker.increment()
        p = jnp.asarray([1, 1, 0, 0])
        t = jnp.asarray([1, epoch % 2, 0, 0])
        tracker(p, t)
        accs.append(float(tracker.compute()))
    all_vals = np.asarray(tracker.compute_all())
    np.testing.assert_allclose(all_vals, accs, atol=1e-6)
    best, step = tracker.best_metric(return_step=True)
    assert float(best) == max(accs) and step == int(np.argmax(accs))

    # minimize mode
    mt = MetricTracker(MeanAbsoluteError(), maximize=False)
    for err in (2.0, 0.5, 1.0):
        mt.increment()
        mt.update(jnp.zeros(4), jnp.full((4,), err))
    assert float(mt.best_metric()) == 0.5

    # reset clears only the current increment; reset_all clears history
    assert tracker.n_steps == 3
    tracker.reset_all()
    assert tracker.n_steps == 0
    with pytest.raises(RuntimeError, match="increment"):
        tracker.update(jnp.array([1]), jnp.array([1]))


# --------------------------------------------- forward paths (fused bypass)
def test_wrappers_forward_accumulates_under_default_jit():
    """Wrappers hold child metrics (not registered states): their forward
    must bypass the fused jitted path and still accumulate."""
    import metrics_tpu

    old = metrics_tpu.set_default_jit(True)
    try:
        bs = BootStrapper(MeanAbsoluteError(), num_bootstraps=4, seed=5)
        p = jnp.arange(32.0)
        t = p + 1.0
        out = bs(p, t)  # forward: batch value AND accumulation
        assert abs(float(out["mean"]) - 1.0) < 1e-6
        after = bs.compute()
        assert abs(float(after["mean"]) - 1.0) < 1e-6  # children really accumulated

        mm = MinMaxMetric(Accuracy())
        v1 = mm(jnp.array([1, 1, 0, 0]), jnp.array([1, 0, 0, 0]))  # 0.75
        v2 = mm(jnp.array([1, 1, 0, 0]), jnp.array([1, 1, 0, 0]))  # 1.0
        assert float(v1["raw"]) == 0.75 and float(v2["raw"]) == 1.0
        # the first step's extrema write persisted through the second forward
        assert float(v2["min"]) == 0.75 and float(v2["max"]) == 1.0
        out = mm.compute()
        assert float(out["min"]) == 0.75 and float(out["max"]) == 1.0
    finally:
        metrics_tpu.set_default_jit(old)


def test_bootstrapper_kwargs_resampled_consistently():
    """preds/target must stay paired when passed as kwargs."""
    p = jnp.asarray(_rng.rand(128).astype(np.float32))
    m = BootStrapper(MeanAbsoluteError(), num_bootstraps=6, seed=2)
    m.update(p, target=p)  # identical pairs: MAE must be exactly 0 in every copy
    out = m.compute()
    assert float(out["mean"]) == 0.0 and float(out["std"]) == 0.0


def test_tracker_reset_clears_cache():
    t = MetricTracker(Accuracy())
    t.increment()
    t.update(jnp.array([1, 1]), jnp.array([1, 1]))
    assert float(t.compute()) == 1.0
    t.reset()
    assert np.isnan(float(t.compute()))  # empty state, not the stale cache


def test_bootstrapper_requires_two_copies():
    with pytest.raises(ValueError, match=">= 2"):
        BootStrapper(Accuracy(), num_bootstraps=1)


def test_multioutput_matches_per_column_metrics():
    from metrics_tpu import MeanSquaredError, MultioutputWrapper, R2Score

    rng = np.random.RandomState(11)
    preds = rng.randn(6, 32, 3).astype(np.float32)
    target = (preds + 0.3 * rng.randn(6, 32, 3)).astype(np.float32)

    wrapper = MultioutputWrapper(MeanSquaredError(), num_outputs=3)
    singles = [MeanSquaredError() for _ in range(3)]
    for b in range(6):
        step_vec = wrapper(jnp.asarray(preds[b]), jnp.asarray(target[b]))
        step_single = [m(jnp.asarray(preds[b, :, i]), jnp.asarray(target[b, :, i]))
                       for i, m in enumerate(singles)]
        np.testing.assert_allclose(np.asarray(step_vec), [float(v) for v in step_single], rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(wrapper.compute()), [float(m.compute()) for m in singles], rtol=1e-6
    )
    # r2 over columns too (different state structure)
    w2 = MultioutputWrapper(R2Score(), num_outputs=3)
    for b in range(6):
        w2.update(jnp.asarray(preds[b]), jnp.asarray(target[b]))
    from sklearn.metrics import r2_score

    want = r2_score(target.reshape(-1, 3), preds.reshape(-1, 3), multioutput="raw_values")
    np.testing.assert_allclose(np.asarray(w2.compute()), want, atol=1e-4)


def test_multioutput_remove_nans():
    from metrics_tpu import MeanSquaredError, MultioutputWrapper

    preds = jnp.asarray(np.array([[1.0, np.nan], [2.0, 5.0], [3.0, 6.0]], dtype=np.float32))
    target = jnp.asarray(np.array([[1.0, 4.0], [np.nan, 5.0], [3.0, 8.0]], dtype=np.float32))
    m = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
    m.update(preds, target)
    # col0 keeps rows {0, 2} -> mse 0; col1 keeps rows {1, 2} -> mse (0+4)/2
    np.testing.assert_allclose(np.asarray(m.compute()), [0.0, 2.0], atol=1e-6)

    keep = MultioutputWrapper(MeanSquaredError(), num_outputs=2, remove_nans=False)
    keep.update(preds, target)
    assert np.isnan(np.asarray(keep.compute())).all()


def test_multioutput_reset_and_validation():
    from metrics_tpu import MeanSquaredError, MultioutputWrapper

    m = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
    m.update(jnp.ones((4, 2)), jnp.zeros((4, 2)))
    m.reset()
    for child in m.metrics:
        assert float(child.total) == 0
    with pytest.raises(ValueError, match="positive int"):
        MultioutputWrapper(MeanSquaredError(), num_outputs=0)
    with pytest.raises(ValueError, match="must be a Metric"):
        MultioutputWrapper(lambda: None, num_outputs=2)


def test_multioutput_pickle_mid_accumulation():
    import pickle

    from metrics_tpu import MeanSquaredError, MultioutputWrapper

    m = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
    m.update(jnp.ones((4, 2)), jnp.zeros((4, 2)))
    m2 = pickle.loads(pickle.dumps(m))
    m2.update(jnp.zeros((4, 2)), jnp.zeros((4, 2)))
    np.testing.assert_allclose(np.asarray(m2.compute()), [0.5, 0.5], atol=1e-6)


def test_running_window_matches_fresh_metric():
    from metrics_tpu import Accuracy, MeanSquaredError, Running

    rng = np.random.RandomState(13)
    preds = rng.rand(8, 16).astype(np.float32)
    target = rng.randint(0, 2, (8, 16))

    running = Running(Accuracy(), window=3)
    for b in range(8):
        running.update(jnp.asarray(preds[b]), jnp.asarray(target[b]))
        fresh = Accuracy()
        for w in range(max(0, b - 2), b + 1):
            fresh.update(jnp.asarray(preds[w]), jnp.asarray(target[w]))
        np.testing.assert_allclose(float(running.compute()), float(fresh.compute()), atol=1e-6)

    # window=1 == per-batch value
    r1 = Running(MeanSquaredError(), window=1)
    for b in range(4):
        step = r1(jnp.asarray(preds[b]), jnp.asarray(preds[b] * 0.5))
        single = MeanSquaredError()(jnp.asarray(preds[b]), jnp.asarray(preds[b] * 0.5))
        np.testing.assert_allclose(float(step), float(single), atol=1e-6)


def test_running_checkpoint_round_trip_restores_the_window():
    """The pre-fix data loss: ``Running`` keeps its window in ``_deltas``,
    which the base ``state_dict`` never serialized — a restored ``Running``
    silently computed over an EMPTY window. The round-trip must restore the
    window exactly (deltas + the epoch watermark for idempotent replay)."""
    from metrics_tpu import MeanSquaredError, Running

    running = Running(MeanSquaredError(), window=2)
    for step in range(4):
        running(jnp.asarray([float(step)]), jnp.asarray([0.0]))
    saved = running.state_dict()
    # the window must actually be IN the checkpoint, not just in memory
    assert len(saved["_running_deltas"]) == 2
    assert all(isinstance(v, np.ndarray) for d in saved["_running_deltas"] for v in d.values())

    restored = Running(MeanSquaredError(), window=2)
    restored.load_state_dict(saved)
    assert len(restored._deltas) == 2
    np.testing.assert_allclose(float(restored.compute()), 6.5, atol=1e-6)  # (2^2+3^2)/2
    np.testing.assert_allclose(float(restored.compute()), float(running.compute()), atol=1e-6)

    # the watermark entry rides along: replaying the last folded step no-ops
    assert restored.epoch_watermark == running.epoch_watermark == 4
    assert restored.guarded_update(3, jnp.asarray([3.0]), jnp.asarray([0.0])) is False
    assert restored.guarded_update(4, jnp.asarray([4.0]), jnp.asarray([0.0])) is True
    np.testing.assert_allclose(float(restored.compute()), (3.0**2 + 4.0**2) / 2, atol=1e-6)

    # a restored window keeps sliding correctly and respects `window`
    wide = Running(MeanSquaredError(), window=3)
    for step in range(3):
        wide(jnp.asarray([float(step)]), jnp.asarray([0.0]))
    narrow = Running(MeanSquaredError(), window=2)
    narrow.load_state_dict(wide.state_dict())  # extra deltas truncate to the window
    assert len(narrow._deltas) == 2
    np.testing.assert_allclose(float(narrow.compute()), (1.0 + 4.0) / 2, atol=1e-6)

    # pre-fix checkpoints (no deltas entry) still load, window empty
    legacy = {k: v for k, v in saved.items() if k != "_running_deltas"}
    old = Running(MeanSquaredError(), window=2)
    old.load_state_dict(legacy)
    assert old._deltas == []


def test_running_reset_and_validation():
    from metrics_tpu import MeanSquaredError, Running

    r = Running(MeanSquaredError(), window=2)
    r.update(jnp.ones(4), jnp.zeros(4))
    r.reset()
    assert np.isnan(float(r.compute()))  # empty: 0/0
    with pytest.raises(ValueError, match="positive int"):
        Running(MeanSquaredError(), window=0)
    with pytest.raises(ValueError, match="must be a Metric"):
        Running(object(), window=2)
