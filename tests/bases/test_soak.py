"""Compile + memory soak guards.

Protect the step-sharing machinery (core/metric.py) against regressions that
would silently re-introduce per-step retraces or per-step buffer leaks: the
fused step must compile ONCE, then replay for every subsequent step and for
every config-identical instance, with a flat live-buffer population.
"""
import gc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu
from metrics_tpu import Accuracy, F1, MetricCollection, Precision


@pytest.fixture()
def jit_on():
    old = metrics_tpu.set_default_jit(True)
    yield
    metrics_tpu.set_default_jit(old)


def _batch(rng, n=32, c=5):
    p = rng.rand(n, c).astype(np.float32)
    p /= p.sum(-1, keepdims=True)
    return jnp.asarray(p), jnp.asarray(rng.randint(0, c, n).astype(np.int32))


def test_fused_step_zero_retraces_and_flat_buffers(jit_on):
    rng = np.random.RandomState(0)
    preds, target = _batch(rng)

    m = Accuracy()
    jax.block_until_ready(m(preds, target))  # step 1: trace + compile
    step = m._jitted_step_fc
    assert step is not None
    traces = step._cache_size()

    jax.block_until_ready(m.compute())
    gc.collect()
    n_live = len(jax.live_arrays())
    for _ in range(50):
        m(preds, target)
    jax.block_until_ready(m.compute())

    # zero retraces after step 1
    assert step._cache_size() == traces
    # flat device-buffer population: steady state allocates nothing beyond
    # the rotating state/value buffers (slack for the last step's outputs)
    gc.collect()
    assert len(jax.live_arrays()) <= n_live + 8


def test_shared_step_across_instances_no_recompile(jit_on):
    rng = np.random.RandomState(1)
    preds, target = _batch(rng)

    first = Accuracy()
    jax.block_until_ready(first(preds, target))
    step = first._jitted_step_fc
    traces = step._cache_size()

    for _ in range(10):
        m = Accuracy()  # config-identical: must share the SAME jitted step
        m(preds, target)
        assert m._jitted_step_fc is step
    assert step._cache_size() == traces


def test_collection_fused_step_soak(jit_on):
    rng = np.random.RandomState(2)
    preds, target = _batch(rng, c=8)

    coll = MetricCollection([
        Accuracy(),
        Precision(num_classes=8, average="macro"),
        F1(num_classes=8, average="macro"),
    ])
    jax.block_until_ready(jax.tree_util.tree_leaves(coll(preds, target)))
    gc.collect()
    n_live = len(jax.live_arrays())
    for _ in range(30):
        coll(preds, target)
    out = coll.compute()
    jax.block_until_ready(jax.tree_util.tree_leaves(out))
    gc.collect()
    assert len(jax.live_arrays()) <= n_live + 12


def test_forward_batched_scan_step_soak(jit_on):
    rng = np.random.RandomState(3)
    p = rng.rand(8, 16, 5).astype(np.float32)
    p /= p.sum(-1, keepdims=True)
    stacked_p = jnp.asarray(p)
    stacked_t = jnp.asarray(rng.randint(0, 5, (8, 16)).astype(np.int32))

    m = Accuracy()
    jax.block_until_ready(m.forward_batched(stacked_p, stacked_t))
    step = m._jitted_scan[1]
    traces = step._cache_size()
    gc.collect()
    n_live = len(jax.live_arrays())
    for _ in range(20):
        m2 = Accuracy()
        m2.forward_batched(stacked_p, stacked_t)
        jax.block_until_ready(m2.compute())
    assert step._cache_size() == traces
    gc.collect()
    assert len(jax.live_arrays()) <= n_live + 8
