"""Group-aware host-plane sync of MetricCollection.compute().

On multi-host (or with a custom ``dist_sync_fn``), each member of a compute
group used to gather its — identical — state independently. The collection
now proves lockstep host-side (array-identity tracking, zero device work) and
routes ONE gather per group through the group's first lockstep member, while
members written outside the collection fall back to their own sync. The
contract: values are bit-identical to the fully-independent path, only the
number of gather calls shrinks.

A counting fake ``dist_sync_fn`` doubles as the two-rank world: it returns
``[x, x]``, exactly what ``gather_all_arrays`` yields on two ranks in
lockstep, and its call count is the observable being optimized.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import Accuracy, F1, MetricCollection, Precision, Recall
from metrics_tpu import observability as obs


class _CountingGather:
    """fn(array) -> [array, array]: a fake 2-rank world that counts calls."""

    def __init__(self):
        self.calls = 0

    def __call__(self, x):
        self.calls += 1
        return [x, x]


def _collection(gather, compute_groups=True):
    return MetricCollection(
        [
            Accuracy(dist_sync_fn=gather),
            F1(num_classes=4, average="macro", dist_sync_fn=gather),
            Precision(num_classes=4, average="macro", dist_sync_fn=gather),
            Recall(num_classes=4, average="macro", dist_sync_fn=gather),
        ],
        compute_groups=compute_groups,
    )


def _data(rng, n=32, c=4):
    logits = rng.rand(n, c).astype(np.float32)
    return (
        jnp.asarray(logits / logits.sum(-1, keepdims=True)),
        jnp.asarray(rng.randint(0, c, n).astype(np.int32)),
    )


def _assert_same(a: dict, b: dict) -> None:
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]), err_msg=k)


def test_grouped_sync_shares_one_gather_per_group():
    rng = np.random.RandomState(11)
    preds, target = _data(rng)

    grouped_gather, ungrouped_gather = _CountingGather(), _CountingGather()
    grouped = _collection(grouped_gather)
    ungrouped = _collection(ungrouped_gather, compute_groups=False)
    grouped(preds, target)
    ungrouped(preds, target)

    _assert_same(grouped.compute(), ungrouped.compute())

    # ungrouped: every member gathers its own states — Accuracy (2 leaves) +
    # 3 x StatScores (4 leaves) = 14 gather calls. Grouped: Accuracy alone
    # (singleton group, 2) + ONE gather plane for the F1/Precision/Recall
    # group (4) = 6 — the same 6-vs-14 dedup the pure plane reports.
    assert ungrouped_gather.calls == 14
    assert grouped_gather.calls == 6


def test_grouped_sync_savings_visible_in_counters():
    rng = np.random.RandomState(12)
    preds, target = _data(rng)
    gather = _CountingGather()
    mc = _collection(gather)
    mc(preds, target)

    obs.enable()
    obs.reset()
    mc.compute()
    snap = obs.counters_snapshot()
    obs.disable()
    # one shared plane (4 StatScores leaves) + Accuracy's own (2 leaves)
    assert snap["states_synced"] == 6


def test_member_updated_outside_collection_syncs_alone():
    rng = np.random.RandomState(13)
    preds, target = _data(rng)
    preds2, target2 = _data(rng)

    grouped_gather, ungrouped_gather = _CountingGather(), _CountingGather()
    grouped = _collection(grouped_gather)
    ungrouped = _collection(ungrouped_gather, compute_groups=False)
    grouped(preds, target)
    ungrouped(preds, target)
    # out-of-collection write: Recall leaves lockstep with its group
    grouped["Recall"].update(preds2, target2)
    ungrouped["Recall"].update(preds2, target2)

    _assert_same(grouped.compute(), ungrouped.compute())
    assert ungrouped_gather.calls == 14
    # Accuracy (2) + shared F1/Precision plane (4) + diverged Recall alone (4)
    assert grouped_gather.calls == 10


def test_collection_reset_restores_lockstep():
    rng = np.random.RandomState(14)
    preds, target = _data(rng)
    gather = _CountingGather()
    mc = _collection(gather)
    mc(preds, target)
    mc["Recall"].update(preds, target)  # diverge
    mc.compute()
    diverged_calls = gather.calls

    mc.reset()
    mc(preds, target)
    gather.calls = 0
    ungrouped = _collection(_CountingGather(), compute_groups=False)
    ungrouped(preds, target)
    _assert_same(mc.compute(), ungrouped.compute())
    assert gather.calls == 6  # full sharing again after reset
    assert diverged_calls == 10


def test_second_compute_hits_member_caches():
    rng = np.random.RandomState(15)
    preds, target = _data(rng)
    gather = _CountingGather()
    mc = _collection(gather)
    mc(preds, target)
    first = mc.compute()
    calls_after_first = gather.calls
    _assert_same(mc.compute(), first)  # cached: no further gathers
    assert gather.calls == calls_after_first


def test_grouped_sync_preserves_local_state():
    """The shared sync must restore each member's LOCAL accumulator, exactly
    like the individual synced-compute path (reference metric.py:208-239)."""
    rng = np.random.RandomState(16)
    preds, target = _data(rng)
    gather = _CountingGather()
    mc = _collection(gather)
    mc(preds, target)
    synced = mc.compute()

    # keep accumulating after the synced compute: the local (unsynced) state
    # must have survived, so a fresh single-"rank" collection fed the same
    # batches twice each (the fake gather doubles the world) agrees
    preds2, target2 = _data(rng)
    mc(preds2, target2)
    twice = _collection(_CountingGather())
    twice(preds, target)
    twice(preds2, target2)
    _assert_same(mc.compute(), twice.compute())
    assert synced is not None


def test_escape_hatch_disables_sharing():
    rng = np.random.RandomState(17)
    preds, target = _data(rng)
    gather = _CountingGather()
    mc = _collection(gather, compute_groups=False)
    mc(preds, target)
    mc.compute()
    assert gather.calls == 14


# ------------------------------------------------- per-step delta sync sharing
def _on_step_collection(gather, compute_groups=True):
    """The dist_sync_on_step shape: every member syncs its delta per forward."""
    return MetricCollection(
        [
            Accuracy(dist_sync_on_step=True, dist_sync_fn=gather),
            F1(num_classes=4, average="macro", dist_sync_on_step=True, dist_sync_fn=gather),
            Precision(num_classes=4, average="macro", dist_sync_on_step=True, dist_sync_fn=gather),
            Recall(num_classes=4, average="macro", dist_sync_on_step=True, dist_sync_fn=gather),
        ],
        compute_groups=compute_groups,
    )


def test_per_step_delta_sync_shares_one_gather_per_group():
    """``dist_sync_on_step`` compute-group members share ONE delta gather per
    step: the group's batch delta is identical by construction, so gathering
    it through each member's compute moved the same payload N times. Values
    must be bit-identical to the fully-independent path."""
    rng = np.random.RandomState(21)
    preds, target = _data(rng)

    grouped_gather, ungrouped_gather = _CountingGather(), _CountingGather()
    grouped = _on_step_collection(grouped_gather)
    ungrouped = _on_step_collection(ungrouped_gather, compute_groups=False)

    _assert_same(grouped(preds, target), ungrouped(preds, target))

    # ungrouped: every member gathers its own delta — Accuracy (2 leaves) +
    # 3 x StatScores (4 leaves) = 14 calls per step. Grouped: Accuracy's own
    # sync (singleton group, 2) + ONE shared plane for the F1/Precision/
    # Recall group (4) = 6 — the per-step mirror of the epoch-level sharing.
    assert ungrouped_gather.calls == 14
    assert grouped_gather.calls == 6

    # a second step pays the same, and the epoch compute still agrees
    _assert_same(grouped(preds, target), ungrouped(preds, target))
    assert grouped_gather.calls == 12
    _assert_same(grouped.compute(), ungrouped.compute())


def test_per_step_delta_sync_savings_visible_in_counters():
    rng = np.random.RandomState(22)
    preds, target = _data(rng)
    gather = _CountingGather()
    mc = _on_step_collection(gather)

    obs.enable()
    obs.reset()
    mc(preds, target)
    snap = obs.counters_snapshot()
    obs.disable()
    # one shared delta plane (4 StatScores leaves) + Accuracy's own (2)
    assert snap["states_synced"] == 6


def test_per_step_delta_sync_mixed_gather_configs_stay_independent():
    """A group member with a DIFFERENT dist_sync_fn must keep its own per-step
    sync (sharing a plane across gather configs would change semantics)."""
    rng = np.random.RandomState(23)
    preds, target = _data(rng)
    shared_gather, lone_gather = _CountingGather(), _CountingGather()
    mc = MetricCollection(
        [
            F1(num_classes=4, average="macro", dist_sync_on_step=True, dist_sync_fn=shared_gather),
            Precision(num_classes=4, average="macro", dist_sync_on_step=True, dist_sync_fn=shared_gather),
            Recall(num_classes=4, average="macro", dist_sync_on_step=True, dist_sync_fn=lone_gather),
        ]
    )
    reference = MetricCollection(
        [
            F1(num_classes=4, average="macro", dist_sync_on_step=True, dist_sync_fn=_CountingGather()),
            Precision(num_classes=4, average="macro", dist_sync_on_step=True, dist_sync_fn=_CountingGather()),
            Recall(num_classes=4, average="macro", dist_sync_on_step=True, dist_sync_fn=_CountingGather()),
        ],
        compute_groups=False,
    )
    _assert_same(mc(preds, target), reference(preds, target))
    assert shared_gather.calls == 4  # F1 + Precision share one plane
    assert lone_gather.calls == 4  # Recall syncs alone through its own fn


# ------------------------------------------------------- host-plane packing
def _packing_state():
    """A mixed state dict covering every leaf kind the packed plane moves."""
    from metrics_tpu.parallel.buffer import buffer_append, buffer_init

    buf = buffer_append(buffer_init(4, (), jnp.float32), jnp.asarray([1.0, 2.0]))
    state = {
        "sum_f": jnp.asarray([1.5, 2.5]),
        "sum_i": jnp.asarray([3, 4], dtype=jnp.int32),
        "other_i": jnp.asarray(7, dtype=jnp.int32),
        "buf": buf,
        "lst": [jnp.asarray([9.0]), jnp.asarray([10.0, 11.0])],
    }
    reductions = {"sum_f": "sum", "sum_i": "sum", "other_i": "max", "buf": None, "lst": "cat"}
    return state, reductions


def test_host_gather_packs_per_dtype_with_identical_values():
    """``host_gather`` through a value-based (packable) gather moves ONE flat
    payload per dtype — f32 (arrays + buffer data + list elements) and i32
    (arrays + buffer count) — with results bit-identical to the per-leaf
    plane a reference-semantics custom gather still gets."""
    from metrics_tpu.parallel.sync import host_gather, packable_gather

    state, reductions = _packing_state()

    per_leaf_gather = _CountingGather()  # unmarked: keeps one call per array
    packed_gather = packable_gather(_CountingGather())
    per_leaf = host_gather(state, reductions, gather_fn=per_leaf_gather)
    packed = host_gather(state, reductions, gather_fn=packed_gather)

    _assert_same(
        {k: v for k, v in per_leaf.items() if k != "lst"},
        {k: v for k, v in packed.items() if k != "lst"},
    )
    # 7 arrays move either way: 4 f32 (sum_f, buf.data, 2 list elements) and
    # 3 i32 (sum_i, other_i, buf.count) — packed: one call per dtype bucket
    assert per_leaf_gather.calls == 7
    assert packed_gather.calls == 2


def test_default_process_gather_is_packable():
    """The real multi-host plane (``gather_all_arrays``, incl. its
    ``process_group``-scoped partial) packs; unmarked custom fns do not."""
    import functools

    from metrics_tpu.parallel.sync import gather_all_arrays, is_packable_gather

    assert is_packable_gather(gather_all_arrays)
    assert is_packable_gather(functools.partial(gather_all_arrays, group=(0,)))
    assert not is_packable_gather(_CountingGather())


def test_grouped_sync_with_packable_gather_packs_each_plane():
    """Grouping and packing compose: one gather plane per compute group, one
    CALL per dtype bucket within it — the 4-metric collection's whole host
    sync collapses to 2 calls (Accuracy int32 bucket + StatScores int32
    bucket), values unchanged."""
    from metrics_tpu.parallel.sync import packable_gather

    rng = np.random.RandomState(19)
    preds, target = _data(rng)

    packed_gather = packable_gather(_CountingGather())
    mc = _collection(packed_gather)
    ref = _collection(_CountingGather(), compute_groups=False)
    mc(preds, target)
    ref(preds, target)

    _assert_same(mc.compute(), ref.compute())
    assert packed_gather.calls == 2


# ------------------------------------------------- deferred epoch gather
def _two_group_collection(gather):
    """Two compute groups (2x Accuracy, 2x F1) sharing one counted gather."""
    return MetricCollection(
        {
            "acc_a": Accuracy(dist_sync_fn=gather),
            "acc_b": Accuracy(dist_sync_fn=gather),
            "f1_a": F1(num_classes=4, average="macro", dist_sync_fn=gather),
            "f1_b": F1(num_classes=4, average="macro", dist_sync_fn=gather),
        }
    )


def test_epoch_sync_deferred_matches_synchronous_with_same_calls():
    """The DEFERRED ``_grouped_host_sync`` form (the default) publishes
    bit-exactly the synchronous form's values with the identical per-group
    gather-call count — only the epoch's critical path moves."""
    rng = np.random.RandomState(31)
    preds, target = _data(rng)

    deferred_gather, sync_gather = _CountingGather(), _CountingGather()
    col_def = _two_group_collection(deferred_gather)
    col_sync = _two_group_collection(sync_gather)
    col_sync.deferred_epoch_sync = False
    col_def(preds, target)
    col_sync(preds, target)

    _assert_same(col_def.compute(), col_sync.compute())
    # one gather plane per group either way: Accuracy group (2 leaves) +
    # F1 group (4 leaves) = 6 calls — deferral moves the fence, not a call
    assert deferred_gather.calls == sync_gather.calls == 6


def test_epoch_sync_dispatches_every_group_before_first_resolve():
    """The overlap evidence: BOTH groups' gathers are in flight before the
    first group's members compute — the ``deferred_depth`` high-water mark
    for the collection's epoch pipeline equals the group count, and the
    pipeline is empty again when ``compute`` returns."""
    rng = np.random.RandomState(32)
    preds, target = _data(rng)
    col = _two_group_collection(_CountingGather())
    col(preds, target)

    obs.enable()
    obs.reset()
    col.compute()
    snap = obs.counters_snapshot()
    obs.disable()
    depth = snap["deferred_depth"]["MetricCollection.epoch"]
    assert depth["max"] == 2  # both group gathers dispatched before any read
    assert depth["current"] == 0  # every handle resolved before returning


def test_epoch_sync_deferred_flag_restores_synchronous_plane():
    """``deferred_epoch_sync=False`` is the escape hatch: no handles, no
    background dispatch — the epoch gathers run on the calling thread."""
    rng = np.random.RandomState(33)
    preds, target = _data(rng)
    col = _two_group_collection(_CountingGather())
    col.deferred_epoch_sync = False
    col(preds, target)

    obs.enable()
    obs.reset()
    col.compute()
    snap = obs.counters_snapshot()
    obs.disable()
    assert "MetricCollection.epoch" not in snap["deferred_depth"]
    assert snap["deferred"]["dispatched"] == 0


@pytest.mark.chaos
def test_epoch_sync_deferred_degrades_without_stalling():
    """Chaos through the deferred epoch plane: a persistent drop under a
    degrade guard latches every group to local-only state — the epoch
    compute finishes (bounded, never wedged) with the unsynced values."""
    from metrics_tpu.parallel import faults
    from metrics_tpu.parallel.sync import SyncGuard, gather_all_arrays, set_sync_guard

    rng = np.random.RandomState(34)
    preds, target = _data(rng)
    col = _two_group_collection(gather_all_arrays)
    col(preds, target)
    local = _two_group_collection(None)  # no gather: pure local values
    local(preds, target)

    guard = SyncGuard(deadline_s=0.3, max_retries=1, backoff_s=0.01, policy="degrade")
    old = set_sync_guard(guard)
    try:
        with faults.ChaosInjector(
            [faults.FaultSpec(kind="drop", rate=1.0, times=100_000)], seed=0
        ):
            values = col.compute()
    finally:
        set_sync_guard(old)
    _assert_same(values, local.compute())


def test_clone_starts_conservative_until_reset():
    """Lockstep is identity-based, so a clone cannot inherit it: members with
    accumulated state start diverged (correct, just unshared) and a
    collection-level reset re-arms full sharing."""
    rng = np.random.RandomState(18)
    preds, target = _data(rng)
    gather = _CountingGather()
    mc = _collection(gather)
    mc(preds, target)

    clone = mc.clone()
    # deepcopy copies the gather fn too (one shared copy across members)
    clone_gather = clone["Accuracy"].dist_sync_fn
    assert clone_gather is not gather
    ref = _collection(_CountingGather(), compute_groups=False)
    ref(preds, target)
    _assert_same(clone.compute(), ref.compute())
    assert clone_gather.calls == 14  # conservative: no sharing on the clone

    clone.reset()
    clone(preds, target)
    clone_gather.calls = 0
    _assert_same(clone.compute(), ref.compute())
    assert clone_gather.calls == 6  # reset re-armed lockstep
