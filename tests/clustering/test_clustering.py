"""Clustering family vs sklearn oracles (contingency-matrix streaming)."""
import jax.numpy as jnp
import numpy as np
import pytest
import sklearn.metrics as sk

from metrics_tpu import (
    AdjustedRandScore,
    CompletenessScore,
    FowlkesMallowsScore,
    HomogeneityScore,
    MutualInfoScore,
    NormalizedMutualInfoScore,
    RandScore,
    VMeasureScore,
)
from metrics_tpu.functional import (
    adjusted_rand_score,
    completeness_score,
    fowlkes_mallows_score,
    homogeneity_score,
    mutual_info_score,
    normalized_mutual_info_score,
    rand_score,
    v_measure_score,
)
from tests.helpers.testers import MetricTester

_rng = np.random.RandomState(53)
NUM_BATCHES, BATCH_SIZE = 10, 32
NUM_CLUSTERS, NUM_CLASSES = 7, 5

_target = _rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))
# correlated predicted clusters so the scores are non-trivial
_preds = (_target + (_rng.rand(NUM_BATCHES, BATCH_SIZE) < 0.3) * _rng.randint(
    0, NUM_CLUSTERS, (NUM_BATCHES, BATCH_SIZE))) % NUM_CLUSTERS

_ARGS = {"num_clusters": NUM_CLUSTERS, "num_classes": NUM_CLASSES}


def _sk(fn):
    def wrapped(preds, target):
        return fn(np.asarray(target).reshape(-1), np.asarray(preds).reshape(-1))

    return wrapped


# last element: rtol. The information-theoretic scores (MI/NMI/homogeneity/
# completeness/V) run p*log terms in f32; TPU log differs ~2e-5 relative
# from the f64 sklearn oracle (same precision class as PSNR's rtol policy).
# The pair-counting closed forms (Rand/ARI/FM) stay at the tight default.
_CASES = [
    (RandScore, rand_score, _sk(sk.rand_score), 1e-7),
    (AdjustedRandScore, adjusted_rand_score, _sk(sk.adjusted_rand_score), 1e-6),
    (MutualInfoScore, mutual_info_score, _sk(sk.mutual_info_score), 1e-4),
    (NormalizedMutualInfoScore, normalized_mutual_info_score, _sk(sk.normalized_mutual_info_score), 1e-4),
    (HomogeneityScore, homogeneity_score, _sk(sk.homogeneity_score), 1e-4),
    (CompletenessScore, completeness_score, _sk(sk.completeness_score), 1e-4),
    (VMeasureScore, v_measure_score, _sk(sk.v_measure_score), 1e-4),
    (FowlkesMallowsScore, fowlkes_mallows_score, _sk(sk.fowlkes_mallows_score), 1e-6),
]


@pytest.mark.parametrize("metric_class, functional, sk_metric, case_rtol", _CASES)
class TestClustering(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("ddp", [False, True])
    def test_clustering_class(self, metric_class, functional, sk_metric, case_rtol, ddp):
        self.rtol = case_rtol
        self.run_class_metric_test(
            ddp=ddp,
            preds=_preds,
            target=_target,
            metric_class=metric_class,
            sk_metric=sk_metric,
            dist_sync_on_step=False,
            metric_args=_ARGS,
        )

    def test_clustering_functional(self, metric_class, functional, sk_metric, case_rtol):
        self.rtol = case_rtol
        self.run_functional_metric_test(
            _preds, _target, metric_functional=functional, sk_metric=sk_metric,
            metric_args=_ARGS,
        )


@pytest.mark.parametrize("avg", ["arithmetic", "geometric", "min", "max"])
def test_nmi_average_methods(avg):
    p, t = jnp.asarray(_preds[0]), jnp.asarray(_target[0])
    got = float(normalized_mutual_info_score(p, t, NUM_CLUSTERS, NUM_CLASSES, average_method=avg))
    want = sk.normalized_mutual_info_score(np.asarray(t), np.asarray(p), average_method=avg)
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_clustering_degenerate():
    """Single-cluster / single-class / perfect labelings match sklearn."""
    t = _rng.randint(0, 3, 50)
    one_cluster = np.zeros(50, int)
    for ours, theirs in [
        (lambda p, y: rand_score(p, y, 1, 3), sk.rand_score),
        (lambda p, y: adjusted_rand_score(p, y, 1, 3), sk.adjusted_rand_score),
        (lambda p, y: completeness_score(p, y, 1, 3), sk.completeness_score),
        (lambda p, y: v_measure_score(p, y, 1, 3), sk.v_measure_score),
    ]:
        got = float(ours(jnp.asarray(one_cluster), jnp.asarray(t)))
        want = float(theirs(t, one_cluster))
        np.testing.assert_allclose(got, want, atol=1e-6)
    perfect = np.arange(20) % 4
    assert float(adjusted_rand_score(jnp.asarray(perfect), jnp.asarray(perfect), 4, 4)) == 1.0


@pytest.mark.parametrize("avg", ["arithmetic", "geometric", "min", "max"])
def test_nmi_one_trivial_labeling(avg):
    """Exactly one trivial labeling: sklearn gives 0.0 under min/geometric
    (vanishing normalizer), not the both-trivial 1.0 fallback."""
    t = _rng.randint(0, 3, 60)
    one_cluster = np.zeros(60, int)
    got = float(normalized_mutual_info_score(
        jnp.asarray(one_cluster), jnp.asarray(t), 1, 3, average_method=avg))
    want = sk.normalized_mutual_info_score(t, one_cluster, average_method=avg)
    np.testing.assert_allclose(got, want, atol=1e-6)
    # both trivial -> 1.0 regardless of average method
    got_both = float(normalized_mutual_info_score(
        jnp.asarray(one_cluster), jnp.asarray(one_cluster), 1, 1, average_method=avg))
    want_both = sk.normalized_mutual_info_score(one_cluster, one_cluster, average_method=avg)
    np.testing.assert_allclose(got_both, want_both, atol=1e-6)


def test_clustering_streaming_equals_one_shot():
    """Batch-streamed contingency equals single-shot on the concatenation."""
    m = MutualInfoScore(**_ARGS)
    for b in range(NUM_BATCHES):
        m.update(jnp.asarray(_preds[b]), jnp.asarray(_target[b]))
    want = sk.mutual_info_score(_target.reshape(-1), _preds.reshape(-1))
    np.testing.assert_allclose(float(m.compute()), want, atol=1e-5)


@pytest.mark.parametrize("avg", ["arithmetic", "geometric", "min", "max"])
def test_adjusted_mutual_info(avg):
    """AMI (the vectorized hypergeometric EMI) vs sklearn, all average
    methods. f32 gammaln bounds the tolerance at these epoch sizes."""
    from metrics_tpu import AdjustedMutualInfoScore
    from metrics_tpu.functional import adjusted_mutual_info_score

    for n in (50, 320):
        t = _rng.randint(0, NUM_CLASSES, n)
        p = (t + (_rng.rand(n) < 0.3) * _rng.randint(0, NUM_CLUSTERS, n)) % NUM_CLUSTERS
        got = float(adjusted_mutual_info_score(
            jnp.asarray(p), jnp.asarray(t), NUM_CLUSTERS, NUM_CLASSES, average_method=avg))
        want = sk.adjusted_mutual_info_score(t, p, average_method=avg)
        np.testing.assert_allclose(got, want, atol=2e-3)

    # stateful streaming equals one-shot
    m = AdjustedMutualInfoScore(NUM_CLUSTERS, NUM_CLASSES, average_method=avg)
    for b in range(NUM_BATCHES):
        m.update(jnp.asarray(_preds[b]), jnp.asarray(_target[b]))
    want = sk.adjusted_mutual_info_score(_target.reshape(-1), _preds.reshape(-1), average_method=avg)
    np.testing.assert_allclose(float(m.compute()), want, atol=2e-3)


def test_adjusted_mutual_info_degenerate():
    one = np.zeros(40, int)
    from metrics_tpu.functional import adjusted_mutual_info_score

    # both labelings trivial -> 1.0 (sklearn short-circuit)
    assert float(adjusted_mutual_info_score(jnp.asarray(one), jnp.asarray(one), 1, 1)) == 1.0
    # exactly one trivial -> ~0.0
    t = _rng.randint(0, 3, 40)
    got = float(adjusted_mutual_info_score(jnp.asarray(one), jnp.asarray(t), 1, 3))
    np.testing.assert_allclose(got, sk.adjusted_mutual_info_score(t, one), atol=1e-3)


def test_clustering_validation():
    with pytest.raises(ValueError, match="positive int"):
        RandScore(num_clusters=0, num_classes=3)
    with pytest.raises(ValueError, match="average_method"):
        NormalizedMutualInfoScore(num_clusters=2, num_classes=2, average_method="median")
    with pytest.raises(ValueError, match="identical shape"):
        rand_score(jnp.zeros(3, dtype=jnp.int32), jnp.zeros(4, dtype=jnp.int32), 2, 2)


def test_clustering_jit():
    import jax

    p, t = jnp.asarray(_preds[0]), jnp.asarray(_target[0])
    got = jax.jit(lambda a, b: v_measure_score(a, b, NUM_CLUSTERS, NUM_CLASSES))(p, t)
    want = sk.v_measure_score(np.asarray(t), np.asarray(p))
    np.testing.assert_allclose(float(got), want, atol=1e-5)
