"""Intrinsic clustering scores (CH / DB) vs sklearn oracles."""
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import (
    calinski_harabasz_score as sk_ch,
    davies_bouldin_score as sk_db,
)

from metrics_tpu import CalinskiHarabaszScore, DaviesBouldinScore
from metrics_tpu.functional import calinski_harabasz_score, davies_bouldin_score
from tests.helpers.testers import MetricTester

_rng = np.random.RandomState(61)
NUM_BATCHES, BATCH_SIZE, NUM_CLUSTERS, DIM = 10, 32, 4, 6

_centers = _rng.randn(NUM_CLUSTERS, DIM) * 8
_labels = _rng.randint(0, NUM_CLUSTERS, (NUM_BATCHES, BATCH_SIZE))
_data = (_centers[_labels] + _rng.randn(NUM_BATCHES, BATCH_SIZE, DIM)).astype(np.float32)


def _sk_wrap(fn):
    def wrapped(preds, target):
        X = np.asarray(preds).reshape(-1, DIM)
        lab = np.asarray(target).reshape(-1)
        return fn(X, lab)

    return wrapped


class TestCalinskiHarabasz(MetricTester):
    atol = 1e-5
    rtol = 1e-4  # f32 moments vs f64 sklearn

    @pytest.mark.parametrize("ddp", [False, True])
    def test_ch_class(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=_data,
            target=_labels,
            metric_class=CalinskiHarabaszScore,
            sk_metric=_sk_wrap(sk_ch),
            dist_sync_on_step=False,
            metric_args={"num_clusters": NUM_CLUSTERS, "num_features": DIM},
        )

    def test_ch_functional(self):
        self.run_functional_metric_test(
            _data, _labels, metric_functional=calinski_harabasz_score,
            sk_metric=_sk_wrap(sk_ch), metric_args={"num_clusters": NUM_CLUSTERS},
        )


class TestDaviesBouldin(MetricTester):
    atol = 1e-5
    rtol = 1e-4

    @pytest.mark.parametrize("ddp", [False, True])
    def test_db_class(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=_data,
            target=_labels,
            metric_class=DaviesBouldinScore,
            sk_metric=_sk_wrap(sk_db),
            dist_sync_on_step=False,
            metric_args={"num_clusters": NUM_CLUSTERS},
        )

    def test_db_functional(self):
        self.run_functional_metric_test(
            _data, _labels, metric_functional=davies_bouldin_score,
            sk_metric=_sk_wrap(sk_db), metric_args={"num_clusters": NUM_CLUSTERS},
        )


def test_ch_streaming_cancellation_stress():
    """Huge cluster offsets: the Chan moment design stays ~f32-exact where
    raw sum-of-squares moments lose several digits."""
    rng = np.random.RandomState(3)
    k, d, n = 5, 8, 1000
    centers = rng.randn(k, d) * 100
    labels = rng.randint(0, k, n)
    X = (centers[labels] + rng.randn(n, d)).astype(np.float32)
    m = CalinskiHarabaszScore(num_clusters=k, num_features=d)
    for b in range(10):
        m.update(jnp.asarray(X[b * 100:(b + 1) * 100]), jnp.asarray(labels[b * 100:(b + 1) * 100]))
    want = sk_ch(X, labels)
    np.testing.assert_allclose(float(m.compute()), want, rtol=1e-5)


def test_intrinsic_empty_cluster_semantics():
    """Static num_clusters larger than the labels actually used: populated
    clusters only, matching sklearn's unique-label semantics."""
    X = np.asarray(_data[0])
    lab = np.asarray(_labels[0]) % 2  # only clusters {0, 1} of 4
    got_ch = float(calinski_harabasz_score(jnp.asarray(X), jnp.asarray(lab), NUM_CLUSTERS))
    got_db = float(davies_bouldin_score(jnp.asarray(X), jnp.asarray(lab), NUM_CLUSTERS))
    np.testing.assert_allclose(got_ch, sk_ch(X, lab), rtol=1e-4)
    np.testing.assert_allclose(got_db, sk_db(X, lab), rtol=1e-4)


def test_intrinsic_validation():
    with pytest.raises(ValueError, match="positive int"):
        CalinskiHarabaszScore(num_clusters=0, num_features=2)
    with pytest.raises(ValueError, match="positive int"):
        DaviesBouldinScore(num_clusters=-1)
    with pytest.raises(ValueError, match=r"data \(N, d\)"):
        calinski_harabasz_score(jnp.zeros(5), jnp.zeros(5, dtype=jnp.int32), 2)


def test_intrinsic_jit():
    import jax

    X, lab = jnp.asarray(_data[0]), jnp.asarray(_labels[0])
    got = jax.jit(lambda a, b: calinski_harabasz_score(a, b, NUM_CLUSTERS))(X, lab)
    np.testing.assert_allclose(float(got), sk_ch(np.asarray(X), np.asarray(lab)), rtol=1e-4)


def test_db_with_capacity_buffer():
    """capacity promotes the cat-states to PaddedBuffers; labels must stay
    integer through the buffer (regression: float32 buffer default broke
    centroid indexing)."""
    m = DaviesBouldinScore(num_clusters=NUM_CLUSTERS, capacity=NUM_BATCHES * BATCH_SIZE)
    for b in range(3):
        m.update(jnp.asarray(_data[b]), jnp.asarray(_labels[b]))
    want = sk_db(_data[:3].reshape(-1, DIM), _labels[:3].reshape(-1))
    np.testing.assert_allclose(float(m.compute()), want, rtol=1e-4)


def test_ch_feature_dim_validation():
    """Mismatched feature dimension must raise, not silently broadcast
    (regression: (N, 1) data against num_features=2 returned a wrong score)."""
    m = CalinskiHarabaszScore(num_clusters=2, num_features=2)
    with pytest.raises(ValueError, match="num_features=2"):
        m.update(jnp.zeros((8, 1)), jnp.zeros(8, dtype=jnp.int32))
