"""Windowed serving plane: watermark/lateness/roll correctness matrix.

The contract under test (wrappers/windowed.py + core/streaming.py):

- watermark semantics: an in-order stream and any shuffle of it whose events
  stay within the allowed lateness produce BIT-EXACT window slabs (verdicts
  depend only on each event's window and the running max — scatter-adds
  commute);
- too-late events are DROPPED AND COUNTED (instance counter + the
  process-wide ``slab_dropped_samples`` evidence trail), never misrouted:
  every resident window's sample count matches an independent router;
- window roll parity: one batch per window makes ``Windowed(window_s=1,
  num_windows=k)`` the event-time twin of ``Running(window=k)``;
- preempt-mid-window resume: ``state_dict`` carries slabs + watermark +
  head + origin + drop counters, and ``guarded_update`` replay of the
  in-flight step is a no-op;
- the decay accumulator is the closed-form exponentially-weighted value;
- on a real (4,2) mesh the synced compute is psum-only and equals the
  single-process stream.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import metrics_tpu.observability as obs
from metrics_tpu import AUROC, Accuracy, MeanSquaredError, Running, Windowed
from metrics_tpu.core.streaming import RouteResult, WindowSpec, route_events
from metrics_tpu.parallel.placement import MeshHierarchy
from metrics_tpu.utils import compat
from metrics_tpu.utils.exceptions import TracingUnsupportedError


def _stream(n=96, seed=0, horizon=60.0):
    rng = np.random.RandomState(seed)
    preds = rng.rand(n).astype(np.float32)
    target = rng.randint(0, 2, n).astype(np.int32)
    times = np.sort(rng.uniform(0.0, horizon, n))
    return times, preds, target


def _ring(**kw):
    args = dict(window_s=20.0, num_windows=4, allowed_lateness_s=60.0)
    args.update(kw)
    return Windowed(Accuracy(), **args)


# ------------------------------------------------------------ routing core
def test_route_events_window_open_rule():
    spec = WindowSpec(10.0, 4, 10.0)
    # watermark 35: window 0 closed (10+10 <= 35), window 1 open until 30... no:
    # (1+1)*10+10 = 30 <= 35 -> closed too; windows 2,3 open
    r = route_events([5.0, 15.0, 25.0, 35.0], None, None, spec)
    assert r.watermark == 35.0 and r.head == 3
    assert list(r.slot_ids) == [-1, -1, 2, 3]
    assert r.n_dropped == 2 and r.n_late == 1
    assert r.min_window == 2
    assert isinstance(r, RouteResult)


def test_route_events_head_window_never_late():
    # zero lateness: the head window's own events always land
    spec = WindowSpec(10.0, 2, 0.0)
    r = route_events([11.0, 14.0, 19.9], None, None, spec)
    assert list(r.slot_ids) == [1, 1, 1] and r.n_dropped == 0


def test_route_events_watermark_monotonic_and_opened():
    spec = WindowSpec(10.0, 3, 0.0)
    r1 = route_events([12.0], None, None, spec)
    r2 = route_events([45.0], r1.watermark, r1.head, spec)
    assert r2.opened == (2, 3, 4) and r2.head == 4
    r3 = route_events([30.0], r2.watermark, r2.head, spec)  # late, window closed
    assert r3.watermark == 45.0 and list(r3.slot_ids) == [-1]


def test_window_spec_validation():
    with pytest.raises(ValueError, match="window_s"):
        WindowSpec(0.0, 4).validate()
    with pytest.raises(ValueError, match="num_windows"):
        WindowSpec(10.0, 0).validate()
    with pytest.raises(ValueError, match="still-open horizon"):
        WindowSpec(10.0, 2, 10.1).validate()
    with pytest.raises(ValueError, match="finite"):
        route_events([np.nan], None, None, WindowSpec(10.0, 2))


# -------------------------------------------------- watermark property matrix
def test_in_order_equals_shuffled_within_lateness_bit_exact():
    """The headline watermark property: shuffling a stream whose events all
    stay within the allowed lateness of the stream maximum changes nothing —
    slabs, rows, watermark, drop count are bit-exact."""
    times, preds, target = _stream()
    rng = np.random.RandomState(1)

    def run(order):
        m = _ring()
        for i in order:
            m.update(jnp.asarray(preds[i:i + 1]), jnp.asarray(target[i:i + 1]),
                     event_time=times[i:i + 1])
        return m

    a = run(range(len(times)))
    b = run(rng.permutation(len(times)))
    for name in a._defaults:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, name)), np.asarray(getattr(b, name)), err_msg=name
        )
    assert a.watermark == b.watermark and a.head_window == b.head_window
    assert a.dropped_samples == b.dropped_samples == 0
    np.testing.assert_array_equal(np.asarray(a.compute()), np.asarray(b.compute()))


def test_too_late_events_dropped_counted_never_misrouted():
    m = Windowed(Accuracy(), window_s=10.0, num_windows=3, allowed_lateness_s=5.0)
    before = obs.COUNTERS.slab_dropped_samples
    m.update(jnp.asarray(np.float32([0.9, 0.9])), jnp.asarray(np.int32([1, 1])),
             event_time=np.array([21.0, 25.0]))
    # watermark 25: window 0 closed at 15, window 1 open until 25 -> an event
    # at 8.0 is too late, an event at 12.0 is NOT ((1+1)*10+5 = 25 > 25 is
    # false -> window 1 closed exactly at 25: also dropped)
    m.update(jnp.asarray(np.float32([0.9, 0.9])), jnp.asarray(np.int32([1, 1])),
             event_time=np.array([8.0, 12.0]))
    assert m.dropped_samples == 2
    assert obs.COUNTERS.slab_dropped_samples - before == 2  # records with obs off
    # nothing was misrouted: both accepted events sit in window 2 alone
    rows = np.asarray(m._current_state()["windowed_rows"])
    assert rows[2 % 3] == 2 and rows.sum() == 2
    assert m.late_samples == 0


def test_rows_match_independent_router_across_rolls():
    """Zero misrouted, long stream: every resident window's row count equals
    a plain-numpy reimplementation of the routing rule."""
    rng = np.random.RandomState(3)
    m = Windowed(Accuracy(), window_s=10.0, num_windows=3, allowed_lateness_s=10.0)
    wm = None
    expected = {}
    dropped = 0
    for i in range(12):
        times = i * 6.0 + rng.uniform(-12.0, 6.0, 8)
        preds = rng.rand(8).astype(np.float32)
        target = rng.randint(0, 2, 8).astype(np.int32)
        m.update(jnp.asarray(preds), jnp.asarray(target), event_time=times)
        wm = times.max() if wm is None else max(wm, times.max())
        head = int(np.floor(wm / 10.0))
        w = np.floor_divide(times, 10.0).astype(int)
        ok = ((w + 1) * 10.0 + 10.0 > wm) & (w > head - 3)
        dropped += int((~ok).sum())
        for wi in w[ok]:
            expected[int(wi)] = expected.get(int(wi), 0) + 1
    rows = np.asarray(m._current_state()["windowed_rows"])
    for w in m.resident_windows():
        assert rows[w % 3] == expected.get(w, 0), w
    assert m.dropped_samples == dropped


def test_window_roll_parity_vs_running():
    """One batch per window == Running's last-k-updates view: the slot
    rotation is the event-time form of Running's delta window."""
    rng = np.random.RandomState(5)
    k = 3
    windowed = Windowed(Accuracy(), window_s=1.0, num_windows=k)
    running = Running(Accuracy(), window=k)
    for step in range(8):
        preds = jnp.asarray(rng.rand(16).astype(np.float32))
        target = jnp.asarray(rng.randint(0, 2, 16).astype(np.int32))
        windowed.update(preds, target, event_time=step + 0.5)
        running.update(preds, target)
        np.testing.assert_array_equal(
            np.asarray(windowed.compute()), np.asarray(running.compute()),
            err_msg=f"step {step}",
        )
        windowed._computed = None


def test_compute_window_and_merged_match_fresh_metrics():
    times, preds, target = _stream(seed=7)
    m = _ring()
    m.update(jnp.asarray(preds), jnp.asarray(target), event_time=times)
    w_idx = np.floor_divide(times, 20.0).astype(int)
    for w in m.resident_windows():
        sel = w_idx == w
        if not sel.any():
            assert np.isnan(float(m.compute_window(w)))
            continue
        fresh = Accuracy()
        fresh.update(jnp.asarray(preds[sel]), jnp.asarray(target[sel]))
        np.testing.assert_array_equal(
            np.asarray(m.compute_window(w)), np.asarray(fresh.compute()), err_msg=str(w)
        )
    fresh = Accuracy()
    fresh.update(jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_array_equal(np.asarray(m.compute()), np.asarray(fresh.compute()))
    with pytest.raises(KeyError, match="not resident"):
        m.compute_window(99)


def test_sketch_inner_per_window_parity():
    """Sketch states window for free: per-window AUROC histograms equal the
    fresh metric's over exactly that window's events."""
    times, preds, target = _stream(seed=11)
    m = Windowed(AUROC(approx="sketch", num_bins=64), window_s=20.0, num_windows=4,
                 allowed_lateness_s=60.0)
    m.update(jnp.asarray(preds), jnp.asarray(target), event_time=times)
    w_idx = np.floor_divide(times, 20.0).astype(int)
    for w in m.resident_windows():
        sel = w_idx == w
        if not sel.any():
            continue
        fresh = AUROC(approx="sketch", num_bins=64)
        fresh.update(jnp.asarray(preds[sel]), jnp.asarray(target[sel]))
        np.testing.assert_allclose(
            np.asarray(m.compute_window(w)), np.asarray(fresh.compute()),
            rtol=1e-6, err_msg=str(w),
        )


def test_windowed_keyed_composition_per_cohort_windows():
    """The headline serving scenario composes: ``Windowed(Keyed(...))`` —
    windows wrap the segment axis ((W, K, ...) states), the merged view
    equals the unwindowed Keyed metric when every window is resident, and a
    per-window read equals a fresh Keyed over exactly that window's events."""
    from metrics_tpu import Keyed

    rng = np.random.RandomState(23)
    scores = rng.rand(300).astype(np.float32)
    labels = rng.randint(0, 2, 300).astype(np.int32)
    slots = rng.randint(0, 3, 300).astype(np.int32)
    times = rng.uniform(0, 120.0, 300)

    ck = Windowed(Keyed(AUROC(approx="sketch", num_bins=64), num_slots=3),
                  window_s=60.0, num_windows=2, allowed_lateness_s=60.0)
    ck.update(jnp.asarray(scores), jnp.asarray(labels), slot=jnp.asarray(slots),
              event_time=times)

    alone = Keyed(AUROC(approx="sketch", num_bins=64), num_slots=3)
    alone.update(jnp.asarray(scores), jnp.asarray(labels), slot=jnp.asarray(slots))
    np.testing.assert_array_equal(np.asarray(ck.compute()), np.asarray(alone.compute()))

    sel = np.floor_divide(times, 60.0).astype(int) == 0
    fresh = Keyed(AUROC(approx="sketch", num_bins=64), num_slots=3)
    fresh.update(jnp.asarray(scores[sel]), jnp.asarray(labels[sel]),
                 slot=jnp.asarray(slots[sel]))
    np.testing.assert_array_equal(
        np.asarray(ck.compute_window(0)), np.asarray(fresh.compute())
    )
    # a roll recycles the nested slab in place
    ck.update(jnp.asarray(scores[:4]), jnp.asarray(labels[:4]),
              slot=jnp.asarray(slots[:4]), event_time=np.full(4, 200.0))
    assert ck.resident_windows() == (2, 3)
    # decay mode rejects nesting loudly (its mean division clamps at 1)
    with pytest.raises(ValueError, match="segment slab"):
        Windowed(Keyed(Accuracy(), num_slots=2), decay_half_life_s=5.0)


# --------------------------------------------------- preemption-safe resume
def test_checkpoint_round_trip_restores_stream_position():
    times, preds, target = _stream(seed=13)
    m = _ring()
    m.update(jnp.asarray(preds), jnp.asarray(target), event_time=times)
    m.update(jnp.asarray(preds[:4]), jnp.asarray(target[:4]),
             event_time=np.full(4, -100.0))  # too late: bump the drop counter
    sd = m.state_dict()
    restored = _ring()
    restored.load_state_dict(sd)
    assert restored.watermark == m.watermark
    assert restored.head_window == m.head_window
    assert restored.resident_windows() == m.resident_windows()
    assert restored.dropped_samples == m.dropped_samples
    assert restored.epoch_watermark == m.epoch_watermark
    for name in m._defaults:
        np.testing.assert_array_equal(
            np.asarray(getattr(restored, name)), np.asarray(getattr(m, name)), err_msg=name
        )
    np.testing.assert_array_equal(np.asarray(restored.compute()), np.asarray(m.compute()))


def test_preempt_mid_window_resume_idempotent_via_guarded_update():
    """The serving resume story at the metric level: checkpoint mid-window,
    'die', restore, and replay from BEFORE the checkpoint — already-folded
    steps no-op, the stream completes identically to the uninterrupted run."""
    rng = np.random.RandomState(17)
    batches = []
    for i in range(8):
        batches.append((
            i * 5.0 + rng.uniform(0, 5.0, 8),
            rng.rand(8).astype(np.float32),
            rng.randint(0, 2, 8).astype(np.int32),
        ))

    straight = Windowed(Accuracy(), window_s=10.0, num_windows=3, allowed_lateness_s=10.0)
    for i, (t, p, y) in enumerate(batches):
        assert straight.guarded_update(i, jnp.asarray(p), jnp.asarray(y), event_time=t)

    interrupted = Windowed(Accuracy(), window_s=10.0, num_windows=3, allowed_lateness_s=10.0)
    for i, (t, p, y) in enumerate(batches[:5]):
        interrupted.guarded_update(i, jnp.asarray(p), jnp.asarray(y), event_time=t)
    snapshot = interrupted.state_dict()  # mid-window checkpoint, then "preempt"

    resumed = Windowed(Accuracy(), window_s=10.0, num_windows=3, allowed_lateness_s=10.0)
    resumed.load_state_dict(snapshot)
    for i, (t, p, y) in enumerate(batches[3:], start=3):  # replay overlaps 3..4
        applied = resumed.guarded_update(i, jnp.asarray(p), jnp.asarray(y), event_time=t)
        assert applied == (i >= 5), i  # below-watermark steps are no-ops
    for name in straight._defaults:
        np.testing.assert_array_equal(
            np.asarray(getattr(resumed, name)), np.asarray(getattr(straight, name)),
            err_msg=name,
        )
    assert resumed.dropped_samples == straight.dropped_samples


# ------------------------------------------------------------- decay mode
def test_decay_accumulator_matches_closed_form():
    m = Windowed(MeanSquaredError(), decay_half_life_s=10.0)
    samples = [(0.0, 4.0), (10.0, 1.0), (20.0, 9.0)]  # (time, squared error)
    for t, sq in samples:
        m.update(jnp.asarray(np.float32([np.sqrt(sq)])), jnp.asarray(np.float32([0.0])),
                 event_time=t)
    weights = [0.5 ** ((20.0 - t) / 10.0) for t, _ in samples]
    expected = sum(w * sq for w, (_, sq) in zip(weights, samples)) / sum(weights)
    np.testing.assert_allclose(float(m.compute()), expected, rtol=1e-6)
    assert m.watermark == 20.0 and m.head_window is None


def test_decay_drops_beyond_lateness_and_rejects_bad_states():
    m = Windowed(MeanSquaredError(), decay_half_life_s=10.0, allowed_lateness_s=5.0)
    m.update(jnp.asarray(np.float32([1.0])), jnp.asarray(np.float32([0.0])), event_time=100.0)
    m.update(jnp.asarray(np.float32([9.0])), jnp.asarray(np.float32([0.0])), event_time=10.0)
    assert m.dropped_samples == 1
    np.testing.assert_allclose(float(m.compute()), 1.0, rtol=1e-6)
    with pytest.raises(ValueError, match="sketch"):
        Windowed(AUROC(approx="sketch"), decay_half_life_s=5.0)
    with pytest.raises(ValueError, match="no windows"):
        m.compute_window(0)


# ------------------------------------------------------------- validation
def test_constructor_validation():
    with pytest.raises(ValueError, match="exactly one of"):
        Windowed(Accuracy())
    with pytest.raises(ValueError, match="exactly one of"):
        Windowed(Accuracy(), window_s=10.0, decay_half_life_s=5.0)
    with pytest.raises(ValueError, match="still-open horizon"):
        Windowed(Accuracy(), window_s=10.0, num_windows=2, allowed_lateness_s=60.0)
    with pytest.raises(ValueError, match="cat/list/buffer"):
        Windowed(AUROC(), window_s=10.0)  # buffer-state curve metric
    with pytest.raises(ValueError, match="must be a Metric"):
        Windowed(object(), window_s=10.0)
    with pytest.raises(ValueError, match="empty"):
        Windowed(Accuracy(), window_s=10.0, empty="drop")


def test_update_requires_event_time_and_matching_sizes():
    m = _ring()
    with pytest.raises(ValueError, match="event_time"):
        m.update(jnp.asarray(np.float32([0.5])), jnp.asarray(np.int32([1])))
    with pytest.raises(ValueError, match="entries"):
        m.update(jnp.asarray(np.float32([0.5, 0.5])), jnp.asarray(np.int32([1, 1])),
                 event_time=np.array([1.0, 2.0, 3.0]))
    # scalar event_time stamps the whole batch
    m.update(jnp.asarray(np.float32([0.9, 0.2])), jnp.asarray(np.int32([1, 1])),
             event_time=5.0)
    assert int(np.asarray(m._current_state()["windowed_rows"]).sum()) == 2


def test_update_under_trace_raises():
    m = _ring()

    def step(p, t):
        m.update(p, t, event_time=1.0)
        return p

    with pytest.raises(TracingUnsupportedError):
        jax.jit(step)(jnp.asarray(np.float32([0.5])), jnp.asarray(np.int32([1])))


def test_empty_policy_nan_vs_zero():
    nan_m = _ring()
    zero_m = Windowed(Accuracy(), window_s=20.0, num_windows=4, empty="zero")
    assert np.isnan(float(nan_m.compute()))
    assert float(zero_m.compute()) == 0.0


def test_reset_clears_stream_position():
    m = _ring()
    m.update(jnp.asarray(np.float32([0.9])), jnp.asarray(np.int32([1])), event_time=50.0)
    m.reset()
    assert m.watermark is None and m.head_window is None
    assert m.resident_windows() == () and m.dropped_samples == 0
    assert np.isnan(float(m.compute()))


# --------------------------------------------------- mesh sync (flat + hier)
@pytest.mark.parametrize("hierarchical", [False, True], ids=["flat", "hier42"])
def test_mesh_synced_compute_matches_single_process(eight_devices, hierarchical):
    """The serving acceptance property on a REAL staged program: 8 device
    shards hold window slabs, one coalesced sync moves every window, and the
    synced compute equals the single-process stream bit-exactly — with a
    PSUM-ONLY program (windows are a state axis, never extra collectives)."""
    m = Windowed(AUROC(approx="sketch", num_bins=32), window_s=20.0, num_windows=4,
                 allowed_lateness_s=60.0)
    rng = np.random.RandomState(7)
    preds = rng.rand(8, 64).astype(np.float32)
    target = rng.randint(0, 2, (8, 64)).astype(np.int32)
    times = rng.uniform(0.0, 80.0, (8, 64))

    # stage the per-shard slabs EAGERLY (the router is host-side), then sync
    # the stacked states in one staged program — the serving deployment
    # shape: local windowed updates, one collective per publish
    shards = []
    for r in range(8):
        shard = Windowed(AUROC(approx="sketch", num_bins=32), window_s=20.0, num_windows=4,
                         allowed_lateness_s=60.0)
        shard.update(jnp.asarray(preds[r]), jnp.asarray(target[r]), event_time=times[r])
        shards.append(shard._current_state())
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *shards)

    if hierarchical:
        mesh = Mesh(np.array(eight_devices).reshape(2, 4), ("dcn", "ici"))
        axis, specs = MeshHierarchy(ici_axis="ici", dcn_axis="dcn"), P(("dcn", "ici"))
    else:
        mesh = Mesh(np.array(eight_devices), ("dp",))
        axis, specs = "dp", P("dp")

    def fn(state):
        local = jax.tree_util.tree_map(lambda x: x[0], state)
        return m.sync_state(local, axis)

    obs.enable()
    obs.COUNTERS.reset()
    f = jax.jit(compat.shard_map(
        fn, mesh=mesh, in_specs=(specs,),
        out_specs=jax.tree_util.tree_map(lambda _: P(), m.init_state()),
        check_vma=False,
    ))
    synced = f(stacked)
    snap = obs.counters_snapshot()
    obs.disable()

    # psum-only: the histogram slab + row-count slab share ONE int32 bucket
    assert snap["calls_by_kind"].get("psum", 0) == (2 if hierarchical else 1)
    for kind in ("all_gather", "coalesced_gather", "process_allgather", "ppermute"):
        assert snap["calls_by_kind"].get(kind, 0) == 0, kind

    single = Windowed(AUROC(approx="sketch", num_bins=32), window_s=20.0, num_windows=4,
                      allowed_lateness_s=60.0)
    single.update(
        jnp.asarray(preds.reshape(-1)), jnp.asarray(target.reshape(-1)),
        event_time=times.reshape(-1),
    )
    np.testing.assert_array_equal(
        np.asarray(synced["hist"].counts), np.asarray(single.hist.counts)
    )
    np.testing.assert_array_equal(
        np.asarray(synced["windowed_rows"]), np.asarray(single.windowed_rows)
    )


def test_windowed_keyed_misrouted_slot_ids_are_counted():
    """The drop-accounting satellite: out-of-range segment ids inside a
    ``Windowed(Keyed)`` update are dropped by the INNER slab scatter (a
    device-side non-event the eager Keyed path would have counted) — the
    host-routed update must record them in ``slab_dropped_samples`` so fleet
    shards surface misrouted-sample drops uniformly with too-late drops."""
    from metrics_tpu import Keyed

    obs.reset()
    try:
        wk = Windowed(Keyed(Accuracy(), num_slots=2), window_s=10.0, num_windows=2)
        preds = jnp.asarray(np.float32([0.9, 0.8, 0.2]))
        target = jnp.asarray(np.int32([1, 0, 0]))
        wk.update(preds, target, event_time=np.array([1.0, 2.0, 3.0]),
                  slot=jnp.asarray(np.int32([0, 5, -1])))  # 2 of 3 misrouted
        snap = obs.counters_snapshot()
        assert snap["slab_dropped_samples"] == 2
        # the samples are gone from the inner slabs but window rows still
        # counted the batch — the drop is only visible through the counter,
        # which is exactly why it must be recorded
        assert float(jnp.sum(wk.windowed_rows)) == 3.0
        assert wk.dropped_samples == 0  # late-event accounting stays separate
    finally:
        obs.reset()


# --------------------------------------------------------- sliding windows
def test_sliding_route_overlap_rows():
    """slide_s < window_s: each event's NEWEST covering window rides
    slot_ids, the older coverings ride overlap_slots, and every row is
    judged independently by the open rule."""
    spec = WindowSpec(6.0, 6, 0.0, 2.0)
    assert spec.stride == 2.0 and spec.overlap == 3
    r = route_events([7.0], None, None, spec)
    # t=7 covers windows 3 ([6,12)), 2 ([4,10)), 1 ([2,8))
    assert list(r.slot_ids) == [3]
    assert [list(row) for row in r.overlap_slots] == [[2], [1]]
    assert r.min_window == 1 and r.head == 3
    # a late event whose older covering windows already closed still lands
    # in every covering window that is open: wm=13, window 3 open until
    # 6+6=12 <= 13 -> closed; windows 4,5 open
    r2 = route_events([11.0], r.watermark, r.head, spec)
    r3 = route_events([11.0], 13.0, 6, spec)
    assert list(r3.slot_ids) == [5 % 6]
    assert [list(row) for row in r3.overlap_slots] == [[4], [-1]]
    assert r3.n_dropped == 0
    del r2


def test_sliding_spec_validation():
    with pytest.raises(ValueError, match="slide_s"):
        WindowSpec(6.0, 6, 0.0, 7.0).validate()  # slide > window
    with pytest.raises(ValueError, match="integer multiple"):
        WindowSpec(6.0, 6, 0.0, 2.5).validate()
    with pytest.raises(ValueError, match="collide in the ring"):
        WindowSpec(6.0, 2, 0.0, 2.0).validate()  # W < overlap
    with pytest.raises(ValueError, match="still-open horizon"):
        # cap: W*slide - window = 12 - 6 = 6
        WindowSpec(6.0, 6, 6.5, 2.0).validate()
    with pytest.raises(ValueError, match="decay accumulator"):
        Windowed(Accuracy(), decay_half_life_s=5.0, slide_s=2.0)


def test_sliding_windows_bitexact_vs_per_window_oracles():
    """Every resident sliding window's value equals a fresh unwindowed
    metric over exactly the events in its [w*slide, w*slide + window)
    span — each event counted once per covering window, never more."""
    rng = np.random.RandomState(5)
    n = 64
    times = np.sort(rng.uniform(0.0, 16.0, n))
    preds = rng.rand(n).astype(np.float32)
    target = rng.randint(0, 2, n).astype(np.int32)
    # ring sized for the full span INCLUDING the pre-origin coverings: the
    # stream's covering windows run -2..7 (10 distinct windows), so W=12
    # keeps them all resident for the conservation check
    m = Windowed(Accuracy(), window_s=6.0, num_windows=12, slide_s=2.0,
                 allowed_lateness_s=12.0)
    for i in range(0, n, 8):
        m.update(jnp.asarray(preds[i:i + 8]), jnp.asarray(target[i:i + 8]),
                 event_time=times[i:i + 8])
    assert m.dropped_samples == 0
    for w in m.resident_windows():
        lo = m.window_start(w)
        mask = (times >= lo) & (times < lo + 6.0)
        if not mask.any():
            continue
        fresh = Accuracy()
        fresh.update(jnp.asarray(preds[mask]), jnp.asarray(target[mask]))
        np.testing.assert_array_equal(
            np.asarray(m.compute_window(w)), np.asarray(fresh.compute()),
            err_msg=f"window {w}",
        )
    # rows conservation: every event lives in exactly overlap=3 windows
    # (minus coverings before the stream origin, which were open — negative
    # windows are real windows here)
    rows = np.asarray(m._current_state()["windowed_rows"])
    assert rows.sum() == n * 3


def test_sliding_compute_is_trailing_full_span_window():
    """Overlapping slots must not be summed (an event lives in several);
    compute() is the newest FULL-span window, head - overlap + 1 — the
    trailing window_s view ending at (head+1)*slide. The head window itself
    spans past the watermark and has only accumulated the newest slide_s
    seconds (near-empty right after a slide boundary)."""
    m = Windowed(Accuracy(), window_s=4.0, num_windows=4, slide_s=2.0)
    m.update(jnp.asarray(np.float32([0.9, 0.1])), jnp.asarray(np.int32([1, 1])),
             event_time=np.array([1.0, 5.0]))
    # head = floor(5/2) = 2; the trailing full-span view is window
    # 2 - 2 + 1 = 1, spanning [2, 6): only the t=5 event (pred 0.1 vs
    # target 1 -> wrong -> accuracy 0)
    assert m.head_window == 2
    np.testing.assert_array_equal(np.asarray(m.compute()), np.asarray(m.compute_window(1)))
    assert float(m.compute_window(1)) == 0.0
    # advance so head and trailing view DIFFER in value: head 3 spans
    # [6, 10) (only t=6.9, correct -> 1.0) while the trailing view 2 spans
    # [4, 8) (t=5 wrong + t=6.9 correct -> 0.5) — the last window_s seconds
    m.update(jnp.asarray(np.float32([0.9])), jnp.asarray(np.int32([1])),
             event_time=np.array([6.9]))
    assert m.head_window == 3
    assert float(m.compute_window(3)) == 1.0
    assert float(np.asarray(m.compute())) == 0.5
    np.testing.assert_array_equal(np.asarray(m.compute()), np.asarray(m.compute_window(2)))


# ------------------------------------------------- cross-rank agreed clock
def _two_ranks(**kw):
    from metrics_tpu import WatermarkAgreement

    args = dict(window_s=10.0, num_windows=4, allowed_lateness_s=10.0)
    args.update(kw)
    ag = WatermarkAgreement(deadline_s=30.0)
    a = Windowed(Accuracy(), **args, agreement=ag, rank=0)
    b = Windowed(Accuracy(), **args, agreement=ag, rank=1)
    return ag, a, b


def test_agreed_clock_keeps_peer_fed_windows_open():
    """The coherence headline: a rank whose LOCAL clock ran ahead judges
    lateness by the AGREED (global-min) clock, so an event its local clock
    would have dropped still routes — 'late' means the same on every rank.
    (The ring is sized for the skew: window 0 must still be RESIDENT on the
    fast rank — an agreement-open window that fell off the local ring is
    dropped-and-counted, never misrouted.)"""
    ag, fast, slow = _two_ranks(num_windows=8)
    fast.update(jnp.asarray(np.float32([0.9])), jnp.asarray(np.int32([1])),
                event_time=[45.0])
    slow.update(jnp.asarray(np.float32([0.8])), jnp.asarray(np.int32([1])),
                event_time=[12.0])
    assert ag.agreed() == 12.0
    # window 0 by the fast rank's local clock: 10+10 <= 45 -> closed; by the
    # agreed clock: 20 > 12 -> open. The event must route, not drop.
    fast.update(jnp.asarray(np.float32([0.7])), jnp.asarray(np.int32([1])),
                event_time=[5.0])
    assert fast.dropped_samples == 0
    rows = np.asarray(fast._current_state()["windowed_rows"])
    assert rows[0] == 1.0
    # without an agreement the same stream drops it
    lone = _ring(window_s=10.0, num_windows=8, allowed_lateness_s=10.0)
    lone.update(jnp.asarray(np.float32([0.9])), jnp.asarray(np.int32([1])),
                event_time=[45.0])
    lone.update(jnp.asarray(np.float32([0.7])), jnp.asarray(np.int32([1])),
                event_time=[5.0])
    assert lone.dropped_samples == 1


def test_close_watermark_is_agreed_and_monotone():
    ag, a, b = _two_ranks()
    assert a.close_watermark is None  # no agreement formed yet
    a.update(jnp.asarray(np.float32([0.9])), jnp.asarray(np.int32([1])),
             event_time=[30.0])
    assert a.close_watermark is None  # b registered, silent: still held open
    b.update(jnp.asarray(np.float32([0.9])), jnp.asarray(np.int32([1])),
             event_time=[8.0])
    assert a.close_watermark == 8.0 and b.close_watermark == 8.0
    b.update(jnp.asarray(np.float32([0.9])), jnp.asarray(np.int32([1])),
             event_time=[25.0])
    assert a.close_watermark == 25.0
    # a lone metric's close clock stays its local watermark
    lone = _ring()
    lone.update(jnp.asarray(np.float32([0.9])), jnp.asarray(np.int32([1])),
                event_time=[7.0])
    assert lone.close_watermark == 7.0


def test_agreement_snapshot_restore_round_trip():
    """The restore satellite: a restored rank rejoins carrying the AGREED
    watermark — it must not regress the global min, must not reopen a
    window the agreed clock closed, and replay through guarded_update must
    not double-count."""
    from metrics_tpu import WatermarkAgreement

    ag, a, b = _two_ranks()
    preds = jnp.asarray(np.float32([0.9, 0.2]))
    target = jnp.asarray(np.int32([1, 0]))
    a.guarded_update(0, preds, target, event_time=np.array([12.0, 15.0]))
    b.guarded_update(0, preds, target, event_time=np.array([33.0, 35.0]))
    agreed_before = ag.agreed()
    assert agreed_before == 15.0
    snap = a.state_dict()

    # the restored rank joins a FRESH agreement (the registry never pickles)
    ag2 = WatermarkAgreement(deadline_s=30.0)
    restored = Windowed(Accuracy(), window_s=10.0, num_windows=4,
                        allowed_lateness_s=10.0, agreement=ag2, rank=0)
    peer = Windowed(Accuracy(), window_s=10.0, num_windows=4,
                    allowed_lateness_s=10.0, agreement=ag2, rank=1)
    peer.update(preds, target, event_time=np.array([33.0, 35.0]))
    restored.load_state_dict(snap)
    # the restore reported the checkpointed local watermark: the agreement
    # re-forms at the same global min, never lower
    assert ag2.agreed() == 15.0
    assert restored.agreed_watermark == 15.0
    assert restored.close_watermark == 15.0
    # replaying the in-flight step is a no-op (no double count)...
    assert restored.guarded_update(0, preds, target,
                                   event_time=np.array([12.0, 15.0])) is False
    rows = np.asarray(restored._current_state()["windowed_rows"])
    assert rows.sum() == 2.0
    # ...and a fresh step advances normally
    assert restored.guarded_update(1, preds, target,
                                   event_time=np.array([18.0, 21.0])) is True
    assert ag2.agreed() == 21.0


def test_agreement_deepcopy_shares_pickle_drops():
    """A deep-copied participant (the service's shadow twin) keeps talking
    to the SAME registry; a pickled one drops it and re-attaches."""
    import pickle
    from copy import deepcopy

    ag, a, _b = _two_ranks()
    twin = deepcopy(a)
    assert twin.agreement is ag
    a.update(jnp.asarray(np.float32([0.9])), jnp.asarray(np.int32([1])),
             event_time=[9.0])
    blob = pickle.dumps(a)
    revived = pickle.loads(blob)
    assert revived.agreement is None
    assert revived.watermark == 9.0
    with pytest.raises(TypeError, match="cannot be pickled"):
        pickle.dumps(ag)


def test_late_verdicts_judged_by_the_agreed_clock():
    """'Late' is a pure function of (window, judging clock): an event the
    LOCAL head left behind is not late while the agreed clock still sits
    inside its window — and nothing can be late before an agreement forms
    (judge clock -inf)."""
    import math

    spec = WindowSpec(10.0, 8, 10.0)
    r = route_events([45.0], None, None, spec)
    assert r.head == 4
    # agreed clock at 8: window 0's span ([0, 10)) has not ended yet ->
    # accepted and NOT late (the local head alone would call it late)
    r2 = route_events([5.0], r.watermark, r.head, spec, agreed=8.0)
    assert list(r2.slot_ids) == [0] and r2.n_late == 0
    # agreed clock at 12: window 0 ended (10 <= 12) but is within the
    # lateness -> accepted AND late, on every rank that sees agreed=12
    r3 = route_events([5.0], r.watermark, r.head, spec, agreed=12.0)
    assert list(r3.slot_ids) == [0] and r3.n_late == 1
    # pre-agreement: no window has closed yet, so nothing can be late
    r4 = route_events([5.0], r.watermark, r.head, spec, agreed=-math.inf)
    assert list(r4.slot_ids) == [0] and r4.n_late == 0


def test_reregistration_lifts_straggler_exclusion():
    """The recovered-shard rejoin: a rank excluded as a straggler whose
    restored report EQUALS its pre-crash watermark (replay advances
    nothing) rejoins by RE-REGISTERING under its old rank — without it the
    recovered-and-healthy rank would stay excluded until a strictly newer
    event arrived, forever on an ended stream."""
    import time as _time

    from metrics_tpu import WatermarkAgreement

    ag = WatermarkAgreement(deadline_s=0.2)
    ag.register(0)
    ag.register(1)
    ag.report(0, 50.0)
    ag.report(1, 10.0)
    assert ag.agreed() == 10.0
    _time.sleep(0.3)
    ag.report(0, 55.0)  # rank 0 stays live; rank 1 crosses its deadline
    assert ag.agreed() == 55.0
    assert ag.excluded() == (1,) and ag.degraded
    assert ag.stragglers == 1
    # an equal-value report alone is NOT an advance and must not rejoin a
    # rank whose clock genuinely stalled (that would re-wedge the frontier)
    ag.report(1, 10.0)
    assert ag.excluded() == (1,)
    # ...but re-registration (recover_shard -> attach_agreement) is a
    # liveness signal: the exclusion lifts and the stamp refreshes, while
    # the agreed high-water never regresses below 55
    ag.register(1)
    assert ag.excluded() == ()
    assert not ag.degraded
    assert ag.agreed() == 55.0
    ag.report(1, 10.0)  # still within the refreshed deadline: included
    assert ag.excluded() == ()


# ------------------------------------------------- partial wire format (v1)
def test_partial_wire_format_version_round_trips():
    """The versioned wire format satellite: every mergeable partial leaves
    the producer stamped with ``PARTIAL_SCHEMA_VERSION``, round-trips
    through ``merge_partials``/``value_from_partials`` unchanged, and a
    drifted or missing version is refused LOUDLY on the consumer side —
    never silently merged into a live aggregate."""
    from metrics_tpu import Keyed
    from metrics_tpu.parallel.slab import PARTIAL_SCHEMA_VERSION, check_partial_version

    times, preds, target = _stream(n=32, horizon=9.0)
    shards = [Windowed(Accuracy(), window_s=10.0, num_windows=3) for _ in range(2)]
    for m in shards:
        m.update(preds, target, event_time=times)
    partials = [m.window_partial(0) for m in shards]
    for p in partials:
        assert p["version"] == PARTIAL_SCHEMA_VERSION
        assert check_partial_version(p) is p  # validation is pass-through
    union = Windowed(Accuracy(), window_s=10.0, num_windows=3)
    merged = np.asarray(union.value_from_partials(partials))
    whole = Windowed(Accuracy(), window_s=10.0, num_windows=3)
    whole.update(preds, target, event_time=times)
    np.testing.assert_array_equal(merged, np.asarray(whole.compute()))
    # the keyed (cross-rank delta) partial speaks the same versioned format
    keyed = Keyed(Accuracy(), num_slots=4)
    keyed.update(preds, target, slot=jnp.asarray(np.int32(np.arange(32) % 4)))
    kp = keyed.mergeable_partial()
    assert kp["version"] == PARTIAL_SCHEMA_VERSION
    np.testing.assert_array_equal(
        np.asarray(keyed.compute()),
        np.asarray(Keyed(Accuracy(), num_slots=4).value_from_partials([kp])),
    )

    # drifted producers are refused at every consumer
    drifted = dict(partials[0], version=PARTIAL_SCHEMA_VERSION + 1)
    unstamped = {k: v for k, v in partials[0].items() if k != "version"}
    for bad in (drifted, unstamped):
        with pytest.raises(ValueError, match="version mismatch"):
            check_partial_version(bad)
        with pytest.raises(ValueError, match="version mismatch"):
            union.merge_partials([partials[1], bad])
    with pytest.raises(ValueError, match="version mismatch"):
        Keyed(Accuracy(), num_slots=4).value_from_partials(
            [dict(kp, version="v0")]
        )
    with pytest.raises(ValueError, match="not a mergeable partial"):
        check_partial_version("partial")
