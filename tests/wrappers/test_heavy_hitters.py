"""HeavyHitters: open-world two-tier correctness matrix.

The contract under test (wrappers/heavy_hitters.py + parallel/cms.py):

- hot keys (admitted with no tail residue) are BIT-EXACT vs independent
  clones of the inner metric — sum/mean array states and sketch states;
- the tail NEVER undercounts, every tail query on the seeded Zipfian stream
  lies within the reported ``(e/width) * N`` certificate, and promotion/
  demotion round-trips are MASS-CONSERVING: hot + tail totals stay bit-exact
  the whole stream's (the property ``Keyed``'s LRU eviction destroys);
- ``compute(key=)`` reads either tier, ``compute_heavy_hitters()`` ranks by
  the space-saving count with honest ``exact`` flags;
- checkpoints round-trip (slabs + tails + the space-saving table + mirror);
- on a real (4,2) mesh the hierarchical synced compute equals the single
  process with a PSUM-ONLY staged program identical to the unkeyed metric's;
- the rejection matrix is loud (min/max, cat/buffer, missing key, tracing).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import metrics_tpu.observability as obs
from metrics_tpu import AUROC, Accuracy, HeavyHitters, Keyed
from metrics_tpu.core.metric import Metric
from metrics_tpu.parallel.cms import CMSTail
from metrics_tpu.parallel.placement import MeshHierarchy
from metrics_tpu.utils import compat
from metrics_tpu.utils.exceptions import TracingUnsupportedError
from metrics_tpu.wrappers.heavy_hitters import SpaceSavingTable


class _Sum(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", default=np.zeros((), np.float32), dist_reduce_fx="sum")

    def update(self, values):
        self.total = self.total + jnp.sum(values)

    def compute(self):
        return self.total


class _Mean(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("value", default=np.zeros((), np.float32), dist_reduce_fx="mean")

    def update(self, values):
        self.value = self.value + jnp.sum(values)  # sum-backed under the wrapper

    def compute(self):
        return self.value


class _Max(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("high", default=np.asarray(-np.inf, np.float32), dist_reduce_fx="max")

    def update(self, values):
        self.high = jnp.maximum(self.high, jnp.max(values))

    def compute(self):
        return self.high


# --------------------------------------------------------------- hot parity
def test_hot_keys_bit_exact_vs_clones():
    hh = HeavyHitters(Accuracy(), num_hot_slots=3, tail=(4, 64))
    clones = {k: Accuracy() for k in ("a", "b", "c")}
    rng = np.random.RandomState(0)
    for _ in range(4):
        preds = jnp.asarray(rng.rand(9).astype(np.float32))
        target = jnp.asarray(rng.randint(0, 2, 9).astype(np.int32))
        keys = ["a", "b", "c"] * 3
        hh.update(preds, target, key=keys)
        for key, clone in clones.items():
            idx = np.asarray([i for i, k in enumerate(keys) if k == key])
            clone.update(preds[idx], target[idx])
    for record in hh.compute_heavy_hitters():
        assert record["exact"] is True
        np.testing.assert_array_equal(
            np.asarray(record["value"]), np.asarray(clones[record["key"]].compute())
        )
        np.testing.assert_array_equal(
            np.asarray(hh.compute(key=record["key"])), np.asarray(record["value"])
        )


def test_mean_kind_divides_by_per_key_count_in_both_tiers():
    hh = HeavyHitters(_Mean(), num_hot_slots=1, tail=(4, 64))
    hh.update(jnp.asarray([2.0, 4.0, 6.0]), key=["hot", "hot", "hot"])
    hh.update(jnp.asarray([10.0]), key=["hot"])
    assert float(hh.compute(key="hot")) == pytest.approx(22.0 / 4)
    hh.update(jnp.asarray([3.0, 5.0]), key=["tail-key", "tail-key"])
    est = hh.tail_estimate("tail-key")
    assert est["count"] == 2
    assert float(est["value"]) == pytest.approx(4.0)


def test_sketch_inner_hot_parity_bit_exact():
    hh = HeavyHitters(AUROC(approx="sketch", num_bins=32), num_hot_slots=2, tail=(4, 64))
    clones = {k: AUROC(approx="sketch", num_bins=32) for k in ("x", "y")}
    rng = np.random.RandomState(1)
    for _ in range(3):
        preds = jnp.asarray(rng.rand(8).astype(np.float32))
        target = jnp.asarray(rng.randint(0, 2, 8).astype(np.int32))
        keys = ["x", "y"] * 4
        hh.update(preds, target, key=keys)
        for key, clone in clones.items():
            idx = np.asarray([i for i, k in enumerate(keys) if k == key])
            clone.update(preds[idx], target[idx])
    for record in hh.compute_heavy_hitters():
        np.testing.assert_array_equal(
            np.asarray(record["value"]), np.asarray(clones[record["key"]].compute())
        )


# --------------------------------------------- promotion / demotion / mass
def _zipf_stream(batches=30, batch=32, space=5_000, seed=7):
    rng = np.random.RandomState(seed)
    for _ in range(batches):
        keys = [int(k) for k in rng.zipf(1.5, batch) % space]
        preds = jnp.asarray(rng.rand(batch).astype(np.float32))
        target = jnp.asarray(rng.randint(0, 2, batch).astype(np.int32))
        yield keys, preds, target


def test_mass_conservation_bit_exact_through_churn():
    """Hot + tail totals equal an unkeyed oracle's state bit-exactly after
    heavy promotion/demotion churn — demotion FOLDS, never destroys."""
    hh = HeavyHitters(Accuracy(), num_hot_slots=8, tail=(4, 256))
    oracle = Accuracy()
    total = 0
    for keys, preds, target in _zipf_stream():
        hh.update(preds, target, key=keys)
        oracle.update(preds, target)
        total += len(keys)
    assert hh._table.demotions > 0  # the stream actually churned
    hot_rows = int(np.asarray(hh.hh_rows).sum())
    assert hot_rows + hh.tail_mass() == total
    for name in ("correct", "total"):
        hot = int(np.asarray(getattr(hh, name)).sum())
        # every tail row carries the full tail mass: row 0's total IS it
        tail = int(np.asarray(getattr(hh, name + "_tail").counts[0]).sum())
        assert hot + tail == int(np.asarray(getattr(oracle, name))), name


def test_tail_never_undercounts_and_respects_certificate():
    hh = HeavyHitters(Accuracy(), num_hot_slots=8, tail=(4, 1024))
    true_counts: dict = {}
    for keys, preds, target in _zipf_stream(space=2_000):
        hh.update(preds, target, key=keys)
        for k in keys:
            true_counts[k] = true_counts.get(k, 0) + 1
    bound = hh.tail_overcount_bound()
    assert bound > 0
    checked = 0
    for key, true in true_counts.items():
        if key in hh._table:
            continue  # hot keys read the exact tier
        est = hh.tail_estimate(key)
        assert est["count"] >= true, key  # never an undercount
        assert est["count"] - true <= bound, key  # within the certificate
        assert est["bound"] == pytest.approx(bound)
        checked += 1
    assert checked > 50  # the stream actually exercised the tail


def test_promotion_takes_coldest_slot_and_flags_residue():
    hh = HeavyHitters(_Sum(), num_hot_slots=2, tail=(4, 64))
    hh.update(jnp.asarray([1.0] * 5 + [1.0] * 2), key=["a"] * 5 + ["b"] * 2)
    # "c" arrives heavier than b's count: promotes into b's slot
    hh.update(jnp.asarray([1.0] * 4), key=["c"] * 4)
    keys = {r["key"]: r for r in hh.compute_heavy_hitters()}
    assert set(keys) == {"a", "c"}
    assert keys["a"]["exact"] is True and keys["a"]["count"] == 5
    assert keys["c"]["count"] == 4
    # b's 2 samples were folded, not destroyed: its tail estimate covers them
    est = hh.tail_estimate("b")
    assert est["count"] >= 2
    assert float(est["value"]) >= 2.0  # the folded sum came along
    # a cold repeat of "b" stays in the tail (2+1 <= a's count), no demotion of a
    hh.update(jnp.asarray([1.0]), key=["b"])
    assert "b" not in hh._table
    assert keys["a"]["count"] == 5


def test_heavy_hitters_ranking_and_k_limit():
    hh = HeavyHitters(_Sum(), num_hot_slots=4, tail=(2, 32))
    hh.update(jnp.ones((6,), jnp.float32), key=["a", "a", "a", "b", "b", "c"])
    records = hh.compute_heavy_hitters()
    assert [r["key"] for r in records] == ["a", "b", "c"]
    assert [r["count"] for r in records] == [3, 2, 1]
    assert [r["key"] for r in hh.compute_heavy_hitters(k=2)] == ["a", "b"]


# --------------------------------------------------------------- tier reads
def test_empty_policies_and_unknown_key():
    hh = HeavyHitters(Accuracy(), num_hot_slots=2, tail=(2, 32))
    values = hh.compute()
    assert np.isnan(np.asarray(values)).all()
    est = hh.tail_estimate("never-seen")
    assert est["count"] == 0 and np.isnan(np.asarray(est["value"])).all()
    zero = HeavyHitters(Accuracy(), num_hot_slots=2, tail=(2, 32), empty="zero")
    assert float(zero.tail_estimate("never-seen")["value"]) == 0.0


def test_compute_key_read_never_poisons_the_cache():
    hh = HeavyHitters(_Sum(), num_hot_slots=2, tail=(2, 32))
    hh.update(jnp.asarray([1.0, 2.0]), key=["a", "b"])
    assert float(hh.compute(key="a")) == 1.0
    full = np.asarray(hh.compute())
    assert full.shape == (2,)
    assert set(full.tolist()) == {1.0, 2.0}


# ------------------------------------------------------------ rejections etc
def test_rejections_are_loud():
    with pytest.raises(ValueError, match="min/max"):
        HeavyHitters(_Max(), num_hot_slots=2)
    with pytest.raises(ValueError, match="cat/list/buffer"):
        HeavyHitters(AUROC(), num_hot_slots=2)  # buffer-state inner
    with pytest.raises(ValueError, match="tail"):
        HeavyHitters(_Sum(), num_hot_slots=2, tail="wide")
    with pytest.raises(ValueError, match="empty"):
        HeavyHitters(_Sum(), num_hot_slots=2, empty="drop")
    hh = HeavyHitters(_Sum(), num_hot_slots=2)
    with pytest.raises(ValueError, match="key="):
        hh.update(jnp.ones((2,)))
    with pytest.raises(ValueError, match="data argument"):
        hh.update(key=["a"])
    with pytest.raises(KeyError):
        hh._table.slot_of("missing")


def test_rejects_jit_tracing():
    hh = HeavyHitters(_Sum(), num_hot_slots=2)

    def step(values):
        hh.update(values, key=["a", "b"])
        return values

    with pytest.raises(TracingUnsupportedError):
        jax.jit(step)(jnp.ones((2,), jnp.float32))


# ----------------------------------------------------------------- lifecycle
def test_checkpoint_roundtrip_with_table_and_mirror():
    hh = HeavyHitters(Accuracy(), num_hot_slots=4, tail=(4, 128))
    for keys, preds, target in _zipf_stream(batches=8, space=200):
        hh.update(preds, target, key=keys)
    state = hh.state_dict()
    fresh = HeavyHitters(Accuracy(), num_hot_slots=4, tail=(4, 128))
    fresh.load_state_dict(state)
    assert fresh._table.keys() == hh._table.keys()
    assert fresh._table.promotions == hh._table.promotions
    np.testing.assert_array_equal(fresh._table._mirror, hh._table._mirror)
    original = {r["key"]: np.asarray(r["value"]) for r in hh.compute_heavy_hitters()}
    restored = {r["key"]: np.asarray(r["value"]) for r in fresh.compute_heavy_hitters()}
    assert original.keys() == restored.keys()
    for key in original:
        np.testing.assert_array_equal(original[key], restored[key])
    # a tail key reads identically through the restored mirror + tails
    tail_keys = [k for k in range(200) if k not in hh._table]
    assert tail_keys
    before, after = hh.tail_estimate(tail_keys[0]), fresh.tail_estimate(tail_keys[0])
    assert after["count"] == before["count"]
    assert after["bound"] == pytest.approx(before["bound"])
    np.testing.assert_array_equal(np.asarray(after["value"]), np.asarray(before["value"]))


def test_reset_clears_tiers_and_table():
    hh = HeavyHitters(_Sum(), num_hot_slots=2, tail=(2, 32))
    hh.update(jnp.ones((3,), jnp.float32), key=["a", "b", "c"])
    hh.reset()
    assert len(hh._table) == 0
    assert hh.tail_mass() == 0
    assert int(np.asarray(hh.hh_rows).sum()) == 0
    assert np.isnan(np.asarray(hh.compute())).all()


def test_gauges_record_tiers():
    obs.reset()
    obs.enable()
    try:
        hh = HeavyHitters(_Sum(), num_hot_slots=2, tail=(2, 32))
        hh.update(jnp.ones((4,), jnp.float32), key=["a", "b", "c", "c"])
        snap = obs.counters_snapshot()
    finally:
        obs.disable()
        obs.reset()
    gauge = snap["heavy_hitters"]["HeavyHitters(_Sum)"]
    assert gauge["hot_slots"] == 2 and gauge["hot_occupied"] == 2
    assert gauge["promotions"] == 2
    assert gauge["tail_mass"] == 2  # c's two samples
    assert gauge["tail_bound"] == pytest.approx(np.e / 32 * 2)


# --------------------------------------------------- mesh sync (flat + hier)
@pytest.mark.parametrize("hierarchical", [False, True])
def test_mesh_synced_compute_matches_single_process(eight_devices, hierarchical):
    """The psum-only contract on a real mesh: per-device heavy-hitter states
    synced through ``coalesced_sync_state`` equal the single process that saw
    all the traffic, and the staged program stages ZERO gathers."""
    from metrics_tpu.parallel.sync import coalesced_sync_state

    rng = np.random.RandomState(3)
    shards = []
    single = HeavyHitters(Accuracy(), num_hot_slots=4, tail=(4, 128))
    # identical heavy warm-up on every shard AND (x8) on the single process:
    # keys 0..3 admit in the same order everywhere with counts no stream key
    # can overtake, so key -> slot layouts stay row-aligned with zero churn
    # (cross-device hot slabs merge soundly only under a shared layout; the
    # tail cells are globally addressed and merge soundly regardless)
    warm_keys = [k for k in range(4) for _ in range(40)]
    warm_p = jnp.zeros((len(warm_keys),), jnp.float32)
    warm_t = jnp.zeros((len(warm_keys),), jnp.int32)
    all_preds, all_target, all_keys = [], [], []
    for _ in range(8):
        preds = rng.rand(16).astype(np.float32)
        target = rng.randint(0, 2, 16).astype(np.int32)
        keys = [int(k) for k in rng.randint(0, 8, 16)]  # 4..7 stay tail
        shard = HeavyHitters(Accuracy(), num_hot_slots=4, tail=(4, 128))
        shard.update(warm_p, warm_t, key=warm_keys)
        shards.append(shard)
        all_preds.append(preds)
        all_target.append(target)
        all_keys.extend(keys)
        shard.update(jnp.asarray(preds), jnp.asarray(target), key=keys)
        assert shard._table.demotions == 0  # layout stayed aligned
    for _ in range(8):
        single.update(warm_p, warm_t, key=warm_keys)
    single.update(
        jnp.asarray(np.concatenate(all_preds)),
        jnp.asarray(np.concatenate(all_target)),
        key=all_keys,
    )
    assert single._table.demotions == 0

    if hierarchical:
        mesh = Mesh(np.array(eight_devices).reshape(2, 4), ("dcn", "ici"))
        axis = MeshHierarchy(ici_axis="ici", dcn_axis="dcn")
    else:
        mesh = Mesh(np.array(eight_devices), ("dp",))
        axis = "dp"
    reductions = shards[0]._reductions
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *[s._current_state() for s in shards])

    def fn(state):
        per = jax.tree_util.tree_map(lambda x: x[0], state)
        return coalesced_sync_state(per, reductions, axis)

    specs = jax.tree_util.tree_map(
        lambda _: P(("dcn", "ici")) if hierarchical else P("dp"), stacked
    )
    synced = jax.jit(compat.shard_map(
        fn, mesh=mesh, in_specs=(specs,),
        out_specs=jax.tree_util.tree_map(lambda _: P(), stacked), check_vma=False,
    ))(stacked)

    obs.reset()
    obs.enable()
    try:
        jax.jit(compat.shard_map(
            fn, mesh=mesh, in_specs=(specs,),
            out_specs=jax.tree_util.tree_map(lambda _: P(), stacked), check_vma=False,
        )).lower(stacked)  # fresh trace under counting
    finally:
        snap = obs.counters_snapshot()
        obs.disable()
        obs.reset()
    gathers = sum(
        snap["calls_by_kind"].get(k, 0)
        for k in ("all_gather", "coalesced_gather", "process_allgather")
    )
    assert gathers == 0 and snap["calls_by_kind"].get("psum", 0) >= 1

    reader = shards[0]
    reader._set_state(synced)
    # hot-tier VALUES merge bit-exactly (the host table's counts stay
    # shard-local bookkeeping, so only key sets and values are compared)
    merged = {r["key"]: np.asarray(r["value"]) for r in reader.compute_heavy_hitters()}
    expected = {r["key"]: np.asarray(r["value"]) for r in single.compute_heavy_hitters()}
    assert set(merged) == set(expected) == {0, 1, 2, 3}
    for key in expected:
        np.testing.assert_array_equal(merged[key], expected[key])
    # and the synced TAIL reads match the single process exactly: tail cells
    # are globally addressed, psum of per-device sketches == one process
    for key in (4, 5, 6, 7):
        got = reader.tail_estimate(key)
        want = single.tail_estimate(key)
        assert got["count"] == want["count"]
        np.testing.assert_array_equal(np.asarray(got["value"]), np.asarray(want["value"]))


# ------------------------------------------------------- space-saving table
def test_space_saving_table_unit():
    table = SpaceSavingTable(2, depth=2, width=32, seed=1)
    ids, demoted = table.resolve(["a", "a", "b"])
    assert demoted == [] and len(set(ids.tolist())) == 2
    assert table.count_of("a") == 2 and table.is_exact("a")
    # c (1) does not beat b (1): tail-routed
    ids, demoted = table.resolve(["c"])
    assert ids.tolist() == [-1] and not demoted
    assert table.tail_estimate("c") == 1 and table.tail_mass() == 1
    # now c (1 tail + 2 batch = 3) beats b (1): demote b, admit c with credit
    ids, demoted = table.resolve(["c", "c"])
    assert len(demoted) == 1 and demoted[0][0] == "b"
    assert "c" in table and not table.is_exact("c")  # carries tail residue
    assert table.count_of("c") == 3  # credit 1 + 2 hot samples
    assert table.tail_estimate("b") >= 1  # b's fold landed in the mirror
    with pytest.raises(ValueError):
        SpaceSavingTable(0, 2, 32, 1)
    state = table.state()
    fresh = SpaceSavingTable(2, depth=2, width=32, seed=1)
    fresh.load_state(state)
    assert fresh.keys() == table.keys() and fresh.count_of("c") == 3
    table.reset()
    assert len(table) == 0 and table.tail_mass() == 0
    assert table.promotions > 0  # lifetime gauges survive reset


def test_keyed_vs_heavy_hitters_is_the_point():
    """The headline contrast: the same churny stream through Keyed(lru=True)
    LOSES the evicted tenant's history (and now counts it), while
    HeavyHitters conserves every sample."""
    stream = list(_zipf_stream(batches=16, batch=6, space=400, seed=9))
    total = sum(len(k) for k, _, _ in stream)
    keyed = Keyed(Accuracy(), num_slots=6, lru=True)
    hh = HeavyHitters(Accuracy(), num_hot_slots=6, tail=(4, 256))
    for keys, preds, target in stream:
        keyed.update(preds, target, slot=keys)
        hh.update(preds, target, key=keys)
    keyed_rows = int(np.asarray(getattr(keyed, "keyed_rows")).sum())
    assert keyed_rows < total  # evictions zeroed history
    assert int(np.asarray(hh.hh_rows).sum()) + hh.tail_mass() == total
