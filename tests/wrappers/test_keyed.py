"""Keyed multi-tenant metric slabs: correctness matrix.

The contract under test (wrappers/keyed.py + parallel/slab.py):

- ``Keyed(metric, K)`` is BIT-EXACT vs K independent clones of the inner
  metric, each fed its own segment's samples — across sum/mean/min/max array
  states and sketch states, over multiple update steps;
- empty slots follow the ``empty=`` policy (NaN vs zero), out-of-range slot
  ids are dropped (never misrouted), and LRU mode evicts in
  least-recently-used order with reset rows and a counted eviction;
- checkpoints round-trip (slab states + the LRU key table + the epoch
  watermark), and ``guarded_update`` replay stays idempotent;
- on a real (4,2) mesh the flat AND hierarchical synced compute equals the
  single-process epoch with a PSUM-ONLY staged program — one bucketed
  collective for all K segments;
- compute-group fingerprints understand slab shapes (equal slab specs
  group, differing slot counts split).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import metrics_tpu.observability as obs
from metrics_tpu import AUROC, Accuracy, Keyed, MetricCollection
from metrics_tpu.core.metric import Metric
from metrics_tpu.parallel.placement import MeshHierarchy
from metrics_tpu.parallel.slab import LRUSlotTable, SlabSpec, make_slab_spec, slab_init
from metrics_tpu.utils import compat


# --------------------------------------------------------------- toy metrics
# One tiny metric per reduce kind: per-sample decomposable by construction,
# integer-valued float inputs keep float sums order-independent (bit-exact).
class _Sum(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", default=np.zeros((), np.float32), dist_reduce_fx="sum")

    def update(self, values):
        self.total = self.total + jnp.sum(values)

    def compute(self):
        return self.total


class _Min(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("low", default=np.asarray(np.inf, np.float32), dist_reduce_fx="min")

    def update(self, values):
        self.low = jnp.minimum(self.low, jnp.min(values))

    def compute(self):
        return self.low


class _Max(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("high", default=np.asarray(-np.inf, np.float32), dist_reduce_fx="max")

    def update(self, values):
        self.high = jnp.maximum(self.high, jnp.max(values))

    def compute(self):
        return self.high


class _Mean(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("avg", default=np.zeros((), np.float32), dist_reduce_fx="mean")

    def update(self, values):
        self.avg = jnp.mean(values)

    def compute(self):
        return self.avg


def _data(seed, n, k):
    rng = np.random.RandomState(seed)
    values = rng.randint(-50, 50, n).astype(np.float32)  # integer-valued: exact sums
    slots = rng.randint(0, k, n).astype(np.int32)
    return values, slots


# ------------------------------------------------------------ clone parity
@pytest.mark.parametrize("cls,reduce", [(_Sum, "sum"), (_Min, "min"), (_Max, "max")])
def test_reduce_kind_parity_vs_clones(cls, reduce):
    """Keyed == K independent clones, bit-exact, across 3 update steps."""
    K = 6
    keyed = Keyed(cls(), num_slots=K)
    clones = [cls() for _ in range(K)]
    for step in range(3):
        values, slots = _data(step, 64, K)
        keyed.update(jnp.asarray(values), slot=jnp.asarray(slots))
        for k in range(K):
            rows = values[slots == k]
            if rows.size:
                clones[k].update(jnp.asarray(rows))
    out = np.asarray(keyed.compute())
    for k in range(K):
        np.testing.assert_array_equal(out[k], np.asarray(clones[k].compute()))


def test_mean_kind_is_per_slot_mean_over_all_samples():
    """Sum-backed mean: the slab reports each slot's mean over EVERY sample
    routed to it, across update steps (exact for integer-valued floats)."""
    K = 4
    keyed = Keyed(_Mean(), num_slots=K)
    all_values, all_slots = [], []
    for step in range(3):
        values, slots = _data(10 + step, 40, K)
        keyed.update(jnp.asarray(values), slot=jnp.asarray(slots))
        all_values.append(values)
        all_slots.append(slots)
    values = np.concatenate(all_values)
    slots = np.concatenate(all_slots)
    out = np.asarray(keyed.compute())
    for k in range(K):
        np.testing.assert_allclose(out[k], values[slots == k].mean(), rtol=0, atol=0)


def test_accuracy_parity_vs_clones():
    """A real library metric (sum-kind count states) through the same gate."""
    K = 5
    rng = np.random.RandomState(3)
    keyed = Keyed(Accuracy(), num_slots=K)
    clones = [Accuracy() for _ in range(K)]
    for step in range(2):
        preds = rng.rand(48).astype(np.float32)
        target = rng.randint(0, 2, 48)
        slots = rng.randint(0, K, 48)
        keyed.update(jnp.asarray(preds), jnp.asarray(target), slot=jnp.asarray(slots))
        for k in range(K):
            m = slots == k
            if m.any():
                clones[k].update(jnp.asarray(preds[m]), jnp.asarray(target[m]))
    out = np.asarray(keyed.compute())
    ref = np.asarray([np.asarray(c.compute()) for c in clones])
    np.testing.assert_array_equal(out, ref)


def test_sketch_state_parity_vs_clones_bit_exact():
    """Keyed(AUROC(approx='sketch'), K) scatters into a (K, 2, B) histogram
    slab; integer counts make the parity vs K clones bit-exact for ANY
    scores."""
    K = 8
    rng = np.random.RandomState(4)
    keyed = Keyed(AUROC(approx="sketch", num_bins=64), num_slots=K)
    clones = [AUROC(approx="sketch", num_bins=64) for _ in range(K)]
    for step in range(3):
        preds = rng.rand(96).astype(np.float32)
        target = rng.randint(0, 2, 96)
        slots = rng.randint(0, K, 96)
        keyed.update(jnp.asarray(preds), jnp.asarray(target), slot=jnp.asarray(slots))
        for k in range(K):
            m = slots == k
            if m.any():
                clones[k].update(jnp.asarray(preds[m]), jnp.asarray(target[m]))
    # the slab rows ARE the clones' histograms
    slab_counts = np.asarray(keyed.hist.counts)
    for k in range(K):
        np.testing.assert_array_equal(slab_counts[k], np.asarray(clones[k].hist.counts))
    np.testing.assert_array_equal(
        np.asarray(keyed.compute()), np.asarray([np.asarray(c.compute()) for c in clones])
    )


def test_fused_jit_forward_matches_eager():
    """The jittable scatter path (jit=True fused step) accumulates and
    reports batch values identically to the eager path."""
    K = 4
    rng = np.random.RandomState(5)
    preds = jnp.asarray(rng.rand(32).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 2, 32))
    slots = jnp.asarray(rng.randint(0, K, 32))
    jitted = Keyed(Accuracy(), num_slots=K, jit=True)
    eager = Keyed(Accuracy(), num_slots=K, jit=False)
    v_jit = jitted(preds, target, slot=slots)
    v_eager = eager(preds, target, slot=slots)
    assert not jitted._jit_failed
    np.testing.assert_array_equal(np.asarray(v_jit), np.asarray(v_eager))
    np.testing.assert_array_equal(np.asarray(jitted.compute()), np.asarray(eager.compute()))


# ----------------------------------------------------- empty / out-of-range
def test_empty_slot_policies():
    values = jnp.asarray(np.asarray([1.0, 2.0], np.float32))
    nan_policy = Keyed(_Sum(), num_slots=3, empty="nan")
    nan_policy.update(values, slot=jnp.asarray([0, 0]))
    out = np.asarray(nan_policy.compute())
    assert out[0] == 3.0 and np.isnan(out[1]) and np.isnan(out[2])

    zero_policy = Keyed(_Sum(), num_slots=3, empty="zero")
    zero_policy.update(values, slot=jnp.asarray([0, 0]))
    np.testing.assert_array_equal(np.asarray(zero_policy.compute()), [3.0, 0.0, 0.0])

    with pytest.raises(ValueError, match="`empty`"):
        Keyed(_Sum(), num_slots=3, empty="skip")


def test_out_of_range_slot_ids_are_dropped():
    """Ids outside [0, K) vanish (XLA scatter drop semantics) — they never
    land in another segment's row, and the dropped rows count nowhere."""
    keyed = Keyed(_Sum(), num_slots=2)
    keyed.update(
        jnp.asarray(np.asarray([1.0, 10.0, 100.0, 1000.0], np.float32)),
        slot=jnp.asarray([0, 7, -3, 1]),
    )
    out = np.asarray(keyed.compute())
    np.testing.assert_array_equal(out, [1.0, 1000.0])
    np.testing.assert_array_equal(np.asarray(keyed.keyed_rows), [1, 1])


# ------------------------------------------------------------------ LRU mode
def test_lru_eviction_order_and_reset():
    """Least-recently-USED goes first (touching refreshes recency), the
    recycled row restarts from the default, and evictions are counted."""
    keyed = Keyed(_Sum(), num_slots=2, lru=True)
    keyed.update(jnp.asarray(np.float32([1.0, 2.0])), slot=["a", "b"])
    keyed.update(jnp.asarray(np.float32([3.0])), slot=["a"])  # touch a: b is now LRU
    keyed.update(jnp.asarray(np.float32([5.0])), slot=["c"])  # evicts b, not a
    table = keyed._slots
    assert table.evictions == 1
    assert set(table.keys()) == {"a", "c"}
    assert float(keyed.compute(slot="a")) == 4.0
    assert float(keyed.compute(slot="c")) == 5.0  # b's old 2.0 was reset away
    with pytest.raises(KeyError, match="evicted or never seen"):
        keyed.compute(slot="b")
    # the evicted key can return; it restarts clean on a recycled row
    keyed.update(jnp.asarray(np.float32([7.0])), slot=["b"])
    assert float(keyed.compute(slot="b")) == 7.0
    assert table.evictions == 2


def test_lru_batch_wider_than_table_raises():
    keyed = Keyed(_Sum(), num_slots=2, lru=True)
    with pytest.raises(ValueError, match="more than num_slots"):
        keyed.update(jnp.asarray(np.float32([1.0, 2.0, 3.0])), slot=["a", "b", "c"])


def test_lru_rejects_jit_tracing():
    keyed = Keyed(_Sum(), num_slots=2, lru=True)

    def step(values):
        keyed.update(values, slot=["a"])

    from metrics_tpu.utils.exceptions import TracingUnsupportedError

    with pytest.raises(TracingUnsupportedError, match="lru"):
        jax.jit(step)(jnp.ones((1,), jnp.float32))


# --------------------------------------------------------------- lifecycle
def test_checkpoint_roundtrip_with_lru_table_and_watermark():
    keyed = Keyed(AUROC(approx="sketch", num_bins=32), num_slots=3, lru=True)
    rng = np.random.RandomState(6)
    for step in range(2):
        preds = jnp.asarray(rng.rand(16).astype(np.float32))
        target = jnp.asarray(rng.randint(0, 2, 16))
        keyed.update(preds, target, slot=["us", "eu"] * 8)
    saved = keyed.state_dict()

    restored = Keyed(AUROC(approx="sketch", num_bins=32), num_slots=3, lru=True)
    restored.load_state_dict(saved)
    assert restored.epoch_watermark == keyed.epoch_watermark == 2
    assert restored._slots.keys() == keyed._slots.keys()
    np.testing.assert_array_equal(
        np.asarray(restored.compute(slot="eu")), np.asarray(keyed.compute(slot="eu"))
    )
    np.testing.assert_array_equal(np.asarray(restored.compute()), np.asarray(keyed.compute()))


def test_guarded_update_replay_is_idempotent():
    """The preemption contract: replaying a step at or below the restored
    watermark is a no-op, so a Keyed epoch resumed mid-flight cannot
    double-count any segment."""
    keyed = Keyed(_Sum(), num_slots=2)
    values = jnp.asarray(np.float32([1.0, 2.0]))
    slots = jnp.asarray([0, 1])
    assert keyed.guarded_update(0, values, slot=slots) is True
    assert keyed.guarded_update(1, values, slot=slots) is True
    saved = keyed.state_dict()

    restored = Keyed(_Sum(), num_slots=2)
    restored.load_state_dict(saved)
    assert restored.epoch_watermark == 2
    assert restored.guarded_update(1, values, slot=slots) is False  # replayed step: no-op
    assert restored.guarded_update(2, values, slot=slots) is True
    # 3 applied steps (0, 1, 2) — the replayed step 1 added nothing
    np.testing.assert_array_equal(np.asarray(restored.compute()), [3.0, 6.0])


def test_reset_clears_slabs_and_lru_keys():
    keyed = Keyed(_Sum(), num_slots=2, lru=True)
    keyed.update(jnp.asarray(np.float32([1.0])), slot=["a"])
    keyed.reset()
    assert len(keyed._slots) == 0
    assert np.isnan(np.asarray(keyed.compute())).all()


def test_compute_slot_read_never_poisons_the_cache():
    keyed = Keyed(_Sum(), num_slots=3)
    keyed.update(jnp.asarray(np.float32([1.0, 2.0, 3.0])), slot=jnp.asarray([0, 1, 2]))
    assert float(keyed.compute(slot=1)) == 2.0
    # the cached value is the FULL slab result, not the slice
    np.testing.assert_array_equal(np.asarray(keyed.compute()), [1.0, 2.0, 3.0])
    assert float(keyed.compute(slot=2)) == 3.0


def test_clone_is_independent():
    keyed = Keyed(_Sum(), num_slots=2)
    keyed.update(jnp.asarray(np.float32([1.0])), slot=jnp.asarray([0]))
    twin = keyed.clone()
    twin.update(jnp.asarray(np.float32([10.0])), slot=jnp.asarray([0]))
    assert float(keyed.compute(slot=0)) == 1.0
    assert float(twin.compute(slot=0)) == 11.0


# -------------------------------------------------------------- validation
def test_rejects_buffer_and_cat_state_inners():
    with pytest.raises(ValueError, match="no per-slot slab form"):
        Keyed(AUROC(), num_slots=4)  # exact AUROC: list cat-states
    with pytest.raises(ValueError, match="no per-slot slab form"):
        Keyed(AUROC(capacity=64), num_slots=4)  # PaddedBuffer cat-states


def test_update_requires_slot_and_data():
    keyed = Keyed(_Sum(), num_slots=2)
    with pytest.raises(ValueError, match="slot"):
        keyed.update(jnp.ones((2,), jnp.float32))
    with pytest.raises(ValueError, match="data argument"):
        keyed.update(slot=jnp.asarray([0, 1]))


def test_slab_spec_rejects_nonzero_sum_template():
    with pytest.raises(ValueError, match="zero default template"):
        make_slab_spec(4, np.ones((2,), np.float32), "sum")


def test_lru_table_free_list_and_contains():
    table = LRUSlotTable(3)
    ids, evicted = table.resolve(["x", "y", "x"])
    assert evicted == [] and len(table) == 2
    assert list(ids) == [0, 1, 0]
    assert "x" in table and "z" not in table


# --------------------------------------------------- mesh sync (flat + hier)
@pytest.mark.parametrize("hierarchical", [False, True], ids=["flat", "hier42"])
def test_mesh_synced_compute_matches_single_process(eight_devices, hierarchical):
    """The acceptance property on a REAL staged program: 8 device shards
    update their local slabs, one coalesced sync moves all K segments, and
    the synced compute equals the single-process epoch bit-exactly — with a
    PSUM-ONLY program (zero staged gathers of any kind), flat and (4,2)
    hierarchical."""
    K = 16
    keyed = Keyed(AUROC(approx="sketch", num_bins=32), num_slots=K)
    rng = np.random.RandomState(7)
    preds = rng.rand(8, 64).astype(np.float32)
    target = rng.randint(0, 2, (8, 64)).astype(np.int32)
    slots = rng.randint(0, K, (8, 64)).astype(np.int32)

    if hierarchical:
        mesh = Mesh(np.array(eight_devices).reshape(2, 4), ("dcn", "ici"))
        axis, specs = MeshHierarchy(ici_axis="ici", dcn_axis="dcn"), P(("dcn", "ici"))
    else:
        mesh = Mesh(np.array(eight_devices), ("dp",))
        axis, specs = "dp", P("dp")

    def fn(p, t, s):
        local = keyed.update_state(keyed.init_state(), p[0], t[0], slot=s[0])
        synced = keyed.sync_state(local, axis)
        return synced

    obs.enable()
    obs.COUNTERS.reset()
    f = jax.jit(compat.shard_map(
        fn, mesh=mesh, in_specs=(specs, specs, specs),
        out_specs=jax.tree_util.tree_map(lambda _: P(), keyed.init_state()),
        check_vma=False,
    ))
    synced = f(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(slots))
    snap = obs.counters_snapshot()
    obs.disable()

    # psum-only: the histogram slab + row-count slab share ONE int32 bucket
    assert snap["calls_by_kind"].get("psum", 0) == (2 if hierarchical else 1)
    for kind in ("all_gather", "coalesced_gather", "process_allgather", "ppermute"):
        assert snap["calls_by_kind"].get(kind, 0) == 0, kind

    single = Keyed(AUROC(approx="sketch", num_bins=32), num_slots=K)
    single.update(
        jnp.asarray(preds.reshape(-1)), jnp.asarray(target.reshape(-1)),
        slot=jnp.asarray(slots.reshape(-1)),
    )
    np.testing.assert_array_equal(
        np.asarray(synced["hist"].counts), np.asarray(single.hist.counts)
    )
    np.testing.assert_array_equal(
        np.asarray(synced["keyed_rows"]), np.asarray(single.keyed_rows)
    )
    np.testing.assert_array_equal(
        np.asarray(single.compute_from_state(synced)), np.asarray(single.compute())
    )


# ----------------------------------------------------------- observability
def test_slab_gauges_and_state_bytes_label():
    obs.enable()
    obs.COUNTERS.reset()
    try:
        keyed = Keyed(_Sum(), num_slots=8, lru=True)
        keyed.update(jnp.asarray(np.float32([1.0, 2.0])), slot=["a", "b"])
        snap = obs.counters_snapshot()
    finally:
        obs.disable()
    gauges = snap["slab_slots"]["Keyed(_Sum)"]
    assert gauges == {"slots": 8, "occupied": 2, "evictions": 0}
    # the state-bytes gauge stays attributable to the inner metric
    assert snap["state_bytes"]["Keyed(_Sum)"] > 0


def test_non_lru_occupancy_gauge_when_counting():
    obs.enable()
    obs.COUNTERS.reset()
    try:
        keyed = Keyed(_Sum(), num_slots=4)
        keyed.update(jnp.asarray(np.float32([1.0, 2.0, 3.0])), slot=jnp.asarray([0, 0, 3]))
        snap = obs.counters_snapshot()
    finally:
        obs.disable()
    assert snap["slab_slots"]["Keyed(_Sum)"]["occupied"] == 2


# ------------------------------------------------ compute-group fingerprints
class _SlabStat(Metric):
    """A metric declaring a slab state directly: the fingerprint surface the
    compute-group machinery must understand (slab shapes split groups)."""

    _GROUP_UPDATE_ATTRS = ("num_slots",)

    def __init__(self, num_slots, **kw):
        super().__init__(**kw)
        self.num_slots = num_slots
        self.add_state(
            "slab",
            default=make_slab_spec(num_slots, np.zeros((3,), np.float32), "sum"),
            dist_reduce_fx="sum",
        )

    def update(self, values, slot):
        import jax as _jax

        self.slab = self.slab + _jax.ops.segment_sum(values, slot, self.num_slots)

    def compute(self):
        return jnp.sum(self.slab, axis=-1)


def test_group_fingerprints_learn_slab_shapes():
    col = MetricCollection({"a": _SlabStat(4), "b": _SlabStat(4), "c": _SlabStat(8)})
    groups = col.compute_groups
    assert groups["a"] == ("a", "b")  # equal slab specs fuse
    assert groups["c"] == ("c",)  # a different slot count splits


def test_slab_spec_materializes_through_add_state():
    m = _SlabStat(4)
    assert isinstance(m._defaults["slab"], SlabSpec)
    assert m.slab.shape == (4, 3)
    np.testing.assert_array_equal(np.asarray(m.slab), np.zeros((4, 3), np.float32))
    fresh = slab_init(m._defaults["slab"])
    assert fresh.shape == (4, 3)
    with pytest.raises(ValueError, match="dist_reduce_fx"):

        class _Bad(Metric):
            def __init__(self):
                super().__init__()
                self.add_state(
                    "slab",
                    default=make_slab_spec(2, np.zeros((), np.float32), "min"),
                    dist_reduce_fx="sum",
                )

            def update(self):
                pass

            def compute(self):
                return None

        _Bad()


def test_retrieval_family_still_groups_after_exclusion_refactor():
    """The _GROUP_UPDATE_ATTRS=() per-class overrides were replaced by the
    base-level _GROUP_COMPUTE_ONLY_ATTRS exclusion; the family must still
    fuse into one flatten-append group, k and policy staying compute-only."""
    from metrics_tpu import RetrievalMRR, RetrievalPrecision, RetrievalRecall

    col = MetricCollection([RetrievalPrecision(k=2), RetrievalRecall(k=1), RetrievalMRR()])
    groups = col.compute_groups
    assert groups["RetrievalPrecision"] == (
        "RetrievalPrecision", "RetrievalRecall", "RetrievalMRR"
    )
    # update-relevant config still splits: capacity changes the state schema
    split = MetricCollection([RetrievalPrecision(capacity=8), RetrievalRecall()])
    assert len(split.compute_groups) == 2


def test_lru_eviction_counts_destroyed_mass_and_warns_once():
    """The data-loss satellite: an eviction that zeroes a resident row must
    bump ``evicted_mass_dropped`` by the row's sample count (recorded even
    with observability OFF, like the fault counters) and warn ONCE naming
    HeavyHitters as the lossless alternative."""
    import warnings

    from metrics_tpu.utils import prints

    obs.reset()
    prints._WARN_ONCE_SEEN.clear()
    try:
        keyed = Keyed(_Sum(), num_slots=2, lru=True)
        keyed.update(jnp.asarray(np.float32([1.0, 2.0, 3.0])), slot=["a", "b", "b"])
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            keyed.update(jnp.asarray(np.float32([5.0])), slot=["c"])  # evicts a (1 sample)
            keyed.update(jnp.asarray(np.float32([6.0])), slot=["d"])  # evicts b (2 samples)
        snap = obs.counters_snapshot()
        assert snap["evicted_mass_dropped"] == 3  # 1 (a) + 2 (b) samples destroyed
        hh_warnings = [w for w in caught if "HeavyHitters" in str(w.message)]
        assert len(hh_warnings) == 1  # deduped: once per process, not per eviction
        assert "evicted_mass_dropped" in str(hh_warnings[0].message)
    finally:
        obs.reset()
        prints._WARN_ONCE_SEEN.clear()
