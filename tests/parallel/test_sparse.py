"""Sparse delta-sync plane: bit-exactness, capacity fallback, chaos matrix.

The plane's contract is the invariant ``merged == dense_sync(current)``:
whatever the dense coalesced plane would produce from the ranks' current
states, the sparse round — touched-row bitmap psum, fixed-capacity union
gather, scatter-add fold — must reproduce BIT-EXACTLY, while staging bytes
proportional to the touched rows. Every parity test here compares against a
real ``coalesced_sync_state`` program on the same mesh. The chaos scenarios
(site ``sparse_sync``) run under an enforced timeout: a fault may cost a
retry, never a hang and never a wrong merged view.
"""
import threading
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from metrics_tpu import AUROC, Accuracy, HeavyHitters, Keyed, Windowed
from metrics_tpu.observability import counters as obs_counters
from metrics_tpu.parallel import faults
from metrics_tpu.parallel.placement import MeshHierarchy
from metrics_tpu.parallel.slab import slab_touched_mask
from metrics_tpu.parallel.sparse import (
    SparseSyncPlane,
    _payload_of,
    pack_touched,
    touched_lane_bits,
    unpack_touched_counts,
)
from metrics_tpu.parallel.sync import SyncGuard, coalesced_sync_state
from metrics_tpu.utils import compat
from metrics_tpu.utils.exceptions import SyncTimeoutError

_TIMEOUT_S = 30.0
N = 32  # slab rows
CAP = 8
I32 = jnp.iinfo(jnp.int32)


def _within(fn, timeout_s: float = _TIMEOUT_S):
    """Run ``fn`` under an enforced deadline — a wedged sparse round fails
    loudly instead of hanging CI (the daemon worker is abandoned)."""
    box = {}
    done = threading.Event()

    def target():
        try:
            box["value"] = fn()
        except BaseException as err:  # noqa: BLE001 - re-raised on the test thread
            box["error"] = err
        finally:
            done.set()

    worker = threading.Thread(target=target, daemon=True)
    worker.start()
    assert done.wait(timeout_s), f"scenario deadlocked: exceeded the {timeout_s}s timeout"
    if "error" in box:
        raise box["error"]
    return box.get("value")


@pytest.fixture(autouse=True)
def _clean_counters():
    obs_counters.reset()
    yield
    obs_counters.reset()


def _mesh(eight_devices, hierarchical):
    if hierarchical:
        return Mesh(np.array(eight_devices).reshape(2, 4), ("dcn", "ici"))
    return Mesh(np.array(eight_devices), ("dp",))


def _axis(hierarchical):
    return ("dcn", "ici") if hierarchical else "dp"


REDUCTIONS = {"hits": "sum", "lo": "min", "hi": "max", "tail": "sum"}


def _reset_state():
    """A hand-built slab state covering every row fold kind plus a dense
    residual, at its reset fill (the plane's valid construction seed)."""
    return {
        "hits": jnp.zeros((N, 3), jnp.int32),
        "lo": jnp.full((N,), I32.max, jnp.int32),
        "hi": jnp.full((N,), I32.min, jnp.int32),
        "tail": jnp.zeros((2, 5), jnp.int32),
    }


def _touch(state, rows, salt=1):
    """Touch ``rows`` of every row leaf (and bump the dense residual)."""
    out = dict(state)
    idx = jnp.asarray(rows, jnp.int32)
    out["hits"] = out["hits"].at[idx].add(salt + idx[:, None] * 3)
    out["lo"] = out["lo"].at[idx].min(salt * 10 + idx)
    out["hi"] = out["hi"].at[idx].max(salt * 10 + idx)
    out["tail"] = out["tail"] + salt
    return out


def _dense_fn(mesh, axis, reductions):
    def body(state):
        return coalesced_sync_state(state, reductions, axis)

    return jax.jit(
        compat.shard_map(body, mesh=mesh, in_specs=(P(),), out_specs=P(), check_vma=False)
    )


def _assert_state_equal(a, b):
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(
            np.asarray(_payload_of(a[k])), np.asarray(_payload_of(b[k])), err_msg=k
        )


def _plane(eight_devices, hierarchical, **kw):
    state = _reset_state()
    mesh = _mesh(eight_devices, hierarchical)
    kw.setdefault("capacity", CAP)
    return SparseSyncPlane(state, REDUCTIONS, N, _axis(hierarchical), mesh, **kw), state, mesh


# ----------------------------------------------------------- bitmap packing
def test_touched_lane_bits_bound_world():
    # psum ADDS per-row flags, so a lane must hold the world's full count
    for world in (1, 2, 3, 4, 7, 8, 15, 16, 255, 256):
        bits = touched_lane_bits(world)
        assert bits in (1, 2, 4, 8, 16, 32)
        assert world < 2 ** bits
    assert touched_lane_bits(8) == 4


def test_pack_unpack_roundtrip_and_lane_addition():
    rng = np.random.RandomState(3)
    world = 8
    m1 = rng.rand(77) < 0.3
    m2 = rng.rand(77) < 0.3
    w1 = np.asarray(pack_touched(jnp.asarray(m1), world))
    w2 = np.asarray(pack_touched(jnp.asarray(m2), world))
    np.testing.assert_array_equal(unpack_touched_counts(w1, 77, world), m1.astype(np.int64))
    # lane addition never carries across rows: the psum of per-rank bitmaps
    # unpacks to the exact per-row touch COUNT
    np.testing.assert_array_equal(
        unpack_touched_counts(w1 + w2, 77, world), (m1.astype(np.int64) + m2)
    )


def test_slab_touched_mask_drops_out_of_range():
    ids = jnp.asarray([3, 3, 7, N + 5, N * 4], jnp.int32)
    mask = np.asarray(slab_touched_mask(ids, N))
    assert mask.dtype == np.bool_ and mask.shape == (N,)
    assert set(np.flatnonzero(mask)) == {3, 7}


# ------------------------------------------------------------- parity suite
@pytest.mark.parametrize("hierarchical", [False, True], ids=["flat", "hier"])
def test_sparse_rounds_bit_exact_vs_dense(eight_devices, hierarchical):
    plane, state, mesh = _plane(eight_devices, hierarchical)
    dense = _dense_fn(mesh, _axis(hierarchical), REDUCTIONS)

    current = _touch(state, [3, 17, 31], salt=1)
    merged = plane.sync(current)
    _assert_state_equal(merged, dense(current))
    assert (plane.rounds, plane.fallbacks, plane.skips) == (1, 0, 0)

    # incremental second round, overlapping + fresh rows: the invariant
    # merged == dense_sync(current) must survive the baseline rebind
    current2 = _touch(current, [0, 17], salt=5)
    merged2 = plane.sync(current2)
    _assert_state_equal(merged2, dense(current2))
    _assert_state_equal(plane.merged, merged2)
    assert plane.rounds == 2 and plane.fallbacks == 0


def test_touched_hint_matches_unhinted(eight_devices):
    rows = [1, 9, 30]
    plane_a, state, mesh = _plane(eight_devices, False)
    plane_b, _, _ = _plane(eight_devices, False)
    current = _touch(state, rows)
    hinted = plane_a.sync(current, touched=slab_touched_mask(jnp.asarray(rows, jnp.int32), N))
    unhinted = plane_b.sync(current)
    _assert_state_equal(hinted, unhinted)


def test_empty_touch_skips_row_exchange(eight_devices):
    plane, state, _ = _plane(eight_devices, False)
    before = obs_counters.snapshot()
    merged = plane.sync(dict(state))
    after = obs_counters.snapshot()
    _assert_state_equal(merged, state)
    assert plane.skips == 1
    assert after["sparse"]["skips"] - before["sparse"]["skips"] == 1
    assert after["gather_skips"] - before["gather_skips"] == 1


def test_overflow_falls_back_dense_bit_exact(eight_devices):
    plane, state, mesh = _plane(eight_devices, False, capacity=4)
    dense = _dense_fn(mesh, "dp", REDUCTIONS)
    current = _touch(state, list(range(0, 20, 2)), salt=2)  # 10 rows > capacity 4
    before = obs_counters.snapshot()
    merged = plane.sync(current)
    after = obs_counters.snapshot()
    _assert_state_equal(merged, dense(current))
    assert plane.fallbacks == 1
    assert after["sparse"]["fallbacks"] - before["sparse"]["fallbacks"] == 1


def test_fallback_warn_once_names_capacity(eight_devices):
    from metrics_tpu.utils import prints

    plane, state, _ = _plane(
        eight_devices, False, capacity=4, fallback_warn_rounds=2, fallback_warn_fraction=0.4
    )
    prints._WARN_ONCE_SEEN.clear()
    wide1 = _touch(state, list(range(10)), salt=1)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # round 1 of 2 must stay silent
        plane.sync(wide1)
    wide2 = _touch(wide1, list(range(10, 20)), salt=2)
    with pytest.warns(UserWarning, match=r"sparse_capacity=4"):
        plane.sync(wide2)
    assert plane.fallbacks == 2
    # warn-ONCE: a third fallback round stays silent
    wide3 = _touch(wide2, list(range(20, 30)), salt=3)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        plane.sync(wide3)


def test_stacked_per_rank_unions(eight_devices):
    """``stacked=True``: each rank contributes ITS OWN rows — the union is
    genuinely cross-rank (the replicated convention can't distinguish a
    local miss from a union miss)."""
    mesh = _mesh(eight_devices, False)
    stacked_reset = {k: jnp.broadcast_to(v, (8,) + v.shape) for k, v in _reset_state().items()}
    plane = SparseSyncPlane(
        stacked_reset, REDUCTIONS, N, "dp", mesh, capacity=16, stacked=True
    )
    plane.rebase(stacked_reset, merged=_reset_state())

    # rank r touches rows {2r, 2r+1} — 16 distinct rows across the world
    current = {k: np.array(v) for k, v in stacked_reset.items()}
    for r in range(8):
        for row in (2 * r, 2 * r + 1):
            current["hits"][r, row] += r + 1
            current["lo"][r, row] = min(current["lo"][r, row], 100 + row)
            current["hi"][r, row] = max(current["hi"][r, row], 100 + row)
        current["tail"][r] += 1
    current = {k: jnp.asarray(v) for k, v in current.items()}

    def body(state):
        local = {k: v[0] for k, v in state.items()}
        return coalesced_sync_state(local, REDUCTIONS, "dp")

    dense = jax.jit(
        compat.shard_map(body, mesh=mesh, in_specs=(P("dp"),), out_specs=P(), check_vma=False)
    )
    merged = plane.sync(current)
    _assert_state_equal(merged, dense(current))
    assert plane.fallbacks == 0


# ------------------------------------------------------------- chaos matrix
@pytest.mark.chaos
def test_chaos_drop_retries_bit_exact(eight_devices):
    plane, state, mesh = _plane(
        eight_devices, False, guard=SyncGuard(max_retries=2, backoff_s=0.0)
    )
    dense = _dense_fn(mesh, "dp", REDUCTIONS)
    current = _touch(state, [4, 8, 15])
    with faults.chaos(
        faults.FaultSpec(kind="drop", call=0, times=2, site="sparse_sync")
    ) as inj:
        merged = _within(lambda: plane.sync(current))
    assert inj.injected["drop"] == 2
    _assert_state_equal(merged, dense(current))
    assert obs_counters.snapshot()["faults"].get("sync_retries", 0) == 2


@pytest.mark.chaos
def test_chaos_stall_deadline_retries_bit_exact(eight_devices):
    plane, state, mesh = _plane(
        eight_devices,
        False,
        guard=SyncGuard(deadline_s=0.25, max_retries=2, backoff_s=0.0),
    )
    dense = _dense_fn(mesh, "dp", REDUCTIONS)
    current = _touch(state, [2, 29])
    plane.sync(current)  # compile OUTSIDE the stall so the deadline bounds the round, not XLA
    current2 = _touch(current, [2, 29], salt=7)
    # the injector numbers site calls from ITS OWN install: the round above
    # predates it, so the stall pins call 0
    with faults.chaos(
        faults.FaultSpec(kind="stall", call=0, times=1, duration_s=1.2, site="sparse_sync")
    ):
        merged = _within(lambda: plane.sync(current2))
    _assert_state_equal(merged, dense(current2))
    assert obs_counters.snapshot()["faults"].get("sync_retries", 0) >= 1


@pytest.mark.chaos
def test_chaos_corrupt_retries_bit_exact(eight_devices):
    plane, state, mesh = _plane(
        eight_devices,
        False,
        guard=SyncGuard(max_retries=2, backoff_s=0.0, check_finite=True),
    )
    dense = _dense_fn(mesh, "dp", REDUCTIONS)
    current = _touch(state, [11, 12])
    with faults.chaos(
        faults.FaultSpec(kind="corrupt", call=0, times=1, site="sparse_sync")
    ) as inj:
        merged = _within(lambda: plane.sync(current))
    assert inj.injected["corrupt"] == 1
    _assert_state_equal(merged, dense(current))
    assert obs_counters.snapshot()["faults"].get("sync_retries", 0) == 1


@pytest.mark.chaos
def test_chaos_exhaustion_degrade_then_recover(eight_devices):
    plane, state, mesh = _plane(
        eight_devices,
        False,
        guard=SyncGuard(max_retries=1, backoff_s=0.0, policy="degrade"),
    )
    dense = _dense_fn(mesh, "dp", REDUCTIONS)
    current = _touch(state, [6, 21])
    with faults.chaos(
        faults.FaultSpec(kind="drop", call=0, times=5, site="sparse_sync")
    ):
        local = _within(lambda: plane.sync(current))
    # degraded round: local-only view, NOTHING committed
    _assert_state_equal(local, current)
    fc = obs_counters.snapshot()["faults"]
    assert fc.get("degraded_computes", 0) == 1
    assert fc.get("sync_deadline_exceeded", 0) == 1
    # baseline/merged were untouched, so a clean round re-offers the deltas
    merged = _within(lambda: plane.sync(current))
    _assert_state_equal(merged, dense(current))


@pytest.mark.chaos
def test_chaos_exhaustion_raise(eight_devices):
    plane, state, _ = _plane(
        eight_devices,
        False,
        guard=SyncGuard(max_retries=1, backoff_s=0.0, policy="raise"),
    )
    current = _touch(state, [5])
    with faults.chaos(
        faults.FaultSpec(kind="drop", call=0, times=5, site="sparse_sync")
    ):
        with pytest.raises(SyncTimeoutError):
            _within(lambda: plane.sync(current))


# -------------------------------------------------------- hierarchy routing
def test_auto_hierarchy_stages_ici_and_dcn(eight_devices):
    obs_counters.enable()
    plane, state, _ = _plane(eight_devices, True)
    plane.sync(_touch(state, [7, 23]))
    crossings = obs_counters.snapshot()["calls_by_crossing"]
    # the ("dcn", "ici") tuple axis auto-derives the two-stage plane: every
    # staged collective is attributed to a REAL crossing, never "world"
    assert crossings.get("ici", 0) > 0 and crossings.get("dcn", 0) > 0
    assert crossings.get("world", 0) == 0


def test_hierarchy_false_pins_flat_world_crossing(eight_devices):
    obs_counters.enable()
    mesh = _mesh(eight_devices, True)
    plane = SparseSyncPlane(
        _reset_state(), REDUCTIONS, N, ("dcn", "ici"), mesh, capacity=CAP, hierarchy=False
    )
    merged = plane.sync(_touch(_reset_state(), [7, 23]))
    crossings = obs_counters.snapshot()["calls_by_crossing"]
    assert crossings.get("world", 0) > 0
    assert crossings.get("ici", 0) == 0 and crossings.get("dcn", 0) == 0
    # flat and auto-derived two-stage fold to the SAME merged view
    obs_counters.disable()
    two_stage = SparseSyncPlane(
        _reset_state(), REDUCTIONS, N, MeshHierarchy(ici_axis="ici", dcn_axis="dcn"),
        mesh, capacity=CAP,
    )
    _assert_state_equal(merged, two_stage.sync(_touch(_reset_state(), [7, 23])))


# ----------------------------------------------------------- wrapper planes
def test_keyed_sparse_plane_bit_exact(eight_devices):
    mesh = _mesh(eight_devices, False)
    metric = Keyed(AUROC(approx="sketch", num_bins=8), num_slots=64)
    plane = metric.sparse_plane("dp", mesh, capacity=16)
    rng = np.random.RandomState(0)
    slots = jnp.asarray(rng.choice(64, 12, replace=False)[rng.randint(0, 12, 40)], jnp.int32)
    metric.update(
        jnp.asarray(rng.rand(40).astype(np.float32)),
        jnp.asarray((rng.rand(40) > 0.5).astype(np.int32)),
        slot=slots,
    )
    current = metric._current_state()
    dense = _dense_fn(mesh, "dp", dict(metric._reductions))
    merged = plane.sync(current, touched=slab_touched_mask(slots, 64))
    _assert_state_equal(merged, dense(current))
    assert plane.fallbacks == 0


def test_heavy_hitters_sparse_plane_routes_tail_dense(eight_devices):
    mesh = _mesh(eight_devices, False)
    metric = HeavyHitters(Accuracy(), num_hot_slots=8)
    plane = metric.sparse_plane("dp", mesh, capacity=8)
    rng = np.random.RandomState(1)
    keys = [f"seg{i % 20}" for i in range(60)]
    metric.update(
        jnp.asarray(rng.rand(60).astype(np.float32)),
        jnp.asarray(rng.randint(0, 2, 60).astype(np.int32)),
        key=keys,
    )
    # the count-min tail is NOT row-shaped: it must delta-sync as a dense
    # residual on the bitmap payload, not ride the row exchange
    assert plane._dense_names
    current = metric._current_state()
    dense = _dense_fn(mesh, "dp", dict(metric._reductions))
    _assert_state_equal(plane.sync(current), dense(current))


def test_windowed_sparse_plane_bit_exact(eight_devices):
    mesh = _mesh(eight_devices, False)
    metric = Windowed(Accuracy(), window_s=10.0, num_windows=4)
    plane = metric.sparse_plane("dp", mesh)
    assert plane.capacity == 4  # defaults to the window count: never overflows
    rng = np.random.RandomState(2)
    metric.update(
        jnp.asarray(rng.rand(30).astype(np.float32)),
        jnp.asarray(rng.randint(0, 2, 30).astype(np.int32)),
        event_time=jnp.asarray(rng.uniform(0.0, 25.0, 30).astype(np.float32)),
    )
    current = metric._current_state()
    dense = _dense_fn(mesh, "dp", dict(metric._reductions))
    _assert_state_equal(plane.sync(current), dense(current))


# ------------------------------------------------------------ deferred hook
def test_sync_deferred_resolves_merged_view(eight_devices):
    plane, state, mesh = _plane(eight_devices, False)
    dense = _dense_fn(mesh, "dp", REDUCTIONS)
    current = _touch(state, [13, 14])
    handle = plane.sync_deferred(current, watermark=7)
    assert handle.label == "sparse_sync" and handle.watermark == 7
    merged = _within(handle.result)
    _assert_state_equal(merged, dense(current))
    _assert_state_equal(plane.merged, merged)


# ------------------------------------------------------------- construction
def test_constructor_validation(eight_devices):
    mesh = _mesh(eight_devices, False)
    state = _reset_state()
    with pytest.raises(ValueError, match="num_rows"):
        SparseSyncPlane(state, REDUCTIONS, 0, "dp", mesh)
    with pytest.raises(ValueError, match="sparse_capacity"):
        SparseSyncPlane(state, REDUCTIONS, N, "dp", mesh, capacity=0)
    with pytest.raises(ValueError, match="at least one state leaf"):
        SparseSyncPlane({}, {}, N, "dp", mesh)
    with pytest.raises(ValueError, match="row slab"):
        SparseSyncPlane(
            {"tail": state["tail"]}, {"tail": "sum"}, N, "dp", mesh
        )
    with pytest.raises(ValueError, match="slab reductions"):
        SparseSyncPlane(state, {**REDUCTIONS, "hits": "mean"}, N, "dp", mesh)
    with pytest.raises(ValueError, match="residual"):
        SparseSyncPlane(
            state, {**REDUCTIONS, "tail": "min"}, N, "dp", mesh,
            row_leaves=("hits", "lo", "hi"),
        )
    with pytest.raises(ValueError, match="mesh"):
        SparseSyncPlane(state, REDUCTIONS, N, "dp", None)


def test_counters_sparse_ledger_shape():
    obs_counters.reset()
    obs_counters.COUNTERS.record_sparse_round(5)
    obs_counters.COUNTERS.record_sparse_fallback()
    obs_counters.COUNTERS.record_sparse_skip()
    assert obs_counters.snapshot()["sparse"] == {
        "syncs": 1, "rows": 5, "fallbacks": 1, "skips": 1,
    }
