"""Real multi-process host-plane sync.

Spawns a genuine 2-process ``jax.distributed`` CPU world (the TPU build's
analogue of the reference's 2-process Gloo group, reference
tests/bases/test_ddp.py:26-87) and drives the production
``gather_all_arrays`` / ``process_allgather`` path — the code a multi-host
deployment takes — end to end through ``Metric.compute()``.
"""
import json
import os
import subprocess
import sys

import jax
import pytest

# jax 0.4.x cannot run cross-process collectives on the CPU backend
# ("Multiprocess computations aren't implemented on the CPU backend"); the
# shard_map compat shim recovers everything else on old jax, but these two
# tests need a jax whose CPU client speaks the distributed protocol.
pytestmark = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="multiprocess CPU collectives unsupported on this jax",
)

_WORKER = r"""
import json
import os
import sys

rank = int(sys.argv[1])
port = sys.argv[2]

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ.pop("METRICS_TPU_TEST_PLATFORM", None)
import jax

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=rank
)
assert jax.process_count() == 2

import jax.numpy as jnp
from metrics_tpu import Metric


class Sum(Metric):

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("x", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, x):
        self.x = self.x + x

    def compute(self):
        return self.x


class Cat(Metric):

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("vals", [], dist_reduce_fx="cat")

    def update(self, x):
        self._append("vals", x)

    def compute(self):
        v = self.vals
        return v if not isinstance(v, list) else jnp.concatenate([jnp.atleast_1d(t) for t in v])


class Stack(Metric):

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("x", jnp.asarray(0.0), dist_reduce_fx=None)

    def update(self, x):
        self.x = self.x + x

    def compute(self):
        return self.x


# sum: ranks hold 1.0 and 2.0 -> both compute 3.0; local state restored after
s = Sum()
s.update(float(rank + 1))
total = float(s.compute())
local_after = float(s.x)

# cat: rank r appends [2r, 2r+1] -> union {0, 1, 2, 3} on both ranks
c = Cat()
c.update(jnp.asarray([2.0 * rank, 2.0 * rank + 1.0]))
cat_vals = sorted(float(v) for v in c.compute())

# None-reduction: states stack to (world,) in rank order
st = Stack()
st.update(float(10 + rank))
st._sync_dist()
stacked = [float(v) for v in st.x]


class MinMax(Metric):

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("mn", jnp.asarray(jnp.inf), dist_reduce_fx="min")
        self.add_state("mx", jnp.asarray(-jnp.inf), dist_reduce_fx="max")

    def update(self, x):
        self.mn = jnp.minimum(self.mn, x)
        self.mx = jnp.maximum(self.mx, x)

    def compute(self):
        return self.mn, self.mx


# min/max host reductions across the real 2-process world
mm = MinMax()
mm.update(float(5 - rank))   # ranks hold 5.0 and 4.0
mm.update(float(rank))       # and 0.0 / 1.0
mn, mx = mm.compute()
minmax = [float(mn), float(mx)]


class Buffered(Metric):
    # PaddedBuffer cat-state -> the host-plane buffer gather branch

    def __init__(self, **kw):
        super().__init__(capacity=4, **kw)
        self.add_state("vals", [], dist_reduce_fx=None, item_shape=())

    def update(self, x):
        self._append("vals", x)

    def compute(self):
        from metrics_tpu.parallel.buffer import as_values

        return as_values(self.vals)


# each rank appends 3 of its 4-capacity rows -> union of 6, no overflow
b = Buffered()
b.update(jnp.asarray([10.0 * rank, 10.0 * rank + 1.0, 10.0 * rank + 2.0]))
buf_vals = sorted(float(v) for v in b.compute())
buf_local_count = int(b.vals.count)  # local state restored after sync

# overflow on ONE rank must raise on EVERY rank (counts are gathered first)
b2 = Buffered()
b2.update(jnp.zeros((4,)))
if rank == 1:
    b2.vals = b2.vals._replace(count=jnp.asarray(5, dtype=b2.vals.count.dtype))
try:
    b2.compute()
    overflow = "no-error"
except RuntimeError as err:
    overflow = "overflow" if "overflow" in str(err) else f"wrong: {err}"

# process_group scoping: a group of 1 syncs only itself...
g1 = Sum(process_group=[rank])
g1.update(float(rank + 1))
group_self = float(g1.compute())
# ...and the full group equals the world sync
g2 = Sum(process_group=[0, 1])
g2.update(float(rank + 1))
group_world = float(g2.compute())
# a group not containing this rank raises loudly
try:
    Sum(process_group=[1 - rank])
    group_error = "no-error"
except ValueError as err:
    group_error = "member" if "member" in str(err) else f"wrong: {err}"

print("RESULT " + json.dumps({
    "rank": rank,
    "sum": total,
    "local_after": local_after,
    "cat": cat_vals,
    "stacked": stacked,
    "minmax": minmax,
    "buf": buf_vals,
    "buf_local_count": buf_local_count,
    "overflow": overflow,
    "group_self": group_self,
    "group_world": group_world,
    "group_error": group_error,
}), flush=True)
"""


_RING_WORKER = r"""
import json
import os
import sys

rank = int(sys.argv[1])
port = sys.argv[2]

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.pop("METRICS_TPU_TEST_PLATFORM", None)
import jax

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=rank
)
assert jax.process_count() == 2 and len(jax.devices()) == 8

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metrics_tpu import AUROC
from metrics_tpu.parallel import row_sharded, sharded_auroc
from metrics_tpu.utils import compat

# a GLOBAL mesh: 8 devices spanning both processes (4 local each). The ring's
# ppermute hops cross the process boundary — the DCN plane of a real pod.
mesh = Mesh(np.array(jax.devices()), ("dp",))

N = 512
rng = np.random.RandomState(97)  # same stream on both ranks
scores = np.round(rng.rand(N), 1).astype(np.float32)  # cross-shard ties
labels = (rng.rand(N) > 0.5).astype(np.int32)

# ---- raw ring engine over the multi-process mesh
sharding = NamedSharding(mesh, P("dp"))
half = N // 2
arr_s = jax.make_array_from_process_local_data(sharding, scores[rank * half:(rank + 1) * half], (N,))
arr_l = jax.make_array_from_process_local_data(sharding, labels[rank * half:(rank + 1) * half], (N,))
ring = jax.jit(compat.shard_map(
    lambda s, t: sharded_auroc(s, t, "dp"), mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P()
))
ring_auroc = float(ring(arr_s, arr_l))

# ---- the STATEFUL front door across processes: row-sharded buffer states,
# replicated batch inputs, compute() dispatches the ring (host-plane gather
# suppressed because the mesh spans every process)
metric = AUROC(pos_label=1, capacity=N)
metric.device_put(row_sharded(mesh, "dp"))
replicated = NamedSharding(mesh, P())
for start in (0, half):
    batch_s = jax.make_array_from_process_local_data(replicated, scores[start:start + half], (half,))
    batch_l = jax.make_array_from_process_local_data(replicated, labels[start:start + half], (half,))
    metric.update(batch_s, batch_l)
assert metric.preds.data.sharding.spec[0] == "dp"
stateful_auroc = float(metric.compute())

# ---- rank correlation through the same front door: row-sharded cat states,
# compute() dispatches the rank-statistics ring (Spearman) and the split
# O(N^2) contraction ring (Kendall) across the process boundary
from metrics_tpu import KendallRankCorrCoef, SpearmanCorrcoef

targets = np.round(scores + 0.3 * rng.randn(N), 1).astype(np.float32)
rank_corr = {}
for name, cls in (("spearman", SpearmanCorrcoef), ("kendall", KendallRankCorrCoef)):
    m = cls(capacity=N)
    m.device_put(row_sharded(mesh, "dp"))
    for start in (0, half):
        bp = jax.make_array_from_process_local_data(replicated, scores[start:start + half], (half,))
        bt = jax.make_array_from_process_local_data(replicated, targets[start:start + half], (half,))
        m.update(bp, bt)
    assert m.preds_all.data.sharding.spec[0] == "dp"
    rank_corr[name] = float(m.compute())

import scipy.stats as st
from sklearn.metrics import roc_auc_score

want = float(roc_auc_score(labels, scores))
print("RESULT " + json.dumps({
    "rank": rank, "ring": ring_auroc, "stateful": stateful_auroc, "want": want,
    "spearman": rank_corr["spearman"],
    "want_spearman": float(st.spearmanr(scores, targets).statistic),
    "kendall": rank_corr["kendall"],
    "want_kendall": float(st.kendalltau(scores, targets).statistic),
}), flush=True)
"""


def test_two_process_sharded_epoch_ring(tmp_path):
    """The ring engine (raw AND through the stateful API) over a mesh whose
    collectives cross a real process boundary — the DCN plane, beyond
    single-process virtual devices."""
    results = _run_workers(tmp_path, _RING_WORKER, port="19741")
    for rank, r in results.items():
        assert abs(r["ring"] - r["want"]) < 1e-6, r
        assert abs(r["stateful"] - r["want"]) < 1e-6, r
        # rank-correlation rings across the same process boundary
        assert abs(r["spearman"] - r["want_spearman"]) < 1e-5, r
        assert abs(r["kendall"] - r["want_kendall"]) < 1e-5, r


def _run_workers(tmp_path, source, port):
    worker = tmp_path / "worker.py"
    worker.write_text(source)

    env = {**os.environ}
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = os.getcwd()

    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(rank), port],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for rank in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=150)
        assert p.returncode == 0, f"worker failed:\nstdout={out}\nstderr={err}"
        outs.append(out)

    results = {}
    for out in outs:
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")][-1]
        r = json.loads(line[len("RESULT "):])
        results[r["rank"]] = r
    assert set(results) == {0, 1}
    return results


def test_two_process_host_plane_sync(tmp_path):
    results = _run_workers(tmp_path, _WORKER, port="19733")
    for rank, r in results.items():
        # sum state reduced across both processes (reference test_ddp.py:26-42)
        assert r["sum"] == 3.0
        # local accumulation preserved after the synced compute
        assert r["local_after"] == float(rank + 1)
        # cat state gathered + concatenated (reference test_ddp.py:44-61)
        assert r["cat"] == [0.0, 1.0, 2.0, 3.0]
        # None-reduction stacks per-rank states in rank order
        assert r["stacked"] == [10.0, 11.0]
        # min/max reduce across the world: min(0,1)=0, max(5,4)=5
        assert r["minmax"] == [0.0, 5.0]
        # PaddedBuffer branch: union of both ranks' valid rows, no padding rows
        assert r["buf"] == [0.0, 1.0, 2.0, 10.0, 11.0, 12.0]
        # local buffer state restored after the synced compute
        assert r["buf_local_count"] == 3
        # rank-1's overflowed buffer raises on BOTH ranks
        assert r["overflow"] == "overflow"
        # a group of one syncs only itself: rank r keeps its own r+1
        assert r["group_self"] == float(rank + 1)
        # the full group behaves like the world sync
        assert r["group_world"] == 3.0
        # a group excluding the local rank is a loud error
        assert r["group_error"] == "member"
