"""Real multi-process host-plane sync.

Spawns a genuine 2-process ``jax.distributed`` CPU world (the TPU build's
analogue of the reference's 2-process Gloo group, reference
tests/bases/test_ddp.py:26-87) and drives the production
``gather_all_arrays`` / ``process_allgather`` path — the code a multi-host
deployment takes — end to end through ``Metric.compute()``.
"""
import json
import os
import subprocess
import sys

_WORKER = r"""
import json
import os
import sys

rank = int(sys.argv[1])
port = sys.argv[2]

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ.pop("METRICS_TPU_TEST_PLATFORM", None)
import jax

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=rank
)
assert jax.process_count() == 2

import jax.numpy as jnp
from metrics_tpu import Metric


class Sum(Metric):

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("x", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, x):
        self.x = self.x + x

    def compute(self):
        return self.x


class Cat(Metric):

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("vals", [], dist_reduce_fx="cat")

    def update(self, x):
        self._append("vals", x)

    def compute(self):
        v = self.vals
        return v if not isinstance(v, list) else jnp.concatenate([jnp.atleast_1d(t) for t in v])


class Stack(Metric):

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("x", jnp.asarray(0.0), dist_reduce_fx=None)

    def update(self, x):
        self.x = self.x + x

    def compute(self):
        return self.x


# sum: ranks hold 1.0 and 2.0 -> both compute 3.0; local state restored after
s = Sum()
s.update(float(rank + 1))
total = float(s.compute())
local_after = float(s.x)

# cat: rank r appends [2r, 2r+1] -> union {0, 1, 2, 3} on both ranks
c = Cat()
c.update(jnp.asarray([2.0 * rank, 2.0 * rank + 1.0]))
cat_vals = sorted(float(v) for v in c.compute())

# None-reduction: states stack to (world,) in rank order
st = Stack()
st.update(float(10 + rank))
st._sync_dist()
stacked = [float(v) for v in st.x]

print("RESULT " + json.dumps({
    "rank": rank,
    "sum": total,
    "local_after": local_after,
    "cat": cat_vals,
    "stacked": stacked,
}), flush=True)
"""


def test_two_process_host_plane_sync(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    port = "19733"

    env = {**os.environ}
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = os.getcwd()

    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(rank), port],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for rank in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=150)
        assert p.returncode == 0, f"worker failed:\nstdout={out}\nstderr={err}"
        outs.append(out)

    results = {}
    for out in outs:
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")][-1]
        r = json.loads(line[len("RESULT "):])
        results[r["rank"]] = r

    assert set(results) == {0, 1}
    for rank, r in results.items():
        # sum state reduced across both processes (reference test_ddp.py:26-42)
        assert r["sum"] == 3.0
        # local accumulation preserved after the synced compute
        assert r["local_after"] == float(rank + 1)
        # cat state gathered + concatenated (reference test_ddp.py:44-61)
        assert r["cat"] == [0.0, 1.0, 2.0, 3.0]
        # None-reduction stacks per-rank states in rank order
        assert r["stacked"] == [10.0, 11.0]
