"""Complete in-jit sync matrix + sharded-state compute.

Covers every branch of ``sync_value`` (metrics_tpu/parallel/sync.py) under
``shard_map`` with real XLA collectives on 8 fake CPU devices, plus metrics
whose states are actually sharded over the mesh via ``NamedSharding`` — the
BASELINE.json north star ("MetricCollection place states on the TPU mesh"),
demonstrated by computing correct results, not just asserting placement.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from sklearn.metrics import confusion_matrix as sk_confusion_matrix
from sklearn.metrics import precision_score as sk_precision_score
from sklearn.metrics import roc_auc_score as sk_roc_auc_score

from metrics_tpu import ConfusionMatrix, Metric, MetricCollection, Precision, PSNR
from metrics_tpu.functional.regression.psnr import psnr as functional_psnr
from metrics_tpu.utils import compat


class _EveryReduction(Metric):
    """One state per reduction kind, to drive every sync_value branch at once."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("s", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("m", jnp.asarray(0.0), dist_reduce_fx="mean")
        self.add_state("mn", jnp.asarray(jnp.inf), dist_reduce_fx="min")
        self.add_state("mx", jnp.asarray(-jnp.inf), dist_reduce_fx="max")
        self.add_state("stacked", jnp.zeros((2,)), dist_reduce_fx=None)

    def update(self, x):
        self.s = self.s + x
        self.m = self.m + x
        self.mn = jnp.minimum(self.mn, x)
        self.mx = jnp.maximum(self.mx, x)
        self.stacked = self.stacked + jnp.stack([x, 2 * x])

    def compute(self):
        return self.s, self.m, self.mn, self.mx, self.stacked


def test_sync_value_all_reductions_shard_map(eight_devices):
    """sum/mean/min/max/None-gather all sync correctly under shard_map."""
    pure = _EveryReduction().pure()
    mesh = Mesh(np.array(eight_devices), ("dp",))

    def fn(x):
        state = pure.update(pure.init(), x[0])
        state = pure.sync(state, "dp")
        return pure.compute(state)

    # all_gather outputs are replicated, but the static vma checker cannot
    # infer that for the None-reduction stacked state
    f = compat.shard_map(fn, mesh=mesh, in_specs=(P("dp"),), out_specs=P(), check_vma=False)
    s, m, mn, mx, stacked = f(jnp.arange(8, dtype=jnp.float32))
    assert float(s) == 28.0  # psum
    assert float(m) == 3.5  # pmean
    assert float(mn) == 0.0  # pmin
    assert float(mx) == 7.0  # pmax
    # None-reduction: all_gather stacks to (world, ...) like the reference
    assert stacked.shape == (8, 2)
    np.testing.assert_allclose(np.asarray(stacked)[:, 0], np.arange(8))
    np.testing.assert_allclose(np.asarray(stacked)[:, 1], 2 * np.arange(8))


def test_sync_callable_reduction_shard_map(eight_devices):
    """A callable dist_reduce_fx is applied to the (world, ...) gathered stack."""

    class CallableRed(Metric):

        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state("x", jnp.asarray(0.0), dist_reduce_fx=lambda t: jnp.max(t, axis=0) - jnp.min(t, axis=0))

        def update(self, x):
            self.x = self.x + x

        def compute(self):
            return self.x

    pure = CallableRed().pure()
    mesh = Mesh(np.array(eight_devices), ("dp",))

    def fn(x):
        state = pure.update(pure.init(), x[0])
        state = pure.sync(state, "dp")
        return pure.compute(state)

    f = compat.shard_map(fn, mesh=mesh, in_specs=(P("dp"),), out_specs=P(), check_vma=False)
    out = f(jnp.arange(8, dtype=jnp.float32))
    assert float(out) == 7.0  # max - min over ranks


def test_psnr_data_range_none_sharded(eight_devices):
    """PSNR with data_range=None end-to-end over the mesh: its min/max states
    ride pmin/pmax (reference regression/psnr.py:102-103) and the synced
    result matches single-device PSNR on the full data."""
    rng = np.random.RandomState(3)
    preds_np = rng.rand(8, 16).astype(np.float32) * 5.0
    target_np = rng.rand(8, 16).astype(np.float32) * 5.0

    pure = PSNR().pure()
    mesh = Mesh(np.array(eight_devices), ("dp",))

    def fn(p, t):
        state = pure.update(pure.init(), p, t)
        state = pure.sync(state, "dp")
        return pure.compute(state)

    f = compat.shard_map(fn, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P())
    sharded = f(jnp.asarray(preds_np), jnp.asarray(target_np))

    # the min/max states initialize at 0 (reference parity), so the inferred
    # range is max(target) - min(0, min(target))
    data_range = float(target_np.max() - min(target_np.min(), 0.0))
    expected = functional_psnr(
        jnp.asarray(preds_np), jnp.asarray(target_np), data_range=data_range
    )
    np.testing.assert_allclose(float(sharded), float(expected), rtol=1e-6)

    # the min/max states really were reduced with pmin/pmax, not summed:
    # replicate and check the synced state directly
    def synced_state(p, t):
        state = pure.update(pure.init(), p, t)
        return pure.sync(state, "dp")

    state = compat.shard_map(
        synced_state, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P()
    )(jnp.asarray(preds_np), jnp.asarray(target_np))
    # states initialize at 0, so the tracked extrema are clamped through 0
    assert float(state["min_target"]) == pytest.approx(min(float(target_np.min()), 0.0))
    assert float(state["max_target"]) == pytest.approx(max(float(target_np.max()), 0.0))


def test_curve_metric_capacity_gather_shard_map(eight_devices):
    """An exact curve metric with bounded buffers syncs through
    buffer_all_gather at module level and matches sklearn on the union."""

    class BufferedScores(Metric):
        """Cat-state preds/target as PaddedBuffers (capacity set)."""

        def __init__(self, **kw):
            super().__init__(capacity=64, **kw)
            self.add_state("preds", [], dist_reduce_fx=None, item_shape=(), item_dtype=jnp.float32)
            self.add_state("tgt", [], dist_reduce_fx=None, item_shape=(), item_dtype=jnp.int32)

        def update(self, p, t):
            self._append("preds", p)
            self._append("tgt", t)

        def compute(self):
            return self.preds, self.tgt

    rng = np.random.RandomState(7)
    preds_np = rng.rand(64).astype(np.float32)
    target_np = (rng.rand(64) > 0.5).astype(np.int32)

    pure = BufferedScores().pure()
    mesh = Mesh(np.array(eight_devices), ("dp",))

    def fn(p, t):
        state = pure.update(pure.init(), p, t)
        state = pure.sync(state, "dp")  # PaddedBuffer -> buffer_all_gather
        return state["preds"].data, state["preds"].count, state["tgt"].data, state["tgt"].count

    f = compat.shard_map(
        fn, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=(P(), P(), P(), P()),
        check_vma=False,  # gather+compaction defeats static replication inference
    )
    p_data, p_count, t_data, t_count = f(jnp.asarray(preds_np), jnp.asarray(target_np))
    assert int(p_count) == 64 and int(t_count) == 64

    # the gathered union reproduces the sklearn AUROC of the full data
    # (gather order is device order; AUROC is permutation-invariant)
    auc = sk_roc_auc_score(np.asarray(t_data)[:64], np.asarray(p_data)[:64])
    assert auc == pytest.approx(sk_roc_auc_score(target_np, preds_np))


# ---------------------------------------------------------- sharded states


def test_precision_sharded_class_states_compute(eight_devices):
    """(C,) stat-score states sharded over the mesh still compute the sklearn
    answer, with updates running jitted."""
    num_classes = 8
    mesh = Mesh(np.array(eight_devices), ("model",))
    sharding = NamedSharding(mesh, P("model"))

    metric = Precision(num_classes=num_classes, average="macro")
    metric.device_put(sharding)

    rng = np.random.RandomState(11)
    all_p, all_t = [], []
    for _ in range(4):
        p = rng.randint(0, num_classes, 128).astype(np.int32)
        t = rng.randint(0, num_classes, 128).astype(np.int32)
        metric.update(jnp.asarray(p), jnp.asarray(t))
        all_p.append(p)
        all_t.append(t)

    # states are actually sharded over the mesh
    assert metric.tp.sharding == sharding
    assert metric.tp.shape == (num_classes,)

    expected = sk_precision_score(
        np.concatenate(all_t), np.concatenate(all_p), average="macro", zero_division=0
    )
    np.testing.assert_allclose(float(metric.compute()), expected, atol=1e-6)


def test_confusion_matrix_sharded_state_compute(eight_devices):
    """(C, C) confusion-matrix state sharded row-wise over the mesh computes
    the sklearn confusion matrix."""
    num_classes = 8
    mesh = Mesh(np.array(eight_devices), ("model",))
    sharding = NamedSharding(mesh, P("model", None))

    metric = ConfusionMatrix(num_classes=num_classes)
    metric.device_put(sharding)

    rng = np.random.RandomState(13)
    all_p, all_t = [], []
    for _ in range(3):
        p = rng.randint(0, num_classes, 256).astype(np.int32)
        t = rng.randint(0, num_classes, 256).astype(np.int32)
        metric.update(jnp.asarray(p), jnp.asarray(t))
        all_p.append(p)
        all_t.append(t)

    assert metric.confmat.sharding == sharding
    result = np.asarray(metric.compute())
    expected = sk_confusion_matrix(np.concatenate(all_t), np.concatenate(all_p), labels=list(range(num_classes)))
    np.testing.assert_allclose(result, expected)


def test_collection_sharded_states_compute(eight_devices):
    """MetricCollection with states placed on the mesh computes correctly and
    reset preserves the placement (north-star flow end to end)."""
    num_classes = 8
    mesh = Mesh(np.array(eight_devices), ("model",))
    sharding = NamedSharding(mesh, P("model"))

    collection = MetricCollection([
        Precision(num_classes=num_classes, average="macro"),
        ConfusionMatrix(num_classes=num_classes),
    ])
    collection.device_put(sharding)

    rng = np.random.RandomState(17)
    p = rng.randint(0, num_classes, 512).astype(np.int32)
    t = rng.randint(0, num_classes, 512).astype(np.int32)
    collection.update(jnp.asarray(p), jnp.asarray(t))

    out = collection.compute()
    np.testing.assert_allclose(
        float(out["Precision"]),
        sk_precision_score(t, p, average="macro", zero_division=0),
        atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(out["ConfusionMatrix"]),
        sk_confusion_matrix(t, p, labels=list(range(num_classes))),
    )

    collection.reset()
    prec = collection["Precision"]
    assert prec.tp.sharding == sharding  # placement survives reset
