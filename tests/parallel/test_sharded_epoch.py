"""Sharded epoch compute: exact AUROC / AP / RetrievalMAP with O(N/n) memory.

Every test keeps the epoch sharded over 8 devices through compute() and
checks the result against sklearn / the single-device engine on the
concatenated data — including cross-shard score ties, sample weights,
skewed query routing, and bucket overflow detection.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from sklearn.metrics import average_precision_score as sk_average_precision
from sklearn.metrics import roc_auc_score as sk_roc_auc_score

from metrics_tpu.parallel import (
    regroup_by_query,
    sharded_auroc,
    sharded_auroc_matrix,
    sharded_average_precision,
    sharded_average_precision_matrix,
    sharded_retrieval_sums,
)
from metrics_tpu.utils import compat

N = 1024  # global epoch rows; 128 per device


@pytest.fixture()
def mesh(eight_devices):
    return Mesh(np.array(eight_devices), ("dp",))


def _shard_map(mesh, fn, n_in, out_specs=P()):
    # check_vma deliberately LEFT ON (the default): the ring/regroup
    # collectives satisfy JAX's varying-manual-axes verification
    return jax.jit(
        compat.shard_map(fn, mesh=mesh, in_specs=(P("dp"),) * n_in, out_specs=out_specs)
    )


@pytest.mark.parametrize("ties", [False, True])
def test_sharded_auroc_exact(mesh, ties):
    rng = np.random.RandomState(41)
    preds = rng.rand(N).astype(np.float32)
    if ties:
        preds = np.round(preds, 1)  # heavy cross-shard ties
    target = (rng.rand(N) > 0.6).astype(np.int32)

    f = _shard_map(mesh, lambda p, t: sharded_auroc(p, t, "dp"), 2)
    got = float(f(jnp.asarray(preds), jnp.asarray(target)))
    np.testing.assert_allclose(got, sk_roc_auc_score(target, preds), atol=1e-6)


def test_sharded_auroc_weighted_and_degenerate(mesh):
    rng = np.random.RandomState(43)
    preds = np.round(rng.rand(N), 2).astype(np.float32)
    target = (rng.rand(N) > 0.5).astype(np.int32)
    weights = rng.rand(N).astype(np.float32)

    f = _shard_map(mesh, lambda p, t, w: sharded_auroc(p, t, "dp", w), 3)
    got = float(f(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(weights)))
    np.testing.assert_allclose(
        got, sk_roc_auc_score(target, preds, sample_weight=weights), rtol=1e-5
    )

    # zero-weight rows are fully neutral (the padding story)
    w2 = weights.copy()
    w2[::3] = 0.0
    got2 = float(f(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(w2)))
    keep = w2 > 0
    np.testing.assert_allclose(
        got2, sk_roc_auc_score(target[keep], preds[keep], sample_weight=w2[keep]), rtol=1e-5
    )

    # single-class epoch -> nan, matching binary_auroc_static
    ones = np.ones(N, dtype=np.int32)
    assert np.isnan(float(f(jnp.asarray(preds), jnp.asarray(ones), jnp.asarray(weights))))


@pytest.mark.parametrize("ties", [False, True])
def test_sharded_average_precision_exact(mesh, ties):
    rng = np.random.RandomState(47)
    preds = rng.rand(N).astype(np.float32)
    if ties:
        preds = np.round(preds, 1)
    target = (rng.rand(N) > 0.7).astype(np.int32)

    f = _shard_map(mesh, lambda p, t: sharded_average_precision(p, t, "dp"), 2)
    got = float(f(jnp.asarray(preds), jnp.asarray(target)))
    np.testing.assert_allclose(got, sk_average_precision(target, preds), atol=1e-6)

    # agreement with the package's own static kernel on the same data
    from metrics_tpu.functional.classification.curve_static import binary_average_precision_static

    np.testing.assert_allclose(
        got, float(binary_average_precision_static(jnp.asarray(preds), jnp.asarray(target))), atol=1e-6
    )


@pytest.mark.parametrize("ties", [False, True])
def test_sharded_auroc_matrix_per_class_exact(mesh, ties):
    """Matrix mode: per-class one-vs-rest scores vs sklearn on 8 shards."""
    rng = np.random.RandomState(67)
    C = 6
    preds = rng.rand(N, C).astype(np.float32)
    if ties:
        preds = np.round(preds, 1)
    labels = rng.randint(0, C, N)
    onehot = (labels[:, None] == np.arange(C)).astype(np.int32)

    f = _shard_map(mesh, lambda p, t: sharded_auroc_matrix(p, t, "dp"), 2)
    got = np.asarray(f(jnp.asarray(preds), jnp.asarray(onehot)))
    want = [sk_roc_auc_score(onehot[:, c], preds[:, c]) for c in range(C)]
    np.testing.assert_allclose(got, want, atol=1e-5)

    # per-row weights broadcast over classes; zero weight neutralizes a row
    w = rng.rand(N).astype(np.float32)
    w[::5] = 0.0
    fw = _shard_map(mesh, lambda p, t, ww: sharded_auroc_matrix(p, t, "dp", ww), 3)
    gotw = np.asarray(fw(jnp.asarray(preds), jnp.asarray(onehot), jnp.asarray(w)))
    keep = w > 0
    wantw = [
        sk_roc_auc_score(onehot[keep, c], preds[keep, c], sample_weight=w[keep]) for c in range(C)
    ]
    np.testing.assert_allclose(gotw, wantw, rtol=1e-5)


def test_sharded_auroc_matrix_absent_class_nan(mesh):
    rng = np.random.RandomState(71)
    preds = rng.rand(N, 3).astype(np.float32)
    onehot = np.zeros((N, 3), dtype=np.int32)
    onehot[:, 0] = (rng.rand(N) > 0.5).astype(np.int32)  # class 1, 2 absent
    f = _shard_map(mesh, lambda p, t: sharded_auroc_matrix(p, t, "dp"), 2)
    got = np.asarray(f(jnp.asarray(preds), jnp.asarray(onehot)))
    assert not np.isnan(got[0]) and np.isnan(got[1]) and np.isnan(got[2])


def test_sharded_average_precision_matrix_exact(mesh):
    rng = np.random.RandomState(73)
    C = 4
    preds = np.round(rng.rand(N, C), 1).astype(np.float32)
    labels = rng.randint(0, C, N)
    onehot = (labels[:, None] == np.arange(C)).astype(np.int32)
    f = _shard_map(mesh, lambda p, t: sharded_average_precision_matrix(p, t, "dp"), 2)
    got = np.asarray(f(jnp.asarray(preds), jnp.asarray(onehot)))
    want = [sk_average_precision(onehot[:, c], preds[:, c]) for c in range(C)]
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_regroup_by_query_routes_and_pads(mesh):
    rng = np.random.RandomState(53)
    idx = rng.randint(0, 37, N).astype(np.int32)  # queries scattered across shards
    preds = rng.rand(N).astype(np.float32)
    target = (rng.rand(N) > 0.5).astype(np.int32)

    def fn(i, p, t):
        gi, gp, gt, pad, dropped = regroup_by_query(i, p, t, "dp")
        return gi, gp, gt, pad, dropped

    f = _shard_map(mesh, fn, 3, out_specs=(P("dp"), P("dp"), P("dp"), P("dp"), P()))
    gi, gp, gt, pad, dropped = f(jnp.asarray(idx), jnp.asarray(preds), jnp.asarray(target))
    assert int(dropped) == 0

    gi, gp, gt, pad = (np.asarray(x) for x in (gi, gp, gt, pad))
    real = ~pad
    # every real row survived, exactly once, with its (idx, pred, target) intact
    got_rows = sorted(zip(gi[real].tolist(), gp[real].tolist(), gt[real].tolist()))
    want_rows = sorted(zip(idx.tolist(), preds.tolist(), target.tolist()))
    assert got_rows == want_rows
    # each query's rows live on exactly the shard idx % 8 (row-block i is
    # shard i's regrouped output)
    per_shard = gi.reshape(8, -1)
    per_real = real.reshape(8, -1)
    for shard in range(8):
        ids = per_shard[shard][per_real[shard]]
        assert np.all(ids % 8 == shard)


def test_sharded_retrieval_map_exact(mesh):
    from metrics_tpu.retrieval import RetrievalMAP

    rng = np.random.RandomState(59)
    idx = rng.randint(0, 61, N).astype(np.int32)
    preds = rng.rand(N).astype(np.float32)
    target = (rng.rand(N) > 0.6).astype(np.int32)

    metric = RetrievalMAP()

    def fn(i, p, t):
        mean, flag, dropped = sharded_retrieval_sums(metric, i, p, t, "dp")
        return mean, flag, dropped

    f = _shard_map(mesh, fn, 3, out_specs=(P(), P(), P()))
    mean, flag, dropped = f(jnp.asarray(idx), jnp.asarray(preds), jnp.asarray(target))
    assert int(dropped) == 0

    oracle = RetrievalMAP()
    oracle.update(jnp.asarray(idx), jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_allclose(float(mean), float(oracle.compute()), atol=1e-6)


@pytest.mark.parametrize("policy", ["skip", "neg", "pos"])
def test_sharded_retrieval_policies_and_sentinels(mesh, policy):
    """Empty-query policies and exclude sentinels survive the regroup."""
    from metrics_tpu.retrieval import RetrievalMRR

    rng = np.random.RandomState(61)
    idx = rng.randint(0, 29, N).astype(np.int32)
    preds = rng.rand(N).astype(np.float32)
    target = (rng.rand(N) > 0.5).astype(np.int32)
    target[idx % 7 == 0] = 0  # force some all-negative queries
    # exclude sentinels — but not on the forced-empty queries: by reference
    # parity a raw -100 makes a query's raw target sum nonzero ("non-empty")
    sentinel_rows = (np.arange(N) % 11 == 0) & (idx % 7 != 0)
    target[sentinel_rows] = -100

    metric = RetrievalMRR(query_without_relevant_docs=policy)

    f = _shard_map(
        mesh,
        lambda i, p, t: sharded_retrieval_sums(metric, i, p, t, "dp"),
        3,
        out_specs=(P(), P(), P()),
    )
    mean, flag, dropped = f(jnp.asarray(idx), jnp.asarray(preds), jnp.asarray(target))
    assert int(dropped) == 0

    oracle = RetrievalMRR(query_without_relevant_docs=policy)
    oracle.update(jnp.asarray(idx), jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_allclose(float(mean), float(oracle.compute()), atol=1e-6)
    assert bool(flag)  # the all-negative queries are visible globally


def test_regroup_overflow_detected(mesh):
    """A skewed id distribution overflowing a bucket is COUNTED, not silent."""
    idx = np.zeros(N, dtype=np.int32)  # every row routes to shard 0
    preds = np.linspace(0, 1, N, dtype=np.float32)
    target = np.ones(N, dtype=np.int32)

    def fn(i, p, t):
        return regroup_by_query(i, p, t, "dp", capacity=8)[4]

    f = _shard_map(mesh, fn, 3, out_specs=P())
    dropped = int(f(jnp.asarray(idx), jnp.asarray(preds), jnp.asarray(target)))
    # 128 local rows per shard, capacity 8 per destination bucket
    assert dropped == 8 * (128 - 8)
