"""Sharded epoch compute through the STATEFUL API (the front door).

The reference gives every metric one distributed interface — ``compute()``
syncs transparently (reference torchmetrics/metric.py:179-197) but always by
materializing the gathered epoch per rank. Here the same interface, with the
states row-sharded over a mesh axis (``parallel.row_sharded``), dispatches the
exact ring / ``all_to_all`` engine instead: every test drives plain
``update()/compute()`` — no user ``shard_map`` — and asserts sklearn-exact
results while the gather path is POISONED (any epoch materialization fails
the test).
"""
import contextlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh
from sklearn.metrics import average_precision_score as sk_ap
from sklearn.metrics import roc_auc_score as sk_auroc

import metrics_tpu.parallel.buffer as buffer_mod
from metrics_tpu import (
    AUROC,
    ROC,
    AveragePrecision,
    KendallRankCorrCoef,
    PrecisionRecallCurve,
    SpearmanCorrcoef,
)
from metrics_tpu.parallel import row_sharded
from metrics_tpu.retrieval import RetrievalMAP, RetrievalMRR
from metrics_tpu.utils import compat


@pytest.fixture()
def mesh(eight_devices):
    return Mesh(np.array(eight_devices), ("dp",))


@contextlib.contextmanager
def no_materialization(monkeypatch):
    """Fail the test if compute() touches any full-epoch gather primitive."""

    def boom(*_a, **_k):
        raise AssertionError("sharded compute materialized the epoch")

    monkeypatch.setattr(buffer_mod, "buffer_values", boom)
    monkeypatch.setattr(buffer_mod, "buffer_all_gather", boom)
    yield


def _batches(rng, steps, batch, ties=True):
    for _ in range(steps):
        p = rng.rand(batch).astype(np.float32)
        if ties:
            p = np.round(p, 1)  # heavy cross-shard ties
        t = (rng.rand(batch) > 0.5).astype(np.int32)
        yield p, t


def test_stateful_sharded_binary_auroc(mesh, monkeypatch):
    rng = np.random.RandomState(11)
    metric = AUROC(pos_label=1, capacity=1024)
    metric.device_put(row_sharded(mesh, "dp"))

    all_p, all_t = [], []
    for p, t in _batches(rng, steps=8, batch=96):
        all_p.append(p)
        all_t.append(t)
        metric.update(jnp.asarray(p), jnp.asarray(t))

    # the epoch rows live sharded over dp (O(capacity/8) per device)
    assert metric.preds.data.sharding.spec[0] == "dp"
    with no_materialization(monkeypatch):
        got = float(metric.compute())
    want = sk_auroc(np.concatenate(all_t), np.concatenate(all_p))
    np.testing.assert_allclose(got, want, atol=1e-6)

    # reset preserves the sharded placement; a second epoch works
    metric.reset()
    p, t = next(_batches(rng, 1, 512))
    metric.update(jnp.asarray(p), jnp.asarray(t))
    assert metric.preds.data.sharding.spec[0] == "dp"
    with no_materialization(monkeypatch):
        np.testing.assert_allclose(float(metric.compute()), sk_auroc(t, p), atol=1e-6)


@pytest.mark.parametrize("average", ["macro", "weighted", None])
def test_stateful_sharded_multiclass_auroc(mesh, monkeypatch, average):
    rng = np.random.RandomState(13)
    num_classes = 5
    metric = AUROC(num_classes=num_classes, average=average, capacity=1024)
    metric.device_put(row_sharded(mesh, "dp"))

    ps, ts = [], []
    for _ in range(4):
        logits = rng.rand(128, num_classes).astype(np.float32)
        p = logits / logits.sum(-1, keepdims=True)
        t = rng.randint(0, num_classes, 128).astype(np.int32)
        ps.append(p)
        ts.append(t)
        metric.update(jnp.asarray(p), jnp.asarray(t))

    with no_materialization(monkeypatch):
        got = metric.compute()
    p, t = np.concatenate(ps), np.concatenate(ts)
    if average is None:
        want = [sk_auroc((t == c).astype(int), p[:, c]) for c in range(num_classes)]
        np.testing.assert_allclose([float(x) for x in got], want, atol=1e-5)
    else:
        want = sk_auroc(t, p, multi_class="ovr", average=average, labels=np.arange(num_classes))
        np.testing.assert_allclose(float(got), want, atol=1e-5)


@pytest.mark.parametrize("average", ["macro", "weighted", "micro"])
def test_stateful_sharded_multilabel_auroc(mesh, monkeypatch, average):
    rng = np.random.RandomState(17)
    metric = AUROC(num_classes=3, average=average, capacity=512)
    metric.device_put(row_sharded(mesh, "dp"))
    p = rng.rand(384, 3).astype(np.float32)
    t = (rng.rand(384, 3) > 0.5).astype(np.int32)
    metric.update(jnp.asarray(p), jnp.asarray(t))
    with no_materialization(monkeypatch):
        got = float(metric.compute())
    np.testing.assert_allclose(got, sk_auroc(t, p, average=average), atol=1e-5)


def test_stateful_sharded_average_precision(mesh, monkeypatch):
    rng = np.random.RandomState(19)
    metric = AveragePrecision(pos_label=1, capacity=1024)
    metric.device_put(row_sharded(mesh, "dp"))
    all_p, all_t = [], []
    for p, t in _batches(rng, steps=6, batch=128):
        all_p.append(p)
        all_t.append(t)
        metric.update(jnp.asarray(p), jnp.asarray(t))
    with no_materialization(monkeypatch):
        got = float(metric.compute())
    want = sk_ap(np.concatenate(all_t), np.concatenate(all_p))
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_stateful_sharded_multiclass_average_precision(mesh, monkeypatch):
    rng = np.random.RandomState(23)
    num_classes = 4
    metric = AveragePrecision(num_classes=num_classes, capacity=512)
    metric.device_put(row_sharded(mesh, "dp"))
    logits = rng.rand(256, num_classes).astype(np.float32)
    p = logits / logits.sum(-1, keepdims=True)
    t = rng.randint(0, num_classes, 256).astype(np.int32)
    metric.update(jnp.asarray(p), jnp.asarray(t))
    with no_materialization(monkeypatch):
        got = [float(x) for x in metric.compute()]
    want = [sk_ap((t == c).astype(int), p[:, c]) for c in range(num_classes)]
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_stateful_sharded_retrieval_map(mesh, monkeypatch):
    rng = np.random.RandomState(29)
    metric = RetrievalMAP(capacity=1024)
    metric.device_put(row_sharded(mesh, "dp"))
    oracle = RetrievalMAP()
    for _ in range(4):
        i = rng.randint(0, 41, 128).astype(np.int32)
        p = rng.rand(128).astype(np.float32)
        t = (rng.rand(128) > 0.6).astype(np.int32)
        metric.update(jnp.asarray(i), jnp.asarray(p), jnp.asarray(t))
        oracle.update(jnp.asarray(i), jnp.asarray(p), jnp.asarray(t))
    with no_materialization(monkeypatch):
        got = float(metric.compute())
    np.testing.assert_allclose(got, float(oracle.compute()), atol=1e-6)


def test_stateful_sharded_retrieval_policies(mesh, monkeypatch):
    rng = np.random.RandomState(31)
    for policy in ("skip", "neg", "pos"):
        metric = RetrievalMRR(query_without_relevant_docs=policy, capacity=512)
        metric.device_put(row_sharded(mesh, "dp"))
        oracle = RetrievalMRR(query_without_relevant_docs=policy)
        i = rng.randint(0, 29, 512).astype(np.int32)
        p = rng.rand(512).astype(np.float32)
        t = (rng.rand(512) > 0.5).astype(np.int32)
        t[i % 7 == 0] = 0  # some all-negative queries
        metric.update(jnp.asarray(i), jnp.asarray(p), jnp.asarray(t))
        oracle.update(jnp.asarray(i), jnp.asarray(p), jnp.asarray(t))
        with no_materialization(monkeypatch):
            got = float(metric.compute())
        np.testing.assert_allclose(got, float(oracle.compute()), atol=1e-6, err_msg=policy)


def test_stateful_sharded_roc_curve(mesh, monkeypatch):
    """Row-sharded ROC returns capacity-length curve VECTORS sklearn-exact
    (distinct points compacted to the front), gather path poisoned."""
    from sklearn.metrics import roc_curve as sk_roc

    rng = np.random.RandomState(61)
    metric = ROC(pos_label=1, capacity=1024)
    metric.device_put(row_sharded(mesh, "dp"))
    all_p, all_t = [], []
    for p, t in _batches(rng, steps=6, batch=96):
        all_p.append(p)
        all_t.append(t)
        metric.update(jnp.asarray(p), jnp.asarray(t))
    with no_materialization(monkeypatch):
        fpr, tpr, th, count = metric.compute()
    c = int(count)
    sk_fpr, sk_tpr, sk_th = sk_roc(
        np.concatenate(all_t), np.concatenate(all_p), drop_intermediate=False
    )
    np.testing.assert_allclose(np.asarray(fpr)[:c], sk_fpr, atol=1e-6)
    np.testing.assert_allclose(np.asarray(tpr)[:c], sk_tpr, atol=1e-6)
    # first threshold: reference convention max+1 where sklearn uses inf
    np.testing.assert_allclose(np.asarray(th)[1:c], sk_th[1:], atol=1e-6)
    np.testing.assert_allclose(float(th[0]), sk_th[1] + 1, atol=1e-6)
    # tail repeats the final point: integrals over the padded arrays agree
    np.testing.assert_allclose(
        np.trapezoid(np.asarray(tpr), np.asarray(fpr)),
        np.trapezoid(sk_tpr, sk_fpr), atol=1e-6)


def test_stateful_sharded_prc_curve(mesh, monkeypatch):
    """Row-sharded PrecisionRecallCurve: sklearn-exact padded curve vectors."""
    from sklearn.metrics import precision_recall_curve as sk_prc

    rng = np.random.RandomState(67)
    metric = PrecisionRecallCurve(pos_label=1, capacity=512)
    metric.device_put(row_sharded(mesh, "dp"))
    p = np.round(rng.rand(384), 1).astype(np.float32)
    t = (rng.rand(384) > 0.5).astype(np.int32)
    metric.update(jnp.asarray(p), jnp.asarray(t))
    with no_materialization(monkeypatch):
        precision, recall, th, count = metric.compute()
    c = int(count)
    sk_p, sk_r, sk_t = sk_prc(t, p)
    # reference orientation == sklearn's: increasing threshold, (1, 0) end
    np.testing.assert_allclose(np.asarray(precision)[:c + 1], sk_p, atol=1e-6)
    np.testing.assert_allclose(np.asarray(recall)[:c + 1], sk_r, atol=1e-6)
    np.testing.assert_allclose(np.asarray(th)[:c], sk_t, atol=1e-6)


def test_stateful_sharded_multiclass_roc_curves(mesh, monkeypatch):
    """Per-class sharded ROC curves (leading class axis, per-class counts)."""
    from sklearn.metrics import roc_curve as sk_roc

    rng = np.random.RandomState(71)
    num_classes = 4
    metric = ROC(num_classes=num_classes, capacity=512)
    metric.device_put(row_sharded(mesh, "dp"))
    logits = rng.rand(256, num_classes).astype(np.float32)
    p = np.round(logits / logits.sum(-1, keepdims=True), 2)
    t = rng.randint(0, num_classes, 256).astype(np.int32)
    metric.update(jnp.asarray(p), jnp.asarray(t))
    with no_materialization(monkeypatch):
        fpr, tpr, th, counts = metric.compute()
    for c in range(num_classes):
        k = int(counts[c])
        sk_fpr, sk_tpr, _ = sk_roc((t == c).astype(int), p[:, c], drop_intermediate=False)
        np.testing.assert_allclose(np.asarray(fpr)[c, :k], sk_fpr, atol=1e-6, err_msg=f"class {c}")
        np.testing.assert_allclose(np.asarray(tpr)[c, :k], sk_tpr, atol=1e-6, err_msg=f"class {c}")


@pytest.mark.parametrize("max_fpr", [0.25, 0.5, 0.9])
def test_stateful_sharded_partial_auroc(mesh, monkeypatch, max_fpr):
    """Binary partial AUC (max_fpr + McClish) through the sharded engine
    matches sklearn's standardized partial AUC, cross-shard ties included."""
    rng = np.random.RandomState(83)
    metric = AUROC(pos_label=1, max_fpr=max_fpr, capacity=1024)
    metric.device_put(row_sharded(mesh, "dp"))
    all_p, all_t = [], []
    for p, t in _batches(rng, steps=6, batch=96):
        all_p.append(p)
        all_t.append(t)
        metric.update(jnp.asarray(p), jnp.asarray(t))
    with no_materialization(monkeypatch):
        got = float(metric.compute())
    want = sk_auroc(np.concatenate(all_t), np.concatenate(all_p), max_fpr=max_fpr)
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_stateful_sharded_partial_auroc_degenerate(mesh, monkeypatch):
    """All-positive targets give nan (the static-kernel convention), never a
    finite-but-meaningless partial AUC."""
    metric = AUROC(pos_label=1, max_fpr=0.5, capacity=256)
    metric.device_put(row_sharded(mesh, "dp"))
    rng = np.random.RandomState(101)
    metric.update(jnp.asarray(rng.rand(64).astype(np.float32)),
                  jnp.ones(64, dtype=jnp.int32))
    with no_materialization(monkeypatch):
        assert np.isnan(float(metric.compute()))


def test_stateful_sharded_multilabel_average_precision(mesh, monkeypatch):
    """The multilabel AP layout through the sharded per-column engine."""
    rng = np.random.RandomState(89)
    num_labels = 3
    metric = AveragePrecision(num_classes=num_labels, capacity=512)
    metric.device_put(row_sharded(mesh, "dp"))
    p = np.round(rng.rand(256, num_labels), 1).astype(np.float32)
    t = (rng.rand(256, num_labels) > 0.5).astype(np.int32)
    metric.update(jnp.asarray(p), jnp.asarray(t))
    with no_materialization(monkeypatch):
        got = [float(x) for x in metric.compute()]
    want = [sk_ap(t[:, c], p[:, c]) for c in range(num_labels)]
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_sharded_fallback_warns_loudly(mesh):
    """A row-sharded metric whose config has no sharded engine must announce
    the gather fallback before computing (or raising)."""
    rng = np.random.RandomState(97)
    metric = AUROC(num_classes=3, average="micro", capacity=512)
    metric.device_put(row_sharded(mesh, "dp"))
    logits = rng.rand(128, 3).astype(np.float32)
    p = logits / logits.sum(-1, keepdims=True)
    t = rng.randint(0, 3, 128).astype(np.int32)
    metric.update(jnp.asarray(p), jnp.asarray(t))
    with pytest.warns(UserWarning, match="fall back to the gathered"):
        with pytest.raises(ValueError, match="average"):
            metric.compute()


def test_stateful_sharded_multilabel_prc_curves(mesh, monkeypatch):
    """The multilabel layout (2-D preds AND 2-D targets) through the sharded
    curve engine: per-column curves sklearn-exact."""
    from sklearn.metrics import precision_recall_curve as sk_prc

    rng = np.random.RandomState(73)
    num_labels = 3
    metric = PrecisionRecallCurve(num_classes=num_labels, capacity=512)
    metric.device_put(row_sharded(mesh, "dp"))
    p = np.round(rng.rand(256, num_labels), 1).astype(np.float32)
    t = (rng.rand(256, num_labels) > 0.5).astype(np.int32)
    metric.update(jnp.asarray(p), jnp.asarray(t))
    with no_materialization(monkeypatch):
        precision, recall, th, counts = metric.compute()
    for c in range(num_labels):
        k = int(counts[c])
        sk_p, sk_r, sk_t = sk_prc(t[:, c], p[:, c])
        np.testing.assert_allclose(np.asarray(precision)[c, :k + 1], sk_p, atol=1e-6, err_msg=f"label {c}")
        np.testing.assert_allclose(np.asarray(recall)[c, :k + 1], sk_r, atol=1e-6, err_msg=f"label {c}")
        np.testing.assert_allclose(np.asarray(th)[c, :k], sk_t, atol=1e-6, err_msg=f"label {c}")


def test_sharded_rank_engine(mesh):
    """The public sharded_rank primitive: global scipy midranks, cross-shard
    ties included, ghost rows excluded by the weight mask."""
    import scipy.stats as st
    from jax.sharding import PartitionSpec as P

    from metrics_tpu.parallel import sharded_rank

    rng = np.random.RandomState(79)
    x = np.round(rng.rand(512), 1).astype(np.float32)
    w = np.ones(512, np.float32)
    w[448:] = 0.0  # ghost tail

    fn = jax.jit(compat.shard_map(
        lambda a, b: sharded_rank(a, "dp", b),
        mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P("dp"),
    ))
    got = np.asarray(fn(jnp.asarray(x), jnp.asarray(w)))[:448]
    want = st.rankdata(x[:448])
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_stateful_sharded_spearman(mesh, monkeypatch):
    """Row-sharded SpearmanCorrcoef computes scipy-exact through the ring —
    cross-shard ties included — with the gather path poisoned."""
    import scipy.stats as st

    rng = np.random.RandomState(47)
    metric = SpearmanCorrcoef(capacity=1024)
    metric.device_put(row_sharded(mesh, "dp"))

    all_p, all_t = [], []
    for _ in range(6):
        p = np.round(rng.rand(96), 1).astype(np.float32)  # heavy cross-shard ties
        t = np.round(p + 0.3 * rng.randn(96), 1).astype(np.float32)
        all_p.append(p)
        all_t.append(t)
        metric.update(jnp.asarray(p), jnp.asarray(t))

    assert metric.preds_all.data.sharding.spec[0] == "dp"
    with no_materialization(monkeypatch):
        got = float(metric.compute())
    want = st.spearmanr(np.concatenate(all_p), np.concatenate(all_t)).statistic
    np.testing.assert_allclose(got, want, atol=1e-5)

    # reset keeps the placement; a second epoch reuses the cached launcher
    metric.reset()
    p = np.round(rng.rand(512), 2).astype(np.float32)
    t = rng.rand(512).astype(np.float32)
    metric.update(jnp.asarray(p), jnp.asarray(t))
    with no_materialization(monkeypatch):
        np.testing.assert_allclose(
            float(metric.compute()), st.spearmanr(p, t).statistic, atol=1e-5
        )


def test_stateful_sharded_kendall(mesh, monkeypatch):
    """Row-sharded KendallRankCorrCoef: the O(N^2) contraction split over the
    ring matches scipy tau-b exactly, cross-shard ties included."""
    import scipy.stats as st

    rng = np.random.RandomState(53)
    metric = KendallRankCorrCoef(capacity=512)
    metric.device_put(row_sharded(mesh, "dp"))

    all_p, all_t = [], []
    for _ in range(4):
        p = np.round(rng.rand(64), 1).astype(np.float32)
        t = np.round(p + 0.4 * rng.randn(64), 1).astype(np.float32)
        all_p.append(p)
        all_t.append(t)
        metric.update(jnp.asarray(p), jnp.asarray(t))

    with no_materialization(monkeypatch):
        got = float(metric.compute())
    want = st.kendalltau(np.concatenate(all_p), np.concatenate(all_t)).statistic
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_stateful_sharded_rank_corr_degenerate(mesh, monkeypatch):
    """Constant input (zero rank variance) gives nan through the sharded
    path, matching the scipy/gather-path convention."""
    metric = SpearmanCorrcoef(capacity=256)
    metric.device_put(row_sharded(mesh, "dp"))
    metric.update(jnp.ones(64, jnp.float32), jnp.asarray(np.random.RandomState(3).rand(64), dtype=jnp.float32))
    with no_materialization(monkeypatch):
        assert np.isnan(float(metric.compute()))

    km = KendallRankCorrCoef(capacity=256)
    km.device_put(row_sharded(mesh, "dp"))
    km.update(jnp.ones(64, jnp.float32), jnp.arange(64, dtype=jnp.float32))
    with no_materialization(monkeypatch):
        assert np.isnan(float(km.compute()))


def test_stateful_sharded_rank_corr_matches_unsharded(mesh):
    import scipy.stats as st

    rng = np.random.RandomState(59)
    p = np.round(rng.rand(768), 1).astype(np.float32)
    t = np.round(rng.rand(768), 1).astype(np.float32)

    for cls, oracle in ((SpearmanCorrcoef, st.spearmanr), (KendallRankCorrCoef, st.kendalltau)):
        plain = cls(capacity=1024)
        plain.update(jnp.asarray(p), jnp.asarray(t))
        sharded = cls(capacity=1024)
        sharded.device_put(row_sharded(mesh, "dp"))
        sharded.update(jnp.asarray(p), jnp.asarray(t))
        np.testing.assert_allclose(
            float(plain.compute()), float(sharded.compute()), atol=1e-5
        )
        np.testing.assert_allclose(float(sharded.compute()), oracle(p, t).statistic, atol=1e-5)


def test_stateful_sharded_matches_unsharded(mesh):
    """The same capacity metric computes the same value sharded or not."""
    rng = np.random.RandomState(37)
    p = np.round(rng.rand(768), 1).astype(np.float32)
    t = (rng.rand(768) > 0.5).astype(np.int32)

    plain = AUROC(pos_label=1, capacity=1024)
    plain.update(jnp.asarray(p), jnp.asarray(t))

    sharded = AUROC(pos_label=1, capacity=1024)
    sharded.device_put(row_sharded(mesh, "dp"))
    sharded.update(jnp.asarray(p), jnp.asarray(t))

    np.testing.assert_allclose(float(plain.compute()), float(sharded.compute()), atol=1e-6)


def test_stateful_sharded_overflow_raises(mesh):
    metric = AUROC(pos_label=1, capacity=64)
    metric.device_put(row_sharded(mesh, "dp"))
    rng = np.random.RandomState(41)
    metric.update(jnp.asarray(rng.rand(96).astype(np.float32)),
                  jnp.asarray((rng.rand(96) > 0.5).astype(np.int32)))
    with pytest.raises(RuntimeError, match="overflow"):
        metric.compute()


def test_stateful_sharded_binary_column_preds(mesh, monkeypatch):
    """Binary preds stored as (rows, 1) — the layout the gather path squeezes
    (functional/classification/auroc.py:172-173) — works sharded too."""
    rng = np.random.RandomState(43)
    metric = AUROC(pos_label=1, capacity=512)
    metric.device_put(row_sharded(mesh, "dp"))
    p = np.round(rng.rand(256, 1), 1).astype(np.float32)
    t = (rng.rand(256) > 0.5).astype(np.int32)
    metric.update(jnp.asarray(p), jnp.asarray(t))
    with no_materialization(monkeypatch):
        got = float(metric.compute())
    np.testing.assert_allclose(got, sk_auroc(t, p[:, 0]), atol=1e-6)


def test_failed_placement_does_not_half_promote(mesh):
    """A placement error during lazy promotion must leave the cat states
    un-promoted and in lockstep (retrying with a fixed placement works)."""
    metric = AUROC(pos_label=1, capacity=100)  # 100 % 8 != 0
    metric.device_put(row_sharded(mesh, "dp"))  # states still lists: no error yet
    p = jnp.asarray(np.random.RandomState(0).rand(8).astype(np.float32))
    t = jnp.asarray(np.array([0, 1] * 4, dtype=np.int32))
    with pytest.raises(ValueError, match="divisible"):
        metric.update(p, t)
    assert isinstance(metric._defaults["preds"], list)  # promotion not committed
    assert metric.preds == [] and metric.target == []   # lockstep preserved
    # a corrected (replicated) placement lets the same metric proceed
    metric.device_put(jax.devices()[0])
    metric.update(p, t)
    np.testing.assert_allclose(float(metric.compute()), sk_auroc(np.asarray(t), np.asarray(p)), atol=1e-6)


def test_row_sharded_requires_divisible_capacity(mesh):
    metric = AUROC(pos_label=1, capacity=100)  # 100 % 8 != 0
    metric.update(jnp.asarray(np.random.rand(8).astype(np.float32)),
                  jnp.asarray(np.array([0, 1] * 4, dtype=np.int32)))
    with pytest.raises(ValueError, match="divisible"):
        metric.device_put(row_sharded(mesh, "dp"))


def test_regroup_overflow_raises_with_knob(mesh):
    """Skewed query ids overflowing the routing buckets raise with the fix."""
    metric = RetrievalMRR(capacity=512)
    metric.regroup_capacity = 4
    metric.device_put(row_sharded(mesh, "dp"))
    i = np.zeros(512, dtype=np.int32)  # every row routes to shard 0
    p = np.linspace(0, 1, 512, dtype=np.float32)
    t = np.ones(512, dtype=np.int32)
    metric.update(jnp.asarray(i), jnp.asarray(p), jnp.asarray(t))
    with pytest.raises(RuntimeError, match="regroup_capacity"):
        metric.compute()
