"""Count-Min sketch state kind + the hash family of record.

The contract under test (parallel/cms.py):

- ``stable_key_hash`` (now living here, re-exported by serving) partitions
  uniformly across shard buckets, and the multiply-shift row family derived
  from it (``cms_buckets``) is uniform per row — both pinned by seeded
  chi-square tests so the router and the sketch hash family cannot regress
  silently;
- ``cms_scatter``/``cms_row_state`` are exact for collision-free keys,
  always OVERCOUNT (never undercount) under collisions, drop the hot-tier
  sentinel bucket, and merge by bit-exact addition (psum mergeability);
- ``CMSSpec`` is a first-class state kind: ``add_state`` materializes it,
  checkpoints round-trip through the counts entry, fingerprints include the
  seed, and sync folds the counts leaf into the existing sum buckets.
"""
import numpy as np
import pytest
from scipy import stats

import jax.numpy as jnp

from metrics_tpu.core.metric import Metric
from metrics_tpu.parallel.cms import (
    CMSSpec,
    CMSTail,
    CountMinSketch,
    cms_buckets,
    cms_error_bound,
    cms_init,
    cms_merge,
    cms_nbytes,
    cms_row_state,
    cms_scatter,
    cms_total,
    is_cms,
    make_cms_spec,
    stable_key_hash,
    stable_key_hash_array,
    stable_key_hashes,
)
from metrics_tpu.parallel.sketch import is_sketch
from metrics_tpu.parallel.sync import is_mergeable, is_stack_mergeable
from metrics_tpu.serving import stable_key_hash as served_hash


# ------------------------------------------------- hash distribution quality
def _chi2_stat(observed: np.ndarray, expected: float) -> float:
    return float(((observed - expected) ** 2 / expected).sum())


def test_stable_key_hash_uniform_over_shard_buckets():
    """The router's chi-square pin: 20k realistic keys over 16 shard buckets
    must look uniform at the 99.9% level (seeded, deterministic — a changed
    hash that skews routing fails here before it skews a fleet)."""
    num_shards = 16
    keys = [f"user-{i}" for i in range(10_000)] + list(range(10_000))
    counts = np.zeros(num_shards, dtype=np.int64)
    for key in keys:
        counts[stable_key_hash(key) % num_shards] += 1
    critical = stats.chi2.ppf(0.999, df=num_shards - 1)
    assert _chi2_stat(counts, len(keys) / num_shards) < critical


def test_cms_row_hashes_uniform_per_row_and_rows_disagree():
    """The sketch family's chi-square pin: every row's bucket distribution
    over 20k keys is uniform at the 99.9% level, and distinct rows assign
    different buckets (pairwise-independent-style disagreement — identical
    rows would collapse depth to 1 and void the ``1 - e^-depth`` bound)."""
    depth, width = 4, 64
    hashes = stable_key_hashes([f"tenant/{i}" for i in range(20_000)])
    buckets = cms_buckets(hashes, depth, width, seed=29)
    assert buckets.shape == (20_000, depth)
    critical = stats.chi2.ppf(0.999, df=width - 1)
    for d in range(depth):
        counts = np.bincount(buckets[:, d], minlength=width)
        assert _chi2_stat(counts, len(hashes) / width) < critical, f"row {d} skewed"
    # rows must disagree on almost all keys (P[collide] ~ 1/width per pair)
    for a in range(depth):
        for b in range(a + 1, depth):
            agree = float((buckets[:, a] == buckets[:, b]).mean())
            assert agree < 0.05, (a, b, agree)


def test_buckets_deterministic_in_seed_and_reexported_hash():
    hashes = stable_key_hashes(["a", b"a", 1, "1"])
    assert len(set(hashes.tolist())) == 4  # type-tagged: no cross-type collisions
    assert served_hash("a") == stable_key_hash("a")  # one hash of record
    b1 = cms_buckets(hashes, 4, 128, seed=7)
    b2 = cms_buckets(hashes, 4, 128, seed=7)
    b3 = cms_buckets(hashes, 4, 128, seed=8)
    np.testing.assert_array_equal(b1, b2)
    assert (b1 != b3).any()
    with pytest.raises(TypeError):
        stable_key_hash(1.5)


def test_stable_key_hash_array_bit_equals_the_scalar_hash():
    """The vectorized FNV-1a is BIT-EQUAL to the scalar hash of record on a
    fixed diverse corpus — per element, per dtype family. The array hash is
    what the fleet router and the CMS ingest path run in production; one
    differing bit would silently reroute keys across a restart, so the pin
    compares against ``stable_key_hash`` itself (which test_fleet pins
    against precomputed constants — the equality is transitive).

    Corpus notes: rows shorter than the widest key must stop folding at
    their own length (padding never hashes); interior NUL bytes are real key
    bytes and must fold; ``'S'``/``'U'`` storage strips trailing NULs, so
    the scalar twin hashes the ARRAY ELEMENT (what storage kept), keeping
    both sides on the same canonical bytes."""
    corpora = (
        # 'U': unicode, empty, interior NUL, and a width-40 row next to 1-char rows
        np.array(["", "a", "1", "tenant/0", "пример-ключа", "雪字キー",
                  "a\x00b", "x" * 40]),
        # 'S': raw bytes incl. interior NUL and high bytes
        np.array([b"", b"a", b"tenant-0", b"a\x00b", b"\xff\xfe\x01"], dtype="S"),
        # signed/unsigned extremes across widths
        np.array([0, 1, -1, 7, 12345, -(2**62), 2**63 - 1], dtype=np.int64),
        np.array([0, 1, 2**63, 2**64 - 1], dtype=np.uint64),
        np.array([-128, -1, 0, 127], dtype=np.int8),
        # object arrays take the scalar fallback (mixed-type key batches)
        np.array(["a", b"a", 1, "1"], dtype=object),
    )
    for arr in corpora:
        got = stable_key_hash_array(arr)
        assert got.dtype == np.uint64
        expect = np.array([stable_key_hash(k) for k in arr], dtype=np.uint64)
        np.testing.assert_array_equal(got, expect, err_msg=str(arr.dtype))
        np.testing.assert_array_equal(got, stable_key_hashes(arr))
    # the precomputed FNV-1a corpus (test_fleet pins the scalar hash to the
    # same constants): the array hash must reproduce the exact values, not
    # merely agree with whatever the in-process scalar computes
    np.testing.assert_array_equal(
        stable_key_hash_array(np.array(["tenant-1"])),
        np.array([0x1CE48904A2FF17A2], dtype=np.uint64),
    )
    np.testing.assert_array_equal(
        stable_key_hash_array(np.array([b"tenant-0"], dtype="S")),
        np.array([0x3D82925F040C1B10], dtype=np.uint64),
    )
    np.testing.assert_array_equal(
        stable_key_hash_array(np.array([0, 12345], dtype=np.int64)),
        np.array([0x2B0A3B192B55573E, 0xDBD8F4A96E701FD1], dtype=np.uint64),
    )
    # shape discipline: lists hash like their array form, N-d flattens,
    # empty stays empty — and non-canonical dtypes are rejected like the
    # scalar hash rejects non-canonical keys
    np.testing.assert_array_equal(
        stable_key_hash_array(["u", "v"]), stable_key_hashes(["u", "v"])
    )
    grid = np.arange(6, dtype=np.int32).reshape(2, 3)
    np.testing.assert_array_equal(
        stable_key_hash_array(grid), stable_key_hashes(grid.reshape(-1))
    )
    assert stable_key_hash_array(np.array([], dtype=np.int64)).shape == (0,)
    with pytest.raises(TypeError, match="str, bytes or int"):
        stable_key_hash_array(np.array([1.5, 2.5]))
    with pytest.raises(TypeError, match="str, bytes or int"):
        stable_key_hash_array(np.array([True, False]))


# ------------------------------------------------------------ sketch algebra
def test_scatter_query_exact_without_collisions_and_overcount_with():
    spec = CMSSpec(4, 64, (), np.int32, seed=3)
    sketch = cms_init(spec)
    assert is_cms(sketch) and is_sketch(sketch)
    keys = ["a", "b", "c"]
    true = {"a": 5, "b": 2, "c": 9}
    buckets = cms_buckets(stable_key_hashes(keys), 4, 64, 3)
    per_key = jnp.asarray(buckets)
    for i, key in enumerate(keys):
        deltas = jnp.ones((true[key],), jnp.int32)
        reps = jnp.broadcast_to(per_key[i][None], (true[key], 4))
        sketch = CountMinSketch(cms_scatter(sketch.counts, reps, deltas))
    assert int(cms_total(sketch.counts)) == 16
    for i, key in enumerate(keys):
        est = int(jnp.min(cms_row_state(sketch.counts, per_key[i])))
        assert est >= true[key]  # NEVER an undercount
    # with width 64 and 3 keys, collisions are absent for this seed: exact
    for i, key in enumerate(keys):
        assert int(jnp.min(cms_row_state(sketch.counts, per_key[i]))) == true[key]
    bound = float(cms_error_bound(sketch.counts))
    assert bound == pytest.approx(np.e / 64 * 16)


def test_scatter_drops_sentinel_buckets():
    """The hot-tier sentinel (bucket == width) must be DROPPED, mirroring the
    slab scatter's out-of-range contract — never wrapped or clipped."""
    sketch = cms_init(CMSSpec(2, 8, (), np.int32, seed=0))
    buckets = jnp.asarray(np.array([[8, 8], [1, 2]], dtype=np.int32))
    out = cms_scatter(sketch.counts, buckets, jnp.ones((2,), jnp.int32))
    assert int(out.sum()) == 2  # only the in-range sample landed (both rows)
    assert int(out[0, 1]) == 1 and int(out[1, 2]) == 1


def test_merge_is_bitexact_addition_and_item_cells():
    spec = CMSSpec(3, 16, (2,), np.int32, seed=5)
    a, b = cms_init(spec), cms_init(spec)
    buckets = jnp.asarray(cms_buckets(stable_key_hashes(["x", "y"]), 3, 16, 5))
    da = jnp.asarray(np.array([[1, 2], [3, 4]], np.int32))
    db = jnp.asarray(np.array([[5, 6], [7, 8]], np.int32))
    a = CountMinSketch(cms_scatter(a.counts, buckets, da))
    b = CountMinSketch(cms_scatter(b.counts, buckets, db))
    merged = cms_merge(a, b)
    both = CountMinSketch(cms_scatter(cms_scatter(cms_init(spec).counts, buckets, da), buckets, db))
    np.testing.assert_array_equal(np.asarray(merged.counts), np.asarray(both.counts))
    assert cms_nbytes(merged) == 3 * 16 * 2 * 4


def test_make_cms_spec_forms_and_validation():
    assert make_cms_spec(CMSTail(2, 32, 1), (), np.int32).shape == (2, 32)
    assert make_cms_spec((3, 64), (5,), np.int32).shape == (3, 64, 5)
    assert make_cms_spec(128, (), np.int32).width == 128
    with pytest.raises(ValueError):
        make_cms_spec((0, 64), (), np.int32)
    with pytest.raises(ValueError):
        make_cms_spec("wide", (), np.int32)
    with pytest.raises(ValueError):
        CMSTail(width=1).validate()


# ------------------------------------------------------- state-kind plumbing
class _CMSMetric(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("tail", default=CMSSpec(2, 16, (), np.int32, seed=1),
                       dist_reduce_fx="sum", persistent=True)

    def update(self, buckets, values):
        self.tail = CountMinSketch(cms_scatter(self.tail.counts, buckets, values))

    def compute(self):
        return cms_total(self.tail.counts)


def test_add_state_materializes_and_requires_sum():
    metric = _CMSMetric()
    assert is_cms(metric.tail) and metric.tail.counts.shape == (2, 16)
    assert is_mergeable("sum", metric._defaults["tail"])
    assert is_stack_mergeable("sum", metric._defaults["tail"])

    class Bad(Metric):
        def __init__(self):
            super().__init__()
            self.add_state("t", default=CMSSpec(2, 16, (), np.int32, seed=1),
                           dist_reduce_fx="max")

        def update(self):
            pass

        def compute(self):
            return 0

    with pytest.raises(ValueError, match="sum-mergeable"):
        Bad()


def test_checkpoint_roundtrip_and_reset():
    metric = _CMSMetric()
    buckets = jnp.asarray(cms_buckets(stable_key_hashes(["k"]), 2, 16, 1))
    metric.update(jnp.broadcast_to(buckets, (3, 2)), jnp.ones((3,), jnp.int32))
    assert int(metric.compute()) == 3
    state = metric.state_dict()
    assert set(state["tail"]) == {"sketch_counts"}  # the counts-entry family
    fresh = _CMSMetric()
    fresh.load_state_dict(state)
    assert is_cms(fresh.tail)
    np.testing.assert_array_equal(np.asarray(fresh.tail.counts), np.asarray(metric.tail.counts))
    fresh.reset()
    assert int(jnp.sum(fresh.tail.counts)) == 0


def test_fingerprint_includes_seed():
    from metrics_tpu.core.metric import _fingerprint_value

    a = _fingerprint_value(CMSSpec(2, 16, (), np.int32, seed=1), [])
    b = _fingerprint_value(CMSSpec(2, 16, (), np.int32, seed=2), [])
    assert a != b and a[0] == "cmsspec"
